"""Gray-failure plane tests: the health scorer's decision table, the
fail-slow injectors, the kernel demote input, and the manager's
partial-gather deadline.

The decision-table half runs the scorer against synthetic beacon
streams — the contract under test is exactly the one the soak relies
on: a single limping outlier is indicted within the hysteresis budget,
while uniform slowness, clock skew, election churn, and oscillating
slowness never demote anyone.
"""

import os
import tempfile
import threading
import time

import pytest

from summerset_tpu.host.health import HealthScorer
from summerset_tpu.host.nemesis import FaultPlan
from summerset_tpu.host.storage import LogAction, StorageHub
from summerset_tpu.utils.safetcp import FrameFaults


# ---------------------------------------------------------------- helpers
def make_scorer(**kw):
    kw.setdefault("hysteresis", 3)
    kw.setdefault("clear_after", 2)
    return HealthScorer(0, 3, **kw)


def feed(scorer, now, fsync_us, peers=None, obs=None):
    """One 'tick' of signals: own fsync sample + one beacon per peer.

    ``peers``: {sid: fsync_us}; ``obs``: {observer: {subject: delay_ms}}
    (observer 0's entries land as local transport observations).
    """
    scorer.note_fsync(fsync_us / 1e6)
    for subj, d in ((obs or {}).get(0) or {}).items():
        scorer.note_peer_delay(subj, d / 1e3)
    scorer.end_tick(queue_depth=0)
    for sid, f in (peers or {}).items():
        scorer.ingest(sid, {
            "f": f, "w": f, "q": 0.0,
            "o": (obs or {}).get(sid, {}),
        }, now)


# ----------------------------------------------------- decision table --
class TestDecisionTable:
    def test_single_outlier_self_indicted_within_budget(self):
        """The limping replica (us: 40ms fsyncs vs the quorum's ~1ms)
        is indicted after exactly ``hysteresis`` consecutive bad
        evaluations — the detection budget the soak's demotion rides."""
        s = make_scorer()
        rounds = 0
        for i in range(10):
            feed(s, float(i), 40_000.0, peers={1: 1000.0, 2: 1200.0})
            v = s.evaluate(float(i))
            assert v.evaluated
            rounds += 1
            if 0 in v.indicted:
                break
        assert s.self_indicted
        assert rounds == s.hysteresis

    def test_uniform_slowness_never_indicts(self):
        """A loaded box slows EVERYONE: the quorum median moves with the
        signal, so the relative outlier rule — explicitly not an
        absolute threshold — stays quiet."""
        s = make_scorer()
        for i in range(10):
            feed(s, float(i), 50_000.0,
                 peers={1: 48_000.0, 2: 55_000.0})
            v = s.evaluate(float(i))
            assert v.evaluated
            assert v.indicted == [], v.outliers
        assert not s.self_indicted

    def test_clock_skew_never_indicts(self):
        """clock_skew stretches the victim's tick INTERVAL, not its
        per-op latencies: fsync duration, frame stamp-to-delivery, and
        queue depth all stay healthy — only its rate drops, which no
        health signal measures.  Healthy per-op signals at skewed
        cadence must never indict."""
        s = make_scorer()
        now = 0.0
        for i in range(10):
            # the skewed replica reports (and is observed) at a slower
            # cadence, but every value is nominal
            now += 3.0 if i % 3 == 0 else 0.2
            feed(s, now, 900.0, peers={1: 1000.0, 2: 1100.0},
                 obs={1: {0: 2.0, 2: 1.5}, 2: {0: 2.5, 1: 1.0},
                      0: {1: 1.2, 2: 1.9}})
            v = s.evaluate(now)
            assert v.indicted == [], (v.outliers, v.table)

    def test_no_quorum_no_verdict(self):
        """A partition minority (or the churn window of an election
        taking peers' frames away) starves the scorer of fresh beacons:
        nothing is evaluated, nothing indicted — absence of evidence
        never indicts, however loud our own signals are."""
        s = make_scorer()
        for i in range(8):
            feed(s, float(i), 80_000.0)  # no peer beacons at all
            v = s.evaluate(float(i))
            assert not v.evaluated
            assert v.indicted == []

    def test_election_churn_resets_streak(self):
        """Two bad rounds, then beacons vanish (a legitimate election's
        frame churn): the streak resets, so the two pre-election rounds
        can never combine with a post-election round into a demotion."""
        s = make_scorer()
        for i in range(2):
            feed(s, float(i), 40_000.0, peers={1: 1000.0, 2: 1200.0})
            assert s.evaluate(float(i)).indicted == []
        # churn: stale beacons (no ingest for > stale_s)
        v = s.evaluate(100.0)
        assert not v.evaluated
        # back to healthy signals: one more bad round must NOT indict
        s._fsync_us = 0.0
        feed(s, 101.0, 40_000.0, peers={1: 1000.0, 2: 1200.0})
        v = s.evaluate(101.0)
        assert v.evaluated and v.indicted == []

    def test_oscillating_slowness_never_flaps(self):
        """Slowness that clears between evaluations resets the bad
        streak every healthy round: with hysteresis 3, alternating
        bad/good rounds never reach an indictment."""
        s = make_scorer()
        for i in range(20):
            bad = i % 2 == 0
            # EWMAs are sticky; drive the own-signal directly so the
            # oscillation is visible at evaluation granularity
            s._fsync_us = 40_000.0 if bad else 900.0
            s._wal_tick_us = 40_000.0 if bad else 900.0
            s._have_own = True
            s.ingest(1, {"f": 1000.0, "w": 1000.0, "q": 0, "o": {}},
                     float(i))
            s.ingest(2, {"f": 1200.0, "w": 1200.0, "q": 0, "o": {}},
                     float(i))
            v = s.evaluate(float(i))
            assert v.indicted == [], f"flapped at round {i}"

    def test_indictment_clears_after_recovery(self):
        s = make_scorer()
        for i in range(4):
            feed(s, float(i), 40_000.0, peers={1: 1000.0, 2: 1200.0})
            s.evaluate(float(i))
        assert s.self_indicted
        s._fsync_us = 900.0
        s._wal_tick_us = 900.0
        for i in range(4, 4 + s.clear_after):
            s.ingest(1, {"f": 1000.0, "w": 1000.0, "q": 0, "o": {}},
                     float(i))
            s.ingest(2, {"f": 1200.0, "w": 1200.0, "q": 0, "o": {}},
                     float(i))
            s.end_tick(0)
            v = s.evaluate(float(i))
        assert not s.self_indicted
        assert v.scores[0] == 1.0

    def test_peer_delay_is_observer_median(self):
        """delay_ms[r] comes from the OBSERVERS of r (median), so a
        limping replica cannot hide its egress delay by self-reporting:
        both peers see replica 0's frames arriving ~80ms late."""
        s = make_scorer()
        for i in range(6):
            feed(s, float(i), 900.0, peers={1: 1000.0, 2: 1100.0},
                 obs={1: {0: 80.0, 2: 2.0}, 2: {0: 90.0, 1: 1.5},
                      0: {1: 1.0, 2: 1.0}})
            v = s.evaluate(float(i))
            if 0 in v.indicted:
                break
        assert s.self_indicted
        assert "delay_ms" in v.outliers.get(0, [])


# --------------------------------------------------- fail-slow injectors
class TestFailSlowInjection:
    def test_slow_disk_inflates_sync_latency(self, tmp_path):
        hub = StorageHub(str(tmp_path / "w.wal"), prefer_native=False)
        try:
            t0 = time.monotonic()
            hub.do_sync_action(LogAction("append", entry=b"x" * 64,
                                         sync=True))
            fast = time.monotonic() - t0
            hub.set_faults({"slow": 6.0, "slow_floor": 0.01})
            t0 = time.monotonic()
            hub.do_sync_action(LogAction("append", entry=b"y" * 64,
                                         sync=True))
            slow = time.monotonic() - t0
            # (factor - 1) * floor = 50ms of injected stall minimum
            assert slow >= fast + 0.045, (fast, slow)
            hub.set_faults(None)
            t0 = time.monotonic()
            hub.do_sync_action(LogAction("append", entry=b"z" * 64,
                                         sync=True))
            assert time.monotonic() - t0 < 0.045
        finally:
            hub.stop()

    def test_slow_disk_count_armed_self_clears(self, tmp_path):
        hub = StorageHub(str(tmp_path / "w.wal"), prefer_native=False)
        try:
            hub.set_faults({"slow": 6.0, "slow_floor": 0.01,
                            "slow_count": 1})
            t0 = time.monotonic()
            hub.do_sync_action(LogAction("append", entry=b"a", sync=True))
            assert time.monotonic() - t0 >= 0.045
            t0 = time.monotonic()
            hub.do_sync_action(LogAction("append", entry=b"b", sync=True))
            assert time.monotonic() - t0 < 0.045  # count exhausted
        finally:
            hub.stop()

    def test_mem_pressure_forces_reclaim_flushes(self, tmp_path):
        from summerset_tpu.host.telemetry import MetricsRegistry

        reg = MetricsRegistry()
        hub = StorageHub(str(tmp_path / "w.wal"), prefer_native=False,
                         registry=reg)
        try:
            for i in range(4):
                hub.do_sync_action(LogAction(
                    "append", entry=b"q" * 300, sync=False))
            base = reg.hist("wal_fsync_us")
            base_n = 0 if base is None else base.count
            hub.set_faults({"mem": 64, "mem_stall": 0.001})
            for i in range(4):
                hub.do_sync_action(LogAction(
                    "append", entry=b"q" * 300, sync=False))
            h = reg.hist("wal_fsync_us")
            # every append overflowed the 64-byte buffer: 4 forced
            # durability points where the unarmed run had none
            assert h is not None and h.count >= base_n + 4
        finally:
            hub.stop()

    def test_frame_faults_bw_token_bucket(self):
        f = FrameFaults({"bw": 1000.0, "stall_cap": 10.0}, seed=0)
        t = 100.0
        assert f.host_stall(500, t) == 0.0       # within the bucket
        s = f.host_stall(1000, t)                # 500 short @ 1000 B/s
        assert 0.45 <= s <= 0.55
        # the repaid deficit refills during the (simulated) sleep
        assert f.host_stall(0, t + s) == pytest.approx(0.0, abs=1e-6)

    def test_frame_faults_starve_excludes_own_sleep(self):
        f = FrameFaults({"starve": 0.5, "stall_cap": 10.0}, seed=0)
        assert f.host_stall(0, 0.0) == 0.0       # no elapsed work yet
        s = f.host_stall(0, 1.0)                  # 1s of work at duty 0.5
        assert s == pytest.approx(1.0, rel=0.01)
        # next call after exactly the injected sleep: zero NEW work, so
        # zero new stall — no exponential feedback
        assert f.host_stall(0, 1.0 + s) == pytest.approx(0.0, abs=1e-3)

    def test_frame_faults_stall_is_capped(self):
        f = FrameFaults({"bw": 10.0, "starve": 0.9}, seed=0)
        f.host_stall(0, 0.0)
        assert f.host_stall(10_000, 50.0) <= f._stall_cap + 1e-9

    def test_failslow_plan_classes_and_lowering(self):
        plan = FaultPlan.generate(
            11, 3, 120,
            classes=("slow_disk", "slow_peer", "mem_pressure"),
        )
        assert plan.timeline() == FaultPlan.generate(
            11, 3, 120,
            classes=("slow_disk", "slow_peer", "mem_pressure"),
        ).timeline()
        acts = plan.host_actions()
        kinds = {a for _t, a, _d, _s in acts}
        assert kinds <= {"wal", "net", "net_clear"}
        # every duration event heals: wal faults clear with spec None,
        # net faults with net_clear
        wal_sets = [s for _t, a, _d, s in acts
                    if a == "wal" and s["spec"] is not None]
        wal_clears = [s for _t, a, _d, s in acts
                      if a == "wal" and s["spec"] is None]
        assert len(wal_sets) == len(wal_clears)
        # fail-slow classes never lower to device masks (host-only)
        dev = plan.compile_device(2)
        assert dev["alive"].all() and dev["link_up"].all()

    def test_failslow_canonical_plan_digest(self):
        a = FaultPlan.failslow("slow_disk", 1, 3, 80)
        b = FaultPlan.failslow("slow_disk", 1, 3, 80)
        assert a.digest() == b.digest()
        assert a.events[0].kind == "slow_disk"
        assert a.digest() != FaultPlan.failslow(
            "slow_peer", 1, 3, 80
        ).digest()
        with pytest.raises(ValueError):
            FaultPlan.failslow("crash", 1, 3, 80)


# ------------------------------------------------------- kernel demote --
@pytest.mark.parametrize("proto", ["multipaxos", "raft"])
def test_kernel_demote_abdicates_and_successor_wins(proto):
    """The shared demotion contract at the kernel level: arming the
    ``demote`` input for the warm-start leader's rows makes it abandon
    leadership, hold off re-campaigning, and a healthy peer wins the
    ordinary election."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from summerset_tpu.core.engine import Engine
    from summerset_tpu.protocols import make_protocol

    k = make_protocol(proto, 1, 3, 32)
    eng = Engine(k)
    state, ns = eng.init()
    G, R = 1, 3

    def seq(ticks, demote_row=None):
        s = {
            "n_proposals": jnp.zeros((ticks, G), jnp.int32),
            "value_base": jnp.zeros((ticks, G), jnp.int32),
            "demote": jnp.zeros((ticks, G, R), bool),
        }
        if demote_row is not None:
            d = np.zeros((ticks, G, R), bool)
            d[:3, :, demote_row] = True
            s["demote"] = jnp.asarray(d)
        return s

    state, ns, _ = eng.run_ticks(state, ns, seq(30))
    assert int(np.asarray(state["leader"])[0, 0]) == 0
    state, ns, _ = eng.run_ticks(state, ns, seq(250, demote_row=0))
    lead = np.asarray(state["leader"])[0]
    if "is_leader" in state:
        isl = np.asarray(state["is_leader"])[0]
    else:
        isl = (
            (np.asarray(state["bal_prepared"])[0]
             == np.asarray(state["bal_max"])[0])
            & (np.asarray(state["bal_prepared"])[0] > 0)
        )
    assert not isl[0], "demoted leader still leads"
    assert isl.any(), "no successor elected"
    assert (lead != 0).all(), lead


# ----------------------------------------- manager partial gather (live)
def test_gather_partial_results_under_slow_peer(tmp_path):
    """``metrics_dump`` under a slow-but-alive server: the gather's
    per-request deadline returns partial results with the straggler
    marked in ``missing`` instead of stalling the scrape for the full
    fan-out window (the limping server's ctrl replies ride its slowed
    tick loop)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_cluster import Cluster

    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint
    from summerset_tpu.host.messages import CtrlRequest

    cluster = Cluster("MultiPaxos", 3, str(tmp_path), tick=0.005)
    try:
        cluster.manager.gather_timeout = 1.0
        ep = GenericEndpoint(cluster.manager_addr)
        ep.connect()
        DriverClosedLoop(ep, timeout=10.0).checked_put("warm", "1")
        victim = sorted(cluster.replicas)[-1]
        # a brutal slow_peer: every send stalls seconds, so the victim's
        # tick loop (and with it its ctrl handling) crawls
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=[victim],
            payload={"net": {"starve": 0.95, "stall_cap": 5.0,
                             "bw": 1.0}},
        ))
        time.sleep(1.0)
        t0 = time.monotonic()
        rep = ep.ctrl.request(CtrlRequest("metrics_dump"), timeout=30.0)
        took = time.monotonic() - t0
        assert took < 6.0, f"gather stalled {took:.1f}s on the straggler"
        healthy = {s for s in cluster.replicas if s != victim}
        assert healthy <= set(rep.payloads or {}), rep.payloads
        if victim not in (rep.payloads or {}):
            assert victim in (rep.missing or []), rep.missing
        ep.ctrl.request(CtrlRequest(
            "inject_faults", servers=[victim], payload={"net": None},
        ))
        ep.leave()
    finally:
        cluster.stop()
