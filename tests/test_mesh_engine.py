"""Pod-scale device plane: the CPU-mesh equivalence gate + the
buffer-donation contract + mesh-geometry hardening (tier-1).

Three gates, all runnable while the TPU tunnel is down (conftest pins
the session to the 8-virtual-device CPU platform via the
``utils/jaxcompat`` ``--xla_force_host_platform_device_count`` helper):

1. **CPU-mesh equivalence** — the same seed/shape compiled unsharded
   and sharded (4x1 AND 2x2 — the latter splits the REPLICA axis, so
   in-group netmodel delivery lowers to a cross-device collective) must
   produce byte-for-byte identical state / effects / telemetry digests
   over a multi-window ``run_ticks`` run with live fault masks and a
   mid-run durable ``reset``.  This is the correctness proof the
   committed MULTICHIP trajectory leans on between live TPU captures.
2. **Donation** — the sharded engine's scan entry points donate the
   carry: the compiled executable must ACTUALLY alias it
   (``memory_analysis`` — argument bytes not double-counted against
   output), a host reuse of a donated buffer must raise rather than
   silently read garbage, and ``reset_durable_rows`` / mid-window
   ``ControlInputs`` must behave identically on the donated path.
3. **Geometry hardening** — ``parse_mesh`` / ``make_mesh`` /
   ``check_mesh`` fail with errors that name the offending axis
   (the raw GSPMD reshape failure is cryptic), and ``state_sharding``
   obeys the replicated-trailing-dims rule.
"""

import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.core import sharding as shardlib
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos

G, R, W, P = 64, 4, 16, 4
TICKS = 8       # per window
WINDOWS = 3

NET = NetConfig(delay_ticks=1, jitter_ticks=1, drop_rate=0.05,
                max_delay_ticks=3)


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual CPU devices (conftest grants 8)")


@pytest.fixture(scope="module")
def kernel():
    cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P)
    return make_protocol("multipaxos", G, R, W, cfg)


def _window_seq(w):
    """One window's stacked per-tick inputs: proposals every tick, a
    paused replica mid-window, and a durable device reset in window 1 —
    the host-fed ControlInputs the donated carry must honor."""
    t = jnp.arange(TICKS, dtype=jnp.int32)
    alive = np.ones((TICKS, G, R), bool)
    alive[3, :, 1] = False
    reset = np.zeros((TICKS, G, R), bool)
    if w == 1:
        reset[5, :, 1] = True
    return {
        "n_proposals": jnp.full((TICKS, G), P, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((w * TICKS + t) * P)[:, None], (TICKS, G)
        ),
        "alive": jnp.asarray(alive),
        "reset": jnp.asarray(reset),
    }


def _window_digests(eng):
    """Per-window sha256 over EVERY state leaf (includes the telemetry
    lane block) + the collected per-tick effects."""
    state, ns = eng.init()
    out = []
    for w in range(WINDOWS):
        state, ns, fx = eng.run_ticks(state, ns, _window_seq(w),
                                      collect=True)
        h = hashlib.sha256()
        for k in sorted(state):
            h.update(np.asarray(state[k]).tobytes())
        h.update(np.asarray(fx.commit_bar).tobytes())
        h.update(np.asarray(fx.exec_bar).tobytes())
        for k in sorted(fx.extra):
            h.update(np.asarray(fx.extra[k]).tobytes())
        out.append(h.hexdigest())
    return out, state


# --------------------------------------------------- CPU-mesh equivalence
class TestCpuMeshEquivalence:
    """Sharded (>= 2 mesh shapes) vs unsharded: byte-identical digests
    over a multi-window donated run — the tier-1 CI gate."""

    @pytest.fixture(scope="class")
    def baseline(self, kernel):
        digs, state = _window_digests(Engine(kernel, netcfg=NET, seed=7))
        assert int(np.asarray(state["commit_bar"]).max()) > 0, (
            "nothing committed during the equivalence run"
        )
        return digs

    @pytest.mark.parametrize("spec", ["4x1", "2x2"])
    def test_sharded_digests_byte_identical(self, kernel, baseline, spec):
        gs, rs = shardlib.parse_mesh(spec)
        _need_devices(gs * rs)
        mesh = shardlib.mesh_for(gs, rs)
        eng = Engine(kernel, netcfg=NET, seed=7, mesh=mesh)
        assert eng.donate, "sharded engines donate the scan carry by default"
        got, _ = _window_digests(eng)
        assert got == baseline, (
            f"mesh {spec}: state/effects/telemetry digests diverge from "
            f"the unsharded run ({got} vs {baseline})"
        )

    def test_init_places_state_on_mesh(self, kernel):
        _need_devices(4)
        mesh = shardlib.mesh_for(4, 1)
        eng = Engine(kernel, netcfg=NET, seed=7, mesh=mesh)
        state, ns = eng.init()
        for k, v in state.items():
            if v.ndim >= 1 and v.shape[0] == G:
                assert len(v.sharding.device_set) == 4, (
                    f"state[{k!r}] not spread over the mesh"
                )
        assert len(ns["rng"].sharding.device_set) == 4


# --------------------------------------------------------------- donation
class TestDonation:
    def _engine(self, kernel, donate=None):
        _need_devices(4)
        return Engine(kernel, netcfg=NET, seed=7,
                      mesh=shardlib.mesh_for(4, 1), donate=donate)

    def test_carry_actually_aliased_in_hlo(self, kernel):
        """The donated executable must alias the WHOLE carry — one
        input_output_alias pair per state+netstate leaf (nothing
        double-counted as both live input and output), vs zero aliasing
        with donation off.  The HLO pairs are the ground truth because
        they survive the persistent compile cache; the memory_analysis
        byte stats corroborate on a fresh compile only."""
        from summerset_tpu.host.profiling import donation_stats

        eng = self._engine(kernel)
        state, ns = eng.init()
        carry_leaves = len(jax.tree.leaves((state, ns)))
        comp = eng.lower_synthetic(state, ns, TICKS, P).compile()
        st = donation_stats(comp)
        assert st["aliased_buffers"] == carry_leaves, (
            f"donated carry not fully aliased: {st['aliased_buffers']} "
            f"alias pairs for {carry_leaves} carry leaves"
        )
        if st.get("alias_bytes", 0) > 0:  # fresh compile (not cache-hit)
            assert st["alias_bytes"] == st["argument_bytes"]
        off = self._engine(kernel, donate=False)
        s2, n2 = off.init()
        st_off = donation_stats(
            off.lower_synthetic(s2, n2, TICKS, P).compile()
        )
        assert st_off["aliased_buffers"] == 0

    def test_donated_buffer_reuse_raises(self, kernel):
        """Reading a donated carry from the host must raise loudly —
        never silently serve a deleted buffer's garbage."""
        eng = self._engine(kernel)
        state, ns = eng.init()
        s1, n1 = eng.run_synthetic(state, ns, TICKS, P)
        jax.block_until_ready(s1["commit_bar"])
        with pytest.raises(RuntimeError, match="deleted|donated"):
            np.asarray(state["commit_bar"])
        with pytest.raises(RuntimeError, match="deleted|donated"):
            np.asarray(ns["rng"])
        # the RETURNED carry is live and chainable
        s2, _ = eng.run_synthetic(s1, n1, TICKS, P)
        assert int(np.asarray(s2["commit_bar"]).max()) > 0

    def test_boot_template_survives_donation(self, kernel):
        """init() hands out mesh COPIES: donating a run's carry must not
        delete the engine's boot template (a second init()/re-trace
        would otherwise read dead buffers)."""
        eng = self._engine(kernel)
        state, ns = eng.init()
        eng.run_synthetic(state, ns, TICKS, P)
        s2, n2 = eng.init()  # must not raise
        s3, _, _ = eng.run_ticks(s2, n2, _window_seq(0))
        assert int(np.asarray(s3["commit_bar"]).max()) >= 0

    def test_1x1_mesh_donation_does_not_alias_boot_template(self, kernel):
        """Regression: ``jax.device_put`` short-circuits when the array
        is already placed compatibly, so on a 1x1 mesh the 'placed
        copies' init() hands out ALIASED the boot template — the first
        donated window deleted the template's buffers out from under
        the jitted tick's closed-over constants and the durable-reset
        path read freed memory (found by the quorum-tally equivalence
        gate: window-1 reset digests diverged on 1x1 only).
        ``sharding._place_copy`` now guarantees fresh buffers."""
        eng = Engine(kernel, netcfg=NET, seed=7,
                     mesh=shardlib.mesh_for(1, 1))
        fresh = kernel.init_state(7)
        state, ns = eng.init()
        for w in range(2):
            state, ns, _ = eng.run_ticks(state, ns, _window_seq(w))
        # the template is alive and byte-identical to a fresh init_state
        for k in fresh:
            assert (
                np.asarray(fresh[k]) == np.asarray(eng._boot[k])
            ).all(), f"boot template leaf {k!r} clobbered by donation"
        # and a fresh init() still hands out a runnable carry
        s2, n2 = eng.init()
        s3, _, _ = eng.run_ticks(s2, n2, _window_seq(0))
        assert int(np.asarray(s3["commit_bar"]).max()) > 0

    def test_meshless_donate_protects_boot_template(self, kernel):
        """Explicit donate=True WITHOUT a mesh: init() must hand out
        copies, not the boot template's own arrays — donating the
        template would kill every later init() and the jitted tick's
        closed-over constants."""
        eng = Engine(kernel, netcfg=NET, seed=7, donate=True)
        state, ns = eng.init()
        s1, n1 = eng.run_synthetic(state, ns, TICKS, P)
        with pytest.raises(RuntimeError, match="deleted|donated"):
            np.asarray(state["commit_bar"])
        # the template survived: a fresh init() is alive and runnable
        s2, n2 = eng.init()
        assert int(np.asarray(s2["commit_bar"]).max()) == 0
        s3, _ = eng.run_synthetic(s2, n2, TICKS, P)
        assert int(np.asarray(s3["commit_bar"]).max()) > 0

    def test_reset_and_control_inputs_on_donated_path(self, kernel):
        """reset_durable_rows + per-tick alive masks fed mid-window must
        produce identical results donated vs not (the equivalence class
        digests cover sharded-vs-unsharded; this isolates donation)."""
        on = self._engine(kernel)
        off = self._engine(kernel, donate=False)
        ds, dn = on.init()
        us, un = off.init()
        for w in range(2):
            ds, dn, _ = on.run_ticks(ds, dn, _window_seq(w))
            us, un, _ = off.run_ticks(us, un, _window_seq(w))
        for k in us:
            assert (np.asarray(ds[k]) == np.asarray(us[k])).all(), (
                f"state[{k!r}] diverges donated vs undonated"
            )


# ----------------------------------------------------- serving-path mesh
class TestServingMesh:
    """The host serving arm: ``_shared_step(kernel, mesh_shape)`` keeps
    the [G, R, ...] state sharded across local devices while the host
    TCP inbox/outbox/effects seams stay unchanged."""

    def _loopback(self, kernel, out):
        """A perfect one-tick network: everyone's outbox delivered as
        everyone's inbox (pair lanes transposed to receiver
        orientation), so consensus actually progresses."""
        return {
            k: (v if k in kernel.broadcast_lanes
                else jnp.swapaxes(v, 1, 2))
            for k, v in out.items()
        }

    def test_shared_step_sharded_equivalence(self):
        _need_devices(2)
        from summerset_tpu.core import telemetry as dev_telemetry
        from summerset_tpu.host.server import _shared_step

        g, r, w = 8, 3, 8
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=1)
        cfg.exec_follows_commit = False
        kernel = make_protocol("multipaxos", g, r, w, cfg)
        base = _shared_step(kernel)
        sharded = _shared_step(kernel, (2, 1))

        def boot():
            st = kernel.init_state(seed=0)
            dev_telemetry.attach(st, g, r)
            return st

        su = boot()
        ss = shardlib.shard_pytree(shardlib.mesh_for(2, 1), boot())
        out_u = kernel.zero_outbox()
        out_s = kernel.zero_outbox()
        for t in range(6):
            inputs = {
                "n_proposals": jnp.full((g,), 1, jnp.int32),
                "value_base": jnp.full((g,), 1 + t, jnp.int32),
                "exec_floor": jnp.full((g, r), 1 << 30, jnp.int32),
            }
            su, out_u, fx_u = base(su, self._loopback(kernel, out_u),
                                   inputs)
            ss, out_s, fx_s = sharded(ss, self._loopback(kernel, out_s),
                                      inputs)
            for k in su:
                assert (np.asarray(su[k]) == np.asarray(ss[k])).all(), (
                    f"tick {t}: state[{k!r}] diverges on the serving mesh"
                )
        # output state stayed ON the mesh (the constraint held)
        assert len(ss["commit_bar"].sharding.device_set) == 2
        assert int(np.asarray(ss["commit_bar"]).max()) > 0

    @pytest.mark.slow
    def test_live_cluster_with_device_mesh(self, tmp_path):
        """A real 3-replica cluster serving over a 2x1 device mesh:
        put/get roundtrips work and the mesh knob leaves the client
        contract untouched."""
        _need_devices(2)
        from test_cluster import Cluster

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint

        cluster = Cluster(
            "MultiPaxos", 3, str(tmp_path),
            config={"device_mesh": "2x1"}, num_groups=4,
        )
        try:
            ep = GenericEndpoint(cluster.manager_addr)
            ep.connect()
            drv = DriverClosedLoop(ep, timeout=5.0)
            for i in range(8):
                assert drv.put(f"mesh-k{i}", f"v{i}").kind == "success"
            for i in range(8):
                rep = drv.get(f"mesh-k{i}")
                assert rep.kind == "success"
                assert rep.result.value == f"v{i}"
            ep.leave()
            rep = next(iter(cluster.replicas.values()))
            assert rep._mesh is not None
            assert len(
                rep.state["commit_bar"].sharding.device_set
            ) == 2, "serving state not spread over the device mesh"
        finally:
            cluster.stop()


# ---------------------------------------------------- geometry hardening
class TestMeshGeometry:
    def test_parse_mesh(self):
        assert shardlib.parse_mesh("4x2") == (4, 2)
        assert shardlib.parse_mesh("1X1") == (1, 1)
        for bad in ("", "4", "4x", "x2", "4x2x1", "axb", "0x2", "4x-1"):
            with pytest.raises(ValueError, match="mesh spec|>= 1"):
                shardlib.parse_mesh(bad)

    def test_make_mesh_device_count_mismatch(self):
        _need_devices(8)
        with pytest.raises(ValueError, match="!= 8 devices"):
            shardlib.make_mesh(3, 2, devices=jax.devices()[:8])

    def test_mesh_for_too_few_devices(self):
        with pytest.raises(ValueError, match="needs 4 devices"):
            shardlib.mesh_for(2, 2, devices=jax.devices()[:2])

    def test_check_mesh_group_axis_error(self):
        _need_devices(4)
        mesh = shardlib.mesh_for(4, 1)
        with pytest.raises(ValueError, match="group_shards=4"):
            shardlib.check_mesh(mesh, G=10, R=3)

    def test_check_mesh_replica_axis_error(self):
        _need_devices(4)
        mesh = shardlib.mesh_for(2, 2)
        with pytest.raises(ValueError, match="replica_shards=2"):
            shardlib.check_mesh(mesh, G=8, R=3)

    def test_engine_refuses_indivisible_geometry(self, kernel):
        """The cryptic GSPMD reshape failure is pre-empted at Engine
        construction with the axis named."""
        _need_devices(4)
        k = make_protocol("multipaxos", 6, 3, 8,
                          ReplicaConfigMultiPaxos(max_proposals_per_tick=2))
        with pytest.raises(ValueError, match="group_shards=4"):
            Engine(k, mesh=shardlib.mesh_for(4, 1))
        with pytest.raises(ValueError, match="replica_shards=2"):
            Engine(k, mesh=shardlib.mesh_for(2, 2))

    def test_state_sharding_trailing_dims_replicated(self):
        """The replicated-trailing-dims rule: [G] shards group only,
        [G, R] shards both, [G, R, W, ...] replicates everything past
        the replica axis, scalars replicate fully."""
        _need_devices(4)
        mesh = shardlib.mesh_for(2, 2)
        from jax.sharding import PartitionSpec as Spec

        tree = {
            "scalar": jnp.int32(0),
            "per_group": jnp.zeros((8,), jnp.int32),
            "per_replica": jnp.zeros((8, 2), jnp.int32),
            "window": jnp.zeros((8, 2, 16), jnp.int32),
            "deep": jnp.zeros((8, 2, 16, 3), jnp.int32),
        }
        specs = shardlib.state_sharding(mesh, tree)
        assert specs["scalar"].spec == Spec()
        assert specs["per_group"].spec == Spec("group")
        assert specs["per_replica"].spec == Spec("group", "replica")
        assert specs["window"].spec == Spec("group", "replica", None)
        assert specs["deep"].spec == Spec("group", "replica", None, None)
