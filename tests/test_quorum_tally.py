"""Collective quorum-tally plane: the pairwise-vs-collective
equivalence gate + lane-geometry proofs (tier-1).

The in-mesh tally (core/quorum.py) replaces the R² pairwise accept-reply
lanes with per-source [G, R] broadcast records while the flags
pair-field keeps per-link masking — so the two transports must be
indistinguishable at the state level under EVERYTHING the netmodel can
do: jittered multi-tick delays, iid drops, pause masks, and a mid-window
durable device reset, on the unsharded engine AND on 1x1/4x1/2x2 CPU
meshes with the scan carry donated (2x2 splits the REPLICA axis, so the
collective lanes' delivery is a genuine cross-device gather).

Three gates:

1. **Window-digest equivalence** — pairwise (unsharded) vs collective
   (unsharded, 1x1, 4x1, 2x2): byte-identical sha256 over every state
   leaf (including telemetry lanes) + the collected per-tick effects,
   per window, for MultiPaxos AND Crossword (whose shard-coverage
   quorums are the largest win and whose recon rq_* lanes ride the
   collective path too).
2. **Per-tick equivalence** — Raft and RSPaxos compared leaf-for-leaf
   (fast single-tick compile).
3. **Lane geometry** — the R² ``ar_*`` pair lanes are ABSENT from the
   collective delay line: the same names ride as [D, G, R] per-source
   buffers; pairwise keeps [D, G, R, R].  Tally lanes stay out of the
   packed transport stacks (they are the attributed quorum_tally
   surface), and the packing plan still packs the bw_* window lanes.
"""

import dataclasses
import hashlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.core import quorum as quorum_lib
from summerset_tpu.core import sharding as shardlib
from summerset_tpu.protocols import make_protocol

G, R, W, P = 32, 4, 16, 4
TICKS = 8       # per window
WINDOWS = 3

NET = NetConfig(delay_ticks=1, jitter_ticks=1, drop_rate=0.05,
                max_delay_ticks=3)


def _need_devices(n):
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} virtual CPU devices (conftest grants 8)")


def _kernel(name, tally):
    base = make_protocol(name, G, R, 64)
    cfg = dataclasses.replace(
        base.config, max_proposals_per_tick=P, tally=tally
    )
    if hasattr(cfg, "fault_tolerance"):
        cfg = dataclasses.replace(cfg, fault_tolerance=0)
    return make_protocol(name, G, R, W, cfg)


def _window_seq(w):
    """Stacked per-tick inputs: proposals every tick, a paused replica
    mid-window, and a durable device reset in window 1."""
    t = jnp.arange(TICKS, dtype=jnp.int32)
    alive = np.ones((TICKS, G, R), bool)
    alive[3, :, 1] = False
    reset = np.zeros((TICKS, G, R), bool)
    if w == 1:
        reset[5, :, 1] = True
    return {
        "n_proposals": jnp.full((TICKS, G), P, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((w * TICKS + t) * P)[:, None], (TICKS, G)
        ),
        "alive": jnp.asarray(alive),
        "reset": jnp.asarray(reset),
    }


def _window_digests(eng):
    """Per-window sha256 over EVERY state leaf (telemetry included) +
    the collected per-tick effects."""
    state, ns = eng.init()
    out = []
    for w in range(WINDOWS):
        state, ns, fx = eng.run_ticks(state, ns, _window_seq(w),
                                      collect=True)
        h = hashlib.sha256()
        for k in sorted(state):
            h.update(np.asarray(state[k]).tobytes())
        h.update(np.asarray(fx.commit_bar).tobytes())
        h.update(np.asarray(fx.exec_bar).tobytes())
        for k in sorted(fx.extra):
            h.update(np.asarray(fx.extra[k]).tobytes())
        out.append(h.hexdigest())
    return out, state


# ------------------------------------------ window-digest equivalence --
class TestCollectiveEquivalence:
    """Pairwise vs collective tally: byte-identical state / effects /
    telemetry digests over a multi-window donated mesh run."""

    @pytest.fixture(scope="class", params=["multipaxos", "crossword"])
    def proto(self, request):
        return request.param

    @pytest.fixture(scope="class")
    def baseline(self, proto):
        digs, state = _window_digests(
            Engine(_kernel(proto, "pairwise"), netcfg=NET, seed=7)
        )
        assert int(np.asarray(state["commit_bar"]).max()) > 0, (
            "nothing committed during the equivalence run"
        )
        return digs

    def test_collective_unsharded_byte_identical(self, proto, baseline):
        got, _ = _window_digests(
            Engine(_kernel(proto, "collective"), netcfg=NET, seed=7)
        )
        assert got == baseline, (
            f"{proto}: collective tally diverges from pairwise "
            f"({got} vs {baseline})"
        )

    @pytest.mark.parametrize("spec", ["1x1", "4x1", "2x2"])
    def test_collective_sharded_byte_identical(self, proto, baseline,
                                               spec):
        gs, rs = shardlib.parse_mesh(spec)
        _need_devices(gs * rs)
        eng = Engine(
            _kernel(proto, "collective"), netcfg=NET, seed=7,
            mesh=shardlib.mesh_for(gs, rs),
        )
        assert eng.donate, "sharded engines donate the scan carry"
        got, _ = _window_digests(eng)
        assert got == baseline, (
            f"{proto} @ {spec}: collective tally diverges from the "
            f"unsharded pairwise run ({got} vs {baseline})"
        )


# ------------------------------------------------ per-tick equivalence --
@pytest.mark.parametrize(
    "proto", ["raft", "rspaxos", "quorumleases", "craft"]
)
def test_per_tick_state_equivalence(proto):
    """The rest of the variant family leaf-for-leaf after a faulted
    multi-window run: Raft's match-index advance, RSPaxos' recon
    plane, the QuorumLeases lease plane (whose grant bookkeeping reads
    ``ar_mine``), and CRaft's per-slot-threshold commit walk."""
    outs = []
    for tally in ("pairwise", "collective"):
        eng = Engine(_kernel(proto, tally), netcfg=NET, seed=11)
        state, ns = eng.init()
        for w in range(2):
            state, ns, _ = eng.run_ticks(state, ns, _window_seq(w))
        outs.append({k: np.asarray(v) for k, v in state.items()})
    pair, coll = outs
    assert sorted(pair) == sorted(coll)
    for k in pair:
        np.testing.assert_array_equal(
            pair[k], coll[k],
            err_msg=f"{proto}: state[{k!r}] diverges collective vs "
                    "pairwise",
        )
    assert int(pair["commit_bar"].max()) > 0


# ----------------------------------------------------- lane geometry --
def test_pairwise_lanes_absent_from_collective_delay_line():
    """The acceptance-criterion shape proof: in collective mode the
    ar_* (and rspaxos-family rq_*) lanes ride the delay line as
    [D, G, R] per-source buffers — the R² pair-shaped enqueue is gone —
    while pairwise keeps [D, G, R, R]."""
    D = NET.max_delay_ticks
    for proto in ("multipaxos", "crossword"):
        for tally, tail in (("pairwise", (G, R, R)),
                            ("collective", (G, R))):
            k = _kernel(proto, tally)
            eng = Engine(k, netcfg=NET, seed=7)
            _, ns = eng.init()
            for lane in k.TALLY_LANES:
                assert ns["bufs"][lane].shape == (D,) + tail, (
                    f"{proto}[{tally}] lane {lane}: "
                    f"{ns['bufs'][lane].shape}"
                )


def test_collective_tally_lanes_are_broadcast_lanes():
    """Collective tally lanes join broadcast_lanes (delivered as-is —
    the all-gather path on a sharded mesh); pairwise mode leaves the
    declared broadcast set untouched."""
    kp = _kernel("multipaxos", "pairwise")
    kc = _kernel("multipaxos", "collective")
    assert kc.tally_lanes <= kc.broadcast_lanes
    assert not (kp.tally_lanes & kp.broadcast_lanes)
    assert kp.tally_lanes == kc.tally_lanes


def test_tally_lanes_stay_out_of_packed_stacks():
    """pack_lanes (the D==1 stacked transport) must keep the tally
    lanes loose in BOTH modes — they are the scoped quorum_tally
    attribution surface — while still packing the bw_* window lanes."""
    for tally in ("pairwise", "collective"):
        k = _kernel("multipaxos", tally)
        eng = Engine(k, netcfg=NetConfig(pack_lanes=True), seed=3)
        _, ns = eng.init()
        net = eng.net
        assert not (set(net._pack_pair) & set(k.TALLY_LANES))
        assert not (set(net._pack_bcast) & set(k.TALLY_LANES))
        assert set(net._pack_bcast) == {"bw_abs", "bw_bal", "bw_val"}, (
            f"[{tally}] window lanes fell out of the packed stack: "
            f"{net._pack_bcast}"
        )
        # loose tally lanes really ride the packed netstate
        for lane in k.TALLY_LANES:
            assert lane in ns["bufs"]


def test_pack_lanes_defaults_on_for_depth_one():
    """Satellite: the measured pack_lanes default — ON for the uniform
    1-tick delay line (PERF.md round 11 A/B), OFF (and refused only
    when EXPLICIT) for deeper jittered lines."""
    assert NetConfig().lanes_packed is True
    assert NetConfig(max_delay_ticks=3, delay_ticks=1,
                     jitter_ticks=1).lanes_packed is False
    with pytest.raises(ValueError, match="pack_lanes"):
        NetConfig(pack_lanes=True, delay_ticks=2, max_delay_ticks=2)
    # the None sentinel survives in the field, so deriving a jittered
    # variant from a default config re-resolves instead of raising
    jittered = dataclasses.replace(NetConfig(), jitter_ticks=2)
    assert jittered.lanes_packed is False


def test_tally_mode_validated():
    with pytest.raises(ValueError, match="tally"):
        _kernel("multipaxos", "telepathy")
    assert quorum_lib.check_tally("pairwise") == "pairwise"


# ------------------------------------------------- segmented reductions --
def test_quorum_frontier_matches_kth_largest():
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.integers(0, 100, size=(4, 3, 5)), jnp.int32)
    for k in (1, 3, 5):
        got = np.asarray(quorum_lib.quorum_frontier(v, k))
        want = np.sort(np.asarray(v), axis=-1)[..., 5 - k]
        np.testing.assert_array_equal(got, want)


def test_coverage_frontier_counts_per_slot():
    """cover=[1 peer past slot 0, ...]: need=1 passes slot 0, need=2
    fails it; out-of-range slots never fail."""
    cover = jnp.asarray([[[1, 0, 0]]], jnp.int32)        # [1, 1, 3]
    abs_w = jnp.asarray([[[0, 1]]], jnp.int32)           # [1, 1, 2]
    known = jnp.ones((1, 1, 2), bool)
    in_rng = jnp.asarray([[[True, False]]])
    one = np.asarray(quorum_lib.coverage_frontier(
        cover, abs_w, jnp.full((1, 1, 2), 1, jnp.int32), known, in_rng
    ))[0, 0]
    two = np.asarray(quorum_lib.coverage_frontier(
        cover, abs_w, jnp.full((1, 1, 2), 2, jnp.int32), known, in_rng
    ))[0, 0]
    assert one == 1 << 30      # need met everywhere in range
    assert two == 0            # slot 0 fails at need=2
