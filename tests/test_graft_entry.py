"""Driver-contract tests: entry() compiles, dryrun_multichip runs on the
8-device virtual CPU mesh with the replica axis genuinely sharded."""

import sys
import os

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_entry_compiles_and_steps():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out[0]["commit_bar"])
    # a second step advances state
    out2 = jax.jit(fn)(out[0], out[1], args[2])
    assert int(out2[0]["next_slot"].max()) >= int(out[0]["next_slot"].max())


def test_dryrun_multichip_8():
    import __graft_entry__ as g

    g.dryrun_multichip(8)
