"""Host EPaxos Tarjan applier tests (parity: the reference's dependency-
graph execution, ``epaxos/execution.rs:11-87``: SCC condensation in
topological order, sequence-number order within an SCC)."""

import numpy as np

from summerset_tpu.host.epaxos_exec import COMMITTED, EPaxosExecutor


def make_space(R, W, instances):
    """instances: {(row, col): (seq, vid, noop, {row: dep_bar})}
    where dep_bar is EXCLUSIVE (columns < bar are dependencies)."""
    abs2 = np.full((R, W), -1, np.int64)
    st2 = np.zeros((R, W), np.int64)
    seq2 = np.zeros((R, W), np.int64)
    val2 = np.zeros((R, W), np.int64)
    noop2 = np.zeros((R, W), bool)
    deps2 = np.zeros((R, W, R), np.int64)  # 0 = no dep (bars)
    for (r, c), (seq, vid, noop, deps) in instances.items():
        p = c % W
        abs2[r, p] = c
        st2[r, p] = COMMITTED
        seq2[r, p] = seq
        val2[r, p] = vid
        noop2[r, p] = noop
        for r2, d in deps.items():
            deps2[r, p, r2] = d
    return abs2, st2, seq2, val2, noop2, deps2


class TestExecutor:
    def test_independent_rows_execute_to_frontier(self):
        R, W = 3, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        space = make_space(R, W, {
            (0, 0): (1, 10, False, {}),
            (0, 1): (2, 11, False, {}),
            (1, 0): (1, 20, False, {}),
        })
        floors = ex.advance(*space, np.array([2, 1, 0]))
        assert floors == [2, 1, 0]
        assert set(order) == {(0, 0), (0, 1), (1, 0)}
        # own-row order is linear
        assert order.index((0, 0)) < order.index((0, 1))

    def test_dependency_order_across_rows(self):
        R, W = 3, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        # (1,0) depends on row 0 below bar 1 -> (0,0) first
        space = make_space(R, W, {
            (0, 0): (1, 10, False, {}),
            (1, 0): (5, 20, False, {0: 1}),
        })
        ex.advance(*space, np.array([1, 1, 0]))
        assert order == [(0, 0), (1, 0)]

    def test_cycle_breaks_by_seq(self):
        R, W = 2, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        # mutual deps (the classic interference cycle): both committed,
        # each deps the other -> one SCC, executed in seq order
        space = make_space(R, W, {
            (0, 0): (7, 10, False, {1: 1}),
            (1, 0): (3, 20, False, {0: 1}),
        })
        ex.advance(*space, np.array([1, 1]))
        assert order == [(1, 0), (0, 0)]  # seq 3 before seq 7

    def test_uncommitted_dependency_blocks(self):
        R, W = 2, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        # (0,0) deps row 1 below bar 1, but row 1 committed nothing
        space = make_space(R, W, {
            (0, 0): (1, 10, False, {1: 1}),
        })
        floors = ex.advance(*space, np.array([1, 0]))
        assert floors == [0, 0] and order == []
        # once row 1 commits, both run in dependency order
        space = make_space(R, W, {
            (0, 0): (2, 10, False, {1: 1}),
            (1, 0): (1, 20, False, {}),
        })
        floors = ex.advance(*space, np.array([1, 1]))
        assert floors == [1, 1]
        assert order == [(1, 0), (0, 0)]

    def test_missing_payload_blocks_transitively(self):
        R, W = 2, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        space = make_space(R, W, {
            (0, 0): (1, 10, False, {}),
            (0, 1): (2, 11, False, {}),
            (1, 0): (9, 20, False, {0: 2}),  # deps row 0 below bar 2
        })
        # payload for vid 11 not here yet: (0,1) blocks, and (1,0)
        # blocks transitively; (0,0) still executes
        floors = ex.advance(*space, np.array([2, 1]),
                            payload_ok=lambda v, n: v != 11)
        assert floors == [1, 0] and order == [(0, 0)]
        floors = ex.advance(*space, np.array([2, 1]),
                            payload_ok=lambda v, n: True)
        assert floors == [2, 1]
        assert order == [(0, 0), (0, 1), (1, 0)]

    def test_noop_executes_without_payload(self):
        R, W = 2, 4
        seen = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: seen.append((r, c, n)))
        space = make_space(R, W, {(0, 0): (1, 0, True, {})})
        floors = ex.advance(*space, np.array([1, 0]),
                            payload_ok=lambda v, n: n or v == 99)
        assert floors == [1, 0] and seen == [(0, 0, True)]

    def test_incremental_advance_is_stable(self):
        R, W = 2, 8
        order = []
        ex = EPaxosExecutor(R, W, lambda r, c, v, n: order.append((r, c)))
        space = make_space(R, W, {(0, 0): (1, 10, False, {})})
        ex.advance(*space, np.array([1, 0]))
        # same call again: nothing re-executes
        ex.advance(*space, np.array([1, 0]))
        assert order == [(0, 0)]
