"""Vectorized MultiPaxos kernel tests: steady state, elections, failover,
partitions, lossy links — checking the classic SMR safety invariants
(agreement, ballot monotonicity, prefix commit) that the reference's tester
suite and TLA+ specs check (SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.core.netmodel import ControlInputs
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.multipaxos import ReplicaConfigMultiPaxos


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P, **kw)
    return make_protocol("multipaxos", G, R, W, cfg)


def active_leaders(state, G, R, alive=None):
    """Per-group list of (live) replicas that believe they're active leader.

    A paused replica keeps its stale leader belief (same as a SIGSTOP'd
    process in the reference), so callers exclude it via ``alive``.
    """
    lead = []
    for g in range(G):
        who = [
            r
            for r in range(R)
            if (alive is None or alive[g][r])
            and int(state["bal_prepared"][g, r]) == int(state["bal_max"][g, r])
            and int(state["bal_prepared"][g, r]) > 0
            and int(state["leader"][g, r]) == r
        ]
        lead.append(who)
    return lead


class TestSteadyState:
    def test_commit_throughput_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, fx = run_segment(eng, state, ns, T, n_prop=P)
        state = {k_: np.asarray(v) for k_, v in state.items()}
        # leader commit bar ~ (T - pipeline latency) * P
        cb = state["commit_bar"][:, 0]
        assert (cb >= (T - 4) * P).all(), cb
        # all groups agree; with value_base = tick*P the value of slot s is s
        for g in range(G):
            vals = committed_values(state, g, 0, W)
            assert vals, "no commits"
            for slot, v in vals.items():
                assert v == slot, (slot, v)
        check_agreement(state, G, R, W)
        # followers converge close behind the leader
        assert (state["commit_bar"].min(axis=1) >= cb - 3 * P).all()

    @pytest.mark.slow
    def test_population_sizes(self):
        for R in (1, 2, 3, 7):
            G, W, P = 2, 32, 4
            k = make_kernel(G, R, W, P)
            eng = Engine(k)
            state, ns = eng.init()
            state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P)
            state = {k_: np.asarray(v) for k_, v in state.items()}
            assert (state["commit_bar"][:, 0] >= (30 - 5) * P).all(), R
            check_agreement(state, G, R, W)

    def test_window_guard_blocks_overrun(self):
        # exec frozen (exec_floor stays 0) -> window fills and proposals stop
        G, R, W, P = 2, 3, 16, 4
        cfg = ReplicaConfigMultiPaxos(
            max_proposals_per_tick=P, exec_follows_commit=False
        )
        k = make_protocol("multipaxos", G, R, W, cfg)
        eng = Engine(k)
        state, ns = eng.init()

        T = 60
        t = jnp.arange(T, dtype=jnp.int32)
        seq = {
            "n_proposals": jnp.full((T, G), P, jnp.int32),
            "value_base": jnp.broadcast_to((t * P)[:, None], (T, G)),
            "exec_floor": jnp.zeros((T, G, R), jnp.int32),
        }
        state, ns, fx = eng.run_ticks(state, ns, seq)
        state = {k_: np.asarray(v) for k_, v in state.items()}
        # next_slot must never pass snap_bar (=0) + W
        assert (state["next_slot"] <= W).all()
        check_agreement(state, G, R, W)


class TestElection:
    def test_cold_start_elects_single_leader(self):
        G, R, W = 8, 5, 32
        cfg = ReplicaConfigMultiPaxos(init_leader=-1)
        k = make_protocol("multipaxos", G, R, W, cfg)
        eng = Engine(k, seed=3)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 300, n_prop=2)
        state = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(state, G, R)
        for g, who in enumerate(leads):
            assert len(who) == 1, f"group {g}: leaders {who}"
        # commits flow after election
        assert (state["commit_bar"].max(axis=1) > 0).all()
        check_agreement(state, G, R, W)

    def test_failover_preserves_committed(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k, seed=7)
        state, ns = eng.init()
        # phase 1: leader 0 commits
        state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P)
        pre = {k_: np.asarray(v) for k_, v in state.items()}
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]
        assert all(len(c) > 0 for c in pre_committed)

        # phase 2: crash replica 0; someone else must take over and commit
        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, alive=alive, base_start=1000
        )
        post = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(post, G, R, alive=np.asarray(alive))
        for g, who in enumerate(leads):
            assert len(who) == 1 and who[0] != 0, f"group {g}: {who}"
        # new commits happened
        live_cb = post["commit_bar"][:, 1:]
        assert (live_cb.max(axis=1) > pre["commit_bar"][:, 1:].max(axis=1)).all()
        # previously committed values survive the failover
        for g in range(G):
            new_leader = leads[g][0]
            vals = committed_values(post, g, new_leader, W)
            for slot, v in pre_committed[g].items():
                if slot in vals:  # may have left the window via GC
                    assert vals[slot] == v, (g, slot, v, vals[slot])
        check_agreement(post, G, R, W)

        # phase 3: revive 0 -> rejoins as follower and catches up
        state, ns, fx = run_segment(
            eng, state, ns, 200, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        assert (
            fin["commit_bar"][:, 0] >= fin["commit_bar"].max(axis=1) - 4 * P
        ).all()
        check_agreement(fin, G, R, W)


class TestPartitions:
    def test_minority_partition_keeps_committing(self):
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        # partition {3,4} away from {0,1,2}
        link = ControlInputs.split_links(G, R, (3, 4))
        state, ns, fx = run_segment(
            eng, state, ns, 100, n_prop=P, link_up=link
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] >= (100 - 10) * P).all()
        check_agreement(st, G, R, W)

    @pytest.mark.slow
    def test_majority_partition_takes_over_no_divergence(self):
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k, seed=11)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 20, n_prop=P)

        # partition leader side {0,1} from majority {2,3,4}
        link = ControlInputs.split_links(G, R, (0, 1))
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, link_up=link,
            base_start=1000,
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        # majority side elected a leader and kept committing
        leads = active_leaders(st, G, R)
        for g, who in enumerate(leads):
            majority_leads = [r for r in who if r >= 2]
            assert majority_leads, f"group {g}: {who}"
        assert (st["commit_bar"][:, 2:].max(axis=1) > 20 * P).all()
        # old leader side must stall (no quorum)
        assert (
            st["commit_bar"][:, 0] <= st["commit_bar"][:, 2:].max(axis=1)
        ).all()
        check_agreement(st, G, R, W)

        # heal: everyone converges to one leader, no divergence
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(fin, G, R)
        for g, who in enumerate(leads):
            assert len(who) == 1, f"group {g}: {who}"
        spread = fin["commit_bar"].max(axis=1) - fin["commit_bar"].min(axis=1)
        assert (spread <= 4 * P).all()
        check_agreement(fin, G, R, W)


class TestBackfill:
    def test_chunked_backfill_heals_hole(self):
        # A follower misses a stretch of accepts narrower than the window;
        # after healing, the leader backfills in chunks smaller than the
        # hole — each below-run chunk must reset/merge the voting run so
        # the follower's commit bar catches up (regression: such chunks
        # were silently dropped).
        G, R, W, P = 2, 3, 32, 4
        cfg = ReplicaConfigMultiPaxos(max_proposals_per_tick=P, chunk_size=4)
        k = make_protocol("multipaxos", G, R, W, cfg)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 10, n_prop=P)

        # partition follower 2 away for 5 ticks (~20 slots < W)
        link = ControlInputs.isolate_links(G, R, 2)
        state, ns, _ = run_segment(
            eng, state, ns, 5, n_prop=P, link_up=link,
            base_start=10,
        )
        # heal; stop proposing so catch-up is pure backfill
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=0)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 2] == st["commit_bar"][:, 0]).all(), st[
            "commit_bar"
        ]
        check_agreement(st, G, R, W)


class TestLossyNetwork:
    @pytest.mark.parametrize("drop", [0.1, 0.3])
    def test_agreement_under_drops_and_jitter(self, drop):
        G, R, W, P = 4, 5, 64, 4
        cfg = ReplicaConfigMultiPaxos(
            max_proposals_per_tick=P, hear_timeout_lo=40, hear_timeout_hi=80
        )
        k = make_protocol("multipaxos", G, R, W, cfg)
        net = NetConfig(delay_ticks=1, jitter_ticks=2, drop_rate=drop,
                        max_delay_ticks=4)
        eng = Engine(k, netcfg=net, seed=23)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 400, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        # progress despite loss
        assert (st["commit_bar"].max(axis=1) > 100).all()
        check_agreement(st, G, R, W)
        # ballot monotonicity is implicit; check bal sanity
        assert (st["bal_max"] >= (1 << 8)).all()


class TestLeaderLeases:
    """Stable-leader lease plane (parity: multipaxos/leaderlease.rs:10-21):
    followers promise vote refusal on accepted heartbeats; the leader
    serves local reads only while a quorum of promises is confirmed, and
    challengers are vetoed until promises lapse."""

    def test_steady_leader_holds_read_lease(self):
        G, R, W, P = 2, 5, 32, 4
        eng = Engine(make_kernel(G, R, W, P, leader_leases=True))
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P,
                                    collect=True)
        ok = np.asarray(fx.extra["leader_read_ok"])  # [T, G, R]
        # after spin-up, the warm leader (replica 0) holds the lease on
        # every tick; no follower ever does
        assert ok[10:, :, 0].all(), ok[:, :, 0]
        assert not ok[:, :, 1:].any()

    def test_lease_blocks_premature_challenger_and_transfers(self):
        G, R, W, P = 2, 3, 32, 4
        cfg = dict(leader_leases=True, leader_lease_len=12, lease_margin=3)
        eng = Engine(make_kernel(G, R, W, P, **cfg))
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 20, n_prop=P)

        # kill the leader; run a couple of lease lengths with collection
        alive = np.ones((G, R), bool)
        alive[:, 0] = False
        state, ns, fx = run_segment(
            eng, state, ns, 120, n_prop=P,
            alive=jnp.asarray(alive), base_start=20, collect=True,
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        ok = np.asarray(fx.extra["leader_read_ok"])
        # a new leader took over and eventually re-established the lease
        leads = active_leaders(st, G, R, alive=alive)
        assert all(len(ws) == 1 and ws[0] != 0 for ws in leads), leads
        assert ok[-1, :, 1:].any(), "new leader never re-acquired lease"
        # while ANY follower still held a promise to the dead leader
        # (ll_left > 0 in the first margin ticks), nobody else led: the
        # first tick where a survivor claims leadership must come after
        # the promise window
        first_new = next(
            t for t in range(ok.shape[0]) if ok[t, :, 1:].any()
        )
        assert first_new > 3, first_new
        check_agreement(st, G, R, W)

    def test_leases_off_by_default_no_extra(self):
        G, R, W, P = 2, 3, 16, 2
        eng = Engine(make_kernel(G, R, W, P))
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 5, n_prop=P,
                                    collect=True)
        assert "leader_read_ok" not in fx.extra
        assert "ll_left" not in state
