"""Wire-plane codec tests: grammar roundtrips for every hot message
kind x ndarray dtype/shape/endianness, decode hardening (typed errors
on truncated/garbage/mutated bodies — never a bare ``struct.error``),
the vectored/recv_into framing layer, and the mixed-mesh interop
contract (a codec-on replica and a pickle replica serving one live
cluster).
"""

import pickle
import random
import socket
import struct
import threading
import time

import numpy as np
import pytest

from summerset_tpu.host.messages import ApiReply, ApiRequest, ShardPayload
from summerset_tpu.host.statemach import Command, CommandResult
from summerset_tpu.utils import safetcp, wirecodec
from summerset_tpu.utils.errors import SummersetError
from summerset_tpu.utils.wirecodec import (
    FrameEncoder,
    WireDecodeError,
    decode_body,
    encode_body,
)


def deep_eq(a, b):
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return (
            isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
            and a.dtype == b.dtype and a.shape == b.shape
            and np.array_equal(a, b)
        )
    if isinstance(a, dict):
        return (
            isinstance(b, dict) and set(a) == set(b)
            and all(deep_eq(a[k], b[k]) for k in a)
        )
    if isinstance(a, (list, tuple)):
        return (
            type(a) is type(b) and len(a) == len(b)
            and all(deep_eq(x, y) for x, y in zip(a, b))
        )
    return type(a) is type(b) and a == b


def rt(obj):
    """Encode -> decode roundtrip through the codec."""
    return decode_body(encode_body(obj))


# ---------------------------------------------------------------- grammar
class TestGenericGrammar:
    @pytest.mark.parametrize("v", [
        None, True, False, 0, 1, -1, 127, -128, 128, -129,
        (1 << 62), -(1 << 62), (1 << 80), -(1 << 80),  # bigint path
        0.0, -1.5, 3.14159, float("inf"),
        "", "key", "uniçødé\U0001f600",
        b"", b"raw", b"x" * 2000,  # > segment threshold
        (), (1, "a", None), [1, [2, [3]]], {},
        {"k": 1, 2: "v", (1, 2): [3.5]},
    ])
    def test_scalars_containers(self, v):
        assert deep_eq(rt(v), v)

    def test_nested_mixed(self):
        v = {
            "pp": {(2, 37): [(5, ApiRequest(
                "req", req_id=9, cmd=Command("put", "k", "v"),
            ))]},
            "need": [(0, 1), (3, 99)],
            "hb": {"f": 1.5, "o": {0: 2.5}},
            "ts": 12.25,
            "flags": True,
        }
        assert deep_eq(rt(v), v)

    def test_pickle_escape_for_unknown_types(self):
        class Odd:  # not registered, not a container
            def __eq__(self, other):
                return isinstance(other, Odd)
        v = {"x": complex(1, 2), "s": {1, 2, 3}}
        assert deep_eq(rt(v)["s"], {1, 2, 3}) or rt(v)["s"] == {1, 2, 3}
        assert rt(v)["x"] == complex(1, 2)

    def test_struct_registry_roundtrip(self):
        sp = ShardPayload(128, {0: np.arange(4, dtype=np.int32)})
        back = rt(sp)
        assert back.data_len == 128
        assert np.array_equal(back.shards[0], sp.shards[0])

    def test_numpy_scalars_canonicalize(self):
        assert rt(np.int32(7)) == 7 and type(rt(np.int32(7))) is int
        assert rt(np.float64(1.5)) == 1.5
        assert rt(np.bool_(True)) is True

    def test_depth_cap(self):
        v = None
        for _ in range(wirecodec.MAX_DEPTH + 4):
            v = [v]
        with pytest.raises(SummersetError):
            encode_body(v)


NDARRAY_DTYPES = [
    "int8", "uint8", "int16", "int32", "uint32", "int64", "uint64",
    "float32", "float64", "bool", ">i4", ">f8", "<u2",
]
NDARRAY_SHAPES = [(), (0,), (1,), (7,), (3, 4), (2, 3, 4), (1, 0, 5)]


class TestNdarrays:
    @pytest.mark.parametrize("dtype", NDARRAY_DTYPES)
    @pytest.mark.parametrize("shape", NDARRAY_SHAPES)
    def test_roundtrip_dtype_shape(self, dtype, shape):
        rng = np.random.default_rng(hash((dtype, shape)) % (1 << 31))
        # size=() yields a numpy SCALAR (which the codec canonicalizes
        # by design); reshape from (1,) to keep a true 0-d ARRAY here
        raw = rng.integers(0, 100, size=shape if shape else (1,))
        a = (raw % 2 if dtype == "bool" else raw).astype(
            dtype
        ).reshape(shape)
        back = rt(a)
        assert back.dtype == a.dtype  # endianness preserved via dtype.str
        assert back.shape == a.shape
        assert np.array_equal(back, a)

    def test_noncontiguous_input(self):
        a = np.arange(24, dtype=np.int32).reshape(4, 6).T  # F-order view
        assert not a.flags.c_contiguous
        back = rt(a)
        assert np.array_equal(back, a)

    def test_decode_is_zero_copy_view(self):
        a = np.arange(256, dtype=np.int32)
        body = encode_body(a)
        back = decode_body(body)
        # the decoded array aliases the received body, not a fresh copy
        assert not back.flags.owndata
        assert np.array_equal(back, a)

    def test_alignment_of_raw_data(self):
        # oddly-sized strings before the array must not misalign it
        for pre in ("", "x", "xy", "xyz", "wxyz", "xxxxx"):
            v = (pre, np.arange(5, dtype=np.int64))
            back = rt(v)
            assert back[0] == pre
            assert np.array_equal(back[1], v[1])


HOT_MESSAGES = [
    ApiRequest("req", req_id=1, cmd=Command("put", "k", "v" * 64)),
    ApiRequest("req", req_id=(1 << 40), cmd=Command("get", "k")),
    ApiRequest("req", req_id=2, cmd=Command("put", "unié", "")),
    ApiRequest("probe", req_id=3, cmd=Command("get", "kx")),
    ApiRequest("batch", req_id=4, batch=[]),
    ApiRequest("batch", req_id=5, batch=[
        (9, Command("put", "a", "1")), (10, Command("get", "b")),
        ((1 << 50), Command("put", "c", "x" * 512)),
    ]),
    ApiReply("reply", req_id=1,
             result=CommandResult("put", old_value=None)),
    ApiReply("reply", req_id=2,
             result=CommandResult("get", value="v" * 128), local=True),
    ApiReply("shed", req_id=3, success=False, retry_after_ms=250),
    ApiReply("probe", req_id=4, success=True, seq=77),
    ApiReply("note", req_id=0, seq=9, notes=[]),
    ApiReply("note", req_id=0, seq=9,
             notes=[(7, "k1", "v1"), (8, "k2", None)]),
    ApiReply("reply", req_id=5, redirect=2, success=False,
             rq_retry=True),
    # ordered range reads: the scan Command fields (end/limit) and the
    # result's sorted items ride the same hot struct lanes — registry
    # ids 3/4 append them, so old decoders drop them and old encoders
    # leave the dataclass defaults
    ApiRequest("req", req_id=6,
               cmd=Command("scan", "w00", end="w10", limit=8)),
    ApiRequest("req", req_id=7, cmd=Command("scan", "a")),  # unbounded
    ApiReply("reply", req_id=6, result=CommandResult(
        "scan", items=(("w00", "v0"), ("w03", "v3"), ("w07", "v7")),
    )),
    ApiReply("reply", req_id=7,
             result=CommandResult("scan", items=())),
]

COLD_MESSAGES = [
    ApiRequest("conf", req_id=1, conf_delta={"responders": [0, 1]}),
    ApiRequest("leave"),
    ApiRequest("sub", req_id=0),
    ApiRequest("stats", req_id=1),
    ApiReply("redirect", req_id=1, redirect=0, success=False),
    ApiReply("error", req_id=2, success=False),
    ApiReply("sub", req_id=0, seq=3, notes={"k": "v"}),
    ApiReply("leave"),
]


class TestHotMessages:
    @pytest.mark.parametrize("msg", HOT_MESSAGES,
                             ids=lambda m: f"{type(m).__name__}-{m.kind}")
    def test_roundtrip(self, msg):
        back = rt(msg)
        assert back == msg
        assert type(back) is type(msg)

    @pytest.mark.parametrize("msg", HOT_MESSAGES,
                             ids=lambda m: f"{type(m).__name__}-{m.kind}")
    def test_hot_and_smaller_than_pickle(self, msg):
        assert wirecodec.is_hot(msg)
        body = encode_body(msg)
        assert body[0] == wirecodec.MAGIC
        assert len(body) < len(
            pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
        )

    @pytest.mark.parametrize("msg", COLD_MESSAGES,
                             ids=lambda m: f"{type(m).__name__}-{m.kind}")
    def test_cold_kinds_stay_pickle_on_the_frame_path(self, msg):
        assert not wirecodec.is_hot(msg)
        frame = safetcp.encode_frame(msg, codec=True)
        assert frame[8] == 0x80  # pickle protocol 2+ opcode
        # ...but the generic grammar still roundtrips them (nested use)
        assert rt(msg) == msg

    def test_non_str_command_value_falls_back_and_roundtrips(self):
        # the flat T_REQ layout is str-only; exotic values must fall
        # back to the generic grammar transparently
        msg = ApiRequest("req", req_id=1,
                         cmd=Command("put", "k", ("tuple", "value")))
        back = rt(msg)
        assert back == msg


class TestTickFrames:
    def mk_frame(self, g=16, r=3, with_pp=True):
        rng = np.random.default_rng(g)
        msg = {
            f"lane{i}": rng.integers(0, 1000, (g,)).astype(np.int32)
            for i in range(5)
        }
        msg["bl"] = rng.integers(0, 9, (g, r)).astype(np.int32)
        msg["flags"] = rng.integers(0, 1 << 30, (g, r)).astype(np.uint32)
        payload = {
            "msg": msg,
            "pp": {(0, 3): [(5, ApiRequest(
                "req", req_id=2, cmd=Command("put", "k", "v"),
            ))]} if with_pp else {},
            "kv_need": False,
            "ts": 123.5,
            "need": [(0, 7)],
            "hb": {"f": 1.5, "o": {0: 2.0, 2: 0.5}},
        }
        return (997, payload)

    @pytest.mark.parametrize("shape", [(1, 3), (16, 3), (64, 5)])
    def test_roundtrip(self, shape):
        g, r = shape
        tick, payload = self.mk_frame(g, r)
        back_tick, back = rt((tick, payload))
        assert back_tick == tick
        assert set(back) == set(payload)
        for k, a in payload["msg"].items():
            assert back["msg"][k].dtype == a.dtype
            assert np.array_equal(back["msg"][k], a)
        assert back["pp"] == payload["pp"]
        assert back["hb"] == payload["hb"]

    def test_is_hot_and_beats_pickle_on_bytes(self):
        frame = self.mk_frame()
        assert wirecodec.is_hot(frame)
        body = encode_body(frame)
        assert body[0] == wirecodec.MAGIC
        assert len(body) < len(
            pickle.dumps(frame, protocol=pickle.HIGHEST_PROTOCOL)
        )

    def test_lane_views_are_zero_copy(self):
        frame = self.mk_frame()
        back = decode_body(encode_body(frame))[1]
        for a in back["msg"].values():
            assert not a.flags.owndata

    def test_empty_msg_and_schema_memo_stability(self):
        t, p = self.mk_frame()
        p = dict(p)
        p["msg"] = {}
        assert deep_eq(rt((t, p))[1]["msg"], {})
        # same lane schema decoded repeatedly (the memo hot path)
        f = self.mk_frame()
        for _ in range(3):
            back = rt(f)
            assert np.array_equal(
                back[1]["msg"]["lane0"], f[1]["msg"]["lane0"]
            )

    def test_vectored_segments_reference_lane_buffers(self):
        tick, payload = self.mk_frame()
        enc = FrameEncoder()
        segs, blen = enc.encode_frame_into((tick, payload))
        try:
            assert sum(len(s) for s in segs) == blen
            # at least one segment must BE a lane array's buffer
            lane_ids = {
                id(a.data.obj if hasattr(a.data, "obj") else a)
                for a in payload["msg"].values()
            }
            views = [s for s in segs if isinstance(s, memoryview)]
            assert views, "no zero-copy segments emitted"
        finally:
            enc.release()

    def test_strided_outbox_slices_stay_on_fast_path(self):
        # regression: _slice_outbox hands STRIDED views (v[:, me] and
        # v[:, me, dst]) — the first live A/B run fell back to the
        # generic walk on every frame because of this, inverting the
        # serialize-time win.  Strided lanes must ride the tick fast
        # path (copied once at emission, like pickle's reduce does).
        g, r = 16, 3
        v = np.arange(g * r * r).reshape(g, r, r).astype(np.int32)
        msg = {"bl": v[:, 1], "pair": v[:, 1, 2]}
        assert not msg["bl"].flags.c_contiguous
        frame = (9, {"msg": msg, "pp": {}, "ts": 1.0})
        body = encode_body(frame)
        assert body[2] == wirecodec.T_TICKFRAME, hex(body[2])
        back = decode_body(body)
        for k, a in msg.items():
            assert np.array_equal(back[1]["msg"][k], a)

    def test_encoder_fallback_when_msg_not_arrays(self):
        # a payload whose "msg" is not all-ndarray still encodes
        frame = (5, {"msg": {"weird": "not an array"}, "ts": 1.0})
        back = rt(frame)
        assert back[1]["msg"]["weird"] == "not an array"


# --------------------------------------------------------------- hardening
def _valid_bodies():
    enc = FrameEncoder()
    frames = HOT_MESSAGES + COLD_MESSAGES + [
        TestTickFrames().mk_frame(),
        {"generic": [1, 2.5, np.arange(6, dtype=np.int16)]},
    ]
    return [enc.encode_bytes(f) for f in frames]


class TestDecodeHardening:
    ALLOWED = (WireDecodeError,)

    def _try(self, body):
        try:
            decode_body(bytes(body))
        except self.ALLOWED:
            pass
        # any other exception type propagates and fails the test

    def test_truncations(self):
        for body in _valid_bodies():
            for cut in range(0, len(body), max(1, len(body) // 37)):
                self._try(body[:cut])

    def test_bitflips_seeded(self):
        rng = random.Random(1234)
        for body in _valid_bodies():
            for _ in range(64):
                b = bytearray(body)
                i = rng.randrange(len(b))
                b[i] ^= 1 << rng.randrange(8)
                self._try(b)

    def test_trailing_garbage(self):
        for body in _valid_bodies():
            self._try(body + b"\x00")
            self._try(body + b"garbage!")

    def test_garbage_prefixes(self):
        self._try(b"")
        self._try(bytes([wirecodec.MAGIC]))
        self._try(bytes([wirecodec.MAGIC, 99, 1]))  # bad version
        self._try(bytes([wirecodec.MAGIC, 1, 0xEE]))  # unknown tag
        with pytest.raises(WireDecodeError):
            decode_body(b"\x00not pickle or codec")

    def test_overcap_lengths_do_not_allocate(self):
        # T_STR with a 4GB length field must fail fast, not allocate
        body = bytes([wirecodec.MAGIC, 1, 0x08]) + struct.pack(
            "<I", 0xFFFFFFF0
        )
        with pytest.raises(WireDecodeError):
            decode_body(body)
        # ndarray with absurd dims
        body = bytes([wirecodec.MAGIC, 1, 0x0C, 3]) + b"<i4" + bytes(
            [4]
        ) + struct.pack("<IIII", 65535, 65535, 65535, 65535)
        with pytest.raises(WireDecodeError):
            decode_body(body)

    def test_never_bare_struct_error(self):
        # regression shape: a body that dies exactly inside unpack_from
        body = bytes([wirecodec.MAGIC, 1, 0x05, 1, 2])  # i64 cut short
        with pytest.raises(WireDecodeError):
            decode_body(body)


# ---------------------------------------------------------------- framing
class TestFraming:
    def test_encode_frame_formats(self):
        req = HOT_MESSAGES[0]
        on = safetcp.encode_frame(req, codec=True)
        off = safetcp.encode_frame(req, codec=False)
        assert on[8] == wirecodec.MAGIC and off[8] == 0x80
        (ln,) = struct.unpack(">Q", on[:8])
        assert ln == len(on) - 8
        # both decode identically through the dispatch
        assert decode_body(on[8:]) == decode_body(off[8:]) == req

    def test_sendmsg_roundtrip_over_socketpair(self):
        a, b = socket.socketpair()
        try:
            # tiny send buffer forces partial sendmsg progress
            a.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4096)
            frame = TestTickFrames().mk_frame(64, 5)
            enc = FrameEncoder()
            segs, total = safetcp.encode_frame_into(frame, enc,
                                                    codec=True)
            done = threading.Event()

            def sender():
                safetcp.sendmsg_all(a, segs, total)
                done.set()

            t = threading.Thread(target=sender, daemon=True)
            t.start()
            rx = safetcp.FrameReceiver()
            obj, nbytes = rx.recv(b)
            t.join(timeout=5)
            assert done.is_set()
            enc.release()
            assert nbytes == total - 8
            assert obj[0] == frame[0]
            for k, arr in frame[1]["msg"].items():
                assert np.array_equal(obj[1]["msg"][k], arr)
        finally:
            a.close()
            b.close()

    def test_many_tiny_segments(self):
        a, b = socket.socketpair()
        try:
            segs = [struct.pack(">Q", 3 * 700)] + [b"abc"] * 700
            total = 8 + 3 * 700
            t = threading.Thread(
                target=safetcp.sendmsg_all, args=(a, segs, total),
                daemon=True,
            )
            t.start()
            rx = safetcp.FrameReceiver()
            body = rx.recv_raw(b)
            t.join(timeout=5)
            assert bytes(body) == b"abc" * 700
        finally:
            a.close()
            b.close()

    def test_recv_into_no_quadratic_accumulation(self):
        # dribble a frame one byte at a time; recv still assembles it
        a, b = socket.socketpair()
        try:
            frame = safetcp.encode_frame({"k": "v" * 100}, codec=False)

            def dripper():
                for i in range(len(frame)):
                    a.sendall(frame[i:i + 1])
                    if i % 37 == 0:
                        time.sleep(0.001)

            t = threading.Thread(target=dripper, daemon=True)
            t.start()
            obj, n = safetcp.recv_msg_sync_len(b)
            t.join(timeout=5)
            assert obj == {"k": "v" * 100}
        finally:
            a.close()
            b.close()

    def test_midframe_timeout_is_fatal_preframe_retryable(self):
        a, b = socket.socketpair()
        try:
            b.settimeout(0.2)
            # nothing sent: zero-consumed timeout stays retryable
            with pytest.raises(TimeoutError):
                safetcp.recv_msg_sync(b)
            # partial header then silence: mid-frame is fatal
            a.sendall(b"\x00\x00\x00")
            with pytest.raises(SummersetError):
                safetcp.recv_msg_sync(b)
        finally:
            a.close()
            b.close()

    def test_frame_cap_enforced(self):
        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">Q", safetcp.MAX_FRAME + 1))
            with pytest.raises(SummersetError):
                safetcp.recv_msg_sync(b)
        finally:
            a.close()
            b.close()


# ------------------------------------------------------------ mixed mesh
@pytest.fixture(scope="class")
def mixed_cluster(tmp_path_factory):
    from test_cluster import Cluster

    c = Cluster(
        "MultiPaxos", 3,
        tmp_path_factory.mktemp("wire_mixed"),
        # replica 0 speaks pickle on every hot path; 1 and 2 speak the
        # codec — every p2p link in the mesh carries BOTH formats, the
        # frame-level dispatch contract under test
        config={"wire_codec": True},
        config_per_slot={0: {"wire_codec": False}},
    )
    yield c
    c.stop()


class TestMixedMesh:
    """pickle replica <-> codec replicas on ONE live cluster, plus
    clients of both persuasions — the mixed-version interop story."""

    def test_mixed_mesh_serves_both_client_formats(self, mixed_cluster):
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint

        for codec, tag in ((True, "c"), (False, "p")):
            ep = GenericEndpoint(
                mixed_cluster.manager_addr, wire_codec=codec,
            )
            ep.connect()
            drv = DriverClosedLoop(ep, timeout=30.0)
            for i in range(6):
                drv.checked_put(f"wm_{tag}{i}", f"val{i}")
            for i in range(6):
                drv.checked_get(f"wm_{tag}{i}", f"val{i}")
            ep.leave()
        # cross-format visibility: a codec client reads pickle writes
        ep = GenericEndpoint(mixed_cluster.manager_addr, wire_codec=True)
        ep.connect()
        drv = DriverClosedLoop(ep, timeout=30.0)
        drv.checked_get("wm_p0", "val0")
        ep.leave()

    def test_both_wire_modes_visible_in_scrape(self, mixed_cluster):
        from summerset_tpu.client.endpoint import scrape_metrics

        snap = scrape_metrics(mixed_cluster.manager_addr, timeout=20.0)
        assert snap, "metrics scrape failed"
        modes = {
            sid: s.get("wire_codec") for sid, s in snap.items()
        }
        assert False in modes.values() and True in modes.values(), modes
        for sid, s in snap.items():
            hists = s["host"]["histograms"]
            assert any(
                k.startswith("wire_encode_us") for k in hists
            ), (sid, sorted(hists))
            assert any(
                k.startswith("wire_decode_us") for k in hists
            ), sid
            counters = s["host"]["counters"]
            assert "wire_bytes_saved" in counters

    def test_codec_replicas_report_bytes_saved(self, mixed_cluster):
        # drive enough ticks that the 1-in-64 savings probe fired on a
        # codec replica (the mesh ticks constantly; just wait)
        from summerset_tpu.client.endpoint import scrape_metrics

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            snap = scrape_metrics(
                mixed_cluster.manager_addr, timeout=20.0
            )
            saved = sum(
                s["host"]["counters"].get("wire_bytes_saved", 0)
                for s in snap.values() if s.get("wire_codec")
            )
            if saved > 0:
                return
            time.sleep(1.0)
        pytest.fail("no codec replica ever sampled wire_bytes_saved")


@pytest.mark.slow
class TestNemesisDigestEquivalence:
    """One small seeded soak cell run codec-on and codec-off: the
    FaultPlan repro contract (byte-identical timeline per seed) must
    hold across wire formats, and both runs must stay linearizable.
    The committed NEMESIS.json wire_ab row is the full-size version of
    this (scripts/nemesis_soak.py --wire-ab)."""

    def test_small_cell_equivalent(self, tmp_path):
        import shutil
        import subprocess
        import sys
        import os
        import json

        out = tmp_path / "NEM_WIRE.json"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        r = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts",
                                          "nemesis_soak.py"),
             "--wire-ab", "--protocol", "MultiPaxos", "--seed", "1",
             "--ticks", "24", "--tick-len", "0.12", "--min-ops", "10",
             "--out", str(out)],
            env=env, cwd=repo, capture_output=True, text=True,
            timeout=900,
        )
        assert r.returncode == 0, (r.stdout[-4000:], r.stderr[-2000:])
        rows = json.loads(out.read_text())
        ab = [x for x in rows if x.get("kind") == "wire_ab"]
        assert len(ab) == 1
        row = ab[0]
        assert row["ok"], row.get("error")
        assert row["digests_identical"]
        assert row["codec_on"]["ok"] and row["codec_off"]["ok"]
        assert row["codec_on"]["wire_codec"] is True
        assert row["codec_off"]["wire_codec"] is False
