"""Protocol-agnostic SMR test harness helpers shared by the per-protocol
kernel test suites (the analog of the reference tester's checked_get/
checked_put assertion machinery, ``summerset_client/src/clients/tester.rs``).
"""

import jax.numpy as jnp
import numpy as np


def run_segment(eng, state, ns, ticks, n_prop=0, alive=None, link_up=None,
                base_start=0, collect=False):
    """Run `ticks` ticks with constant control masks; returns (state, ns, fx).

    Proposal value ids are ``(base_start + tick) * P + i`` so that in a
    from-slot-0 run the committed value of slot s is s (checkable).
    """
    G = eng.kernel.G
    P = eng.kernel.config.max_proposals_per_tick
    t = jnp.arange(ticks, dtype=jnp.int32)
    seq = {
        "n_proposals": jnp.full((ticks, G), n_prop, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((base_start + t) * P)[:, None], (ticks, G)
        ),
    }
    if alive is not None:
        seq["alive"] = jnp.broadcast_to(alive, (ticks,) + alive.shape)
    if link_up is not None:
        seq["link_up"] = jnp.broadcast_to(link_up, (ticks,) + link_up.shape)
    return eng.run_ticks(state, ns, seq, collect=collect)


def committed_values(state, g, r, window, val_key="win_val"):
    """Map {slot: value} of committed slots still inside r's window."""
    cb = int(state["commit_bar"][g, r])
    out = {}
    abs_ = np.asarray(state["win_abs"][g, r])
    val = np.asarray(state[val_key][g, r])
    for p in range(window):
        a = int(abs_[p])
        if 0 <= a < cb:
            out[a] = int(val[p])
    return out


def check_agreement(state, G, R, W, val_key="win_val"):
    """No two replicas commit different values for the same slot."""
    for g in range(G):
        merged = {}
        for r in range(R):
            vals = committed_values(state, g, r, W, val_key=val_key)
            for slot, v in vals.items():
                if slot in merged:
                    assert merged[slot] == v, (
                        f"group {g} slot {slot}: {merged[slot]} != {v}"
                    )
                else:
                    merged[slot] = v
    return True
