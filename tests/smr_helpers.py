"""Protocol-agnostic SMR test harness helpers shared by the per-protocol
kernel test suites (the analog of the reference tester's checked_get/
checked_put assertion machinery, ``summerset_client/src/clients/tester.rs``).
"""

import jax.numpy as jnp
import numpy as np


def run_segment(eng, state, ns, ticks, n_prop=0, alive=None, link_up=None,
                base_start=0, collect=False):
    """Run `ticks` ticks with constant control masks; returns (state, ns, fx).

    Proposal value ids are ``(base_start + tick) * P + i`` so that in a
    from-slot-0 run the committed value of slot s is s (checkable).
    """
    G = eng.kernel.G
    P = eng.kernel.config.max_proposals_per_tick
    t = jnp.arange(ticks, dtype=jnp.int32)
    seq = {
        "n_proposals": jnp.full((ticks, G), n_prop, jnp.int32),
        "value_base": jnp.broadcast_to(
            ((base_start + t) * P)[:, None], (ticks, G)
        ),
    }
    if alive is not None:
        seq["alive"] = jnp.broadcast_to(alive, (ticks,) + alive.shape)
    if link_up is not None:
        seq["link_up"] = jnp.broadcast_to(link_up, (ticks,) + link_up.shape)
    return eng.run_ticks(state, ns, seq, collect=collect)


def committed_values(state, g, r, window, val_key="win_val"):
    """Map {slot: value} of committed slots still inside r's window."""
    cb = int(state["commit_bar"][g, r])
    out = {}
    abs_ = np.asarray(state["win_abs"][g, r])
    val = np.asarray(state[val_key][g, r])
    for p in range(window):
        a = int(abs_[p])
        if 0 <= a < cb:
            out[a] = int(val[p])
    return out


def check_agreement(state, G, R, W, val_key="win_val"):
    """No two replicas commit different values for the same slot."""
    for g in range(G):
        merged = {}
        for r in range(R):
            vals = committed_values(state, g, r, W, val_key=val_key)
            for slot, v in vals.items():
                if slot in merged:
                    assert merged[slot] == v, (
                        f"group {g} slot {slot}: {merged[slot]} != {v}"
                    )
                else:
                    merged[slot] = v
    return True


# ---------------------------------------------------------------- EPaxos
# EPaxos states a 2-D instance space instead of a slot window, so the
# sweep checks instance-level invariants (the ones tla+/ checks for the
# slot protocols, adapted): committed (value, seq, noop, deps) agreement
# per instance, durability of committed bindings, and identical
# host-Tarjan execution order per interference bucket across replicas.

EPAXOS_COMMITTED = 3  # protocols/epaxos.py status code


def epaxos_committed_instances(st, g, r):
    """{(row, col): (val, seq, noop, deps-tuple)} committed in r's view."""
    out = {}
    R, W = st["st2"].shape[2], st["st2"].shape[3]
    for row in range(R):
        for w in range(W):
            if st["st2"][g, r, row, w] == EPAXOS_COMMITTED:
                col = int(st["abs2"][g, r, row, w])
                if col >= 0:
                    out[(row, col)] = (
                        int(st["val2"][g, r, row, w]),
                        int(st["seq2"][g, r, row, w]),
                        bool(st["noop2"][g, r, row, w]),
                        tuple(int(d) for d in st["deps2"][g, r, row, w]),
                    )
    return out


def epaxos_check_and_merge(st, G, R, acc):
    """Cross-replica committed-instance agreement + durable-binding merge.

    Asserts the full (value, seq, noop, deps) tuple agrees — EPaxos
    commits carry final attributes, so any divergence is a safety bug —
    and that no binding in ``acc`` ever changes across segments."""
    for g in range(G):
        merged = {}
        for r in range(R):
            for inst, tup in epaxos_committed_instances(st, g, r).items():
                if inst in merged:
                    assert merged[inst] == tup, (
                        f"g{g} instance {inst}: replica {r} committed "
                        f"{tup} but another replica has {merged[inst]}"
                    )
                else:
                    merged[inst] = tup
        for inst, tup in merged.items():
            key = (g,) + inst
            if key in acc:
                assert acc[key] == tup, (
                    f"committed binding changed: {key}: {acc[key]} -> {tup}"
                )
            else:
                acc[key] = tup
    return acc


def _epaxos_common_floors(st, g, R, W):
    """Per-row start columns every replica can execute from: the window
    is a ring, so late-run snapshots no longer hold column 0 — each
    executor starts at the highest column from which EVERY replica's
    window still holds a contiguous committed run up to its own
    cmt_row (identical start floors keep the emitted orders comparable)."""
    floors = [0] * R
    for row in range(R):
        for r in range(R):
            cmt = int(st["cmt_row"][g, r, row])
            lo = cmt
            while lo - 1 >= 0 and lo - 1 > cmt - W:
                p = (lo - 1) % W
                if (st["abs2"][g, r, row, p] == lo - 1
                        and st["st2"][g, r, row, p] == EPAXOS_COMMITTED):
                    lo -= 1
                else:
                    break
            floors[row] = max(floors[row], lo)
    return floors


def epaxos_exec_orders(st, G, R, W, K):
    """Host-Tarjan execution order per (group, replica), projected per
    interference bucket (vid % K).  The authoritative execution path is
    the host applier (host/epaxos_exec.py), so the sweep checks THAT
    order, not the in-kernel frontier heuristic."""
    from summerset_tpu.host.epaxos_exec import EPaxosExecutor

    orders = {}
    for g in range(G):
        floors = _epaxos_common_floors(st, g, R, W)
        for r in range(R):
            rec = []
            ex = EPaxosExecutor(
                R, W,
                apply_fn=lambda row, col, vid, noop: rec.append(
                    (row, col, int(vid), bool(noop))
                ),
            )
            ex.floor = list(floors)
            ex.advance(
                st["abs2"][g, r], st["st2"][g, r], st["seq2"][g, r],
                st["val2"][g, r], st["noop2"][g, r], st["deps2"][g, r],
                st["cmt_row"][g, r],
            )
            per_bucket = {b: [] for b in range(K)}
            for row, col, vid, noop in rec:
                per_bucket[vid % K].append((row, col, vid))
            orders[(g, r)] = per_bucket
    return orders


def epaxos_check_exec_prefix(st, G, R, W, K, require_progress=0):
    """Every pair of replicas must agree on same-bucket execution order
    up to the shorter one's length (EPaxos's determinism guarantee)."""
    orders = epaxos_exec_orders(st, G, R, W, K)
    total = 0
    for g in range(G):
        for b in range(K):
            seqs = [orders[(g, r)][b] for r in range(R)]
            for r in range(1, R):
                n = min(len(seqs[0]), len(seqs[r]))
                assert seqs[0][:n] == seqs[r][:n], (
                    f"g{g} bucket {b}: replica {r} exec order diverges "
                    f"at {[i for i in range(n) if seqs[0][i] != seqs[r][i]][:3]}"
                )
            total += max(len(s) for s in seqs)
    assert total >= require_progress, (
        f"host-Tarjan executed only {total} instances"
    )
