"""Randomized fault-schedule property sweep across protocol kernels.

The tier-4 assurance layer alongside the linearizability harness
(SURVEY.md §4: "property tests replacing TLA+ assurance"): seeded random
schedules of pauses and link partitions drive each consensus kernel
through segments of lockstep ticks on a lossy network, asserting the
safety invariants every TLA+ spec in the reference checks:

- **agreement**: no two replicas ever commit different values for the
  same slot (tla+/multipaxos_smr_style/MultiPaxos.tla consistency);
- **durability of decisions**: once a (slot -> value) binding is
  committed anywhere, later states never show a different value there;
- **EPaxos** (instance-space): committed (value, seq, noop, deps)
  agreement per instance, binding durability, and identical host-Tarjan
  execution order per interference bucket across replicas
  (tla+ checks these via the reference's dependency invariants,
  src/protocols/epaxos/dependency.rs:249-330).

Liveness is deliberately NOT asserted (schedules may partition away the
majority for a while).  Seeds are fixed — failures reproduce
deterministically.

Two tiers share one implementation (and, via the persistent XLA compile
cache, one set of compiled segment variants — segment lengths are
quantized to {32, 64, 128} so random schedules never mint new shapes):

- default (ci.sh tier 1): every kernel, one seed, ~370 ticks;
- ``slow`` superset: every kernel, 8 seeds, ~1100 ticks per seed.
"""

import random
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol

from smr_helpers import (
    check_agreement,
    committed_values,
    epaxos_check_and_merge,
    epaxos_check_exec_prefix,
    run_segment,
)

G, R, W, P = 4, 3, 32, 4
EPAXOS_K = 2  # few buckets -> heavy cross-row interference

CONFIGS = {
    "multipaxos": {},
    # the stable-leader lease plane under the same randomized drops /
    # partitions / jitter as the QL/Bodega lease planes: the lease veto
    # must never let two eras serve concurrently (lease_margin raised
    # above the sweep's max_delay_ticks=3 — the Engine refuses the
    # default margin 3 at this geometry, by design)
    "multipaxos_ll": {"leader_leases": True, "lease_margin": 4},
    "raft": {},
    "rspaxos": {"fault_tolerance": 0},
    "craft": {"fault_tolerance": 0},
    "crossword": {"fault_tolerance": 0},
    "quorumleases": {},
    "bodega": {},
    "epaxos": {"num_key_buckets": EPAXOS_K},
    # the collective quorum-tally transport (core/quorum.py) under the
    # same randomized drops/partitions/jitter: per-source [G, R] tally
    # lanes must uphold the exact safety envelope the pairwise lanes do
    # (the equivalence gate proves byte-identity; these rows prove the
    # invariants independently, on the kernels the tally plane targets)
    "multipaxos_coll": {"tally": "collective"},
    "raft_coll": {"tally": "collective"},
    "crossword_coll": {"fault_tolerance": 0, "tally": "collective"},
}


def _kernel(name):
    import dataclasses

    proto = name.partition("_")[0]  # config-variant rows: "<proto>_<tag>"
    base = make_protocol(proto, G, R, W)
    cfg = dataclasses.replace(
        base.config, max_proposals_per_tick=P, **CONFIGS[name]
    )
    return make_protocol(proto, G, R, W, cfg)


def _merge_committed(st, acc):
    """Fold every replica's committed bindings into acc, asserting no
    binding ever changes (durability of decisions)."""
    for g in range(G):
        for r in range(R):
            for slot, v in committed_values(st, g, r, W).items():
                key = (g, slot)
                if key in acc:
                    assert acc[key] == v, (
                        f"committed value changed: g{g} slot {slot}: "
                        f"{acc[key]} -> {v} (replica {r})"
                    )
                else:
                    acc[key] = v
    return acc


def _sweep(name, seed, segments):
    rng = random.Random(1000 * seed + zlib.crc32(name.encode()))
    net = NetConfig(delay_ticks=1, jitter_ticks=1, drop_rate=0.05,
                    max_delay_ticks=3)
    eng = Engine(_kernel(name), netcfg=net, seed=seed)
    state, ns = eng.init()
    epaxos = name == "epaxos"

    committed: dict = {}
    base = 1

    def _check(state):
        st = {k: np.asarray(v) for k, v in state.items()}
        if epaxos:
            epaxos_check_and_merge(st, G, R, committed)
        else:
            check_agreement(st, G, R, W)
            _merge_committed(st, committed)
        return st

    for _segment in range(segments):
        # random pause set (any subset, including majority loss) and a
        # random symmetric partition for this segment
        alive = np.ones((G, R), bool)
        for r in range(R):
            if rng.random() < 0.25:
                alive[:, r] = False
        link = np.ones((G, R, R), bool)
        if rng.random() < 0.4:
            cut = rng.randrange(R)
            link[:, cut, :] = link[:, :, cut] = False
            link[:, cut, cut] = True
        ticks = rng.choice([32, 64])  # quantized: bounded compile variants
        state, ns, _ = run_segment(
            eng, state, ns, ticks, n_prop=P,
            alive=jnp.asarray(alive), link_up=jnp.asarray(link),
            base_start=base,
        )
        base += ticks
        _check(state)

    # heal completely and confirm the invariants still hold after
    # recovery traffic (masks passed explicitly so the compiled segment
    # variant is shared with the fault segments)
    state, ns, _ = run_segment(
        eng, state, ns, 128, n_prop=P,
        alive=jnp.asarray(np.ones((G, R), bool)),
        link_up=jnp.asarray(np.ones((G, R, R), bool)),
        base_start=base,
    )
    st = _check(state)
    assert len(committed) > 0, "nothing ever committed"
    if epaxos:
        # the authoritative execution path must order interfering
        # commands identically on every replica
        epaxos_check_exec_prefix(st, G, R, W, EPAXOS_K,
                                 require_progress=G * 4)


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_fault_schedule_safety_quick(name):
    """Default-tier sweep: every kernel, one seed, ~6 segments."""
    _sweep(name, seed=3, segments=5)


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(CONFIGS))
@pytest.mark.parametrize("seed", [3, 17, 29, 41, 53, 67, 71, 89])
def test_fault_schedule_safety_full(name, seed):
    """Superset-tier sweep: 8 seeds x ~20 segments (~1100 ticks)."""
    _sweep(name, seed=seed, segments=20)
