"""Randomized fault-schedule property sweep across protocol kernels.

The tier-4 assurance layer alongside the linearizability harness
(SURVEY.md §4: "property tests replacing TLA+ assurance"): seeded random
schedules of pauses and link partitions drive each consensus kernel
through segments of lockstep ticks on a lossy network, asserting the two
safety invariants every TLA+ spec in the reference checks:

- **agreement**: no two replicas ever commit different values for the
  same slot (tla+/multipaxos_smr_style/MultiPaxos.tla consistency);
- **durability of decisions**: once a (slot -> value) binding is
  committed anywhere, later states never show a different value there.

Liveness is deliberately NOT asserted (schedules may partition away the
majority for a while); Raft-family and Paxos-family kernels share the
same harness.  Seeds are fixed — failures reproduce deterministically.
"""

import random
import zlib

import jax.numpy as jnp
import numpy as np
import pytest

from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.protocols import make_protocol

from smr_helpers import check_agreement, committed_values, run_segment

# 7 protocols x 2 seeds x ~400 lockstep ticks each: superset-run only
pytestmark = pytest.mark.slow

G, R, W, P = 2, 3, 32, 4

CONFIGS = {
    "multipaxos": {},
    "raft": {},
    "rspaxos": {"fault_tolerance": 0},
    "craft": {"fault_tolerance": 0},
    "crossword": {"fault_tolerance": 0},
    "quorumleases": {},
    "bodega": {},
}


def _kernel(name):
    import dataclasses

    base = make_protocol(name, G, R, W)
    cfg = dataclasses.replace(
        base.config, max_proposals_per_tick=P, **CONFIGS[name]
    )
    return make_protocol(name, G, R, W, cfg)


def _merge_committed(st, acc):
    """Fold every replica's committed bindings into acc, asserting no
    binding ever changes (durability of decisions)."""
    for g in range(G):
        for r in range(R):
            for slot, v in committed_values(st, g, r, W).items():
                key = (g, slot)
                if key in acc:
                    assert acc[key] == v, (
                        f"committed value changed: g{g} slot {slot}: "
                        f"{acc[key]} -> {v} (replica {r})"
                    )
                else:
                    acc[key] = v
    return acc


@pytest.mark.parametrize("name", sorted(CONFIGS))
@pytest.mark.parametrize("seed", [3, 17])
def test_random_fault_schedule_safety(name, seed):
    rng = random.Random(1000 * seed + zlib.crc32(name.encode()))
    net = NetConfig(delay_ticks=1, jitter_ticks=1, drop_rate=0.05,
                    max_delay_ticks=3)
    eng = Engine(_kernel(name), netcfg=net, seed=seed)
    state, ns = eng.init()

    committed = {}
    base = 1
    for segment in range(6):
        # random pause set (any subset, including majority loss) and a
        # random symmetric partition for this segment
        alive = np.ones((G, R), bool)
        for r in range(R):
            if rng.random() < 0.25:
                alive[:, r] = False
        link = np.ones((G, R, R), bool)
        if rng.random() < 0.4:
            cut = rng.randrange(R)
            link[:, cut, :] = link[:, :, cut] = False
            link[:, cut, cut] = True
        ticks = rng.randrange(30, 70)
        state, ns, _ = run_segment(
            eng, state, ns, ticks, n_prop=P,
            alive=jnp.asarray(alive), link_up=jnp.asarray(link),
            base_start=base,
        )
        base += ticks
        st = {k: np.asarray(v) for k, v in state.items()}
        check_agreement(st, G, R, W)
        committed = _merge_committed(st, committed)

    # heal completely and confirm the invariants still hold after
    # recovery traffic
    state, ns, _ = run_segment(
        eng, state, ns, 120, n_prop=P, base_start=base,
    )
    st = {k: np.asarray(v) for k, v in state.items()}
    check_agreement(st, G, R, W)
    _merge_committed(st, committed)
    assert len(committed) > 0, "nothing ever committed"
