"""Long-lived cluster survival: snapshot/compaction recovery semantics
and the snapshot crash-point model.

Fast half — recovery decision table on a skeleton replica (no sockets):

- unreadable snapshot + COMPACTED WAL (``snap_floor`` marker present) is
  FATAL: apply history below the floor lives only in the snapshot, so
  proceeding would silently un-commit acked state;
- unreadable snapshot + FULL (never-compacted) WAL proceeds: the replay
  alone rebuilds everything, the bad snapshot is truly ignorable;
- readable snapshot + marker: floors reconciled, no crash.

Slow half — the live crash point: a ``fault_ctl {"snap_crash": 1}``-armed
``take_snapshot`` dies between the snapshot write and the WAL truncate;
the supervisor restart must recover the new-snapshot + old-WAL overlap
without losing acked writes, and a later snapshot still compacts.
"""

import os
import pickle
import time

import numpy as np
import pytest

from summerset_tpu.host.server import ServerReplica
from summerset_tpu.host.statemach import Command, StateMachine, apply_command
from summerset_tpu.host.messages import ApiRequest, CtrlRequest
from summerset_tpu.host.payload import PayloadStore
from summerset_tpu.host.storage import LogAction, StorageHub
from summerset_tpu.protocols import make_protocol
from summerset_tpu.utils.errors import SummersetError


def _skeleton(tmp_path, me=0, G=1, R=3, W=32):
    """A ServerReplica shell with exactly the state the recovery methods
    touch — no manager, no sockets, no threads."""
    rep = ServerReplica.__new__(ServerReplica)
    rep.G = G
    rep.me = me
    rep.window = W
    rep.kernel = make_protocol("multipaxos", G, R, W)
    rep.state = rep.kernel.init_state(seed=0)
    rep.statemach = StateMachine()
    rep.payloads = PayloadStore(G)
    rep.applied = [0] * G
    rep._wslot = {}
    rep._ep_exec = {}
    rep._epaxos = False
    # live-resharding recovery state: WAL replay consults the range
    # table for straggler floor-filtering and re-seals rseal records
    from summerset_tpu.host.resharding import RangeTable
    rep.rangetab = RangeTable()
    rep._range_sealed = {}
    rep._range_adopted = set()
    rep.codewords = None
    rep._logged_vids = {g: set() for g in range(G)}
    rep._logged_keys = np.empty(0, np.int64)
    rep._snap_unreadable = None
    rep._snap_floors = None
    rep.snap_path = os.path.join(str(tmp_path), f"r{me}.snap")
    rep.wal_path = os.path.join(str(tmp_path), f"r{me}.wal")
    rep.wal = StorageHub(rep.wal_path)
    return rep


def _put_batch(key, value, req_id=1):
    return [(7, ApiRequest("req", req_id=req_id,
                           cmd=Command("put", key, value)))]


def _append(wal, entry):
    res = wal.do_sync_action(LogAction("append", entry=entry, sync=True))
    assert res.offset_ok
    return res


class TestSnapshotRecoveryDecision:
    def test_unreadable_snapshot_with_compacted_wal_is_fatal(self, tmp_path):
        rep = _skeleton(tmp_path)
        # a compacted WAL: the snap_floor marker first, then a vote row
        _append(rep.wal, ("snap_floor", [5]))
        with open(rep.snap_path, "wb") as f:
            f.write(b"\x80garbage not a pickle")
        rep._recover_from_snapshot()
        assert rep._snap_unreadable is not None
        with pytest.raises(SummersetError, match="compacted"):
            rep._recover_from_wal()
        rep.wal.stop()

    def test_unreadable_snapshot_with_full_wal_proceeds(self, tmp_path):
        rep = _skeleton(tmp_path)
        # full history: apply records only, no compaction marker
        _append(rep.wal, (0, 0, 1, _put_batch("k", "v1")))
        _append(rep.wal, (0, 1, 2, _put_batch("k", "v2")))
        with open(rep.snap_path, "wb") as f:
            f.write(b"\x80garbage not a pickle")
        rep._recover_from_snapshot()
        rep._recover_from_wal()  # must NOT raise: replay covers history
        assert rep.statemach._kv["k"] == "v2"
        assert rep.applied[0] == 2
        rep.wal.stop()

    def test_readable_snapshot_with_marker_reconciles_floors(self, tmp_path):
        rep = _skeleton(tmp_path)
        kv = {}
        apply_command(kv, Command("put", "k", "snapval"))
        with open(rep.snap_path, "wb") as f:
            pickle.dump(("kv", kv, {"applied": [5], "wslots": {"k": 4}}),
                        f)
        _append(rep.wal, ("snap_floor", [5]))
        # a post-snapshot apply record above the floor still replays
        _append(rep.wal, (0, 5, 9, _put_batch("k2", "late")))
        rep._recover_from_snapshot()
        assert rep._snap_unreadable is None
        rep._recover_from_wal()
        assert rep.statemach._kv["k"] == "snapval"
        assert rep.statemach._kv["k2"] == "late"
        assert rep.applied[0] == 6
        rep.wal.stop()

    def test_missing_snapshot_is_not_unreadable(self, tmp_path):
        rep = _skeleton(tmp_path)
        rep._recover_from_snapshot()  # absent file: a first boot
        assert rep._snap_unreadable is None
        rep.wal.stop()

    def test_missing_snapshot_with_compacted_wal_is_fatal(self, tmp_path):
        """A lost snapshot FILE is as fatal as an unreadable one once
        the WAL is compacted (e.g. a crash where the compacted-WAL
        rename reached the disk but the snapshot rename did not)."""
        rep = _skeleton(tmp_path)
        _append(rep.wal, ("snap_floor", [5]))
        rep._recover_from_snapshot()  # no file at all
        with pytest.raises(SummersetError, match="missing"):
            rep._recover_from_wal()
        rep.wal.stop()

    def test_stale_snapshot_below_marker_floor_is_fatal(self, tmp_path):
        """A readable but OLDER snapshot (floors below the compaction
        marker's) cannot cover the discarded apply history either."""
        rep = _skeleton(tmp_path)
        with open(rep.snap_path, "wb") as f:
            pickle.dump(("kv", {}, {"applied": [2], "wslots": {}}), f)
        _append(rep.wal, ("snap_floor", [5]))
        rep._recover_from_snapshot()
        assert rep._snap_floors == [2]
        with pytest.raises(SummersetError, match="stale"):
            rep._recover_from_wal()
        rep.wal.stop()


@pytest.mark.slow
class TestSnapshotCrashPoint:
    def test_armed_snapshot_crashes_then_recovers_and_compacts(
        self, tmp_path
    ):
        """The live crash-point model: snapshot written, WAL untouched,
        replica dead — restart reconciles both without losing acked
        writes, and an unarmed snapshot afterwards still compacts."""
        from test_cluster import Cluster

        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint

        cluster = Cluster("MultiPaxos", 3, str(tmp_path))
        try:
            ep = GenericEndpoint(cluster.manager_addr)
            ep.connect()
            drv = DriverClosedLoop(ep, timeout=10.0)
            for i in range(8):
                drv.checked_put(f"sc{i}", f"v{i}")

            # arm the crash point on ONE replica (a minority victim,
            # like the soak's schedules), then snapshot it: the victim
            # dies between the snapshot write and the WAL truncate and
            # its supervisor restarts it while the quorum keeps serving
            victim = 0
            old_rep = cluster.replicas[victim]
            ep.ctrl.request(CtrlRequest(
                "inject_faults", servers=[victim],
                payload={"snap_crash": 1, "seed": 0},
            ), timeout=30.0)
            ep.ctrl.request(
                CtrlRequest("take_snapshot", servers=[victim]),
                timeout=60.0,
            )
            deadline = time.monotonic() + 90
            while time.monotonic() < deadline:
                fresh = cluster.replicas.get(victim)
                if cluster.crash_reports and fresh is not None \
                        and fresh is not old_rep:
                    break
                time.sleep(0.25)
            crashed = [c for c in cluster.crash_reports
                       if "snapshot crash point" in c["error"]]
            assert len(crashed) >= 1, cluster.crash_reports
            assert cluster.replicas.get(victim) is not old_rep

            # acked writes survived the half-finished compaction
            drv2 = DriverClosedLoop(ep, timeout=15.0)
            for i in range(8):
                drv2.checked_get(f"sc{i}", f"v{i}")

            # the crash left the victim's snapshot ON DISK but its WAL
            # uncompacted; an unarmed snapshot now must compact for real
            wal_mid = {
                me: r.wal.size for me, r in cluster.replicas.items()
            }
            assert wal_mid[victim] > 0, wal_mid
            ep.ctrl.request(
                CtrlRequest("take_snapshot", servers=None), timeout=60.0
            )
            time.sleep(0.5)
            for me, r in sorted(cluster.replicas.items()):
                assert r.wal.size <= wal_mid[me], (me, r.wal.size, wal_mid)
            ep.leave()
        finally:
            cluster.stop()
