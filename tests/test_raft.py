"""Vectorized Raft kernel tests: steady state, elections, failover,
partitions, lossy links — the same SMR safety invariants the reference's
tester suite checks for Raft (SURVEY.md §4; reference ``src/protocols/raft``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from smr_helpers import check_agreement, committed_values, run_segment
from summerset_tpu.core import Engine, NetConfig
from summerset_tpu.core.netmodel import ControlInputs
from summerset_tpu.protocols import make_protocol
from summerset_tpu.protocols.raft import ReplicaConfigRaft


def make_kernel(G, R, W, P, **kw):
    cfg = ReplicaConfigRaft(max_proposals_per_tick=P, **kw)
    return make_protocol("raft", G, R, W, cfg)


def active_leaders(state, G, R, alive=None):
    lead = []
    for g in range(G):
        who = [
            r
            for r in range(R)
            if (alive is None or alive[g][r])
            and bool(state["is_leader"][g, r])
            and int(state["leader"][g, r]) == r
        ]
        lead.append(who)
    return lead


class TestSteadyState:
    def test_commit_throughput_and_values(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        T = 50
        state, ns, fx = run_segment(eng, state, ns, T, n_prop=P)
        state = {k_: np.asarray(v) for k_, v in state.items()}
        cb = state["commit_bar"][:, 0]
        assert (cb >= (T - 4) * P).all(), cb
        for g in range(G):
            vals = committed_values(state, g, 0, W)
            assert vals, "no commits"
            for slot, v in vals.items():
                assert v == slot, (slot, v)
        check_agreement(state, G, R, W)
        assert (state["commit_bar"].min(axis=1) >= cb - 3 * P).all()

    def test_population_sizes(self):
        for R in (1, 2, 3, 7):
            G, W, P = 2, 32, 4
            k = make_kernel(G, R, W, P)
            eng = Engine(k)
            state, ns = eng.init()
            state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P)
            state = {k_: np.asarray(v) for k_, v in state.items()}
            assert (state["commit_bar"][:, 0] >= (30 - 5) * P).all(), R
            check_agreement(state, G, R, W)

    def test_terms_persist_and_logs_match(self):
        # followers' logs carry the leader's term per entry
        G, R, W, P = 2, 3, 32, 2
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["term"] == 1).all()
        # every committed entry has term 1
        for g in range(G):
            for r in range(R):
                cb = st["commit_bar"][g, r]
                m = (st["win_abs"][g, r] >= 0) & (st["win_abs"][g, r] < cb)
                assert (st["win_term"][g, r][m] == 1).all()


class TestElection:
    def test_cold_start_elects_single_leader(self):
        G, R, W = 8, 5, 32
        cfg = ReplicaConfigRaft(init_leader=-1)
        k = make_protocol("raft", G, R, W, cfg)
        eng = Engine(k, seed=3)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 300, n_prop=2)
        state = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(state, G, R)
        for g, who in enumerate(leads):
            assert len(who) == 1, f"group {g}: leaders {who}"
        assert (state["commit_bar"].max(axis=1) > 0).all()
        check_agreement(state, G, R, W)

    def test_at_most_one_leader_per_term(self):
        G, R, W = 8, 5, 32
        cfg = ReplicaConfigRaft(init_leader=-1, hear_timeout_lo=15,
                                hear_timeout_hi=30)
        k = make_protocol("raft", G, R, W, cfg)
        eng = Engine(k, seed=5)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 200, n_prop=2)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        # all believed leaders in the same term must be the same replica
        for g in range(G):
            by_term = {}
            for r in range(R):
                if st["is_leader"][g, r]:
                    t = int(st["term"][g, r])
                    assert by_term.setdefault(t, r) == r, (g, t)

    @pytest.mark.slow
    def test_failover_preserves_committed(self):
        G, R, W, P = 4, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k, seed=7)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 30, n_prop=P)
        pre = {k_: np.asarray(v) for k_, v in state.items()}
        pre_committed = [committed_values(pre, g, 1, W) for g in range(G)]
        assert all(len(c) > 0 for c in pre_committed)

        alive = jnp.ones((G, R), jnp.bool_).at[:, 0].set(False)
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, alive=alive, base_start=1000
        )
        post = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(post, G, R, alive=np.asarray(alive))
        for g, who in enumerate(leads):
            assert len(who) == 1 and who[0] != 0, f"group {g}: {who}"
        live_cb = post["commit_bar"][:, 1:]
        assert (live_cb.max(axis=1) > pre["commit_bar"][:, 1:].max(axis=1)).all()
        for g in range(G):
            new_leader = leads[g][0]
            vals = committed_values(post, g, new_leader, W)
            for slot, v in pre_committed[g].items():
                if slot in vals:
                    assert vals[slot] == v, (g, slot, v, vals[slot])
        check_agreement(post, G, R, W)

        # revive 0 -> rejoins as follower and catches up
        state, ns, fx = run_segment(
            eng, state, ns, 200, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        assert (
            fin["commit_bar"][:, 0] >= fin["commit_bar"].max(axis=1) - 4 * P
        ).all()
        check_agreement(fin, G, R, W)


class TestPartitions:
    def test_minority_partition_keeps_committing(self):
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k)
        state, ns = eng.init()
        link = ControlInputs.split_links(G, R, (3, 4))
        state, ns, fx = run_segment(
            eng, state, ns, 100, n_prop=P, link_up=link
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 0] >= (100 - 10) * P).all()
        check_agreement(st, G, R, W)

    def test_majority_partition_takes_over_no_divergence(self):
        G, R, W, P = 2, 5, 32, 4
        k = make_kernel(G, R, W, P)
        eng = Engine(k, seed=11)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 20, n_prop=P)

        link = ControlInputs.split_links(G, R, (0, 1))
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, link_up=link,
            base_start=1000,
        )
        st = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(st, G, R)
        for g, who in enumerate(leads):
            majority_leads = [r for r in who if r >= 2]
            assert majority_leads, f"group {g}: {who}"
        assert (st["commit_bar"][:, 2:].max(axis=1) > 20 * P).all()
        assert (
            st["commit_bar"][:, 0] <= st["commit_bar"][:, 2:].max(axis=1)
        ).all()
        check_agreement(st, G, R, W)

        # heal: everyone converges, the stale minority leader steps down
        state, ns, fx = run_segment(
            eng, state, ns, 300, n_prop=P, base_start=2000
        )
        fin = {k_: np.asarray(v) for k_, v in state.items()}
        leads = active_leaders(fin, G, R)
        for g, who in enumerate(leads):
            assert len(who) == 1, f"group {g}: {who}"
        spread = fin["commit_bar"].max(axis=1) - fin["commit_bar"].min(axis=1)
        assert (spread <= 4 * P).all()
        check_agreement(fin, G, R, W)


class TestBackfill:
    def test_chunked_backfill_heals_hole(self):
        G, R, W, P = 2, 3, 32, 4
        cfg = ReplicaConfigRaft(max_proposals_per_tick=P, chunk_size=4)
        k = make_protocol("raft", G, R, W, cfg)
        eng = Engine(k)
        state, ns = eng.init()
        state, ns, _ = run_segment(eng, state, ns, 10, n_prop=P)

        link = ControlInputs.isolate_links(G, R, 2)
        state, ns, _ = run_segment(
            eng, state, ns, 5, n_prop=P, link_up=link,
            base_start=10,
        )
        state, ns, _ = run_segment(eng, state, ns, 40, n_prop=0)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"][:, 2] == st["commit_bar"][:, 0]).all(), st[
            "commit_bar"
        ]
        check_agreement(st, G, R, W)


class TestLossyNetwork:
    @pytest.mark.parametrize("drop", [0.1, 0.3])
    def test_agreement_under_drops_and_jitter(self, drop):
        G, R, W, P = 4, 5, 64, 4
        cfg = ReplicaConfigRaft(
            max_proposals_per_tick=P, hear_timeout_lo=40, hear_timeout_hi=80
        )
        k = make_protocol("raft", G, R, W, cfg)
        net = NetConfig(delay_ticks=1, jitter_ticks=2, drop_rate=drop,
                        max_delay_ticks=4)
        eng = Engine(k, netcfg=net, seed=23)
        state, ns = eng.init()
        state, ns, fx = run_segment(eng, state, ns, 400, n_prop=P)
        st = {k_: np.asarray(v) for k_, v in state.items()}
        assert (st["commit_bar"].max(axis=1) > 100).all()
        check_agreement(st, G, R, W)
        assert (st["term"] >= 1).all()
