"""Workload plane unit tests: WorkloadPlan seeded determinism, class
character (skew / mixes / tenancy / burst phases), ingress backpressure
(bounded queue + shed replies + telemetry/flight visibility), and the
drivers' shed handling."""

import socket
import time

import pytest

from summerset_tpu.client.drivers import (
    Backoff, DriverClosedLoop, DriverOpenLoopPaced,
)
from summerset_tpu.client.endpoint import ClientApiStub
from summerset_tpu.host.external import ExternalApi
from summerset_tpu.host.messages import ApiReply, ApiRequest
from summerset_tpu.host.statemach import Command
from summerset_tpu.host.telemetry import DECLARED, MetricsRegistry
from summerset_tpu.host.tracing import EVENT_TYPES, FlightRecorder
from summerset_tpu.host.workload import WORKLOAD_CLASSES, WorkloadPlan


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------------- plans --
@pytest.mark.parametrize(
    "wl_class", [c for c in WORKLOAD_CLASSES if c != "trace"]
)
def test_plan_seed_determinism(wl_class):
    """Same seed -> byte-identical timeline AND identical op streams;
    different seeds differ (the FaultPlan repro contract, workload
    side).  The "trace" class is excluded here: its plans come from
    from_trace (generate() refuses it) — determinism for it is proven
    in TestTracePlans below."""
    a = WorkloadPlan.generate(7, wl_class)
    b = WorkloadPlan.generate(7, wl_class)
    assert a.timeline() == b.timeline()
    assert a.digest() == b.digest()
    sa, sb = a.opstream(1), b.opstream(1)
    assert [sa.next() for _ in range(300)] == [
        sb.next() for _ in range(300)
    ]
    assert a.digest() != WorkloadPlan.generate(8, wl_class).digest()


def test_plan_classes_are_salted():
    """Seed 1 of two classes must not share a random stream."""
    assert (
        WorkloadPlan.generate(1, "read_mostly").digest()
        != WorkloadPlan.generate(1, "write_heavy").digest()
    )


def test_zipf_skew_and_mixes():
    n = 4000
    hot = WorkloadPlan.generate(3, "hot_burst").opstream(0)
    uni = WorkloadPlan.generate(3, "uniform").opstream(0)

    def top_frac(stream):
        from collections import Counter

        c = Counter(stream.next()[1] for _ in range(n))
        return c.most_common(1)[0][1] / n

    assert top_frac(hot) > 3 * top_frac(uni)
    rm = WorkloadPlan.generate(3, "read_mostly").opstream(0)
    puts = sum(1 for _ in range(n) if rm.next()[0] == "put")
    assert puts / n < 0.15
    wh = WorkloadPlan.generate(3, "write_heavy").opstream(0)
    puts = sum(1 for _ in range(n) if wh.next()[0] == "put")
    assert puts / n > 0.7


def test_value_sizes_within_bounds():
    p = WorkloadPlan.generate(5, "value_mix")
    st = p.opstream(0)
    sizes = [s for k, _, s in (st.next() for _ in range(3000))
             if k == "put"]
    assert sizes and min(sizes) >= p.value_lo - 1
    assert max(sizes) <= p.value_hi + 1
    # log-uniform: the tail must actually reach past the midpoint
    assert max(sizes) > (p.value_lo + p.value_hi) // 2


def test_multi_tenant_ranges_disjoint_with_shared_overlap():
    p = WorkloadPlan.generate(2, "multi_tenant")
    streams = [p.opstream(ci) for ci in range(p.clients)]
    privs = []
    for st in streams:
        keys = {st.next()[1] for _ in range(1500)}
        assert any(k.startswith("t_shared") for k in keys)
        privs.append({k for k in keys if not k.startswith("t_shared")})
    for i in range(len(privs)):
        for j in range(i + 1, len(privs)):
            assert not (privs[i] & privs[j])


def test_hot_burst_phases_shape():
    p = WorkloadPlan.generate(11, "hot_burst")
    assert len(p.phases) == 3
    steady, burst, recover = p.phases
    assert burst.rate_x >= 1.9           # ~2x ingress capacity
    assert steady.rate_x == recover.rate_x < 1.0
    assert p.rate_x_at(burst.tick) == burst.rate_x
    assert p.rate_x_at(p.horizon()) == 0.0  # issuing stops past horizon
    assert p.horizon() == 120


def test_unknown_class_refused():
    with pytest.raises(ValueError):
        WorkloadPlan.generate(1, "nope")


# --------------------------------------------------- scans & traces --
def test_ycsb_e_shape():
    """YCSB-E character: scans dominate (~95%), scan lengths are
    uniform in [1, scan_max], scan starts are zipfian (hot start key
    well above uniform share), and the thin put stream is live."""
    p = WorkloadPlan.generate(9, "ycsb_e")
    assert 0.9 <= p.scan_frac <= 1.0 and 6 <= p.scan_max <= 12
    st = p.opstream(0)
    n = 4000
    ops = [st.next() for _ in range(n)]
    kinds = [o[0] for o in ops]
    scans = [o for o in ops if o[0] == "scan"]
    assert len(scans) / n > 0.8
    assert 0 < kinds.count("put") / n < 0.15
    lens = {o[2] for o in scans}
    assert min(lens) >= 1 and max(lens) <= p.scan_max
    # uniform lengths actually spread across the range
    assert len(lens) >= p.scan_max - 1
    from collections import Counter

    starts = Counter(o[1] for o in scans)
    assert starts.most_common(1)[0][1] / len(scans) \
        > 3.0 / p.num_keys
    # scan knob is in the committed timeline (digest covers it)
    assert f"scan={p.scan_frac:g}@max{p.scan_max}" in p.timeline()


def test_ycsb_e_scans_issue_through_paced_driver():
    """The open-loop paced driver lowers a plan scan into a scan
    Command with the stream's length as the limit (wire-shape unit:
    no cluster)."""
    from summerset_tpu.host.statemach import Command as Cmd

    sent = []

    class _Ep:
        def send_req(self, req_id, cmd):
            sent.append(cmd)

    drv = DriverOpenLoopPaced(_Ep(), max_inflight=4)
    drv.issue("scan", "w3", 7, end="w9\x00")
    (cmd,) = sent
    assert isinstance(cmd, Cmd)
    assert (cmd.kind, cmd.key, cmd.end, cmd.limit) \
        == ("scan", "w3", "w9\x00", 7)


class TestTracePlans:
    ROWS = [
        "READ usertable user3 [ field0 ]",
        "INSERT usertable user7 [ field0=abcdefgh ]",
        "SCAN usertable user2 12 [ field0 ]",
        "UPDATE usertable user3 [ field0=x ]",
        "[OVERALL] operations so far: 4",   # runner noise: skipped
        "SCAN user5 3",                     # bare form
        "READ user9",
    ]

    def test_normalization_both_directions(self):
        p = WorkloadPlan.from_trace(self.ROWS, seed=1)
        assert p.wl_class == "trace"
        assert p.trace == (
            # put sizes = joined field-text length (brackets included),
            # floored at 8, capped at 2048
            ("get", "user3", 0),
            ("put", "user7", len("[ field0=abcdefgh ]")),
            ("scan", "user2", 12),
            ("put", "user3", len("[ field0=x ]")),
            ("scan", "user5", 3),
            ("get", "user9", 0),
        )
        # num_keys = distinct keys, put_ratio = observed put share
        assert p.num_keys == 5
        assert p.put_ratio == round(2 / 6, 3)

    def test_same_trace_same_digest(self):
        a = WorkloadPlan.from_trace(self.ROWS, seed=1)
        b = WorkloadPlan.from_trace(list(self.ROWS), seed=1)
        assert a.trace_sha() == b.trace_sha()
        assert a.digest() == b.digest()
        assert f"trace_sha={a.trace_sha()} rows=6" in a.timeline()
        # one changed row changes both digests
        c = WorkloadPlan.from_trace(
            self.ROWS[:-1] + ["READ user8"], seed=1
        )
        assert c.trace_sha() != a.trace_sha()
        assert c.digest() != a.digest()

    def test_streams_cover_all_rows_in_order(self):
        """Client streams stride the normalized rows: the union over
        one pass of every client is exactly the trace."""
        p = WorkloadPlan.from_trace(self.ROWS, seed=0, clients=2)
        got = []
        for ci in range(p.clients):
            st = p.opstream(ci)
            got.append([st.next() for _ in range(3)])
        merged = [op for i in range(3) for ci in range(2)
                  for op in [got[ci][i]]]
        assert sorted(merged) == sorted(p.trace)

    def test_file_roundtrip(self, tmp_path):
        f = tmp_path / "t.trace"
        f.write_text("\n".join(self.ROWS) + "\n")
        a = WorkloadPlan.from_trace(str(f), seed=1)
        b = WorkloadPlan.from_trace(self.ROWS, seed=1)
        assert a.digest() == b.digest()

    def test_empty_trace_refused(self):
        with pytest.raises(ValueError):
            WorkloadPlan.from_trace(["junk line", "# comment"])

    def test_generate_refuses_trace_class(self):
        with pytest.raises(ValueError):
            WorkloadPlan.generate(1, "trace")

    def test_committed_fixture_is_stable(self):
        """The committed CI trace fixture regenerates the exact digests
        the WORKLOADS.json trace cell carries."""
        import os

        path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts", "data", "ycsb_e_sample.trace",
        )
        p = WorkloadPlan.from_trace(path, seed=1)
        assert p.trace_sha() == "5ed30ebc826f2d35"
        assert len(p.trace) == 408


# ------------------------------------------------- ingress backpressure --
def test_bounded_queue_sheds_with_hint_and_telemetry():
    """Requests beyond max_pending draw shed replies (retry_after_ms
    hint), never enter the queue, and are visible in the api_shed
    counter, the api_queue_depth gauge, and typed flight events."""
    reg = MetricsRegistry()
    fl = FlightRecorder()
    api = ExternalApi(("127.0.0.1", _free_port()), max_pending=4,
                      registry=reg, flight=fl)
    try:
        stub = ClientApiStub(7, api.api_addr)
        for i in range(10):
            stub.send_req(ApiRequest(
                "req", req_id=i, cmd=Command("put", "k", "v")
            ))
        sheds = []
        try:
            while True:
                sheds.append(stub.recv_reply(timeout=1.0))
        except Exception:
            pass
        assert len(sheds) == 6
        assert all(
            r.kind == "shed" and not r.success
            and r.retry_after_ms >= 1 for r in sheds
        )
        # the queue holds exactly the bound, nothing more
        batch = api.get_req_batch(timeout=2.0)
        assert len(batch) == 4
        assert reg.counter_value("api_shed") == 6
        assert "api_queue_depth" in reg.snapshot()["gauges"]
        evs = [e for e in fl.dump()["events"]
               if e["type"] == "api_shed"]
        assert len(evs) == 6
        assert evs[0]["retry_ms"] >= 1 and evs[0]["client"] == 7
        stub.close()
    finally:
        api.stop()


def test_shed_metrics_pre_registered():
    """A zero api_shed series must exist BEFORE any overload (so "never
    overloaded" is distinguishable from "not measured"), and both lanes
    are in the telemetry smoke gate's declared set."""
    assert "api_shed" in DECLARED and "api_queue_depth" in DECLARED
    assert "api_shed" in EVENT_TYPES
    reg = MetricsRegistry()
    api = ExternalApi(("127.0.0.1", _free_port()), registry=reg)
    try:
        snap = reg.snapshot()
        assert snap["counters"].get("api_shed") == 0
        assert snap["gauges"].get("api_queue_depth") == 0
    finally:
        api.stop()


def test_conf_requests_bypass_the_bound():
    """Control-plane requests must not starve under data overload."""
    api = ExternalApi(("127.0.0.1", _free_port()), max_pending=1)
    try:
        stub = ClientApiStub(3, api.api_addr)
        stub.send_req(ApiRequest(
            "req", req_id=0, cmd=Command("put", "k", "v")
        ))
        stub.send_req(ApiRequest("conf", req_id=1,
                                 conf_delta={"responders": [0]}))
        deadline = time.monotonic() + 3.0
        got = []
        while len(got) < 2 and time.monotonic() < deadline:
            got.extend(api.get_req_batch(timeout=0.5))
        kinds = sorted(req.kind for _c, req in got)
        assert kinds == ["conf", "req"]
        stub.close()
    finally:
        api.stop()


# ----------------------------------------------------- driver shed path --
class _FakeEndpoint:
    """Minimal endpoint double: scripted replies, no sockets."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.sent = []
        self.current = 0
        self.id = 0

    def send_req(self, rid, cmd):
        self.sent.append((rid, cmd))

    def recv_reply(self, timeout=None):
        if not self.replies:
            raise socket.timeout()
        return self.replies.pop(0)

    def note_leader(self, sid):
        pass

    def reconnect(self, sid=None, timeout=None):
        pass

    def rotate(self, avoid=None, deadline=None):
        pass


def test_closed_loop_driver_returns_shed_with_hint():
    ep = _FakeEndpoint([ApiReply("shed", req_id=0, success=False,
                                 retry_after_ms=120)])
    drv = DriverClosedLoop(ep, timeout=1.0)
    rep = drv.put("k", "v")
    assert rep.kind == "shed"
    assert abs(rep.retry_after - 0.12) < 1e-9


def test_backoff_sleep_hint_is_jittered_and_capped():
    b = Backoff(cap=0.05, seed=3)
    t0 = time.monotonic()
    d = b.sleep_hint(10.0)  # absurd hint: the cap must bound it
    assert d <= 0.05 and time.monotonic() - t0 < 1.0
    # jitter is seeded: same seed, same delays
    assert Backoff(cap=1.0, seed=5).sleep_hint(0.001) == \
        Backoff(cap=1.0, seed=5).sleep_hint(0.001)


def test_open_loop_paced_shed_gates_issuing():
    ep = _FakeEndpoint([ApiReply("shed", req_id=0, success=False,
                                 retry_after_ms=200)])
    drv = DriverOpenLoopPaced(ep, timeout=1.0, seed=4)
    assert drv.issue("put", "k", "v") == 0
    out = drv.poll(0.2)
    assert len(out) == 1
    info, rep = out[0]
    assert rep.kind == "shed" and info["key"] == "k"
    assert drv.gated(time.monotonic())
    assert drv.counts["shed"] == 1
    assert not drv.inflight  # the shed op left the window


def test_open_loop_paced_window_bound_and_expiry():
    ep = _FakeEndpoint([])
    drv = DriverOpenLoopPaced(ep, timeout=0.01, seed=1, max_inflight=2)
    assert drv.issue("put", "a", "1") is not None
    assert drv.issue("put", "b", "2") is not None
    assert drv.issue("put", "c", "3") is None  # window full: dropped
    assert drv.counts["window"] == 1
    time.sleep(0.02)
    dead = drv.expired()
    assert {d["key"] for d in dead} == {"a", "b"}
    assert drv.counts["expired"] == 2 and not drv.inflight
