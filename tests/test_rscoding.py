"""GF(2^8) Reed-Solomon codec tests against a host oracle.

Parity: reference ``src/utils/rscoding.rs`` unit tests (``rscoding.rs:686+``)
— compute/reconstruct/verify round trips over schemes like (3, 2).
"""

import itertools

import numpy as np
import pytest

from summerset_tpu.ops import rscoding as rs


class TestGFField:
    def test_mul_identities(self):
        for a in range(256):
            assert rs.gf_mul(a, 1) == a
            assert rs.gf_mul(a, 0) == 0
            assert rs.gf_mul(1, a) == a

    def test_mul_commutes_and_inverse(self):
        rng = np.random.default_rng(0)
        for _ in range(200):
            a, b = int(rng.integers(1, 256)), int(rng.integers(1, 256))
            assert rs.gf_mul(a, b) == rs.gf_mul(b, a)
            assert rs.gf_mul(a, rs.gf_inv(a)) == 1

    def test_matrix_inverse(self):
        M = rs.build_encode_matrix(3, 2)[[0, 3, 4]]  # rows 0, p0, p1
        inv = rs.gf_inv_matrix_host(M)
        assert (rs.gf_matmul_host(inv, M) == np.eye(3, dtype=np.uint8)).all()

    def test_cauchy_mds(self):
        # every d-subset of rows of [I; C] must be invertible
        M = rs.build_encode_matrix(3, 2)
        for rows in itertools.combinations(range(5), 3):
            rs.gf_inv_matrix_host(M[list(rows)])  # raises if singular


def host_parity(code, data_bytes):
    """Oracle: per-byte GF matmul on the host."""
    P = code.matrix[code.d:]
    out = np.zeros((code.p, data_bytes.shape[1]), np.uint8)
    for i in range(code.p):
        for j in range(code.d):
            out[i] ^= np.array(
                [rs.gf_mul(int(P[i, j]), int(b)) for b in data_bytes[j]],
                np.uint8,
            )
    return out


class TestRSCode:
    @pytest.mark.parametrize("d,p", [(3, 2), (2, 1), (5, 3), (4, 0)])
    def test_parity_matches_byte_oracle(self, d, p):
        code = rs.RSCode(d, p, use_pallas=False)
        rng = np.random.default_rng(d * 10 + p)
        raw = rng.integers(0, 256, size=(d, 32), dtype=np.uint8)
        data = rs.pack_bytes(raw.tobytes(), d)
        parity = np.asarray(code.compute_parity(data))
        # unpack parity lanes back to bytes and compare with byte oracle
        got = np.frombuffer(
            rs.unpack_bytes(parity, p * 32), np.uint8
        ).reshape(p, 32) if p else np.zeros((0, 32), np.uint8)
        want = host_parity(code, raw)
        np.testing.assert_array_equal(got, want)

    def test_batched_shapes(self):
        code = rs.RSCode(3, 2, use_pallas=False)
        rng = np.random.default_rng(7)
        data = rng.integers(-2**31, 2**31, size=(16, 3, 8), dtype=np.int32)
        parity = np.asarray(code.compute_parity(data))
        assert parity.shape == (16, 2, 8)
        # batching == per-item
        for g in range(16):
            one = np.asarray(code.compute_parity(data[g]))
            np.testing.assert_array_equal(parity[g], one)

    @pytest.mark.parametrize(
        "present", list(itertools.combinations(range(5), 3))
    )
    def test_reconstruct_from_any_quorum(self, present):
        code = rs.RSCode(3, 2, use_pallas=False)
        rng = np.random.default_rng(sum(present))
        data = rng.integers(-2**31, 2**31, size=(3, 16), dtype=np.int32)
        parity = np.asarray(code.compute_parity(data))
        full = np.concatenate([data, parity], axis=0)
        got = np.asarray(
            code.reconstruct_data(full[list(present)], present)
        )
        np.testing.assert_array_equal(got, data)
        # reconstruct_all also restores parity
        all_ = np.asarray(code.reconstruct_all(full[list(present)], present))
        np.testing.assert_array_equal(all_, full)

    def test_verify_parity_detects_corruption(self):
        code = rs.RSCode(3, 2, use_pallas=False)
        rng = np.random.default_rng(11)
        data = rng.integers(-2**31, 2**31, size=(4, 3, 8), dtype=np.int32)
        parity = code.compute_parity(data)
        ok = np.asarray(code.verify_parity(data, parity))
        assert ok.all()
        bad = np.asarray(parity).copy()
        bad[2, 0, 3] ^= 0x40
        ok2 = np.asarray(code.verify_parity(data, bad))
        assert ok2.tolist() == [True, True, False, True]

    def test_pack_unpack_roundtrip(self):
        buf = bytes(range(256)) * 3 + b"tail"
        shards = rs.pack_bytes(buf, 3)
        assert rs.unpack_bytes(shards, len(buf)) == buf

    def test_pallas_path_on_cpu_interpreter(self):
        # exercise the pallas kernel via interpret mode on CPU
        import functools

        import jax
        from jax.experimental import pallas as pl

        code = rs.RSCode(3, 2, use_pallas=False)
        rng = np.random.default_rng(13)
        data = rng.integers(-2**31, 2**31, size=(4, 3, 128), dtype=np.int32)

        out = pl.pallas_call(
            functools.partial(rs._bitslice_kernel, rows=2, cols=3),
            out_shape=jax.ShapeDtypeStruct((4, 2, 128), np.int32),
            grid=(4, 1),
            in_specs=[
                pl.BlockSpec((2, 3, 8), lambda b, l: (0, 0, 0)),
                pl.BlockSpec((1, 3, 128), lambda b, l: (b, 0, l)),
            ],
            out_specs=pl.BlockSpec((1, 2, 128), lambda b, l: (b, 0, l)),
            interpret=True,
        )(code._parity_tbl, data)
        want = np.asarray(code.compute_parity(data))
        np.testing.assert_array_equal(np.asarray(out), want)
