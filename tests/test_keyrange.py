"""utils/keyrange coverage: KeyRangeMap sliver semantics, unbounded
ends, bisect lookup edges, and RespondersConf — routing-critical now
that the ingress-proxy tier (host/ingress.py) resolves every op's owner
shard through a KeyRangeMap."""

import pytest

from summerset_tpu.utils.bitmap import Bitmap
from summerset_tpu.utils.errors import SummersetError
from summerset_tpu.utils.keyrange import KeyRangeMap, RespondersConf


class TestKeyRangeMapLookup:
    def test_empty_map_returns_none(self):
        m = KeyRangeMap()
        assert m.get("") is None
        assert m.get("anything") is None
        assert len(m) == 0

    def test_key_below_first_start(self):
        m = KeyRangeMap()
        m.insert("m", "t", 1)
        # bisect lands at -1 for keys sorting before every start
        assert m.get("a") is None
        assert m.get("lzzz") is None
        assert m.get("m") == 1

    def test_half_open_semantics(self):
        m = KeyRangeMap()
        m.insert("b", "d", 7)
        assert m.get("b") == 7          # start inclusive
        assert m.get("c") == 7
        assert m.get("czzz") == 7
        assert m.get("d") is None       # end exclusive
        assert m.get("dzz") is None

    def test_gap_between_ranges(self):
        m = KeyRangeMap()
        m.insert("a", "b", 1)
        m.insert("x", "y", 2)
        assert m.get("a") == 1
        assert m.get("m") is None       # lands in the gap
        assert m.get("x") == 2

    def test_unbounded_end_range(self):
        m = KeyRangeMap()
        m.insert("k", None, 9)
        assert m.get("k") == 9
        assert m.get("zzzzzz") == 9     # None = +infinity
        assert m.get("j") is None

    def test_empty_string_start_covers_everything_below(self):
        m = KeyRangeMap()
        m.full_range(5)
        assert m.get("") == 5
        assert m.get("\x00") == 5
        assert m.get("zzz") == 5
        assert len(m) == 1


class TestKeyRangeMapInsertOverlap:
    def test_invalid_range_refused(self):
        m = KeyRangeMap()
        with pytest.raises(SummersetError):
            m.insert("b", "b", 1)       # empty
        with pytest.raises(SummersetError):
            m.insert("c", "a", 1)       # inverted

    def test_overwrite_middle_keeps_both_slivers(self):
        m = KeyRangeMap()
        m.insert("a", "z", 1)
        m.insert("g", "k", 2)
        assert m.get("a") == 1          # left sliver [a, g)
        assert m.get("f") == 1
        assert m.get("g") == 2          # new range [g, k)
        assert m.get("jzz") == 2
        assert m.get("k") == 1          # right sliver [k, z)
        assert m.get("y") == 1
        assert len(m) == 3

    def test_overwrite_prefix_and_suffix(self):
        m = KeyRangeMap()
        m.insert("c", "m", 1)
        m.insert("a", "e", 2)           # overlaps the left edge
        assert m.get("b") == 2
        assert m.get("d") == 2
        assert m.get("e") == 1          # surviving sliver [e, m)
        m.insert("j", "q", 3)           # overlaps the right edge
        assert m.get("i") == 1
        assert m.get("j") == 3
        assert m.get("p") == 3
        assert m.get("q") is None

    def test_insert_swallowing_whole_range(self):
        m = KeyRangeMap()
        m.insert("d", "f", 1)
        m.insert("a", "z", 2)
        assert m.get("d") == 2
        assert m.get("e") == 2
        assert len(m) == 1

    def test_overwrite_into_unbounded_range_keeps_tail(self):
        m = KeyRangeMap()
        m.insert("a", None, 1)
        m.insert("g", "k", 2)
        assert m.get("a") == 1
        assert m.get("h") == 2
        assert m.get("k") == 1          # right sliver [k, None)
        assert m.get("zzzz") == 1

    def test_unbounded_insert_truncates_everything_above(self):
        m = KeyRangeMap()
        m.insert("a", "e", 1)
        m.insert("p", "t", 2)
        m.insert("c", None, 3)
        assert m.get("a") == 1          # left sliver survives
        assert m.get("c") == 3
        assert m.get("q") == 3          # old [p, t) swallowed
        assert m.get("zz") == 3

    def test_full_range_resets(self):
        m = KeyRangeMap()
        m.insert("a", "b", 1)
        m.insert("c", "d", 2)
        m.full_range(9)
        assert len(m) == 1
        assert m.get("a") == 9 and m.get("zz") == 9

    def test_adjacent_ranges_no_overlap_kept_intact(self):
        m = KeyRangeMap()
        m.insert("a", "g", 1)
        m.insert("g", "m", 2)           # exactly adjacent
        assert m.get("fzz") == 1
        assert m.get("g") == 2
        assert len(m) == 2

    def test_items_sorted_by_start(self):
        m = KeyRangeMap()
        m.insert("x", "y", 1)
        m.insert("a", "b", 2)
        m.insert("m", "n", 3)
        assert [s for s, _e, _v in m.items()] == ["a", "m", "x"]


class TestRespondersConf:
    def test_leader_and_range_responders(self):
        rc = RespondersConf(3)
        rc.set_leader(1)
        assert rc.is_leader(1) and not rc.is_leader(0)
        bm = Bitmap.from_ids(3, [0, 2])
        rc.set_responders(("a", "m"), bm)
        assert rc.is_responder_by_key("b", 0)
        assert not rc.is_responder_by_key("b", 1)
        assert not rc.is_responder_by_key("z", 0)  # outside the range

    def test_full_range_responders(self):
        rc = RespondersConf(3)
        rc.set_responders(None, Bitmap.from_ids(3, [2]), leader=0)
        assert rc.is_responder_by_key("anything", 2)
        assert rc.leader == 0

    def test_invalid_leader_and_size_mismatch(self):
        rc = RespondersConf(3)
        with pytest.raises(SummersetError):
            rc.set_leader(3)
        with pytest.raises(SummersetError):
            rc.set_responders(None, Bitmap.from_ids(4, [0]))
