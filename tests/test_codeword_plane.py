"""Codeword payload plane: the sharded data plane behind RSPaxos / CRaft /
Crossword serving.

Fast tier: the serving-shape codec entry points (``ops/rscoding.py``) and
the :class:`~summerset_tpu.host.codeword.CodewordStore` contract (encode-
once caching, availability bitmaps, shard-subset queries, reconstruction
from arbitrary d-subsets, WAL subset selection).

Cluster tier (slow-marked; ``ci.sh`` runs it as its own tier): live
3-replica clusters assert the bandwidth economy that is the RS family's
reason to exist — peer payload frames at the leader shrink to shard-sized
(~1/d of the batch + parity/framing overhead) vs MultiPaxos full-copy —
and that committed values survive a leader crash via shard
reconstruction (``rspaxos/messages.rs:227-256``; gossip heal parity:
``crossword/gossiping.rs:14-193``).
"""

import pickle
import time

import numpy as np
import pytest

from summerset_tpu.host.codeword import CodewordStore, assigned_sids
from summerset_tpu.ops.rscoding import (
    RSCode,
    decode_payload,
    encode_payload,
)

from test_cluster import Cluster


# ---------------------------------------------------------------- fast tier
class TestServingCodec:
    def test_encode_decode_roundtrip(self):
        code = RSCode(3, 2, use_pallas=False)
        buf = bytes(range(256)) * 5 + b"tail"
        dlen, cw = encode_payload(code, buf)
        assert dlen == len(buf) and cw.shape[0] == 5
        # identity fast path (all data shards held)
        assert decode_payload(code, {i: cw[i] for i in range(3)}, dlen) == buf
        # every 3-subset of the 5 shards reconstructs
        import itertools

        for present in itertools.combinations(range(5), 3):
            held = {i: cw[i] for i in present}
            assert decode_payload(code, held, dlen) == buf

    def test_decode_needs_d_shards(self):
        code = RSCode(2, 1, use_pallas=False)
        dlen, cw = encode_payload(code, b"hello world")
        with pytest.raises(ValueError):
            decode_payload(code, {0: cw[0]}, dlen)

    def test_assigned_sids_geometry(self):
        # RSPaxos/CRaft degenerate case: shard r -> replica r
        assert assigned_sids(2, 1, 1, 5) == (2,)
        # Crossword diagonal slices wrap mod T
        assert assigned_sids(2, 3, 2, 6) == (4, 5, 0)


class TestCodewordStore:
    def _store(self, d=2, p=1):
        return CodewordStore(2, RSCode(d, p, use_pallas=False), d + p)

    def test_encode_once_and_availability(self):
        st = self._store()
        batch = [(7, ("req", i, f"k{i}", "v" * 64)) for i in range(3)]
        dlen, cw = st.encode(0, 4, batch, spr=1)
        assert cw.shape[0] == 3
        assert st.have_mask(0, 4) == 0b111
        # cached: a second encode returns identical rows, no re-encode
        dlen2, cw2 = st.encode(0, 4, batch, spr=1)
        assert dlen2 == dlen
        np.testing.assert_array_equal(cw, cw2)

    def test_reconstruct_from_parity_subset(self):
        st = self._store()
        batch = {"cmd": "put", "val": "z" * 500}
        dlen, cw = st.encode(0, 9, batch, spr=1)
        st2 = self._store()
        # hold data shard 1 + parity shard 2 only
        st2.add_shards(0, 9, dlen, {1: cw[1], 2: cw[2]})
        assert st2.can_reconstruct(0, 9)
        got = st2.reconstruct_batch(0, 9)
        assert got == batch
        # reconstruction restored the full codeword: any shard servable
        assert st2.have_mask(0, 9) == 0b111
        held = st2.shards_for(0, 9, exclude_mask=0b110)
        assert held is not None and sorted(held[1]) == [0]
        np.testing.assert_array_equal(np.asarray(held[1][0]), cw[0])

    def test_reconstruct_short_returns_none(self):
        st = self._store()
        st.add_shards(0, 3, 100, {2: np.zeros(8, np.int32)})
        assert st.reconstruct_batch(0, 3) is None

    def test_wal_shards_encoder_logs_own_slice_only(self):
        st = self._store()
        batch = ["x"] * 10
        st.encode(1, 6, batch, spr=1)
        # encoder (holds all shards): logs its assigned slice
        dlen, sub = st.wal_shards(1, 6, me=1)
        assert sorted(sub) == [1]
        # a follower holding its proposer-sent slice logs exactly it
        st3 = self._store()
        _, cw = st.encode(1, 6, batch, spr=1)
        st3.add_shards(1, 6, dlen, {0: cw[0]}, assigned=True)
        _, sub3 = st3.wal_shards(1, 6, me=0)
        assert sorted(sub3) == [0]

    def test_wal_shards_never_logs_foreign_gossip_rows(self):
        """A vote's durable record must stand for the voter's OWN slice:
        logging a gossip-received foreign shard would double-count that
        shard across voters and leave a committed value short of d
        distinct slices after a full-cluster crash."""
        st = self._store()
        batch = ["y"] * 10
        dlen, cw = st.encode(0, 8, batch, spr=1)
        follower = self._store()
        # only a foreign gossip fill arrived (own "ps" slice was lost)
        follower.add_shards(0, 8, dlen, {2: cw[2]}, assigned=False)
        assert follower.wal_shards(0, 8, me=0) is None
        # once the heal completes (all T rows restored), the follower
        # logs its own diagonal again
        follower.add_shards(0, 8, dlen, {1: cw[1]}, assigned=False)
        assert follower.reconstruct_batch(0, 8) == batch
        _, sub = follower.wal_shards(0, 8, me=0)
        assert sorted(sub) == [0]

    def test_gc_below(self):
        st = self._store()
        for vid in (1, 2, 5):
            st.encode(0, vid, ["b"], spr=1)
        assert st.gc_below(0, 3) == 2
        assert st.size(0) == 1 and st.have_mask(0, 5) != 0


# ------------------------------------------------------------- cluster tier
VALUE = "x" * 3000
KEYS = 8


def _run_workload(cluster, prefix):
    from summerset_tpu.client.drivers import DriverClosedLoop
    from summerset_tpu.client.endpoint import GenericEndpoint

    ep = GenericEndpoint(cluster.manager_addr)
    ep.connect()
    drv = DriverClosedLoop(ep)
    for i in range(KEYS):
        drv.checked_put(f"{prefix}{i}", VALUE + str(i))
    ep.leave()


def _leader_replica(cluster):
    for me, rep in sorted(cluster.replicas.items()):
        if bool(rep._is_leader[0]):
            return me, rep
    return None, None


def _wait_all_applied(cluster, prefix, timeout=45):
    """Poll until every replica's KV holds every workload key (followers
    heal through the shard-gossip plane, off the critical path)."""
    deadline = time.monotonic() + timeout
    want = {f"{prefix}{i}" for i in range(KEYS)}
    while time.monotonic() < deadline:
        reps = list(cluster.replicas.values())
        if len(reps) == cluster.n and all(
            want <= set(rep.statemach._kv) for rep in reps
        ):
            return True
        time.sleep(0.3)
    return False


@pytest.fixture(scope="module")
def mp_baseline_cluster(tmp_path_factory):
    c = Cluster("MultiPaxos", 3, tmp_path_factory.mktemp("cwmp_cluster"))
    yield c
    c.stop()


@pytest.fixture(
    scope="class", params=["RSPaxos", "CRaft", "Crossword"]
)
def cw_cluster(request, tmp_path_factory):
    cfg = {"fault_tolerance": 1}
    if request.param == "Crossword":
        # pin the assignment width to the diagonal (spr = dj) so the
        # slicing is deterministic; adaptive widening is covered by the
        # rs_cluster suite in test_cluster.py
        cfg["assignment_adaptive"] = False
    c = Cluster(
        request.param, 3,
        tmp_path_factory.mktemp(f"cw_{request.param.lower()}"),
        config=cfg,
    )
    yield c
    c.stop()


@pytest.mark.slow
class TestClusterCodewordPlane:
    def test_peer_frames_shard_sized_vs_multipaxos(
            self, cw_cluster, mp_baseline_cluster):
        """The acceptance meter: the leader's payload-plane egress per
        peer under the RS family is ~1/d of the MultiPaxos full-copy
        baseline for the same workload (d = 2 at R = 3), parity +
        pickle framing overhead included."""
        _run_workload(mp_baseline_cluster, "cwb")
        _run_workload(cw_cluster, "cwk")
        assert _wait_all_applied(cw_cluster, "cwk"), {
            me: rep.debug_state()
            for me, rep in sorted(cw_cluster.replicas.items())
        }
        _, mp_leader = _leader_replica(mp_baseline_cluster)
        _, cw_leader = _leader_replica(cw_cluster)
        assert mp_leader is not None and cw_leader is not None
        mp_total = sum(mp_leader.pp_bytes)
        assert mp_total > 2 * KEYS * len(VALUE), (
            f"baseline too small to compare: {mp_leader.pp_bytes}"
        )
        # per-payload frame size is the invariant (lifetime totals are
        # retry/election-sensitive on a loaded box): a full-copy payload
        # carries the whole ~3KB batch, a shard send ~batch/d + parity
        # and framing overhead — strictly below 0.75x at d = 2
        mp_avg = mp_total / max(1, sum(mp_leader.pp_items))
        cw_avg = sum(cw_leader.pp_bytes) / max(
            1, sum(cw_leader.pp_items)
        )
        assert cw_avg < 0.75 * mp_avg, (
            f"{cw_cluster.protocol} bytes/payload-frame {cw_avg:.0f} vs "
            f"MultiPaxos {mp_avg:.0f}: not shard-sized"
        )
        assert cw_avg > 0.2 * mp_avg
        # heal traffic is shed off the leader: gossip requests target
        # the fewest covering peers, leaders last, so the leader's
        # gossip-reply egress stays a small fraction of its propose
        # plane (not silently re-centralized through reconstruction)
        assert sum(cw_leader.cw_bytes) <= 0.5 * sum(cw_leader.pp_bytes), (
            f"leader gossip egress {cw_leader.cw_bytes} vs propose "
            f"plane {cw_leader.pp_bytes}"
        )

    def test_leader_crash_reconstructs_committed(self, cw_cluster):
        """Crash-restart the leader right after a committed burst: the
        new leader adopts from >= d distinct shard holders, rebuilds the
        batches host-side through the gossip plane, and serves every
        committed value; the crashed node itself recovers its shard
        subset from the WAL's cw records."""
        from summerset_tpu.client.drivers import DriverClosedLoop
        from summerset_tpu.client.endpoint import GenericEndpoint
        from summerset_tpu.host.messages import CtrlRequest

        _run_workload(cw_cluster, "cwx")
        leader_id, _ = _leader_replica(cw_cluster)
        assert leader_id is not None
        ep = GenericEndpoint(cw_cluster.manager_addr)
        ep.connect()
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=[leader_id],
                        durable=True),
            timeout=180,
        )
        time.sleep(2.0)
        ep2 = GenericEndpoint(cw_cluster.manager_addr)
        ep2.connect()
        drv = DriverClosedLoop(ep2)
        try:
            for i in range(KEYS):
                drv.checked_get(f"cwx{i}", expect=VALUE + str(i),
                                retries=40)
        except AssertionError as e:
            dumps = {
                me: rep.debug_state()
                for me, rep in sorted(cw_cluster.replicas.items())
            }
            raise AssertionError(f"{e}\nreplica states: {dumps}") from e
        ep2.leave()
        ep.leave()
        # the restarted node rebuilt shard state from its WAL cw records
        assert any(
            rep.codewords is not None and rep.codewords.size(0) > 0
            for rep in cw_cluster.replicas.values()
        )
