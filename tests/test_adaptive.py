"""Crossword host adaptive policy: linreg perf models + qdisc folding
drive the shards-per-replica override (parity: crossword/adaptive.rs:274+
fed by utils/linreg.rs and utils/qdisc.rs)."""

from summerset_tpu.host.adaptive import CrosswordAdaptive


def feed(ad, peer, alpha, beta, n=50):
    for i in range(n):
        x = 1000.0 * (i % 10 + 1)
        ad.observe(peer, x, alpha + beta * x)


class TestCrosswordAdaptive:
    def test_uniform_fast_peers_prefer_narrow_shards(self):
        """With all peers equally bandwidth-bound, narrower shards ship
        less data on the critical path -> choose spr < d."""
        ad = CrosswordAdaptive(5, 3, me=0, refit_interval=0.0)
        for p in range(1, 5):
            feed(ad, p, alpha=0.1, beta=0.01)  # strongly size-dependent
        assert ad.choose_spr(30000.0) == 1

    def test_slow_tail_peers_prefer_full_copies(self):
        """When the peers a larger quorum must include are very slow,
        wide assignments (smaller quorum) win -> spr = d."""
        ad = CrosswordAdaptive(5, 3, me=0, refit_interval=0.0)
        for p in (1, 2):
            feed(ad, p, alpha=0.1, beta=0.0001)   # fast, size-insensitive
        for p in (3, 4):
            feed(ad, p, alpha=1000.0, beta=0.0001)  # straggler tail
        assert ad.choose_spr(30000.0) == 3

    def test_no_samples_defaults_to_full_copy(self):
        ad = CrosswordAdaptive(5, 3, me=0)
        assert ad.choose_spr(30000.0) == 3
        assert ad.overrides(4, 0.0) == [3, 3, 3, 3]

    def test_qdisc_rate_folds_into_prediction(self):
        ad = CrosswordAdaptive(3, 2, me=0, refit_interval=0.0)
        feed(ad, 1, alpha=1.0, beta=0.0)
        base = ad.predict_ms(1, 8000.0)
        ad._qdisc.delay_ms = 5.0
        ad._qdisc.rate_gbps = 0.001  # 1 Mbit/s emulated link
        slow = ad.predict_ms(1, 8000.0)
        # 8000 B at 1 Mbit/s = 64 ms serialization + 5 ms delay
        assert slow - base > 60.0
