#!/bin/bash
# CI entry (parity: the reference's tests_unit + tests_proc workflows).
#
#   ./ci.sh            # tiers 1+2+2b: default suite + proc tests + codeword
#   ./ci.sh --full     # adds the slow-marked superset (pytest -m "")
#
# Tier 1: kernel/unit/integration suites on the 8-device virtual CPU
#         mesh (tests/conftest.py pins the platform + compile cache).
# Tier 2: real multi-process clusters (manager + 3 servers + tester
#         client over localhost TCP) for MultiPaxos AND Raft — the
#         reference's proc-test shape (.github/workflow_test.py).
# Tier 2b: the codeword payload plane — live RSPaxos/CRaft/Crossword
#         clusters asserting shard-sized peer payload frames (~1/d vs
#         MultiPaxos full-copy) and leader-crash shard reconstruction.
# Tier 2c: the nemesis soak matrix — seeded fault schedules (crash +
#         partition + message + disk faults) against live MultiPaxos /
#         Raft / RSPaxos clusters, 3 seeds each; asserts linearizable
#         histories + bounded recovery, and dumps the fault timeline +
#         operation history on failure (re-run any seed for a
#         byte-identical schedule: scripts/nemesis_soak.py --seed N).
# Tier 2d: the telemetry plane — fails if the in-kernel metric lanes
#         cost >5% of a steady tick (ablation) or a declared metric
#         name is missing from a live cluster's metrics_dump scrape;
#         regenerates TELEMETRY.json as a side effect.
# Tier 2e: graftlint — the kernel-contract verifier (C1-C10), the
#         flags-taint pass (T1/T9), and the host-plane concurrency
#         lint (H101-H104) against the committed LINT.json baseline:
#         fails on any new finding OR on baseline drift (regenerate
#         with scripts/graftlint.py and commit the diff), then runs
#         the linter's own fast test suite.
# Tier 2f: graftscope — the flight recorder + causal tracing plane:
#         live MultiPaxos cluster under pipelined load with the
#         recorder on vs off (interleaved A/B windows, adaptively
#         escalated against fsync noise, fails >5% overhead), then a
#         flight_dump scrape → merged Chrome-trace
#         export → schema check + connected api→propose→commit→apply→
#         reply chain + cross-replica frame tx/rx pairing; regenerates
#         TRACE.json as a side effect (open the full trace in
#         chrome://tracing via scripts/trace_smoke.py --trace-out).
# Tier 3 (--full): every slow-marked fault-scenario kernel test and the
#         randomized property sweep.
set -e
cd "$(dirname "$0")"

echo "=== tier 1: pytest default suite ==="
python -m pytest tests/ -q

echo "=== tier 2: process-level cluster tests (MultiPaxos, Raft) ==="
python scripts/proc_test.py

echo "=== tier 2b: codeword payload plane (RS shard serving) ==="
# the slow-marked cluster tier only — tier 1 already ran this file's
# fast (codec/store) half
python -m pytest tests/test_codeword_plane.py -q -m slow

echo "=== tier 2c: nemesis soak matrix (3 seeds x 3 protocols) ==="
python scripts/nemesis_soak.py --matrix

echo "=== tier 2d: telemetry plane (lane overhead + scrape smoke) ==="
python scripts/telemetry_smoke.py

echo "=== tier 2e: graftlint (kernel contract + flags-taint + host lint) ==="
python scripts/graftlint.py --check
python -m pytest tests/test_graftlint.py -q -m "not slow"

echo "=== tier 2f: graftscope (recorder overhead + causal-trace smoke) ==="
python scripts/trace_smoke.py

if [ "$1" = "--full" ]; then
  echo "=== tier 3: full superset (slow tests included) ==="
  python -m pytest tests/ -q -m ""
fi
echo "CI PASS"
