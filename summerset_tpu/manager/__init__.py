"""Cluster manager oracle.

Parity: reference ``src/manager/`` (SURVEY.md §2.3) — a standalone process
that assigns replica IDs, distributes peer addresses, tracks leader status,
and injects control actions (reset / pause / resume / snapshot).  It is
explicitly *not* part of protocol logic (``clusman.rs:41-116``).
"""

from .clusman import ClusterManager  # noqa: F401
