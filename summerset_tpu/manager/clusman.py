"""ClusterManager: the oracle event loop plus its two TCP endpoints.

Parity: reference ``src/manager/clusman.rs`` (oracle, :41-185) composed of
``ServerReigner`` (server-facing control, ``reigner.rs:86-160``) and
``ClientReactor`` (client-facing control, ``reactor.rs:108-140``).  Here
the two endpoints are asyncio servers feeding one event loop; IDs are
assigned on connect, joins answer with ``ConnectToPeers`` carrying the
addresses of lower-id peers (the reference's proactive-connect rule,
``multipaxos/mod.rs:717-737``), and client control requests (reset / pause
/ resume / snapshot) fan out ``CtrlMsg``s and gather replies.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, Optional, Tuple

from ..host.graftwatch import FleetSeries
from ..host.messages import CtrlMsg, CtrlReply, CtrlRequest
from ..host.resharding import RangeChange
from ..utils.errors import SummersetError
from ..utils import safetcp
from ..utils.logging import pf_info, pf_logger, pf_warn, set_me
from ..utils.timer import Timer

logger = pf_logger("clusman")


class _ServerConn:
    def __init__(self, sid, reader, writer):
        self.sid = sid
        self.reader = reader
        self.writer = writer
        self.api_addr: Optional[Tuple[str, int]] = None
        self.p2p_addr: Optional[Tuple[str, int]] = None
        self.joined = False


class ClusterManager:
    def __init__(
        self,
        protocol: str,
        srv_addr: Tuple[str, int],
        cli_addr: Tuple[str, int],
        population: int,
    ):
        self.protocol = protocol
        self.srv_addr = srv_addr
        self.cli_addr = cli_addr
        self.population = population
        # reset orchestration budgets (shrunk by unit tests)
        self.ack_timeout = 30.0
        self.rejoin_timeout = 120.0
        self.settle_delay = 0.5
        # gather fan-outs (metrics_dump / flight_dump) run against a
        # TOTAL deadline instead of a per-reply 15s wait: one slow-but-
        # alive server (fail-slow: its ctrl handling rides its limping
        # tick loop) must not stall every scrape for the full window —
        # the reply returns partial with the straggler in ``missing``
        self.gather_timeout = 5.0
        self.servers: Dict[int, _ServerConn] = {}
        self.leader: Optional[int] = None
        self.conf: Optional[dict] = None
        # registered ingress proxies (host/ingress.py): ctrl-conn cid ->
        # api_addr.  A proxy registers with CtrlRequest("proxy_join")
        # over its ctrl connection and is DE-registered the moment that
        # connection drops — so a crashed proxy disappears from the very
        # next query_info, and client rediscovery is one manager round
        # (the re-announce the proxy_crash nemesis class relies on)
        self.proxies: Dict[int, Tuple[str, int]] = {}
        self._next_sid = 0
        self._next_cid = 1000
        self._conf_seq = 0  # total order over relayed ConfChanges
        # the newest relayed install_conf payload; re-announced to every
        # later joiner so a server that (re)joins AFTER a ConfChange was
        # relayed still observes it (receivers apply newest-seq-wins, so
        # the replay can never regress a fresher conf)
        self._conf_last: Optional[dict] = None
        # live resharding (host/resharding.py): rc_id assignment plus the
        # installed/pending range sets, re-announced to proxies via
        # query_info and to (late-joining) servers via install_ranges —
        # the same newest-seq-wins contract as install_conf.  The seq is
        # seeded from the wall clock, NOT 0: surviving servers keep their
        # adopted-rc_id idempotency sets and newest-seq-seen watermarks
        # across a manager restart, so a reborn manager restarting at 0
        # would mint colliding rc_ids (seals silently skipped yet acked)
        # and re-announce seqs below every survivor's watermark —
        # resharding would silently stop converging
        self._range_seq = int(time.time() * 1000)
        self._ranges_installed: Dict[int, dict] = {}
        self._ranges_pending: Dict[int, dict] = {}
        # seal-TTL escape hatch: pending changes expired on a source
        # server's range_expire request (destination leaderless past
        # seal_ttl_ticks).  Kept forever (rc_ids are unique) so a
        # straggling re-announce can list them and a late seal replay
        # cannot resurrect a rolled-back change.  _adopt_granted is the
        # pivot that makes adopt-vs-expire race-free: both the grant
        # (adopt_intent) and the expiry resolve HERE, on the one event
        # loop, and an expiry is refused once the grant was issued.
        # Grants are deliberately non-revocable — post-grant liveness
        # rides the idempotent adopt re-propose (a new destination
        # leader re-asks and gets ok=True again), not the TTL.
        self._ranges_expired: Dict[int, dict] = {}
        self._adopt_granted: set = set()
        # graftwatch (host/graftwatch.py): the fleet time-series ring —
        # servers stream one-way watch_frame deltas on their tick
        # cadence; clients read the aligned ring via watch_series
        self.fleet = FleetSeries(retain=256)
        # kind -> list of waiter queues: every waiter sees every reply of
        # that kind (and filters by sid), so concurrent ctrl clients can't
        # steal each other's acks
        self._pending_replies: Dict[str, list] = {}
        self._join_event = asyncio.Event()
        # leader staleness: when the tracked leader's control connection
        # drops and nobody steps up within the grace window, stop steering
        # clients at a ghost (utils.Timer — the reference Timer's role as
        # liveness backbone, timer.rs:39-143)
        self._leader_timer = Timer(explode_callback=self._leader_expired)
        self._leader_lost: Optional[int] = None

    def _leader_expired(self) -> None:
        if self._leader_lost is not None and self.leader == self._leader_lost:
            pf_warn(
                logger,
                f"leader {self.leader} gone with no successor; clearing",
            )
            self.leader = None
        self._leader_lost = None

    # ------------------------------------------------------- server plane
    async def _serve_server(self, reader, writer) -> None:
        # id assignment: reuse the lowest free id (a restarted server takes
        # its old id back once the dead connection is reaped)
        sid = None
        for cand in range(self.population):
            conn = self.servers.get(cand)
            if conn is None or conn.writer.is_closing():
                sid = cand
                break
        if sid is None:
            writer.close()
            return
        conn = _ServerConn(sid, reader, writer)
        self.servers[sid] = conn
        await safetcp.send_msg(writer, (sid, self.population))
        pf_info(logger, f"assigned server id {sid}")
        try:
            while True:
                msg = await safetcp.recv_msg(reader)
                if not isinstance(msg, CtrlMsg):
                    continue
                await self._handle_ctrl(conn, msg)
                if msg.kind == "leave":
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pf_warn(logger, f"server {sid} connection lost")
        finally:
            writer.close()
            # free the id once this connection is truly gone so a
            # restarted server can reclaim it (clusman.rs assigned_ids)
            if self.servers.get(sid) is conn:
                del self.servers[sid]
            if self.leader == sid:
                self._leader_lost = sid
                self._leader_timer.kickoff(5.0)

    async def _handle_ctrl(self, conn: _ServerConn, msg: CtrlMsg) -> None:
        p = msg.payload
        if msg.kind == "new_server_join":
            conn.api_addr = p["api_addr"]
            conn.p2p_addr = p["p2p_addr"]
            conn.joined = True
            self._join_event.set()
            # the joiner proactively connects to ALL existing known peers
            # (clusman.rs:209-233) — a restarted low-id server must rebuild
            # its links itself, since live higher-id peers never re-dial
            to_peers = {
                s.sid: s.p2p_addr
                for s in self.servers.values()
                if s.joined and s.sid != conn.sid
                and not s.writer.is_closing()
            }
            await safetcp.send_msg(
                conn.writer,
                CtrlMsg(
                    "connect_to_peers",
                    {"population": self.population, "to_peers": to_peers},
                ),
            )
            if self._conf_last is not None:
                # late joiner catch-up: the _conf_seq total order only
                # helps servers that were connected at relay time — a
                # crash-restarted server re-joining after a ConfChange
                # must still converge on the same final conf
                try:
                    await safetcp.send_msg(
                        conn.writer, CtrlMsg("install_conf", self._conf_last)
                    )
                except (ConnectionError, OSError):
                    pass
            if self._ranges_installed or self._ranges_pending \
                    or self._ranges_expired:
                # same late-joiner contract for range installs: a server
                # re-joining after a RangeChange must converge on the
                # installed range table (and re-seal still-pending ones)
                try:
                    await safetcp.send_msg(
                        conn.writer,
                        CtrlMsg("install_ranges", self._ranges_payload()),
                    )
                except (ConnectionError, OSError):
                    pass
            if any(not ch.get("sealed_ok")
                   for ch in self._ranges_pending.values()):
                # a pending cutover is still waiting for cluster-wide
                # seal confirmation (a server was down during the
                # original fan-out — possibly this very rejoiner):
                # re-drive the seal now that the membership changed
                asyncio.ensure_future(self._retry_pending_seals())
            pf_info(logger, f"server {conn.sid} joined")
        elif msg.kind == "leader_status":
            if p.get("step_up"):
                self.leader = conn.sid
                self._leader_timer.cancel()
                self._leader_lost = None
            elif self.leader == conn.sid:
                self.leader = None
            pf_info(logger, f"leader status: {self.leader}")
        elif msg.kind == "responders_conf":
            self.conf = p.get("new_conf")
        elif msg.kind == "conf_forward":
            # a server that does not lead every group relays a client
            # ConfChange here; re-announce it to ALL servers so each
            # group's leader proposes the conf entry for its groups.
            # The seq is assigned synchronously (single event loop) so
            # concurrent relays are totally ordered; receivers apply
            # newest-seq-wins, which keeps every group converging on the
            # same final conf even when per-connection deliveries of two
            # racing changes interleave differently.
            self._conf_seq += 1
            payload = {"delta": p.get("delta") or {}, "seq": self._conf_seq}
            self._conf_last = payload
            for s in list(self.servers.values()):
                if s.joined and not s.writer.is_closing():
                    try:
                        await safetcp.send_msg(
                            s.writer, CtrlMsg("install_conf", payload)
                        )
                    except (ConnectionError, OSError):
                        pass
            pf_info(logger, f"conf relayed (seq {self._conf_seq}): "
                            f"{p.get('delta')}")
        elif msg.kind == "range_installed":
            # the adopting proposer's notice that a RangeChange finished
            # its cutover; move it pending -> installed and re-announce
            # the whole table (newest-seq-wins at receivers) so every
            # server — including ones that missed the original fan-out —
            # converges on the same installed set
            entry = dict(p.get("entry") or {})
            # stamp the announcing server as the range's owner sid: the
            # announcer IS the adopting proposer (destination-group
            # leader), which is where proxies should steer ops for this
            # range — per-group owner routing instead of pinning every
            # installed range to the cluster-wide announced leader
            entry.setdefault("owner", int(conn.sid))
            rc_id = int(entry.get("rc_id", 0))
            fresh = rc_id not in self._ranges_installed
            self._ranges_pending.pop(rc_id, None)
            self._ranges_installed[rc_id] = entry
            if fresh:
                self._range_seq += 1
                payload = self._ranges_payload()
                for s in list(self.servers.values()):
                    if s.joined and not s.writer.is_closing():
                        try:
                            await safetcp.send_msg(
                                s.writer,
                                CtrlMsg("install_ranges", payload),
                            )
                        except (ConnectionError, OSError):
                            pass
                pf_info(logger, f"range {rc_id} installed: "
                                f"[{entry.get('start')!r}, "
                                f"{entry.get('end')!r}) -> "
                                f"group {entry.get('group')}")
        elif msg.kind == "adopt_intent":
            # the adopting leader's barrier cleared and it asks to
            # propose the cutover.  Granting here — on the single event
            # loop that also resolves range_expire — is what makes
            # adopt-vs-seal-TTL-expiry race-free: once granted, the
            # change can no longer expire; once expired, the intent is
            # refused (the server rolls its seal back).  Re-asks after a
            # grant (a new destination leader re-driving an idempotent
            # adopt) are answered ok again.
            rc_id = int(p.get("rc_id", 0))
            ch = self._ranges_pending.get(rc_id)
            ok = (
                rc_id not in self._ranges_expired
                and (rc_id in self._adopt_granted
                     or (ch is not None and bool(ch.get("sealed_ok"))))
            )
            if ok:
                self._adopt_granted.add(rc_id)
            try:
                await safetcp.send_msg(conn.writer, CtrlMsg(
                    "adopt_decision", {"rc_id": rc_id, "ok": ok},
                ))
            except (ConnectionError, OSError):
                pass
            if not ok and rc_id in self._ranges_pending:
                pf_warn(logger, f"range {rc_id}: adopt intent from "
                                f"server {conn.sid} refused")
        elif msg.kind == "range_expire":
            # seal-TTL escape hatch: a source server reports the sealed
            # range's destination stayed leaderless past its TTL.
            # Honored only while the change is pending AND un-granted;
            # the rollback is a normal re-announce (the expired list
            # rides install_ranges), so paused/partitioned servers
            # un-seal when they drain their queues — per-connection
            # FIFO puts the expiry after any straggling seal.
            rc_id = int(p.get("rc_id", 0))
            ch = self._ranges_pending.get(rc_id)
            if ch is not None and rc_id not in self._adopt_granted:
                self._ranges_pending.pop(rc_id, None)
                self._ranges_expired[rc_id] = ch
                self._range_seq += 1
                await self._announce_ranges()
                pf_warn(logger, f"range {rc_id}: seal expired "
                                f"(reported by server {conn.sid}) — "
                                "change rolled back")
        elif msg.kind == "snapshot_up_to":
            pf_info(
                logger,
                f"server {conn.sid} snapshot up to {p.get('new_start')}",
            )
        elif msg.kind == "watch_frame":
            # graftwatch delta frame: one-way ingest into the fleet
            # time-series ring (no reply — the server's tick loop never
            # blocks on the manager)
            self.fleet.ingest(conn.sid, p)
        elif msg.kind in (
            "pause_reply", "resume_reply", "reset_reply", "snapshot_reply",
            "fault_reply", "metrics_reply", "flight_reply", "range_reply",
            "autopilot_reply",
        ):
            # waiters get (sid, payload): orchestration kinds ignore the
            # payload, gather kinds (metrics_reply) collect it per sid
            for q in self._pending_replies.get(msg.kind, ()):
                q.put_nowait((conn.sid, msg.payload))
        elif msg.kind == "leave":
            await safetcp.send_msg(conn.writer, CtrlMsg("leave_reply"))

    # ------------------------------------------------------- client plane
    async def _serve_client(self, reader, writer) -> None:
        cid = self._next_cid
        self._next_cid += 1
        await safetcp.send_msg(writer, cid)
        try:
            while True:
                req = await safetcp.recv_msg(reader)
                if not isinstance(req, CtrlRequest):
                    continue
                if req.kind == "leave":
                    await safetcp.send_msg(writer, CtrlReply("leave"))
                    break
                reply = await self._handle_request(req, cid=cid)
                await safetcp.send_msg(writer, reply)
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()
            # a proxy lives exactly as long as its ctrl connection: the
            # pop here IS the deregistration clients rediscover through
            if self.proxies.pop(cid, None) is not None:
                pf_warn(logger, f"proxy {cid} deregistered")

    def _ranges_payload(self) -> dict:
        """install_ranges payload: full installed + pending sets under a
        monotone seq (receivers apply newest-seq-wins, the install_conf
        convergence rule)."""
        return {
            "seq": self._range_seq,
            "installed": [
                self._ranges_installed[k]
                for k in sorted(self._ranges_installed)
            ],
            "pending": [
                self._ranges_pending[k]
                for k in sorted(self._ranges_pending)
            ],
            "expired": sorted(self._ranges_expired),
        }

    async def _announce_ranges(self) -> None:
        """Fan the current install_ranges payload to every joined server
        (fire-and-forget; receivers converge newest-seq-wins)."""
        payload = self._ranges_payload()
        for s in list(self.servers.values()):
            if s.joined and not s.writer.is_closing():
                try:
                    await safetcp.send_msg(
                        s.writer, CtrlMsg("install_ranges", payload)
                    )
                except (ConnectionError, OSError):
                    pass

    async def _maybe_seal_complete(self, rc_id: int, reply) -> None:
        """Grant seal-complete for a pending RangeChange iff EVERY member
        of the population acked the seal fan-out, then re-announce so the
        adopting leader's barrier can clear (_range_progress gates on the
        flag).  A partial fan-out must NOT clear it: an unreached server
        is still admitting writes to the range, and an adopt proposed
        against only the local vote window could let the old group
        overwrite a newer destination-group write after the cutover."""
        ch = self._ranges_pending.get(rc_id)
        if ch is None or ch.get("sealed_ok"):
            return
        done = set(reply.done or ())
        if len(done) < self.population:
            pf_warn(
                logger,
                f"range {rc_id}: seal acked by {sorted(done)} of "
                f"{self.population} — cutover held (sheds) until every "
                "server seals",
            )
            return
        ch["sealed_ok"] = True
        self._range_seq += 1
        await self._announce_ranges()
        pf_info(logger, f"range {rc_id}: seal confirmed cluster-wide")

    async def _retry_pending_seals(self) -> None:
        """Re-drive the seal fan-out for pending RangeChanges still
        missing cluster-wide confirmation (a server was down or
        unreachable the first time).  Sealing is idempotent per rc_id and
        every server always acks the fan-out, so re-fanning is safe; on a
        full-population ack the cutover finally unblocks."""
        for rc_id in sorted(self._ranges_pending):
            ch = self._ranges_pending.get(rc_id)
            if ch is None or ch.get("sealed_ok"):
                continue
            reply = await self._fanout_wait(
                "range_change", "range_reply",
                CtrlRequest("range_change"),
                extra={"change": dict(ch)},
            )
            await self._maybe_seal_complete(rc_id, reply)

    def _targets(self, req: CtrlRequest):
        ids = req.servers
        return [
            s for s in self.servers.values()
            if s.joined and not s.writer.is_closing()
            and (ids is None or s.sid in ids)
        ]

    async def _fanout_wait(
        self, kind: str, reply_kind: str, req: CtrlRequest, extra=None
    ) -> CtrlReply:
        """Fan a CtrlMsg to target servers, await one reply from each
        (parity: clusman.rs:382-606 orchestration handlers)."""
        targets = self._targets(req)
        q: asyncio.Queue = asyncio.Queue()
        self._pending_replies.setdefault(reply_kind, []).append(q)
        payload = dict(extra or {})
        done = []
        gathered: Dict[int, Any] = {}
        # gather kinds return per-sid payloads; orchestration kinds ack
        gather_key = {
            "metrics_dump": "snapshot", "flight_dump": "flight",
        }.get(kind)
        # gather kinds run under a TOTAL per-request deadline (a limping
        # server's ctrl replies ride its slowed tick loop — the scrape
        # returns partial, marking it); orchestration kinds keep the
        # 15s PER-REPLY wait they always had (their acks gate real
        # process control, and serial-but-live acks must not share one
        # window)
        deadline = (
            asyncio.get_event_loop().time() + self.gather_timeout
            if gather_key is not None else None
        )
        want = set()
        failed = []
        try:
            for s in targets:
                try:
                    await safetcp.send_msg(s.writer, CtrlMsg(kind, payload))
                    want.add(s.sid)
                except (ConnectionError, OSError):
                    # this target died mid-fan-out; the rest still
                    # count, but the dead sid must stay VISIBLE in
                    # `missing` (neither done nor silently absent)
                    failed.append(s.sid)
                    pf_warn(logger, f"{kind}: send to {s.sid} failed")
            while want:
                if deadline is not None:
                    budget = deadline - asyncio.get_event_loop().time()
                    if budget <= 0:
                        raise asyncio.TimeoutError
                else:
                    budget = 15.0
                sid, rp = await asyncio.wait_for(q.get(), timeout=budget)
                if sid in want:
                    want.discard(sid)
                    done.append(sid)
                    gathered[sid] = rp
        except asyncio.TimeoutError:
            pf_warn(
                logger,
                f"{kind}: deadline hit; missing {sorted(want)} — "
                "returning partial",
            )
        finally:
            self._pending_replies[reply_kind].remove(q)
        missing = sorted(set(want) | set(failed))
        if gather_key is not None:
            return CtrlReply(kind, done=done, missing=missing,
                             payloads={
                sid: rp.get(gather_key) for sid, rp in gathered.items()
            })
        return CtrlReply(kind, done=done, missing=missing)

    async def _reset_servers(self, req: CtrlRequest) -> CtrlReply:
        """Reset targets ONE AT A TIME, each step waiting for the old
        connection's reply, freeing its id, and waiting for the restarted
        server to re-join before touching the next — concurrent restarts
        would otherwise race id reclamation and mesh rebuild (parity:
        clusman.rs:382-438 pops targets one by one with an id re-assign
        wait + settle sleep in between)."""
        targets = sorted(s.sid for s in self._targets(req))
        done = []
        for sid in targets:
            conn = self.servers.get(sid)
            if conn is None or conn.writer.is_closing():
                continue
            q: asyncio.Queue = asyncio.Queue()
            self._pending_replies.setdefault("reset_reply", []).append(q)
            acked = True
            try:
                await safetcp.send_msg(
                    conn.writer,
                    CtrlMsg("reset_state", {"durable": req.durable}),
                )
                while True:  # drain until THIS sid acks
                    got, _rp = await asyncio.wait_for(
                        q.get(), timeout=self.ack_timeout
                    )
                    if got == sid:
                        break
            except (asyncio.TimeoutError, ConnectionError, OSError):
                # the server may still have received reset_state and be
                # restarting — free the id anyway so its reconnect is not
                # refused at the handshake (the old conn is dead either way)
                pf_warn(logger, f"reset: no ack from server {sid}")
                acked = False
            finally:
                self._pending_replies["reset_reply"].remove(q)
            # free the id; the restarting server reclaims it (it is the
            # only one connecting right now), then wait for its re-join
            if self.servers.get(sid) is conn:
                del self.servers[sid]
            # an un-acked server may still restart (its conn died after
            # receiving reset_state) — give it a short rejoin window, vs
            # the long one for a confirmed restart
            rejoin_deadline = asyncio.get_event_loop().time() + (
                self.rejoin_timeout if acked else self.rejoin_timeout / 8
            )
            rejoined = False
            while True:
                c = self.servers.get(sid)
                if c is not None and c.joined and c is not conn:
                    rejoined = True
                    break
                self._join_event.clear()
                budget = rejoin_deadline - asyncio.get_event_loop().time()
                if budget <= 0:
                    pf_warn(logger, f"reset: server {sid} never rejoined")
                    break
                try:
                    await asyncio.wait_for(
                        self._join_event.wait(), timeout=budget
                    )
                except asyncio.TimeoutError:
                    pass
            # settle so the rejoined server's transport mesh completes
            # before the next victim goes down (clusman.rs 500ms sleep)
            await asyncio.sleep(self.settle_delay)
            if acked and rejoined:
                done.append(sid)
        return CtrlReply("reset_state", done=done)

    async def _handle_request(self, req: CtrlRequest,
                              cid: Optional[int] = None) -> CtrlReply:
        if req.kind == "query_info":
            return CtrlReply(
                "info",
                population=self.population,
                servers={
                    s.sid: (s.api_addr, s.p2p_addr)
                    for s in self.servers.values()
                    if s.joined
                },
                leader=self.leader,
                proxies=dict(self.proxies),
                ranges=[
                    self._ranges_installed[k]
                    for k in sorted(self._ranges_installed)
                ],
            )
        if req.kind == "proxy_join":
            # ingress-proxy registration (host/ingress.py): the proxy's
            # identity is its ctrl-connection cid, so no id plane is
            # added — registration and liveness share one socket
            addr = tuple((req.payload or {}).get("api_addr") or ())
            if cid is None or len(addr) != 2:
                return CtrlReply("proxy_join", done=[])
            self.proxies[cid] = (str(addr[0]), int(addr[1]))
            pf_info(logger, f"proxy {cid} joined @ {addr}")
            return CtrlReply("proxy_join", done=[cid])
        if req.kind == "query_conf":
            return CtrlReply("conf", conf=self.conf, leader=self.leader)
        if req.kind == "pause_servers":
            return await self._fanout_wait("pause", "pause_reply", req)
        if req.kind == "resume_servers":
            return await self._fanout_wait("resume", "resume_reply", req)
        if req.kind == "reset_servers":
            return await self._reset_servers(req)
        if req.kind == "take_snapshot":
            return await self._fanout_wait(
                "take_snapshot", "snapshot_reply", req
            )
        if req.kind == "inject_faults":
            # nemesis plane: relay the fault spec to each target server
            # (host/nemesis.py composes these into seeded schedules)
            return await self._fanout_wait(
                "fault_ctl", "fault_reply", req, extra=req.payload
            )
        if req.kind == "range_change":
            # live resharding: validate, assign the rc_id, fan the seal
            # to EVERY server (each replica of the source group must stop
            # admitting ops for the range before the destination adopts),
            # and await their acks.  Only when the FULL population acked
            # does the manager grant seal-complete (re-announced via
            # install_ranges) — the adopting leader's barrier gates on
            # that flag, making the cutover two-phase; adoption then
            # rides the destination group's own log asynchronously.  The
            # reply means "sealed everywhere reachable", with conf
            # carrying the rc_id for the caller to poll installation via
            # query_info.
            try:
                change = RangeChange.from_payload(dict(req.payload or {}))
            except SummersetError as e:
                pf_warn(logger, f"range_change refused: {e}")
                return CtrlReply("error")
            self._range_seq += 1
            change = dataclasses.replace(change, rc_id=self._range_seq)
            self._ranges_pending[change.rc_id] = change.as_dict()
            reply = await self._fanout_wait(
                "range_change", "range_reply", req,
                extra={"change": change.as_dict()},
            )
            await self._maybe_seal_complete(change.rc_id, reply)
            return dataclasses.replace(reply, conf={"rc_id": change.rc_id})
        if req.kind == "autopilot_ctl":
            # autopilot actuation (host/autopilot.py driver in act
            # mode): relay the act to the target servers and await
            # their applied-acks — the same orchestration shape as
            # inject_faults
            return await self._fanout_wait(
                "autopilot_ctl", "autopilot_reply", req,
                extra=req.payload,
            )
        if req.kind == "metrics_dump":
            # telemetry scrape: gather each live server's snapshot
            # (device metric lanes + host registry + sampled traces)
            return await self._fanout_wait(
                "metrics_dump", "metrics_reply", req
            )
        if req.kind == "flight_dump":
            # graftscope scrape: gather each live server's flight-
            # recorder ring (payload relays e.g. {"last_n": n})
            return await self._fanout_wait(
                "flight_dump", "flight_reply", req, extra=req.payload
            )
        if req.kind == "watch_series":
            # graftwatch: answered straight from the manager's fleet
            # ring — no server fan-out, so a limping replica can't
            # stall the dashboard (its STALE frames are the signal)
            return CtrlReply(
                "watch_series", payloads={"fleet": self.fleet.export()}
            )
        return CtrlReply("unknown")

    # ------------------------------------------------------------- runner
    async def run(self) -> None:
        set_me("m")
        srv = await safetcp.tcp_bind_with_retry(
            self.srv_addr[0], self.srv_addr[1], self._serve_server
        )
        cli = await safetcp.tcp_bind_with_retry(
            self.cli_addr[0], self.cli_addr[1], self._serve_client
        )
        pf_info(
            logger,
            f"manager up: srv @ {self.srv_addr} cli @ {self.cli_addr} "
            f"population {self.population}",
        )
        async with srv, cli:
            await asyncio.gather(srv.serve_forever(), cli.serve_forever())
