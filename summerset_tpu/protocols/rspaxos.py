"""Vectorized RSPaxos: MultiPaxos + Reed-Solomon erasure-coded payloads.

Parity target: reference ``src/protocols/rspaxos/`` (SURVEY.md §2.5) — the
leader encodes each instance's request batch with RS scheme
``(d = majority, p = population - majority)`` and sends replica ``r`` only
shard ``r`` (``rspaxos/mod.rs:597-608``); an instance commits only after
``majority + fault_tolerance`` Accept acks (``rspaxos/messages.rs:435``);
a new leader reconstructs voted values from >= ``majority`` distinct shard
holders in its Prepare quorum, treating shard-starved slots as provably
uncommitted no-ops once >= ``population - fault_tolerance`` replicas have
replied (``rspaxos/messages.rs:227-256``); committed-but-shard-starved
replicas issue Reconstruct reads (``rspaxos/leadership.rs:142-165``,
``messages.rs:468-560``).

TPU-first redesign on the MultiPaxos lockstep skeleton:

- The device runs the consensus **control plane** only: ``win_val`` stays an
  int32 reference to the host payload store, which holds the actual
  RS-coded shards (encode/decode via :class:`summerset_tpu.ops.rscoding
  .RSCode`'s bit-sliced GF(2^8) Pallas kernel).  What the kernel tracks is
  *shard availability*: replica ``r`` holding its vote for slot ``s`` means
  "shard ``r`` of value ``win_val[s]`` is available at ``r``".
- **Commit tally**: the cumulative-frontier quorum count is simply raised
  from ``quorum`` to ``quorum + fault_tolerance`` (``commit_k``).
- **Prepare adoption** cannot take one best sender's lane: a voted value is
  recoverable only if >= ``d`` distinct senders voted it at the max ballot.
  The candidate accumulates a per-slot voter bitmap ``prep_voters`` (reset
  when a higher per-slot ballot appears) across campaign ticks; at step-up
  a slot is adopted if its voter count reaches ``d``, no-op-filled
  otherwise.  Step-up therefore requires either every tallied slot to be
  recoverable, or promises from >= ``population - fault_tolerance``
  replicas (the reference's two-tier rule).
- **Execution gating**: a replica executes slot ``s`` only when it can
  materialize the full value — it tracks a contiguous *full-data frontier*
  ``full_bar`` (always at the leader, whose proposals carry full batches:
  the ``[f2_lo, f2_hi)`` leader interval) and fills it at followers with
  RECON_REQ/RECON_REPLY rounds: a needy replica broadcasts its wanted range
  and peers reply with the prefix their current (ballot-safe) voting run
  covers; ``d``-th largest cover across peers advances ``full_bar``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core import quorum as quorum_lib
from ..utils.bitmap import popcount
from . import register_protocol
from .common import (
    NULL_VAL,
    kth_largest,
    not_self,
    range_cover,
)
from .multipaxos import (
    MultiPaxosKernel,
    ReplicaConfigMultiPaxos,
)

RECON_REQ = 256    # needy replica -> all: want full data for [rq_lo, rq_hi)
RECON_REPLY = 512  # peer -> needy: my shards cover [rq_lo, rr_hi)


@dataclasses.dataclass
class ReplicaConfigRSPaxos(ReplicaConfigMultiPaxos):
    """Extends the MultiPaxos knobs (parity: ``ReplicaConfigRSPaxos``,
    ``rspaxos/mod.rs:49-110``)."""

    fault_tolerance: int = 0     # extra acks required beyond majority
    recon_interval: int = 4      # ticks between Reconstruct read rounds


@register_protocol("RSPaxos")
class RSPaxosKernel(MultiPaxosKernel):
    # the reconstruct-request record (wanted range + requester ballot)
    # is destination-independent like the accept-reply record: under
    # tally="collective" the gossip plane's rq_* lanes ride per-source
    # [G, R] broadcast lanes too (RECON_REQ flags stay per-link);
    # rr_hi stays pairwise — a reply's cover range genuinely depends on
    # the requester it answers
    TALLY_LANES = MultiPaxosKernel.TALLY_LANES + (
        "rq_bal", "rq_lo", "rq_hi",
    )

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigRSPaxos | None = None,
    ):
        config = config or ReplicaConfigRSPaxos()
        super().__init__(num_groups, population, window, config)
        # RS scheme (d, p) = (majority, population - majority),
        # rspaxos/mod.rs:597-608
        self.num_data = self.quorum
        self.num_parity = population - self.quorum
        if config.fault_tolerance > self.num_parity:
            raise ValueError(
                f"invalid fault_tolerance {config.fault_tolerance} "
                f"(max {self.num_parity})"
            )

    # commit needs majority + fault_tolerance cumulative acks
    @property
    def commit_k(self) -> int:
        return self.quorum + self.config.fault_tolerance

    # ------------------------------------------------------------------ state
    def _extra_state(self, st, seed):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        st.update(
            # candidate-side per-slot adoption tally (ring-indexed like win)
            prep_voters=jnp.zeros((G, R, W), jnp.uint32),
            prep_pbal=jnp.zeros((G, R, W), i32),
            prep_pval=jnp.full((G, R, W), NULL_VAL, i32),
            # full-data frontier + the leader's full interval [f2_lo, f2_hi)
            full_bar=jnp.zeros((G, R), i32),
            f2_lo=jnp.zeros((G, R), i32),
            f2_hi=jnp.zeros((G, R), i32),
            # reconstruction read bookkeeping
            recon_cover=jnp.zeros((G, R, R), i32),
            recon_cnt=jnp.zeros((G, R), i32),
        )
        # (a warm-start leader needs no f2 seeding: [0, 0) grows with its
        # proposals, which carry full batches)

    def _extra_outbox(self, out):
        G, R = self.G, self.R
        i32 = jnp.int32
        rq_shape = (G, R) if self.collective_tally else (G, R, R)
        out.update(
            rq_bal=jnp.zeros(rq_shape, i32),
            rq_lo=jnp.zeros(rq_shape, i32),
            rq_hi=jnp.zeros(rq_shape, i32),
            rr_hi=jnp.zeros((G, R, R), i32),
        )

    # ------------------------------------------------- accept-side additions
    def _ingest_accept(self, s, c):
        super()._ingest_accept(s, c)
        # a run re-based by another proposer invalidates the leader-era
        # full interval (those slots' values may be superseded)
        foreign = c.a_new_run & (c.a_src != c.rid)
        s["f2_lo"] = jnp.where(foreign, s["full_bar"], s["f2_lo"])
        s["f2_hi"] = jnp.where(foreign, s["full_bar"], s["f2_hi"])

    def _ingest_snapshot(self, s, c):
        super()._ingest_snapshot(s, c)
        # install jumps the full-data frontier too (host transfers KV state)
        s["full_bar"] = jnp.where(
            c.sn_adv, jnp.maximum(s["full_bar"], c.sn_to), s["full_bar"]
        )
        s["f2_lo"] = jnp.where(c.sn_adv, c.sn_to, s["f2_lo"])
        s["f2_hi"] = jnp.where(c.sn_adv, c.sn_to, s["f2_hi"])

    # --------------------------------------------- prepare-reply accumulation
    def _ingest_prepare_reply(self, s, c):
        self._prep_reply_common(s, c)
        ok = c.pr_ok
        eff_bal = jnp.where(ok, c.pr_lane_bal, 0)     # [G, R, R_src, W]
        tick_best = eff_bal.max(axis=2)               # [G, R, W]
        best_src = eff_bal.argmax(axis=2)[:, :, None, :]
        tick_val = jnp.take_along_axis(
            jnp.broadcast_to(c.pr_lane_val, eff_bal.shape), best_src, axis=2
        )[:, :, 0, :]

        # the tally tracks the max-ballot value per slot, but counts shard
        # holders BY VALUE, at any ballot: shards of the same value id are
        # byte-identical regardless of the ballot they were voted at (a
        # higher-ballot proposal of a committed slot must carry the committed
        # value), so discarding lower-ballot same-value voters — as the
        # reference's per-ballot absorb does, rspaxos/messages.rs:185-195 —
        # would let a partially-propagated re-proposal mask a committed
        # slot's recoverability and no-op it in the full-quorum tier below
        higher = tick_best > s["prep_pbal"]
        new_pbal = jnp.maximum(s["prep_pbal"], tick_best)
        new_pval = jnp.where(higher, tick_val, s["prep_pval"])
        src_bits = (jnp.uint32(1) << jnp.arange(
            self.R, dtype=jnp.uint32
        ))[None, None, :, None]
        tick_voters = jnp.where(
            ok & (c.pr_lane_val == new_pval[:, :, None, :]),
            src_bits,
            jnp.uint32(0),
        ).sum(axis=2, dtype=jnp.uint32)
        value_kept = ~higher | (tick_val == s["prep_pval"])
        s["prep_voters"] = (
            jnp.where(value_kept, s["prep_voters"], jnp.uint32(0))
            | tick_voters
        )
        s["prep_pbal"] = new_pbal
        s["prep_pval"] = new_pval
        self._on_prep_tally(s, c, ok, value_kept, new_pval)

    def _on_prep_tally(self, s, c, ok, value_kept, new_pval):
        """Hook: extra per-slot lanes tracked alongside the shard-holder
        tally (Crossword records the min assignment width among voters)."""

    def _on_explode(self, s, c, explode):
        # seed the tally with the candidate's own voted window
        W = self.W
        trig = jnp.where(explode, s["commit_bar"], s["prep_trigger"])
        _, abs_ad = range_cover(trig, trig + W, W)
        own_vote = (
            explode[..., None]
            & (s["win_abs"] == abs_ad)
            & (s["win_bal"] > 0)
        )
        c.own_vote = own_vote
        own_bit = (jnp.uint32(1) << c.rid.astype(jnp.uint32))[..., None]
        s["prep_voters"] = jnp.where(
            explode[..., None],
            jnp.where(own_vote, own_bit, jnp.uint32(0)),
            s["prep_voters"],
        )
        s["prep_pbal"] = jnp.where(
            explode[..., None], jnp.where(own_vote, s["win_bal"], 0),
            s["prep_pbal"],
        )
        s["prep_pval"] = jnp.where(
            explode[..., None],
            jnp.where(own_vote, s["win_val"], NULL_VAL),
            s["prep_pval"],
        )

    # -------------------------------------------------- step-up + adoption
    def _prep_recover_need(self, s):
        """Hook: per-slot distinct-voter count needed to rebuild a tallied
        value (Crossword derives it from the voted assignment widths)."""
        return jnp.full((self.G, self.R, self.W), self.num_data, jnp.int32)

    def _win_condition(self, s, c):
        W = self.W
        cfg = self.config
        trig = s["prep_trigger"]
        _, abs_ad = range_cover(trig, trig + W, W)
        tallied = abs_ad < s["prep_hi"][..., None]
        cnt = popcount(s["prep_voters"])
        # slot resolvable: untouched, or enough distinct shards to rebuild
        need = self._prep_recover_need(s)
        slot_ok = ~tallied | (s["prep_pbal"] == 0) | (cnt >= need)
        acks = popcount(s["prep_acks"])
        full_quorum = acks >= (self.R - cfg.fault_tolerance)
        return c.candidate & (
            (acks >= self.quorum) & slot_ok.all(axis=2) | full_quorum
        )

    def _adopt_on_win(self, s, c, win, m_re, abs_re):
        # recoverable slots adopt the tallied value; the rest (including
        # shard-starved ones, provably uncommitted by the win condition)
        # become no-ops — all stamped at the new ballot
        cnt = popcount(s["prep_voters"])
        need = self._prep_recover_need(s)
        recover = m_re & (s["prep_pbal"] > 0) & (cnt >= need)
        s["win_val"] = jnp.where(
            m_re, jnp.where(recover, s["prep_pval"], NULL_VAL), s["win_val"]
        )
        s["win_abs"] = jnp.where(m_re, abs_re, s["win_abs"])
        s["win_bal"] = jnp.where(m_re, s["bal_max"][..., None], s["win_bal"])
        # the winner reconstructs every adopted value from its quorum's
        # shards (host-side decode), so its full interval covers the
        # re-proposed tail; [full_bar, trigger) still heals via recon reads
        s["f2_lo"] = jnp.where(win, s["prep_trigger"], s["f2_lo"])
        s["f2_hi"] = jnp.where(win, s["next_slot"], s["f2_hi"])

    def _leader_propose(self, s, c):
        super()._leader_propose(s, c)
        # fresh proposals carry full batches at the leader
        s["f2_hi"] = jnp.where(
            c.active_leader, jnp.maximum(s["f2_hi"], s["next_slot"]), s["f2_hi"]
        )

    # ------------------------------------------------- execution gating
    def _exec_gate(self, s, c):
        # merge the leader-era full interval into the contiguous frontier
        s["full_bar"] = jnp.where(
            s["full_bar"] >= s["f2_lo"],
            jnp.maximum(s["full_bar"], s["f2_hi"]),
            s["full_bar"],
        )
        if self.config.exec_follows_commit:
            s["exec_bar"] = jnp.minimum(s["commit_bar"], s["full_bar"])
        else:
            s["exec_bar"] = jnp.maximum(
                s["exec_bar"],
                jnp.minimum(
                    jnp.minimum(s["commit_bar"], s["full_bar"]),
                    c.inputs["exec_floor"].astype(jnp.int32),
                ),
            )

    # ------------------------------------------------- reconstruction reads
    def _extra_sends(self, s, c, out, oflags):
        R = self.R
        cfg = self.config
        ns_mask = not_self(self.G, R)
        inbox = c.inbox

        # ingest RECON_REPLY: per-peer cover frontiers (monotone; covered
        # slots are committed so their values never change)
        rr_valid = (c.flags & RECON_REPLY) != 0
        s["recon_cover"] = jnp.where(
            rr_valid,
            jnp.maximum(s["recon_cover"], inbox["rr_hi"]),
            s["recon_cover"],
        )
        # own shards count within the current ballot-safe run
        own_cover = jnp.where(
            s["vote_from"] <= s["full_bar"], s["vote_bar"], s["full_bar"]
        )
        eye = jnp.eye(R, dtype=jnp.bool_)[None]
        cover = jnp.where(eye, own_cover[..., None], s["recon_cover"])
        self._advance_full_bar(s, cover)

        # send RECON_REQ every recon_interval ticks while starved
        goal = self._recon_goal(s)
        needy = s["full_bar"] < goal
        s["recon_cnt"] = jnp.where(needy, s["recon_cnt"] - 1, cfg.recon_interval)
        fire = needy & (s["recon_cnt"] <= 0)
        s["recon_cnt"] = jnp.where(fire, cfg.recon_interval, s["recon_cnt"])
        do_rq = fire[..., None] & ns_mask
        oflags = oflags | jnp.where(do_rq, jnp.uint32(RECON_REQ), 0)
        if self.collective_tally:
            # per-source tally records (core/quorum.py); RECON_REQ flags
            # above stay per-link
            out["rq_bal"] = quorum_lib.source_lane(fire, s["bal_max"])
            out["rq_lo"] = quorum_lib.source_lane(fire, s["full_bar"])
            out["rq_hi"] = quorum_lib.source_lane(fire, goal)
        else:
            out["rq_bal"] = jnp.where(do_rq, s["bal_max"][..., None], 0)
            out["rq_lo"] = jnp.where(do_rq, s["full_bar"][..., None], 0)
            out["rq_hi"] = jnp.where(do_rq, goal[..., None], 0)

        # serve RECON_REQ: my current run covers [rq_lo, min(rq_hi,
        # vote_bar)) iff it reaches back to rq_lo and is at a ballot >= the
        # requester's bal_max (such votes are the committed values below the
        # requester's commit bar)
        rq = quorum_lib.pair_views(
            inbox, ("rq_bal", "rq_lo", "rq_hi"), self.collective_tally
        )
        rq_valid = (c.flags & RECON_REQ) != 0
        can_serve = (
            rq_valid
            & (s["vote_bal"][..., None] >= rq["rq_bal"])
            & (s["vote_from"][..., None] <= rq["rq_lo"])
        )
        cover_hi = jnp.where(
            can_serve,
            jnp.minimum(rq["rq_hi"], s["vote_bar"][..., None]),
            0,
        )
        # the inbox is receiver-oriented [G, self, src], so replying to each
        # requester writes the same [G, self, dst=src] layout the outbox uses
        do_rr = can_serve & (cover_hi > rq["rq_lo"]) & ns_mask
        oflags = oflags | jnp.where(do_rr, jnp.uint32(RECON_REPLY), 0)
        out["rr_hi"] = jnp.where(do_rr, cover_hi, 0)
        return oflags

    def _advance_full_bar(self, s, cover):
        """Hook: advance the contiguous full-data frontier from per-peer
        cover frontiers (Crossword uses a per-slot assignment-aware tally)."""
        d_cover = kth_largest(cover, self.num_data)
        s["full_bar"] = jnp.clip(
            jnp.maximum(s["full_bar"], d_cover),
            s["full_bar"],
            s["commit_bar"],
        )

    def _recon_goal(self, s):
        """Hook: upper end of the wanted reconstruct range (Crossword
        subtracts the gossip tail-ignore margin)."""
        return s["commit_bar"]

    def _effects_extra(self, s, c):
        return {"full_bar": s["full_bar"]}
