"""Vectorized Bodega: roster-leased always-local linearizable reads.

Parity target: reference ``src/protocols/bodega/`` (SURVEY.md §2.5; the
Bodega thesis chapter) — a ballot-numbered ``RespondersConf`` roster
(leader + per-key-range responder sets) is installed through all-to-all
**config leases**; roster responders serve linearizable reads locally, and
writes wait for acks from *all* responders of their touched keys:

- ``conflease.rs:10-47`` ``heard_new_conf``: a higher-ballot conf triggers
  revoke -> adopt -> regrant, and step-up if the new conf names me leader;
- ``localread.rs:8-26``: a stable leader / responder serves local reads
  when majority-leased and ``commit_bar >= peer_accept_max``;
- ``localread.rs:32-56`` ``commit_condition``: quorum AND all responders of
  every written key acked;
- ``durability.rs:137-175`` + ``messages.rs:419-525``: follower-to-follower
  ``AcceptNotice`` gossip releases held reads once a majority accepted;
- ``heartbeat.rs:85-108``: peer hear-timeout composes a filtered conf
  (dead peer dropped, self volunteering as leader) at a higher ballot.

TPU-first redesign on the MultiPaxos lockstep skeleton:

- **The conf is state, not log**: ``(conf_bal, conf_leader, conf_resp[K])``
  per replica, with responder bitmaps per key bucket (the host's
  ``KeyRangeMap`` folds real key ranges onto buckets, ``utils/keyrange.py``).
  CONF broadcasts carry it every tick; a receiver holding a higher-ballot
  pending conf *defers installing* until all of its own outgoing leases at
  the old conf have lapsed — the lockstep form of the reference's blocking
  revoke-then-adopt (``conflease.rs:22-38``), which is exactly what makes
  the lease chain safe: nobody acks new-epoch writes while a lease it
  granted under the old roster may still be serving reads.
- **Epoch-tagged consensus traffic**: every replica's per-tick CONF lane
  doubles as the epoch tag; receivers defer ACCEPT/PREPARE/HEARTBEAT from
  senders whose conf ballot differs from their own installed conf (the
  ballot-coupling the reference gets from confs riding heartbeats,
  ``mod.rs:306-318``).
- **Config leases are all-to-all countdowns**: grantor-side expiry runs
  ``lease_margin`` ticks longer than the granted length (clock-free safety,
  same role as ``T_guard``); active revoke (REVOKE/REVOKE_REPLY) shortcuts
  the wait on conf changes.  Grants carry the grantor's accept frontier;
  the holder's ``peer_accept_max`` is the min-over-time of the quorum-th
  smallest grant-time accept bar (``conflease.rs:267-282``).
- **Write barrier is a per-slot tally**: slot ``s`` commits once a quorum
  of cumulative ack frontiers pass it AND every responder of
  ``bucket(value)`` has acked past it (no-ops skip the responder clause).
- **AcceptNotice** is a per-tick accept-frontier + liveness beacon lane;
  the reference's majority-notice read release is subsumed by the
  exec-gated pending check (see the NOTE at the AN ingest), and commit
  learning rides the leader heartbeat path, which respects the barrier.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from . import register_protocol
from .common import (
    INF as _INF,
    initial_ballot,
    kth_largest,
    make_greater_ballot,
    not_self,
    range_cover,
    take_lane,
    take_src,
)
from .multipaxos import (
    ACCEPT,
    HEARTBEAT,
    PREPARE,
    SNAPSHOT,
    MultiPaxosKernel,
    ReplicaConfigMultiPaxos,
)

CONF = 1024          # conf broadcast (doubles as the sender's epoch tag)
GRANT = 2048         # config-lease grant/refresh
REVOKE = 4096        # active revoke request
REVOKE_REPLY = 8192  # holder confirms the lease is dropped
AN = 16384           # accept-frontier notice (AcceptNotice analog)

_EPOCH_BITS = jnp.uint32(ACCEPT | PREPARE | HEARTBEAT | SNAPSHOT)


@dataclasses.dataclass
class ReplicaConfigBodega(ReplicaConfigMultiPaxos):
    """Extends the MultiPaxos knobs (parity: ``ReplicaConfigBodega``,
    ``bodega/mod.rs``)."""

    lease_len: int = 12          # config lease length granted (ticks)
    lease_margin: int = 4        # grantor-side slack > max one-way delay
    grant_interval: int = 4      # lease refresh period (ticks)
    num_key_buckets: int = 8     # key-hash buckets (host KeyRangeMap folds)
    init_responders: int = 0     # initial all-bucket responders bitmap
    conf_timeout: int = 40       # ticks without hearing a peer -> failover


@register_protocol("Bodega")
class BodegaKernel(MultiPaxosKernel):
    broadcast_lanes = frozenset(
        {"bw_abs", "bw_bal", "bw_val", "bw_noop", "cf_resp"}
    )

    # the no-op marker lane is part of the voted window content (the conf
    # itself is lease-installed, not logged: a restarted replica re-learns
    # it from heartbeats, conflease.rs heard_new_conf)
    DURABLE_WINDOWS = MultiPaxosKernel.DURABLE_WINDOWS + ("win_noop",)

    # host conf-change plane: the announcing replica + leader/responder
    # targets + optional key bucket (contract metadata, core/protocol.py)
    EXTRA_INPUTS = MultiPaxosKernel.EXTRA_INPUTS + (
        ("conf_init", "g"),
        ("conf_leader_target", "g"),
        ("conf_resp_target", "g"),
        ("conf_bucket", "g"),
    )

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigBodega | None = None,
    ):
        config = config or ReplicaConfigBodega()
        if config.leader_leases:
            raise ValueError(
                "Bodega's roster leases subsume leader leases; the base "
                "MultiPaxos leader_leases flag is not supported here"
            )
        super().__init__(num_groups, population, window, config)
        if config.num_key_buckets > 30:
            raise ValueError("num_key_buckets must be <= 30 (int32 bitmaps)")

    # ------------------------------------------------------------------ state
    def _extra_state(self, st, seed):
        G, R, K = self.G, self.R, self.config.num_key_buckets
        cfg = self.config
        i32 = jnp.int32
        # warm-start roster mirrors the warm-start leader
        if cfg.init_leader >= 0:
            bal0 = int(initial_ballot(cfg.init_leader))
            lead0 = cfg.init_leader
        else:
            bal0, lead0 = 0, -1
        st.update(
            conf_bal=jnp.full((G, R), bal0, i32),
            conf_leader=jnp.full((G, R), lead0, i32),
            conf_resp=jnp.full((G, R, K), cfg.init_responders, i32),
            pend_bal=jnp.zeros((G, R), i32),
            pend_leader=jnp.full((G, R), -1, i32),
            pend_resp=jnp.zeros((G, R, K), i32),
            # all-to-all lease countdowns + the conf ballot they bind to
            lease_out=jnp.zeros((G, R, R), i32),
            lease_in=jnp.zeros((G, R, R), i32),
            in_bal=jnp.zeros((G, R, R), i32),
            grant_cnt=jnp.zeros((G, R), i32),
            # grant-time peer accept bars -> peer_accept_max
            pab=jnp.full((G, R, R), _INF, i32),
            pam=jnp.full((G, R), _INF, i32),
            # AN-fed peer liveness for conf failover
            conf_alive=jnp.full((G, R, R), cfg.conf_timeout, i32),
            # explicit no-op marks: value ids are opaque host references
            # (0 is a legal id), so bucket classification must not key off
            # the NULL_VAL sentinel
            win_noop=jnp.zeros((G, R, self.W), jnp.bool_),
        )

    def _extra_outbox(self, out):
        G, R, K = self.G, self.R, self.config.num_key_buckets
        i32 = jnp.int32
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        out.update(
            cf_bal=pair(), cf_leader=pair(),
            cf_resp=jnp.zeros((G, R, K), i32),
            gr_len=pair(), gr_bal=pair(), gr_abar=pair(),
            rv_bal=pair(), rvr_bal=pair(),
            bw_noop=jnp.zeros((G, R, self.W), jnp.bool_),
        )

    # ------------------------------------------------- conf + lease ingest
    def _ingest_heartbeat(self, s, c):
        cfg = self.config
        R, K = self.R, self.config.num_key_buckets
        inbox = c.inbox
        flags = c.flags
        eye = jnp.eye(R, dtype=jnp.bool_)[None]

        # epoch gate: defer consensus traffic from senders whose installed
        # conf differs from ours (their per-tick CONF lane is the tag; an
        # absent CONF bit reads as cf_bal 0, which matches only the
        # no-conf cold-start epoch).  The cf_bal read must itself be
        # gated on cf_valid: senders only populate the lane under the
        # CONF bit, so the gate is semantically free — but without it a
        # dead link's stale cf_bal garbage flows into the epoch
        # predicate (flags-taint rule T1, graftlint)
        cf_valid = (flags & CONF) != 0
        epoch_ok = (
            jnp.where(cf_valid, inbox["cf_bal"], 0)
            == s["conf_bal"][..., None]
        )
        c.flags = jnp.where(
            epoch_ok, flags, flags & ~_EPOCH_BITS
        )

        super()._ingest_heartbeat(s, c)

        # countdowns tick once per lockstep tick
        for k in ("lease_out", "lease_in", "grant_cnt", "conf_alive"):
            s[k] = jnp.maximum(s[k] - 1, 0)

        # --- CONF ingest: stage the highest conf ballot heard as pending
        eff = jnp.where(cf_valid, inbox["cf_bal"], -1)
        best = eff.max(axis=2)
        src = eff.argmax(axis=2).astype(jnp.int32)
        newer = (best > s["conf_bal"]) & (best > s["pend_bal"])
        s["pend_leader"] = jnp.where(
            newer, take_src(inbox["cf_leader"], src), s["pend_leader"]
        )
        new_resp = take_lane(inbox["cf_resp"], src)  # [G, R, K]
        s["pend_resp"] = jnp.where(
            newer[..., None], new_resp, s["pend_resp"]
        )
        s["pend_bal"] = jnp.where(newer, best, s["pend_bal"])

        # --- REVOKE ingest: drop held leases, confirm to grantor (echoing
        # the revoke's conf ballot so stale replies can't release leases
        # granted under a later conf)
        rv_valid = (c.flags & REVOKE) != 0
        s["lease_in"] = jnp.where(rv_valid, 0, s["lease_in"])
        c.rv_reply = rv_valid
        c.rv_echo = inbox["rv_bal"]
        # REVOKE_REPLY ingest: grantor releases its countdown only when the
        # echoed ballot matches its still-installed conf (pre-install epoch)
        rr_valid = ((c.flags & REVOKE_REPLY) != 0) & (
            inbox["rvr_bal"] == s["conf_bal"][..., None]
        )
        s["lease_out"] = jnp.where(rr_valid, 0, s["lease_out"])

        # --- GRANT ingest: hold the lease, learn grant-time accept bars
        g_valid = (c.flags & GRANT) != 0
        s["lease_in"] = jnp.where(g_valid, inbox["gr_len"], s["lease_in"])
        s["in_bal"] = jnp.where(g_valid, inbox["gr_bal"], s["in_bal"])
        g_cur = g_valid & (inbox["gr_bal"] == s["conf_bal"][..., None])
        s["pab"] = jnp.where(g_cur, inbox["gr_abar"], s["pab"])

        # --- AN ingest: per-tick liveness beacon + peer accept frontiers.
        # NOTE deliberately NOT a commit fast path: a quorum of same-ballot
        # accept frontiers proves a slot *chosen* in the Paxos sense, but
        # Bodega's commit additionally requires acks from all responders of
        # the written keys — advancing commit_bar on chosen-ness alone would
        # let a responder skip a write it never saw.  The reference uses
        # majority AcceptNotices only to release reads held behind accepts
        # the responder itself holds (``localread.rs:81,225,265``); here
        # that release is subsumed by the (conservative) exec-gated pending
        # check in the effects.
        an_valid = (c.flags & AN) != 0
        s["conf_alive"] = jnp.where(
            an_valid | eye, cfg.conf_timeout, s["conf_alive"]
        )

        # --- conf failover: a conf member went silent -> stage a filtered
        # conf at a higher ballot (heartbeat.rs:85-108)
        dead = (s["conf_alive"] <= 0) & ~eye  # [G, R, R_peer]
        dead_bits = jnp.sum(
            jnp.where(dead, jnp.int32(1) << jnp.arange(R, dtype=jnp.int32), 0),
            axis=2,
        )
        lead_dead = jnp.where(
            s["conf_leader"] >= 0,
            ((dead_bits >> jnp.clip(s["conf_leader"], 0, R - 1)) & 1) != 0,
            False,
        )
        in_roster = (
            jnp.any((s["conf_resp"] & dead_bits[..., None]) != 0, axis=2)
            | lead_dead
        )
        fire = (
            in_roster
            & (s["pend_bal"] <= s["conf_bal"])
            & (s["conf_bal"] > 0)
        )
        new_bal = make_greater_ballot(
            jnp.maximum(s["bal_max"], s["pend_bal"]), c.rid
        )
        s["pend_leader"] = jnp.where(
            fire,
            jnp.where(lead_dead, c.rid, s["conf_leader"]),
            s["pend_leader"],
        )
        s["pend_resp"] = jnp.where(
            fire[..., None],
            s["conf_resp"] & ~dead_bits[..., None],
            s["pend_resp"],
        )
        s["pend_bal"] = jnp.where(fire, new_bal, s["pend_bal"])

        # --- host-initiated conf change (client ConfChange analog,
        # request.rs:12-90): inputs name the announcing replica + targets
        tgt_init = c.inputs.get("conf_init")
        if tgt_init is not None:
            i32 = jnp.int32
            init = jnp.broadcast_to(
                tgt_init[:, None].astype(i32), (self.G, R)
            )
            lead_t = jnp.broadcast_to(
                c.inputs["conf_leader_target"][:, None].astype(i32),
                (self.G, R),
            )
            resp_t = jnp.broadcast_to(
                c.inputs["conf_resp_target"][:, None].astype(i32),
                (self.G, R),
            )
            bucket_t = c.inputs.get("conf_bucket")
            if bucket_t is None:
                bucket_t = jnp.full((self.G,), -1, i32)
            bucket_t = jnp.broadcast_to(
                bucket_t[:, None].astype(i32), (self.G, R)
            )
            want = (init == c.rid) & (s["pend_bal"] <= s["conf_bal"])
            new_bal2 = make_greater_ballot(
                jnp.maximum(s["bal_max"], s["pend_bal"]), c.rid
            )
            karange = jnp.arange(K, dtype=i32)[None, None, :]
            sel = (bucket_t[..., None] < 0) | (
                karange == bucket_t[..., None]
            )
            s["pend_resp"] = jnp.where(
                want[..., None] & sel,
                resp_t[..., None],
                jnp.where(want[..., None], s["conf_resp"], s["pend_resp"]),
            )
            s["pend_leader"] = jnp.where(want, lead_t, s["pend_leader"])
            s["pend_bal"] = jnp.where(want, new_bal2, s["pend_bal"])

        # --- install the pending conf once every outgoing lease at the
        # old conf has lapsed (the revoke-then-adopt barrier)
        pending = s["pend_bal"] > s["conf_bal"]
        clear = jnp.max(s["lease_out"], axis=2) <= 0
        install = pending & clear
        s["conf_bal"] = jnp.where(install, s["pend_bal"], s["conf_bal"])
        s["conf_leader"] = jnp.where(
            install, s["pend_leader"], s["conf_leader"]
        )
        s["conf_resp"] = jnp.where(
            install[..., None], s["pend_resp"], s["conf_resp"]
        )
        s["bal_max"] = jnp.maximum(s["bal_max"], s["conf_bal"])
        s["pab"] = jnp.where(install[..., None], _INF, s["pab"])
        s["pam"] = jnp.where(install, _INF, s["pam"])
        c.conf_pending = pending & ~install
        c.conf_installed = install

        # new-conf leader steps up through the normal campaign path
        stepup = (
            install
            & (s["pend_leader"] == c.rid)
            & (s["bal_prepared"] < s["conf_bal"])
        )
        s["hb_cnt"] = jnp.where(stepup, 0, s["hb_cnt"])

        # pam: min-over-time of the quorum-th smallest grant-time bar
        pab_eff = jnp.where(
            jnp.eye(R, dtype=jnp.bool_)[None],
            s["vote_bar"][..., None],
            s["pab"],
        )
        q_small = kth_largest(pab_eff, R - self.quorum + 1)
        s["pam"] = jnp.minimum(s["pam"], q_small)

    # --------------------------------------------------- no-op lane plumbing
    def _on_accept_write(self, s, c, m_acc, a_src):
        lane = take_lane(c.inbox["bw_noop"], a_src)
        s["win_noop"] = jnp.where(m_acc, lane, s["win_noop"])

    def _on_adopt(self, s, c, adopt, best_src):
        lane = c.inbox["bw_noop"][:, None, :, :]  # [G, 1, R_src, W]
        shape = adopt.shape[:2] + (self.R,) + adopt.shape[2:]
        best = jnp.take_along_axis(
            jnp.broadcast_to(lane, shape), best_src, axis=2
        )[:, :, 0, :]
        s["win_noop"] = jnp.where(adopt, best, s["win_noop"])

    def _adopt_on_win(self, s, c, win, m_re, abs_re):
        hole = m_re & (s["win_abs"] != abs_re)
        super()._adopt_on_win(s, c, win, m_re, abs_re)
        s["win_noop"] = s["win_noop"] | hole

    def _leader_propose(self, s, c):
        super()._leader_propose(s, c)
        s["win_noop"] = jnp.where(c.m_new, False, s["win_noop"])

    # ------------------------------------------------------- write barrier
    def _commit_cap(self, s, c, peer_f):
        # per-slot responder clause: every responder of bucket(value) must
        # have acked past the slot (localread.rs:32-56); the first slot
        # failing it caps the commit frontier
        R, W, K = self.R, self.W, self.config.num_key_buckets
        _, abs_w = range_cover(s["commit_bar"], s["commit_bar"] + W, W)
        bucket = jnp.where(
            ~s["win_noop"], s["win_val"] % K, -1
        )  # no-ops skip
        resp_bits = jnp.take_along_axis(
            s["conf_resp"], jnp.clip(bucket, 0, K - 1), axis=2
        )
        resp_bits = jnp.where(bucket >= 0, resp_bits, 0)  # [G, R, W]
        member = (
            (resp_bits[..., None] >> jnp.arange(R, dtype=jnp.int32)) & 1
        ) != 0  # [G, R, W, R_peer]
        acked = peer_f[..., None, :] > abs_w[..., None]  # [G, R, W, R_peer]
        resp_ok = ~jnp.any(member & ~acked, axis=3)  # [G, R, W]
        slot_known = s["win_abs"] == abs_w
        in_rng = abs_w < s["next_slot"][..., None]
        fail = in_rng & ~(resp_ok & slot_known)
        fail_abs = jnp.min(jnp.where(fail, abs_w, _INF), axis=2)
        return fail_abs

    # ----------------------------------------------------- sends + leases
    def _extra_sends(self, s, c, out, oflags):
        R = self.R
        cfg = self.config
        ns_mask = not_self(self.G, R)
        eye = jnp.eye(R, dtype=jnp.bool_)[None]

        # CONF: every tick (epoch tag + propagation)
        has_conf = (s["conf_bal"] > 0)[..., None] & ns_mask
        oflags = oflags | jnp.where(has_conf, jnp.uint32(CONF), 0)
        out["cf_bal"] = jnp.where(has_conf, s["conf_bal"][..., None], 0)
        out["cf_leader"] = jnp.where(
            has_conf, s["conf_leader"][..., None], 0
        )
        out["cf_resp"] = s["conf_resp"]

        # AN: per-tick liveness beacon (see ingest NOTE)
        do_an = jnp.broadcast_to(ns_mask, (self.G, R, R))
        oflags = oflags | jnp.where(do_an, jnp.uint32(AN), 0)
        out["bw_noop"] = s["win_noop"]

        # GRANT: refresh config leases at the installed conf; while a conf
        # change is pending, stop refreshing (passive revoke) and actively
        # REVOKE instead
        s["grant_cnt"] = jnp.where(
            c.conf_pending | (s["grant_cnt"] > 0), s["grant_cnt"],
            cfg.grant_interval,
        )
        fire = (
            ~c.conf_pending
            & (s["conf_bal"] > 0)
            & (s["grant_cnt"] == cfg.grant_interval)
        )
        do_grant = fire[..., None] & ns_mask
        oflags = oflags | jnp.where(do_grant, jnp.uint32(GRANT), 0)
        out["gr_len"] = jnp.where(do_grant, cfg.lease_len, 0)
        out["gr_bal"] = jnp.where(do_grant, s["conf_bal"][..., None], 0)
        out["gr_abar"] = jnp.where(do_grant, s["vote_bar"][..., None], 0)
        s["lease_out"] = jnp.where(
            do_grant, cfg.lease_len + cfg.lease_margin, s["lease_out"]
        )

        do_rv = (
            c.conf_pending[..., None] & (s["lease_out"] > 0) & ns_mask
        )
        oflags = oflags | jnp.where(do_rv, jnp.uint32(REVOKE), 0)
        out["rv_bal"] = jnp.where(do_rv, s["conf_bal"][..., None], 0)
        do_rvr = c.rv_reply & ns_mask
        oflags = oflags | jnp.where(do_rvr, jnp.uint32(REVOKE_REPLY), 0)
        out["rvr_bal"] = jnp.where(do_rvr, c.rv_echo, 0)

        return oflags

    # ------------------------------------------------------------- effects
    def _effects_extra(self, s, c):
        cfg = self.config
        R, K = self.R, cfg.num_key_buckets
        eye = jnp.eye(R, dtype=jnp.bool_)[None]
        lease_ok = (
            (s["lease_in"] > 0)
            & (s["in_bal"] == s["conf_bal"][..., None])
            & ~eye
        )
        lease_cnt = jnp.sum(lease_ok.astype(jnp.int32), axis=2)
        majority_leased = (lease_cnt + 1) >= self.quorum
        quiet = s["commit_bar"] >= s["pam"]

        # per-bucket local-read service: responder membership + no pending
        # write on the bucket in the un-executed window tail
        member = (
            (s["conf_resp"] >> c.rid[..., None]) & 1
        ) != 0  # [G, R, K]
        tail = (
            (s["win_bal"] > 0)
            & (s["win_abs"] >= s["exec_bar"][..., None])
            & (
                s["win_abs"]
                < jnp.maximum(s["vote_bar"], s["next_slot"])[..., None]
            )
            & ~s["win_noop"]
        )
        bucket = s["win_val"] % K
        karange = jnp.arange(K, dtype=jnp.int32)[None, None, :]
        pend = jnp.any(
            tail[..., None, :] & (bucket[..., None, :] == karange[..., None]),
            axis=3,
        )  # [G, R, K]
        can_serve = (
            member
            & ~pend
            & (majority_leased & quiet)[..., None]
        )
        local_buckets = jnp.sum(
            jnp.where(can_serve, jnp.int32(1) << karange, 0), axis=2
        )
        stable_leader = (
            c.active_leader
            & (s["conf_leader"] == c.rid)
            & majority_leased
            & quiet
        )
        return {
            "conf_bal": s["conf_bal"],
            "conf_leader": s["conf_leader"],
            "lease_cnt": lease_cnt,
            "stable_leader": stable_leader,
            "local_read_buckets": local_buckets,
            "n_local_buckets": jnp.sum(
                can_serve.astype(jnp.int32), axis=2
            ),
        }
