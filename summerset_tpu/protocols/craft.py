"""Vectorized CRaft: Raft + Reed-Solomon erasure coding with full-copy
fallback.

Parity target: reference ``src/protocols/craft/`` (SURVEY.md §2.5; FAST'20
CRaft) — the leader erasure-codes each entry with RS scheme
``(d = majority, p = population - majority)`` and ships replica ``r`` shard
``r``; a coded entry commits only at ``majority + fault_tolerance`` match
acks, while a full-copy entry commits at plain ``majority``
(``craft/messages.rs:307-312``); when more than ``fault_tolerance`` peers
look dead the leader falls back to full-copy replication
(``craft/leadership.rs:75-137, 280-287``; the reference latches the switch
one-way and notes follow-up works propose more gradual mechanisms —
``craft/mod.rs:280-283``).

TPU-first redesign on the Raft lockstep skeleton:

- **Per-slot mode bits** instead of the reference's global latch: each
  appended entry is stamped coded/full (``win_full`` + ``bw_full`` lane)
  from the leader's live peer count at append time, which *is* the "more
  gradual fallback" the reference's NOTE points at — mode switches
  per-entry, both directions, and each slot's commit threshold is pinned at
  propose time so the mixed-mode commit frontier stays well-defined.
- **Commit frontier with per-slot thresholds**: two cumulative tallies
  ``f_coded = kth(match, majority + ft)`` and ``f_full = kth(match,
  majority)``; the commit bar walks forward over window slots while each
  slot's own threshold is satisfied (a vectorized prefix-scan, not a
  per-slot loop).
- **Peer liveness** is a per-peer reply-countdown at the leader (the
  conservative reply-counter scheme of the reference Heartbeater,
  ``src/server/heartbeat.rs:244-276``).
- **Execution gating + reconstruction**: like the RSPaxos kernel, replicas
  execute only below their full-data frontier ``full_bar``.  Full-copy
  slots received via AppendEntries are immediately full; coded slots at
  followers heal via RECON_REQ/RECON_REPLY rounds where peers report both a
  shard-cover (own shard, k-th largest over ``d`` peers) and a full-cover
  (their own ``full_bar``, any single peer suffices).  Serving is gated on
  the *server's own commit bar* — committed prefixes are unique, so shards
  from different peers are always of the same value.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..utils.bitmap import popcount
from . import register_protocol
from .common import kth_largest, not_self, range_cover, take_lane
from .raft import RaftKernel, ReplicaConfigRaft

RECON_REQ = 128    # needy replica -> all: want full data for [rq_lo, rq_hi)
RECON_REPLY = 256  # peer -> needy: shard cover rr_hi, full cover rr_fhi


@dataclasses.dataclass
class ReplicaConfigCRaft(ReplicaConfigRaft):
    """Extends the Raft knobs (parity: ``ReplicaConfigCRaft``,
    ``craft/mod.rs:46-100``)."""

    fault_tolerance: int = 0     # extra acks required for coded commits
    recon_interval: int = 4      # ticks between Reconstruct read rounds
    alive_timeout: int = 20      # ticks without any reply -> peer dead


@register_protocol("CRaft")
class CRaftKernel(RaftKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_term", "bw_val", "bw_full"})

    # the per-slot full-copy/coded mode marker is voted content (the
    # commit tally depends on it, cf. craft full-copy fallback)
    DURABLE_WINDOWS = RaftKernel.DURABLE_WINDOWS + ("win_full",)

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigCRaft | None = None,
    ):
        config = config or ReplicaConfigCRaft()
        super().__init__(num_groups, population, window, config)
        self.num_data = self.quorum
        self.num_parity = population - self.quorum
        if config.fault_tolerance > self.num_parity:
            raise ValueError(
                f"invalid fault_tolerance {config.fault_tolerance} "
                f"(max {self.num_parity})"
            )

    # ------------------------------------------------------------------ state
    def _extra_state(self, st, seed):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        st.update(
            win_full=jnp.zeros((G, R, W), jnp.bool_),
            # leader-side conservative peer liveness countdowns
            alive_cnt=jnp.full(
                (G, R, R), self.config.alive_timeout, i32
            ),
            # full-data frontier + reconstruction bookkeeping (cf. rspaxos)
            full_bar=jnp.zeros((G, R), i32),
            recon_cover=jnp.zeros((G, R, R), i32),
            recon_fcover=jnp.zeros((G, R, R), i32),
            recon_cnt=jnp.zeros((G, R), i32),
        )

    def _extra_outbox(self, out):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        out.update(
            rq_lo=jnp.zeros((G, R, R), i32),
            rq_hi=jnp.zeros((G, R, R), i32),
            rr_hi=jnp.zeros((G, R, R), i32),
            rr_fhi=jnp.zeros((G, R, R), i32),
            bw_full=jnp.zeros((G, R, W), jnp.bool_),
        )

    # ---------------------------------------------------- mode-bit plumbing
    def _on_ae_write(self, s, c, m_acc, a_src):
        lane_full = take_lane(c.inbox["bw_full"], a_src)
        s["win_full"] = jnp.where(m_acc, lane_full, s["win_full"])

    def _append_mode(self, s, c):
        # full-copy when more than `fault_tolerance` peers look dead
        # (craft/leadership.rs:280-287); evaluated per append tick, so the
        # mode heals back to coded once peers return (the own column never
        # decays — it is refreshed unconditionally in the liveness update)
        n_dead = jnp.sum((s["alive_cnt"] <= 0).astype(jnp.int32), axis=2)
        return n_dead > self.config.fault_tolerance

    def _on_append(self, s, c, m_new, mode):
        s["win_full"] = jnp.where(m_new, mode[..., None], s["win_full"])

    def _leader_append(self, s, c):
        super()._leader_append(s, c)
        # while in fallback the leader also re-stamps its *pending* coded
        # tail [commit_bar, log_end) as full-copy so the whole frontier can
        # commit at the majority threshold — the per-slot form of the
        # reference's global-latch switch, which reinterprets every entry at
        # the full-copy threshold and accepts the documented weakening
        # against extra failures during the period (craft/leadership.rs:82,
        # messages.rs:307-312); the leader itself holds full data for the
        # re-stamped range (it is above own_from or within its full_bar), so
        # followers heal through its full-cover recon replies
        mode = self._append_mode(s, c)
        m, abs_s = range_cover(s["commit_bar"], s["log_end"], self.W)
        restamp = (
            (s["is_leader"] & mode)[..., None]
            & m
            & (s["win_abs"] == abs_s)
            # only own-term appends: the leader provably holds their full
            # batches, so a majority-committed re-stamped slot always has a
            # full-cover source (stricter than the reference, which accepts
            # unrecoverable commits in this corner)
            & (abs_s >= s["own_from"][..., None])
        )
        s["win_full"] = s["win_full"] | restamp

    def _try_win(self, s, c):
        super()._try_win(s, c)
        # a fresh leader starts from optimistic liveness (coded mode) and
        # lets the countdown discover dead peers
        s["alive_cnt"] = jnp.where(
            c.win[..., None], self.config.alive_timeout, s["alive_cnt"]
        )

    # --------------------------------------------------- liveness countdown
    def _ingest_ae_reply(self, s, c):
        super()._ingest_ae_reply(s, c)
        # any reply (vote or append) refreshes the sender's liveness
        heard = c.ar_valid | c.vr_valid | ((c.flags & RECON_REPLY) != 0)
        eye = jnp.eye(self.R, dtype=jnp.bool_)[None]
        s["alive_cnt"] = jnp.where(
            heard | eye,
            self.config.alive_timeout,
            s["alive_cnt"] - s["is_leader"][..., None].astype(jnp.int32),
        )

    # ------------------------------------------- per-slot-threshold commit
    def _commit_frontier(self, s, c, peer_f):
        W = self.W
        f_full = kth_largest(peer_f, self.quorum)
        f_coded = kth_largest(
            peer_f, self.quorum + self.config.fault_tolerance
        )
        # walk the window from commit_bar: slot a commits iff its own
        # stamped threshold frontier exceeds a, and all before it did
        m, abs_s = range_cover(s["commit_bar"], s["log_end"], W)
        thresh = jnp.where(s["win_full"], f_full[..., None], f_coded[..., None])
        in_win = s["win_abs"] == abs_s
        ok = (~m) | (in_win & (thresh > abs_s))
        # first failing absolute slot = the new commit frontier
        fail_at = jnp.where(m & ~ok, abs_s, jnp.iinfo(jnp.int32).max)
        return jnp.minimum(jnp.min(fail_at, axis=2), s["log_end"])

    # ------------------------------------------------- execution gating
    def _exec_gate(self, s, c):
        W = self.W
        # full-copy slots received intact extend the full frontier without
        # reconstruction; the leader's own appends are always full (it holds
        # the un-coded batch), coded slots at followers need recon covers
        eye = jnp.eye(self.R, dtype=jnp.bool_)[None]
        own_shard_cover = jnp.where(
            eye, jnp.iinfo(jnp.int32).max, s["recon_cover"]
        )
        d_cover = (
            kth_largest(own_shard_cover, self.num_data)
            if self.num_data > 1
            else s["commit_bar"]
        )
        f_cover = jnp.max(s["recon_fcover"], axis=2)
        healed = jnp.maximum(d_cover, f_cover)

        m, abs_s = range_cover(s["full_bar"], s["commit_bar"], W)
        in_win = s["win_abs"] == abs_s
        slot_full = (in_win & s["win_full"]) | (abs_s < healed[..., None])
        ok = (~m) | slot_full
        fail_at = jnp.where(m & ~ok, abs_s, jnp.iinfo(jnp.int32).max)
        s["full_bar"] = jnp.clip(
            jnp.min(fail_at, axis=2),
            s["full_bar"],
            s["commit_bar"],
        )
        # leaders hold full batches for their own appends [own_from, end)
        s["full_bar"] = jnp.where(
            s["is_leader"] & (s["full_bar"] >= s["own_from"]),
            jnp.maximum(s["full_bar"], s["commit_bar"]),
            s["full_bar"],
        )
        if self.config.exec_follows_commit:
            s["exec_bar"] = jnp.minimum(s["commit_bar"], s["full_bar"])
        else:
            s["exec_bar"] = jnp.maximum(
                s["exec_bar"],
                jnp.minimum(
                    jnp.minimum(s["commit_bar"], s["full_bar"]),
                    c.inputs["exec_floor"].astype(jnp.int32),
                ),
            )

    def _ingest_snapshot(self, s, c):
        super()._ingest_snapshot(s, c)
        s["full_bar"] = jnp.where(
            c.sn_adv, jnp.maximum(s["full_bar"], c.sn_to), s["full_bar"]
        )

    # ------------------------------------------------- reconstruction reads
    def _extra_sends(self, s, c, out, oflags):
        R = self.R
        cfg = self.config
        ns_mask = not_self(self.G, R)
        inbox = c.inbox

        # ingest RECON_REPLY covers (monotone: committed values never change)
        rr_valid = (c.flags & RECON_REPLY) != 0
        s["recon_cover"] = jnp.where(
            rr_valid,
            jnp.maximum(s["recon_cover"], inbox["rr_hi"]),
            s["recon_cover"],
        )
        s["recon_fcover"] = jnp.where(
            rr_valid,
            jnp.maximum(s["recon_fcover"], inbox["rr_fhi"]),
            s["recon_fcover"],
        )

        # send RECON_REQ while starved
        needy = s["full_bar"] < s["commit_bar"]
        s["recon_cnt"] = jnp.where(
            needy, s["recon_cnt"] - 1, cfg.recon_interval
        )
        fire = needy & (s["recon_cnt"] <= 0)
        s["recon_cnt"] = jnp.where(fire, cfg.recon_interval, s["recon_cnt"])
        do_rq = fire[..., None] & ns_mask
        oflags = oflags | jnp.where(do_rq, jnp.uint32(RECON_REQ), 0)
        out["rq_lo"] = jnp.where(do_rq, s["full_bar"][..., None], 0)
        out["rq_hi"] = jnp.where(do_rq, s["commit_bar"][..., None], 0)

        # serve RECON_REQ: below my own commit bar the log is the committed
        # prefix (unique values), so my shards/full-data there are always
        # compatible with any other server's
        rq_valid = (c.flags & RECON_REQ) != 0
        shard_hi = jnp.minimum(
            inbox["rq_hi"],
            jnp.minimum(s["commit_bar"], s["match_bar"])[..., None],
        )
        full_hi = jnp.minimum(inbox["rq_hi"], s["full_bar"][..., None])
        do_rr = (
            rq_valid
            & (
                (shard_hi > inbox["rq_lo"]) | (full_hi > inbox["rq_lo"])
            )
            & ns_mask
        )
        oflags = oflags | jnp.where(do_rr, jnp.uint32(RECON_REPLY), 0)
        out["rr_hi"] = jnp.where(do_rr, shard_hi, 0)
        out["rr_fhi"] = jnp.where(do_rr, full_hi, 0)

        # broadcast mode-bit lane rides with the log content lanes
        out["bw_full"] = s["win_full"]
        return oflags

    def _effects_extra(self, s, c):
        return {"full_bar": s["full_bar"]}
