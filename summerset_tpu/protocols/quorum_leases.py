"""Vectorized QuorumLeases: MultiPaxos + quorum read leases on a
configurable grantee set.

Parity target: reference ``src/protocols/quorum_leases/`` (SURVEY.md §2.5;
the CMU Quorum-Read-Leases design) — clients install a grantee config
through consensus (``quorumconf.rs``, driven by ``ConfChange`` requests);
**every replica is a grantor** of quorum leases to the configured grantees;
a grantee serves reads locally only while it holds leases from a majority
of grantors (``quorumlease.rs:10-17`` ``is_local_reader``:
``lease_cnt() >= quorum_cnt``); and the leader's commit condition requires
Accept acks from every grantee appearing in any acceptor's reported grant
set (``quorumlease.rs:22-42`` ``commit_condition`` over
``accept_grant_sets``; ``AcceptReply`` carries the sender's ``grant_set``,
``messages.rs:367-403``).  Majority intersection is what makes this safe
without epochs: a serving grantee holds leases from a majority of
grantors, every write quorum intersects that majority, and the
intersecting grantor's reported grant set forces the leader to wait for
the grantee's ack.  A second lease plane keeps the leader stable (dual
``LeaseManager``s, lease gids 0/1, ``leaderlease.rs:10-21``).

The reference's guard/promise/revoke clock-free lease machinery
(``src/server/leaseman.rs:122-131``) becomes counter arithmetic in
lockstep ticks:

- a grantor's countdown runs ``lease_margin`` ticks longer than the length
  it granted, so every holder-side expiry strictly precedes its
  grantor-side expiry as long as ``lease_margin > max network delay`` —
  the same role ``T_guard`` plays against unbounded in-flight time;
- revocation is passive (stop refreshing, wait out the countdown), which
  is the reference's expire path; grant sets reported to the leader decay
  on the same clock, so the write barrier never frees before the last
  possibly-live lease.

Kernel semantics on the MultiPaxos lockstep skeleton:

- **Grantee conf changes ride the log**: a conf entry (``win_cfg`` lane,
  value = grantee bitmap) is proposed by the leader from the
  ``conf_target`` host input and applied when executed — the analog of the
  reference's ``ConfChange -> quorumconf`` flow.  Grants are tagged with
  the grantor's applied conf slot; holders count only same-conf leases.
- **All-to-all grants**: every replica refreshes leases to the configured
  grantees it believes alive (GRANT / GRANT_ACK), and beacons its current
  outstanding-grant bitmap to the leader every tick (GSET).  The leader
  caps the commit frontier at the ack frontier of every grantee in any
  live-reported grant set (``_commit_cap``) — the frontier form of
  ``commit_condition``.
- **Local reads**: a majority-leased grantee serves key buckets with no
  pending write in its own un-executed log tail; key buckets are
  ``value_id % num_key_buckets`` (the host hashes real keys to buckets).
- **Leader leases**: followers promise the heartbeat sender vote-refusal
  for ``leader_lease_len`` ticks; the leader counts confirmed promises
  from heartbeat replies (shortened by ``lease_margin``) and may serve
  local reads while a quorum holds — reference ``leaderlease.rs:10-21``.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..utils.bitmap import popcount
from . import register_protocol
from .common import INF as _INF, range_cover
from .multipaxos import MultiPaxosKernel, ReplicaConfigMultiPaxos

GRANT = 1024      # quorum-lease grant/refresh: grantor -> grantee
GRANT_ACK = 2048  # grantee -> grantor (liveness for refresh gating)
GSET = 4096       # per-tick outstanding-grant bitmap beacon (to the leader)


@dataclasses.dataclass
class ReplicaConfigQuorumLeases(ReplicaConfigMultiPaxos):
    """Extends the MultiPaxos knobs (parity: ``ReplicaConfigQuorumLeases``,
    ``quorum_leases/mod.rs``)."""

    lease_len: int = 12          # quorum-lease length granted (ticks)
    alive_timeout: int = 10      # ticks without a reply -> stop refreshing
    leader_lease_len: int = 12   # follower vote-refusal promise (ticks)
    lease_margin: int = 4        # grantor-side slack; must exceed the
                                 # network's max one-way delay in ticks
    grant_interval: int = 4      # lease refresh period (ticks)
    num_key_buckets: int = 8     # key-hash buckets for quiescence checks
    init_responders: int = 0     # initial grantee bitmap (0 = none)
    enable_leader_leases: bool = True


@register_protocol("QuorumLeases")
class QuorumLeasesKernel(MultiPaxosKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_bal", "bw_val", "bw_cfg"})

    # the grantee conf rides the log: its window lane and the applied conf
    # are part of the durable acceptor record (parity: quorumconf.rs conf
    # entries are WAL-logged like any instance)
    DURABLE_SCALARS = MultiPaxosKernel.DURABLE_SCALARS + (
        "conf_cur", "conf_slot",
    )
    DURABLE_WINDOWS = MultiPaxosKernel.DURABLE_WINDOWS + ("win_cfg",)

    # host conf-change plane: the leader's responder-set target
    # (contract metadata, see core/protocol.py)
    EXTRA_INPUTS = MultiPaxosKernel.EXTRA_INPUTS + (("conf_target", "g"),)

    def restore_durable(self, st, g, me, rec, floor):
        super().restore_durable(st, g, me, rec, floor)
        i32 = jnp.int32
        st["conf_cur"] = st["conf_cur"].at[g, me].set(i32(rec["conf_cur"]))
        st["conf_slot"] = st["conf_slot"].at[g, me].set(
            i32(rec["conf_slot"])
        )

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigQuorumLeases | None = None,
    ):
        config = config or ReplicaConfigQuorumLeases()
        if config.leader_leases:
            raise ValueError(
                "QuorumLeases carries its own leader-lease plane; use "
                "enable_leader_leases, not the base MultiPaxos flag"
            )
        super().__init__(num_groups, population, window, config)
        if config.hear_timeout_lo <= config.leader_lease_len:
            raise ValueError(
                "hear_timeout_lo must exceed leader_lease_len (a follower "
                "must outwait its own promise before campaigning)"
            )

    # ------------------------------------------------------------------ state
    def _extra_state(self, st, seed):
        G, R = self.G, self.R
        i32 = jnp.int32
        cfg = self.config
        hold = cfg.lease_len + cfg.lease_margin
        st.update(
            win_cfg=jnp.zeros((G, R, self.W), jnp.bool_),
            conf_cur=jnp.full((G, R), cfg.init_responders, i32),
            conf_slot=jnp.full((G, R), -1, i32),
            conf_prop=jnp.full((G, R), -1, i32),
            # grantor side: per-grantee outstanding countdown
            ql_out=jnp.zeros((G, R, R), i32),
            # holder side: per-grantor countdown + conf slot it bound to
            ql_in=jnp.zeros((G, R, R), i32),
            ql_slot=jnp.full((G, R, R), -1, i32),
            grant_cnt=jnp.zeros((G, R), i32),
            # leader side: peers' reported outstanding-grant bitmaps, with
            # a decay ttl so a silent peer's claim expires on the lease
            # clock; conservative full-grant init (a fresh leader cannot
            # know what anyone granted)
            rep_gset=jnp.full((G, R, R), cfg.init_responders, i32),
            gset_ttl=jnp.full((G, R, R), hold, i32),
            # leader-lease countdowns: holder (follower promise) and the
            # leader's confirmed view per peer.  ll_left starts FULL
            # (same conservative init as gset_ttl above): a restarted
            # replica may have promised vote refusal just before dying,
            # so it waits a full promise window before granting
            # challengers; hear timeouts exceed leader_lease_len, so
            # election liveness is unaffected
            ll_left=jnp.full(
                (G, R),
                cfg.leader_lease_len if cfg.enable_leader_leases else 0,
                i32,
            ),
            ll_in=jnp.zeros((G, R, R), i32),
            # reply-based peer liveness: grants to a dead grantee must stop
            # or the write barrier never frees
            alive_cnt=jnp.full((G, R, R), cfg.alive_timeout, i32),
        )

    def _extra_outbox(self, out):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        out.update(
            gr_len=jnp.zeros((G, R, R), i32),
            gr_slot=jnp.zeros((G, R, R), i32),
            gs_bits=jnp.zeros((G, R, R), i32),
            bw_cfg=jnp.zeros((G, R, W), jnp.bool_),
        )

    # ------------------------------------------------------ lane plumbing
    def _on_accept_write(self, s, c, m_acc, a_src):
        G = self.G
        lane_cfg = c.inbox["bw_cfg"][jnp.arange(G)[:, None], a_src]
        s["win_cfg"] = jnp.where(m_acc, lane_cfg, s["win_cfg"])

    def _on_adopt(self, s, c, adopt, best_src):
        lane_cfg = c.inbox["bw_cfg"][:, None, :, :]  # [G, 1, R_src, W]
        shape = adopt.shape[:2] + (self.R,) + adopt.shape[2:]
        best_cfg = jnp.take_along_axis(
            jnp.broadcast_to(lane_cfg, shape), best_src, axis=2
        )[:, :, 0, :]
        s["win_cfg"] = jnp.where(adopt, best_cfg, s["win_cfg"])

    def _adopt_on_win(self, s, c, win, m_re, abs_re):
        hole = m_re & (s["win_abs"] != abs_re)
        super()._adopt_on_win(s, c, win, m_re, abs_re)
        # no-op filled holes are not conf entries
        s["win_cfg"] = jnp.where(hole, False, s["win_cfg"])

    # ------------------------------------------------------ leader leases
    def _ingest_heartbeat(self, s, c):
        super()._ingest_heartbeat(s, c)
        cfg = self.config
        inbox = c.inbox
        # countdowns tick once per lockstep tick (done here: the first
        # phase to run); holder promises refresh on an accepted heartbeat
        for k in ("ql_out", "ql_in", "grant_cnt", "gset_ttl", "ll_left",
                  "ll_in", "alive_cnt"):
            s[k] = jnp.maximum(s[k] - 1, 0)
        if cfg.enable_leader_leases:
            s["ll_left"] = jnp.where(
                c.hb_ok, cfg.leader_lease_len, s["ll_left"]
            )
        # lease-plane ingest must precede the commit tally in
        # _advance_bars: the write barrier may never lag the ack frontiers
        # it is compared against (reference carries grant_set inside
        # AcceptReply for the same reason, quorum_leases/messages.rs:367)
        g_valid = (c.flags & GRANT) != 0
        s["ql_in"] = jnp.where(g_valid, inbox["gr_len"], s["ql_in"])
        s["ql_slot"] = jnp.where(g_valid, inbox["gr_slot"], s["ql_slot"])
        c.ql_ga = g_valid  # ack back to the grantor in _extra_sends
        ga_valid = (c.flags & GRANT_ACK) != 0
        s["alive_cnt"] = jnp.where(
            ga_valid, cfg.alive_timeout, s["alive_cnt"]
        )
        gs_valid = (c.flags & GSET) != 0
        s["rep_gset"] = jnp.where(gs_valid, inbox["gs_bits"], s["rep_gset"])
        s["gset_ttl"] = jnp.where(
            gs_valid, cfg.lease_len + cfg.lease_margin, s["gset_ttl"]
        )

    def _vote_gate(self, s, c, p_bal, p_src):
        if not self.config.enable_leader_leases:
            return jnp.ones((self.G, self.R), jnp.bool_)
        # refuse challengers while our promise to the current leader runs
        # (no unknown-leader escape: leader == -1 is exactly the
        # post-restart state in which an outstanding promise must be
        # waited out)
        return (s["ll_left"] <= 0) | (p_src == s["leader"])

    def _campaign_gate(self, s, c):
        if not self.config.enable_leader_leases:
            return jnp.ones((self.G, self.R), jnp.bool_)
        return s["ll_left"] <= 0

    def _ingest_hb_reply(self, s, c):
        super()._ingest_hb_reply(s, c)
        if self.config.enable_leader_leases:
            # a heartbeat reply confirms the sender's promise; the leader's
            # belief is shortened by the margin so it expires first
            s["ll_in"] = jnp.where(
                c.hbr_valid,
                self.config.leader_lease_len - self.config.lease_margin,
                s["ll_in"],
            )
        s["alive_cnt"] = jnp.where(
            c.hbr_valid | c.ar_mine,
            self.config.alive_timeout,
            s["alive_cnt"],
        )

    # ------------------------------------------------------- conf changes
    def _leader_propose(self, s, c):
        W = self.W
        i32 = jnp.int32
        i_am_leader = (s["bal_prepared"] == s["bal_max"]) & (
            s["bal_prepared"] > 0
        )
        active_leader = i_am_leader & (s["leader"] == c.rid)
        # a deposed replica forgets its in-flight conf proposal: if the
        # entry was lost to a no-op fill it must be re-proposable later
        s["conf_prop"] = jnp.where(active_leader, s["conf_prop"], -1)
        tgt = c.inputs.get("conf_target")
        if tgt is None:
            tgt = jnp.full((self.G,), -1, i32)
        tgt = jnp.broadcast_to(tgt[:, None].astype(i32), (self.G, self.R))
        space = jnp.maximum(s["exec_bar"] + W - s["next_slot"], 0)
        want = (
            active_leader
            & (tgt >= 0)
            & (tgt != s["conf_cur"])
            & (tgt != s["conf_prop"])
            & (space > 0)
        )
        n_cfg = want.astype(i32)
        m_cfg, abs_cfg = range_cover(s["next_slot"], s["next_slot"] + n_cfg, W)
        s["win_abs"] = jnp.where(m_cfg, abs_cfg, s["win_abs"])
        s["win_bal"] = jnp.where(m_cfg, s["bal_max"][..., None], s["win_bal"])
        s["win_val"] = jnp.where(m_cfg, tgt[..., None], s["win_val"])
        s["win_cfg"] = jnp.where(m_cfg, True, s["win_cfg"])
        s["next_slot"] = s["next_slot"] + n_cfg
        s["conf_prop"] = jnp.where(want, tgt, s["conf_prop"])
        super()._leader_propose(s, c)
        # fresh client proposals are data entries
        s["win_cfg"] = jnp.where(c.m_new, False, s["win_cfg"])

    def _exec_gate(self, s, c):
        super()._exec_gate(s, c)
        # apply the latest executed conf entry (the reference applies conf
        # changes at execution order, quorumconf.rs)
        applied = (
            s["win_cfg"]
            & (s["win_abs"] >= 0)
            & (s["win_abs"] < s["exec_bar"][..., None])
            & (s["win_abs"] > s["conf_slot"][..., None])
        )
        eff = jnp.where(applied, s["win_abs"], -1)
        best = eff.max(axis=2)
        pos = eff.argmax(axis=2)
        newer = best > s["conf_slot"]
        val = jnp.take_along_axis(s["win_val"], pos[..., None], axis=2)[..., 0]
        s["conf_cur"] = jnp.where(newer, val, s["conf_cur"])
        s["conf_slot"] = jnp.where(newer, best, s["conf_slot"])

    # ---------------------------------------------------- takeover safety
    def _try_step_up(self, s, c):
        super()._try_step_up(s, c)
        # a fresh leader cannot know the cluster's outstanding grants: it
        # assumes every peer may be granting to every configured grantee
        # until real GSET beacons replace the claim or the lease clock
        # lapses (reference: revoke-and-wait at step-up, leadership.rs)
        hold = self.config.lease_len + self.config.lease_margin
        s["rep_gset"] = jnp.where(
            c.win[..., None], s["conf_cur"][..., None], s["rep_gset"]
        )
        s["gset_ttl"] = jnp.where(c.win[..., None], hold, s["gset_ttl"])

    def _own_gset(self, s):
        """Bitmap of grantees this replica may still have leases out to;
        both the local barrier and the GSET beacon must use this exact set."""
        R = self.R
        return jnp.sum(
            jnp.where(
                s["ql_out"] > 0,
                jnp.int32(1) << jnp.arange(R, dtype=jnp.int32),
                0,
            ),
            axis=2,
        )

    # ------------------------------------------------------ write barrier
    def _commit_cap(self, s, c, peer_f):
        R = self.R
        # union of live-reported outstanding grant sets (own included)
        own_gset = self._own_gset(s)
        live_rep = jnp.where(s["gset_ttl"] > 0, s["rep_gset"], 0)
        ar = jnp.arange(R, dtype=jnp.int32)
        rep_member = (
            ((live_rep[..., :, None] >> ar[None, None, None, :]) & 1) != 0
        ).any(axis=2)  # [G, R, R_grantee]
        member = rep_member | (((own_gset[..., None] >> ar) & 1) != 0)
        cap = jnp.where(member, peer_f, _INF)
        return jnp.min(cap, axis=2)

    # ------------------------------------------------------ grants + reads
    def _extra_sends(self, s, c, out, oflags):
        R = self.R
        cfg = self.config
        eye = jnp.eye(R, dtype=jnp.bool_)[None]
        ns_mask = ~eye

        # ack received grants back to their grantors (directed: the inbox
        # mask c.ql_ga is [G, self, src], matching the outbox [G, self, dst])
        do_ga = c.ql_ga & ns_mask
        oflags = oflags | jnp.where(do_ga, jnp.uint32(GRANT_ACK), 0)

        # every replica refreshes grants to alive configured grantees
        fire = s["grant_cnt"] <= 0
        s["grant_cnt"] = jnp.where(fire, cfg.grant_interval, s["grant_cnt"])
        grantee = (
            (s["conf_cur"][..., None] >> jnp.arange(R, dtype=jnp.int32)) & 1
        ) != 0  # [G, R, R_grantee]
        do_grant = (
            fire[..., None] & grantee & (s["alive_cnt"] > 0) & ns_mask
        )
        oflags = oflags | jnp.where(do_grant, jnp.uint32(GRANT), 0)
        out["gr_len"] = jnp.where(do_grant, cfg.lease_len, 0)
        out["gr_slot"] = jnp.where(do_grant, s["conf_slot"][..., None], 0)
        s["ql_out"] = jnp.where(
            do_grant, cfg.lease_len + cfg.lease_margin, s["ql_out"]
        )

        # GSET beacon every tick (leaders may change any tick; cheap lane)
        own_gset = self._own_gset(s)
        do_gs = jnp.broadcast_to(ns_mask, (self.G, R, R))
        oflags = oflags | jnp.where(do_gs, jnp.uint32(GSET), 0)
        out["gs_bits"] = jnp.where(do_gs, own_gset[..., None], 0)

        out["bw_cfg"] = s["win_cfg"]
        return oflags

    def _telemetry(self, old, s, c) -> dict:
        """Metric lanes (core/telemetry.py SPI): a grantor-side countdown
        raised above its old value is a lease grant/refresh issued this
        tick."""
        tel = super()._telemetry(old, s, c)
        tel["grants"] = jnp.sum(
            (s["ql_out"] > old["ql_out"]).astype(jnp.int32), axis=2
        )
        return tel

    def _effects_extra(self, s, c):
        cfg = self.config
        R = self.R
        K = cfg.num_key_buckets
        eye = jnp.eye(R, dtype=jnp.bool_)[None]
        # majority-leased check: same-conf leases from a majority of
        # grantors (self counts as one), quorumlease.rs:10-17
        lease_ok = (
            (s["ql_in"] > 0)
            & (s["ql_slot"] == s["conf_slot"][..., None])
            & ~eye
        )
        lease_cnt = jnp.sum(lease_ok.astype(jnp.int32), axis=2) + 1
        self_member = ((s["conf_cur"] >> c.rid) & 1) != 0
        lease_held = self_member & (lease_cnt >= self.quorum)
        # pending-write buckets: un-executed tail of the own voted log
        tail = (
            (s["win_bal"] > 0)
            & (s["win_abs"] >= s["exec_bar"][..., None])
            & (
                s["win_abs"]
                < jnp.maximum(s["vote_bar"], s["next_slot"])[..., None]
            )
        )
        bucket = s["win_val"] % K
        pend = jnp.zeros(tail.shape[:2], jnp.uint32)
        for b in range(K):  # K is small and static; unrolled bucket ORs
            has = jnp.any(tail & (bucket == b), axis=2)
            pend = pend | (has.astype(jnp.uint32) << b)
        n_local = jnp.where(
            lease_held, K - popcount(pend & jnp.uint32((1 << K) - 1)), 0
        )
        # leader local reads under a confirmed quorum of vote promises
        ll_cnt = jnp.sum((s["ll_in"] > 0).astype(jnp.int32), axis=2) + 1
        leader_read_ok = c.active_leader & (
            (ll_cnt >= self.quorum)
            if cfg.enable_leader_leases
            else jnp.zeros_like(c.active_leader)
        )
        return {
            "lease_held": lease_held,
            "lease_cnt": lease_cnt,
            "n_local_buckets": n_local.astype(jnp.int32),
            "leader_read_ok": leader_read_ok,
            "conf_cur": s["conf_cur"],
        }
