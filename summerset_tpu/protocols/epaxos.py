"""Vectorized EPaxos: leaderless consensus over a 2-D instance space.

Parity target: reference ``src/protocols/epaxos/`` (SURVEY.md §2.5) —
Egalitarian Paxos with a 2-D instance space ``SlotIdx(row, col)``
(``epaxos/mod.rs:199``), per-instance sequence numbers and per-row
dependency frontiers (``DepSet``, ``mod.rs:110-124``: "dependencies here
are naturally transitive, we just need to record the highest interfering
column index for each row"), a fast path committing at the optimized
super quorum ``N/2 + ceil((N/2)/2)`` when enough PreAccept replies agree
(``mod.rs:694-697``, ``dependency.rs:180-240``), a slow Accept round at
the simple majority otherwise, per-row ``ExpPrepare`` failover with the
reference's decision ladder (committed > accepting > >= quorum-1
identical non-owner preaccepts > re-propose-with-voted-value > no-op;
``dependency.rs:249-330``), and dependency-graph execution ordering
(``execution.rs:11-87``).

TPU-first redesign (lockstep, struct-of-arrays):

- **Interference tables instead of per-instance reply payloads.**  Every
  replica broadcasts, per tick, its per-bucket interference table
  (``tb_col[k][row]`` = highest same-bucket column bar per row,
  ``tb_seq[k][row]`` = max seq there) plus its contiguous per-row ingest
  frontier ``sb``.  A command leader reconstructs any peer's PreAccept
  merge from that peer's table; the stored merge at ingest and the
  leader-side check use the same deterministic formula (max/union over
  rows other than the instance's own — own-row interference is always in
  the owner's ``deps0``, since the owner knows its own row).  Tables are
  monotone, so a fast-path identity check that passes against a *later*
  table also passed at ingest time: fast commits are sound, and
  interference merely demotes to the slow path — exactly EPaxos's
  behavior.  The "two interfering commits: at least one deps the other"
  invariant holds because a leader counts a peer only once the peer's
  ``sb`` covers the instance, and ``sb`` ships with the same tick's
  table.
- **Cumulative accept frontiers**: acceptors report, per row owner,
  their contiguous accepted frontier over that row (``rp_abar``:
  column-identified, Accepting-or-higher at the row's default ballot);
  slow-path commits tally frontier coverage.  (A per-position bitmask
  was unsound — ring aliasing let an ack for column c' = c (mod W)
  count for c; see the producer note in ``_build_outbox``.)
- **Execution** (device-only mode) is a row-frontier heuristic: per row,
  the first unexecuted instance; a row-level dependency closure (R x R
  boolean squaring) detects cycles, broken by ``(seq, row)`` order — the
  reference's SCC-topo + seq-within-SCC order at row granularity.  Known
  deviation: chains mixing distinct instance-level SCCs inside one
  row-level cycle may execute in seq order rather than topo order; the
  host applier (``exec_floor_rows`` input) is the authoritative path and
  runs exact Tarjan per committed frontier (SURVEY.md §7).
- **Row failover**: the nearest alive ring predecessor-chain successor
  volunteers as recoverer for a dead row, campaigns with per-row ERP
  ballots, gathers survivors' stored copies through response lanes, walks
  the reference's decision ladder, and drives outcomes through recovery
  lanes at the ERP ballot.  A replica whose own row wedges (e.g. revived
  after its row was partially recovered at a higher ballot) heals by
  running the same machinery on its own row.

Caveat (mirrored from the reference): the decision ladder implements the
original EPaxos paper's recovery, whose optimized-quorum corner (a fast
commit whose surviving identical preaccepts fall below ``quorum - 1`` in
the recovery quorum) is known to be unsound in theory ("EPaxos
Revisited", NSDI'21).  The reference carries the same semantics
(``dependency.rs:288-307``); we match it rather than silently diverge.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax.numpy as jnp

from ..core.protocol import ProtocolKernel, StepEffects
from . import register_protocol
from .common import INF as _INF, make_greater_ballot, range_cover

# flag bits
BEACON = 1    # ow/tb/sb/rp_abar lanes valid (sent every tick)
ERP = 2       # explicit-prepare campaign for erp_row at erp_bal
RV = 4        # rv_* lanes carry my stored copy of rv_row (ERP response)
RO = 8        # ro_* lanes drive a recovered row at ro_bal

# status codes (parity: Status enum, epaxos/mod.rs:137-146)
NULL, PREACC, ACCEPTING, COMMITTED = 0, 1, 2, 3


@dataclasses.dataclass
class ReplicaConfigEPaxos:
    """Static knobs (parity: ``ReplicaConfigEPaxos``, ``epaxos/mod.rs``)."""

    max_proposals_per_tick: int = 8   # per group, split across replicas
    num_key_buckets: int = 8          # conflict-detection key buckets
    optimized_quorum: bool = True     # super = N/2 + ceil((N/2)/2)
    alive_timeout: int = 30           # ticks silent -> peer considered dead
    stall_timeout: int = 40           # own-row wedge -> self-ERP heal
    exec_follows_commit: bool = True  # device-only exec heuristic on


@register_protocol("EPaxos")
class EPaxosKernel(ProtocolKernel):
    broadcast_lanes = frozenset({
        "ow_abs", "ow_phase", "ow_bal", "ow_seq", "ow_val", "ow_noop",
        "ow_deps", "tb_col", "tb_seq", "sb",
        "ro_row", "ro_bal", "ro_abs", "ro_phase", "ro_seq", "ro_val",
        "ro_noop", "ro_deps",
        "rv_row", "rv_bal", "rv_abs", "rv_st", "rv_vbal", "rv_seq",
        "rv_val", "rv_noop", "rv_deps", "rv_bump", "rv_cmt",
    })

    # durable acceptor record: the whole 2-D stored-copy space plus the
    # interference tables and own-row cursor (parity: the reference WAL-
    # logs every instance status transition, epaxos/durability.rs; the
    # tables must survive restart or new proposals could under-detect
    # interference and break execution order)
    DURABLE_SCALARS = ("own_next",)
    DURABLE_WINDOWS = (
        "abs2", "st2", "bal2", "seq2", "val2", "noop2", "deps2", "pbump2",
        "it_col", "it_seq",
    )
    VALUE_WINDOW = "val2"

    # host-serving inputs (contract metadata; see core/protocol.py):
    # the proposing replica id, its minted vid list, and the per-row
    # exec floors from the host Tarjan executor (host/epaxos_exec.py)
    EXTRA_INPUTS = (
        ("prop_replica", "g"),
        ("prop_vids", "gp"),
        ("exec_floor_rows", "grr"),
    )

    def restore_durable(self, st, g, me, rec, floor):
        i32 = jnp.int32
        st["own_next"] = st["own_next"].at[g, me].set(
            i32(rec["own_next"])
        )
        for k in self.DURABLE_WINDOWS:
            st[k] = st[k].at[g, me].set(jnp.asarray(rec[k], st[k].dtype))
        # own seen frontier covers the restored own row; cmt_row/exec_row
        # re-derive from the window content and the host exec floors
        st["seen_bar"] = st["seen_bar"].at[g, me, me].set(
            i32(rec["own_next"])
        )

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 32,
        config: ReplicaConfigEPaxos | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigEPaxos()
        half = population // 2
        self.simple_q = half + 1
        self.super_q = (
            half + -(-half // 2) if self.config.optimized_quorum else 2 * half
        )
        self.super_q = max(self.simple_q, min(self.super_q, population))

    # ------------------------------------------------------------------ init
    def init_state(self, seed: int = 0):
        G, R, W, K = self.G, self.R, self.W, self.config.num_key_buckets
        i32 = jnp.int32
        z = lambda *s: jnp.zeros(s, i32)  # noqa: E731
        return {
            "own_next": z(G, R),
            # 2-D instance space [G, R, row, W] (+ deps [..., R]); window
            # position p of a row holds the column c == p (mod W), with
            # abs2 recording which c (-1 = empty)
            "abs2": jnp.full((G, R, R, W), -1, i32),
            "st2": z(G, R, R, W),
            "bal2": z(G, R, R, W),
            "seq2": z(G, R, R, W),
            "val2": z(G, R, R, W),
            "noop2": jnp.zeros((G, R, R, W), jnp.bool_),
            "deps2": z(G, R, R, W, R),
            # preaccept-merge marker: True iff my stored PREACC copy's
            # (seq, deps) were bumped past the owner's lane by my tables
            # at ingest — an UNBUMPED copy equals the owner's original
            # attrs and is the only valid fast-commit witness in recovery
            "pbump2": jnp.zeros((G, R, R, W), jnp.bool_),
            # per-row frontiers
            "seen_bar": z(G, R, R),
            "cmt_row": z(G, R, R),
            "exec_row": z(G, R, R),
            "ext_row": z(G, R, R),
            # per-bucket interference tables
            "it_col": z(G, R, K, R),
            "it_seq": z(G, R, K, R),
            # per-row ballot ceiling + the column extent it protects
            "rbm": z(G, R, R),
            "rbm_ext": z(G, R, R),
            # liveness + recovery driver + own-row wedge detector
            "alive_cnt": jnp.full((G, R, R), self.config.alive_timeout, i32),
            "rec_row": jnp.full((G, R), -1, i32),
            "rec_bal": z(G, R),
            "stall_cnt": jnp.full((G, R), self.config.stall_timeout, i32),
            "last_cmt": z(G, R),
            # engine-required aggregate bars
            "commit_bar": z(G, R),
            "exec_bar": z(G, R),
        }

    def zero_outbox(self):
        G, R, W, K = self.G, self.R, self.W, self.config.num_key_buckets
        i32 = jnp.int32
        wl = lambda: jnp.zeros((G, R, W), i32)  # noqa: E731
        wb = lambda: jnp.zeros((G, R, W), jnp.bool_)  # noqa: E731
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        return {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "rp_abar": jnp.zeros((G, R, R), i32),
            "rp_abase": jnp.zeros((G, R, R), i32),
            "rp_pbar": jnp.zeros((G, R, R), i32),
            "erp_row": pair(), "erp_bal": pair(), "erp_ext": pair(),
            "ow_abs": jnp.full((G, R, W), -1, i32),
            "ow_phase": wl(), "ow_bal": wl(), "ow_seq": wl(), "ow_val": wl(),
            "ow_noop": wb(), "ow_deps": jnp.zeros((G, R, W, R), i32),
            "tb_col": jnp.zeros((G, R, K, R), i32),
            "tb_seq": jnp.zeros((G, R, K, R), i32),
            "sb": jnp.zeros((G, R, R), i32),
            "ro_row": jnp.full((G, R), -1, i32), "ro_bal": jnp.zeros((G, R), i32),
            "ro_abs": jnp.full((G, R, W), -1, i32),
            "ro_phase": wl(), "ro_seq": wl(), "ro_val": wl(),
            "ro_noop": wb(), "ro_deps": jnp.zeros((G, R, W, R), i32),
            "rv_row": jnp.full((G, R), -1, i32), "rv_bal": jnp.zeros((G, R), i32),
            "rv_abs": jnp.full((G, R, W), -1, i32),
            "rv_st": wl(), "rv_vbal": wl(), "rv_seq": wl(), "rv_val": wl(),
            "rv_noop": wb(), "rv_deps": jnp.zeros((G, R, W, R), i32),
            "rv_bump": wb(), "rv_cmt": jnp.zeros((G, R), i32),
        }

    # ------------------------------------------------------------- helpers
    def _default_bal(self, row):
        """Default (pre-failover) ballot of a row."""
        return (jnp.int32(1) << 8) | row

    def _row_slice(self, s, key, row):
        """Gather s[key][g, r, row[g, r]] -> [G, R, W(, R)]."""
        G = self.G
        gar = jnp.arange(G)[:, None]
        rar = jnp.arange(self.R)[None, :]
        return s[key][gar, rar, row]

    def _own_scatter(self, s, c, key, lane):
        """Scatter a [G, R, W(, R)] lane into s[key] at row = own rid."""
        R = self.R
        if s[key].ndim == 5:
            sel = (
                jnp.arange(R)[None, None, :, None, None]
                == c.rid[:, :, None, None, None]
            )
            s[key] = jnp.where(sel, lane[:, :, None], s[key])
        else:
            sel = (
                jnp.arange(R)[None, None, :, None]
                == c.rid[:, :, None, None]
            )
            s[key] = jnp.where(sel, lane[:, :, None], s[key])

    def _bucket_gather(self, table, bucket):
        """table [G, A, K, R], bucket [G, A, ...] -> [G, A, ..., R]."""
        K = self.config.num_key_buckets
        G, A = table.shape[0], table.shape[1]
        gar = jnp.arange(G).reshape((G,) + (1,) * (bucket.ndim - 1))
        aar = jnp.arange(A).reshape((1, A) + (1,) * (bucket.ndim - 2))
        return table[gar, aar, bucket.clip(0, K - 1)]

    def _bump_tables(self, s, m, abs_col, bucket, seq):
        """Fold applied instances (masked [G, R, row, W]) into tables."""
        K = self.config.num_key_buckets
        kar = jnp.arange(K)[None, None, :, None, None]
        mb = m[:, :, None] & (bucket[:, :, None] == kar)  # [G,R,K,row,W]
        col_c = jnp.max(jnp.where(mb, abs_col[:, :, None] + 1, 0), axis=4)
        seq_c = jnp.max(jnp.where(mb, seq[:, :, None], 0), axis=4)
        s["it_col"] = jnp.maximum(s["it_col"], col_c)
        s["it_seq"] = jnp.maximum(s["it_seq"], seq_c)

    # ------------------------------------------------------------------ step
    # graftprof phase registry (core/protocol.py): tuple order is
    # execution order.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("liveness", "_liveness"),
        ("ingest_erp", "_ingest_erp"),
        ("ingest_recovery_drive", "_ingest_recovery_drive"),
        ("ingest_own_streams", "_ingest_own_streams"),
        ("leader_decide", "_leader_decide"),
        ("recovery_control", "_recovery_control"),
        ("propose", "_propose"),
        ("advance_commit_rows", "_advance_commit_rows"),
        ("execute", "_execute"),
        ("telemetry", "_phase_telemetry"),
        ("build_outbox", "_phase_build_outbox"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        G, R = self.G, self.R
        c.rid = jnp.broadcast_to(
            jnp.arange(R, dtype=jnp.int32)[None, :], (G, R)
        )
        c.eye = jnp.eye(R, dtype=jnp.bool_)[None]
        c.heard = c.flags != 0

        self._run_phases(s, c)
        fx = self._effects(s, c)
        return s, c.out, fx

    # ========== liveness
    def _liveness(self, s, c):
        s["alive_cnt"] = jnp.where(
            c.heard | c.eye,
            self.config.alive_timeout,
            jnp.maximum(s["alive_cnt"] - 1, 0),
        )

    # ========== ERP campaigns (acceptor side): raise per-row ballot ceiling
    def _ingest_erp(self, s, c):
        inbox = c.inbox
        erp_valid = (c.flags & ERP) != 0           # [G, R, src]
        rows = jnp.arange(self.R)[None, None, :, None]
        m = erp_valid[:, :, None, :] & (
            inbox["erp_row"][:, :, None, :] == rows
        )
        best = jnp.max(jnp.where(m, inbox["erp_bal"][:, :, None, :], 0),
                       axis=3)                     # [G, R, row]
        ext = jnp.max(jnp.where(m, inbox["erp_ext"][:, :, None, :], 0),
                      axis=3)
        newer = best > s["rbm"]
        s["rbm"] = jnp.where(newer, best, s["rbm"])
        s["rbm_ext"] = jnp.where(
            newer, jnp.maximum(s["rbm_ext"], ext), s["rbm_ext"]
        )

    # ========== shared ingestion core
    def _apply_lanes(self, s, c, lanes, bal_lane, row_mask):
        """Apply per-(row, window) lanes (abs/phase/seq/val/noop/deps) onto
        the 2-D log.  ``row_of``: [G, R, row] bool — which (replica, row)
        pairs these lanes target; lanes are [G, R, row, W(, R)].  Phase 1
        entries merge against my tables; phases 2/3 adopt verbatim.
        Committed entries never regress.  Returns the applied mask."""
        K = self.config.num_key_buckets
        W, R = self.W, self.R
        l_abs, l_ph, l_seq, l_val, l_noop, l_deps = lanes
        pos_ok = (
            row_mask
            & (l_abs >= 0)
            & (l_abs % W == jnp.arange(W)[None, None, None, :])
            & (l_ph >= PREACC)
            & (bal_lane > 0)
            & (l_abs >= s["exec_row"][..., None])
            & (l_abs < s["exec_row"][..., None] + W)
        )
        # ballot gates: entries under a row's ERP-protected extent need the
        # ceiling ballot; above it the default ballot is fine
        bal_ok = (bal_lane >= s["rbm"][..., None]) | (
            l_abs >= s["rbm_ext"][..., None]
        )
        pos_ok = pos_ok & bal_ok

        cur = s["abs2"] == l_abs
        fresh = pos_ok & ~cur & (
            (s["abs2"] < l_abs) | (s["st2"] == NULL)
        )
        upgrade = pos_ok & cur & (
            (l_ph > s["st2"]) | ((l_ph == s["st2"]) & (bal_lane > s["bal2"]))
        ) & ~((s["st2"] == COMMITTED) & (l_ph < COMMITTED))
        apply_m = fresh | upgrade

        # phase-1 merge against my (pre-application) tables
        bucket = l_val % K
        itc = self._bucket_gather(s["it_col"], bucket)  # [G,R,row,W,R]
        its = self._bucket_gather(s["it_seq"], bucket)
        not_own = (
            jnp.arange(R)[None, None, None, None, :]
            != jnp.arange(R)[None, None, :, None, None]
        )
        merge_seq = jnp.maximum(
            l_seq, 1 + jnp.max(jnp.where(not_own, its, 0), axis=4)
        )
        merge_deps = jnp.where(not_own, jnp.maximum(l_deps, itc), l_deps)
        is_pre = l_ph == PREACC
        take_seq = jnp.where(is_pre & fresh, merge_seq, l_seq)
        take_deps = jnp.where(
            (is_pre & fresh)[..., None], merge_deps, l_deps
        )

        # did my phase-1 merge change the owner's attrs?  Tracked so
        # recovery can tell "copy == owner's original" (fast-commit
        # witness) from "copy already reflects MY interference view"
        bumped = (is_pre & fresh) & (
            (merge_seq > l_seq)
            | jnp.any(merge_deps != l_deps, axis=4)
        )

        s["abs2"] = jnp.where(apply_m, l_abs, s["abs2"])
        s["st2"] = jnp.where(apply_m, l_ph, s["st2"])
        s["bal2"] = jnp.where(apply_m, bal_lane, s["bal2"])
        s["seq2"] = jnp.where(apply_m, take_seq, s["seq2"])
        s["val2"] = jnp.where(apply_m, l_val, s["val2"])
        s["noop2"] = jnp.where(apply_m, l_noop, s["noop2"])
        s["deps2"] = jnp.where(apply_m[..., None], take_deps, s["deps2"])
        s["pbump2"] = jnp.where(apply_m, bumped, s["pbump2"])
        self._bump_tables(
            s, apply_m & ~l_noop, l_abs, bucket, take_seq
        )
        return apply_m

    # ========== recovery drive lanes (acceptor side)
    def _ingest_recovery_drive(self, s, c):
        G, R, W = self.G, self.R, self.W
        inbox = c.inbox
        has = (c.flags & RO) != 0                          # [G, R, src]
        rows = jnp.arange(R)[None, None, :, None]
        m = has[:, :, None, :] & (
            inbox["ro_row"][:, None, None, :] == rows
        )
        eff = jnp.where(m, inbox["ro_bal"][:, None, None, :], 0)
        best_bal = eff.max(axis=3)                         # [G, R, row]
        best_src = eff.argmax(axis=3).astype(jnp.int32)
        ok_row = (best_bal > 0) & (best_bal >= s["rbm"])
        s["rbm"] = jnp.where(ok_row, best_bal, s["rbm"])

        gar = jnp.arange(G)[:, None, None]

        def lane(name):
            return inbox[name][gar, best_src]  # [G, R, row, W(, R)]

        lanes = (lane("ro_abs"), lane("ro_phase"), lane("ro_seq"),
                 lane("ro_val"), lane("ro_noop"), lane("ro_deps"))
        bal_lane = jnp.where(
            lanes[1] > 0, best_bal[..., None], 0
        )
        self._apply_lanes(s, c, lanes, bal_lane, ok_row[..., None])

    # ========== own-row preaccept/accept/commit stream ingestion
    def _ingest_own_streams(self, s, c):
        G, R, W = self.G, self.R, self.W
        inbox = c.inbox
        beacon = ((c.flags & BEACON) != 0) & ~c.eye  # [G, R, src(=row)]

        bshape = (G, R, R, W)

        def lane(name, extra=()):
            return jnp.broadcast_to(
                inbox[name][:, None], bshape + extra
            )

        lanes = (
            lane("ow_abs"), lane("ow_phase"), lane("ow_seq"),
            lane("ow_val"), lane("ow_noop"), lane("ow_deps", (R,)),
        )
        bal_lane = lane("ow_bal")
        self._apply_lanes(s, c, lanes, bal_lane, beacon[..., None])

        # advance contiguous ingest frontiers: seen_bar per row walks over
        # stored entries (abs2 alignment), independent of this tick's lanes
        _, abs_w = range_cover(s["seen_bar"], s["seen_bar"] + W, W)
        present = (s["abs2"] == abs_w) & (s["st2"] >= PREACC)
        gap = (abs_w >= s["seen_bar"][..., None]) & ~present
        first_gap = jnp.min(jnp.where(gap, abs_w, _INF), axis=3)
        s["seen_bar"] = jnp.clip(
            first_gap, s["seen_bar"], s["seen_bar"] + W
        )
        # extent: max of my frontier and peers' reported frontiers
        sb_peers = jnp.broadcast_to(inbox["sb"][:, None], (G, R, R, R))
        sb_max = jnp.max(
            jnp.where(beacon[..., None], sb_peers, 0), axis=2
        )
        s["ext_row"] = jnp.maximum(
            jnp.maximum(s["ext_row"], s["seen_bar"]), sb_max
        )

    # ========== command-leader decisions on own row (fast/slow/commit)
    def _leader_decide(self, s, c):
        G, R, W, K = self.G, self.R, self.W, self.config.num_key_buckets
        inbox = c.inbox
        rid = c.rid
        beacon = ((c.flags & BEACON) != 0) & ~c.eye

        st_o = self._row_slice(s, "st2", rid)
        abs_o = self._row_slice(s, "abs2", rid)
        bal_o = self._row_slice(s, "bal2", rid)
        seq_o = self._row_slice(s, "seq2", rid)
        val_o = self._row_slice(s, "val2", rid)
        deps_o = self._row_slice(s, "deps2", rid)  # [G, R, W, R]
        dbal = self._default_bal(rid)[..., None]
        live = (abs_o >= 0) & (abs_o < s["own_next"][..., None]) & (
            bal_o == dbal
        )

        # peers' ingest coverage of my row: the attested default-ballot
        # run [rp_abase, rp_pbar) (pair lanes; see the producer note —
        # raw seen_bar counted recovery outcomes stored at other ballots
        # as ingests of MY entries)
        gar = jnp.arange(G)[:, None, None]
        pbar_mine = jnp.where(beacon, inbox["rp_pbar"], 0)
        pbase_mine = inbox["rp_abase"]
        ing = (
            (pbase_mine[:, :, :, None] <= abs_o[:, :, None, :])
            & (pbar_mine[:, :, :, None] > abs_o[:, :, None, :])
        )  # [G,me,src,W]

        # fast-path identity reconstruction from peers' tables
        bucket = val_o % K
        gar4 = jnp.arange(G)[:, None, None, None]
        par = jnp.arange(R)[None, None, :, None]
        bidx = bucket[:, :, None, :]
        tbc = inbox["tb_col"][gar4, par, bidx.clip(0, K - 1)]
        tbs = inbox["tb_seq"][gar4, par, bidx.clip(0, K - 1)]
        # tbc/tbs: [G, me, p, W, row]
        not_own = (
            jnp.arange(R)[None, None, None, None, :]
            != rid[:, :, None, None, None]
        )
        extra_seq = 1 + jnp.max(jnp.where(not_own, tbs, 0), axis=4)
        seq_same = extra_seq <= seq_o[:, :, None, :]
        deps_same = ~jnp.any(
            not_own & (tbc > deps_o[:, :, None, :, :]), axis=4
        )
        identical = seq_same & deps_same              # [G, me, p, W]

        fast_votes = 1 + jnp.sum(
            (ing & identical).astype(jnp.int32), axis=2
        )
        ing_cnt = 1 + jnp.sum(ing.astype(jnp.int32), axis=2)
        alive_total = jnp.sum(
            (s["alive_cnt"] > 0).astype(jnp.int32), axis=2
        )  # includes self

        pending = live & (st_o == PREACC)
        decide = pending & (
            (ing_cnt >= self.super_q)
            | ((ing_cnt >= self.simple_q)
               & (ing_cnt >= alive_total[..., None]))
        )
        fast = decide & (fast_votes >= self.super_q)
        slow = decide & ~fast

        # slow-path union attrs from ingested peers' tables + my own
        u_seq = jnp.maximum(
            seq_o, jnp.max(jnp.where(ing, extra_seq, 0), axis=2)
        )
        u_deps = jnp.maximum(
            deps_o, jnp.max(jnp.where(ing[..., None], tbc, 0), axis=2)
        )
        own_r = jnp.arange(R)[None, None, None, :] == rid[..., None, None]
        u_deps = jnp.where(own_r, deps_o, u_deps)

        # accept tally via the peers' contiguous accepted frontiers over
        # my row (column-identified; see the rp_abar producer note on why
        # a per-position bitmask was unsound)
        accing = live & (st_o == ACCEPTING)
        # rp_abar/rp_abase are PAIR lanes addressed to the row owner:
        # inbox is already [G, me, src] = src's accepted run over MY row.
        # An ack for column c needs c INSIDE the attested half-open run —
        # c below the base means the peer executed past it (possibly a
        # recovery outcome), which attests nothing about MY attrs.
        abar_mine = jnp.where(beacon, inbox["rp_abar"], 0)
        abase_mine = inbox["rp_abase"]
        acc_cnt = 1 + jnp.sum(
            ((abase_mine[:, :, :, None] <= abs_o[:, :, None, :])
             & (abar_mine[:, :, :, None] > abs_o[:, :, None, :])).astype(
                jnp.int32
            ),
            axis=2,
        )
        acc_done = accing & (acc_cnt >= self.simple_q)

        new_st = jnp.where(
            fast | acc_done, COMMITTED, jnp.where(slow, ACCEPTING, st_o)
        )
        new_seq = jnp.where(slow, u_seq, seq_o)
        new_deps = jnp.where(slow[..., None], u_deps, deps_o)
        self._own_scatter(s, c, "st2", new_st)
        self._own_scatter(s, c, "seq2", new_seq)
        self._own_scatter(s, c, "deps2", new_deps)
        # slow-path seq bumps also feed the tables
        own_sel = jnp.arange(R)[None, None, :, None] == rid[:, :, None, None]
        self._bump_tables(
            s,
            (slow & ~self._row_slice(s, "noop2", rid))[:, :, None, :]
            & own_sel,
            jnp.broadcast_to(abs_o[:, :, None, :], (G, R, R, W)),
            jnp.broadcast_to(bucket[:, :, None, :], (G, R, R, W)),
            jnp.broadcast_to(new_seq[:, :, None, :], (G, R, R, W)),
        )

    # ========== recovery control: volunteer, campaign, decide, drive
    def _recovery_control(self, s, c):
        G, R = self.G, self.R
        inbox = c.inbox
        rid = c.rid
        dead = (s["alive_cnt"] <= 0) & ~c.eye[0][None]  # [G, R, peer]

        # volunteer for the nearest dead ring-predecessor whose in-between
        # predecessors are all dead too (deterministic, collision-free
        # among live replicas)
        vol_tgt = jnp.full((G, R), -1, jnp.int32)
        taken = jnp.zeros((G, R), jnp.bool_)
        chain = jnp.ones((G, R), jnp.bool_)
        for k in range(1, R):
            cand = (rid - k) % R
            cand_dead = jnp.take_along_axis(
                dead, cand[..., None], axis=2
            )[..., 0]
            # skip rows already fully recovered so a chain of adjacent
            # dead replicas gets each of its rows driven in turn
            cand_done = (
                jnp.take_along_axis(s["cmt_row"], cand[..., None], axis=2)
                >= jnp.take_along_axis(s["ext_row"], cand[..., None], axis=2)
            )[..., 0]
            pick = cand_dead & ~cand_done & chain & ~taken
            vol_tgt = jnp.where(pick, cand, vol_tgt)
            taken = taken | pick
            chain = chain & cand_dead

        # own-row wedge detector -> self-ERP heal
        own_cmt = jnp.take_along_axis(s["cmt_row"], rid[..., None], axis=2)[
            ..., 0
        ]
        wedged = own_cmt < s["own_next"]
        prog = own_cmt > s["last_cmt"]
        s["last_cmt"] = own_cmt
        s["stall_cnt"] = jnp.where(
            prog | ~wedged,
            self.config.stall_timeout,
            jnp.maximum(s["stall_cnt"] - 1, 0),
        )
        self_heal = wedged & (s["stall_cnt"] <= 0)
        vol_tgt = jnp.where(
            (vol_tgt < 0) & self_heal, rid, vol_tgt
        )

        # start / continue / finish / abort
        cur = s["rec_row"]
        cur_c = jnp.maximum(cur, 0)
        cur_dead = jnp.take_along_axis(dead, cur_c[..., None], axis=2)[
            ..., 0
        ]
        cur_done = (
            jnp.take_along_axis(s["cmt_row"], cur_c[..., None], axis=2)[
                ..., 0
            ]
            >= jnp.take_along_axis(s["ext_row"], cur_c[..., None], axis=2)[
                ..., 0
            ]
        )
        keep = (cur >= 0) & ~cur_done & (cur_dead | (cur == rid))
        start = (cur < 0) & (vol_tgt >= 0) | ((cur >= 0) & ~keep
                                              & (vol_tgt >= 0))
        tgt = jnp.where(keep, cur, jnp.where(start, vol_tgt, -1))
        s["rec_row"] = tgt
        tgt_c = jnp.maximum(tgt, 0)
        tgt_rbm = jnp.take_along_axis(s["rbm"], tgt_c[..., None], axis=2)[
            ..., 0
        ]
        # bid once per campaign; re-bid only when a strictly higher foreign
        # ballot appears (the local rbm claim below equals rec_bal, so a
        # non-strict check would re-bid every tick and outrun the
        # one-delay echo in responders' RV replies)
        need_bid = (tgt >= 0) & (start | (s["rec_bal"] < tgt_rbm))
        s["rec_bal"] = jnp.where(
            need_bid,
            make_greater_ballot(tgt_rbm, rid),
            jnp.where(tgt >= 0, s["rec_bal"], 0),
        )
        # claim the ballot ceiling locally
        tgt_ext = jnp.take_along_axis(s["ext_row"], tgt_c[..., None],
                                      axis=2)[..., 0]
        claim = (
            jnp.arange(R)[None, None, :] == tgt[..., None]
        ) & (tgt >= 0)[..., None]
        s["rbm"] = jnp.where(
            claim, jnp.maximum(s["rbm"], s["rec_bal"][..., None]), s["rbm"]
        )
        s["rbm_ext"] = jnp.where(
            claim, jnp.maximum(s["rbm_ext"], tgt_ext[..., None]),
            s["rbm_ext"],
        )

        # tally this tick's RV responses to my campaign
        rv_on = (c.flags & RV) != 0
        rv_row_in = jnp.broadcast_to(inbox["rv_row"][:, None], (G, R, R))
        rv_bal_in = jnp.broadcast_to(inbox["rv_bal"][:, None], (G, R, R))
        rv_mine = (
            rv_on
            & (rv_row_in == tgt[..., None])
            & (rv_bal_in == s["rec_bal"][..., None])
            & (tgt >= 0)[..., None]
        )
        c.rec_tgt = tgt
        c.rv_mine = rv_mine
        c.rec_have_q = 1 + jnp.sum(
            rv_mine.astype(jnp.int32), axis=2
        ) >= self.simple_q

    # ========== proposals (every replica is a command leader)
    def _propose(self, s, c):
        G, R, W, K = self.G, self.R, self.W, self.config.num_key_buckets
        i32 = jnp.int32
        rid = c.rid
        n_prop = jnp.broadcast_to(
            c.inputs["n_proposals"][:, None].astype(i32), (G, R)
        )
        # host-serving mode: ``prop_replica`` [G] names the ONE replica
        # proposing this tick (its host owns the payload vids), and value
        # ids are used verbatim; without it (device bench mode) the count
        # splits across all command leaders with rid-interleaved ids
        pr = c.inputs.get("prop_replica")
        if pr is None:
            pr2 = jnp.full((G, R), -1, i32)
        else:
            pr2 = jnp.broadcast_to(pr[:, None].astype(i32), (G, R))
        host_mode = pr2 >= 0
        share = jnp.where(
            host_mode,
            jnp.where(rid == pr2, n_prop, 0),
            n_prop // R + (rid < (n_prop % R)).astype(i32),
        )
        own_exec = jnp.take_along_axis(
            s["exec_row"], rid[..., None], axis=2
        )[..., 0]
        space = jnp.maximum(own_exec + W - s["own_next"], 0)
        n_new = jnp.minimum(share, space)
        pv0 = c.inputs.get("prop_vids")
        if pv0 is not None:
            # never propose past the vid list's width: an out-of-range
            # gather would silently duplicate the last vid across
            # distinct instances (payload exchange is first-writer-wins,
            # so the duplicate would commit the wrong batch)
            n_new = jnp.minimum(n_new, pv0.shape[1])
        vbase = jnp.broadcast_to(
            c.inputs["value_base"][:, None].astype(i32), (G, R)
        )
        m_new, abs_new = range_cover(s["own_next"], s["own_next"] + n_new, W)
        off = abs_new - s["own_next"][..., None]
        # distinct value ids across replicas: interleave by rid.  In host
        # mode an explicit per-tick vid LIST may be supplied
        # (``prop_vids`` [G, max_props], entries beyond n_proposals
        # ignored): the host mints vids in per-bucket residue classes, so
        # one tick can propose SEVERAL key buckets at once — consecutive
        # vbase+off ints could not express that (reference behavior:
        # EPaxos commits interfering and non-interfering commands
        # concurrently, dependency.rs:180-240)
        pv = pv0
        if pv is not None:
            pmax = pv.shape[1]
            pvb = jnp.broadcast_to(
                pv[:, None, :].astype(i32), (G, R, pmax)
            )
            host_vals = jnp.take_along_axis(
                pvb, jnp.clip(off, 0, pmax - 1), axis=2
            )
        else:
            host_vals = vbase[..., None] + off
        new_vals = jnp.where(
            host_mode[..., None],
            host_vals,
            vbase[..., None] * R + rid[..., None] + off * R,
        )
        bucket = new_vals % K

        # seq0/deps0 from my tables
        itc = self._bucket_gather(s["it_col"], bucket)  # [G,R,W,row]
        its = self._bucket_gather(s["it_seq"], bucket)
        seq0 = 1 + jnp.max(its, axis=3)
        deps0 = itc
        # intra-batch same-bucket chaining: rank among same-bucket batch
        # positions bumps seq; dep on the immediately preceding one
        for kb in range(K):
            mk = m_new & (bucket == kb)
            before = (
                mk[..., None, :] & mk[..., :, None]
                & (off[..., None, :] < off[..., :, None])
            )  # [G,R,W(i),W(j<i)]
            rank = jnp.sum(before.astype(i32), axis=3)
            seq0 = jnp.where(mk, seq0 + rank, seq0)
            prev_bar = jnp.max(
                jnp.where(before, abs_new[..., None, :] + 1, 0), axis=3
            )
            own_sel = (
                jnp.arange(R)[None, None, None, :] == rid[..., None, None]
            )
            deps0 = jnp.where(
                own_sel & mk[..., None],
                jnp.maximum(deps0, prev_bar[..., None]),
                deps0,
            )

        dlane = jnp.broadcast_to(
            self._default_bal(rid)[..., None], (G, R, W)
        )
        for key, lane in (
            ("abs2", jnp.where(m_new, abs_new, self._row_slice(s, "abs2", rid))),
            ("st2", jnp.where(m_new, PREACC, self._row_slice(s, "st2", rid))),
            ("bal2", jnp.where(m_new, dlane, self._row_slice(s, "bal2", rid))),
            ("seq2", jnp.where(m_new, seq0, self._row_slice(s, "seq2", rid))),
            ("val2", jnp.where(m_new, new_vals,
                               self._row_slice(s, "val2", rid))),
            ("noop2", jnp.where(m_new, False,
                                self._row_slice(s, "noop2", rid))),
        ):
            self._own_scatter(s, c, key, lane)
        deps_lane = jnp.where(
            m_new[..., None], deps0, self._row_slice(s, "deps2", rid)
        )
        self._own_scatter(s, c, "deps2", deps_lane)
        s["own_next"] = s["own_next"] + n_new
        own_sel3 = jnp.arange(R)[None, None, :] == rid[..., None]
        s["seen_bar"] = jnp.where(
            own_sel3, s["own_next"][..., None], s["seen_bar"]
        )
        s["ext_row"] = jnp.maximum(s["ext_row"], s["seen_bar"])
        self._bump_tables(
            s,
            m_new[:, :, None, :] & own_sel3[..., None],
            jnp.broadcast_to(abs_new[:, :, None, :], (G, R, R, W)),
            jnp.broadcast_to(bucket[:, :, None, :], (G, R, R, W)),
            jnp.broadcast_to(seq0[:, :, None, :], (G, R, R, W)),
        )
        c.n_new = n_new

    # ========== per-row contiguous commit frontier
    def _advance_commit_rows(self, s, c):
        W = self.W
        _, abs_w = range_cover(s["cmt_row"], s["cmt_row"] + W, W)
        ok = (s["abs2"] == abs_w) & (s["st2"] == COMMITTED)
        fail = (abs_w >= s["cmt_row"][..., None]) & ~ok
        first_fail = jnp.min(jnp.where(fail, abs_w, _INF), axis=3)
        s["cmt_row"] = jnp.clip(
            first_fail, s["cmt_row"], s["cmt_row"] + W
        )
        s["commit_bar"] = jnp.sum(s["cmt_row"], axis=2)

    # ========== execution: row-frontier heuristic with cycle breaking
    def _execute(self, s, c):
        G, R, W = self.G, self.R, self.W
        if not self.config.exec_follows_commit:
            floor = c.inputs["exec_floor_rows"].astype(jnp.int32)
            s["exec_row"] = jnp.clip(floor, s["exec_row"], s["cmt_row"])
            s["exec_bar"] = jnp.sum(s["exec_row"], axis=2)
            return
        gar = jnp.arange(G)[:, None, None]
        rar = jnp.arange(R)[None, :, None]
        rowar = jnp.arange(R)[None, None, :]
        # R passes per tick: a row-cycle of m rows drains one instance per
        # pass (min key first), so R passes keep up with a full round of
        # per-row commits each tick
        go_passes, seq_passes, val_passes = [], [], []
        for _ in range(R):
            pos = s["exec_row"] % W
            x_seq = s["seq2"][gar, rar, rowar, pos]
            x_deps = s["deps2"][gar, rar, rowar, pos]   # [G, R, a, b]
            committed = s["exec_row"] < s["cmt_row"]
            edge = (x_deps > s["exec_row"][:, :, None, :]) & ~jnp.eye(
                R, dtype=jnp.bool_
            )[None, None]
            edge = edge & committed[..., None]
            clo = edge
            for _ in range(max(1, (R - 1).bit_length())):
                nxt = jnp.einsum(
                    "grab,grbc->grac",
                    clo.astype(jnp.int32), clo.astype(jnp.int32),
                ) > 0
                clo = clo | nxt
            key_less = (
                x_seq[:, :, :, None] < x_seq[:, :, None, :]
            ) | (
                (x_seq[:, :, :, None] == x_seq[:, :, None, :])
                & (jnp.arange(R)[None, None, :, None]
                   < jnp.arange(R)[None, None, None, :])
            )
            cyc_ok = (
                jnp.swapaxes(clo, 2, 3)
                & committed[:, :, None, :]
                & key_less
            )
            blocked = jnp.any(edge & ~cyc_ok, axis=3)
            go = committed & ~blocked
            s["exec_row"] = s["exec_row"] + go.astype(jnp.int32)
            go_passes.append(go)
            x_val = s["val2"][gar, rar, rowar, pos]
            seq_passes.append(jnp.where(go, x_seq, 0))
            val_passes.append(jnp.where(go, x_val, 0))
        s["exec_bar"] = jnp.sum(s["exec_row"], axis=2)
        # per-pass execution events [G, R, row, pass] for lossless
        # host-side order reconstruction (pass order, then (seq, row))
        c.exec_go = jnp.stack(go_passes, axis=-1)
        c.exec_seq = jnp.stack(seq_passes, axis=-1)
        c.exec_val = jnp.stack(val_passes, axis=-1)

    # ========== outbox
    def _ring_abs(self, top):
        """[..., W]: largest col < top at each ring position (may be < 0 =
        empty; consumers also check the stored abs lanes)."""
        W = self.W
        p = jnp.arange(W, dtype=jnp.int32)
        t = top[..., None]
        return t - 1 - ((t - 1 - p) % W)

    def _build_outbox(self, s, c):
        G, R, W = self.G, self.R, self.W
        out = self.zero_outbox()
        rid = c.rid
        ns_mask = jnp.broadcast_to(~c.eye, (G, R, R))
        oflags = jnp.where(ns_mask, jnp.uint32(BEACON), jnp.uint32(0))

        # own-row stream straight from the 2-D log
        st_o = self._row_slice(s, "st2", rid)
        abs_o = self._row_slice(s, "abs2", rid)
        live = (st_o > NULL) & (abs_o >= 0)
        out["ow_abs"] = jnp.where(live, abs_o, -1)
        out["ow_phase"] = jnp.where(live, st_o, 0)
        out["ow_bal"] = jnp.where(live, self._row_slice(s, "bal2", rid), 0)
        out["ow_seq"] = jnp.where(live, self._row_slice(s, "seq2", rid), 0)
        out["ow_val"] = jnp.where(live, self._row_slice(s, "val2", rid), 0)
        out["ow_noop"] = jnp.where(
            live, self._row_slice(s, "noop2", rid), False
        )
        out["ow_deps"] = jnp.where(
            live[..., None], self._row_slice(s, "deps2", rid), 0
        )
        out["tb_col"] = s["it_col"]
        out["tb_seq"] = s["it_seq"]
        out["sb"] = s["seen_bar"]

        # rp_abar: per destination row owner d, this sender's CONTIGUOUS
        # accepted frontier over d's row — the first column (walking up
        # from the sender's exec frontier) NOT held Accepting+ at the
        # row's DEFAULT ballot.  Column-identified (abs2 must equal the
        # walked column), unlike a per-position bitmask: a bitmask over
        # ``abs2 % W`` let an ACCEPTING entry for a DIFFERENT column
        # c' = c (mod W) of the same row count as an ack for c, and a
        # command leader could "commit" slow-path attrs no acceptor ever
        # stored (found by the randomized sweep, seed 71, instance
        # (1, 236): committed (seq, deps) diverged across replicas).
        # Entries stored at recovery ballots are deliberately excluded: a
        # revived row owner must not count them as acks of its own
        # (possibly different) attrs — its tally wedges instead, and the
        # stall detector walks it through self-ERP to learn the
        # recovered outcomes.  Recovery-driven instances commit via the
        # racc tally.
        dbal_rows = self._default_bal(
            jnp.arange(R, dtype=jnp.int32)
        )[None, None, :, None]
        _, acc_absw = range_cover(s["exec_row"], s["exec_row"] + W, W)
        acc_cov = (
            (s["abs2"] == acc_absw)
            & (s["st2"] >= ACCEPTING)
            & (s["bal2"] == dbal_rows)
        )
        acc_gap = (acc_absw >= s["exec_row"][..., None]) & ~acc_cov
        acc_first = jnp.min(jnp.where(acc_gap, acc_absw, _INF), axis=3)
        # the attestation is the HALF-OPEN run [rp_abase, rp_abar): the
        # base ships too because columns below my exec frontier are NOT
        # implicit acks — I may have executed a RECOVERY outcome there
        # (non-default ballot, possibly different attrs), and a revived
        # owner counting "executed past c" as "accepted my attrs at c"
        # re-committed divergent (seq, deps) (sweep seed 3, instance
        # (1, 0): recovery committed the original seq=1, the revived
        # owner then slow-"committed" seq=58 off this phantom ack)
        out["rp_abase"] = s["exec_row"]
        out["rp_abar"] = jnp.clip(
            acc_first, s["exec_row"], s["exec_row"] + W
        )  # [G, R, row] -> per-pair [G, src, dst=row]
        # the PREACC-level run backs the owner's fast-path ingest count:
        # sb (seen_bar) counts ANY stored entry, so a recovery-driven
        # no-op at position c read as "peer ingested my entry" and a
        # revived owner could fast-commit its original value over a
        # committed recovery no-op; this run requires the row's DEFAULT
        # ballot, which any recovery outcome breaks
        pre_cov = (
            (s["abs2"] == acc_absw)
            & (s["st2"] >= PREACC)
            & (s["bal2"] == dbal_rows)
        )
        pre_gap = (acc_absw >= s["exec_row"][..., None]) & ~pre_cov
        pre_first = jnp.min(jnp.where(pre_gap, acc_absw, _INF), axis=3)
        out["rp_pbar"] = jnp.clip(
            pre_first, s["exec_row"], s["exec_row"] + W
        )

        # ERP campaign
        rec_on = s["rec_row"] >= 0
        do_erp = rec_on[..., None] & ns_mask
        oflags = oflags | jnp.where(do_erp, jnp.uint32(ERP), 0)
        out["erp_row"] = jnp.where(do_erp, s["rec_row"][..., None], 0)
        out["erp_bal"] = jnp.where(do_erp, s["rec_bal"][..., None], 0)
        tgt_ext = jnp.take_along_axis(
            s["ext_row"], jnp.maximum(s["rec_row"], 0)[..., None], axis=2
        )[..., 0]
        out["erp_ext"] = jnp.where(do_erp, tgt_ext[..., None], 0)

        # RV responses: serve the highest-ballot ERP heard this tick
        erp_in = (c.flags & ERP) != 0
        erp_bal_in = jnp.where(erp_in, c.inbox["erp_bal"], 0)
        best_bal = erp_bal_in.max(axis=2)
        best_src = erp_bal_in.argmax(axis=2)[..., None]
        srow = jnp.take_along_axis(c.inbox["erp_row"], best_src, axis=2)[
            ..., 0
        ]
        srow_c = jnp.maximum(srow, 0)
        # never answer a campaign below a ballot already promised for that
        # row (rbm was raised by _ingest_erp this tick, so this also means
        # only the max concurrent campaign gets served) — otherwise two
        # overlapping recoverers at different ballots can both reach quorum
        srow_rbm = jnp.take_along_axis(s["rbm"], srow_c[..., None], axis=2)[
            ..., 0
        ]
        serve = (best_bal > 0) & (best_bal >= srow_rbm)
        out["rv_row"] = jnp.where(serve, srow, -1)
        out["rv_bal"] = jnp.where(serve, best_bal, 0)
        rv_live = (self._row_slice(s, "st2", srow_c) > NULL) & serve[
            ..., None
        ]
        out["rv_abs"] = jnp.where(
            rv_live, self._row_slice(s, "abs2", srow_c), -1
        )
        out["rv_st"] = jnp.where(rv_live, self._row_slice(s, "st2", srow_c), 0)
        out["rv_vbal"] = jnp.where(
            rv_live, self._row_slice(s, "bal2", srow_c), 0
        )
        out["rv_seq"] = jnp.where(
            rv_live, self._row_slice(s, "seq2", srow_c), 0
        )
        out["rv_val"] = jnp.where(
            rv_live, self._row_slice(s, "val2", srow_c), 0
        )
        out["rv_noop"] = jnp.where(
            rv_live, self._row_slice(s, "noop2", srow_c), False
        )
        out["rv_deps"] = jnp.where(
            rv_live[..., None], self._row_slice(s, "deps2", srow_c), 0
        )
        out["rv_bump"] = jnp.where(
            rv_live, self._row_slice(s, "pbump2", srow_c), False
        )
        # my committed frontier over the served row: columns below it are
        # committed HERE even if my window already slid past their copies
        # — the recoverer must not re-decide them from weaker evidence
        out["rv_cmt"] = jnp.where(
            serve,
            jnp.take_along_axis(s["cmt_row"], srow_c[..., None], axis=2)[
                ..., 0
            ],
            0,
        )
        do_rv = serve[..., None] & ns_mask
        oflags = oflags | jnp.where(do_rv, jnp.uint32(RV), 0)

        # RO drive lanes from the decision ladder
        ro = self._recovery_apply(s, c)
        out.update(ro)
        do_ro = (out["ro_row"] >= 0)[..., None] & ns_mask
        oflags = oflags | jnp.where(do_ro, jnp.uint32(RO), 0)

        out["flags"] = oflags
        return out

    # ========== recovery decision ladder (recoverer side)
    def _recovery_apply(self, s, c):
        G, R, W, K = self.G, self.R, self.W, self.config.num_key_buckets
        inbox = c.inbox
        tgt = c.rec_tgt
        tgt_c = jnp.maximum(tgt, 0)
        rv_mine = c.rv_mine                      # [G, me, src]

        tgt_ext = jnp.take_along_axis(
            s["ext_row"], tgt_c[..., None], axis=2
        )[..., 0]
        tgt_cmt = jnp.take_along_axis(
            s["cmt_row"], tgt_c[..., None], axis=2
        )[..., 0]
        my_ring = self._ring_abs(tgt_ext)        # [G, R, W]

        def rin(name, extra=()):
            return jnp.broadcast_to(
                inbox[name][:, None], (G, R, R, W) + extra
            )

        align = (
            rv_mine[..., None]
            & (rin("rv_abs") == my_ring[:, :, None, :])
            & (my_ring[:, :, None, :] >= 0)
        )
        rv_st = jnp.where(align, rin("rv_st"), 0)
        rv_vbal = jnp.where(align, rin("rv_vbal"), 0)
        rv_seq = rin("rv_seq")
        rv_val = rin("rv_val")
        rv_noop = rin("rv_noop")
        rv_deps = rin("rv_deps", (R,))
        rv_bump = jnp.where(align, rin("rv_bump"), False)
        # highest committed frontier any responder reports for the row:
        # columns below it are committed SOMEWHERE even if every visible
        # window slid past them — re-deciding those from preaccept-level
        # evidence fabricated fresh attrs over committed instances
        # (randomized sweep, seed 3, g0 instance (1, 0))
        rv_cmt_in = jnp.broadcast_to(inbox["rv_cmt"][:, None], (G, R, R))
        resp_cmt = jnp.max(jnp.where(rv_mine, rv_cmt_in, 0), axis=2)

        own_st = self._row_slice(s, "st2", tgt_c)
        own_abs = self._row_slice(s, "abs2", tgt_c)
        own_ok = own_abs == my_ring
        own_st = jnp.where(own_ok, own_st, 0)
        own_vbal = jnp.where(own_ok, self._row_slice(s, "bal2", tgt_c), 0)
        own_seq = self._row_slice(s, "seq2", tgt_c)
        own_val = self._row_slice(s, "val2", tgt_c)
        own_noop = self._row_slice(s, "noop2", tgt_c)
        own_deps = self._row_slice(s, "deps2", tgt_c)

        unresolved = (
            (my_ring >= tgt_cmt[..., None])
            & (my_ring < tgt_ext[..., None])
            & (own_st < COMMITTED)
        )
        act = c.rec_have_q[..., None] & unresolved & (tgt >= 0)[..., None]

        def from_src(lane, ownl, src, use_own):
            got = jnp.take_along_axis(
                jnp.swapaxes(lane, 2, 3), src, axis=3
            )[..., 0]
            return jnp.where(use_own, ownl, got)

        def from_src_d(lane, ownl, src, use_own):
            got = jnp.take_along_axis(
                jnp.swapaxes(lane, 2, 3), src[..., None], axis=3
            )[..., 0, :]
            return jnp.where(use_own[..., None], ownl, got)

        # ladder 1: committed copy anywhere
        own_cmt = own_st >= COMMITTED
        any_cmt = act & (jnp.any(rv_st >= COMMITTED, axis=2) | own_cmt)
        csrc = jnp.argmax((rv_st >= COMMITTED), axis=2)[..., None]
        c_seq = from_src(rv_seq, own_seq, csrc, own_cmt)
        c_val = from_src(rv_val, own_val, csrc, own_cmt)
        c_noop = from_src(rv_noop, own_noop, csrc, own_cmt)
        c_deps = from_src_d(rv_deps, own_deps, csrc, own_cmt)

        # columns committed at some responder but not visible as committed
        # copies anywhere in the quorum: leave them alone — the outcome
        # reaches us via normal commit propagation or the lost-row
        # install plane, never via re-decision from weaker evidence
        lost = (my_ring < resp_cmt[..., None]) & ~any_cmt

        # ladder 2: accepting copy at the max voted ballot
        accm = rv_st == ACCEPTING
        own_acc = own_st == ACCEPTING
        acc_best = jnp.maximum(
            jnp.max(jnp.where(accm, rv_vbal, 0), axis=2),
            jnp.where(own_acc, own_vbal, 0),
        )
        any_acc = act & ~any_cmt & ~lost & (acc_best > 0)
        use_own_a = own_acc & (own_vbal >= acc_best)
        asrc = jnp.argmax(jnp.where(accm, rv_vbal, -1), axis=2)[..., None]
        a_seq = from_src(rv_seq, own_seq, asrc, use_own_a)
        a_val = from_src(rv_val, own_val, asrc, use_own_a)
        a_noop = from_src(rv_noop, own_noop, asrc, use_own_a)
        a_deps = from_src_d(rv_deps, own_deps, asrc, use_own_a)

        # ladder 3: an UNBUMPED preaccept copy at the row's default ballot
        # — an acceptor whose merge did not change the owner's attrs
        # stores exactly the original (seq, deps), the only attrs a fast
        # commit can decide.  One witness suffices: if the fast path
        # committed, it committed these attrs; if the slow path
        # committed, an ACCEPTING copy is guaranteed visible in any
        # recovery quorum (2*simple_q - R >= 1 intersection) and ladder 2
        # already took it; if nothing committed, the original is a valid
        # free choice and every racing recoverer derives the same one.
        # (The previous rule counted BUMPED copies as witnesses and
        # tie-broke between divergent candidate attrs by loop order —
        # two recoverers could commit different (seq, deps) for one
        # instance; randomized sweep seeds 3/41/67/71.)
        dbal = self._default_bal(tgt_c)[..., None]        # [G, R, 1]
        pre_all = (rv_st == PREACC) & (rv_vbal == dbal[:, :, None, :])
        pre = pre_all & ~rv_bump
        own_pre_all = (own_st == PREACC) & (own_vbal == dbal)
        own_bump = own_ok & self._row_slice(s, "pbump2", tgt_c)
        own_pre = own_pre_all & ~own_bump
        ident = act & ~any_cmt & ~any_acc & ~lost & (
            jnp.any(pre, axis=2) | own_pre
        )
        use_own_i = own_pre & ~jnp.any(pre, axis=2)
        isrc = jnp.argmax(pre, axis=2)[..., None]
        i_seq = from_src(rv_seq, own_seq, isrc, use_own_i)
        i_val = from_src(rv_val, own_val, isrc, use_own_i)
        i_noop = from_src(rv_noop, own_noop, isrc, use_own_i)
        i_deps = from_src_d(rv_deps, own_deps, isrc, use_own_i)

        # ladder 4: only bumped preaccepts -> the fast path provably did
        # not commit (a bumped acceptor's tables fail the owner's
        # identity check from ingest on) and no accept is visible:
        # re-propose the voted value with a fresh merge from my tables;
        # ladder 5: nothing -> no-op
        any_pre = jnp.any(pre_all, axis=2) | own_pre_all
        repro = act & ~any_cmt & ~any_acc & ~lost & ~ident & any_pre
        noopf = act & ~any_cmt & ~any_acc & ~lost & ~ident & ~any_pre
        use_own_p = own_pre_all & ~jnp.any(pre_all, axis=2)
        psrc = jnp.argmax(pre_all, axis=2)[..., None]
        p_val = from_src(rv_val, own_val, psrc, use_own_p)
        p_noop = from_src(rv_noop, own_noop, psrc, use_own_p)
        pbucket = p_val % K
        itc = self._bucket_gather(s["it_col"], pbucket)
        its = self._bucket_gather(s["it_seq"], pbucket)
        p_seq = 1 + jnp.max(its, axis=3)
        p_deps = itc

        phase = jnp.where(
            any_cmt,
            COMMITTED,
            jnp.where(any_acc | ident | repro | noopf, ACCEPTING, 0),
        )
        o_seq = jnp.where(any_cmt, c_seq, jnp.where(
            any_acc, a_seq, jnp.where(ident, i_seq, jnp.where(
                repro, p_seq, 1))))
        o_val = jnp.where(any_cmt, c_val, jnp.where(
            any_acc, a_val, jnp.where(ident, i_val, jnp.where(
                repro, p_val, 0))))
        o_noop = jnp.where(any_cmt, c_noop, jnp.where(
            any_acc, a_noop, jnp.where(ident, i_noop, jnp.where(
                repro, p_noop, True))))
        o_deps = jnp.where(any_cmt[..., None], c_deps, jnp.where(
            any_acc[..., None], a_deps, jnp.where(
                ident[..., None], i_deps, jnp.where(
                    repro[..., None], p_deps, 0))))

        # accept tally for driven instances: responders ACCEPTING at
        # exactly my ERP ballot — rec_bal embeds my replica id, so equality
        # uniquely identifies entries driven by *this* campaign; a >= check
        # would count a higher-ballot concurrent recoverer's different
        # value as an ack of mine
        racc = 1 + jnp.sum(
            (align & (rv_st == ACCEPTING)
             & (rv_vbal == s["rec_bal"][..., None, None])).astype(jnp.int32),
            axis=2,
        )
        promote = act & (phase == ACCEPTING) & (racc >= self.simple_q)
        phase = jnp.where(promote, COMMITTED, phase)

        # store outcomes locally (the recoverer is an acceptor too)
        tgt_sel = (
            jnp.arange(R)[None, None, :, None] == tgt[:, :, None, None]
        ) & (tgt >= 0)[:, :, None, None]
        act4 = tgt_sel & (phase > 0)[:, :, None, :]
        keep_cmt = act4 & (s["st2"] == COMMITTED) & (
            phase[:, :, None, :] < COMMITTED
        )
        act4 = act4 & ~keep_cmt
        s["abs2"] = jnp.where(act4, my_ring[:, :, None, :], s["abs2"])
        s["st2"] = jnp.where(act4, phase[:, :, None, :], s["st2"])
        s["bal2"] = jnp.where(act4, s["rec_bal"][:, :, None, None], s["bal2"])
        s["seq2"] = jnp.where(act4, o_seq[:, :, None, :], s["seq2"])
        s["val2"] = jnp.where(act4, o_val[:, :, None, :], s["val2"])
        s["noop2"] = jnp.where(act4, o_noop[:, :, None, :], s["noop2"])
        s["deps2"] = jnp.where(
            act4[..., None], o_deps[:, :, None, :, :], s["deps2"]
        )

        rec_on = (tgt >= 0) & c.rec_have_q
        return {
            "ro_row": jnp.where(rec_on, tgt, -1),
            "ro_bal": jnp.where(rec_on, s["rec_bal"], 0),
            "ro_abs": jnp.where(rec_on[..., None] & (phase > 0), my_ring, -1),
            "ro_phase": jnp.where(rec_on[..., None], phase, 0),
            "ro_seq": jnp.where(rec_on[..., None], o_seq, 0),
            "ro_val": jnp.where(rec_on[..., None], o_val, 0),
            "ro_noop": jnp.where(rec_on[..., None], o_noop, False),
            "ro_deps": jnp.where(rec_on[..., None, None], o_deps, 0),
        }

    # ----------------------------------------------------------- telemetry
    def _telemetry(self, old, s, c) -> dict:
        """Metric lanes (core/telemetry.py SPI): the 2-D instance space
        has no ballot/window analog of the slot protocols, so commits are
        the committed-row delta, occupancy is the replica's OWN proposal
        row, and recovery drives count as elections."""
        G, R = self.G, self.R
        tel = {
            "commits": jnp.maximum(
                jnp.sum(s["cmt_row"], axis=2)
                - jnp.sum(old["cmt_row"], axis=2),
                0,
            ),
            "proposals": c.n_new,
            # a recovery takeover is the leaderless analog of a campaign
            "elections": (s["rec_row"] >= 0) & (old["rec_row"] < 0),
        }
        # own-row live span (cheap proxy, see _occupancy_span): columns
        # minted but not yet executed on this replica's own proposal row
        idx = jnp.arange(R)
        exec_own = s["exec_row"][:, idx, idx]
        tel["win_occupancy_hw"] = jnp.clip(
            s["own_next"] - exec_own, 0, self.window
        )
        return tel

    # ------------------------------------------------------------- effects
    def _effects(self, s, c):
        G, R = self.G, self.R
        zero = jnp.zeros((G, R, R, R), jnp.bool_)
        return StepEffects(
            commit_bar=s["commit_bar"],
            exec_bar=s["exec_bar"],
            extra={
                "n_accepted": c.n_new,
                "cmt_row": s["cmt_row"],
                "exec_row": s["exec_row"],
                "rec_row": s["rec_row"],
                "exec_go": getattr(c, "exec_go", zero),
                "exec_seq": getattr(
                    c, "exec_seq", zero.astype(jnp.int32)
                ),
                "exec_val": getattr(
                    c, "exec_val", zero.astype(jnp.int32)
                ),
            },
        )
