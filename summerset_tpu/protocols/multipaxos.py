"""Vectorized MultiPaxos: batched multi-decree Paxos stepped in lockstep.

Parity target: reference ``src/protocols/multipaxos/`` (SURVEY.md §2.5) —
slot-wise instances with ``(round << 8) | id`` ballots, bulk Prepare from a
trigger slot with per-slot value adoption, Accept quorum tally, commit/exec
bars advanced in order, leader step-up on heartbeat timeout, snapshot-style
log GC bounded by the min peer exec bar (``snap_bar``,
``multipaxos/mod.rs:470-478``).

TPU-first redesign (NOT a port of the tokio event loop):

- **State** is struct-of-arrays over ``[G groups, R replicas]`` with a
  ``W``-slot ring log window per replica (``win_abs/win_bal/win_val``).
  Values are int32 *references* into a host-side payload store — the device
  runs the control plane of consensus; bulky request batches never touch HBM
  (SURVEY.md §7 hard part (b)).
- **Replication is per-peer go-back-N range streams with cumulative acks**:
  the leader keeps a ``next_idx`` send cursor per peer; followers maintain a
  contiguous voting run ``[vote_from, vote_bar)`` at their current ballot and
  ack with their durable frontier; a gap triggers a NACK with a rewind hint.
  The reference's per-slot ack bitmap tally (``messages.rs:370-442``)
  becomes a k-th-largest over R cumulative frontiers — O(R log R) vector ops
  per group per tick instead of per-slot scatter/gather, which is what makes
  the quorum tally MXU/VPU-friendly.
- **Commit propagation rides heartbeats** (the reference's CommitSlot WAL
  entry + urgent CommitNotice, ``durability.rs:148``): followers advance
  ``commit_bar`` to ``min(leader commit_bar, own voted frontier)`` only when
  their voting run is at the leader's ballot — the vote-at-ballot-b condition
  that makes heartbeat commit safe.
- **Leader election**: per-replica jittered countdowns (reference randomized
  hear-timeouts, ``heartbeat.rs:96-116``) -> candidate broadcasts Prepare
  with ``trigger = commit_bar`` (``leadership.rs:113-134``); followers reply
  with their voted window (broadcast lanes ``bw_abs/bw_bal/bw_val``), the
  candidate adopts max-ballot values per slot, fills holes with no-ops, and
  re-proposes the tail at its ballot (``messages.rs:87`` semantics).

Known deviation from the reference: message loss here means silent drop (the
netmodel's masks), so liveness machinery (candidate re-Prepare each tick,
per-peer retry countdown with go-back-to-matched-frontier) is built into the
kernel rather than delegated to TCP retransmission.

Structure note: ``step`` is decomposed into phase methods with designated
override hooks — the reference's protocol-variant family (RSPaxos,
Crossword, QuorumLeases, Bodega all embed the MultiPaxos skeleton,
SURVEY.md §2.5) maps to subclasses overriding the tally / adoption /
commit-condition hooks rather than re-implementing the event loop.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax
import jax.numpy as jnp

from ..core import quorum as quorum_lib
from ..core.protocol import ProtocolKernel, StepEffects
from ..ops import prng
from ..utils.bitmap import popcount
from . import register_protocol
from .common import (
    NO_SLOT,
    NULL_VAL,
    advance_durability,
    advance_exec,
    best_by_ballot,
    client_intake,
    dst_onehot,
    initial_ballot,
    make_greater_ballot,
    not_self,
    range_cover,
    take_lane,
    take_src,
)

# message flag bits
ACCEPT = 1
ACCEPT_REPLY = 2
HEARTBEAT = 4
HB_REPLY = 8
PREPARE = 16
PREPARE_REPLY = 32
AR_NACK = 64  # modifier on ACCEPT_REPLY: sender saw a gap; rewind to ar_hint
SNAPSHOT = 128  # install-snapshot: jump a >window-behind follower forward
# bits 256+ are reserved for subclass extensions (rspaxos reconstruction,
# crossword gossip, lease planes)


@dataclasses.dataclass
class ReplicaConfigMultiPaxos:
    """Static per-run knobs (parity: ``ReplicaConfigMultiPaxos``,
    ``multipaxos/mod.rs:49-120``, re-expressed in ticks)."""

    max_proposals_per_tick: int = 16    # client batch intake per group/tick
    chunk_size: int = 64                # max Accept slots per peer per tick
                                        # (parity: msg_chunk_size)
    hb_send_interval: int = 1           # leader heartbeat period (ticks)
    hear_timeout_lo: int = 30           # election timeout jitter range
    hear_timeout_hi: int = 60
    retry_interval: int = 8             # go-back-N resend countdown
    dur_lag: int = 0                    # WAL ack lag in slots/tick (0=instant)
    exec_follows_commit: bool = True    # device-only mode: exec == commit
    init_leader: int = 0                # warm-start leader id; -1 = cold elect
    # stable-leader lease plane (reference leaderlease.rs:10-21 via the
    # clock-free countdown scheme): followers promise vote refusal for
    # ``leader_lease_len`` ticks on every accepted heartbeat; the leader
    # counts confirmed promises from heartbeat replies (shortened by
    # ``lease_margin`` so its belief expires first) and may serve local
    # reads while a quorum holds
    leader_leases: bool = False
    leader_lease_len: int = 12
    # must exceed the max one-way network delay (in ticks): the grantor's
    # promise outliving the holder's belief by more than a delivery delay
    # is the whole clock-free safety argument.  Engine construction
    # enforces lease_margin > NetConfig.max_delay_ticks whenever a lease
    # plane is active (core/engine.py); host deployments over real TCP
    # must budget it against tick_interval x observed one-way latency.
    lease_margin: int = 3
    # quorum-tally transport (core/quorum.py): "pairwise" keeps the
    # accept-reply lanes as R² [G, R, R] delay-line traffic (the
    # digest-compatible default); "collective" shrinks them to
    # per-source [G, R] broadcast records — the NetPaxos-style in-mesh
    # tally — with byte-identical state/effects/telemetry (the flags
    # pair-field keeps per-link masking, so the collective reads the
    # same D-tick-delayed votes the pairwise path would deliver)
    tally: str = "pairwise"


@register_protocol("MultiPaxos")
class MultiPaxosKernel(ProtocolKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_bal", "bw_val"})

    # quorum-tally lanes (core/quorum.py): the accept-reply record a
    # follower sends its leader is destination-independent (vote
    # ballot, run start, durable frontier, nack rewind hint) — under
    # ``tally="collective"`` these shrink from [G, R, R] pair lanes to
    # per-source [G, R] broadcast records while the flags pair-field
    # keeps per-link visibility (ACCEPT_REPLY / AR_NACK bits)
    TALLY_LANES: Tuple[str, ...] = ("ar_bal", "ar_from", "ar_f", "ar_hint")

    # voluntary leader demotion (gray-failure mitigation): a [G, R] bool
    # mask from the host — rows the health plane indicted abandon their
    # prepared leadership and hold off re-campaigning (host/health.py is
    # the detector, host/server.py the driver; the whole variant family
    # — RSPaxos/Crossword/QuorumLeases/Bodega — inherits the same path)
    EXTRA_INPUTS: Tuple[Tuple[str, str], ...] = (("demote", "gr"),)

    # durable acceptor record (host WAL contract; parity: the reference
    # fsyncs PrepareBal/AcceptData before AcceptReply, durability.rs:85-216)
    DURABLE_SCALARS = ("bal_max", "vote_bal", "vote_from", "vote_bar")
    DURABLE_WINDOWS = ("win_abs", "win_bal", "win_val")

    def restore_durable(self, st, g, me, rec, floor):
        """Reinstate our acceptor row from the last logged record — a
        crash-restarted replica must not forget its promises/votes
        (double-vote) nor its voted window content."""
        i32 = jnp.int32
        fl = i32(floor)
        vbar = jnp.maximum(i32(rec["vote_bar"]), fl)
        st["bal_max"] = st["bal_max"].at[g, me].max(i32(rec["bal_max"]))
        st["vote_bal"] = st["vote_bal"].at[g, me].set(i32(rec["vote_bal"]))
        st["vote_from"] = st["vote_from"].at[g, me].set(
            i32(rec["vote_from"])
        )
        st["vote_bar"] = st["vote_bar"].at[g, me].max(vbar)
        st["dur_bar"] = st["dur_bar"].at[g, me].set(vbar)
        st["commit_bar"] = st["commit_bar"].at[g, me].max(fl)
        st["exec_bar"] = st["exec_bar"].at[g, me].max(fl)
        for k in self.DURABLE_WINDOWS:
            st[k] = st[k].at[g, me].set(jnp.asarray(rec[k], st[k].dtype))
        # proposal cursor: resume AFTER everything this replica ever
        # voted or executed.  Without this, a warm-init leader that
        # crash-restarts fast enough that no follower campaigns (its
        # ballot still prepared) re-proposes at slot 0 over committed
        # slots: the re-proposals can never commit (commit_bar is capped
        # at next_slot) and every new request wedges behind them.
        abs_arr = jnp.asarray(rec["win_abs"], jnp.int32)
        bal_arr = jnp.asarray(rec["win_bal"], jnp.int32)
        filled = (bal_arr > 0) & (abs_arr >= 0)
        nslot = jnp.maximum(
            fl, jnp.max(jnp.where(filled, abs_arr + 1, 0))
        )
        st["next_slot"] = st["next_slot"].at[g, me].max(nslot)

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigMultiPaxos | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigMultiPaxos()
        quorum_lib.check_tally(getattr(self.config, "tally", "pairwise"))
        if self.collective_tally:
            # collective tally records are per-source lanes: delivered
            # by the broadcast path (all-gather over a sharded replica
            # axis), never transposed
            self.broadcast_lanes = (
                frozenset(type(self).broadcast_lanes) | self.tally_lanes
            )
        if self.config.max_proposals_per_tick > window // 2:
            raise ValueError("max_proposals_per_tick must be <= window/2")
        if getattr(self.config, "leader_leases", False) and (
            self.config.hear_timeout_lo <= self.config.leader_lease_len
        ):
            raise ValueError(
                "hear_timeout_lo must exceed leader_lease_len (a follower "
                "must outlive its own promise before campaigning)"
            )
        # an Accept range never exceeds the ring window
        self._chunk = min(self.config.chunk_size, window)

    # ------------------------------------------------------- subclass hooks
    @property
    def commit_k(self) -> int:
        """Cumulative-frontier tally order for commit (reference per-slot
        quorum count, ``messages.rs:370-442``).  RSPaxos/CRaft raise it to
        ``quorum + fault_tolerance``."""
        return self.quorum

    @property
    def prepare_k(self) -> int:
        """Promise count required for step-up."""
        return self.quorum

    def _extra_state(self, st: dict, seed: int) -> None:
        """Subclass state fields (added in place)."""

    def _extra_outbox(self, out: dict) -> None:
        """Subclass outbox fields (added in place)."""

    # ------------------------------------------------------------------ init
    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        cfg = self.config
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))

        rng = prng.seed_state(seed, (G, R))
        rng, hb_cnt = prng.uniform_int(
            rng, cfg.hear_timeout_lo, cfg.hear_timeout_hi
        )

        st = {
            "bal_max": zeros(G, R),
            "bal_prepared": zeros(G, R),
            "bal_prep_sent": zeros(G, R),
            "leader": jnp.full((G, R), -1, i32),
            "prep_trigger": zeros(G, R),
            "prep_acks": jnp.zeros((G, R), jnp.uint32),
            "prep_hi": zeros(G, R),
            "next_slot": zeros(G, R),
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "vote_bal": zeros(G, R),
            "vote_from": zeros(G, R),
            "vote_bar": zeros(G, R),
            "dur_bar": zeros(G, R),
            "hb_cnt": hb_cnt,
            "hb_send_cnt": zeros(G, R),
            "rng": rng,
            "next_idx": zeros(G, R, R),
            "match_f": zeros(G, R, R),
            "match_from": zeros(G, R, R),
            "match_bal": zeros(G, R, R),
            "retry_cnt": jnp.full((G, R, R), cfg.retry_interval, i32),
            "peer_exec": zeros(G, R, R),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_bal": zeros(G, R, W),
            "win_val": jnp.full((G, R, W), NULL_VAL, i32),
        }

        if getattr(cfg, "leader_leases", False):
            # follower-side promise countdown + leader-side confirmed
            # promises (QL/Bodega-style clock-free margin arithmetic).
            # ll_left starts FULL, not zero: a crash-restarted replica
            # cannot know whether it promised vote refusal just before
            # dying, so it must sit out a full promise window before
            # granting challengers — otherwise a restarted follower
            # votes a new leader in while the old one still believes
            # its lease quorum holds and serves stale local reads
            # (same conservative-full-init pattern as QL's gset_ttl).
            # Election liveness is unaffected: hear timeouts exceed
            # leader_lease_len (validated in __init__), so campaigns
            # start after the holdoff has lapsed anyway.
            st["ll_left"] = jnp.full((G, R), cfg.leader_lease_len, i32)
            st["ll_in"] = zeros(G, R, R)

        if cfg.init_leader >= 0:
            L = cfg.init_leader
            bal0 = int(initial_ballot(jnp.int32(L)))
            is_l = rid == L
            st["bal_max"] = jnp.full((G, R), bal0, i32)
            st["bal_prepared"] = jnp.where(is_l, bal0, 0)
            st["bal_prep_sent"] = jnp.where(is_l, bal0, 0)
            st["leader"] = jnp.full((G, R), L, i32)
            st["vote_bal"] = jnp.full((G, R), bal0, i32)
        self._extra_state(st, seed)
        return st

    # ---------------------------------------------------------------- outbox
    def zero_outbox(self):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        # tally lanes: per-source [G, R] records in collective mode
        # (core/quorum.py), classic [G, R, R] pair lanes otherwise
        tlane = (
            (lambda: jnp.zeros((G, R), i32))
            if self.collective_tally else pair
        )
        out = {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "acc_bal": pair(), "acc_lo": pair(), "acc_hi": pair(),
            "ar_bal": tlane(), "ar_from": tlane(), "ar_f": tlane(),
            "ar_hint": tlane(),
            "hb_bal": pair(), "hb_cbar": pair(), "hb_ebar": pair(),
            "hbr_ebar": pair(),
            "prp_bal": pair(), "prp_trigger": pair(),
            "prr_bal": pair(), "prr_hi": pair(),
            "snp_bal": pair(), "snp_to": pair(),
            "bw_abs": jnp.zeros((G, R, W), i32),
            "bw_bal": jnp.zeros((G, R, W), i32),
            "bw_val": jnp.zeros((G, R, W), i32),
        }
        self._extra_outbox(out)
        return out

    # ------------------------------------------------------------------ step
    # The graftprof phase registry (core/protocol.py): execution order is
    # the tuple order, method overrides in the variant family (RSPaxos /
    # Crossword / QuorumLeases / Bodega tally, adoption and send hooks)
    # keep their phase attribution.  ``telemetry`` runs after
    # ``build_outbox`` on purpose: send-side hooks (_extra_sends) mutate
    # state too — lease grants live there — and telemetry reads
    # old-vs-new.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("ingest_heartbeat", "_ingest_heartbeat"),
        ("ingest_prepare", "_ingest_prepare"),
        ("ingest_snapshot", "_ingest_snapshot"),
        ("ingest_accept", "_ingest_accept"),
        ("ingest_accept_reply", "_ingest_accept_reply"),
        ("ingest_hb_reply", "_ingest_hb_reply"),
        ("ingest_prepare_reply", "_gated_prepare_reply"),
        ("election", "_election"),
        ("try_step_up", "_try_step_up"),
        ("leader_propose", "_leader_propose"),
        (quorum_lib.PHASE_TALLY, "_phase_quorum_tally"),
        ("advance_bars", "_advance_bars"),
        ("build_outbox", "_phase_build_outbox"),
        ("telemetry", "_phase_telemetry"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        c.rid = jnp.broadcast_to(
            jnp.arange(self.R, dtype=jnp.int32)[None, :], (self.G, self.R)
        )
        self._run_phases(s, c)
        fx = self._effects(s, c)
        return s, c.out, fx

    # ========== 1. HEARTBEAT ingest (leader liveness + commit notice)
    def _ingest_heartbeat(self, s, c):
        cfg = self.config
        inbox = c.inbox
        if getattr(cfg, "leader_leases", False):
            # countdowns tick once per lockstep tick (first phase to run)
            s["ll_left"] = jnp.maximum(s["ll_left"] - 1, 0)
            s["ll_in"] = jnp.maximum(s["ll_in"] - 1, 0)
        hb_ok, hb_bal, hb_src = best_by_ballot(
            c.flags, HEARTBEAT, inbox["hb_bal"]
        )
        hb_ok &= hb_bal >= s["bal_max"]
        s["leader"] = jnp.where(hb_ok, hb_src, s["leader"])
        s["bal_max"] = jnp.where(hb_ok, hb_bal, s["bal_max"])
        s["rng"], c.reload = prng.uniform_int(
            s["rng"], cfg.hear_timeout_lo, cfg.hear_timeout_hi
        )
        s["hb_cnt"] = jnp.where(hb_ok, c.reload, s["hb_cnt"])
        # follower commit advance: only when voting at the leader's ballot
        # with a run reaching back to (at or below) our commit bar
        hb_cbar = take_src(inbox["hb_cbar"], hb_src)
        can_commit = (
            hb_ok
            & (s["vote_bal"] == hb_bal)
            & (s["vote_from"] <= s["commit_bar"])
        )
        s["commit_bar"] = jnp.where(
            can_commit,
            jnp.maximum(s["commit_bar"], jnp.minimum(hb_cbar, s["vote_bar"])),
            s["commit_bar"],
        )
        if getattr(cfg, "leader_leases", False):
            # an accepted heartbeat refreshes our vote-refusal promise to
            # its sender (reference promise refresh, leaderlease.rs:10-21)
            s["ll_left"] = jnp.where(hb_ok, cfg.leader_lease_len, s["ll_left"])
        c.hb_ok, c.hb_bal, c.hb_src = hb_ok, hb_bal, hb_src
        c.hb_reply_to = hb_ok

    def _vote_gate(self, s, c, p_bal, p_src):
        """Hook: extra veto on granting a Prepare promise (leader leases
        refuse votes for challengers while the promise countdown runs)."""
        if not getattr(self.config, "leader_leases", False):
            return jnp.ones((self.G, self.R), jnp.bool_)
        # no unknown-leader escape: leader is -1 exactly when we have no
        # heartbeat source — after a restart that is precisely the state
        # in which a possibly-outstanding promise must be waited out
        return (s["ll_left"] <= 0) | (p_src == s["leader"])

    # ========== 2. PREPARE ingest (promise + voted-window reply)
    def _ingest_prepare(self, s, c):
        p_ok, p_bal, p_src = best_by_ballot(
            c.flags, PREPARE, c.inbox["prp_bal"]
        )
        p_ok &= p_bal >= s["bal_max"]
        p_ok &= self._vote_gate(s, c, p_bal, p_src)
        s["bal_max"] = jnp.where(p_ok, p_bal, s["bal_max"])
        s["leader"] = jnp.where(p_ok, p_src, s["leader"])
        # also reset the election countdown: someone is actively campaigning
        s["hb_cnt"] = jnp.where(p_ok, c.reload, s["hb_cnt"])
        c.voted_extent = jnp.max(
            jnp.where(s["win_bal"] > 0, s["win_abs"] + 1, 0), axis=2
        )
        c.prr_hi_out = c.voted_extent
        c.p_ok, c.p_bal, c.p_src = p_ok, p_bal, p_src

    # ========== 2b. SNAPSHOT ingest (install: jump forward)
    def _ingest_snapshot(self, s, c):
        # The reference never discards log a peer still needs (conservative
        # snap_bar, mod.rs:470-478) at the cost of unbounded memory; fixed
        # ring windows instead bound capacity by the leader's own exec bar
        # and laggards get a Raft-style install-snapshot (state itself is
        # transferred host-side; the device installs the bars).
        inbox = c.inbox
        sn_ok, sn_bal, sn_src = best_by_ballot(
            c.flags, SNAPSHOT, inbox["snp_bal"]
        )
        sn_ok &= sn_bal >= s["bal_max"]
        sn_to = take_src(inbox["snp_to"], sn_src)
        sn_adv = sn_ok & (sn_to > s["commit_bar"])
        s["bal_max"] = jnp.where(sn_ok, sn_bal, s["bal_max"])
        s["leader"] = jnp.where(sn_ok, sn_src, s["leader"])
        s["hb_cnt"] = jnp.where(sn_ok, c.reload, s["hb_cnt"])
        s["commit_bar"] = jnp.where(sn_adv, sn_to, s["commit_bar"])
        s["exec_bar"] = jnp.where(
            sn_adv, jnp.maximum(s["exec_bar"], sn_to), s["exec_bar"]
        )
        s["vote_bal"] = jnp.where(sn_adv, sn_bal, s["vote_bal"])
        s["vote_from"] = jnp.where(sn_adv, sn_to, s["vote_from"])
        s["vote_bar"] = jnp.where(sn_adv, sn_to, s["vote_bar"])
        s["dur_bar"] = jnp.where(sn_adv, sn_to, s["dur_bar"])
        # drop window entries below the install point (now host-state)
        stale_win = sn_adv[..., None] & (s["win_abs"] < sn_to[..., None])
        s["win_abs"] = jnp.where(stale_win, NO_SLOT, s["win_abs"])
        s["win_bal"] = jnp.where(stale_win, 0, s["win_bal"])
        c.sn_ok, c.sn_adv, c.sn_to = sn_ok, sn_adv, sn_to

    # ========== 3. ACCEPT ingest (acceptor voting run)
    def _ingest_accept(self, s, c):
        W = self.W
        inbox = c.inbox
        a_ok, a_bal, a_src = best_by_ballot(c.flags, ACCEPT, inbox["acc_bal"])
        a_ok &= a_bal >= s["bal_max"]
        a_lo = take_src(inbox["acc_lo"], a_src)
        a_hi = take_src(inbox["acc_hi"], a_src)
        s["bal_max"] = jnp.where(a_ok, a_bal, s["bal_max"])
        s["leader"] = jnp.where(a_ok, a_src, s["leader"])
        s["hb_cnt"] = jnp.where(a_ok, c.reload, s["hb_cnt"])

        same_run = a_ok & (s["vote_bal"] == a_bal)
        # a range entirely below the current run (leader backfilling after a
        # NACK rewind in chunks smaller than the hole) RESETS the run to it:
        # shrinking the claimed frontier is always safe, and the following
        # chunks re-merge up to the old frontier
        run_reset = (a_ok & (s["vote_bal"] != a_bal)) | (
            same_run & (a_hi < s["vote_from"])
        )
        # same-ballot: contiguity with the run (overlap or adjacency)
        run_merge = (
            same_run
            & (a_lo <= s["vote_bar"])
            & (a_hi >= s["vote_from"])
            & ~run_reset
        )
        gap = same_run & (a_lo > s["vote_bar"]) & ~run_reset
        new_run = run_reset
        apply_rng = run_merge | new_run

        # window writes for the applied range, values from the sender's lane
        m_acc, abs_acc = range_cover(a_lo, a_hi, W)
        m_acc &= apply_rng[..., None]
        lane_val = take_lane(inbox["bw_val"], a_src)
        s["win_abs"] = jnp.where(m_acc, abs_acc, s["win_abs"])
        s["win_bal"] = jnp.where(m_acc, a_bal[..., None], s["win_bal"])
        s["win_val"] = jnp.where(m_acc, lane_val, s["win_val"])
        self._on_accept_write(s, c, m_acc, a_src)

        s["vote_from"] = jnp.where(
            new_run,
            a_lo,
            jnp.where(
                run_merge, jnp.minimum(s["vote_from"], a_lo), s["vote_from"]
            ),
        )
        s["vote_bar"] = jnp.where(
            new_run,
            a_hi,
            jnp.where(
                run_merge, jnp.maximum(s["vote_bar"], a_hi), s["vote_bar"]
            ),
        )
        s["vote_bal"] = jnp.where(a_ok & apply_rng, a_bal, s["vote_bal"])
        # a new run that starts above our commit bar leaves a hole -> nack
        # so the leader rewinds and backfills [commit_bar, lo)
        c.nack = gap | (new_run & (a_lo > s["commit_bar"]))
        c.nack_hint = jnp.where(gap, s["vote_bar"], s["commit_bar"])
        c.a_ok, c.a_src, c.a_bal = a_ok, a_src, a_bal
        c.a_new_run, c.a_applied, c.m_acc = new_run, apply_rng, m_acc
        c.a_lo, c.a_hi = a_lo, a_hi

    # ========== 4. ACCEPT_REPLY ingest (leader match bookkeeping)
    def _ingest_accept_reply(self, s, c):
        cfg = self.config
        # receiver-oriented tally views: pairwise lanes as delivered, or
        # collective [G, R_src] records broadcast over the dst axis —
        # value-identical wherever the flags bit is set (core/quorum.py)
        ar = quorum_lib.pair_views(
            c.inbox, self.TALLY_LANES, self.collective_tally
        )
        ar_valid = (c.flags & ACCEPT_REPLY) != 0
        i_am_leader = (s["bal_prepared"] == s["bal_max"]) & (
            s["bal_prepared"] > 0
        )
        ar_mine = (
            ar_valid
            & (ar["ar_bal"] == s["bal_max"][..., None])
            & i_am_leader[..., None]
        )
        prog = ar_mine & (ar["ar_f"] > s["match_f"])
        c.ar_prog = prog
        s["match_f"] = jnp.where(
            ar_mine, jnp.maximum(s["match_f"], ar["ar_f"]), s["match_f"]
        )
        s["match_from"] = jnp.where(
            ar_mine, ar["ar_from"], s["match_from"]
        )
        s["match_bal"] = jnp.where(ar_mine, ar["ar_bal"], s["match_bal"])
        ar_nacked = ar_mine & ((c.flags & AR_NACK) != 0)
        s["next_idx"] = jnp.where(
            ar_nacked,
            jnp.minimum(s["next_idx"], ar["ar_hint"]),
            s["next_idx"],
        )
        s["retry_cnt"] = jnp.where(
            prog | ar_nacked, cfg.retry_interval, s["retry_cnt"]
        )
        c.ar_mine = ar_mine

    # ========== 5. HB_REPLY ingest (peer exec bars for snap_bar GC)
    def _ingest_hb_reply(self, s, c):
        hbr_valid = (c.flags & HB_REPLY) != 0
        c.hbr_valid = hbr_valid
        if getattr(self.config, "leader_leases", False):
            # a heartbeat reply confirms the sender's promise; the
            # leader's belief is shortened by the margin so it expires
            # strictly before the follower's own countdown
            s["ll_in"] = jnp.where(
                hbr_valid,
                self.config.leader_lease_len - self.config.lease_margin,
                s["ll_in"],
            )
        s["peer_exec"] = jnp.where(
            hbr_valid,
            jnp.maximum(s["peer_exec"], c.inbox["hbr_ebar"]),
            s["peer_exec"],
        )

    def _candidate_mask(self, s):
        """[G, R] bool: replicas mid-campaign (prepare sent, not yet won)."""
        return (s["bal_prep_sent"] == s["bal_max"]) & (
            s["bal_prepared"] != s["bal_max"]
        )

    # -- prepare-reply gate --------------------------------------------------
    def _gated_prepare_reply(self, s, c):
        """Run ``_ingest_prepare_reply`` only when some candidate actually
        received a PREPARE_REPLY this tick.

        The adoption path materializes ``[G, R, R_src, W]`` tensors — ~87%
        of steady-state tick time at bench shapes — yet is a provable no-op
        whenever ``pr_mine`` is all-false (tally ORs zero bits, adoption
        mask is all-false; same for the RSPaxos/Crossword overrides).  A
        global ``lax.cond`` lets XLA skip it at runtime; campaigns are rare
        (elections only), so the heavy branch almost never executes.

        Contract for ``_ingest_prepare_reply`` and its hook family: all
        effects must land in the state dict ``s`` — context attributes set
        on ``c`` inside the branch are DISCARDED (the branch runs on a
        throwaway namespace copy so branch-local tracers cannot leak).
        """
        c.candidate = self._candidate_mask(s)
        any_pr = jnp.any(
            ((c.flags & PREPARE_REPLY) != 0) & c.candidate[..., None]
        )

        def heavy(sd):
            cc = SimpleNamespace(**vars(c))
            sd = dict(sd)
            self._ingest_prepare_reply(sd, cc)
            return sd

        s.update(jax.lax.cond(any_pr, heavy, lambda sd: dict(sd), dict(s)))

    # -- prepare-reply shared prologue (tally + voted-lane views) ------------
    def _prep_reply_common(self, s, c):
        R, W = self.R, self.W
        inbox = c.inbox
        candidate = self._candidate_mask(s)
        pr_valid = (c.flags & PREPARE_REPLY) != 0
        pr_mine = (
            pr_valid
            & (inbox["prr_bal"] == s["bal_prep_sent"][..., None])
            & candidate[..., None]
        )
        src_bits = (jnp.uint32(1) << jnp.arange(R, dtype=jnp.uint32))[
            None, None, :
        ]
        s["prep_acks"] = s["prep_acks"] | jnp.where(
            pr_mine, src_bits, jnp.uint32(0)
        ).sum(axis=2, dtype=jnp.uint32)
        s["prep_hi"] = jnp.maximum(
            s["prep_hi"],
            jnp.max(jnp.where(pr_mine, inbox["prr_hi"], 0), axis=2),
        )
        c.candidate = candidate
        c.pr_mine = pr_mine
        # per-slot voted-lane views over [G, R, R_src, W]: abs slots from the
        # campaign trigger, the senders' voted (ballot, value) lanes, and the
        # valid-vote mask used by both adoption rules
        trig = s["prep_trigger"]
        _, abs_ad = range_cover(trig, trig + W, W)  # [G, R, W]; mask all-True
        c.pr_abs_ad = abs_ad
        c.pr_lane_bal = inbox["bw_bal"][:, None, :, :]  # [G, 1, R_src, W]
        c.pr_lane_val = inbox["bw_val"][:, None, :, :]
        in_rng = abs_ad[:, :, None, :] < jnp.minimum(
            inbox["prr_hi"], trig[..., None] + W
        )[..., None]
        c.pr_ok = (
            pr_mine[..., None]
            & (inbox["bw_abs"][:, None, :, :] == abs_ad[:, :, None, :])
            & (c.pr_lane_bal > 0)
            & in_rng
        )

    # ========== 6. PREPARE_REPLY ingest (candidate tally + adoption) [HOOK]
    def _ingest_prepare_reply(self, s, c):
        self._prep_reply_common(s, c)
        # per-slot max-ballot value adoption across all replying senders,
        # vectorized over [G, R, R_src, W] (classic Paxos adoption rule)
        abs_ad, ok = c.pr_abs_ad, c.pr_ok
        eff_bal = jnp.where(ok, c.pr_lane_bal, 0)  # [G, R, R_src, W]
        best_bal = eff_bal.max(axis=2)  # [G, R, W]
        best_src = eff_bal.argmax(axis=2)[:, :, None, :]
        best_val = jnp.take_along_axis(
            jnp.broadcast_to(c.pr_lane_val, eff_bal.shape), best_src, axis=2
        )[:, :, 0, :]
        adopt = (best_bal > 0) & (
            (s["win_abs"] != abs_ad) | (best_bal > s["win_bal"])
        )
        s["win_abs"] = jnp.where(adopt, abs_ad, s["win_abs"])
        s["win_bal"] = jnp.where(adopt, best_bal, s["win_bal"])
        s["win_val"] = jnp.where(adopt, best_val, s["win_val"])
        self._on_adopt(s, c, adopt, best_src)

    def _on_accept_write(self, s, c, m_acc, a_src):
        """Hook: extra per-slot lanes copied on an applied Accept range."""

    def _on_adopt(self, s, c, adopt, best_src):
        """Hook: extra per-slot lanes adopted from the best prepare-reply
        sender (``best_src`` is ``[G, R, 1, W]`` for take_along_axis)."""

    def _on_explode(self, s, c, explode):
        """Hook: candidate-side bookkeeping at campaign start."""

    def _campaign_gate(self, s, c):
        """Hook: extra veto on starting a campaign (own outstanding
        promises must lapse before campaigning at a higher ballot)."""
        if not getattr(self.config, "leader_leases", False):
            return jnp.ones((self.G, self.R), jnp.bool_)
        return s["ll_left"] <= 0

    def _apply_demote(self, s, c):
        """Voluntary step-down (the fail-slow mitigation; the same
        abdication MultiPaxos crash-failover already tolerates, entered
        deliberately): rows flagged in the host ``demote`` input drop
        their prepared ballot and any in-flight candidacy, then reload
        their election countdown to a LONG holdoff — the limping
        ex-leader goes quiet, a healthy peer's jittered hear-timeout
        fires first, and the existing election machinery does the rest.
        Lease safety needs nothing new: a silent ex-leader's follower
        promises (and its own granted leases) lapse by countdown before
        anyone can campaign, exactly as if it had crashed."""
        dem = c.inputs.get("demote")
        if dem is None:
            return
        d = dem.astype(jnp.bool_)
        holdoff = jnp.int32(8 * self.config.hear_timeout_hi)
        s["bal_prepared"] = jnp.where(d, 0, s["bal_prepared"])
        s["bal_prep_sent"] = jnp.where(d, 0, s["bal_prep_sent"])
        s["leader"] = jnp.where(d & (s["leader"] == c.rid), -1, s["leader"])
        s["hb_cnt"] = jnp.where(d, holdoff, s["hb_cnt"])

    # ========== 7. election timeout -> campaign
    def _election(self, s, c):
        cfg = self.config
        W = self.W
        rid = c.rid
        self._apply_demote(s, c)
        i_am_leader = (s["bal_prepared"] == s["bal_max"]) & (
            s["bal_prepared"] > 0
        )
        active_leader = i_am_leader & (s["leader"] == rid)
        s["hb_cnt"] = jnp.where(active_leader, s["hb_cnt"], s["hb_cnt"] - 1)
        # a replica whose voted tail spans more than the window past its
        # commit bar cannot safely lead (it would have to re-propose slots
        # it cannot hold) — it skips candidacy without inflating its ballot,
        # staying receptive to the current leader's backfill/snapshot heal
        viable = c.voted_extent - s["commit_bar"] <= W
        explode = (
            (~active_leader)
            & (s["hb_cnt"] <= 0)
            & viable
            & self._campaign_gate(s, c)
        )
        timer_out = (~active_leader) & (s["hb_cnt"] <= 0)
        new_bal = make_greater_ballot(s["bal_max"], rid)
        s["bal_max"] = jnp.where(explode, new_bal, s["bal_max"])
        s["bal_prep_sent"] = jnp.where(explode, new_bal, s["bal_prep_sent"])
        s["prep_trigger"] = jnp.where(
            explode, s["commit_bar"], s["prep_trigger"]
        )
        s["prep_acks"] = jnp.where(
            explode, jnp.uint32(1) << rid.astype(jnp.uint32), s["prep_acks"]
        )
        s["prep_hi"] = jnp.where(
            explode, jnp.maximum(c.voted_extent, s["commit_bar"]), s["prep_hi"]
        )
        s["leader"] = jnp.where(explode, rid, s["leader"])
        s["rng"], reload2 = prng.uniform_int(
            s["rng"], cfg.hear_timeout_lo, cfg.hear_timeout_hi
        )
        s["hb_cnt"] = jnp.where(timer_out, reload2, s["hb_cnt"])
        self._on_explode(s, c, explode)
        c.candidate = (c.candidate | explode) & (
            s["bal_prep_sent"] == s["bal_max"]
        )

    def _win_condition(self, s, c):
        """Hook: promise tally -> step-up decision (`[G, R]` bool)."""
        return c.candidate & (popcount(s["prep_acks"]) >= self.prepare_k)

    def _adopt_on_win(self, s, c, win, m_re, abs_re):
        """Hook: write the re-proposal window content for winners.

        Default: keep adopted values merged during PREPARE_REPLY ingest,
        fill holes with no-ops, stamp everything at the new ballot."""
        hole = m_re & (s["win_abs"] != abs_re)
        s["win_val"] = jnp.where(hole, NULL_VAL, s["win_val"])
        s["win_abs"] = jnp.where(m_re, abs_re, s["win_abs"])
        s["win_bal"] = jnp.where(m_re, s["bal_max"][..., None], s["win_bal"])

    # ========== 8. candidate -> leader on prepare quorum
    def _try_step_up(self, s, c):
        cfg = self.config
        W = self.W
        # A candidate whose window cannot hold the voted tail it would have
        # to re-propose (> W behind the frontier) must yield: proposing
        # unseen slots would overwrite committed values.  It stops
        # campaigning; a more current replica wins and snapshots it forward.
        behind = c.candidate & (s["prep_hi"] - s["prep_trigger"] > W)
        # A candidate must also be able to HEAL laggards from its window:
        # the install-snapshot plane jumps a >window-behind peer to the
        # leader's exec_bar and resumes the accept stream there, so a
        # leader whose exec_bar sits below next_slot - W would stream
        # slots its window no longer holds — the broadcast value lanes
        # alias (position p serves a NEWER slot) and the peer votes, then
        # commits, garbage over committed values.  This bites protocols
        # whose exec frontier can trail votes by more than a window
        # (RSPaxos full_bar gating; host-mode exec floors), found by the
        # randomized sweep at seed 29/71 (rspaxos, g0 slot 96).
        behind |= c.candidate & (
            jnp.maximum(s["prep_hi"], s["commit_bar"]) - s["exec_bar"] > W
        )
        s["bal_prep_sent"] = jnp.where(behind, 0, s["bal_prep_sent"])
        c.candidate &= ~behind
        win = self._win_condition(s, c)
        trig = s["prep_trigger"]
        nslot = jnp.maximum(s["prep_hi"], s["commit_bar"])
        m_re, abs_re = range_cover(trig, nslot, W)
        m_re &= win[..., None]
        self._adopt_on_win(s, c, win, m_re, abs_re)
        s["bal_prepared"] = jnp.where(win, s["bal_max"], s["bal_prepared"])
        s["next_slot"] = jnp.where(win, nslot, s["next_slot"])
        s["next_idx"] = jnp.where(
            win[..., None], trig[..., None], s["next_idx"]
        )
        s["match_bal"] = jnp.where(win[..., None], 0, s["match_bal"])
        s["match_f"] = jnp.where(win[..., None], 0, s["match_f"])
        s["vote_bal"] = jnp.where(win, s["bal_max"], s["vote_bal"])
        s["vote_from"] = jnp.where(win, trig, s["vote_from"])
        s["vote_bar"] = jnp.where(win, nslot, s["vote_bar"])
        s["hb_send_cnt"] = jnp.where(win, 0, s["hb_send_cnt"])
        c.win = win

    # ========== 9. leader proposals (client batch intake)
    def _leader_propose(self, s, c):
        cfg = self.config
        W = self.W
        i_am_leader = (s["bal_prepared"] == s["bal_max"]) & (
            s["bal_prepared"] > 0
        )
        active_leader = i_am_leader & (s["leader"] == c.rid)
        # ring capacity is bounded by the leader's own exec bar (own window
        # reuse safety); laggards beyond it are healed via SNAPSHOT sends,
        # not by stalling the group (availability > reference's conservative
        # all-peers-executed GC rule).
        n_new, m_new, abs_new, new_vals = client_intake(
            s, c.inputs, active_leader, cfg.max_proposals_per_tick, W
        )
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_bal"] = jnp.where(m_new, s["bal_max"][..., None], s["win_bal"])
        s["win_val"] = jnp.where(m_new, new_vals, s["win_val"])
        s["next_slot"] = s["next_slot"] + n_new
        s["vote_bar"] = jnp.where(active_leader, s["next_slot"], s["vote_bar"])
        c.active_leader = active_leader
        c.n_new, c.m_new = n_new, m_new

    def _exec_gate(self, s, c):
        """Hook: exec-bar advance (RSPaxos gates it on shard availability)."""
        s["exec_bar"] = advance_exec(
            s, c.inputs, self.config.exec_follows_commit
        )

    def _peer_frontiers(self, s):
        """Per-peer ballot-matched acked frontiers [G, R, R_peer]; own
        column = own durable frontier (the leader's tally input)."""
        peer_f = jnp.where(
            (s["match_bal"] == s["bal_max"][..., None])
            & (s["match_from"] <= s["commit_bar"][..., None]),
            s["match_f"],
            0,
        )
        eye = jnp.eye(self.R, dtype=jnp.bool_)[None]
        return jnp.where(eye, s["dur_bar"][..., None], peer_f)

    # ========== 10. quorum tally: durability + the frontier reduction
    def _phase_quorum_tally(self, s, c):
        """The tally phase (core/quorum.py): advance the durable-ack
        frontier, assemble the per-peer ballot-matched frontiers, and
        reduce them to every group's accept-quorum frontier in one
        segmented replica-axis reduction.  Scoped as ``quorum_tally``
        so graftprof attributes the tally cost in both transport modes
        (the netmodel tags the ar_* lanes' delay-line work with the
        same scope)."""
        s["dur_bar"] = advance_durability(
            s, self.config.dur_lag, frontier="vote_bar"
        )
        c.peer_f = self._peer_frontiers(s)
        c.q_tally = self._tally_frontier(s, c, c.peer_f)

    def _tally_frontier(self, s, c, peer_f):
        """Hook: segmented reduction over acked frontiers -> [G, R]
        accept-quorum frontier (Crossword swaps in its per-slot
        shard-coverage tally)."""
        return quorum_lib.quorum_frontier(peer_f, self.commit_k)

    # ========== 10b. commit/exec bar advance off the tallied frontier
    def _advance_bars(self, s, c):
        q_f = jnp.minimum(c.q_tally, self._commit_cap(s, c, c.peer_f))
        s["commit_bar"] = jnp.where(
            c.active_leader,
            jnp.clip(q_f, s["commit_bar"], s["next_slot"]),
            s["commit_bar"],
        )
        self._exec_gate(s, c)

    def _commit_cap(self, s, c, peer_f):
        """Hook: extra cap on the commit frontier (quorum-lease write
        barriers cap it at unacked leased responders' frontiers)."""
        return jnp.full((self.G, self.R), jnp.iinfo(jnp.int32).max)

    def _extra_sends(self, s, c, out, oflags):
        """Hook: subclass message sends; returns updated oflags."""
        return oflags

    # ========== 11. build outbox
    def _build_outbox(self, s, c):
        G, R, W = self.G, self.R, self.W
        cfg = self.config
        out = self.zero_outbox()
        oflags = out["flags"]
        ns_mask = not_self(G, R)
        active_leader = c.active_leader

        # ACCEPT streams: per-peer go-back-N with retry rewind
        stale = (
            active_leader[..., None]
            & ns_mask
            & (
                s["next_idx"]
                > jnp.maximum(s["match_f"], s["prep_trigger"][..., None])
            )
        )
        s["retry_cnt"] = jnp.where(
            stale, s["retry_cnt"] - 1, cfg.retry_interval
        )
        rewind = stale & (s["retry_cnt"] <= 0)
        matched_ok = s["match_bal"] == s["bal_max"][..., None]
        s["next_idx"] = jnp.where(
            rewind,
            jnp.where(matched_ok, s["match_f"], s["prep_trigger"][..., None]),
            s["next_idx"],
        )
        s["retry_cnt"] = jnp.where(rewind, cfg.retry_interval, s["retry_cnt"])

        # peers fallen below the leader's window get an install-snapshot
        # jump to the leader's exec bar (which is always in-window by the
        # proposal guard), then the accept stream resumes from there
        too_behind = (
            active_leader[..., None]
            & ns_mask
            & (s["next_idx"] < (s["next_slot"] - W)[..., None])
            # the jump target (exec_bar) must itself be in-window, or the
            # resumed accept stream would serve aliased lane values; the
            # step-up veto keeps this true for any replica that wins, and
            # this gate makes an out-of-window exec_bar stall the heal
            # instead of corrupting it
            & (s["exec_bar"] >= s["next_slot"] - W)[..., None]
        )
        oflags = oflags | jnp.where(too_behind, jnp.uint32(SNAPSHOT), 0)
        out["snp_bal"] = jnp.where(too_behind, s["bal_max"][..., None], 0)
        out["snp_to"] = jnp.where(too_behind, s["exec_bar"][..., None], 0)
        s["next_idx"] = jnp.where(
            too_behind, s["exec_bar"][..., None], s["next_idx"]
        )

        snd_lo = s["next_idx"]
        snd_hi = jnp.minimum(s["next_slot"][..., None], snd_lo + self._chunk)
        do_acc = active_leader[..., None] & ns_mask & (snd_hi > snd_lo)
        oflags = oflags | jnp.where(do_acc, jnp.uint32(ACCEPT), 0)
        out["acc_bal"] = jnp.where(do_acc, s["bal_max"][..., None], 0)
        out["acc_lo"] = jnp.where(do_acc, snd_lo, 0)
        out["acc_hi"] = jnp.where(do_acc, snd_hi, 0)
        s["next_idx"] = jnp.where(do_acc, snd_hi, s["next_idx"])

        # HEARTBEAT: leader every hb_send_interval ticks
        s["hb_send_cnt"] = jnp.where(
            active_leader, s["hb_send_cnt"] - 1, cfg.hb_send_interval
        )
        do_hb = (active_leader & (s["hb_send_cnt"] <= 0))[..., None] & ns_mask
        s["hb_send_cnt"] = jnp.where(
            active_leader & (s["hb_send_cnt"] <= 0),
            cfg.hb_send_interval,
            s["hb_send_cnt"],
        )
        oflags = oflags | jnp.where(do_hb, jnp.uint32(HEARTBEAT), 0)
        out["hb_bal"] = jnp.where(do_hb, s["bal_max"][..., None], 0)
        out["hb_cbar"] = jnp.where(do_hb, s["commit_bar"][..., None], 0)
        out["hb_ebar"] = jnp.where(do_hb, s["exec_bar"][..., None], 0)

        # HB_REPLY: to the heartbeat sender
        do_hbr = c.hb_reply_to[..., None] & dst_onehot(c.hb_src, R) & ns_mask
        oflags = oflags | jnp.where(do_hbr, jnp.uint32(HB_REPLY), 0)
        out["hbr_ebar"] = jnp.where(do_hbr, s["exec_bar"][..., None], 0)

        # ACCEPT_REPLY: follower acks its durable frontier to its leader.
        # The flags bits are per-link in BOTH tally modes (delivery
        # masking / visibility semantics never change); only the record
        # lanes differ — pairwise R² fan-out vs one per-source [G, R]
        # tally lane (core/quorum.py)
        is_follower = (
            (s["leader"] >= 0)
            & (s["leader"] != c.rid)
            & (s["vote_bal"] == s["bal_max"])
            & (s["vote_bal"] > 0)
        )
        do_ar = is_follower[..., None] & dst_onehot(s["leader"], R) & ns_mask
        oflags = oflags | jnp.where(do_ar, jnp.uint32(ACCEPT_REPLY), 0)
        do_nack = do_ar & c.nack[..., None]
        oflags = oflags | jnp.where(do_nack, jnp.uint32(AR_NACK), 0)
        if self.collective_tally:
            out["ar_bal"] = quorum_lib.source_lane(is_follower, s["vote_bal"])
            out["ar_from"] = quorum_lib.source_lane(
                is_follower, s["vote_from"]
            )
            out["ar_f"] = quorum_lib.source_lane(is_follower, s["dur_bar"])
            out["ar_hint"] = quorum_lib.source_lane(
                is_follower & c.nack, c.nack_hint
            )
        else:
            out["ar_bal"] = jnp.where(do_ar, s["vote_bal"][..., None], 0)
            out["ar_from"] = jnp.where(do_ar, s["vote_from"][..., None], 0)
            out["ar_f"] = jnp.where(do_ar, s["dur_bar"][..., None], 0)
            out["ar_hint"] = jnp.where(do_nack, c.nack_hint[..., None], 0)

        # PREPARE: candidates campaign every tick (loss-tolerant)
        do_prp = c.candidate[..., None] & ns_mask
        oflags = oflags | jnp.where(do_prp, jnp.uint32(PREPARE), 0)
        out["prp_bal"] = jnp.where(do_prp, s["bal_prep_sent"][..., None], 0)
        out["prp_trigger"] = jnp.where(
            do_prp, s["prep_trigger"][..., None], 0
        )

        # PREPARE_REPLY: to the campaigner we just promised
        do_prr = c.p_ok[..., None] & dst_onehot(c.p_src, R) & ns_mask
        oflags = oflags | jnp.where(do_prr, jnp.uint32(PREPARE_REPLY), 0)
        out["prr_bal"] = jnp.where(do_prr, c.p_bal[..., None], 0)
        out["prr_hi"] = jnp.where(do_prr, c.prr_hi_out[..., None], 0)

        # broadcast window lanes: voted log content (consumed by both
        # ACCEPT receivers and PREPARE_REPLY adopters)
        out["bw_abs"] = s["win_abs"]
        out["bw_bal"] = s["win_bal"]
        out["bw_val"] = s["win_val"]
        out["flags"] = self._extra_sends(s, c, out, oflags)
        return out

    def _telemetry(self, old, s, c) -> dict:
        """Metric-lane contributions (core/telemetry.py SPI): ballots are
        ``(round << 8) | id``, so a bal_max raise whose low byte equals
        the raiser's own id is a campaign it started; any other raise is
        a foreign adoption."""
        tel = super()._telemetry(old, s, c)
        raised = s["bal_max"] > old["bal_max"]
        own = (s["bal_max"] & 255) == c.rid
        tel["elections"] = raised & own
        tel["ballots_adopted"] = raised & ~own
        tel["heartbeats"] = c.hb_ok
        # proposals (c.n_new) and win_occupancy_hw (next_slot span) are
        # already set by the base hook
        return tel

    def _effects_extra(self, s, c) -> dict:
        """Hook: protocol-specific effects fields."""
        return {}

    def _effects(self, s, c):
        R = self.R
        # conservative min-exec over the group (the reference's snap_bar,
        # mod.rs:470-478): the host WAL/payload store may GC below it —
        # every replica has executed those slots
        eye_max = jnp.where(
            jnp.eye(R, dtype=jnp.bool_)[None],
            jnp.iinfo(jnp.int32).max,
            s["peer_exec"],
        )
        snap_bar = jnp.minimum(jnp.min(eye_max, axis=2), s["exec_bar"])
        extra = {
            "n_accepted": c.n_new,  # per [G, R]; engine masks paused rows
            "is_leader": c.active_leader,
            "snap_bar": snap_bar,
        }
        if getattr(self.config, "leader_leases", False):
            # leader local reads under a confirmed quorum of vote
            # promises (self counts as one; reference leaderlease.rs
            # lease_cnt >= majority)
            ll_cnt = jnp.sum((s["ll_in"] > 0).astype(jnp.int32), axis=2) + 1
            extra["leader_read_ok"] = c.active_leader & (
                ll_cnt >= self.quorum
            )
        extra.update(self._effects_extra(s, c))
        return StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"], extra=extra
        )
