"""Vectorized Chain Replication (partial, no fault tolerance).

Parity target: reference ``src/protocols/chain_rep/`` (SURVEY.md §2.5) —
head -> tail ``Propagate`` of write batches down a fixed chain ordered by
replica id, reads served at the tail, per-node ``prop_bar``/``exec_bar``
(``chain_rep/mod.rs:148-156``).  Like the reference, node failure handling
is out of scope ("partial, no fault tolerance").

TPU-first shape: each node runs a go-back-N range stream to its successor
(position ``rid + 1``); the tail's durable frontier is the commit point and
acks ripple back up the chain as a cumulative ``commit_bar`` carried on
ACK messages (the reference's reply propagation).
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax.numpy as jnp

from ..core.protocol import ProtocolKernel, StepEffects
from . import register_protocol
from .common import (
    NO_SLOT,
    advance_durability,
    advance_exec,
    client_intake,
    range_cover,
    take_lane,
    take_src,
)

PROP = 1   # Propagate range down-chain
ACK = 2    # cumulative ack up-chain (carries committed frontier)


@dataclasses.dataclass
class ReplicaConfigChainRep:
    """Parity: ``ReplicaConfigChainRep`` (``chain_rep/mod.rs``)."""

    max_proposals_per_tick: int = 16
    chunk_size: int = 64
    retry_interval: int = 8
    dur_lag: int = 0
    exec_follows_commit: bool = True


@register_protocol("ChainRep")
class ChainRepKernel(ProtocolKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_val"})

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigChainRep | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigChainRep()
        if self.config.max_proposals_per_tick > window // 2:
            raise ValueError("max_proposals_per_tick must be <= window/2")
        self._chunk = min(self.config.chunk_size, window)

    # durable record: a chain node's received/appended prefix (the
    # propagate stream certifies whole prefixes, like the reference's
    # prop_bar, chain_rep/mod.rs:148-156)
    DURABLE_SCALARS = ("prop_bar", "dur_bar")
    DURABLE_WINDOWS = ("win_abs", "win_val")

    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        return {
            "prop_bar": zeros(G, R),   # contiguous received/appended frontier
            "dur_bar": zeros(G, R),
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "next_idx": zeros(G, R),   # send cursor toward successor
            "match_f": zeros(G, R),    # successor's acked frontier
            "retry_cnt": jnp.full((G, R), self.config.retry_interval, i32),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_val": zeros(G, R, W),
        }

    def zero_outbox(self):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        return {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "pp_lo": pair(), "pp_hi": pair(),
            "ak_f": pair(), "ak_cbar": pair(),
            "bw_abs": jnp.zeros((G, R, W), i32),
            "bw_val": jnp.zeros((G, R, W), i32),
        }

    # graftprof phase registry (core/protocol.py): tuple order is
    # execution order — the pre-registry monolithic step, split at its
    # own section comments.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("ingest_prop", "_ingest_prop"),
        ("ingest_ack", "_ingest_ack"),
        ("intake", "_intake"),
        ("advance_bars", "_advance_bars"),
        ("build_outbox", "_phase_build_outbox"),
        ("telemetry", "_phase_telemetry"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        G, R = self.G, self.R
        i32 = jnp.int32
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        c.rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))
        c.is_head = c.rid == 0
        c.is_tail = c.rid == R - 1
        self._run_phases(s, c)
        fx = StepEffects(
            commit_bar=s["commit_bar"],
            exec_bar=s["exec_bar"],
            extra={
                "n_accepted": c.n_new,
                "is_leader": c.is_head,
                "snap_bar": s["exec_bar"],
            },
        )
        return s, c.out, fx

    # ---- PROP ingest (from predecessor): contiguous range accept
    def _ingest_prop(self, s, c):
        i32 = jnp.int32
        p_valid = (c.flags & PROP) != 0
        p_src = jnp.argmax(p_valid, axis=2).astype(i32)
        p_ok = p_valid.any(axis=2) & ~c.is_head & (p_src == c.rid - 1)
        p_lo = take_src(c.inbox["pp_lo"], p_src)
        p_hi = take_src(c.inbox["pp_hi"], p_src)
        acc = p_ok & (p_lo <= s["prop_bar"]) & (p_hi > s["prop_bar"])
        m_acc, abs_acc = range_cover(p_lo, p_hi, self.W)
        m_acc &= acc[..., None]
        lane_val = take_lane(c.inbox["bw_val"], p_src)
        s["win_abs"] = jnp.where(m_acc, abs_acc, s["win_abs"])
        s["win_val"] = jnp.where(m_acc, lane_val, s["win_val"])
        s["prop_bar"] = jnp.where(
            acc, jnp.maximum(s["prop_bar"], p_hi), s["prop_bar"]
        )

    # ---- ACK ingest (from successor): acked frontier + commit ripple
    def _ingest_ack(self, s, c):
        cfg = self.config
        i32 = jnp.int32
        a_valid = (c.flags & ACK) != 0
        a_src = jnp.argmax(a_valid, axis=2).astype(i32)
        a_ok = a_valid.any(axis=2) & ~c.is_tail & (a_src == c.rid + 1)
        a_f = take_src(c.inbox["ak_f"], a_src)
        a_cbar = take_src(c.inbox["ak_cbar"], a_src)
        prog = a_ok & (a_f > s["match_f"])
        s["match_f"] = jnp.where(
            a_ok, jnp.maximum(s["match_f"], a_f), s["match_f"]
        )
        s["retry_cnt"] = jnp.where(prog, cfg.retry_interval, s["retry_cnt"])
        c.up_commit = jnp.where(a_ok, a_cbar, 0)

    # ---- head proposals
    def _intake(self, s, c):
        cfg = self.config
        n_new, m_new, abs_new, new_vals = client_intake(
            s, c.inputs, c.is_head, cfg.max_proposals_per_tick, self.W,
            frontier="prop_bar",
        )
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_val"] = jnp.where(m_new, new_vals, s["win_val"])
        s["prop_bar"] = s["prop_bar"] + n_new
        c.n_new = n_new

    # ---- durability + commit
    def _advance_bars(self, s, c):
        cfg = self.config
        s["dur_bar"] = advance_durability(s, cfg.dur_lag, frontier="prop_bar")
        # tail: everything durable at the tail is committed (it has passed
        # every chain node); others: commit ripples up via ACKs
        s["commit_bar"] = jnp.where(
            c.is_tail,
            s["dur_bar"],
            jnp.maximum(
                s["commit_bar"], jnp.minimum(c.up_commit, s["prop_bar"])
            ),
        )
        s["exec_bar"] = advance_exec(s, c.inputs, cfg.exec_follows_commit)

    # ---- outbox
    def _build_outbox(self, s, c):
        G, R = self.G, self.R
        cfg = self.config
        i32 = jnp.int32
        out = self.zero_outbox()
        oflags = out["flags"]
        succ = jnp.broadcast_to(
            (jnp.arange(R, dtype=i32)[None, None, :] ==
             (c.rid + 1)[..., None]),
            (G, R, R),
        ) & ~c.is_tail[..., None]

        stale = ~c.is_tail & (s["next_idx"] > s["match_f"])
        s["retry_cnt"] = jnp.where(
            stale, s["retry_cnt"] - 1, cfg.retry_interval
        )
        rewind = stale & (s["retry_cnt"] <= 0)
        s["next_idx"] = jnp.where(rewind, s["match_f"], s["next_idx"])
        s["retry_cnt"] = jnp.where(rewind, cfg.retry_interval, s["retry_cnt"])

        snd_lo = s["next_idx"]
        snd_hi = jnp.minimum(s["dur_bar"], snd_lo + self._chunk)
        do_prop = (snd_hi > snd_lo) & ~c.is_tail
        oflags = oflags | jnp.where(
            do_prop[..., None] & succ, jnp.uint32(PROP), 0
        )
        out["pp_lo"] = jnp.where(succ, snd_lo[..., None], 0)
        out["pp_hi"] = jnp.where(succ, snd_hi[..., None], 0)
        s["next_idx"] = jnp.where(do_prop, snd_hi, s["next_idx"])

        # ACK to predecessor every tick: durable frontier + commit bar
        pred = jnp.broadcast_to(
            (jnp.arange(R, dtype=i32)[None, None, :] ==
             (c.rid - 1)[..., None]),
            (G, R, R),
        ) & ~c.is_head[..., None]
        oflags = oflags | jnp.where(pred, jnp.uint32(ACK), 0)
        out["ak_f"] = jnp.where(pred, s["dur_bar"][..., None], 0)
        out["ak_cbar"] = jnp.where(pred, s["commit_bar"][..., None], 0)

        out["bw_abs"] = s["win_abs"]
        out["bw_val"] = s["win_val"]
        out["flags"] = oflags
        return out
