"""Vectorized protocol kernels + the SmrProtocol factory.

Parity: reference ``src/protocols/`` — 11 protocol modules dispatched by the
``SmrProtocol`` enum (``src/protocols/mod.rs:63-280``).  Here each protocol
is a :class:`~summerset_tpu.core.protocol.ProtocolKernel` subclass stepping
``[num_groups, population]`` replicas in lockstep; the factory maps protocol
names to kernel classes.
"""

from typing import Dict, Type

from ..core.protocol import ProtocolKernel


_REGISTRY: Dict[str, Type[ProtocolKernel]] = {}


def register_protocol(name: str):
    def deco(cls):
        _REGISTRY[name.lower()] = cls
        cls.name = name
        return cls

    return deco


def protocol_names():
    return sorted(_REGISTRY)


def protocol_display_name(name: str) -> str:
    """The registered (cased) protocol name for a lowered registry key."""
    return _REGISTRY[name.lower()].name


def make_protocol(name: str, *args, **kwargs) -> ProtocolKernel:
    """Factory dispatch (parity: ``SmrProtocol`` enum construction)."""
    try:
        cls = _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unrecognized protocol name '{name}'; have {protocol_names()}"
        ) from None
    return cls(*args, **kwargs)


# import protocol modules for registration side effects
from . import bodega  # noqa: E402,F401
from . import chain_rep  # noqa: E402,F401
from . import craft  # noqa: E402,F401
from . import crossword  # noqa: E402,F401
from . import epaxos  # noqa: E402,F401
from . import multipaxos  # noqa: E402,F401
from . import quorum_leases  # noqa: E402,F401
from . import raft  # noqa: E402,F401
from . import rep_nothing  # noqa: E402,F401
from . import rspaxos  # noqa: E402,F401
from . import simple_push  # noqa: E402,F401
