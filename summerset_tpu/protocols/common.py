"""Shared helpers for vectorized consensus kernels.

These encode the recurring shapes of lockstep SMR: ballot arithmetic,
ring-window range covers, per-sender message selection, and k-th-largest
quorum tallies.  All functions are jit-safe elementwise/vector ops.
"""

from __future__ import annotations

import jax.numpy as jnp

NULL_VAL = jnp.int32(0)   # reserved value id: no-op filler
NO_SLOT = jnp.int32(-1)   # empty window position marker
INF = jnp.int32(1 << 30)  # frontier-min sentinel (safe to add small ints)


# ----------------------------------------------------------------- ballots --
def make_greater_ballot(bal_max, rid):
    """Next ballot for `rid` above `bal_max`: ``(round+1) << 8 | id``.

    Parity: reference ballot composition ``(base << 8) | id``
    (``src/protocols/multipaxos/mod.rs:553-561``) — uniqueness per
    (round, replica) makes ballot comparison a total order with owner
    recoverable via ``bal & 0xff``.
    """
    return (((bal_max >> 8) + 1) << 8) | rid


def ballot_owner(bal):
    return bal & 0xFF


def initial_ballot(rid):
    return (1 << 8) | rid


# ------------------------------------------------------------- ring window --
def range_cover(lo, hi, window: int):
    """Cover of absolute-slot range [lo, hi) on a size-`window` ring.

    ``lo``/``hi``: int32 arrays [...]; returns ``(mask, abs_slots)`` of shape
    ``[..., W]`` where position ``p`` holds absolute slot
    ``lo + ((p - lo) mod W)`` and ``mask`` selects those below ``hi``.
    Requires ``hi - lo <= W`` (guaranteed by the log-window guard).
    """
    p = jnp.arange(window, dtype=jnp.int32)
    lo_e = lo[..., None]
    abs_slots = lo_e + ((p - lo_e) % window)
    mask = abs_slots < hi[..., None]
    return mask, abs_slots


# ------------------------------------------------------------ msg selection --
def best_by_ballot(flags, bit, bal_field):
    """Among senders with `bit` set in flags, pick the max-ballot one.

    ``flags``/``bal_field``: [G, R, R_src].  Returns ``(ok, bal, src)`` each
    [G, R]: ok = any valid sender, bal = its ballot, src = its index.
    """
    valid = (flags & jnp.uint32(bit)) != 0
    eff = jnp.where(valid, bal_field, jnp.int32(-1))
    best = eff.max(axis=2)
    src = eff.argmax(axis=2).astype(jnp.int32)
    return best >= 0, best, src


def take_src(field, src):
    """Gather per-sender scalar field [G, R, R_src] at src [G, R] -> [G, R]."""
    return jnp.take_along_axis(field, src[..., None], axis=2)[..., 0]


def take_lane(lane, src):
    """Gather broadcast window lane [G, R_src, W] at src [G, R] -> [G, R, W]."""
    G = lane.shape[0]
    return lane[jnp.arange(G)[:, None], src]


# ------------------------------------------------------------ quorum tally --
def kth_largest(values, k: int):
    """k-th largest along the last axis (k>=1): the quorum-frontier tally.

    For cumulative-ack replication, ``kth_largest(frontiers, quorum)`` is the
    highest slot bound such that >= quorum replicas acked everything below it
    — the vectorized form of the reference's per-slot quorum count
    (``multipaxos/messages.rs:370-442``) under FIFO range streams.
    Delegates to the quorum-tally plane's canonical segmented reduction
    (``core/quorum.py``), which is what lowers to a replica-axis
    collective on a sharded mesh.
    """
    from ..core.quorum import quorum_frontier

    return quorum_frontier(values, k)


# --------------------------------------------------- shared lockstep blocks --
def client_intake(s, inputs, serving, cap: int, window: int,
                  frontier: str = "next_slot"):
    """Clamp this tick's client proposals to window space and batch cap.

    The vectorized form of the reference's ``handle_req_batch`` intake
    (``multipaxos/request.rs:112-190``): ``serving`` marks replicas that take
    proposals; space is bounded by the ring window above the replica's own
    exec bar.  Returns ``(n_new, m_new, abs_new, new_vals)`` — the caller
    writes its protocol-specific window fields and advances the frontier.
    """
    G, R = s["exec_bar"].shape
    i32 = jnp.int32
    space = jnp.maximum(s["exec_bar"] + window - s[frontier], 0)
    # clamp the host-supplied count at the kernel edge: ControlInputs
    # are untrusted (top) to the analysis passes, and a negative count
    # would walk every slot frontier backwards — the clamp is what
    # makes `next_slot >= 0` (and the bars above it) inductive
    n_prop = jnp.maximum(jnp.broadcast_to(
        inputs["n_proposals"][:, None].astype(i32), (G, R)
    ), 0)
    n_new = jnp.where(
        serving, jnp.minimum(jnp.minimum(n_prop, space), cap), 0
    )
    vbase = jnp.broadcast_to(
        inputs["value_base"][:, None].astype(i32), (G, R)
    )
    m_new, abs_new = range_cover(s[frontier], s[frontier] + n_new, window)
    new_vals = vbase[..., None] + (abs_new - s[frontier][..., None])
    return n_new, m_new, abs_new, new_vals


def advance_durability(s, dur_lag: int, frontier: str = "next_slot"):
    """WAL-ack progression: instant, or `dur_lag` slots/tick (the host
    logger-latency stand-in for device-only runs; reference StorageHub)."""
    if dur_lag > 0:
        return jnp.minimum(s[frontier], s["dur_bar"] + dur_lag)
    return s[frontier]


def advance_exec(s, inputs, exec_follows_commit: bool):
    """Exec bar: mirrors commit in device-only mode, else follows the host
    applier's reported floor (``exec_floor`` input)."""
    if exec_follows_commit:
        return s["commit_bar"]
    return jnp.maximum(
        s["exec_bar"],
        jnp.minimum(s["commit_bar"], inputs["exec_floor"].astype(jnp.int32)),
    )


def dst_onehot(src, R: int):
    """[G, R] sender index -> [G, R, R_dst] bool one-hot (for reply routing)."""
    return jnp.arange(R, dtype=jnp.int32)[None, None, :] == src[..., None]


def not_self(G: int, R: int):
    """[G, R_src, R_dst] mask: True off-diagonal (no self-sends)."""
    eye = jnp.eye(R, dtype=jnp.bool_)
    return jnp.broadcast_to(~eye[None, :, :], (G, R, R))
