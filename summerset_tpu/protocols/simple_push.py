"""Vectorized SimplePush: push-to-peers best-effort replication.

Parity target: reference ``src/protocols/simple_push/`` (SURVEY.md §2.5) —
the serving node pushes each command batch to ``rep_degree`` peers
(``PeerMsg::{Push,PushReply}``), waits for all pushed acks, then executes
and replies.  No leader election, no ballots — explicitly *not* fault
tolerant ("no consistency guarantee").

TPU-first shape: the per-batch Push/PushReply exchange becomes a per-peer
go-back-N range stream with cumulative acks (same machinery as the
MultiPaxos accept stream, minus ballots): the serving node keeps a
``next_idx`` cursor per pushed peer and commits up to
``min(own durable frontier, min over pushed peers' acked frontiers)``.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax.numpy as jnp

from ..core.protocol import ProtocolKernel, StepEffects
from . import register_protocol
from .common import (
    NO_SLOT,
    advance_durability,
    advance_exec,
    client_intake,
    range_cover,
    take_lane,
    take_src,
)

PUSH = 1
PUSH_REPLY = 2


@dataclasses.dataclass
class ReplicaConfigSimplePush:
    """Parity: ``ReplicaConfigSimplePush`` (``simple_push/mod.rs``) —
    notably ``rep_degree`` (how many peers each batch is pushed to)."""

    max_proposals_per_tick: int = 16
    chunk_size: int = 64
    rep_degree: int = -1              # peers pushed to; -1 = all peers
    retry_interval: int = 8
    dur_lag: int = 0
    exec_follows_commit: bool = True


@register_protocol("SimplePush")
class SimplePushKernel(ProtocolKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_val"})

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigSimplePush | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigSimplePush()
        if self.config.max_proposals_per_tick > window // 2:
            raise ValueError("max_proposals_per_tick must be <= window/2")
        self._chunk = min(self.config.chunk_size, window)
        deg = self.config.rep_degree
        self._degree = population - 1 if deg < 0 else min(deg, population - 1)

    # durable record: the serving node's appended log and a peer's
    # contiguous received frontier both certify durably-held batches
    DURABLE_SCALARS = ("next_slot", "dur_bar")
    DURABLE_WINDOWS = ("win_abs", "win_val")

    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        return {
            "next_slot": zeros(G, R),      # serving: append frontier;
            "dur_bar": zeros(G, R),        # peers: contiguous recv frontier
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "next_idx": zeros(G, R, R),
            "match_f": zeros(G, R, R),
            "retry_cnt": jnp.full((G, R, R), self.config.retry_interval, i32),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_val": zeros(G, R, W),
        }

    def zero_outbox(self):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        return {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "ps_lo": pair(), "ps_hi": pair(), "ps_cbar": pair(),
            "pr_f": pair(),
            "bw_abs": jnp.zeros((G, R, W), i32),
            "bw_val": jnp.zeros((G, R, W), i32),
        }

    # graftprof phase registry (core/protocol.py): tuple order is
    # execution order — the pre-registry monolithic step, split at its
    # own section comments.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("ingest_push", "_ingest_push"),
        ("ingest_push_reply", "_ingest_push_reply"),
        ("intake", "_intake"),
        ("advance_bars", "_advance_bars"),
        ("build_outbox", "_phase_build_outbox"),
        ("telemetry", "_phase_telemetry"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        G, R = self.G, self.R
        i32 = jnp.int32
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        c.rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))
        c.serving = c.rid == 0
        # pushed peer set: replicas 1..degree (deterministic, like the
        # reference's fixed peer selection)
        c.pushed = (c.rid >= 1) & (c.rid <= self._degree)
        self._run_phases(s, c)
        fx = StepEffects(
            commit_bar=s["commit_bar"],
            exec_bar=s["exec_bar"],
            extra={
                "n_accepted": c.n_new,
                "is_leader": c.serving,
                "snap_bar": s["exec_bar"],
            },
        )
        return s, c.out, fx

    # ---- PUSH ingest (peers): contiguous range accept
    def _ingest_push(self, s, c):
        i32 = jnp.int32
        p_valid = (c.flags & PUSH) != 0
        p_src = jnp.argmax(p_valid, axis=2).astype(i32)
        p_ok = p_valid.any(axis=2) & ~c.serving
        p_lo = take_src(c.inbox["ps_lo"], p_src)
        p_hi = take_src(c.inbox["ps_hi"], p_src)
        p_cbar = take_src(c.inbox["ps_cbar"], p_src)
        acc = p_ok & (p_lo <= s["next_slot"]) & (p_hi > s["next_slot"])
        m_acc, abs_acc = range_cover(p_lo, p_hi, self.W)
        m_acc &= acc[..., None]
        lane_val = take_lane(c.inbox["bw_val"], p_src)
        s["win_abs"] = jnp.where(m_acc, abs_acc, s["win_abs"])
        s["win_val"] = jnp.where(m_acc, lane_val, s["win_val"])
        s["next_slot"] = jnp.where(
            acc, jnp.maximum(s["next_slot"], p_hi), s["next_slot"]
        )
        c.peer_commit = p_ok & ~c.serving
        c.new_cbar = jnp.minimum(p_cbar, s["next_slot"])

    # ---- PUSH_REPLY ingest (serving node): cumulative ack frontiers
    def _ingest_push_reply(self, s, c):
        cfg = self.config
        r_valid = (c.flags & PUSH_REPLY) != 0
        prog = r_valid & (c.inbox["pr_f"] > s["match_f"])
        s["match_f"] = jnp.where(
            r_valid, jnp.maximum(s["match_f"], c.inbox["pr_f"]), s["match_f"]
        )
        s["retry_cnt"] = jnp.where(prog, cfg.retry_interval, s["retry_cnt"])

    # ---- serving node proposals
    def _intake(self, s, c):
        cfg = self.config
        n_new, m_new, abs_new, new_vals = client_intake(
            s, c.inputs, c.serving, cfg.max_proposals_per_tick, self.W
        )
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_val"] = jnp.where(m_new, new_vals, s["win_val"])
        s["next_slot"] = s["next_slot"] + n_new
        c.n_new = n_new

    # ---- durability + commit
    def _advance_bars(self, s, c):
        cfg = self.config
        s["dur_bar"] = advance_durability(s, cfg.dur_lag)
        # serving commit: all pushed peers acked (min over pushed frontiers)
        pushed_row = c.pushed[:, None, :]  # [G, 1, R_dst] seen by serving
        acked_min = jnp.min(
            jnp.where(pushed_row, s["match_f"], jnp.iinfo(jnp.int32).max),
            axis=2,
        )
        srv_commit = jnp.minimum(
            s["dur_bar"],
            jnp.where(self._degree > 0, acked_min, s["dur_bar"]),
        )
        s["commit_bar"] = jnp.where(
            c.serving,
            jnp.maximum(s["commit_bar"], srv_commit),
            jnp.where(
                c.peer_commit,
                jnp.maximum(s["commit_bar"], c.new_cbar),
                s["commit_bar"],
            ),
        )
        s["exec_bar"] = advance_exec(s, c.inputs, cfg.exec_follows_commit)

    # ---- outbox
    def _build_outbox(self, s, c):
        G, R = self.G, self.R
        cfg = self.config
        i32 = jnp.int32
        out = self.zero_outbox()
        oflags = out["flags"]
        dst_pushed = jnp.broadcast_to(c.pushed[:, None, :], (G, R, R))

        stale = c.serving[..., None] & dst_pushed & (
            s["next_idx"] > s["match_f"]
        )
        s["retry_cnt"] = jnp.where(
            stale, s["retry_cnt"] - 1, cfg.retry_interval
        )
        rewind = stale & (s["retry_cnt"] <= 0)
        s["next_idx"] = jnp.where(rewind, s["match_f"], s["next_idx"])
        s["retry_cnt"] = jnp.where(rewind, cfg.retry_interval, s["retry_cnt"])

        snd_lo = s["next_idx"]
        snd_hi = jnp.minimum(s["next_slot"][..., None], snd_lo + self._chunk)
        do_push = c.serving[..., None] & dst_pushed & (snd_hi > snd_lo)
        # heartbeat-style empty push keeps peer commit bars advancing
        do_note = c.serving[..., None] & dst_pushed & ~do_push
        oflags = oflags | jnp.where(do_push | do_note, jnp.uint32(PUSH), 0)
        out["ps_lo"] = jnp.where(do_push, snd_lo, s["next_slot"][..., None])
        out["ps_hi"] = jnp.where(do_push, snd_hi, s["next_slot"][..., None])
        out["ps_cbar"] = jnp.where(
            do_push | do_note, s["commit_bar"][..., None], 0
        )
        s["next_idx"] = jnp.where(do_push, snd_hi, s["next_idx"])

        # peers ack their durable contiguous frontier to the serving node
        do_reply = c.pushed[..., None] & (
            jnp.arange(R, dtype=i32)[None, None, :] == 0
        )
        oflags = oflags | jnp.where(do_reply, jnp.uint32(PUSH_REPLY), 0)
        out["pr_f"] = jnp.where(
            do_reply, jnp.minimum(s["next_slot"], s["dur_bar"])[..., None], 0
        )

        out["bw_abs"] = s["win_abs"]
        out["bw_val"] = s["win_val"]
        out["flags"] = oflags
        return out
