"""Vectorized Raft: batched term-based SMR stepped in lockstep.

Parity target: reference ``src/protocols/raft/`` (SURVEY.md §2.5) — ATC'14
Raft with terms, roles Follower/Candidate/Leader (``raft/mod.rs:237-253``),
``AppendEntries``/``RequestVote`` with conflict-index backtracking
(``PeerMsg``, ``raft/mod.rs:203-235``), durable ``curr_term``/``voted_for``
metadata (``raft/mod.rs:144-176``), log-matching recovery, and snapshotting
with log discard (``raft/snapshot.rs``).

TPU-first redesign (NOT a port of the tokio event loop):

- State is struct-of-arrays over ``[G groups, R replicas]`` with a ``W``-slot
  ring log window (``win_abs/win_term/win_val``).  Values are int32
  references into a host-side payload store, same as the MultiPaxos kernel.
- **AppendEntries is a per-peer go-back-N range stream**: the leader keeps a
  ``next_idx`` cursor per peer and sends ``[lo, hi)`` chunks with the term of
  entry ``lo-1`` (``prev_log_term``); the follower's prev-check certifies the
  whole prefix via the Log Matching Property, so its certified frontier
  ``match_bar`` jumps to ``hi`` without run-contiguity bookkeeping.  A prev
  mismatch NACKs with a rewind hint (conflict backtracking,
  ``raft/messages.rs`` conflict-index reply): hint = own ``log_end`` when the
  range starts past the log, else own ``commit_bar`` (committed prefix
  matches any leader by Leader Completeness — one-shot rewind instead of the
  reference's per-term walk).
- **Elections**: randomized per-replica countdowns; a candidate bumps its
  term, votes for itself, and re-broadcasts RequestVote every tick (loss
  tolerance); voters grant at most one vote per term, gated on the
  up-to-date check ``(last_term, log_end)``; quorum grants -> leader.
- **Commit rule**: k-th-largest over durably-acked match frontiers, allowed
  only once at least one *current-term* entry is replicated
  (``q > own_from`` where ``own_from`` = log length at election) — the
  vectorized form of Raft's commit-only-current-term rule (Fig. 8 safety).
- Heartbeat = empty AppendEntries carrying ``leader_commit`` (the reference
  separates a heartbeat module; Raft folds them naturally).
- Followers too far behind the leader's ring window receive an
  install-snapshot jump (``SNAPSHOT``), the analog of the reference's
  snapshot transfer; the snapshot body itself lives host-side.

Structure note: like the MultiPaxos kernel, ``step`` is decomposed into
phase methods with override hooks — CRaft subclasses the append / commit /
exec phases to add erasure-coded replication with full-copy fallback.
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax.numpy as jnp

from ..core import quorum as quorum_lib
from ..core.protocol import ProtocolKernel, StepEffects
from ..ops import prng
from ..utils.bitmap import popcount
from . import register_protocol
from .common import (
    NO_SLOT,
    NULL_VAL,
    advance_durability,
    advance_exec,
    best_by_ballot,
    client_intake,
    dst_onehot,
    kth_largest,
    not_self,
    range_cover,
    take_lane,
    take_src,
)

# message flag bits
AE = 1            # AppendEntries (empty range = heartbeat)
AE_REPLY = 2
AR_NACK = 4       # modifier on AE_REPLY: prev-check failed; rewind to hint
REQVOTE = 8
VOTE_REPLY = 16
VOTE_GRANT = 32   # modifier on VOTE_REPLY
SNAPSHOT = 64     # install-snapshot: jump a >window-behind follower forward
# bits 128+ reserved for subclass extensions (craft reconstruction reads)


@dataclasses.dataclass
class ReplicaConfigRaft:
    """Static per-run knobs (parity: ``ReplicaConfigRaft``,
    ``raft/mod.rs:46-97``, re-expressed in ticks)."""

    max_proposals_per_tick: int = 16    # client batch intake per group/tick
    chunk_size: int = 64                # max AE slots per peer per tick
    hb_send_interval: int = 1           # leader heartbeat period (ticks)
    hear_timeout_lo: int = 30           # election timeout jitter range
    hear_timeout_hi: int = 60
    retry_interval: int = 8             # go-back-N resend countdown
    dur_lag: int = 0                    # WAL ack lag (0 = instant durability)
    exec_follows_commit: bool = True    # device-only mode: exec == commit
    init_leader: int = 0                # warm-start leader id; -1 = cold elect
    # quorum-tally transport (core/quorum.py): "collective" carries the
    # AppendEntries-reply match-index records as per-source [G, R]
    # broadcast lanes instead of R² pair lanes — byte-identical state
    # (per-link flags keep the visibility semantics), one all-gather
    # instead of an all-to-all on a replica-sharded mesh
    tally: str = "pairwise"


def _gather_slot(win_abs, win_field, slot):
    """Look up ``slot`` in a ring window: ``[..., W]`` at ``[...]`` int32.

    Returns ``(ok, value)`` where ok = the window currently holds that
    absolute slot.  Negative slots never match (``win_abs`` init is -1 but
    position ``W-1`` could hold a real slot; the explicit ``slot >= 0`` guard
    covers it).
    """
    W = win_abs.shape[-1]
    pos = slot % W
    a = jnp.take_along_axis(win_abs, pos[..., None], axis=-1)[..., 0]
    v = jnp.take_along_axis(win_field, pos[..., None], axis=-1)[..., 0]
    return (a == slot) & (slot >= 0), v


@register_protocol("Raft")
class RaftKernel(ProtocolKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_term", "bw_val"})

    # quorum-tally lanes (core/quorum.py): the AE-reply record (term,
    # certified durable match frontier, nack hint, exec bar) is
    # destination-independent — Raft's match-index advance tallies the
    # same per-source lane at every receiver under tally="collective"
    TALLY_LANES: Tuple[str, ...] = ("ar_term", "ar_f", "ar_hint", "ar_ebar")

    # voluntary leader demotion (gray-failure mitigation): same contract
    # as the MultiPaxos family — a [G, R] bool mask from the host; the
    # indicted leader reverts to follower and holds off re-campaigning
    # (stepping down is always safe in Raft: it is the same transition
    # an AppendEntries at a higher term forces)
    EXTRA_INPUTS: Tuple[Tuple[str, str], ...] = (("demote", "gr"),)

    # durable acceptor record: Raft persists curr_term/voted_for metadata
    # plus the appended log tail (parity: raft/mod.rs:144-176 pack_meta +
    # DurEntry log entries) — a restarted replica must not double-vote in
    # a term it already voted in, nor forget entries it acked
    DURABLE_SCALARS = ("term", "voted_for", "log_end", "last_term")
    DURABLE_WINDOWS = ("win_abs", "win_term", "win_val")

    def restore_durable(self, st, g, me, rec, floor):
        i32 = jnp.int32
        fl = i32(floor)
        st["term"] = st["term"].at[g, me].max(i32(rec["term"]))
        st["voted_for"] = st["voted_for"].at[g, me].set(
            i32(rec["voted_for"])
        )
        st["log_end"] = st["log_end"].at[g, me].set(
            jnp.maximum(i32(rec["log_end"]), fl)
        )
        st["last_term"] = st["last_term"].at[g, me].set(
            i32(rec["last_term"])
        )
        # everything in the record is on disk again after replay; bars
        # resume from the applier's floor (commit_bar is re-learned from
        # the leader, Leader Completeness makes floor a safe base)
        st["match_bar"] = st["match_bar"].at[g, me].set(fl)
        st["dur_bar"] = st["dur_bar"].at[g, me].set(
            jnp.maximum(i32(rec["log_end"]), fl)
        )
        st["commit_bar"] = st["commit_bar"].at[g, me].max(fl)
        st["exec_bar"] = st["exec_bar"].at[g, me].max(fl)
        for k in self.DURABLE_WINDOWS:
            st[k] = st[k].at[g, me].set(jnp.asarray(rec[k], st[k].dtype))

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigRaft | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigRaft()
        quorum_lib.check_tally(getattr(self.config, "tally", "pairwise"))
        if self.collective_tally:
            self.broadcast_lanes = (
                frozenset(type(self).broadcast_lanes) | self.tally_lanes
            )
        if self.config.max_proposals_per_tick > window // 2:
            raise ValueError("max_proposals_per_tick must be <= window/2")
        self._chunk = min(self.config.chunk_size, window)

    # ------------------------------------------------------- subclass hooks
    def _extra_state(self, st: dict, seed: int) -> None:
        """Subclass state fields (added in place)."""

    def _extra_outbox(self, out: dict) -> None:
        """Subclass outbox fields (added in place)."""

    # ------------------------------------------------------------------ init
    def init_state(self, seed: int = 0):
        G, R = self.G, self.R
        W = self.W
        cfg = self.config
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))

        rng = prng.seed_state(seed, (G, R))
        rng, hb_cnt = prng.uniform_int(
            rng, cfg.hear_timeout_lo, cfg.hear_timeout_hi
        )

        st = {
            "term": zeros(G, R),
            "voted_for": jnp.full((G, R), -1, i32),
            "cand_term": jnp.full((G, R), -1, i32),
            "grants": jnp.zeros((G, R), jnp.uint32),
            "is_leader": jnp.zeros((G, R), jnp.bool_),
            "leader": jnp.full((G, R), -1, i32),
            "own_from": zeros(G, R),
            "log_end": zeros(G, R),
            "last_term": zeros(G, R),
            "match_bar": zeros(G, R),
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "dur_bar": zeros(G, R),
            "hb_cnt": hb_cnt,
            "hb_send_cnt": zeros(G, R),
            "rng": rng,
            "next_idx": zeros(G, R, R),
            "match_f": zeros(G, R, R),
            "retry_cnt": jnp.full((G, R, R), cfg.retry_interval, i32),
            "peer_exec": zeros(G, R, R),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_term": zeros(G, R, W),
            "win_val": jnp.full((G, R, W), 0, i32),
        }

        if cfg.init_leader >= 0:
            L = cfg.init_leader
            is_l = rid == L
            st["term"] = jnp.ones((G, R), i32)
            st["voted_for"] = jnp.full((G, R), L, i32)
            st["is_leader"] = is_l
            st["leader"] = jnp.full((G, R), L, i32)
        self._extra_state(st, seed)
        return st

    # ---------------------------------------------------------------- outbox
    def zero_outbox(self):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        pair = lambda: jnp.zeros((G, R, R), i32)  # noqa: E731
        # tally lanes: per-source [G, R] records in collective mode
        tlane = (
            (lambda: jnp.zeros((G, R), i32))
            if self.collective_tally else pair
        )
        out = {
            "flags": jnp.zeros((G, R, R), jnp.uint32),
            "ae_term": pair(), "ae_lo": pair(), "ae_hi": pair(),
            "ae_prev": pair(), "ae_cbar": pair(),
            "ar_term": tlane(), "ar_f": tlane(), "ar_hint": tlane(),
            "ar_ebar": tlane(),
            "rv_term": pair(), "rv_lidx": pair(), "rv_lterm": pair(),
            "vr_term": pair(),
            "snp_term": pair(), "snp_to": pair(), "snp_lterm": pair(),
            "bw_abs": jnp.zeros((G, R, W), i32),
            "bw_term": jnp.zeros((G, R, W), i32),
            "bw_val": jnp.zeros((G, R, W), i32),
        }
        self._extra_outbox(out)
        return out

    # ------------------------------------------------------------------ step
    # graftprof phase registry (core/protocol.py): tuple order is
    # execution order; CRaft inherits the table with its tally/adoption
    # method overrides keeping their attribution.  ``telemetry`` sits
    # before ``build_outbox`` — Raft's send path does not mutate state
    # the lanes read, and the pre-refactor accumulate ran here.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("ingest_reqvote", "_ingest_reqvote"),
        ("ingest_vote_reply", "_ingest_vote_reply"),
        ("ingest_ae", "_ingest_ae"),
        ("ingest_snapshot", "_ingest_snapshot"),
        ("ingest_ae_reply", "_ingest_ae_reply"),
        ("election", "_election"),
        ("try_win", "_try_win"),
        ("leader_append", "_leader_append"),
        (quorum_lib.PHASE_TALLY, "_phase_quorum_tally"),
        ("advance_bars", "_advance_bars"),
        ("telemetry", "_phase_telemetry"),
        ("build_outbox", "_phase_build_outbox"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        c.rid = jnp.broadcast_to(
            jnp.arange(self.R, dtype=jnp.int32)[None, :], (self.G, self.R)
        )
        c.src_bits = (jnp.uint32(1) << jnp.arange(self.R, dtype=jnp.uint32))[
            None, None, :
        ]
        s["rng"], c.reload = prng.uniform_int(
            s["rng"], self.config.hear_timeout_lo, self.config.hear_timeout_hi
        )
        self._run_phases(s, c)
        fx = self._effects(s, c)
        return s, c.out, fx

    # ========== 1. REQVOTE ingest (vote granting; may bump term)
    def _ingest_reqvote(self, s, c):
        inbox = c.inbox
        rv_ok, rv_term, rv_src = best_by_ballot(
            c.flags, REQVOTE, inbox["rv_term"]
        )
        higher = rv_ok & (rv_term > s["term"])
        s["voted_for"] = jnp.where(higher, -1, s["voted_for"])
        s["is_leader"] &= ~higher
        s["cand_term"] = jnp.where(higher, -1, s["cand_term"])
        s["term"] = jnp.where(higher, rv_term, s["term"])
        # any term change invalidates the per-(leader, term) certification
        # behind match_bar; commit_bar is the safe floor (Leader Completeness)
        s["match_bar"] = jnp.where(higher, s["commit_bar"], s["match_bar"])
        rv_lidx = take_src(inbox["rv_lidx"], rv_src)
        rv_lterm = take_src(inbox["rv_lterm"], rv_src)
        uptodate = (rv_lterm > s["last_term"]) | (
            (rv_lterm == s["last_term"]) & (rv_lidx >= s["log_end"])
        )
        can_vote = (
            rv_ok
            & (rv_term == s["term"])
            & ((s["voted_for"] < 0) | (s["voted_for"] == rv_src))
            & uptodate
            & ~s["is_leader"]
        )
        s["voted_for"] = jnp.where(can_vote, rv_src, s["voted_for"])
        s["hb_cnt"] = jnp.where(can_vote, c.reload, s["hb_cnt"])
        c.rv_ok, c.rv_src, c.can_vote = rv_ok, rv_src, can_vote

    # ========== 2. VOTE_REPLY ingest (candidate tally)
    def _ingest_vote_reply(self, s, c):
        vr_valid = (c.flags & VOTE_REPLY) != 0
        vr_grant = (
            vr_valid
            & ((c.flags & VOTE_GRANT) != 0)
            & (c.inbox["vr_term"] == s["term"][..., None])
        )
        s["grants"] = s["grants"] | jnp.where(
            vr_grant, c.src_bits, jnp.uint32(0)
        ).sum(axis=2, dtype=jnp.uint32)
        c.vr_valid = vr_valid

    def _on_ae_write(self, s, c, m_acc, a_src):
        """Hook: extra per-slot lanes copied on an applied AE range."""

    # ========== 3. AE ingest (prev-check, entry write, commit notice)
    def _ingest_ae(self, s, c):
        W = self.W
        inbox = c.inbox
        a_ok, a_term, a_src = best_by_ballot(c.flags, AE, inbox["ae_term"])
        a_ok &= a_term >= s["term"]
        # a leader never yields to an equal-term AE (impossible by election
        # safety); a candidate at the same term steps down to the winner
        a_ok &= (a_term > s["term"]) | ~s["is_leader"]
        newterm = a_ok & (a_term > s["term"])
        s["voted_for"] = jnp.where(newterm, -1, s["voted_for"])
        s["term"] = jnp.where(a_ok, a_term, s["term"])
        # certified-match frontier resets to the committed prefix whenever
        # the (leader, term) authority changes (Leader Completeness makes
        # commit_bar a safe floor under any future leader)
        s["match_bar"] = jnp.where(
            a_ok & (newterm | (s["leader"] != a_src)),
            s["commit_bar"],
            s["match_bar"],
        )
        s["is_leader"] &= ~a_ok
        s["cand_term"] = jnp.where(a_ok, -1, s["cand_term"])
        s["leader"] = jnp.where(a_ok, a_src, s["leader"])
        s["hb_cnt"] = jnp.where(a_ok, c.reload, s["hb_cnt"])
        c.ae_ok = a_ok  # telemetry: accepted leader appends/heartbeats

        a_lo = take_src(inbox["ae_lo"], a_src)
        a_hi = take_src(inbox["ae_hi"], a_src)
        a_prev = take_src(inbox["ae_prev"], a_src)
        a_cbar = take_src(inbox["ae_cbar"], a_src)

        prev_in_win, own_pterm = _gather_slot(
            s["win_abs"], s["win_term"], a_lo - 1
        )
        prev_ok = (
            (a_lo <= s["commit_bar"])
            | (prev_in_win & (own_pterm == a_prev) & (a_lo - 1 < s["log_end"]))
        )
        gap = a_ok & (a_lo > s["log_end"])
        acc = a_ok & ~gap & prev_ok
        rej = a_ok & ~gap & ~prev_ok
        c.nack = gap | rej
        # conflict backtrack hint: log_end for past-the-end, commit_bar for
        # term mismatch (one-shot rewind; the committed prefix always matches)
        c.nack_hint = jnp.where(gap, s["log_end"], s["commit_bar"])

        m_acc, abs_acc = range_cover(a_lo, a_hi, W)
        m_acc &= acc[..., None]
        lane_term = take_lane(inbox["bw_term"], a_src)
        lane_val = take_lane(inbox["bw_val"], a_src)
        conflict = (
            m_acc
            & (s["win_abs"] == abs_acc)
            & (s["win_term"] != lane_term)
            & (abs_acc < s["log_end"][..., None])
        )
        any_conflict = conflict.any(axis=2)
        s["win_abs"] = jnp.where(m_acc, abs_acc, s["win_abs"])
        s["win_term"] = jnp.where(m_acc, lane_term, s["win_term"])
        s["win_val"] = jnp.where(m_acc, lane_val, s["win_val"])
        self._on_ae_write(s, c, m_acc, a_src)
        # Raft truncation rule: a conflicting entry and all that follow are
        # deleted; the written range replaces them, so log_end = hi on
        # conflict, else extend-only
        s["log_end"] = jnp.where(
            acc,
            jnp.where(any_conflict, a_hi, jnp.maximum(s["log_end"], a_hi)),
            s["log_end"],
        )
        s["dur_bar"] = jnp.minimum(s["dur_bar"], s["log_end"])
        s["match_bar"] = jnp.where(
            acc, jnp.maximum(s["match_bar"], a_hi), s["match_bar"]
        )
        s["commit_bar"] = jnp.where(
            acc,
            jnp.maximum(
                s["commit_bar"], jnp.minimum(a_cbar, s["match_bar"])
            ),
            s["commit_bar"],
        )
        lt_ok, lt_term = _gather_slot(
            s["win_abs"], s["win_term"], s["log_end"] - 1
        )
        s["last_term"] = jnp.where(
            acc,
            jnp.where(
                s["log_end"] > 0,
                jnp.where(lt_ok, lt_term, s["last_term"]),
                0,
            ),
            s["last_term"],
        )
        c.a_ok, c.a_src, c.a_acc = a_ok, a_src, acc

    # ========== 3b. SNAPSHOT ingest (install: jump forward)
    def _ingest_snapshot(self, s, c):
        inbox = c.inbox
        sn_ok, sn_term, sn_src = best_by_ballot(
            c.flags, SNAPSHOT, inbox["snp_term"]
        )
        sn_ok &= sn_term >= s["term"]
        sn_ok &= (sn_term > s["term"]) | ~s["is_leader"]
        sn_to = take_src(inbox["snp_to"], sn_src)
        sn_lterm = take_src(inbox["snp_lterm"], sn_src)
        sn_new = sn_ok & (sn_term > s["term"])
        s["voted_for"] = jnp.where(sn_new, -1, s["voted_for"])
        # authority change without install (sn_to <= commit_bar) still
        # invalidates match_bar certification
        s["match_bar"] = jnp.where(
            sn_ok & (sn_new | (s["leader"] != sn_src)),
            s["commit_bar"],
            s["match_bar"],
        )
        s["term"] = jnp.where(sn_ok, sn_term, s["term"])
        s["is_leader"] &= ~sn_ok
        s["cand_term"] = jnp.where(sn_ok, -1, s["cand_term"])
        s["leader"] = jnp.where(sn_ok, sn_src, s["leader"])
        s["hb_cnt"] = jnp.where(sn_ok, c.reload, s["hb_cnt"])
        sn_adv = sn_ok & (sn_to > s["commit_bar"])
        s["commit_bar"] = jnp.where(sn_adv, sn_to, s["commit_bar"])
        s["exec_bar"] = jnp.where(
            sn_adv, jnp.maximum(s["exec_bar"], sn_to), s["exec_bar"]
        )
        s["log_end"] = jnp.where(
            sn_adv, jnp.maximum(s["log_end"], sn_to), s["log_end"]
        )
        s["match_bar"] = jnp.where(sn_adv, sn_to, s["match_bar"])
        s["dur_bar"] = jnp.where(
            sn_adv, jnp.maximum(s["dur_bar"], sn_to), s["dur_bar"]
        )
        s["last_term"] = jnp.where(
            sn_adv & (s["log_end"] == sn_to), sn_lterm, s["last_term"]
        )
        stale_win = sn_adv[..., None] & (s["win_abs"] < sn_to[..., None])
        s["win_abs"] = jnp.where(stale_win, NO_SLOT, s["win_abs"])
        s["win_term"] = jnp.where(stale_win, 0, s["win_term"])
        c.sn_ok, c.sn_adv, c.sn_to = sn_ok, sn_adv, sn_to

    # ========== 4. AE_REPLY ingest (leader match bookkeeping + step-down)
    def _ingest_ae_reply(self, s, c):
        cfg = self.config
        inbox = c.inbox
        # receiver-oriented tally views (core/quorum.py): pairwise lanes
        # as delivered, or collective [G, R_src] records broadcast over
        # the dst axis — value-identical wherever the flags bit is set
        ar = quorum_lib.pair_views(
            c.inbox, self.TALLY_LANES, self.collective_tally
        )
        ar_valid = (c.flags & AE_REPLY) != 0
        ar_mine = (
            ar_valid
            & (ar["ar_term"] == s["term"][..., None])
            & s["is_leader"][..., None]
        )
        prog = ar_mine & (ar["ar_f"] > s["match_f"])
        s["match_f"] = jnp.where(
            ar_mine, jnp.maximum(s["match_f"], ar["ar_f"]), s["match_f"]
        )
        ar_nacked = ar_mine & ((c.flags & AR_NACK) != 0)
        s["next_idx"] = jnp.where(
            ar_nacked,
            jnp.minimum(s["next_idx"], ar["ar_hint"]),
            s["next_idx"],
        )
        s["retry_cnt"] = jnp.where(
            prog | ar_nacked, cfg.retry_interval, s["retry_cnt"]
        )
        s["peer_exec"] = jnp.where(
            ar_valid,
            jnp.maximum(s["peer_exec"], ar["ar_ebar"]),
            s["peer_exec"],
        )
        c.ar_valid, c.ar_mine = ar_valid, ar_mine

        # higher terms piggybacked on replies force step-down
        reply_tmax = jnp.maximum(
            jnp.max(jnp.where(c.vr_valid, inbox["vr_term"], 0), axis=2),
            jnp.max(jnp.where(ar_valid, ar["ar_term"], 0), axis=2),
        )
        stepdown = reply_tmax > s["term"]
        s["term"] = jnp.where(stepdown, reply_tmax, s["term"])
        s["voted_for"] = jnp.where(stepdown, -1, s["voted_for"])
        s["is_leader"] &= ~stepdown
        s["cand_term"] = jnp.where(stepdown, -1, s["cand_term"])
        s["match_bar"] = jnp.where(stepdown, s["commit_bar"], s["match_bar"])

    def _apply_demote(self, s, c):
        """Voluntary step-down (fail-slow mitigation): flagged rows
        revert to follower — the transition a higher-term AppendEntries
        would force, entered deliberately — abandon any candidacy, and
        reload their election countdown to a long holdoff so a healthy
        peer's jittered timeout campaigns first."""
        dem = c.inputs.get("demote")
        if dem is None:
            return
        d = dem.astype(jnp.bool_)
        holdoff = jnp.int32(8 * self.config.hear_timeout_hi)
        s["is_leader"] &= ~d
        s["cand_term"] = jnp.where(d, -1, s["cand_term"])
        s["leader"] = jnp.where(d & (s["leader"] == c.rid), -1, s["leader"])
        s["hb_cnt"] = jnp.where(d, holdoff, s["hb_cnt"])

    # ========== 5. election timeout -> campaign
    def _election(self, s, c):
        W = self.W
        rid = c.rid
        self._apply_demote(s, c)
        s["hb_cnt"] = jnp.where(s["is_leader"], s["hb_cnt"], s["hb_cnt"] - 1)
        # viability guard (cf. multipaxos `viable`): a replica whose log tail
        # already fills its ring window could never append the current-term
        # entry the commit rule needs (space stays 0) — it skips candidacy
        # without inflating its term, staying receptive to a heal
        viable = s["log_end"] - s["exec_bar"] < W
        timer_out = ~s["is_leader"] & (s["hb_cnt"] <= 0)
        explode = timer_out & viable
        s["term"] = jnp.where(explode, s["term"] + 1, s["term"])
        s["match_bar"] = jnp.where(explode, s["commit_bar"], s["match_bar"])
        s["voted_for"] = jnp.where(explode, rid, s["voted_for"])
        s["cand_term"] = jnp.where(explode, s["term"], s["cand_term"])
        s["grants"] = jnp.where(
            explode, jnp.uint32(1) << rid.astype(jnp.uint32), s["grants"]
        )
        s["leader"] = jnp.where(explode, -1, s["leader"])
        s["rng"], reload2 = prng.uniform_int(
            s["rng"], self.config.hear_timeout_lo, self.config.hear_timeout_hi
        )
        s["hb_cnt"] = jnp.where(timer_out, reload2, s["hb_cnt"])
        c.candidate = ~s["is_leader"] & (s["cand_term"] == s["term"])

    # ========== 6. candidate -> leader on vote quorum
    def _try_win(self, s, c):
        cfg = self.config
        win = c.candidate & (popcount(s["grants"]) >= self.quorum)
        s["is_leader"] |= win
        s["leader"] = jnp.where(win, c.rid, s["leader"])
        s["own_from"] = jnp.where(win, s["log_end"], s["own_from"])
        s["match_bar"] = jnp.where(win, s["log_end"], s["match_bar"])
        s["next_idx"] = jnp.where(
            win[..., None], s["log_end"][..., None], s["next_idx"]
        )
        s["match_f"] = jnp.where(win[..., None], 0, s["match_f"])
        s["retry_cnt"] = jnp.where(
            win[..., None], cfg.retry_interval, s["retry_cnt"]
        )
        s["hb_send_cnt"] = jnp.where(win, 0, s["hb_send_cnt"])
        c.candidate &= ~win
        c.win = win

    def _append_mode(self, s, c):
        """Hook: per-slot replication mode stamp for new appends (CRaft)."""
        return None

    def _on_append(self, s, c, m_new, mode):
        """Hook: extra per-slot lanes written on leader appends."""

    # ========== 7. leader appends: term no-op, then client proposals
    def _leader_append(self, s, c):
        W = self.W
        cfg = self.config
        i32 = jnp.int32
        lead = s["is_leader"]
        space = jnp.maximum(s["exec_bar"] + W - s["log_end"], 0)
        mode = self._append_mode(s, c)
        # current-term no-op: a fresh leader with an uncommitted predecessor
        # tail appends one no-op so the commit rule (q_f > own_from) can fire
        # even with zero client load (standard Raft practice; the reference
        # instead relies on incoming client traffic)
        need_noop = (
            lead
            & (s["log_end"] == s["own_from"])
            & (s["commit_bar"] < s["log_end"])
            & (space > 0)
        )
        n_noop = need_noop.astype(i32)
        m_np, abs_np = range_cover(s["log_end"], s["log_end"] + n_noop, W)
        s["win_abs"] = jnp.where(m_np, abs_np, s["win_abs"])
        s["win_term"] = jnp.where(m_np, s["term"][..., None], s["win_term"])
        s["win_val"] = jnp.where(m_np, NULL_VAL, s["win_val"])
        self._on_append(s, c, m_np, mode)
        s["log_end"] = s["log_end"] + n_noop
        s["last_term"] = jnp.where(need_noop, s["term"], s["last_term"])
        n_new, m_new, abs_new, new_vals = client_intake(
            s, c.inputs, lead, cfg.max_proposals_per_tick, W,
            frontier="log_end",
        )
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_term"] = jnp.where(m_new, s["term"][..., None], s["win_term"])
        s["win_val"] = jnp.where(m_new, new_vals, s["win_val"])
        self._on_append(s, c, m_new, mode)
        s["log_end"] = s["log_end"] + n_new
        s["last_term"] = jnp.where(n_new > 0, s["term"], s["last_term"])
        s["match_bar"] = jnp.where(lead, s["log_end"], s["match_bar"])
        c.n_new = n_new

    def _commit_frontier(self, s, c, peer_f):
        """Hook: quorum-tally frontier from durably-acked match frontiers."""
        return kth_largest(peer_f, self.quorum)

    def _exec_gate(self, s, c):
        """Hook: exec-bar advance (CRaft gates on shard availability)."""
        s["exec_bar"] = advance_exec(
            s, c.inputs, self.config.exec_follows_commit
        )

    # ========== 8. quorum tally: durability + match-index reduction
    def _phase_quorum_tally(self, s, c):
        """The tally phase (core/quorum.py): Raft's match-index advance
        as one segmented replica-axis reduction over durably-acked
        match frontiers — scoped ``quorum_tally`` so graftprof
        attributes the tally cost in both transport modes."""
        R = self.R
        s["dur_bar"] = advance_durability(
            s, self.config.dur_lag, frontier="log_end"
        )
        eye = jnp.eye(R, dtype=jnp.bool_)[None]
        c.eye = eye
        c.peer_f = jnp.where(eye, s["dur_bar"][..., None], s["match_f"])
        c.q_tally = self._commit_frontier(s, c, c.peer_f)

    # ========== 8b. commit/exec bar advance off the tallied frontier
    def _advance_bars(self, s, c):
        # commit-only-current-term: at least one own-term entry replicated
        q_f = c.q_tally
        can_commit = s["is_leader"] & (q_f > s["own_from"])
        s["commit_bar"] = jnp.where(
            can_commit,
            jnp.clip(q_f, s["commit_bar"], s["log_end"]),
            s["commit_bar"],
        )
        self._exec_gate(s, c)

    def _extra_sends(self, s, c, out, oflags):
        """Hook: subclass message sends; returns updated oflags."""
        return oflags

    # ========== 9. build outbox
    def _build_outbox(self, s, c):
        G, R, W = self.G, self.R, self.W
        cfg = self.config
        out = self.zero_outbox()
        oflags = out["flags"]
        ns_mask = not_self(G, R)
        lead = s["is_leader"]

        # AE streams: go-back-N with retry rewind
        stale = lead[..., None] & ns_mask & (s["next_idx"] > s["match_f"])
        s["retry_cnt"] = jnp.where(
            stale, s["retry_cnt"] - 1, cfg.retry_interval
        )
        rewind = stale & (s["retry_cnt"] <= 0)
        s["next_idx"] = jnp.where(rewind, s["match_f"], s["next_idx"])
        s["retry_cnt"] = jnp.where(rewind, cfg.retry_interval, s["retry_cnt"])

        # peers fallen below the ring window get an install-snapshot jump
        too_behind = (
            lead[..., None]
            & ns_mask
            & (s["next_idx"] < (s["log_end"] - W)[..., None])
        )
        snap_lt_ok, snap_lterm = _gather_slot(
            s["win_abs"], s["win_term"], s["exec_bar"] - 1
        )
        oflags = oflags | jnp.where(too_behind, jnp.uint32(SNAPSHOT), 0)
        out["snp_term"] = jnp.where(too_behind, s["term"][..., None], 0)
        out["snp_to"] = jnp.where(too_behind, s["exec_bar"][..., None], 0)
        out["snp_lterm"] = jnp.where(
            too_behind,
            jnp.where(snap_lt_ok, snap_lterm, s["last_term"])[..., None],
            0,
        )
        s["next_idx"] = jnp.where(
            too_behind, s["exec_bar"][..., None], s["next_idx"]
        )

        # heartbeat cadence: empty AE when nothing to replicate
        s["hb_send_cnt"] = jnp.where(
            lead, s["hb_send_cnt"] - 1, cfg.hb_send_interval
        )
        hb_fire = lead & (s["hb_send_cnt"] <= 0)
        s["hb_send_cnt"] = jnp.where(
            hb_fire, cfg.hb_send_interval, s["hb_send_cnt"]
        )

        snd_lo = s["next_idx"]
        snd_hi = jnp.minimum(s["log_end"][..., None], snd_lo + self._chunk)
        have_data = snd_hi > snd_lo
        do_ae = (
            lead[..., None]
            & ns_mask
            & (have_data | hb_fire[..., None])
            & ~too_behind
        )
        snd_hi = jnp.maximum(snd_hi, snd_lo)  # empty heartbeat: hi == lo
        # prev_log_term at lo-1 from own window (always in-window because
        # too_behind peers were snapshotted past this branch)
        prev_ok_l, prev_t = _gather_slot(
            jnp.broadcast_to(s["win_abs"][:, :, None, :], (G, R, R, W)),
            jnp.broadcast_to(s["win_term"][:, :, None, :], (G, R, R, W)),
            snd_lo - 1,
        )
        oflags = oflags | jnp.where(do_ae, jnp.uint32(AE), 0)
        out["ae_term"] = jnp.where(do_ae, s["term"][..., None], 0)
        out["ae_lo"] = jnp.where(do_ae, snd_lo, 0)
        out["ae_hi"] = jnp.where(do_ae, snd_hi, 0)
        out["ae_prev"] = jnp.where(do_ae & prev_ok_l, prev_t, 0)
        out["ae_cbar"] = jnp.where(do_ae, s["commit_bar"][..., None], 0)
        s["next_idx"] = jnp.where(do_ae, snd_hi, s["next_idx"])

        # AE_REPLY: follower acks its durable certified frontier.  Flags
        # bits stay per-link in both tally modes; collective mode sends
        # ONE per-source record instead of the R² fan-out
        is_follower = (
            (s["leader"] >= 0) & (s["leader"] != c.rid) & ~s["is_leader"]
        )
        do_ar = is_follower[..., None] & dst_onehot(s["leader"], R) & ns_mask
        oflags = oflags | jnp.where(do_ar, jnp.uint32(AE_REPLY), 0)
        do_nack = do_ar & c.nack[..., None]
        oflags = oflags | jnp.where(do_nack, jnp.uint32(AR_NACK), 0)
        if self.collective_tally:
            out["ar_term"] = quorum_lib.source_lane(is_follower, s["term"])
            out["ar_f"] = quorum_lib.source_lane(
                is_follower, jnp.minimum(s["match_bar"], s["dur_bar"])
            )
            out["ar_ebar"] = quorum_lib.source_lane(
                is_follower, s["exec_bar"]
            )
            out["ar_hint"] = quorum_lib.source_lane(
                is_follower & c.nack, c.nack_hint
            )
        else:
            out["ar_term"] = jnp.where(do_ar, s["term"][..., None], 0)
            out["ar_f"] = jnp.where(
                do_ar,
                jnp.minimum(s["match_bar"], s["dur_bar"])[..., None],
                0,
            )
            out["ar_ebar"] = jnp.where(do_ar, s["exec_bar"][..., None], 0)
            out["ar_hint"] = jnp.where(do_nack, c.nack_hint[..., None], 0)

        # REQVOTE: candidates campaign every tick (loss-tolerant)
        do_rv = c.candidate[..., None] & ns_mask
        oflags = oflags | jnp.where(do_rv, jnp.uint32(REQVOTE), 0)
        out["rv_term"] = jnp.where(do_rv, s["term"][..., None], 0)
        out["rv_lidx"] = jnp.where(do_rv, s["log_end"][..., None], 0)
        out["rv_lterm"] = jnp.where(do_rv, s["last_term"][..., None], 0)

        # VOTE_REPLY: to the candidate we just heard (grant bit if granted)
        do_vr = c.rv_ok[..., None] & dst_onehot(c.rv_src, R) & ns_mask
        oflags = oflags | jnp.where(do_vr, jnp.uint32(VOTE_REPLY), 0)
        oflags = oflags | jnp.where(
            do_vr & c.can_vote[..., None], jnp.uint32(VOTE_GRANT), 0
        )
        out["vr_term"] = jnp.where(do_vr, s["term"][..., None], 0)

        # broadcast window lanes: log content for AE receivers
        out["bw_abs"] = s["win_abs"]
        out["bw_term"] = s["win_term"]
        out["bw_val"] = s["win_val"]
        out["flags"] = self._extra_sends(s, c, out, oflags)
        return out

    def _telemetry(self, old, s, c) -> dict:
        """Metric lanes (core/telemetry.py SPI): a term raise with
        ``voted_for == self`` is a campaign this replica started (the
        election path votes for itself at explode); any other raise is a
        foreign term adoption."""
        tel = super()._telemetry(old, s, c)
        raised = s["term"] > old["term"]
        own = s["voted_for"] == c.rid
        tel["elections"] = raised & own
        tel["ballots_adopted"] = raised & ~own
        tel["heartbeats"] = c.ae_ok
        tel["proposals"] = c.n_new
        tel["win_occupancy_hw"] = self._occupancy_span(s, "log_end")
        return tel

    def _effects_extra(self, s, c) -> dict:
        return {}

    def _effects(self, s, c):
        R = self.R
        # conservative min-exec over the group (snap_bar GC rule)
        eye_max = jnp.where(
            c.eye, jnp.iinfo(jnp.int32).max, s["peer_exec"]
        )
        snap_bar = jnp.minimum(jnp.min(eye_max, axis=2), s["exec_bar"])
        extra = {
            "n_accepted": c.n_new,
            "is_leader": s["is_leader"] & (s["leader"] == c.rid),
            "snap_bar": snap_bar,
        }
        extra.update(self._effects_extra(s, c))
        return StepEffects(
            commit_bar=s["commit_bar"], exec_bar=s["exec_bar"], extra=extra
        )
