"""Vectorized Crossword: MultiPaxos + tunable per-instance shard assignment.

Parity target: reference ``src/protocols/crossword/`` (SURVEY.md §2.5) —
MultiPaxos with flexible Reed-Solomon sharding where each instance carries
its own shard-to-replica assignment (``crossword/mod.rs:259-292,360-361``),
the commit condition generalizes the quorum-size vs. shards-per-replica
tradeoff (``messages.rs:15-62`` ``coverage_under_faults``: with a balanced
round-robin assignment, acks ``a`` cover at least
``(a - f - 1) * dj_spr + spr`` distinct shards), follower gossiping fills
missing shards off the critical path (``gossiping.rs:14-193``), and an
adaptive policy re-picks the assignment from live per-peer responsiveness
(``adaptive.rs:274+`` linreg perf models + qdisc introspection).

TPU-first redesign on the RSPaxos lockstep skeleton:

- **Assignment is a per-slot lane** ``win_spr``: balanced round-robin of
  width ``spr`` over ``T = rs_total_shards`` shards (replica ``r`` holds
  shards ``[r*dj, r*dj + spr) mod T`` where ``dj = T // R`` — the
  reference's default diagonal policy family, ``adaptive.rs:44-67``).  The
  leader stamps each proposal with its current choice; the lane travels in
  the ``bw_spr`` broadcast window and is adopted like values.  Arbitrary
  unbalanced ``Vec<Bitmap>`` assignments (reference static-config niche)
  reduce to their worst-case balanced bound and are not materialized.
- **Commit tally is per-slot**: slot ``s`` with width ``spr`` commits once
  ``max(majority, f + 1 + ceil((d - spr) / dj))`` cumulative ack frontiers
  pass it — the closed form of ``coverage_under_faults >= d`` for balanced
  assignments.  ``spr = d`` degrades to MultiPaxos (majority), ``spr = dj``
  to RSPaxos (majority + f): the Crossword tradeoff knob, exactly.
- **Gossip**: RSPaxos's RECON_REQ/RECON_REPLY rounds serve as the gossip
  plane; the full-data frontier advances when enough distinct cover
  frontiers pass a slot (``1 + ceil((d - spr) / dj)`` for its width), and a
  configurable tail margin keeps gossip off the freshest slots
  (``gossip_tail_ignores``, ``mod.rs:88-90``).
- **Adaptive assignment**: per-peer responsiveness counters (ticks since
  ack progress / heartbeat reply) replace the reference's RTT linreg; each
  tick the leader picks the smallest viable width
  ``spr >= d - (resp - f - 1) * dj`` — bandwidth-optimal when all peers are
  fast, sliding toward full-copy as peers stall, which is the same
  liveness-constrained envelope the reference optimizes within
  (``adaptive.rs:274+``).  Host-side linreg/qdisc models
  (``utils/linreg.py``, ``utils/qdisc.py``) can override the choice via the
  ``spr_override`` input.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ..core import quorum as quorum_lib
from . import register_protocol
from .common import (
    not_self,
    range_cover,
    take_lane,
)
from .rspaxos import ReplicaConfigRSPaxos, RSPaxosKernel


@dataclasses.dataclass
class ReplicaConfigCrossword(ReplicaConfigRSPaxos):
    """Extends the RSPaxos knobs (parity: ``ReplicaConfigCrossword``,
    ``crossword/mod.rs:46-150``)."""

    rs_total_shards: int = 0    # codeword width T; 0 = population
    rs_data_shards: int = 0     # data shards d; 0 = majority * dj
    init_spr: int = 0           # initial shards per replica; 0 = dj (diagonal)
    assignment_adaptive: bool = True   # re-pick spr from live responsiveness
    lag_threshold: int = 8      # ticks without ack/hb-reply -> unresponsive
    gossip_tail_ignores: int = 0  # freshest slots exempt from gossip rounds


@register_protocol("Crossword")
class CrosswordKernel(RSPaxosKernel):
    broadcast_lanes = frozenset({"bw_abs", "bw_bal", "bw_val", "bw_spr"})

    # per-slot assignment width is voted content: an acceptor's restart
    # must not forget how wide the value it voted for was (the commit
    # coverage tally counts it, crossword/mod.rs:324-396)
    DURABLE_WINDOWS = RSPaxosKernel.DURABLE_WINDOWS + ("win_spr",)

    # host perf-model override of the shards-per-replica choice
    # (host/adaptive.py; contract metadata, see core/protocol.py)
    EXTRA_INPUTS = RSPaxosKernel.EXTRA_INPUTS + (("spr_override", "g"),)

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigCrossword | None = None,
    ):
        config = config or ReplicaConfigCrossword()
        # RSPaxosKernel.__init__ validates fault_tolerance <= R - majority
        super().__init__(num_groups, population, window, config)
        T = config.rs_total_shards or population
        if T % population != 0:
            raise ValueError("rs_total_shards must be a multiple of population")
        self.total_shards = T
        self.dj = T // population
        d = config.rs_data_shards or self.quorum * self.dj
        if not self.dj <= d <= T:
            raise ValueError(f"invalid rs_data_shards {d} (T={T}, dj={self.dj})")
        self.data_shards = d
        spr0 = config.init_spr or self.dj
        if not self.dj <= spr0 <= d:
            raise ValueError(f"invalid init_spr {spr0} (dj={self.dj}, d={d})")
        self.init_spr = spr0

    # ------------------------------------------------------------- need math
    def _cdiv_pos(self, x):
        """max(0, ceil(x / dj)) elementwise — shard deficit in replicas."""
        return jnp.maximum(0, -((-x) // self.dj))

    def _commit_need(self, spr):
        """Acks required to commit a slot of width `spr`: quorum AND
        worst-case (f+1-survivor) coverage >= d (``messages.rs:15-62``)."""
        f = self.config.fault_tolerance
        cov = f + 1 + self._cdiv_pos(self.data_shards - spr)
        return jnp.maximum(self.quorum, cov)

    def _recover_need(self, spr):
        """Distinct cover frontiers needed to rebuild a slot of width `spr`
        (the f=0 coverage bound: adjacent-replica worst case)."""
        return 1 + self._cdiv_pos(self.data_shards - spr)

    # ------------------------------------------------------------------ state
    def _extra_state(self, st, seed):
        super()._extra_state(st, seed)
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        d = self.data_shards
        st.update(
            # per-slot assignment width lane (full-copy-safe default)
            win_spr=jnp.full((G, R, W), d, i32),
            # candidate-side min voter width of the tallied value
            prep_pspr=jnp.full((G, R, W), d, i32),
            # adaptive policy: per-peer staleness + current choice
            lag_cnt=jnp.zeros((G, R, R), i32),
            cur_spr=jnp.full((G, R), self.init_spr, i32),
        )

    def _extra_outbox(self, out):
        super()._extra_outbox(out)
        out["bw_spr"] = jnp.zeros((self.G, self.R, self.W), jnp.int32)

    # --------------------------------------------------- accept-side additions
    def _on_accept_write(self, s, c, m_acc, a_src):
        super()._on_accept_write(s, c, m_acc, a_src)
        lane_spr = take_lane(c.inbox["bw_spr"], a_src)
        s["win_spr"] = jnp.where(m_acc, lane_spr, s["win_spr"])

    # ---------------------------------------------- prepare tally extensions
    def _on_prep_tally(self, s, c, ok, value_kept, new_pval):
        # worst-case recoverability must assume the narrowest assignment any
        # era voted this value under: track the min width among contributors
        d = jnp.int32(self.data_shards)
        lane_spr = jnp.minimum(c.inbox["bw_spr"][:, None, :, :], d)
        contrib = ok & (c.pr_lane_val == new_pval[:, :, None, :])
        tick_min = jnp.min(jnp.where(contrib, lane_spr, d), axis=2)
        base = jnp.where(value_kept, s["prep_pspr"], d)
        s["prep_pspr"] = jnp.minimum(base, tick_min)

    def _on_explode(self, s, c, explode):
        super()._on_explode(s, c, explode)
        d = jnp.int32(self.data_shards)
        s["prep_pspr"] = jnp.where(
            explode[..., None],
            jnp.where(c.own_vote, jnp.minimum(s["win_spr"], d), d),
            s["prep_pspr"],
        )

    # -------------------------------------------------- step-up + adoption
    def _prep_recover_need(self, s):
        return self._recover_need(s["prep_pspr"])

    def _adopt_on_win(self, s, c, win, m_re, abs_re):
        super()._adopt_on_win(s, c, win, m_re, abs_re)
        # re-proposals are re-encoded under the winner's current assignment
        s["win_spr"] = jnp.where(m_re, s["cur_spr"][..., None], s["win_spr"])

    # ------------------------------------------------ adaptive policy + intake
    def _leader_propose(self, s, c):
        cfg = self.config
        d, dj, f = self.data_shards, self.dj, cfg.fault_tolerance
        prog = c.ar_prog | c.hbr_valid
        s["lag_cnt"] = jnp.where(prog, 0, s["lag_cnt"] + 1)
        if cfg.assignment_adaptive:
            ns_mask = not_self(self.G, self.R)
            resp = 1 + jnp.sum(
                ns_mask & (s["lag_cnt"] < cfg.lag_threshold),
                axis=2,
                dtype=jnp.int32,
            )
            choice = jnp.clip(d - (resp - 1 - f) * dj, self.init_spr, d)
        else:
            choice = jnp.full((self.G, self.R), self.init_spr, jnp.int32)
        # host perf models (linreg over ack latencies + qdisc state) may
        # override per group: the adaptive.rs analog computed off-device
        if "spr_override" in c.inputs:
            ov = c.inputs["spr_override"].astype(jnp.int32)  # [G]
            choice = jnp.where(
                ov[:, None] > 0, jnp.clip(ov[:, None], self.dj, d), choice
            )
        s["cur_spr"] = choice
        super()._leader_propose(s, c)
        s["win_spr"] = jnp.where(
            c.m_new, s["cur_spr"][..., None], s["win_spr"]
        )
        # NOTE an instance's assignment is fixed at propose time (reference:
        # Accept carries the per-instance assignment, mod.rs:360-361).
        # Re-stamping the pending tail wider would lower its ack requirement
        # against followers who only hold the narrow shards — a committed
        # slot could then be unrecoverable after one leader crash.  So, as
        # in the reference, pending narrow slots under excess failures stall
        # the (execution-ordered) commit frontier until peers heal; the
        # widened choice applies to slots proposed from now on.

    # ----------------------------------------------- per-slot commit tally
    def _tally_frontier(self, s, c, peer_f):
        """Crossword's shard-coverage quorum as ONE segmented reduction
        (core/quorum.py): per-slot coverage counting over the
        ``[G, R, R_peer, W]`` ack-vs-slot bitmap, with the per-slot
        required count derived from each instance's voted assignment
        width.  Runs inside the ``quorum_tally`` phase the base class
        declares, so graftprof attributes it alongside the transport."""
        W = self.W
        _, abs_w = range_cover(s["commit_bar"], s["commit_bar"] + W, W)
        fail_abs = quorum_lib.coverage_frontier(
            peer_f, abs_w,
            need=self._commit_need(s["win_spr"]),
            slot_known=s["win_abs"] == abs_w,
            in_rng=abs_w < s["next_slot"][..., None],
        )
        return jnp.minimum(fail_abs, s["next_slot"])

    # ------------------------------------------- per-slot gossip cover tally
    def _advance_full_bar(self, s, cover):
        W = self.W
        _, abs_w = range_cover(s["full_bar"], s["full_bar"] + W, W)
        fail_abs = quorum_lib.coverage_frontier(
            cover, abs_w,
            need=self._recover_need(s["win_spr"]),
            slot_known=s["win_abs"] == abs_w,
            in_rng=abs_w < s["commit_bar"][..., None],
        )
        s["full_bar"] = jnp.clip(
            jnp.minimum(fail_abs, s["commit_bar"]),
            s["full_bar"],
            s["commit_bar"],
        )

    def _recon_goal(self, s):
        tail = self.config.gossip_tail_ignores
        if tail <= 0:
            return s["commit_bar"]
        return jnp.maximum(s["full_bar"], s["commit_bar"] - tail)

    def _extra_sends(self, s, c, out, oflags):
        out["bw_spr"] = s["win_spr"]
        return super()._extra_sends(s, c, out, oflags)

    def _effects_extra(self, s, c):
        fx = super()._effects_extra(s, c)
        fx["cur_spr"] = s["cur_spr"]
        return fx
