"""Vectorized RepNothing: no replication, the baseline protocol.

Parity target: reference ``src/protocols/rep_nothing/`` (SURVEY.md §2.5) —
log the request batch locally (WAL append), execute, reply.  No peer
messages at all; population may be > 1 but replicas never talk (each serves
its own clients independently in the reference; here the group's proposal
stream lands on replica 0, the "serving node").
"""

from __future__ import annotations

import dataclasses
from types import SimpleNamespace
from typing import Any, Tuple

import jax.numpy as jnp

from ..core.protocol import ProtocolKernel, StepEffects
from . import register_protocol
from .common import NO_SLOT, advance_durability, advance_exec, client_intake


@dataclasses.dataclass
class ReplicaConfigRepNothing:
    """Parity: ``ReplicaConfigRepNothing`` (``rep_nothing/mod.rs``) —
    batching + WAL sync knobs, re-expressed in ticks."""

    max_proposals_per_tick: int = 16
    dur_lag: int = 0                  # WAL ack lag in slots/tick (0=instant)
    exec_follows_commit: bool = True


@register_protocol("RepNothing")
class RepNothingKernel(ProtocolKernel):
    # durable record: the local log IS the only copy (rep_nothing logs
    # each batch durably before replying — its whole point as a baseline)
    DURABLE_SCALARS = ("next_slot",)
    DURABLE_WINDOWS = ("win_abs", "win_val")

    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigRepNothing | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigRepNothing()
        if self.config.max_proposals_per_tick > window:
            raise ValueError("max_proposals_per_tick must be <= window")

    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        return {
            "next_slot": zeros(G, R),
            "dur_bar": zeros(G, R),
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_val": zeros(G, R, W),
        }

    def zero_outbox(self):
        G, R = self.G, self.R
        return {"flags": jnp.zeros((G, R, R), jnp.uint32)}

    # graftprof phase registry (core/protocol.py): tuple order is
    # execution order.
    PHASES: Tuple[Tuple[str, str], ...] = (
        ("intake", "_intake"),
        ("advance_bars", "_advance_bars"),
        ("build_outbox", "_phase_build_outbox"),
        ("telemetry", "_phase_telemetry"),
    )

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        G, R = self.G, self.R
        i32 = jnp.int32
        s = dict(state)
        c = SimpleNamespace(
            inbox=inbox, inputs=inputs, flags=inbox["flags"], old=state
        )
        c.rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))
        c.serving = c.rid == 0
        self._run_phases(s, c)
        fx = StepEffects(
            commit_bar=s["commit_bar"],
            exec_bar=s["exec_bar"],
            extra={
                "n_accepted": c.n_new,
                "is_leader": c.serving,
                "snap_bar": s["exec_bar"],
            },
        )
        return s, c.out, fx

    def _intake(self, s, c):
        cfg = self.config
        n_new, m_new, abs_new, new_vals = client_intake(
            s, c.inputs, c.serving, cfg.max_proposals_per_tick, self.W
        )
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_val"] = jnp.where(m_new, new_vals, s["win_val"])
        s["next_slot"] = s["next_slot"] + n_new
        c.n_new = n_new

    def _advance_bars(self, s, c):
        cfg = self.config
        s["dur_bar"] = advance_durability(s, cfg.dur_lag)
        s["commit_bar"] = s["dur_bar"]
        s["exec_bar"] = advance_exec(s, c.inputs, cfg.exec_follows_commit)

    def _build_outbox(self, s, c):
        return self.zero_outbox()
