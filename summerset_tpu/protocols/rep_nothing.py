"""Vectorized RepNothing: no replication, the baseline protocol.

Parity target: reference ``src/protocols/rep_nothing/`` (SURVEY.md §2.5) —
log the request batch locally (WAL append), execute, reply.  No peer
messages at all; population may be > 1 but replicas never talk (each serves
its own clients independently in the reference; here the group's proposal
stream lands on replica 0, the "serving node").
"""

from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax.numpy as jnp

from ..core.protocol import ProtocolKernel, StepEffects
from . import register_protocol
from .common import NO_SLOT, range_cover


@dataclasses.dataclass
class ReplicaConfigRepNothing:
    """Parity: ``ReplicaConfigRepNothing`` (``rep_nothing/mod.rs``) —
    batching + WAL sync knobs, re-expressed in ticks."""

    max_proposals_per_tick: int = 16
    dur_lag: int = 0                  # WAL ack lag in slots/tick (0=instant)
    exec_follows_commit: bool = True


@register_protocol("RepNothing")
class RepNothingKernel(ProtocolKernel):
    def __init__(
        self,
        num_groups: int,
        population: int,
        window: int = 64,
        config: ReplicaConfigRepNothing | None = None,
    ):
        super().__init__(num_groups, population, window)
        self.config = config or ReplicaConfigRepNothing()
        if self.config.max_proposals_per_tick > window:
            raise ValueError("max_proposals_per_tick must be <= window")

    def init_state(self, seed: int = 0):
        G, R, W = self.G, self.R, self.W
        i32 = jnp.int32
        zeros = lambda *shape: jnp.zeros(shape, i32)  # noqa: E731
        return {
            "next_slot": zeros(G, R),
            "dur_bar": zeros(G, R),
            "commit_bar": zeros(G, R),
            "exec_bar": zeros(G, R),
            "win_abs": jnp.full((G, R, W), NO_SLOT, i32),
            "win_val": zeros(G, R, W),
        }

    def zero_outbox(self):
        G, R = self.G, self.R
        return {"flags": jnp.zeros((G, R, R), jnp.uint32)}

    def step(self, state, inbox, inputs) -> Tuple[Any, Any, StepEffects]:
        G, R, W = self.G, self.R, self.W
        cfg = self.config
        i32 = jnp.int32
        s = dict(state)
        rid = jnp.broadcast_to(jnp.arange(R, dtype=i32)[None, :], (G, R))

        serving = rid == 0
        space = jnp.maximum(s["exec_bar"] + W - s["next_slot"], 0)
        n_prop = jnp.broadcast_to(
            inputs["n_proposals"][:, None].astype(i32), (G, R)
        )
        n_new = jnp.where(
            serving,
            jnp.minimum(jnp.minimum(n_prop, space), cfg.max_proposals_per_tick),
            0,
        )
        vbase = jnp.broadcast_to(
            inputs["value_base"][:, None].astype(i32), (G, R)
        )
        m_new, abs_new = range_cover(s["next_slot"], s["next_slot"] + n_new, W)
        s["win_abs"] = jnp.where(m_new, abs_new, s["win_abs"])
        s["win_val"] = jnp.where(
            m_new, vbase[..., None] + (abs_new - s["next_slot"][..., None]),
            s["win_val"],
        )
        s["next_slot"] = s["next_slot"] + n_new

        if cfg.dur_lag > 0:
            s["dur_bar"] = jnp.minimum(s["next_slot"], s["dur_bar"] + cfg.dur_lag)
        else:
            s["dur_bar"] = s["next_slot"]
        s["commit_bar"] = s["dur_bar"]

        if cfg.exec_follows_commit:
            s["exec_bar"] = s["commit_bar"]
        else:
            s["exec_bar"] = jnp.maximum(
                s["exec_bar"],
                jnp.minimum(s["commit_bar"], inputs["exec_floor"].astype(i32)),
            )

        fx = StepEffects(
            commit_bar=s["commit_bar"],
            exec_bar=s["exec_bar"],
            extra={
                "n_accepted": n_new,
                "is_leader": serving,
                "snap_bar": s["exec_bar"],
            },
        )
        return s, self.zero_outbox(), fx
