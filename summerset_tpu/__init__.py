"""summerset_tpu — a TPU-native multi-group state machine replication framework.

A brand-new framework with the capabilities of josehu07/summerset (a
protocol-generic replicated KV store supporting many SMR/consensus protocols),
re-designed TPU-first: per-slot consensus state machines are lifted into
struct-of-arrays JAX state batched over ``[num_groups, population, log_window]``
and stepped in lockstep by a jitted kernel under ``vmap`` / ``shard_map`` on the
ICI mesh.  Reed-Solomon GF(2^8) coding runs as a Pallas kernel.  The durable
logger, KV state machine, client I/O and manager oracle run host-side behind
channel-style interfaces (asyncio + a C++ WAL core).

Layer map (mirrors reference src/ layout; see SURVEY.md §1):

- ``utils``      — leaf helpers (bitmap, config, timers, keyrange, linreg, ...)
- ``ops``        — device kernels (GF(2^8) RS coding, per-group PRNG)
- ``core``       — the batched lockstep engine: network model, protocol SPI,
                   mesh sharding
- ``protocols``  — vectorized protocol kernels (MultiPaxos, Raft, EPaxos,
                   RSPaxos, CRaft, Crossword, QuorumLeases, Bodega, ChainRep,
                   SimplePush, RepNothing)
- ``server``     — host runtime (state machine, WAL storage, external API,
                   control, heartbeater, lease manager, replica process)
- ``manager``    — cluster manager oracle (reigner / reactor)
- ``client``     — client library (endpoint, stubs, drivers, bench / tester /
                   repl / mess utilities)
"""

__version__ = "0.1.0"
