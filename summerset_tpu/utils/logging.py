"""Node-identity-prefixed logging.

Parity: reference ``src/utils/print.rs`` — a process-global ``ME`` identity
string set once (e.g. ``"0"`` for replica 0, ``"m"`` for the manager), plus
``pf_trace!/pf_debug!/pf_info!/pf_warn!/pf_error!`` macros that prefix every
line with ``(id)``.  Cluster orchestration scripts *parse these lines* (e.g.
the "accepting clients" readiness probe), so the exact prefix format is part
of the de-facto API.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_ME: Optional[str] = None

TRACE = 5
logging.addLevelName(TRACE, "TRACE")


def set_me(identity: str) -> None:
    """Set the process-global node identity (once; later calls ignored).

    Parity: ``ME: OnceLock<String>`` (``src/utils/print.rs:8``).
    """
    global _ME
    if _ME is None:
        _ME = identity


def me() -> str:
    return _ME if _ME is not None else "?"


class _IdentityFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        record.me = me()
        return super().format(record)


def logger_init(level: Optional[str] = None) -> None:
    """Initialise root logging similar to ``logger_init`` (``print.rs:96``).

    Level comes from the ``SMR_LOG`` env var (parity with ``RUST_LOG``) unless
    given explicitly.  Format: ``[LEVEL (me) module] message``.
    """
    lvl_name = (level or os.environ.get("SMR_LOG", "info")).upper()
    lvl = TRACE if lvl_name == "TRACE" else getattr(logging, lvl_name, logging.INFO)
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        _IdentityFormatter("[%(levelname)s (%(me)s) %(name)s] %(message)s")
    )
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(lvl)


def pf_logger(name: str) -> logging.Logger:
    """Get a module logger; use with the ``pf_*`` convention."""
    return logging.getLogger(name)


def pf_trace(logger: logging.Logger, msg: str, *args) -> None:
    logger.log(TRACE, msg, *args)


def pf_debug(logger: logging.Logger, msg: str, *args) -> None:
    logger.debug(msg, *args)


def pf_info(logger: logging.Logger, msg: str, *args) -> None:
    logger.info(msg, *args)


def pf_warn(logger: logging.Logger, msg: str, *args) -> None:
    logger.warning(msg, *args)


def pf_error(logger: logging.Logger, msg: str, *args) -> None:
    logger.error(msg, *args)
