"""TOML-string config overrides over dataclass defaults.

Parity: reference ``parsed_config!`` macro (``src/utils/config.rs:12-47``):
each protocol has a ``ReplicaConfigXxx`` / ``ClientConfigXxx`` struct with
``Default``; the CLI passes ``--config "a=1+b='x'"`` where ``+`` means
newline; the macro TOML-parses the string, overrides only the listed fields,
and *rejects unknown fields*.

Here every config is a ``@dataclass`` with defaults and ``parsed_config``
applies the same semantics via ``tomllib``.
"""

from __future__ import annotations

import dataclasses
try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib
from typing import Type, TypeVar

from .errors import SummersetError

T = TypeVar("T")

config_field = dataclasses.field  # re-export for config dataclass authors


def _plus_to_newlines(s: str) -> str:
    """Replace ``+`` with newlines, except inside quoted TOML strings."""
    out = []
    quote = None
    for ch in s:
        if quote is None and ch in ("'", '"'):
            quote = ch
        elif ch == quote:
            quote = None
        if ch == "+" and quote is None:
            out.append("\n")
        else:
            out.append(ch)
    return "".join(out)


def parsed_config(cls: Type[T], config_str: str | None) -> T:
    """Build ``cls()`` from defaults, overridden by a TOML config string.

    ``config_str`` uses ``+`` as a line separator (parity with the server CLI
    ``--config`` flag, reference ``summerset_server/src/main.rs:112``).
    Unknown fields raise ``SummersetError`` (parity with the macro's
    unknown-field rejection).
    """
    if not dataclasses.is_dataclass(cls):
        raise SummersetError(f"config class {cls!r} is not a dataclass")
    inst = cls()
    if not config_str:
        return inst
    toml_src = _plus_to_newlines(config_str)
    try:
        overrides = tomllib.loads(toml_src)
    except tomllib.TOMLDecodeError as e:
        raise SummersetError(f"invalid config string {config_str!r}: {e}") from e
    valid = {f.name: f for f in dataclasses.fields(cls)}
    for key, val in overrides.items():
        if key not in valid:
            raise SummersetError(
                f"unknown config field '{key}' for {cls.__name__}"
            )
        cur = getattr(inst, key)
        # Accept int where float expected (TOML "1" parses as int).
        if isinstance(cur, float) and isinstance(val, int) and not isinstance(val, bool):
            val = float(val)
        # bool is a subclass of int in Python; treat them as distinct here.
        if cur is not None and (
            not isinstance(val, type(cur)) or isinstance(cur, bool) != isinstance(val, bool)
        ):
            raise SummersetError(
                f"config field '{key}' expects {type(cur).__name__}, "
                f"got {type(val).__name__}"
            )
        setattr(inst, key, val)
    return inst


def config_to_str(cfg) -> str:
    """Render a config dataclass back to the ``+``-separated string form."""
    parts = []
    for f in dataclasses.fields(cfg):
        v = getattr(cfg, f.name)
        if isinstance(v, bool):
            parts.append(f"{f.name}={'true' if v else 'false'}")
        elif isinstance(v, str):
            parts.append(f"{f.name}='{v}'")
        elif v is not None and not isinstance(v, (list, dict)):
            parts.append(f"{f.name}={v}")
    return "+".join(parts)
