"""Leaf utility layer — no dependencies on other summerset_tpu modules.

Mirrors the reference's ``src/utils/`` public surface (SURVEY.md §2.1):
Bitmap, SummersetError, Timer, RespondersConf/KeyRangeMap,
LinearRegressor/PerfModel, QdiscInfo, safe TCP framing, config parsing and
the ``pf_*`` logging helpers.
"""

from .errors import SummersetError, logged_err
from .bitmap import Bitmap
from .config import config_field, parsed_config
from .keyrange import KeyRangeMap, RespondersConf
from .linreg import LinearRegressor, PerfModel
from .timer import Timer
from .qdisc import QdiscInfo

__all__ = [
    "SummersetError",
    "logged_err",
    "Bitmap",
    "config_field",
    "parsed_config",
    "KeyRangeMap",
    "RespondersConf",
    "LinearRegressor",
    "PerfModel",
    "Timer",
    "QdiscInfo",
]
