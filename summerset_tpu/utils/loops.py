"""Event-loop teardown helper shared by the asyncio-owning hubs."""

from __future__ import annotations

import asyncio


def drain_and_close(loop: asyncio.AbstractEventLoop) -> None:
    """Cancel every pending task, let cancellations run, close the loop.

    Prevents the 'Task was destroyed but it is pending!' / 'Event loop is
    closed' teardown spray from orphaned tickers, servants, and in-flight
    sends (used by ExternalApi and the test harness's manager thread)."""
    try:
        pending = asyncio.all_tasks(loop)
        for task in pending:
            task.cancel()
        if pending:
            loop.run_until_complete(
                asyncio.gather(*pending, return_exceptions=True)
            )
    except Exception:
        pass
    loop.close()
