"""Linearizability checking of client-observed KV histories.

This is the executable stand-in for the reference's TLA+ pillar: the TLA+
specs check linearizability from the client's observed event sequence
(``tla+/multipaxos_smr_style/MultiPaxos.tla:1-19`` models the network as
a message set and asserts the observed history embeds into a legal
sequential one).  Here the same property is checked on *real* histories
recorded by clients against a live cluster under fault schedules — the
assurance path for lease local reads (QuorumLeases/Bodega), whose whole
point is returning linearizable values without touching the quorum.

Model: each key is an independent register (linearizability is
compositional, Herlihy & Wing §3), puts carry globally unique values, and
un-acknowledged operations (timeouts) may or may not have taken effect —
the checker may place them at any point after invocation or drop them.
SHED operations (``shed=True``) are *negatively acknowledged*: the
server's load-shed reply guarantees the request was refused before ever
entering the ingress queue, so unlike an unacked op the checker must
NEVER place a shed put — it is dropped outright, which makes any get
observing its (globally unique) value a linearizability violation ("an
ack lost to a shed" / a shed that secretly executed).

Algorithm: Wing & Gong tree search with memoization on
(remaining-operation set, register value), per key.  Histories from the
test harness are mostly per-client sequential, which keeps the search
effectively linear.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Tuple

INF = float("inf")


@dataclasses.dataclass(frozen=True)
class Op:
    """One client-observed operation."""

    client: int
    kind: str                  # "put" | "get"
    key: str
    value: Optional[str]       # put: written value; get: returned value
    t_inv: float
    t_resp: float = INF        # INF = never acknowledged (may have run)
    acked: bool = True         # False: op may be dropped by the checker
    shed: bool = False         # True: negatively acked (load shed) —
    #                            guaranteed never executed; the checker
    #                            drops it and may NOT place it


def record_put(client: int, key: str, value: str, t_inv: float,
               t_resp: Optional[float], acked: bool) -> Op:
    return Op(client, "put", key, value, t_inv,
              INF if t_resp is None else t_resp, acked)


def record_shed_put(client: int, key: str, value: str, t_inv: float,
                    t_resp: float) -> Op:
    """A put refused by ingress backpressure (``ApiReply(kind="shed")``):
    recorded so overload histories carry the negative acks, excluded by
    the checker on the server's never-proposed guarantee."""
    return Op(client, "put", key, value, t_inv, t_resp,
              acked=False, shed=True)


def record_get(client: int, key: str, value: Optional[str], t_inv: float,
               t_resp: float) -> Op:
    return Op(client, "get", key, value, t_inv, t_resp, True)


def check_history(ops: List[Op]) -> Tuple[bool, Optional[str]]:
    """True iff the whole history is linearizable; on failure returns the
    offending key's diagnosis.  Keys are checked independently
    (P-compositionality)."""
    by_key: Dict[str, List[Op]] = {}
    for op in ops:
        by_key.setdefault(op.key, []).append(op)
    # the per-key search recurses one frame per placed op, so a long
    # soak's hottest zipfian key (thousands of ops) outruns CPython's
    # default 1000-frame limit long before time or memory matter — a
    # clean history resolves greedily in O(n) placements
    deepest = max((len(k) for k in by_key.values()), default=0)
    want = 2000 + 4 * deepest
    if sys.getrecursionlimit() < want:
        sys.setrecursionlimit(want)
    for key, kops in by_key.items():
        ok = _check_key(kops)
        if not ok:
            return False, _diagnose(key, kops)
    return True, None


def _prune_unobserved_unacked(kops: List[Op]) -> List[Op]:
    """Drop unacked puts whose value no get ever returned.

    Sound under the stated model (put values globally unique): the
    checker may always DROP an unacked put, and *placing* a never-read
    put can only restrict later gets — any get sequenced between it and
    the next put would have to return its (unique, never-observed) value,
    a contradiction — so removal never changes the verdict.  This is the
    load-bearing bound for fault-schedule histories: a nemesis soak can
    leave dozens of timed-out (unacked) puts per key, and each one
    otherwise doubles the Wing&Gong search space (observed: a ~70-op
    soak history spinning for minutes at >10GB of memo set)."""
    read = {o.value for o in kops if o.kind == "get"}
    return [
        o for o in kops
        if o.kind != "put" or o.acked or o.value in read
    ]


def _check_key(kops: List[Op]) -> bool:
    # shed ops are dropped BEFORE unacked pruning, and unconditionally:
    # an unacked put whose value was read stays placeable, but a SHED
    # put must never be placed even when observed — the shed reply
    # guarantees it did not execute, so an observation of its unique
    # value must FAIL the search (no remaining put can write it), not
    # be legalized by placement
    kops = [o for o in kops if not o.shed]
    kops = _prune_unobserved_unacked(kops)
    n = len(kops)
    if n == 0:
        return True
    kops = sorted(kops, key=lambda o: o.t_inv)
    inv = [o.t_inv for o in kops]
    resp = [o.t_resp for o in kops]
    full = frozenset(range(n))
    seen: set = set()

    def search(remaining: frozenset, state: Optional[str]) -> bool:
        if not any(kops[i].acked for i in remaining):
            return True  # everything left is droppable
        sig = (remaining, state)
        if sig in seen:
            return False
        seen.add(sig)
        # an op can go first iff nothing else still pending responded
        # strictly before its invocation (real-time order preservation)
        bar = min(resp[i] for i in remaining)
        for i in sorted(remaining, key=lambda j: inv[j]):
            if inv[i] > bar:
                break
            o = kops[i]
            if o.kind == "put":
                if search(remaining - {i}, o.value):
                    return True
                if not o.acked:
                    # an unacked put may also have never happened
                    if search(remaining - {i}, state):
                        return True
            else:
                if o.value == state and search(remaining - {i}, state):
                    return True
        return False

    return search(full, None)


def _diagnose(key: str, kops: List[Op]) -> str:
    lines = [f"key {key!r}: history not linearizable; ops:"]
    for o in sorted(kops, key=lambda x: x.t_inv):
        end = "∞" if o.t_resp == INF else f"{o.t_resp:.4f}"
        lines.append(
            f"  c{o.client} {o.kind}({o.value}) [{o.t_inv:.4f}, {end}]"
            + (" (shed)" if o.shed else "" if o.acked else " (unacked)")
        )
    return "\n".join(lines)
