"""Linearizability checking of client-observed KV histories.

This is the executable stand-in for the reference's TLA+ pillar: the TLA+
specs check linearizability from the client's observed event sequence
(``tla+/multipaxos_smr_style/MultiPaxos.tla:1-19`` models the network as
a message set and asserts the observed history embeds into a legal
sequential one).  Here the same property is checked on *real* histories
recorded by clients against a live cluster under fault schedules — the
assurance path for lease local reads (QuorumLeases/Bodega), whose whole
point is returning linearizable values without touching the quorum.

Model: each key is an independent register (linearizability is
compositional, Herlihy & Wing §3), puts carry globally unique values, and
un-acknowledged operations (timeouts) may or may not have taken effect —
the checker may place them at any point after invocation or drop them.
SHED operations (``shed=True``) are *negatively acknowledged*: the
server's load-shed reply guarantees the request was refused before ever
entering the ingress queue, so unlike an unacked op the checker must
NEVER place a shed put — it is dropped outright, which makes any get
observing its (globally unique) value a linearizability violation ("an
ack lost to a shed" / a shed that secretly executed).

Algorithm: Wing & Gong tree search with memoization on
(remaining-operation set, register value), per key.  Histories from the
test harness are mostly per-client sequential, which keeps the search
effectively linear.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Dict, List, Optional, Tuple

INF = float("inf")


@dataclasses.dataclass(frozen=True)
class Op:
    """One client-observed operation."""

    client: int
    kind: str                  # "put" | "get" | "scan"
    key: str                   # scan: the range's inclusive lower bound
    value: Optional[str]       # put: written value; get: returned value
    t_inv: float
    t_resp: float = INF        # INF = never acknowledged (may have run)
    acked: bool = True         # False: op may be dropped by the checker
    shed: bool = False         # True: negatively acked (load shed) —
    #                            guaranteed never executed; the checker
    #                            drops it and may NOT place it
    end: Optional[str] = None  # scan: exclusive upper bound (None = inf)
    items: Optional[tuple] = None  # scan: observed ((key, value), ...)
    truncated: bool = False    # scan: limit hit — the observed span ends
    #                            at the last returned key, not ``end``


def record_put(client: int, key: str, value: str, t_inv: float,
               t_resp: Optional[float], acked: bool) -> Op:
    return Op(client, "put", key, value, t_inv,
              INF if t_resp is None else t_resp, acked)


def record_shed_put(client: int, key: str, value: str, t_inv: float,
                    t_resp: float) -> Op:
    """A put refused by ingress backpressure (``ApiReply(kind="shed")``):
    recorded so overload histories carry the negative acks, excluded by
    the checker on the server's never-proposed guarantee."""
    return Op(client, "put", key, value, t_inv, t_resp,
              acked=False, shed=True)


def record_get(client: int, key: str, value: Optional[str], t_inv: float,
               t_resp: float) -> Op:
    return Op(client, "get", key, value, t_inv, t_resp, True)


def record_scan(client: int, start: str, end: Optional[str],
                items, t_inv: float, t_resp: float,
                truncated: bool = False) -> Op:
    """An acked ordered range read over ``[start, end)``: ``items`` is
    the returned sorted ``(key, value)`` sequence; ``truncated`` marks a
    limit-capped result (absence of keys past the last returned one
    proves nothing).  Shed/timed-out scans are reads — callers simply
    don't record them."""
    return Op(client, "scan", start, None, t_inv, t_resp, True,
              end=end, items=tuple(tuple(i) for i in items),
              truncated=truncated)


def _expand_scans(ops: List[Op]) -> List[Op]:
    """Decompose each scan into synthetic per-key gets at the scan's
    [t_inv, t_resp] window: one get per observed pair, plus one
    ``get = None`` absence witness for every key some put in the history
    wrote that falls inside the scan's *proven* span (up to the last
    returned key when the limit was hit) yet was not returned.  Sound:
    a linearizable scan IS a multi-key read at one point, so each
    projection must linearize as a get; the cross-key single-point
    obligation is checked separately (:func:`_scan_point_violation`)."""
    put_keys = {
        o.key for o in ops if o.kind == "put" and not o.shed
    }
    out: List[Op] = []
    for o in ops:
        if o.kind != "scan":
            out.append(o)
            continue
        if o.shed:
            continue  # a refused read observes (and proves) nothing
        items = o.items or ()
        seen = set()
        for k, v in items:
            seen.add(k)
            out.append(Op(o.client, "get", k, v, o.t_inv, o.t_resp))
        if o.truncated and not items:
            continue  # limit 0-shaped edge: no proven span at all
        hi = items[-1][0] if o.truncated else o.end
        for k in put_keys:
            if k in seen or k < o.key:
                continue
            if o.truncated:
                if k > hi:
                    continue
            elif hi is not None and k >= hi:
                continue
            out.append(Op(o.client, "get", k, None, o.t_inv, o.t_resp))
    return out


def _scan_point_violation(ops: List[Op]) -> Optional[Tuple[Op, str]]:
    """Cross-key single-point check: every scan must admit ONE instant
    inside [t_inv, t_resp] at which every observed value (and proven
    absence) is simultaneously current.  Windows are conservative
    over-approximations — per key, a value's earliest feasible instant
    is its put's invocation, and its latest is the first acked put that
    *definitely* linearizes later (invoked after the observed put's
    response) — so an empty intersection is a real violation (the
    fresh-here-stale-there cut a per-key projection can't see), while a
    non-empty one proves nothing extra (sound, incomplete)."""
    puts_by_key: Dict[str, List[Op]] = {}
    put_by_value: Dict[Optional[str], Op] = {}
    for o in ops:
        if o.kind == "put" and not o.shed:
            puts_by_key.setdefault(o.key, []).append(o)
            put_by_value[o.value] = o
    for o in ops:
        if o.kind != "scan" or o.shed:
            continue
        lo, hi = o.t_inv, o.t_resp
        for k, v in (o.items or ()):
            writer = put_by_value.get(v)
            if writer is None or writer.key != k:
                continue  # the per-key projection fails this one
            lo = max(lo, writer.t_inv)
            gone = writer.t_resp
            for q in puts_by_key.get(k, ()):
                if q is writer or not q.acked:
                    continue
                if q.t_inv >= gone:
                    hi = min(hi, q.t_resp)
            if lo > hi:
                return o, (
                    f"key {k!r}={v!r} current no earlier than "
                    f"{lo:.4f} but another observed key pins the "
                    f"scan before {hi:.4f}"
                )
        if (o.items is not None) and not o.truncated:
            # proven-absent keys: None stops being current once ANY
            # acked put to the key has completed
            seen = {k for k, _ in o.items}
            for k, qs in puts_by_key.items():
                if k in seen or k < o.key:
                    continue
                if o.end is not None and k >= o.end:
                    continue
                for q in qs:
                    if q.acked:
                        hi = min(hi, q.t_resp)
                if lo > hi:
                    return o, (
                        f"key {k!r} observed absent after an acked "
                        f"put to it completed by {hi:.4f} (scan "
                        f"pinned after {lo:.4f})"
                    )
    return None


def check_history(ops: List[Op]) -> Tuple[bool, Optional[str]]:
    """True iff the whole history is linearizable; on failure returns the
    offending key's diagnosis.  Keys are checked independently
    (P-compositionality); scans first face the cross-key single-point
    check, then decompose into per-key read projections."""
    bad = _scan_point_violation(ops)
    if bad is not None:
        scan, why = bad
        return False, (
            f"scan [{scan.key!r}, {scan.end!r}) by c{scan.client} at "
            f"[{scan.t_inv:.4f}, {scan.t_resp:.4f}] admits no single "
            f"linearization point: {why}"
        )
    ops = _expand_scans(ops)
    by_key: Dict[str, List[Op]] = {}
    for op in ops:
        by_key.setdefault(op.key, []).append(op)
    # the per-key search recurses one frame per placed op, so a long
    # soak's hottest zipfian key (thousands of ops) outruns CPython's
    # default 1000-frame limit long before time or memory matter — a
    # clean history resolves greedily in O(n) placements
    deepest = max((len(k) for k in by_key.values()), default=0)
    want = 2000 + 4 * deepest
    if sys.getrecursionlimit() < want:
        sys.setrecursionlimit(want)
    for key, kops in by_key.items():
        ok = _check_key(kops)
        if not ok:
            return False, _diagnose(key, kops)
    return True, None


def _prune_unobserved_unacked(kops: List[Op]) -> List[Op]:
    """Drop unacked puts whose value no get ever returned.

    Sound under the stated model (put values globally unique): the
    checker may always DROP an unacked put, and *placing* a never-read
    put can only restrict later gets — any get sequenced between it and
    the next put would have to return its (unique, never-observed) value,
    a contradiction — so removal never changes the verdict.  This is the
    load-bearing bound for fault-schedule histories: a nemesis soak can
    leave dozens of timed-out (unacked) puts per key, and each one
    otherwise doubles the Wing&Gong search space (observed: a ~70-op
    soak history spinning for minutes at >10GB of memo set)."""
    read = {o.value for o in kops if o.kind == "get"}
    return [
        o for o in kops
        if o.kind != "put" or o.acked or o.value in read
    ]


def _check_key(kops: List[Op]) -> bool:
    # shed ops are dropped BEFORE unacked pruning, and unconditionally:
    # an unacked put whose value was read stays placeable, but a SHED
    # put must never be placed even when observed — the shed reply
    # guarantees it did not execute, so an observation of its unique
    # value must FAIL the search (no remaining put can write it), not
    # be legalized by placement
    kops = [o for o in kops if not o.shed]
    kops = _prune_unobserved_unacked(kops)
    n = len(kops)
    if n == 0:
        return True
    kops = sorted(kops, key=lambda o: o.t_inv)
    inv = [o.t_inv for o in kops]
    resp = [o.t_resp for o in kops]
    full = frozenset(range(n))
    seen: set = set()

    def search(remaining: frozenset, state: Optional[str]) -> bool:
        if not any(kops[i].acked for i in remaining):
            return True  # everything left is droppable
        sig = (remaining, state)
        if sig in seen:
            return False
        seen.add(sig)
        # an op can go first iff nothing else still pending responded
        # strictly before its invocation (real-time order preservation)
        bar = min(resp[i] for i in remaining)
        for i in sorted(remaining, key=lambda j: inv[j]):
            if inv[i] > bar:
                break
            o = kops[i]
            if o.kind == "put":
                if search(remaining - {i}, o.value):
                    return True
                if not o.acked:
                    # an unacked put may also have never happened
                    if search(remaining - {i}, state):
                        return True
            else:
                if o.value == state and search(remaining - {i}, state):
                    return True
        return False

    return search(full, None)


def _diagnose(key: str, kops: List[Op]) -> str:
    lines = [f"key {key!r}: history not linearizable; ops:"]
    for o in sorted(kops, key=lambda x: x.t_inv):
        end = "∞" if o.t_resp == INF else f"{o.t_resp:.4f}"
        lines.append(
            f"  c{o.client} {o.kind}({o.value}) [{o.t_inv:.4f}, {end}]"
            + (" (shed)" if o.shed else "" if o.acked else " (unacked)")
        )
    return "\n".join(lines)
