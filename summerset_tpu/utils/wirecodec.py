"""Wire-plane binary codec: compact, versioned, self-describing frames
for the HOT message kinds, with pickle as the universal fallback.

Every safetcp frame is ``8-byte BE length + body``.  Historically the
body was always ``pickle.dumps`` of a plain Python object; on the hot
planes (p2p tick frames, client ``req``/``reply``/``shed`` traffic,
proxy forward batches) that pays a full serialize + copy per frame per
peer per tick.  This module adds a second body format distinguished by
its FIRST BYTE:

- ``0x80`` (pickle protocol 2+ opcode)  -> legacy pickle body;
- :data:`MAGIC` (``0xC7``)              -> wirecodec body.

Because the decoder dispatches per frame on that tag byte, a mixed mesh
(codec-on peer talking to a codec-off peer) interoperates frame by
frame with no negotiation: every NEW decoder reads both formats, and a
codec-off sender simply keeps emitting pickle.  The codec is only ever
an ENCODER-side choice, flipped by the ``wire_codec`` server knob / the
``SMR_WIRE_CODEC`` env default (see :func:`default_on`).

Body grammar (all fixed-width ints little-endian; lengths ``u32``)::

    body    := MAGIC(0xC7) VERSION(0x01) value
    value   := tag(u8) payload
    tags      0x01 None | 0x02 False | 0x03 True
              0x04 i8 scalar | 0x05 i64 scalar | 0x0F bigint (u32 len,
                   signed little-endian bytes)
              0x06 f64 | 0x07 bytes (u32 len) | 0x08 str (u32 len, utf8)
              0x09 tuple (u32 n) | 0x0A list (u32 n)
              0x0B dict (u32 n, key value pairs)
              0x0C ndarray: u8 dtype-str len, dtype.str utf8 (carries
                   endianness, e.g. "<i4"), u8 ndim, ndim * u32 dims,
                   zero-pad to 8-byte alignment FROM BODY START, raw
                   bytes — decoded zero-copy via ``np.frombuffer`` over
                   a memoryview of the received body
              0x0D struct: u8 struct-id, then the registered fields in
                   declaration order (ApiRequest / ApiReply / Command /
                   CommandResult / ShardPayload)
              0x0E pickle escape (u32 len, pickle bytes): any value the
                   grammar does not cover rides through verbatim, so a
                   codec frame can always be built — "hot" is a fast
                   path, never a compatibility wall

Four SPECIALIZED top-level tags cover the steady-state frame shapes,
where a generic per-value walk would give back most of the win (they
appear only as the body's first value; nested occurrences of the same
objects use the generic tags):

              0x10 tick frame: i64 tick, u32 rest-pickle len + blob
                   (every non-lane payload key, C-speed both ways),
                   u8 lane count, u16 schema len + a CONTIGUOUS schema
                   block (per lane: u8 name len, name, u8 dtype len,
                   dtype.str, u8 ndim, ndim * u32 dims), then the raw
                   lane arrays each 8-aligned from body start.  The
                   contiguous schema is the decode accelerator: its
                   bytes are memoized, so a steady mesh decodes each
                   frame's lane table with one dict hit + one zero-copy
                   view per lane instead of re-parsing dtype/shape
                   strings every tick
              0x11 hot ApiRequest (req/probe with a get/put Command):
                   u8 kind, i64 req_id, u8 cmd kind, u32 key len,
                   u32 value len + 1 (0 = None), key utf8, value utf8
              0x12 hot ApiReply (reply/shed/note/probe): u8 kind,
                   i64 req_id, u8 flag bits (success/rq_retry/local/
                   has_result/has_redirect/has_notes), u32
                   retry_after_ms, i64 seq, then the optional result
                   (u8 kind, u32 len + 1 value/old_value pairs),
                   i32 redirect, and a packed note list (u32 n, then
                   per note i64 seq, u32 key len, u32 value len + 1)
              0x13 batch ApiRequest (proxy forward): i64 req_id, u32 n,
                   then per op i64 prid, u8 cmd kind, u32 key len,
                   u32 value len + 1, key, value

Encoding is segment-oriented: :class:`FrameEncoder` writes scalars and
small fields into a reusable scratch list that one C-speed join
coalesces, and emits ndarray payloads as zero-copy ``memoryview``
segments referencing the array's own buffer — the segment list feeds
``socket.sendmsg`` (vectored I/O), so a tick frame's lane arrays go
from kernel outbox to the NIC without a single Python-side copy.
Decoding never raises a bare ``struct.error``: truncated, garbage, or
over-cap bodies raise the typed :class:`WireDecodeError`.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .errors import SummersetError

MAGIC = 0xC7
VERSION = 1

#: hard caps enforced on decode (a garbage length field must fail the
#: frame, never allocate unboundedly); MAX_BODY mirrors safetcp's frame
#: cap so the two layers agree on "absurd"
MAX_BODY = 64 * 1024 * 1024
MAX_ITEMS = 1 << 24
MAX_DEPTH = 32

_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")
_I32 = struct.Struct("<i")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

T_NONE = 0x01
T_FALSE = 0x02
T_TRUE = 0x03
T_I8 = 0x04
T_I64 = 0x05
T_F64 = 0x06
T_BYTES = 0x07
T_STR = 0x08
T_TUPLE = 0x09
T_LIST = 0x0A
T_DICT = 0x0B
T_NDARRAY = 0x0C
T_STRUCT = 0x0D
T_PICKLE = 0x0E
T_BIGINT = 0x0F
T_TICKFRAME = 0x10
T_REQ = 0x11
T_REPLY = 0x12
T_BATCH = 0x13

# fast-path field packers (fixed little-endian layouts)
_REQ_HDR = struct.Struct("<BqBII")       # kind, req_id, ck, klen, vlen+1
_REPLY_HDR = struct.Struct("<BqBIq")     # kind, req_id, flags, retry, seq
_RESULT_HDR = struct.Struct("<BII")      # kind, vlen+1, ovlen+1
_BATCH_HDR = struct.Struct("<qI")        # req_id, n ops
_BOP_HDR = struct.Struct("<qBII")        # prid, ck, klen, vlen+1
_NOTE_HDR = struct.Struct("<qII")        # seq, klen, vlen+1
_TICK_HDR = struct.Struct("<qI")         # tick, rest-pickle len

_REQ_KINDS = ("req", "probe")
_REPLY_KINDS = ("reply", "shed", "note", "probe")
_CMD_KINDS = ("get", "put")
_REQ_KIND_ID = {k: i for i, k in enumerate(_REQ_KINDS)}
_REPLY_KIND_ID = {k: i for i, k in enumerate(_REPLY_KINDS)}
_CMD_KIND_ID = {k: i for i, k in enumerate(_CMD_KINDS)}

_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


class WireEncodeError(SummersetError):
    """A value the caller asserted codec-encodable was not."""


class WireDecodeError(SummersetError):
    """Truncated / garbage / over-cap codec body.  The one decode
    error type: callers treat it exactly like a pickle failure (dead
    frame), and it NEVER surfaces as a bare ``struct.error``."""


# --------------------------------------------------------------- registry
# Struct ids are wire format: appending is fine, renumbering or field
# reordering breaks mixed-version meshes (same contract as the frame
# tags).  Fields are encoded positionally in declaration order.
_STRUCTS: List[Optional[Tuple[type, Tuple[str, ...]]]] = [None] * 8
_STRUCT_ID: Dict[type, int] = {}
_structs_ready = False
# class refs for the specialized fast paths (set by _ensure_structs)
_CLS_REQ = _CLS_REPLY = _CLS_CMD = _CLS_RESULT = None


def _register(sid: int, cls: type, fields: Tuple[str, ...]) -> None:
    _STRUCTS[sid] = (cls, fields)
    _STRUCT_ID[cls] = sid


def _ensure_structs() -> None:
    """Lazy one-shot registration of the host message dataclasses.

    Lives here (not in host/messages.py) so a bare ``utils`` import
    never drags the host package in, while any process that actually
    encodes/decodes these types resolves them on first use."""
    global _structs_ready, _CLS_REQ, _CLS_REPLY, _CLS_CMD, _CLS_RESULT
    if _structs_ready:
        return
    from ..host.messages import ApiReply, ApiRequest, ShardPayload
    from ..host.statemach import Command, CommandResult

    _register(1, ApiRequest,
              ("kind", "req_id", "cmd", "conf_delta", "batch"))
    _register(2, ApiReply,
              ("kind", "req_id", "result", "redirect", "success",
               "rq_retry", "local", "retry_after_ms", "seq", "notes"))
    # scan fields appended (wire-safe per the registry contract above):
    # old decoders drop them, old encoders omit them and the dataclass
    # defaults fill in — only scan traffic, which old peers never
    # originate, actually populates them
    _register(3, Command, ("kind", "key", "value", "end", "limit"))
    _register(4, CommandResult, ("kind", "value", "old_value", "items"))
    _register(5, ShardPayload, ("data_len", "shards"))
    _CLS_REQ, _CLS_REPLY = ApiRequest, ApiReply
    _CLS_CMD, _CLS_RESULT = Command, CommandResult
    _structs_ready = True


# tick-frame lane-schema memos: encode side keys the built block by the
# lanes' (name, dtype, shape) tuple; decode side keys the parsed
# [(name, dtype, shape, nbytes), ...] table by the block's bytes.  Both
# are tiny (one entry per kernel config variant in the mesh) and live
# for the process.
_SCHEMA_ENC: Dict[tuple, bytes] = {}
_SCHEMA_DEC: Dict[bytes, list] = {}

# the encoder-side hot gate: only these ApiRequest/ApiReply kinds ride
# the codec — cold/ctrl kinds (conf, leave, sub, stats, redirect,
# error, ...) stay pickle, per the wire-plane contract
HOT_REQUEST_KINDS = frozenset(("req", "batch", "probe"))
HOT_REPLY_KINDS = frozenset(("reply", "shed", "note", "probe"))

_default_on = os.environ.get(
    "SMR_WIRE_CODEC", "1"
).strip().lower() not in ("0", "off", "false", "no")


def default_on() -> bool:
    """Process-wide codec default (env ``SMR_WIRE_CODEC``, on unless
    explicitly disabled).  Components take ``codec=None`` to mean
    "follow this": one env var flips a whole child process for A/B
    runs, while explicit ``codec=True/False`` pins one instance (the
    mixed-mesh tests)."""
    return _default_on


def set_default(on: bool) -> bool:
    """Flip the process default (tests / bench harnesses); returns the
    previous value."""
    global _default_on
    prev = _default_on
    _default_on = bool(on)
    return prev


def payload_nbytes(obj: Any) -> int:
    """Wire size of a payload-plane object (the ``pp`` piggyback) as the
    frame formats actually serialize it.  BOTH formats pickle it at
    ``HIGHEST_PROTOCOL``: the codec tick frame carries every non-lane
    payload key in its C-speed rest-pickle blob, and the pickle fallback
    pickles the whole frame the same way — so the shard-economy meters
    (``pp_bytes``) size with this one helper instead of a bare default-
    protocol ``pickle.dumps`` that diverges from the wire."""
    return len(pickle.dumps(obj, pickle.HIGHEST_PROTOCOL))


def is_hot(obj: Any) -> bool:
    """Should this object take the codec fast path?  Hot = the data
    plane's steady-state kinds; everything else is rare enough that
    pickle's universality wins."""
    _ensure_structs()
    t = type(obj)
    if t is _CLS_REQ:
        return obj.kind in HOT_REQUEST_KINDS
    if t is _CLS_REPLY:
        return obj.kind in HOT_REPLY_KINDS
    # transport tick frames: (tick:int, payload:dict)
    return (
        t is tuple and len(obj) == 2
        and type(obj[0]) is int and type(obj[1]) is dict
    )


# ---------------------------------------------------------------- encoder
# Hot-path notes: the specialized paths dispatch BEFORE the generic
# closures are built (their construction alone costs more than a small
# frame), append a handful of small ``bytes`` objects that one C-speed
# ``b"".join`` coalesces, and emit ndarray payloads as ZERO-COPY
# memoryview segments straight into ``socket.sendmsg``.  This is what
# lets a pure-Python codec beat C pickle per frame: pickle walks every
# array through a Python-level ``__reduce_ex__`` AND copies the raw
# bytes into its output; here the raw bytes are never touched.


class FrameEncoder:
    """Reusable segment-oriented encoder (one per hub hot loop).

    ``encode_frame_into(obj)`` returns ``(segments, body_len)`` where
    ``segments`` is a list of buffer objects (joined small-field chunks
    + zero-copy ndarray views) whose concatenation is the codec body.
    The internal scratch list is reused across calls; ndarray segments
    reference live array buffers, so callers finish the send (or copy)
    before mutating the arrays — the tick loop's natural discipline
    (encode, sendmsg, next tick).  :meth:`release` drops the buffer
    references afterwards."""

    __slots__ = ("_parts", "_segs")

    def __init__(self):
        self._parts: list = []
        self._segs: list = []

    def encode_frame_into(self, obj: Any) -> Tuple[List[Any], int]:
        if not _structs_ready:
            _ensure_structs()
        segs = self._segs
        parts = self._parts
        del segs[:]
        del parts[:]
        ap = parts.append
        ap(b"\xc7\x01")
        # ---- specialized fast paths.  Each validates fully BEFORE its
        # first append, so a fallback leaves only the magic prefix in
        # ``parts`` and the generic walk below re-encodes from scratch.
        t = type(obj)
        if t is _CLS_REQ:
            if _fast_request(obj, ap):
                body = b"".join(parts)
                del parts[:]
                segs.append(body)
                return segs, len(body)
        elif t is _CLS_REPLY:
            if _fast_reply(obj, ap):
                body = b"".join(parts)
                del parts[:]
                segs.append(body)
                return segs, len(body)
        elif (
            t is tuple and len(obj) == 2 and type(obj[0]) is int
            and type(obj[1]) is dict and type(obj[1].get("msg")) is dict
        ):
            blen = _fast_tick(obj, ap, parts, segs)
            if blen:
                if parts:
                    segs.append(b"".join(parts))
                    del parts[:]
                return segs, blen
        return self._generic(obj, parts, segs)

    def _generic(self, obj: Any, parts: list, segs: list
                 ) -> Tuple[List[Any], int]:
        ap = parts.append
        blen = 2  # MAGIC + VERSION already in parts
        # local bindings: the recursion below is the per-frame hot loop
        pk_i64 = _I64.pack
        pk_u32 = _U32.pack
        pk_f64 = _F64.pack
        struct_id = _STRUCT_ID
        structs = _STRUCTS
        ndarray_t = np.ndarray

        def flush() -> None:
            if parts:
                segs.append(b"".join(parts))
                del parts[:]

        def enc(obj, depth: int) -> None:
            nonlocal blen
            if depth > MAX_DEPTH:
                raise WireEncodeError("wirecodec: nesting too deep")
            t = type(obj)
            if t is int:
                if -128 <= obj <= 127:
                    ap(bytes((T_I8, obj & 0xFF)))
                    blen += 2
                elif _I64_MIN <= obj <= _I64_MAX:
                    ap(b"\x05" + pk_i64(obj))
                    blen += 9
                else:
                    raw = obj.to_bytes(
                        (obj.bit_length() + 8) // 8, "little", signed=True
                    )
                    ap(b"\x0f" + pk_u32(len(raw)) + raw)
                    blen += 5 + len(raw)
            elif t is str:
                raw = obj.encode("utf-8")
                ap(b"\x08" + pk_u32(len(raw)))
                ap(raw)
                blen += 5 + len(raw)
            elif obj is None:
                ap(b"\x01")
                blen += 1
            elif t is bool:
                ap(b"\x03" if obj else b"\x02")
                blen += 1
            elif t is float:
                ap(b"\x06" + pk_f64(obj))
                blen += 9
            elif t is tuple:
                ap(b"\x09" + pk_u32(len(obj)))
                blen += 5
                for x in obj:
                    enc(x, depth + 1)
            elif t is list:
                ap(b"\x0a" + pk_u32(len(obj)))
                blen += 5
                for x in obj:
                    enc(x, depth + 1)
            elif t is dict:
                ap(b"\x0b" + pk_u32(len(obj)))
                blen += 5
                for k, v in obj.items():
                    enc(k, depth + 1)
                    enc(v, depth + 1)
            elif t is ndarray_t:
                if obj.dtype.hasobject:
                    raw = pickle.dumps(obj, pickle.HIGHEST_PROTOCOL)
                    ap(b"\x0e" + pk_u32(len(raw)))
                    ap(raw)
                    blen += 5 + len(raw)
                    return
                if not obj.flags.c_contiguous:
                    obj = np.ascontiguousarray(obj)
                ds = obj.dtype.str.encode("ascii")
                hdr = (
                    bytes((T_NDARRAY, len(ds)))
                    + ds
                    + bytes((obj.ndim,))
                    + b"".join(pk_u32(d) for d in obj.shape)
                )
                blen += len(hdr)
                pad = (-blen) % 8  # align raw data from body start
                if pad:
                    hdr += b"\x00" * pad
                    blen += pad
                ap(hdr)
                nb = obj.nbytes
                if nb:
                    blen += nb
                    if nb > 128:
                        # zero-copy: segment references the array buffer
                        flush()
                        segs.append(obj.data.cast("B"))
                    else:
                        ap(obj.tobytes())
            elif t is bytes:
                n = len(obj)
                ap(b"\x07" + pk_u32(n))
                blen += 5 + n
                if n > 512:
                    flush()
                    segs.append(obj)
                else:
                    ap(obj)
            else:
                sid = struct_id.get(t)
                if sid is not None:
                    ap(bytes((T_STRUCT, sid)))
                    blen += 2
                    for f in structs[sid][1]:
                        enc(getattr(obj, f), depth + 1)
                elif isinstance(obj, np.generic):
                    # numpy scalars leak into frames; canonicalize
                    # rather than pickle-escape them
                    if isinstance(obj, np.bool_):
                        enc(bool(obj), depth)
                    elif isinstance(obj, np.integer):
                        enc(int(obj), depth)
                    elif isinstance(obj, np.floating):
                        enc(float(obj), depth)
                    else:
                        raw = pickle.dumps(obj, pickle.HIGHEST_PROTOCOL)
                        ap(b"\x0e" + pk_u32(len(raw)))
                        ap(raw)
                        blen += 5 + len(raw)
                else:
                    raw = pickle.dumps(obj, pickle.HIGHEST_PROTOCOL)
                    ap(b"\x0e" + pk_u32(len(raw)))
                    ap(raw)
                    blen += 5 + len(raw)

        enc(obj, 0)
        flush()
        return segs, blen

    def encode_bytes(self, obj: Any) -> bytes:
        """Joined-body convenience (asyncio writers, tests)."""
        segs, _n = self.encode_frame_into(obj)
        try:
            if len(segs) == 1 and type(segs[0]) is bytes:
                return segs[0]
            return b"".join(
                s if type(s) is bytes else bytes(s) for s in segs
            )
        finally:
            self.release()

    def release(self) -> None:
        """Drop buffer references (ndarray views) so the frame's
        arrays are mutable again.  Send paths call this after the bytes
        are on the wire."""
        del self._segs[:]
        del self._parts[:]


# -- specialized encoders (module level: no per-call closure builds) ------
def _fast_request(obj, ap) -> bool:
    """T_REQ / T_BATCH for hot ApiRequests; False = generic fallback."""
    kid = _REQ_KIND_ID.get(obj.kind)
    cmd = obj.cmd
    rid = obj.req_id
    if type(rid) is not int or not _I64_MIN <= rid <= _I64_MAX:
        return False
    if (
        kid is not None and type(cmd) is _CLS_CMD
        and obj.conf_delta is None and obj.batch is None
    ):
        ck = _CMD_KIND_ID.get(cmd.kind)
        v = cmd.value
        if ck is None or type(cmd.key) is not str \
                or not (v is None or type(v) is str):
            return False
        k = cmd.key.encode("utf-8")
        vb = b"" if v is None else v.encode("utf-8")
        ap(b"\x11" + _REQ_HDR.pack(
            kid, rid, ck, len(k), 0 if v is None else len(vb) + 1,
        ))
        ap(k)
        ap(vb)
        return True
    if obj.kind == "batch" and cmd is None and obj.conf_delta is None \
            and type(obj.batch) is list:
        chunks = []
        cap = chunks.append
        pk = _BOP_HDR.pack
        for item in obj.batch:
            if type(item) is not tuple or len(item) != 2:
                return False
            prid, c = item
            if type(prid) is not int or type(c) is not _CLS_CMD \
                    or not _I64_MIN <= prid <= _I64_MAX:
                return False
            ck = _CMD_KIND_ID.get(c.kind)
            v = c.value
            if ck is None or type(c.key) is not str \
                    or not (v is None or type(v) is str):
                return False
            k = c.key.encode("utf-8")
            vb = b"" if v is None else v.encode("utf-8")
            cap(pk(prid, ck, len(k), 0 if v is None else len(vb) + 1))
            cap(k)
            cap(vb)
        ap(b"\x13" + _BATCH_HDR.pack(rid, len(obj.batch)))
        ap(b"".join(chunks))
        return True
    return False


def _fast_reply(obj, ap) -> bool:
    kid = _REPLY_KIND_ID.get(obj.kind)
    rid, seq = obj.req_id, obj.seq
    if (
        kid is None
        or type(rid) is not int or not _I64_MIN <= rid <= _I64_MAX
        or type(seq) is not int or not _I64_MIN <= seq <= _I64_MAX
    ):
        return False
    flags = (
        (1 if obj.success else 0)
        | (2 if obj.rq_retry else 0)
        | (4 if obj.local else 0)
    )
    res = obj.result
    res_tail = None
    if res is not None:
        if type(res) is not _CLS_RESULT:
            return False
        rk = _CMD_KIND_ID.get(res.kind)
        v, ov = res.value, res.old_value
        if rk is None or not (v is None or type(v) is str) \
                or not (ov is None or type(ov) is str):
            return False
        vb = b"" if v is None else v.encode("utf-8")
        ovb = b"" if ov is None else ov.encode("utf-8")
        res_tail = (
            _RESULT_HDR.pack(
                rk,
                0 if v is None else len(vb) + 1,
                0 if ov is None else len(ovb) + 1,
            ),
            vb, ovb,
        )
        flags |= 8
    red = obj.redirect
    if red is not None:
        if type(red) is not int or not -(1 << 31) <= red < (1 << 31):
            return False
        flags |= 16
    notes = obj.notes
    note_chunks = None
    if notes is not None:
        # the hot notes shape is the commit feed's [(seq, key, value)]
        # stream; anything else (the "sub" snapshot dict rides a cold
        # kind anyway) falls back to the generic grammar
        if type(notes) is not list:
            return False
        note_chunks = []
        ncap = note_chunks.append
        npk = _NOTE_HDR.pack
        for e in notes:
            if type(e) is not tuple or len(e) != 3:
                return False
            s, k, v = e
            if type(s) is not int or not _I64_MIN <= s <= _I64_MAX \
                    or type(k) is not str \
                    or not (v is None or type(v) is str):
                return False
            kb = k.encode("utf-8")
            vb = b"" if v is None else v.encode("utf-8")
            ncap(npk(s, len(kb), 0 if v is None else len(vb) + 1))
            ncap(kb)
            ncap(vb)
        flags |= 32
    retry = obj.retry_after_ms
    if type(retry) is not int or not 0 <= retry < (1 << 32):
        return False
    ap(b"\x12" + _REPLY_HDR.pack(kid, rid, flags, retry, seq))
    if res_tail is not None:
        ap(res_tail[0])
        ap(res_tail[1])
        ap(res_tail[2])
    if red is not None:
        ap(_I32.pack(red))
    if note_chunks is not None:
        ap(_U32.pack(len(notes)))
        ap(b"".join(note_chunks))
    return True


def _fast_tick(obj, ap, parts, segs) -> int:
    """T_TICKFRAME: raw zero-copy lane segments + one C-speed pickle
    blob for the rest of the payload.  Returns the body length, or 0
    to fall back."""
    tick, payload = obj
    msg = payload["msg"]
    if len(msg) > 255 or not _I64_MIN <= tick <= _I64_MAX:
        return 0
    lanes = []
    skey_parts = []
    for name, a in msg.items():
        if (
            type(name) is not str or type(a) is not np.ndarray
            or a.dtype.hasobject or a.ndim > 255 or len(name) > 255
        ):
            return 0
        lanes.append(a)
        skey_parts.append((name, a.dtype.str, a.shape))
    skey = tuple(skey_parts)
    sch = _SCHEMA_ENC.get(skey)
    if sch is None:
        bb = bytearray()
        for name, ds, shape in skey_parts:
            nb = name.encode("utf-8")
            db = ds.encode("ascii")
            if len(nb) > 255 or len(db) > 255:
                return 0
            bb.append(len(nb))
            bb += nb
            bb.append(len(db))
            bb += db
            bb.append(len(shape))
            for d in shape:
                bb += _U32.pack(d)
        if len(bb) > 0xFFFF:
            return 0
        sch = _SCHEMA_ENC[skey] = bytes(bb)
    rest = {k: v for k, v in payload.items() if k != "msg"}
    rp = pickle.dumps(rest, pickle.HIGHEST_PROTOCOL)
    ap(b"\x10" + _TICK_HDR.pack(tick, len(rp)))
    ap(rp)
    ap(bytes((len(lanes),)) + _U16.pack(len(sch)))
    ap(sch)
    blen = 2 + 1 + _TICK_HDR.size + len(rp) + 3 + len(sch)
    for a in lanes:
        nb = a.nbytes
        if not nb:
            continue
        pad = (-blen) % 8
        if pad:
            ap(b"\x00" * pad)
            blen += pad
        blen += nb
        if a.flags.c_contiguous:
            # zero-copy: the segment references the array's own buffer
            if parts:
                segs.append(b"".join(parts))
                del parts[:]
            segs.append(a.data.cast("B"))
        else:
            # the outbox slicer hands strided views ([G, R] lanes cut
            # at src, [G, R, R] pair fields cut at (src, dst)); a
            # strided buffer cannot ride the wire raw, so pay the one
            # C-level copy (pickle pays the same inside its reduce)
            ap(a.tobytes())
    return blen


# one shared encoder for the convenience entry points (the hubs own
# their private instances on their hot loops)
_shared = FrameEncoder()
_shared_lock = threading.Lock()


def encode_body(obj: Any) -> bytes:
    """One-shot codec body (joined bytes)."""
    with _shared_lock:
        return _shared.encode_bytes(obj)


# ---------------------------------------------------------------- decoder
# Like the encoder, the specialized tags decode through module-level
# straight-line functions (no closure builds) and construct the frozen
# message dataclasses the way pickle does — ``__new__`` + ``__dict__``
# fill — because a frozen dataclass ``__init__`` pays object.__setattr__
# per field.
_NEW = object.__new__
_SETATTR = object.__setattr__  # frozen dataclasses block plain __dict__
#                              # assignment; the base-class hook does not


def _mk_cmd(kind: str, key: str, value) -> Any:
    c = _NEW(_CLS_CMD)
    _SETATTR(c, "__dict__", {"kind": kind, "key": key, "value": value})
    return c


def _dec_str_pair(mv, pos: int, lk: int, lv: int, total: int):
    """(key, value, pos) for the codec's u32 klen / u32 vlen+1 pairs."""
    if lk + (lv - 1 if lv else 0) > total - pos:
        raise WireDecodeError("wirecodec: truncated key/value")
    key = str(mv[pos:pos + lk], "utf-8")
    pos += lk
    if lv:
        value = str(mv[pos:pos + lv - 1], "utf-8")
        pos += lv - 1
    else:
        value = None
    return key, value, pos


def _dec_req(mv, total: int):
    kid, rid, ck, lk, lv = _REQ_HDR.unpack_from(mv, 3)
    if kid >= len(_REQ_KINDS) or ck >= len(_CMD_KINDS):
        raise WireDecodeError("wirecodec: bad T_REQ kinds")
    key, value, pos = _dec_str_pair(mv, 3 + _REQ_HDR.size, lk, lv, total)
    r = _NEW(_CLS_REQ)
    _SETATTR(r, "__dict__", {
        "kind": _REQ_KINDS[kid], "req_id": rid,
        "cmd": _mk_cmd(_CMD_KINDS[ck], key, value),
        "conf_delta": None, "batch": None,
    })
    return r, pos


def _dec_batch(mv, total: int):
    rid, n = _BATCH_HDR.unpack_from(mv, 3)
    pos = 3 + _BATCH_HDR.size
    if n > MAX_ITEMS or n * _BOP_HDR.size > total - pos:
        raise WireDecodeError(f"wirecodec: batch length {n} over cap")
    unpack = _BOP_HDR.unpack_from
    sz = _BOP_HDR.size
    kinds = _CMD_KINDS
    nk = len(kinds)
    new = _NEW
    setattr_ = _SETATTR
    cmd_cls = _CLS_CMD
    ops = [None] * n
    for i in range(n):
        prid, ck, lk, lv = unpack(mv, pos)
        pos += sz
        if ck >= nk or lk + (lv - 1 if lv else 0) > total - pos:
            raise WireDecodeError("wirecodec: bad batch op")
        key = str(mv[pos:pos + lk], "utf-8")
        pos += lk
        if lv:
            value = str(mv[pos:pos + lv - 1], "utf-8")
            pos += lv - 1
        else:
            value = None
        c = new(cmd_cls)
        setattr_(c, "__dict__",
                 {"kind": kinds[ck], "key": key, "value": value})
        ops[i] = (prid, c)
    r = _NEW(_CLS_REQ)
    _SETATTR(r, "__dict__", {
        "kind": "batch", "req_id": rid, "cmd": None,
        "conf_delta": None, "batch": ops,
    })
    return r, pos


def _dec_reply(mv, total: int):
    kid, rid, flags, retry, seq = _REPLY_HDR.unpack_from(mv, 3)
    pos = 3 + _REPLY_HDR.size
    if kid >= len(_REPLY_KINDS):
        raise WireDecodeError("wirecodec: bad T_REPLY kind")
    result = None
    if flags & 8:
        rk, lv, lov = _RESULT_HDR.unpack_from(mv, pos)
        if rk >= len(_CMD_KINDS):
            raise WireDecodeError("wirecodec: bad T_REPLY result kind")
        v, ov, pos = _dec_str_pair(
            mv, pos + _RESULT_HDR.size, (lv - 1 if lv else 0), lov, total
        )
        if not lv:
            v = None
        result = _NEW(_CLS_RESULT)
        _SETATTR(result, "__dict__", {
            "kind": _CMD_KINDS[rk], "value": v, "old_value": ov,
        })
    redirect = None
    if flags & 16:
        redirect = _I32.unpack_from(mv, pos)[0]
        pos += 4
    notes = None
    if flags & 32:
        n = _U32.unpack_from(mv, pos)[0]
        pos += 4
        if n > MAX_ITEMS or n * _NOTE_HDR.size > total - pos:
            raise WireDecodeError(f"wirecodec: note count {n} over cap")
        unpack = _NOTE_HDR.unpack_from
        sz = _NOTE_HDR.size
        notes = [None] * n
        for i in range(n):
            s, lk, lv = unpack(mv, pos)
            pos += sz
            if lk + (lv - 1 if lv else 0) > total - pos:
                raise WireDecodeError("wirecodec: truncated note")
            k = str(mv[pos:pos + lk], "utf-8")
            pos += lk
            if lv:
                v = str(mv[pos:pos + lv - 1], "utf-8")
                pos += lv - 1
            else:
                v = None
            notes[i] = (s, k, v)
    r = _NEW(_CLS_REPLY)
    _SETATTR(r, "__dict__", {
        "kind": _REPLY_KINDS[kid], "req_id": rid, "result": result,
        "redirect": redirect, "success": bool(flags & 1),
        "rq_retry": bool(flags & 2), "local": bool(flags & 4),
        "retry_after_ms": retry, "seq": seq, "notes": notes,
    })
    return r, pos


def _dec_tick(mv, total: int):
    tick, rl = _TICK_HDR.unpack_from(mv, 3)
    pos = 3 + _TICK_HDR.size
    if rl > total - pos:
        raise WireDecodeError("wirecodec: truncated tick rest")
    try:
        rest = pickle.loads(mv[pos:pos + rl])
    except Exception as e:
        raise WireDecodeError(
            f"wirecodec: tick rest pickle failed: {e!r}"
        ) from None
    if type(rest) is not dict:
        raise WireDecodeError("wirecodec: tick rest not a dict")
    pos += rl
    if total - pos < 3:
        raise WireDecodeError("wirecodec: truncated lane header")
    nl = mv[pos]
    slen = _U16.unpack_from(mv, pos + 1)[0]
    pos += 3
    if slen > total - pos:
        raise WireDecodeError("wirecodec: truncated lane schema")
    skey = bytes(mv[pos:pos + slen])
    table = _SCHEMA_DEC.get(skey)
    if table is None:
        table = _parse_lane_schema(skey, nl)
        _SCHEMA_DEC[skey] = table
    elif len(table) != nl:
        raise WireDecodeError("wirecodec: lane count mismatch")
    pos += slen
    msg = {}
    nda = np.ndarray
    for name, dt, shape, nbytes in table:
        if not nbytes:
            msg[name] = np.empty(shape, dtype=dt)
            continue
        pos += (-pos) % 8
        if nbytes > total - pos:
            raise WireDecodeError("wirecodec: truncated lane body")
        # zero-copy read-only view over the received body
        msg[name] = nda(shape, dt, mv[pos:pos + nbytes])
        pos += nbytes
    rest["msg"] = msg
    return (tick, rest), pos


def _parse_lane_schema(skey: bytes, nl: int) -> list:
    table = []
    p = 0
    slen = len(skey)
    for _ in range(nl):
        if p >= slen:
            raise WireDecodeError("wirecodec: truncated lane schema")
        ln = skey[p]
        name = str(skey[p + 1:p + 1 + ln], "utf-8")
        p += 1 + ln
        if p >= slen:
            raise WireDecodeError("wirecodec: truncated lane schema")
        dl = skey[p]
        try:
            dt = np.dtype(str(skey[p + 1:p + 1 + dl], "ascii"))
        except (TypeError, ValueError, UnicodeDecodeError) as e:
            raise WireDecodeError(f"wirecodec: bad lane dtype: {e}") from None
        if dt.hasobject:
            raise WireDecodeError("wirecodec: object lane dtype refused")
        p += 1 + dl
        if p >= slen:
            raise WireDecodeError("wirecodec: truncated lane schema")
        nd = skey[p]
        p += 1
        if nd > 16:
            raise WireDecodeError(f"wirecodec: lane ndim {nd} over cap")
        shape = []
        count = 1
        for _ in range(nd):
            if p + 4 > slen:
                raise WireDecodeError("wirecodec: truncated lane schema")
            d = _U32.unpack_from(skey, p)[0]
            p += 4
            shape.append(d)
            count *= d
        nbytes = count * dt.itemsize
        if nbytes > MAX_BODY:
            raise WireDecodeError(f"wirecodec: lane {nbytes}B over cap")
        table.append((name, dt, tuple(shape), nbytes))
    if p != slen:
        raise WireDecodeError("wirecodec: lane schema length mismatch")
    return table


_FAST_DEC = {}  # tag -> decoder, filled below


def decode_codec_body(buf) -> Any:
    """Decode a body known to start with :data:`MAGIC`.

    Every malformation — truncation, garbage tags/lengths, over-cap
    allocations, bad utf-8/dtype, a length field pointing past the end
    — raises :class:`WireDecodeError`; ``struct.error``/``IndexError``
    never escape (bounds checks stay implicit where the struct module
    already does them, and the outer handler retypes)."""
    if not _structs_ready:
        _ensure_structs()
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    total = len(mv)
    if total > MAX_BODY:
        raise WireDecodeError(f"wirecodec: body {total}B over cap")
    if total < 3 or mv[0] != MAGIC:
        raise WireDecodeError("wirecodec: not a codec body")
    if mv[1] != VERSION:
        raise WireDecodeError(
            f"wirecodec: unsupported version {mv[1]} (have {VERSION})"
        )
    fast = _FAST_DEC.get(mv[2])
    if fast is not None:
        try:
            obj, pos = fast(mv, total)
        except WireDecodeError:
            raise
        except (struct.error, IndexError, UnicodeDecodeError):
            raise WireDecodeError(
                "wirecodec: truncated or garbage body"
            ) from None
        if pos != total:
            raise WireDecodeError(
                f"wirecodec: {total - pos} trailing bytes after value"
            )
        return obj
    return _decode_generic(mv, total)


_FAST_DEC[T_REQ] = _dec_req
_FAST_DEC[T_REPLY] = _dec_reply
_FAST_DEC[T_BATCH] = _dec_batch
_FAST_DEC[T_TICKFRAME] = _dec_tick


def _decode_generic(mv: memoryview, total: int) -> Any:
    pos = 2
    up_i64 = _I64.unpack_from
    up_u32 = _U32.unpack_from
    up_f64 = _F64.unpack_from
    structs = _STRUCTS

    def val(depth: int):
        nonlocal pos
        if depth > MAX_DEPTH:
            raise WireDecodeError("wirecodec: nesting too deep")
        tag = mv[pos]
        pos += 1
        if tag == T_I8:
            v = mv[pos]
            pos += 1
            return v - 256 if v >= 128 else v
        if tag == T_I64:
            v = up_i64(mv, pos)[0]
            pos += 8
            return v
        if tag == T_STR:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > total - pos:
                raise WireDecodeError("wirecodec: truncated string")
            raw = mv[pos:pos + n]
            pos += n
            try:
                return str(raw, "utf-8")
            except UnicodeDecodeError as e:
                raise WireDecodeError(
                    f"wirecodec: bad utf-8: {e}"
                ) from None
        if tag == T_NONE:
            return None
        if tag == T_TRUE:
            return True
        if tag == T_FALSE:
            return False
        if tag == T_F64:
            v = up_f64(mv, pos)[0]
            pos += 8
            return v
        if tag == T_TUPLE or tag == T_LIST:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > MAX_ITEMS or n > total - pos:
                raise WireDecodeError(
                    f"wirecodec: sequence length {n} over cap"
                )
            out = [None] * n
            for i in range(n):
                out[i] = val(depth + 1)
            return tuple(out) if tag == T_TUPLE else out
        if tag == T_DICT:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > MAX_ITEMS or 2 * n > total - pos:
                raise WireDecodeError(
                    f"wirecodec: dict length {n} over cap"
                )
            d = {}
            for _ in range(n):
                try:
                    k = val(depth + 1)
                    d[k] = val(depth + 1)
                except TypeError:
                    raise WireDecodeError(
                        "wirecodec: unhashable dict key"
                    ) from None
            return d
        if tag == T_STRUCT:
            sid = mv[pos]
            pos += 1
            entry = structs[sid] if sid < len(structs) else None
            if entry is None:
                raise WireDecodeError(
                    f"wirecodec: unknown struct id {sid}"
                )
            cls, fields = entry
            vals = [val(depth + 1) for _ in fields]
            try:
                return cls(*vals)
            except TypeError as e:
                raise WireDecodeError(
                    f"wirecodec: bad {cls.__name__} fields: {e}"
                ) from None
        if tag == T_NDARRAY:
            dlen = mv[pos]
            pos += 1
            if dlen > total - pos:
                raise WireDecodeError("wirecodec: truncated dtype")
            try:
                dt = np.dtype(str(mv[pos:pos + dlen], "ascii"))
            except (TypeError, ValueError, UnicodeDecodeError) as e:
                raise WireDecodeError(
                    f"wirecodec: bad dtype: {e}"
                ) from None
            if dt.hasobject:
                raise WireDecodeError("wirecodec: object dtype refused")
            pos += dlen
            ndim = mv[pos]
            pos += 1
            if ndim > 16:
                raise WireDecodeError(f"wirecodec: ndim {ndim} over cap")
            shape = []
            count = 1
            for _ in range(ndim):
                d = up_u32(mv, pos)[0]
                pos += 4
                shape.append(d)
                count *= d
            nbytes = count * dt.itemsize
            if nbytes > MAX_BODY:
                raise WireDecodeError(
                    f"wirecodec: array {nbytes}B over cap"
                )
            pos += (-pos) % 8  # the encoder's alignment pad
            if nbytes > total - pos:
                raise WireDecodeError("wirecodec: truncated array body")
            if count:
                # zero-copy: a read-only view over the received body
                a = np.frombuffer(
                    mv[pos:pos + nbytes], dtype=dt
                ).reshape(shape)
            else:
                a = np.empty(shape, dtype=dt)
            pos += nbytes
            return a
        if tag == T_BYTES:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > total - pos:
                raise WireDecodeError("wirecodec: truncated bytes")
            raw = bytes(mv[pos:pos + n])
            pos += n
            return raw
        if tag == T_PICKLE:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > total - pos:
                raise WireDecodeError("wirecodec: truncated pickle blob")
            raw = mv[pos:pos + n]
            pos += n
            try:
                return pickle.loads(raw)
            except Exception as e:
                raise WireDecodeError(
                    f"wirecodec: embedded pickle failed: {e!r}"
                ) from None
        if tag == T_BIGINT:
            n = up_u32(mv, pos)[0]
            pos += 4
            if n > 4096 or n > total - pos:
                raise WireDecodeError("wirecodec: bigint over cap")
            v = int.from_bytes(mv[pos:pos + n], "little", signed=True)
            pos += n
            return v
        raise WireDecodeError(f"wirecodec: unknown value tag 0x{tag:02x}")

    try:
        obj = val(0)
    except WireDecodeError:
        raise
    except (struct.error, IndexError, UnicodeDecodeError):
        raise WireDecodeError(
            f"wirecodec: truncated or garbage body at offset "
            f"{pos}/{total}"
        ) from None
    if pos != total:
        raise WireDecodeError(
            f"wirecodec: {total - pos} trailing bytes after value"
        )
    return obj


def decode_body(buf) -> Any:
    """The one ingress dispatch: codec bodies by :data:`MAGIC`, anything
    else through pickle (the mixed-version path — an old/codec-off peer
    keeps sending pickle and is decoded transparently)."""
    mv = buf if isinstance(buf, memoryview) else memoryview(buf)
    if len(mv) >= 1 and mv[0] == MAGIC:
        return decode_codec_body(mv)
    try:
        return pickle.loads(mv)
    except WireDecodeError:
        raise
    except Exception as e:
        raise WireDecodeError(f"wirecodec: pickle body failed: {e!r}") from e
