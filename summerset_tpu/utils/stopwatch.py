"""Multi-record step-timestamp stopwatch for perf breakdowns.

Parity: reference ``src/utils/stopwatch.rs`` (``record_now:35``,
``summarize:91``) — per-slot step timestamping used by leaders to print
durable-log / accept-reply / quorum / exec stage breakdowns.  The device
analog records tick counters per stage; this host class aggregates either.
"""

from __future__ import annotations

import math
import time
from typing import Dict, List, Optional, Tuple


class Stopwatch:
    def __init__(self):
        # record id -> list of (step index, timestamp)
        self._records: Dict[int, List[Tuple[int, float]]] = {}

    def record_now(self, rec_id: int, step: int, ts: Optional[float] = None) -> None:
        self._records.setdefault(rec_id, []).append(
            (step, time.monotonic() if ts is None else ts)
        )

    def remove(self, rec_id: int) -> None:
        self._records.pop(rec_id, None)

    def remove_all(self) -> None:
        self._records.clear()

    def has_record(self, rec_id: int) -> bool:
        return rec_id in self._records

    def summarize(self, num_steps: int) -> List[Tuple[float, float]]:
        """Mean/stdev of the interval before each step 1..num_steps.

        Returns a list of (mean_us, stdev_us) of step[i] - step[i-1] across
        all records that contain both steps, in microseconds.
        """
        out: List[Tuple[float, float]] = []
        for step in range(1, num_steps + 1):
            deltas: List[float] = []
            for rec in self._records.values():
                by_step = dict(rec)
                if step in by_step and step - 1 in by_step:
                    deltas.append((by_step[step] - by_step[step - 1]) * 1e6)
            if deltas:
                mean = sum(deltas) / len(deltas)
                var = sum((d - mean) ** 2 for d in deltas) / len(deltas)
                out.append((mean, math.sqrt(var)))
            else:
                out.append((0.0, 0.0))
        return out
