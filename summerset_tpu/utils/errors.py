"""Framework-wide error type.

Parity: reference ``src/utils/error.rs:7-11`` (``SummersetError(String)`` with
conversions from all underlying error types).  In Python a single Exception
subclass with a message plays the same role.
"""

from __future__ import annotations

import logging


class SummersetError(Exception):
    """Single string-carrying error used across the framework."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.msg = msg

    def __str__(self) -> str:  # pragma: no cover - trivial
        return self.msg


def logged_err(logger: logging.Logger, msg: str) -> SummersetError:
    """Log an error message and return a ``SummersetError`` to raise.

    Parity: reference ``logged_err!`` macro (``src/utils/print.rs:16-40``).

    Usage::

        raise logged_err(log, f"unexpected message type: {m}")
    """
    logger.error(msg)
    return SummersetError(msg)
