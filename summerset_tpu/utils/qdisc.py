"""Netem qdisc introspection (delay / jitter / rate of an emulated link).

Parity: reference ``src/utils/qdisc.rs`` (``QdiscInfo::new/update``) — shells
out to ``tc qdisc show dev <dev>`` and parses netem delay/jitter/rate so that
Crossword can fold emulated-network state into its perf model.  Here parsing
is factored out for testability and the shell-out is optional (absent ``tc``
degrades to zeros).
"""

from __future__ import annotations

import re
import shutil
import subprocess
from typing import Optional

_UNITS_TIME = {"us": 0.001, "ms": 1.0, "s": 1000.0}
_UNITS_RATE = {"bit": 1e-9, "Kbit": 1e-6, "Mbit": 1e-3, "Gbit": 1.0, "Tbit": 1e3}


def _parse_time_ms(tok: str) -> float:
    m = re.fullmatch(r"([0-9.]+)(us|ms|s)", tok)
    if not m:
        return 0.0
    return float(m.group(1)) * _UNITS_TIME[m.group(2)]


def _parse_rate_gbps(tok: str) -> float:
    m = re.fullmatch(r"([0-9.]+)(Tbit|Gbit|Mbit|Kbit|bit)", tok)
    if not m:
        return 0.0
    return float(m.group(1)) * _UNITS_RATE[m.group(2)]


class QdiscInfo:
    """Parsed netem state of one device: delay (ms), jitter (ms), rate (Gbps)."""

    def __init__(self, dev: Optional[str] = None):
        self.dev = dev
        self.delay_ms = 0.0
        self.jitter_ms = 0.0
        self.rate_gbps = 0.0

    def parse_output(self, output: str) -> bool:
        """Parse ``tc qdisc show`` output; returns True if netem was found."""
        for line in output.splitlines():
            if "netem" not in line:
                continue
            # reset: fields absent from the current netem line must not keep
            # stale values from a previous update
            self.delay_ms = 0.0
            self.jitter_ms = 0.0
            self.rate_gbps = 0.0
            toks = line.split()
            for i, tok in enumerate(toks):
                if tok == "delay" and i + 1 < len(toks):
                    self.delay_ms = _parse_time_ms(toks[i + 1])
                    if i + 2 < len(toks) and re.fullmatch(
                        r"[0-9.]+(us|ms|s)", toks[i + 2]
                    ):
                        self.jitter_ms = _parse_time_ms(toks[i + 2])
                elif tok == "rate" and i + 1 < len(toks):
                    self.rate_gbps = _parse_rate_gbps(toks[i + 1])
            return True
        return False

    def update(self) -> bool:
        """Refresh by shelling out to ``tc`` (no-op without tc or dev)."""
        if self.dev is None or shutil.which("tc") is None:
            return False
        try:
            out = subprocess.run(
                ["tc", "qdisc", "show", "dev", self.dev],
                capture_output=True,
                text=True,
                timeout=2.0,
                check=False,
            ).stdout
        except (subprocess.SubprocessError, OSError):
            return False
        return self.parse_output(out)
