"""Resettable asynchronous timeout timer (host side).

Parity: reference ``src/utils/timer.rs:39-143`` (``Timer::new/kickoff/extend/
cancel/exploded``) — the backbone of heartbeats, leases and client timeouts in
the host runtime.  Implemented over asyncio instead of a spawned tokio task.

Device-side timers are *not* this class: vectorized protocols represent
timeouts as per-(group, replica) integer countdown arrays decremented each
tick with PRNG jitter (see ``summerset_tpu.ops.prng`` and protocol kernels),
mirroring how randomized hear-timeout ranges (``heartbeat.rs:96-116``) become
jittered countdown reloads.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional


class Timer:
    """One-shot resettable timer.

    - ``kickoff(dur)``: (re)start the countdown; cancels a pending one.
    - ``extend(dur)``: push the deadline further out without restarting flags.
    - ``cancel()``: stop without exploding.
    - ``exploded``: True once the deadline passed without cancel/restart.
    - optionally fires a callback and/or sets an asyncio.Event on explosion.
    """

    def __init__(
        self,
        explode_callback: Optional[Callable[[], None]] = None,
        explode_async: Optional[Callable[[], Awaitable[None]]] = None,
    ):
        self._cb = explode_callback
        self._acb = explode_async
        self._task: Optional[asyncio.Task] = None
        self._deadline: float = 0.0
        self._exploded = asyncio.Event()

    @property
    def exploded(self) -> bool:
        return self._exploded.is_set()

    async def wait_exploded(self) -> None:
        await self._exploded.wait()

    def kickoff(self, dur_secs: float) -> None:
        self.cancel()
        loop = asyncio.get_event_loop()
        self._deadline = loop.time() + dur_secs
        self._exploded.clear()
        self._task = loop.create_task(self._run())

    def extend(self, dur_secs: float) -> None:
        """Push the current deadline out by ``dur`` (kickoff if not ticking).

        Parity: reference ``timer.rs:94`` does ``*ddl += dur``.
        """
        if self._task is None or self._task.done():
            self.kickoff(dur_secs)
        else:
            self._deadline += dur_secs

    def cancel(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
        self._task = None
        self._exploded.clear()

    async def _run(self) -> None:
        loop = asyncio.get_event_loop()
        try:
            while True:
                now = loop.time()
                if now >= self._deadline:
                    break
                await asyncio.sleep(self._deadline - now)
            self._exploded.set()
            if self._cb is not None:
                self._cb()
            if self._acb is not None:
                await self._acb()
        except asyncio.CancelledError:
            pass
