"""Length-prefixed message framing over asyncio TCP, with retrying bind/connect.

Parity: reference ``src/utils/safetcp.rs`` — 8-byte big-endian length prefix +
serialized body (``safe_tcp_read:31`` / ``safe_tcp_write:105``), plus
``tcp_bind_with_retry`` / ``tcp_connect_with_retry``.  The reference's
cancellation-safe partial-read buffers map to asyncio's ``readexactly``;
its non-blocking would-block write contract maps to ``drain()``.

Serialization: the reference uses bincode over serde structs.  Here messages
are plain Python objects (dataclasses / tuples / dicts); the frame format
(8-byte BE length + body) is preserved so wire-level tooling carries over.
The BODY has two formats, dispatched per frame on its first byte
(``utils/wirecodec.py``): pickle (the universal fallback, and the only
format for cold/ctrl kinds) and the compact wirecodec binary form for the
hot data-plane kinds — transport tick frames, ``req``/``reply``/``shed``/
``batch``/``note``/``probe`` api messages.  Because dispatch is
per-frame, a codec-on sender and a codec-off sender interoperate on one
mesh with no negotiation.

Hot-path I/O: egress uses ``socket.sendmsg`` over the encoder's segment
list (vectored writes — the length prefix, the small-field chunks, and
zero-copy ndarray views leave in ONE syscall per frame, with no join
copy); ingress reads the length prefix into a reusable buffer and the
body into one exact-size buffer via ``recv_into`` (the old
``buf += chunk`` accumulation re-copied the partial frame on every
chunk — quadratic in frame size).  Body buffers are per-frame on
purpose: the codec decodes ndarray lanes as zero-copy views INTO the
received body, so recycling a body ring would corrupt frames already
handed to the replica.
"""

from __future__ import annotations

import asyncio
import errno
import pickle
import random
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from . import wirecodec
from .errors import SummersetError

_LEN = struct.Struct(">Q")

# Refuse absurd frames (reference caps values at 16MB; give headroom).
MAX_FRAME = 64 * 1024 * 1024

#: sendmsg scatter-gather cap (IOV_MAX is 1024 on Linux; stay under it)
_IOV_MAX = 512


class FrameFaults:
    """Seeded per-frame fault verdicts for the live TCP planes (the
    host-side analog of the netmodel's loss/partition masks; parity role:
    ``tc qdisc netem`` loss/delay/duplicate per veth in the reference's
    ``scripts/utils/net.py``).

    The spec is a plain dict (it rides a CtrlMsg through the manager):

    - ``mute``:  [peer, ...] — egress to these peers is silently dropped
                 (one half of a partition; asymmetric faults use only one
                 side's mute).
    - ``deaf``:  [peer, ...] — ingress from these peers is discarded.
    - ``drop``:  {peer or "*": prob} — iid per-frame egress loss.
    - ``dup``:   {peer or "*": prob} — per-frame egress duplication.
    - ``delay``: {peer or "*": seconds} — added one-way ingress delay
                 (applied in the per-peer receive thread, so per-link
                 FIFO order is preserved — a slow link, not reordering).
    - ``bw``:    bytes/second — token-bucket cap on TOTAL egress
                 bandwidth; the deficit is paid as a sleep in the
                 SENDER's tick loop (``TransportHub.send_tick``), so a
                 rate-limited NIC backpressures the host it sits in.
                 This is the fail-slow ``slow_peer`` host model: unlike
                 ``delay`` (which slows the LINK in the receiver's
                 messenger thread and leaves the sender at full speed),
                 a bandwidth cap limps the replica itself while it stays
                 alive enough to keep leases and leadership.
    - ``starve``: fraction in [0, 1) — CPU-starvation duty cycle: the
                 victim's send path sleeps ``f / (1 - f)`` times the
                 real work time elapsed since the last send, i.e. the
                 host only gets ``1 - f`` of the CPU.  Rides the same
                 ``slow_peer`` nemesis class as ``bw``.

    Verdict draws come from one seeded ``random.Random`` behind a lock:
    the verdict *sequence* is deterministic per (spec, seed), which is
    what makes a nemesis schedule a one-line repro; wall-clock
    interleaving with the replica's tick loop is the only nondeterminism
    left, exactly as with real netem.
    """

    def __init__(self, spec: Dict[str, Any], seed: int = 0):
        self.spec = dict(spec or {})
        self._mute = {int(p) for p in self.spec.get("mute", ())}
        self._deaf = {int(p) for p in self.spec.get("deaf", ())}
        self._drop = {
            str(k): float(v) for k, v in self.spec.get("drop", {}).items()
        }
        self._dup = {
            str(k): float(v) for k, v in self.spec.get("dup", {}).items()
        }
        self._delay = {
            str(k): float(v) for k, v in self.spec.get("delay", {}).items()
        }
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # fail-slow host faults (slow_peer): egress token bucket + CPU
        # starve duty cycle, both consulted by TransportHub.send_tick
        self._bw = float(self.spec.get("bw", 0.0) or 0.0)
        self._starve = min(0.95, max(
            0.0, float(self.spec.get("starve", 0.0) or 0.0)
        ))
        # gray, not dead: the per-call stall cap keeps the victim's
        # heartbeats landing inside its peers' election timeouts — the
        # whole point of fail-slow is a leader that LIMPS while holding
        # leadership, so the stall must slow the tick loop ~10-20x, not
        # freeze it (an unbounded token-bucket deficit would read as
        # fail-stop and the ordinary election machinery would mask it)
        self._stall_cap = float(self.spec.get("stall_cap", 0.04) or 0.04)
        self._tokens = self._bw  # one second of headroom at arm time
        self._t_last: Optional[float] = None
        self._last_stall = 0.0

    def host_stall(self, nbytes: int, now: float) -> float:
        """Seconds the SENDER must stall before putting ``nbytes`` more
        on the wire: the token-bucket deficit at the ``bw`` cap plus the
        CPU-starve share of the WORK time elapsed since the last call
        (the previously injected stall is subtracted out — feeding the
        sleep back into the duty cycle would compound exponentially and
        freeze the victim).  0.0 when neither knob is armed."""
        if self._bw <= 0.0 and self._starve <= 0.0:
            return 0.0
        stall = 0.0
        with self._lock:
            dt = 0.0 if self._t_last is None else max(0.0, now - self._t_last)
            self._t_last = now
            if self._bw > 0.0:
                # the bucket refills over the FULL elapsed time (real
                # seconds pass while the victim sleeps)
                self._tokens = min(
                    self._bw, self._tokens + dt * self._bw
                ) - float(nbytes)
                if self._tokens < 0.0:
                    stall += -self._tokens / self._bw
            if self._starve > 0.0:
                work = max(0.0, dt - self._last_stall)
                stall += work * self._starve / (1.0 - self._starve)
            stall = min(stall, self._stall_cap)
            if self._bw > 0.0:
                # deficit beyond what the capped stall repays is
                # forgiven, or it would accumulate into a freeze anyway
                self._tokens = max(self._tokens, -stall * self._bw)
            self._last_stall = stall
        return stall

    @staticmethod
    def _rate(table: Dict[str, float], peer: int) -> float:
        return table.get(str(peer), table.get("*", 0.0))

    def egress(self, peer: int) -> str:
        """Verdict for one outgoing frame: "drop" | "dup" | "send"."""
        if peer in self._mute:
            return "drop"
        p_drop = self._rate(self._drop, peer)
        p_dup = self._rate(self._dup, peer)
        if p_drop <= 0.0 and p_dup <= 0.0:
            return "send"
        with self._lock:
            u = self._rng.random()
        if u < p_drop:
            return "drop"
        if u < p_drop + p_dup:
            return "dup"
        return "send"

    def ingress_drop(self, peer: int) -> bool:
        return peer in self._deaf

    def ingress_delay(self, peer: int) -> float:
        return self._rate(self._delay, peer)


def encode_frame(obj: Any, codec: Optional[bool] = None) -> bytes:
    """One joined frame (8-byte BE length + body).  ``codec=None``
    follows the process default; the codec only ever engages for hot
    objects (``wirecodec.is_hot``) — everything else stays pickle."""
    if codec is None:
        codec = wirecodec.default_on()
    if codec and wirecodec.is_hot(obj):
        body = wirecodec.encode_body(obj)
    else:
        body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return _LEN.pack(len(body)) + body


def encode_frame_into(
    obj: Any, enc: "wirecodec.FrameEncoder", codec: Optional[bool] = None
) -> Tuple[List[Any], int]:
    """Encode one frame as a segment list for vectored egress: the
    8-byte length prefix, the encoder's small-field chunks, and
    zero-copy ndarray views.  Returns ``(segments, total_bytes)``.  The
    segments borrow ``enc``'s scratch and (for arrays) the frame's own
    buffers: send them (:func:`sendmsg_all`), then ``enc.release()``."""
    if codec is None:
        codec = wirecodec.default_on()
    if codec and wirecodec.is_hot(obj):
        segs, blen = enc.encode_frame_into(obj)
        return [_LEN.pack(blen)] + segs, 8 + blen
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return [_LEN.pack(len(body)), body], 8 + len(body)


def sendmsg_all(sock, segs: List[Any], total: int) -> None:
    """``sendall`` semantics over a segment list via ``socket.sendmsg``
    (scatter-gather): the whole frame leaves in one syscall in the
    common case, with no Python-side join copy; partial sends (signals,
    tiny socket buffers) resume from the exact byte."""
    sent = sock.sendmsg(segs[:_IOV_MAX])
    if sent >= total and len(segs) <= _IOV_MAX:
        return
    # slow path: advance past what left, retry the remainder
    idx = 0
    remaining = total - sent
    while sent > 0:
        n = len(segs[idx])
        if sent >= n:
            sent -= n
            idx += 1
        else:
            segs = list(segs)
            segs[idx] = memoryview(segs[idx])[sent:]
            sent = 0
    segs = segs[idx:]
    while remaining > 0:
        sent = sock.sendmsg(segs[:_IOV_MAX])
        remaining -= sent
        if remaining <= 0:
            return
        idx = 0
        while sent > 0:
            n = len(segs[idx])
            if sent >= n:
                sent -= n
                idx += 1
            else:
                segs[idx] = memoryview(segs[idx])[sent:]
                sent = 0
        segs = segs[idx:]


def encode_frame_bytes(
    obj: Any, enc: "wirecodec.FrameEncoder",
    codec: Optional[bool] = None,
) -> bytes:
    """One joined frame through a CALLER-OWNED encoder (hot loops that
    need bytes — asyncio writers — without the shared encoder's lock)."""
    segs, _total = encode_frame_into(obj, enc, codec=codec)
    try:
        return b"".join(
            s if type(s) is bytes else bytes(s) for s in segs
        )
    finally:
        enc.release()


async def send_msg(writer: asyncio.StreamWriter, obj: Any,
                   codec: Optional[bool] = None) -> None:
    writer.write(encode_frame(obj, codec=codec))
    await writer.drain()


async def recv_msg(reader: asyncio.StreamReader) -> Any:
    return (await recv_msg_timed(reader))[0]


async def recv_msg_timed(reader: asyncio.StreamReader) -> Tuple[Any, float]:
    """:func:`recv_msg` plus the decode-only wall seconds (the socket
    wait excluded) — feeds the ``wire_decode_us`` histograms."""
    import time

    hdr = await reader.readexactly(_LEN.size)
    (length,) = _LEN.unpack(hdr)
    if length > MAX_FRAME:
        raise SummersetError(f"frame length {length} exceeds cap {MAX_FRAME}")
    body = await reader.readexactly(length)
    t0 = time.monotonic()
    obj = wirecodec.decode_body(body)
    return obj, time.monotonic() - t0


def send_msg_sync(sock, obj: Any, codec: Optional[bool] = None) -> None:
    """Blocking-socket variant (CLI tools, ctrl planes, proxy hops)."""
    sock.sendall(encode_frame(obj, codec=codec))


def recv_msg_sync(sock) -> Any:
    return recv_msg_sync_len(sock)[0]


def _recv_exact_into(sock, view: memoryview, consumed_before: int) -> None:
    """Fill ``view`` with ``recv_into`` (no accumulation copies).

    Timeout semantics on timeout-armed sockets: ``socket.timeout``
    propagates ONLY when zero bytes of the frame were consumed — the
    stream is still frame-aligned and the caller may safely retry the
    recv in place.  A timeout after partial consumption raises
    :class:`SummersetError` instead: the next read would start mid-frame
    and decode garbage, so the caller must treat the connection as dead
    and reconnect (the ``DriverReply('disconnect')`` path in
    client/drivers.py)."""
    got = 0
    n = len(view)
    while got < n:
        try:
            k = sock.recv_into(view[got:])
        except TimeoutError:
            if consumed_before or got:
                raise SummersetError(
                    f"recv timed out mid-frame ({consumed_before + got} "
                    "bytes consumed): stream no longer frame-aligned"
                ) from None
            raise
        if not k:
            raise SummersetError("connection closed mid-frame")
        got += k


def recv_msg_sync_len(sock) -> Tuple[Any, int]:
    """Like :func:`recv_msg_sync` but also returns the frame body length
    (consumed by the Crossword adaptive perf model's delivery samples).
    One-shot form of :class:`FrameReceiver` (which hot loops hold to
    reuse the header buffer); timeout semantics in
    :func:`_recv_exact_into`."""
    return FrameReceiver().recv(sock)


class FrameReceiver:
    """Per-connection ingress state for a hot receive loop: a reusable
    length-prefix buffer plus exact-size body reads via ``recv_into``.
    Body buffers stay per-frame (decoded ndarray lanes are zero-copy
    views into them); only the 8-byte header is recycled."""

    __slots__ = ("_hdr", "_hdr_mv")

    def __init__(self):
        self._hdr = bytearray(_LEN.size)
        self._hdr_mv = memoryview(self._hdr)

    def recv_raw(self, sock) -> memoryview:
        """Receive one frame's body bytes (undecoded) — lets hot loops
        time the decode separately from the blocking socket wait."""
        _recv_exact_into(sock, self._hdr_mv, 0)
        (length,) = _LEN.unpack(self._hdr)
        if length > MAX_FRAME:
            raise SummersetError(
                f"frame length {length} exceeds cap {MAX_FRAME}"
            )
        body = bytearray(length)
        _recv_exact_into(sock, memoryview(body), _LEN.size)
        return memoryview(body)

    def recv(self, sock) -> Tuple[Any, int]:
        """Receive and decode one frame; returns ``(obj, body_len)``."""
        body = self.recv_raw(sock)
        return wirecodec.decode_body(body), len(body)


async def tcp_bind_with_retry(
    host: str, port: int, handler, retries: int = 10, delay: float = 0.2
) -> asyncio.base_events.Server:
    """Bind a TCP server, retrying on transient EADDRINUSE."""
    for attempt in range(retries + 1):
        try:
            return await asyncio.start_server(handler, host, port)
        except OSError as e:
            if e.errno != errno.EADDRINUSE or attempt == retries:
                raise
            await asyncio.sleep(delay)
    raise SummersetError("unreachable")


async def tcp_connect_with_retry(
    host: str, port: int, retries: int = 30, delay: float = 0.2
) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter]:
    """Connect to a TCP server, retrying while it comes up."""
    for attempt in range(retries + 1):
        try:
            return await asyncio.open_connection(host, port)
        except OSError:
            if attempt == retries:
                raise
            await asyncio.sleep(delay)
    raise SummersetError("unreachable")
