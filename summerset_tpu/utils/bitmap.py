"""Fixed-size bitmap keyed by replica ID, host class + device helpers.

Parity: reference ``src/utils/bitmap.rs:63-146`` (``Bitmap::new/set/get/
count/flip/union/iter``) — used for quorum ack tallies, peer-alive sets and
erasure-shard maps.

TPU-side, bitmaps over populations ≤ 32 are packed into ``uint32`` lanes so a
``[G, R, W]`` array of ack-sets is a single int array; quorum tally is
``lax.population_count``.  The device helpers here are thin, jit-friendly
functions over such packed lanes.
"""

from __future__ import annotations

from typing import Iterator, List

import jax
import jax.numpy as jnp

from .errors import SummersetError

MAX_POPULATION = 32  # packed into one uint32 lane on device


class Bitmap:
    """Host-side fixed-size bitset keyed by small integer replica IDs."""

    def __init__(self, size: int, ones: bool = False):
        if size <= 0:
            raise SummersetError(f"invalid bitmap size {size}")
        self._size = size
        self._bits: int = (1 << size) - 1 if ones else 0

    @classmethod
    def from_ids(cls, size: int, ids) -> "Bitmap":
        bm = cls(size)
        for i in ids:
            bm.set(i)
        return bm

    @classmethod
    def from_u32(cls, size: int, packed: int) -> "Bitmap":
        bm = cls(size)
        bm._bits = packed & ((1 << size) - 1)
        return bm

    @property
    def size(self) -> int:
        return self._size

    def _check(self, idx: int) -> None:
        if not 0 <= idx < self._size:
            raise SummersetError(f"index {idx} out of bound {self._size}")

    def set(self, idx: int) -> None:
        self._check(idx)
        self._bits |= 1 << idx

    def clear(self, idx: int) -> None:
        self._check(idx)
        self._bits &= ~(1 << idx)

    def get(self, idx: int) -> bool:
        self._check(idx)
        return bool(self._bits >> idx & 1)

    def count(self) -> int:
        return self._bits.bit_count()

    def flip(self) -> None:
        self._bits = ~self._bits & ((1 << self._size) - 1)

    def union(self, other: "Bitmap") -> None:
        if other._size != self._size:
            raise SummersetError("bitmap size mismatch")
        self._bits |= other._bits

    def clear_all(self) -> None:
        self._bits = 0

    def set_all(self) -> None:
        self._bits = (1 << self._size) - 1

    def iter_ones(self) -> Iterator[int]:
        for i in range(self._size):
            if self._bits >> i & 1:
                yield i

    def to_list(self) -> List[bool]:
        return [bool(self._bits >> i & 1) for i in range(self._size)]

    def to_u32(self) -> int:
        return self._bits

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Bitmap)
            and other._size == self._size
            and other._bits == self._bits
        )

    def __hash__(self) -> int:
        return hash((self._size, self._bits))

    def __repr__(self) -> str:
        return f"Bitmap({self._size}; {{{','.join(map(str, self.iter_ones()))}}})"


# ---------------------------------------------------------------------------
# Device-side packed-bitmap helpers (uint32 lanes, population <= 32)
# ---------------------------------------------------------------------------


def bits_full(population) -> jnp.ndarray:
    """All-ones mask for a population (jit-safe for static population)."""
    return jnp.uint32((1 << population) - 1)


def bit_of(idx) -> jnp.ndarray:
    """``1 << idx`` as uint32; idx may be a traced int array."""
    return jnp.left_shift(jnp.uint32(1), idx.astype(jnp.uint32) if hasattr(idx, "astype") else jnp.uint32(idx))


def bit_set(lane, idx):
    return jnp.bitwise_or(lane, bit_of(idx))


def bit_clear(lane, idx):
    return jnp.bitwise_and(lane, jnp.bitwise_not(bit_of(idx)))


def bit_get(lane, idx):
    return jnp.bitwise_and(jnp.right_shift(lane, idx), 1).astype(jnp.bool_)


def popcount(lane):
    """Set-bit count per lane element — the vectorized quorum tally."""
    return jax.lax.population_count(lane.astype(jnp.uint32)).astype(jnp.int32)
