"""Key-range maps and responders configuration.

Parity: reference ``src/utils/keyrange.rs`` — ``KeyRangeMap`` (rangemap-backed
map from key ranges to values, ``keyrange.rs:316``) and ``RespondersConf``
(``keyrange.rs:29``: a leader + per-key-range responder bitmaps with a config
ballot number), used by QuorumLeases / Bodega for conf changes and local-read
eligibility (``is_leader:72``, ``is_responder_by_key:79``,
``set_responders:125``).
"""

from __future__ import annotations

import bisect
from typing import Generic, List, Optional, Tuple, TypeVar

from .bitmap import Bitmap
from .errors import SummersetError

V = TypeVar("V")

# Keys are strings compared lexicographically; a range is [start, end) with
# end == None meaning unbounded.
KeyRange = Tuple[str, Optional[str]]


class KeyRangeMap(Generic[V]):
    """Map from disjoint half-open string-key ranges to values.

    Stored as a sorted list of (start, end, value); later inserts overwrite
    overlapped portions of earlier ranges (rangemap crate semantics).
    """

    def __init__(self):
        self._ranges: List[Tuple[str, Optional[str], V]] = []
        self._starts: List[str] = []  # parallel column for bisect lookups

    @staticmethod
    def _lt(a: Optional[str], b: Optional[str]) -> bool:
        """Compare range ends where None = +infinity."""
        if a is None:
            return False
        if b is None:
            return True
        return a < b

    def insert(self, start: str, end: Optional[str], value: V) -> None:
        if end is not None and end <= start:
            raise SummersetError(f"invalid key range [{start!r}, {end!r})")
        out: List[Tuple[str, Optional[str], V]] = []
        for s, e, v in self._ranges:
            # keep the non-overlapping parts of (s, e)
            if e is not None and e <= start:
                out.append((s, e, v))
                continue
            if end is not None and s >= end:
                out.append((s, e, v))
                continue
            # overlap: keep left sliver and/or right sliver
            if s < start:
                out.append((s, start, v))
            if end is not None and self._lt(end, e):
                out.append((end, e, v))
        out.append((start, end, value))
        out.sort(key=lambda t: t[0])
        self._ranges = out
        self._starts = [s for s, _, _ in out]

    def get(self, key: str) -> Optional[V]:
        i = bisect.bisect_right(self._starts, key) - 1
        if i < 0:
            return None
        s, e, v = self._ranges[i]
        if key >= s and (e is None or key < e):
            return v
        return None

    def full_range(self, value: V) -> None:
        """Reset to a single range covering all keys."""
        self._ranges = [("", None, value)]
        self._starts = [""]

    def items(self):
        return list(self._ranges)

    def __len__(self) -> int:
        return len(self._ranges)


class RespondersConf:
    """Leader + per-key-range responders, with a config number (ballot).

    Parity: ``RespondersConf`` (``keyrange.rs:29``).  The device analog packs
    the responder set of the (single) active range of each group into a uint32
    lane (see protocol kernels for Bodega/QuorumLeases); this host class keeps
    the general per-key-range form for the control plane.
    """

    def __init__(self, population: int):
        self.population = population
        self.leader: Optional[int] = None
        self._map: KeyRangeMap[Bitmap] = KeyRangeMap()
        self.conf_num: int = 0

    def is_leader(self, replica: int) -> bool:
        return self.leader == replica

    def set_leader(self, replica: Optional[int]) -> None:
        if replica is not None and not 0 <= replica < self.population:
            raise SummersetError(f"invalid leader id {replica}")
        self.leader = replica

    def set_responders(
        self, rng: Optional[KeyRange], responders: Bitmap, leader: Optional[int] = None
    ) -> None:
        if responders.size != self.population:
            raise SummersetError("responders bitmap size mismatch")
        if rng is None:
            self._map.full_range(responders)
        else:
            self._map.insert(rng[0], rng[1], responders)
        if leader is not None:
            self.set_leader(leader)

    def is_responder_by_key(self, key: str, replica: int) -> bool:
        bm = self._map.get(key)
        return bm.get(replica) if bm is not None else False

    def responders_for_key(self, key: str) -> Optional[Bitmap]:
        return self._map.get(key)

    def __repr__(self) -> str:
        return (
            f"RespondersConf(leader={self.leader}, conf_num={self.conf_num}, "
            f"ranges={len(self._map)})"
        )
