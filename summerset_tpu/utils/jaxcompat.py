"""jax version compatibility shims + the jax-free pre-backend helpers.

The repo targets the ``jax_num_cpu_devices`` config knob (jax >= 0.5) to
build the 8-device virtual CPU mesh the driver contract specifies; older
jax spells the same thing as an XLA flag that must be in the environment
before the CPU backend initializes.  Callers here all run before any
backend-initializing jax call, so the env-var fallback still takes effect.

This module is deliberately import-light (no jax at module scope):
drivers that must size the virtual CPU platform BEFORE anything
initializes the backend (importing ``summerset_tpu.core`` does, via
module-level device constants) import their helpers — including the
canonical ``parse_mesh`` grammar — from here.
"""

from __future__ import annotations

import os
from typing import Tuple


def parse_mesh(spec: str) -> Tuple[int, int]:
    """Parse a ``"GxR"`` mesh spec (e.g. ``"4x2"``) into
    ``(group_shards, replica_shards)``.

    THE one definition of the mesh-spec grammar — every driver's
    ``--mesh`` flag, the server's ``device_mesh`` knob, and
    ``core/sharding.py`` (which re-exports it) parse through here, so
    the accepted spelling cannot diverge.  Lives in this jax-free
    module because drivers must parse the spec before the backend
    initializes (to size the virtual CPU platform)."""
    parts = str(spec).lower().split("x")
    try:
        gs, rs = (int(p) for p in parts)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh spec {spec!r} is not of the form 'GxR' (e.g. '4x2': "
            "4 group shards x 2 replica shards)"
        ) from None
    if gs < 1 or rs < 1:
        raise ValueError(
            f"mesh spec {spec!r}: both axes must be >= 1"
        )
    return gs, rs


def set_cpu_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices, portably across jax versions.

    Must be called before the first backend-initializing jax operation.
    If the backend is already up this is a no-op — callers that care
    assert on ``len(jax.devices())`` afterwards.
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # jax < 0.5: env-var spelling
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n}"
        if "xla_force_host_platform_device_count" in flags:
            # replace a pre-existing (possibly different) count rather
            # than silently keeping it — mesh tests would otherwise fail
            # with opaque sharding errors under a stale preset
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
    except RuntimeError:
        pass  # backend already initialized; caller asserts device count
