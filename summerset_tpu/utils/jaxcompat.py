"""jax version compatibility shims.

The repo targets the ``jax_num_cpu_devices`` config knob (jax >= 0.5) to
build the 8-device virtual CPU mesh the driver contract specifies; older
jax spells the same thing as an XLA flag that must be in the environment
before the CPU backend initializes.  Callers here all run before any
backend-initializing jax call, so the env-var fallback still takes effect.
"""

from __future__ import annotations

import os


def set_cpu_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices, portably across jax versions.

    Must be called before the first backend-initializing jax operation.
    If the backend is already up this is a no-op — callers that care
    assert on ``len(jax.devices())`` afterwards.
    """
    import jax

    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # jax < 0.5: env-var spelling
        import re

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={n}"
        if "xla_force_host_platform_device_count" in flags:
            # replace a pre-existing (possibly different) count rather
            # than silently keeping it — mesh tests would otherwise fail
            # with opaque sharding errors under a stale preset
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", want, flags
            )
            os.environ["XLA_FLAGS"] = flags
        else:
            os.environ["XLA_FLAGS"] = (flags + " " + want).strip()
    except RuntimeError:
        pass  # backend already initialized; caller asserts device count
