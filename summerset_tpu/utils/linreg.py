"""Per-peer linear-regression performance model.

Parity: reference ``src/utils/linreg.rs`` — ``LinearRegressor`` accumulates
(payload size → delivery time) samples per peer and fits y = a + b*x
(``append_sample:97``, ``calc_model:137``); ``PerfModel::predict``
(``linreg.rs:56``) projects expected delivery time for a payload size.  Used
by Crossword's adaptive shard-assignment policy.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Optional, Tuple


class PerfModel:
    """y = interval + alpha + beta * x, with jitter allowance."""

    def __init__(self, interval_ms: float = 0.0, alpha: float = 0.0, beta: float = 0.0, jitter: float = 0.0):
        self.interval_ms = interval_ms
        self.alpha = alpha
        self.beta = beta
        self.jitter = jitter

    def update(self, alpha: float, beta: float) -> None:
        self.alpha = alpha
        self.beta = beta

    def predict(self, x: float) -> float:
        return self.interval_ms + self.alpha + self.beta * x + self.jitter

    def __repr__(self) -> str:
        return f"PerfModel({self.interval_ms}+{self.alpha}+{self.beta}*x~{self.jitter})"


class LinearRegressor:
    """Ordinary least squares over a sliding window of samples."""

    def __init__(self, window: int = 1000):
        self._samples: Deque[Tuple[float, float, float]] = deque(maxlen=window)

    def append_sample(self, t_ms: float, x: float, y: float) -> None:
        self._samples.append((t_ms, x, y))

    def discard_before(self, t_ms: float) -> None:
        while self._samples and self._samples[0][0] < t_ms:
            self._samples.popleft()

    def calc_model(self) -> Optional[Tuple[float, float]]:
        """Fit (alpha, beta); None if under-determined."""
        n = len(self._samples)
        if n < 2:
            return None
        xs = [s[1] for s in self._samples]
        ys = [s[2] for s in self._samples]
        mx = sum(xs) / n
        my = sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        if sxx == 0.0:
            return (my, 0.0)
        beta = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        alpha = my - beta * mx
        return (alpha, beta)
