"""Ingress-proxy process entry (serving-plane split, host/ingress.py).

Runs ONE stateless ingress proxy (+ optional learner read tier) as its
own OS process: registers with the manager, serves clients on the api
port, forwards batched ops to the owner shards.  The proxy never
touches an accelerator backend — a proxy host needs sockets and
pickle, nothing else — which is exactly the compartmentalization
claim: the client-facing tier scales on cheap frontend boxes while the
replica shards keep the accelerators.

Usage:
    python -m summerset_tpu.cli.proxy -m 127.0.0.1:52600 -a 52900 \
        [--forward-batch 64] [--no-read-tier]
"""

from __future__ import annotations

import argparse
import signal
import threading

from ..utils.logging import logger_init, pf_info, pf_logger

logger = pf_logger("proxy_main")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="summerset_tpu ingress proxy"
    )
    ap.add_argument("--bind-ip", default="127.0.0.1")
    ap.add_argument("-a", "--api-port", type=int, default=52900)
    ap.add_argument("-m", "--manager", default="127.0.0.1:52600")
    ap.add_argument("--max-batch", type=int, default=4096)
    ap.add_argument("--max-pending", type=int, default=1024)
    ap.add_argument("--forward-batch", type=int, default=64)
    ap.add_argument("--upstream-window", type=int, default=4)
    ap.add_argument("--backlog-limit", type=int, default=None)
    ap.add_argument("--tick-interval", type=float, default=0.001)
    ap.add_argument("--no-read-tier", action="store_true")
    args = ap.parse_args(argv)

    logger_init()
    mhost, mport = args.manager.rsplit(":", 1)

    from ..host.ingress import IngressProxy

    proxy = IngressProxy(
        (mhost, int(mport)),
        (args.bind_ip, args.api_port),
        max_batch=args.max_batch,
        max_pending=args.max_pending,
        forward_batch=args.forward_batch,
        upstream_window=args.upstream_window,
        backlog_limit=args.backlog_limit,
        tick_interval=args.tick_interval,
        read_tier=not args.no_read_tier,
    )
    pf_info(logger, f"proxy {proxy.cid} up @ "
                    f"{args.bind_ip}:{args.api_port}")
    done = threading.Event()

    def _stop(_sig, _frm) -> None:
        done.set()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    done.wait()
    proxy.stop()


if __name__ == "__main__":
    main()
