"""summerset_server analog (reference summerset_server/src/main.rs).

Config strings use ``a=1+b='x'`` with ``+`` -> newline like the reference
(main.rs:112), parsed by ``utils.config.parsed_config``.  The replica runs
in a crash-restart while loop: ``run()`` returning True restarts
(main.rs:127-160).
"""

from __future__ import annotations

import argparse
import os

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: tomli is API-compatible
    import tomli as tomllib

from ..utils.logging import logger_init, pf_info, pf_logger

logger = pf_logger("server_main")


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="summerset_tpu server replica")
    ap.add_argument("-p", "--protocol", default="MultiPaxos")
    ap.add_argument("--bind-ip", default="127.0.0.1")
    ap.add_argument("-a", "--api-port", type=int, default=52700)
    ap.add_argument("-i", "--p2p-port", type=int, default=52800)
    ap.add_argument("-m", "--manager", default="127.0.0.1:52600")
    ap.add_argument("-c", "--config", default="")
    ap.add_argument("-g", "--num-groups", type=int, default=1)
    ap.add_argument("--window", type=int, default=64)
    ap.add_argument("--tick-interval", type=float, default=0.002)
    ap.add_argument("--backer-dir", default="/tmp/summerset_tpu")
    args = ap.parse_args(argv)

    logger_init()
    mhost, mport = args.manager.rsplit(":", 1)
    cfg = (
        tomllib.loads(args.config.replace("+", "\n"))
        if args.config
        else {}
    )

    mesh_spec = str(cfg.get("device_mesh", "") or "")
    if mesh_spec and os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
        # grow the virtual CPU platform to the mesh size BEFORE the
        # ServerReplica import below initializes the backend (after
        # which the device count is locked and the mesh constructor can
        # only fail).  Parsed from the REAL config dict via the one
        # canonical grammar; harmless when a real accelerator backend
        # ends up selected (the host-platform count is CPU-only), and
        # on a real TPU host the devices simply exist.
        from ..utils.jaxcompat import parse_mesh, set_cpu_devices

        gs, rs = parse_mesh(mesh_spec)
        if gs * rs > 1:
            set_cpu_devices(gs * rs)

    from ..host.server import ServerReplica
    boot_fails = 0
    while True:
        try:
            replica = ServerReplica(
                args.protocol,
                (args.bind_ip, args.api_port),
                (args.bind_ip, args.p2p_port),
                (mhost, int(mport)),
                config=cfg,
                num_groups=args.num_groups,
                window=args.window,
                tick_interval=args.tick_interval,
                backer_dir=args.backer_dir,
            )
        except Exception as e:
            # transient bring-up failure (a peer mid-crash-restart, a
            # port still draining): retry a few times before giving up —
            # persistent errors (bad config) still surface
            boot_fails += 1
            if boot_fails > 5:
                raise
            pf_info(logger, f"bring-up failed: {e!r}; retrying "
                            f"({boot_fails}/5)")
            import time

            time.sleep(1.0)
            continue
        boot_fails = 0
        try:
            restart = replica.run()
        except Exception as e:
            # a crash (e.g. the durability gate refusing to ack past a
            # failed group-commit fsync) restarts like a supervised
            # process: recovery replays whatever reached the disk.  The
            # sleep keeps a persistently-crashing replica from
            # hot-looping through construct/crash cycles.
            pf_info(logger, f"replica crashed: {e!r}")
            try:
                # graftscope crash report: stamp the terminal marker and
                # log what this replica was doing in its final ticks
                replica.flight.record("crash", error=repr(e))
                for line in replica.flight.tail(12):
                    pf_info(logger, f"  flight: {line}")
            except Exception:
                pass
            restart = True
            import time

            time.sleep(0.5)
        replica.shutdown()
        if not restart:
            break
        pf_info(logger, "restarting replica (reset)")


if __name__ == "__main__":
    main()
