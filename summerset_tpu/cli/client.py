"""summerset_client analog (reference summerset_client/src/main.rs):
utility mode dispatch repl | bench | tester | mess."""

from __future__ import annotations

import argparse
import json

from ..client.bench import ClientBench
from ..client.endpoint import GenericEndpoint
from ..client.repl import ClientMess, ClientRepl
from ..client.tester import ClientTester
from ..utils.logging import logger_init


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="summerset_tpu client")
    ap.add_argument("-u", "--utility", default="repl",
                    choices=["repl", "bench", "tester", "mess"])
    ap.add_argument("-m", "--manager", default="127.0.0.1:52601")
    # bench knobs (parity: bench.rs CLI surface)
    ap.add_argument("--secs", type=float, default=10.0)
    ap.add_argument("--freq", type=float, default=0.0)
    ap.add_argument("--put-ratio", type=float, default=0.5)
    ap.add_argument("--value-size", default="128")
    ap.add_argument("--num-keys", type=int, default=5)
    ap.add_argument("--trace-file", default=None)  # YCSB run log replay
    # tester knobs
    ap.add_argument("--tests", default="")
    # mess knobs
    ap.add_argument("--pause", default=None)
    ap.add_argument("--resume", default=None)
    ap.add_argument("--write", default=None)  # key=value
    ap.add_argument("--responders", default=None)  # comma ids (conf)
    ap.add_argument("--leader", type=int, default=None)  # conf leader
    args = ap.parse_args(argv)

    logger_init()
    mhost, mport = args.manager.rsplit(":", 1)
    addr = (mhost, int(mport))

    if args.utility == "repl":
        ClientRepl(addr).run()
    elif args.utility == "bench":
        from ..client.bench import load_ycsb_trace

        ep = GenericEndpoint(addr)
        ep.connect()
        summary = ClientBench(
            ep,
            secs=args.secs,
            freq=args.freq,
            put_ratio=args.put_ratio,
            value_size=args.value_size,
            num_keys=args.num_keys,
            trace=(
                load_ycsb_trace(args.trace_file)
                if args.trace_file else None
            ),
        ).run()
        ep.leave()
        print(json.dumps(summary))
    elif args.utility == "tester":
        names = [t for t in args.tests.split(",") if t] or None
        results = ClientTester(addr).run_tests(names)
        print(json.dumps(results))
        if any(v != "PASS" for v in results.values()):
            raise SystemExit(1)
    elif args.utility == "mess":
        def parse_ids(s):
            if s is None:
                return None
            return [int(x) for x in s.split(",") if x] or []

        write = None
        if args.write:
            k, v = args.write.split("=", 1)
            write = (k, v)
        ClientMess(addr).run(
            pause=parse_ids(args.pause),
            resume=parse_ids(args.resume),
            write=write,
            responders=parse_ids(args.responders),
            leader=args.leader,
        )


if __name__ == "__main__":
    main()
