"""summerset_manager analog (reference summerset_manager/src/main.rs)."""

from __future__ import annotations

import argparse
import asyncio

from ..manager import ClusterManager
from ..utils.logging import logger_init


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="summerset_tpu cluster manager")
    ap.add_argument("-p", "--protocol", default="MultiPaxos")
    ap.add_argument("--bind-ip", default="127.0.0.1")
    ap.add_argument("--srv-port", type=int, default=52600)
    ap.add_argument("--cli-port", type=int, default=52601)
    ap.add_argument("-n", "--population", type=int, default=3)
    args = ap.parse_args(argv)

    logger_init()
    man = ClusterManager(
        args.protocol,
        (args.bind_ip, args.srv_port),
        (args.bind_ip, args.cli_port),
        args.population,
    )
    asyncio.run(man.run())


if __name__ == "__main__":
    main()
