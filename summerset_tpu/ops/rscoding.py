"""GF(2^8) Reed-Solomon erasure coding: batched device ops + Pallas kernel.

Parity target: reference ``src/utils/rscoding.rs`` (SURVEY.md §2.1
[NATIVE-HOT]) — the ``RSCodeword`` engine behind RSPaxos / CRaft /
Crossword: split serialized data into ``d`` data + ``p`` parity shards,
``compute_parity`` (``rscoding.rs:447``), ``reconstruct_data/all``
(``rscoding.rs:524-540``), ``verify_parity`` (``rscoding.rs:542``).  The
reference delegates to the ``reed-solomon-erasure`` crate's galois_8 SIMD
path; here the field arithmetic itself is re-designed for the TPU's VPU.

TPU-first design — **bit-sliced GF(2^8) matmul on int32 lanes**, no table
gathers: multiplying a byte ``x`` by a constant ``c`` in GF(2^8) is a
GF(2)-linear map, so ``c * x = XOR_{i: bit i of x set} (c * 2^i)``.  With 4
shard bytes packed per int32 lane, ``((x >> i) & 0x01010101) * tbl[c][i]``
replicates the precomputed byte ``c * 2^i`` into exactly the byte positions
whose ``i``-th bit is set (no cross-byte carries: indicator bytes are 0/1
and ``tbl`` bytes are < 256), so one parity shard is ``d * 8``
multiply-XOR vector ops — pure VPU work with zero dynamic indexing, the
shape XLA and Pallas both love.  The same path runs: (a) as plain jnp
(CPU tests / XLA fusion), (b) as a Pallas TPU kernel tiling the shard-byte
axis through VMEM, (c) for decoding, with rows of the inverted encode
submatrix (host-side GF Gauss-Jordan, cached per availability mask).

The encode matrix is systematic: identity over the data shards plus a
parity block from an extended Cauchy construction (guaranteed MDS: every
d x d submatrix of [I; C] is invertible), matching the reference's
"any d of d+p shards reconstruct" contract.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------- GF tables --
_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1, the classic RS polynomial


def _build_tables() -> Tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(512, np.int32)
    log = np.zeros(256, np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= _POLY
    exp[255:510] = exp[0:255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar GF(2^8) multiply (host)."""
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[GF_LOG[a] + GF_LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("gf_inv(0)")
    return int(GF_EXP[255 - GF_LOG[a]])


def gf_matmul_host(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """Host GF(2^8) matrix product (small matrices; reference oracle)."""
    n, k = A.shape
    k2, m = B.shape
    assert k == k2
    out = np.zeros((n, m), np.uint8)
    for i in range(n):
        for j in range(m):
            acc = 0
            for t in range(k):
                acc ^= gf_mul(int(A[i, t]), int(B[t, j]))
            out[i, j] = acc
    return out


def gf_inv_matrix_host(M: np.ndarray) -> np.ndarray:
    """Gauss-Jordan inversion over GF(2^8) (host, small d)."""
    d = M.shape[0]
    aug = np.concatenate(
        [M.astype(np.uint8), np.eye(d, dtype=np.uint8)], axis=1
    )
    for col in range(d):
        piv = next(
            (r for r in range(col, d) if aug[r, col] != 0), None
        )
        if piv is None:
            raise ValueError("singular matrix over GF(2^8)")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = [gf_mul(int(v), inv_p) for v in aug[col]]
        for r in range(d):
            if r != col and aug[r, col] != 0:
                f = int(aug[r, col])
                aug[r] ^= np.array(
                    [gf_mul(f, int(v)) for v in aug[col]], np.uint8
                )
    return aug[:, d:]


def build_encode_matrix(num_data: int, num_parity: int) -> np.ndarray:
    """Systematic [I; C] encode matrix, C an extended Cauchy parity block.

    C[i, j] = 1 / (x_i + y_j) with disjoint {x_i}, {y_j} — every square
    submatrix of a Cauchy matrix is nonsingular, so [I; C] is MDS.
    """
    if num_data + num_parity > 256:
        raise ValueError("d + p must be <= 256 for GF(2^8)")
    C = np.zeros((num_parity, num_data), np.uint8)
    for i in range(num_parity):
        for j in range(num_data):
            C[i, j] = gf_inv((num_data + i) ^ j)
    return np.concatenate([np.eye(num_data, dtype=np.uint8), C], axis=0)


# ------------------------------------------------- bit-sliced coefficients --
def _bitslice_coeffs(M: np.ndarray) -> np.ndarray:
    """[rows, cols] GF coeff matrix -> [rows, cols, 8] int32 table of
    ``M[r, c] * 2^i`` (each a byte), the per-bit contributions."""
    rows, cols = M.shape
    t = np.zeros((rows, cols, 8), np.int32)
    for r in range(rows):
        for c in range(cols):
            for i in range(8):
                t[r, c, i] = gf_mul(int(M[r, c]), 1 << i)
    return t


_LANE_ONES = 0x01010101  # per-byte LSB mask, a plain int so kernels see a literal


def _bitslice_matmul_jnp(tbl: jnp.ndarray, shards: jnp.ndarray) -> jnp.ndarray:
    """GF matmul via bit-slicing on packed int32 lanes.

    ``tbl``: [rows, cols, 8] int32 per-bit coefficient bytes.
    ``shards``: [..., cols, L] int32, 4 shard bytes per lane.
    Returns [..., rows, L] int32: ``out[r] = GF-XOR_c M[r,c] * shards[c]``.
    """
    rows, cols, _ = tbl.shape
    out = []
    for r in range(rows):
        acc = jnp.zeros(shards.shape[:-2] + shards.shape[-1:], jnp.int32)
        for c in range(cols):
            x = shards[..., c, :]
            for i in range(8):
                coeff = tbl[r, c, i]
                acc = acc ^ (((x >> i) & _LANE_ONES) * coeff)
        out.append(acc)
    return jnp.stack(out, axis=-2)


# -------------------------------------------------------------- Pallas path --
def _bitslice_kernel(tbl_ref, x_ref, o_ref, *, rows: int, cols: int):
    x = x_ref[0]  # block [1, cols, TL] -> [cols, TL]
    for r in range(rows):
        acc = jnp.zeros(x.shape[-1:], jnp.int32)
        for c in range(cols):
            xc = x[c]
            for i in range(8):
                acc = acc ^ (((xc >> i) & _LANE_ONES) * tbl_ref[r, c, i])
        o_ref[0, r, :] = acc


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _bitslice_matmul_pallas(tbl, shards, rows, cols, tile):
    """Pallas TPU kernel: grid over batch x shard-length tiles; the small
    coefficient table rides along in SMEM-adjacent VMEM per block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    B, C, L = shards.shape
    grid = (B, L // tile)
    return pl.pallas_call(
        functools.partial(_bitslice_kernel, rows=rows, cols=cols),
        out_shape=jax.ShapeDtypeStruct((B, rows, L), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, cols, 8), lambda b, l: (0, 0, 0)),
            pl.BlockSpec((1, cols, tile), lambda b, l: (b, 0, l)),
        ],
        out_specs=pl.BlockSpec((1, rows, tile), lambda b, l: (b, 0, l)),
    )(tbl, shards)


# ------------------------------------------------------------------- RSCode --
class RSCode:
    """Batched GF(2^8) Reed-Solomon codec for scheme ``(d, p)``.

    Shards are ``[..., shard, L]`` int32 arrays holding 4 packed bytes per
    lane (shard byte length = 4 * L).  ``use_pallas=None`` auto-selects the
    Pallas kernel on TPU backends and plain jnp elsewhere.
    """

    def __init__(self, num_data: int, num_parity: int,
                 use_pallas: bool | None = None):
        self.d = num_data
        self.p = num_parity
        self.matrix = build_encode_matrix(num_data, num_parity)
        self._parity_tbl = jnp.asarray(
            _bitslice_coeffs(self.matrix[num_data:])
        )
        if use_pallas is None:
            use_pallas = jax.default_backend() == "tpu"
        self.use_pallas = use_pallas
        self._decode_cache = {}

    # -- encode ----------------------------------------------------------
    def compute_parity(self, data: jnp.ndarray) -> jnp.ndarray:
        """[..., d, L] int32 -> [..., p, L] parity shards
        (parity: ``RSCodeword::compute_parity``, ``rscoding.rs:447``)."""
        if self.p == 0:
            return data[..., :0, :]
        if self.use_pallas and data.ndim == 3 and data.shape[-1] % 128 == 0:
            # lane-aligned blocks only; anything else takes the jnp path
            return _bitslice_matmul_pallas(
                self._parity_tbl, data, self.p, self.d, 128
            )
        return _bitslice_matmul_jnp(self._parity_tbl, data)

    # -- verify ----------------------------------------------------------
    def verify_parity(self, data: jnp.ndarray, parity: jnp.ndarray):
        """Recompute and compare: [...] bool per codeword
        (parity: ``rscoding.rs:542``)."""
        want = self.compute_parity(data)
        return (want == parity).all(axis=(-2, -1))

    # -- decode ----------------------------------------------------------
    def _decode_tbl(self, present: Tuple[int, ...]) -> jnp.ndarray:
        """Decode table mapping d present shards -> d data shards."""
        key = tuple(present)
        if key not in self._decode_cache:
            if len(key) != self.d:
                raise ValueError(f"need exactly {self.d} present shards")
            sub = self.matrix[list(key)]  # [d, d]
            inv = gf_inv_matrix_host(sub)
            self._decode_cache[key] = jnp.asarray(_bitslice_coeffs(inv))
        return self._decode_cache[key]

    def reconstruct_data(
        self, shards: jnp.ndarray, present: Tuple[int, ...]
    ) -> jnp.ndarray:
        """Recover the d data shards from any d available shards.

        ``shards``: [..., d, L] where axis -2 indexes the ``present`` shard
        ids (in that order); ``present`` is a static tuple of shard indices
        into the full d+p codeword (parity: ``rscoding.rs:532``).
        """
        if shards.shape[-2] != self.d:
            raise ValueError(
                f"shards axis -2 must hold exactly the {self.d} present "
                f"shards (got {shards.shape[-2]})"
            )
        tbl = self._decode_tbl(tuple(present))
        return _bitslice_matmul_jnp(tbl, shards)

    def reconstruct_all(
        self, shards: jnp.ndarray, present: Tuple[int, ...]
    ) -> jnp.ndarray:
        """Data + parity from any d shards (parity: ``rscoding.rs:524``)."""
        data = self.reconstruct_data(shards, present)
        parity = self.compute_parity(data)
        return jnp.concatenate([data, parity], axis=-2)


# ----------------------------------------------------------- byte utilities --
def pack_bytes(buf: bytes, num_data: int, lane_multiple: int = 1
               ) -> np.ndarray:
    """Split a byte string into d equal shards, packed [d, L] int32
    (zero-padded; shard byte length rounded up to a multiple of 4;
    little-endian byte order within each lane).  ``lane_multiple`` rounds
    the lane count L up further (128 keeps the Pallas encode path's
    lane-aligned tiling eligible on TPU backends)."""
    shard_len = -(-len(buf) // num_data)
    shard_len = -(-shard_len // 4) * 4
    if lane_multiple > 1:
        q = 4 * lane_multiple
        shard_len = -(-shard_len // q) * q
    padded = np.zeros(num_data * shard_len, np.uint8)
    padded[: len(buf)] = np.frombuffer(buf, np.uint8)
    return (
        padded.reshape(num_data, shard_len // 4, 4)
        .view("<u4")[..., 0]
        .view(np.int32)
        .copy()
    )


def unpack_bytes(shards: np.ndarray, data_len: int) -> bytes:
    """Inverse of :func:`pack_bytes` given the original byte length."""
    u = np.ascontiguousarray(np.asarray(shards), dtype="<i4")
    return u.view(np.uint8).reshape(-1).tobytes()[:data_len]


# -------------------------------------------------- serving entry points --
# The host data plane (host/codeword.py) ships one serialized ReqBatch per
# consensus value; these helpers are the serving-shape adapters between
# byte strings and the codec's [shard, L] int32 lane layout.  On TPU
# backends the batch dim + 128-lane alignment keep encode on the Pallas
# kernel; on CPU the same call lowers to the XLA bit-slice path.

def encode_payload(code: RSCode, buf: bytes) -> Tuple[int, np.ndarray]:
    """Serialized payload -> ``(data_len, [d + p, L] int32 codeword)``.

    The returned codeword rows are the full shard set: rows ``[0, d)``
    are the (padded) data split, rows ``[d, d + p)`` the parity shards —
    any ``d`` of them reconstruct the payload (``decode_payload``)."""
    lane = 128 if code.use_pallas else 1
    data = pack_bytes(buf, code.d, lane_multiple=lane)
    if code.p == 0:
        return len(buf), data
    parity = np.asarray(code.compute_parity(jnp.asarray(data)[None])[0])
    return len(buf), np.concatenate([data, parity], axis=0)


def decode_rows(code: RSCode, shards: dict) -> np.ndarray:
    """Any ``d`` held shards ``{shard id: [L] int32}`` -> the ``d`` data
    shard rows ``[d, L]`` at the encoder's exact lane geometry.

    Prefers data-shard identity rows (no GF work when rows ``[0, d)`` are
    all held); otherwise inverts the availability submatrix through the
    codec's cached decode tables (``RSCode.reconstruct_data``)."""
    d = code.d
    if len(shards) < d:
        raise ValueError(f"need {d} shards, have {len(shards)}")
    if all(i in shards for i in range(d)):
        return np.stack([np.asarray(shards[i]) for i in range(d)])
    present = tuple(sorted(shards))[:d]
    stacked = np.stack([np.asarray(shards[i]) for i in present])
    return np.asarray(code.reconstruct_data(jnp.asarray(stacked), present))


def decode_payload(code: RSCode, shards: dict, data_len: int) -> bytes:
    """Any ``d`` held shards ``{shard id: [L] int32}`` -> payload bytes."""
    return unpack_bytes(decode_rows(code, shards), data_len)
