"""Device kernels and low-level op helpers (Pallas GF(2^8) RS coding, PRNG)."""
