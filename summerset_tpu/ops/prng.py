"""Cheap per-(group, replica) counter-based PRNG for timeout jitter.

Parity: the reference randomizes per-peer hear-timeouts from a configured
range (``src/server/heartbeat.rs:96-116``); in the lockstep design every
(group, replica) carries a uint32 LCG state advanced inside the jitted step,
so elections de-synchronize across the batch without host involvement.

A full counter-based Threefry (jax.random) would be overkill here: jitter
quality requirements are "don't let all replicas time out on the same tick",
which a 32-bit LCG with multiplier 1664525 (Numerical Recipes) satisfies at
~4 ops per draw on the VPU.
"""

from __future__ import annotations

import jax.numpy as jnp

_MULT = jnp.uint32(1664525)
_INC = jnp.uint32(1013904223)


def seed_state(seed: int, shape) -> jnp.ndarray:
    """Deterministic distinct uint32 seeds for an array of generators."""
    n = 1
    for d in shape:
        n *= d
    base = jnp.arange(n, dtype=jnp.uint32).reshape(shape)
    return (base * jnp.uint32(2654435761) + jnp.uint32(seed)) | jnp.uint32(1)


def lcg_next(state: jnp.ndarray) -> jnp.ndarray:
    return state * _MULT + _INC


def uniform_int(state: jnp.ndarray, lo, hi):
    """Draw ints in [lo, hi) elementwise; returns (new_state, draws).

    ``lo``/``hi`` may be scalars or arrays broadcastable to ``state.shape``.
    Uses the high-entropy upper bits of the LCG state.
    """
    nxt = lcg_next(state)
    span = jnp.asarray(hi - lo, jnp.uint32)
    draw = (nxt >> jnp.uint32(8)) % jnp.maximum(span, jnp.uint32(1))
    return nxt, (jnp.asarray(lo, jnp.int32) + draw.astype(jnp.int32))


def uniform_unit(state: jnp.ndarray):
    """Draw floats in [0, 1); returns (new_state, draws)."""
    nxt = lcg_next(state)
    return nxt, (nxt >> jnp.uint32(8)).astype(jnp.float32) * (1.0 / (1 << 24))
