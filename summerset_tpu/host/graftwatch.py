"""graftwatch: the fleet-wide windowed time-series + SLO burn-rate
plane over the ctrl channel.

Every observability surface before this one was a point-in-time scrape
(``metrics_dump``) or an end-of-run dump (flight rings, soak
artifacts) — nobody could answer "what did p99 do DURING the nemesis
window on replica 2".  graftwatch closes that gap with three pieces:

- :class:`WatchEmitter` (server side): every ``watch_ticks`` ticks the
  replica diffs its :class:`~summerset_tpu.host.telemetry.MetricsRegistry`
  against the previous emit (one ``export_raw`` lock hold) and ships a
  compact DELTA frame over the existing ctrl connection as a one-way
  ``CtrlMsg("watch_frame", ...)`` — counter deltas, gauge values, and
  histogram WINDOW snapshots (bucket deltas via ``Histogram.since``,
  so windowed quantiles come for free).  Frames are indexed by
  ``widx = tick // span_ticks`` — the tick counter, never wallclock —
  so every replica's window n means "its ticks [n*span, (n+1)*span)"
  and fleet alignment needs no clock agreement (graftlint H103 holds
  for this module like every other host plane).

- :class:`FleetSeries` (manager side): a bounded per-``(sid, tier,
  group)`` ring of the frames each server shipped, aligned by widx,
  with a deterministic JSON export.  ``clusman`` ingests frames
  exactly like the other one-way ctrl kinds and serves the ring to
  clients via ``CtrlRequest("watch_series")`` — the data source for
  ``scripts/fleet_top.py``, the autopilot's burn senses, and the
  committed per-phase windows in ``SLO.json``.

- :class:`SloPolicy`: declared objectives (reply p99, shed rate, WAL
  fsync lag, scan starvation) evaluated with SRE-style multi-window
  burn rates.  Per window, each objective turns its slice of the
  fleet's deltas into an error rate (fraction of latency samples over
  the threshold — ``Histogram.frac_over`` — or a bad/total counter
  ratio); ``burn = error_rate / error_budget``.  A fast mean (last
  ``fast_windows``) catches cliffs, a slow mean (last
  ``slow_windows``) filters blips; the alert latches when BOTH clear
  ``burn_hi`` and un-latches when the fast mean drops below
  ``burn_clear``.  Evaluation is a pure fold over frames — the same
  code scores a live fleet and the committed SLO.json windows.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .telemetry import Histogram, MetricsRegistry

#: frame schema version (committed SLO.json embeds frames; bump on
#: field renames, append freely)
FRAME_VERSION = 1

#: the default declared objectives — each is (error rate)/(budget) per
#: window.  ``kind=quantile``: error rate is the fraction of the
#: window's histogram samples above ``threshold_us`` and the budget is
#: ``1 - q`` (e.g. p99 => 1% of samples may exceed the threshold).
#: ``kind=ratio``: error rate is ``num/den`` counter deltas and the
#: budget is explicit.  Thresholds are deliberately loose defaults for
#: the CI-scale localhost fleet; soaks override per artifact.
DEFAULT_OBJECTIVES = (
    {
        "name": "reply_p99", "kind": "quantile",
        "metric": "api_request_latency_us", "q": 0.99,
        "threshold_us": 250_000,
    },
    {
        "name": "shed_rate", "kind": "ratio",
        "num": "api_shed", "den": "api_requests_total",
        "budget": 0.05,
    },
    {
        "name": "wal_fsync_lag", "kind": "quantile",
        "metric": "wal_fsync_us", "q": 0.99,
        "threshold_us": 500_000,
    },
    {
        # starved scans / all scans: den is served-only, so the num is
        # folded back in (shed + served = attempted)
        "name": "scan_starvation", "kind": "ratio",
        "num": "scan_shed", "den": "scan_served",
        "den_excludes_num": True,
        "budget": 0.05,
    },
)


def base_name(key: str) -> str:
    """Strip the ``{label=...}`` suffix off a registry key."""
    return key.split("{", 1)[0]


# ---------------------------------------------------------------- emitter --
class WatchEmitter:
    """Server-side delta-frame builder.

    Holds the previous ``export_raw`` state; :meth:`frame` diffs the
    registry against it and returns one JSON-able delta frame.  The
    caller (the replica tick loop) owns cadence and shipping — the
    emitter never touches sockets, so it is trivially testable and the
    overhead ablation can flip it off by simply not calling it.
    """

    def __init__(self, registry: MetricsRegistry, me: int,
                 span_ticks: int = 50, tier: str = "shard",
                 group: int = 0):
        self.registry = registry
        self.me = int(me)
        self.span_ticks = max(1, int(span_ticks))
        self.tier = str(tier)
        self.group = int(group)
        self._prev_counters: Dict[str, int] = {}
        self._prev_hists: Dict[str, Histogram] = {}
        self.frames_emitted = 0

    def frame(self, tick: int) -> Dict[str, Any]:
        """Build the delta frame for the window ending at ``tick``.

        Counters ship as deltas (zero deltas elided), gauges as values,
        histograms as window snapshots (only windows that actually saw
        samples).  The first frame is the delta against an empty
        registry, i.e. the cumulative state — merging every frame of a
        series reproduces the registry, which is what makes the stream
        lossless for downstream accounting.
        """
        counters, gauges, hists = self.registry.export_raw()
        c_delta = {}
        for k, v in counters.items():
            d = v - self._prev_counters.get(k, 0)
            if d:
                c_delta[k] = d
        h_delta = {}
        for k, h in hists.items():
            win = h.since(self._prev_hists.get(k))
            if win.count > 0:
                h_delta[k] = win.snapshot()
        self._prev_counters = counters
        self._prev_hists = hists
        self.frames_emitted += 1
        return {
            "v": FRAME_VERSION,
            "sid": self.me,
            "tier": self.tier,
            "group": self.group,
            "widx": int(tick) // self.span_ticks,
            "tick": int(tick),
            "span_ticks": self.span_ticks,
            "counters": {k: c_delta[k] for k in sorted(c_delta)},
            "gauges": {k: gauges[k] for k in sorted(gauges)},
            "hists": {k: h_delta[k] for k in sorted(h_delta)},
        }


# ----------------------------------------------------------- fleet series --
class FleetSeries:
    """Manager-side bounded ring of per-server delta frames.

    Keyed ``(sid, tier, group)``; each key retains the newest
    ``retain`` frames.  Ingest is append-only and tolerant (a frame
    from an unknown/old schema is kept as-is — consumers filter by
    ``v``); export is deterministic (sorted keys, frames in arrival
    order, which per key is widx order because each server emits
    monotonically).  Thread-safe: clusman ingests on the asyncio loop
    while gate scripts may export from another thread.
    """

    def __init__(self, retain: int = 256):
        self.retain = max(8, int(retain))
        self._lock = threading.Lock()
        self._rings: Dict[Tuple[int, str, int], deque] = {}
        self.frames_ingested = 0

    def ingest(self, sid: int, frame: Dict[str, Any]) -> None:
        if not isinstance(frame, dict):
            return
        key = (
            int(sid),
            str(frame.get("tier", "shard")),
            int(frame.get("group", 0)),
        )
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = deque(maxlen=self.retain)
            ring.append(frame)
            self.frames_ingested += 1

    def export(self) -> Dict[str, Any]:
        """Deterministic JSON-able dump of the retained fleet series."""
        with self._lock:
            keys = sorted(self._rings)
            return {
                "v": FRAME_VERSION,
                "retain": self.retain,
                "frames_ingested": self.frames_ingested,
                "series": [
                    {
                        "sid": sid, "tier": tier, "group": group,
                        "frames": list(self._rings[(sid, tier, group)]),
                    }
                    for sid, tier, group in keys
                ],
            }

    def sids(self) -> List[int]:
        with self._lock:
            return sorted({sid for sid, _, _ in self._rings})


def windows(export: Dict[str, Any],
            tier: Optional[str] = None) -> List[Dict[str, Any]]:
    """Fold a :meth:`FleetSeries.export` doc into per-widx fleet
    windows, each the MERGE of every server's frame for that widx:
    counter deltas summed, histograms merged (``Histogram.merge`` over
    ``from_snapshot``), gauges kept per-sid.  Returns windows sorted by
    widx; each carries the contributing sids so partial windows (a
    crashed replica's missing frame) are visible, not silent.
    """
    acc: Dict[int, Dict[str, Any]] = {}
    for s in export.get("series", []):
        if tier is not None and s.get("tier") != tier:
            continue
        for fr in s.get("frames", []):
            w = acc.setdefault(int(fr.get("widx", 0)), {
                "widx": int(fr.get("widx", 0)),
                "span_ticks": int(fr.get("span_ticks", 1)),
                "sids": [],
                "counters": {},
                "gauges": {},
                "_hists": {},
            })
            sid = int(fr.get("sid", s.get("sid", -1)))
            if sid not in w["sids"]:
                w["sids"].append(sid)
            for k, d in (fr.get("counters") or {}).items():
                b = base_name(k)
                w["counters"][b] = w["counters"].get(b, 0) + int(d)
            for k, v in (fr.get("gauges") or {}).items():
                w["gauges"].setdefault(base_name(k), {})[sid] = v
            for k, snap in (fr.get("hists") or {}).items():
                b = base_name(k)
                h = w["_hists"].get(b)
                win = Histogram.from_snapshot(snap)
                if h is None:
                    w["_hists"][b] = win
                else:
                    h.merge(win)
    out = []
    for widx in sorted(acc):
        w = acc[widx]
        w["sids"].sort()
        w["hists"] = {k: w["_hists"][k] for k in sorted(w["_hists"])}
        del w["_hists"]
        out.append(w)
    return out


# ------------------------------------------------------------- SLO policy --
class SloPolicy:
    """Multi-window burn-rate evaluation over fleet windows.

    Feed windows in widx order via :meth:`observe_window`; read
    :meth:`status` (or the per-window rows it appends to
    :attr:`history`).  Stateless alternative: :func:`evaluate_series`
    folds a whole export in one call — the committed-artifact path.
    """

    def __init__(self, objectives=DEFAULT_OBJECTIVES,
                 fast_windows: int = 3, slow_windows: int = 12,
                 burn_hi: float = 2.0, burn_clear: float = 1.0):
        self.objectives = [dict(o) for o in objectives]
        self.fast_windows = max(1, int(fast_windows))
        self.slow_windows = max(self.fast_windows, int(slow_windows))
        self.burn_hi = float(burn_hi)
        self.burn_clear = float(burn_clear)
        self._burns: Dict[str, deque] = {
            o["name"]: deque(maxlen=self.slow_windows)
            for o in self.objectives
        }
        self._alerting: Dict[str, bool] = {
            o["name"]: False for o in self.objectives
        }
        self.history: List[Dict[str, Any]] = []
        self.n_windows = 0

    # -- per-objective window error rate ------------------------------------
    @staticmethod
    def window_burn(obj: Dict[str, Any], window: Dict[str, Any]) -> float:
        """One objective's burn rate over one fleet window.  A window
        with no relevant activity burns 0 (no samples => no errors)."""
        if obj["kind"] == "quantile":
            h = window.get("hists", {}).get(obj["metric"])
            if h is None or h.count == 0:
                return 0.0
            err = h.frac_over(int(obj["threshold_us"]))
            budget = max(1e-9, 1.0 - float(obj["q"]))
            return err / budget
        if obj["kind"] == "ratio":
            num = int(window.get("counters", {}).get(obj["num"], 0))
            den = int(window.get("counters", {}).get(obj["den"], 0))
            den += num if obj.get("den_excludes_num") else 0
            if den <= 0:
                return 0.0
            err = num / den
            return err / max(1e-9, float(obj["budget"]))
        raise ValueError(f"unknown objective kind: {obj['kind']!r}")

    def observe_window(self, window: Dict[str, Any]) -> Dict[str, Any]:
        """Score one fleet window; returns (and records) the per-
        objective row {burn, fast, slow, alerting}."""
        self.n_windows += 1
        row: Dict[str, Any] = {"widx": window.get("widx")}
        for obj in self.objectives:
            name = obj["name"]
            burn = self.window_burn(obj, window)
            burns = self._burns[name]
            burns.append(burn)
            recent = list(burns)
            fast = sum(recent[-self.fast_windows:]) / min(
                len(recent), self.fast_windows
            )
            slow = sum(recent) / len(recent)
            if fast >= self.burn_hi and slow >= self.burn_hi:
                self._alerting[name] = True
            elif fast < self.burn_clear:
                self._alerting[name] = False
            row[name] = {
                "burn": round(burn, 4),
                "fast": round(fast, 4),
                "slow": round(slow, 4),
                "alerting": self._alerting[name],
            }
        self.history.append(row)
        return row

    def status(self) -> Dict[str, Any]:
        """The latest per-objective verdicts (empty before any window).
        This is the autopilot's ``slo_burn`` sense payload."""
        if not self.history:
            return {}
        latest = self.history[-1]
        return {
            o["name"]: latest[o["name"]] for o in self.objectives
        }


def evaluate_series(export: Dict[str, Any],
                    objectives=DEFAULT_OBJECTIVES,
                    tier: Optional[str] = None,
                    **policy_kw) -> Dict[str, Any]:
    """Fold a whole FleetSeries export through an :class:`SloPolicy` —
    the deterministic re-derivation path the SLO.json gate uses (same
    frames in => same verdicts out, no wallclock anywhere)."""
    pol = SloPolicy(objectives=objectives, **policy_kw)
    for w in windows(export, tier=tier):
        pol.observe_window(w)
    return {
        "n_windows": pol.n_windows,
        "status": pol.status(),
        "history": pol.history,
    }
