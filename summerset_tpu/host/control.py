"""ControlHub: the server's channel to the cluster manager.

Parity: reference ``src/server/control.rs`` — connect to the manager, read
the assigned ``(id, population)`` handshake, then exchange framed
``CtrlMsg``s through send/recv queues owned by a messenger task
(control.rs:19-252).  Deviation: the handshake rides a normal frame rather
than 2 raw bytes (symmetric framing everywhere).
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Optional, Tuple

from ..utils import safetcp
from ..utils.errors import SummersetError
from ..utils.logging import pf_logger, set_me
from .messages import CtrlMsg

logger = pf_logger("control")


class ControlHub:
    def __init__(self, manager_addr: Tuple[str, int], timeout: float = 15.0):
        # handshake with retry: during a crash-restart the manager may not
        # have reaped our old connection yet, in which case it finds no
        # free id and closes the fresh connection — retry until it does
        # (reference servers retry manager connects too, control.rs:43)
        import time

        deadline = time.monotonic() + 60.0
        self.sock = None
        while True:
            try:
                self.sock = socket.create_connection(
                    manager_addr, timeout=timeout
                )
                self.sock.settimeout(timeout)
                me_id, population = safetcp.recv_msg_sync(self.sock)
                self.sock.settimeout(None)
                break
            except (OSError, EOFError, SummersetError):
                if self.sock is not None:
                    try:
                        self.sock.close()
                    except OSError:
                        pass
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        self.me: int = int(me_id)
        self.population: int = int(population)
        set_me(str(self.me))
        self._recv: queue.Queue = queue.Queue()
        self._alive = True
        self._reader = threading.Thread(target=self._recv_loop, daemon=True)
        self._reader.start()
        self._wlock = threading.Lock()

    def send_ctrl(self, msg: CtrlMsg) -> None:
        # graftlint: disable=H101 -- per-socket writer serialization is this lock's whole job: concurrent send_ctrl callers must not interleave frame bytes on the one manager socket
        with self._wlock:
            safetcp.send_msg_sync(self.sock, msg)

    def recv_ctrl(self, timeout: Optional[float] = None) -> CtrlMsg:
        msg = self._recv.get(timeout=timeout)
        if msg is None:
            raise SummersetError("manager connection closed")
        return msg

    def try_recv_ctrl(self) -> Optional[CtrlMsg]:
        try:
            msg = self._recv.get_nowait()
        except queue.Empty:
            return None
        if msg is None:
            raise SummersetError("manager connection closed")
        return msg

    def close(self) -> None:
        self._alive = False
        # shutdown() BEFORE close(): the reader thread is blocked in
        # recv() holding a reference to the file description, so a bare
        # close() only drops our fd — no FIN is ever sent and the
        # manager keeps the dead connection (and our id!) forever,
        # wedging every rejoin attempt of a self-crashed replica in the
        # handshake retry loop.  shutdown() tears the connection down
        # immediately regardless of the concurrent recv.
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_loop(self) -> None:
        try:
            while self._alive:
                self._recv.put(safetcp.recv_msg_sync(self.sock))
        except Exception:
            self._recv.put(None)
