"""CodewordStore: the host-side sharded codeword payload plane.

Parity: reference ``RSCodeword`` storage inside RSPaxos / CRaft /
Crossword instances (``rspaxos/mod.rs:597-608``: the leader erasure-codes
each request batch and sends replica ``r`` only its assigned shard
subset; ``crossword/gossiping.rs:14-193``: followers fill missing shards
off the critical path; ``rspaxos/leadership.rs:142-165``: committed-but-
shard-starved replicas issue Reconstruct reads answered from held
shards).

TPU-native split: the device kernels track shard *availability* (vote
runs, ``full_bar`` gating, RECON_REQ/RECON_REPLY cover frontiers) over
int32 value references; this store owns the actual shard bytes keyed by
``(group, value id)``:

- **encode-once caching**: the proposer serializes a ReqBatch once and
  encodes it through :class:`~summerset_tpu.ops.rscoding.RSCode` (Pallas
  bit-sliced GF(2^8) on TPU, XLA bit-slice on CPU) into the full
  ``[T, L]`` codeword; per-peer sends are row slices of that cache.
- **availability bitmaps**: one int mask over the ``T`` shard ids per
  value, maintained on every ingest — the host analog of the kernel's
  per-slot shard-holder tallies.
- **reconstruct integration**: once ``d`` distinct shards are held,
  ``reconstruct_batch`` decodes back to the request batch (and restores
  the full codeword via ``reconstruct_all`` so the replica can serve any
  shard id in later gossip rounds — what a new leader needs before
  re-distributing adopted slots under its own assignment).

Assignment geometry (balanced diagonal family, ``adaptive.rs:44-67``):
replica ``r`` owns base shards ``[r * dj, (r + 1) * dj) mod T``; a width-
``spr`` assignment extends that run to ``spr`` shards.  RSPaxos/CRaft are
the ``dj = 1, T = R, spr = 1`` degenerate case (shard ``r`` -> replica
``r``).
"""

from __future__ import annotations

import pickle
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..ops.rscoding import (
    RSCode,
    decode_rows,
    encode_payload,
    unpack_bytes,
)


def assigned_sids(replica: int, spr: int, dj: int, total: int
                  ) -> Tuple[int, ...]:
    """Shard ids assigned to ``replica`` under a width-``spr`` balanced
    diagonal assignment (``crossword/adaptive.rs:44-67``)."""
    return tuple((replica * dj + k) % total for k in range(spr))


class CodewordStore:
    """Per-(group, value-id) RS shard maps with availability bitmaps."""

    def __init__(self, num_groups: int, code: RSCode, total: int,
                 dj: int = 1):
        self.code = code
        self.d = code.d
        self.T = total
        self.dj = dj
        self._lock = threading.Lock()
        # group -> vid -> {shard id: [L] int32}
        self._shards: list = [dict() for _ in range(num_groups)]
        self._dlen: list = [dict() for _ in range(num_groups)]
        self._spr: list = [dict() for _ in range(num_groups)]  # encoder only
        # shard ids received as OUR assignment (proposer "ps" sends /
        # recovered WAL slices) — the only rows a vote may durably log
        self._asg: list = [dict() for _ in range(num_groups)]

    # ------------------------------------------------------------- encode
    def encode(self, group: int, vid: int, batch: Any, spr: int
               ) -> Tuple[int, np.ndarray]:
        """Encode-once: serialize + RS-encode ``batch`` into the full
        ``[T, L]`` codeword, caching every shard locally.  Returns
        ``(data_len, codeword)``; re-encoding an already-held vid returns
        the cached rows."""
        with self._lock:
            held = self._shards[group].get(vid)
            if held is not None and len(held) == self.T:
                self._spr[group].setdefault(vid, spr)
                return (
                    self._dlen[group].get(vid, 0),
                    np.stack([held[i] for i in range(self.T)]),
                )
        dlen, cw = encode_payload(self.code, pickle.dumps(batch))
        with self._lock:
            self._dlen[group][vid] = dlen
            self._spr[group][vid] = spr
            self._shards[group].setdefault(vid, {}).update(
                {i: cw[i] for i in range(self.T)}
            )
        return dlen, cw

    # ------------------------------------------------------------- ingest
    def add_shards(self, group: int, vid: int, data_len: int,
                   shards: Dict[int, np.ndarray],
                   assigned: bool = False) -> None:
        """Ingest shard rows.  ``assigned=True`` marks them as THIS
        replica's assignment (a proposer "ps" send or a recovered WAL
        slice) — eligible for durable vote logging; gossip fills are
        not (a vote must stand for the voter's own slice, or recovery
        coverage counts the same shard twice across voters)."""
        with self._lock:
            self._dlen[group].setdefault(vid, int(data_len))
            self._shards[group].setdefault(vid, {}).update(shards)
            if assigned:
                self._asg[group].setdefault(vid, set()).update(shards)

    # ------------------------------------------------------------ queries
    def have_mask(self, group: int, vid: int) -> int:
        """Availability bitmap over shard ids (bit s = shard s held)."""
        with self._lock:
            held = self._shards[group].get(vid)
            if not held:
                return 0
            m = 0
            for s in held:
                m |= 1 << s
            return m

    def can_reconstruct(self, group: int, vid: int) -> bool:
        with self._lock:
            return len(self._shards[group].get(vid) or ()) >= self.d

    def shards_for(self, group: int, vid: int,
                   exclude_mask: int = 0,
                   only_sids: Optional[Tuple[int, ...]] = None,
                   ) -> Optional[Tuple[int, Dict[int, np.ndarray]]]:
        """Held shards for a vid as ``(data_len, {sid: rows})``, minus
        the requester's ``exclude_mask`` bitmap, optionally restricted to
        ``only_sids`` (the responder's own diagonal in non-urgent gossip
        rounds).  None when nothing useful is held."""
        with self._lock:
            held = self._shards[group].get(vid)
            if not held:
                return None
            sids = held.keys() if only_sids is None else [
                s for s in only_sids if s in held
            ]
            out = {
                s: held[s] for s in sids if not (exclude_mask >> s) & 1
            }
            if not out:
                return None
            return self._dlen[group].get(vid, 0), out

    def wal_shards(self, group: int, vid: int, me: int
                   ) -> Optional[Tuple[int, Dict[int, np.ndarray]]]:
        """The shard subset this replica durably logs for a voted vid —
        always its OWN assignment, never gossip-received foreign rows
        (``durability.rs`` logs accepted shard data, not full batches):

        - full-codeword holders (the encoder, or a replica that gossip-
          healed to all T rows) log their assigned diagonal slice —
          logging all T rows would be worse write amplification than the
          full-copy pp path this plane replaces;
        - partial holders log the rows that arrived AS their assignment
          (proposer sends / recovered WAL slices).  Foreign gossip rows
          alone yield None: a vote logged over someone else's shard
          would double-count that shard across voters and leave a
          committed value unreconstructable after a full-cluster crash
          (the d-distinct-slices recovery invariant).  The vid then
          simply stays unlogged until the heal completes (reconstruction
          restores all T rows, re-entering the first case)."""
        with self._lock:
            held = self._shards[group].get(vid)
            if not held:
                return None
            if len(held) == self.T:
                spr = self._spr[group].get(vid) or self.dj
                own = assigned_sids(me, max(spr, self.dj), self.dj, self.T)
                sub = {s: held[s] for s in own if s in held}
            else:
                asg = self._asg[group].get(vid) or ()
                sub = {s: held[s] for s in asg if s in held}
            if not sub:
                return None
            return self._dlen[group].get(vid, 0), sub

    # -------------------------------------------------------- reconstruct
    def reconstruct_batch(self, group: int, vid: int) -> Optional[Any]:
        """Decode the request batch once >= d shards are held (None
        otherwise).  Also restores the full codeword rows so later gossip
        rounds can serve ANY shard id of this value."""
        with self._lock:
            held = self._shards[group].get(vid)
            if held is None or len(held) < self.d:
                return None
            dlen = self._dlen[group].get(vid)
            if dlen is None:
                return None
            held = dict(held)
        rows = decode_rows(self.code, held)
        buf = unpack_bytes(rows, dlen)
        if len(held) < self.T:
            # restore every row from the decoded data rows — the SAME
            # lane geometry the encoder used (decode_rows preserves it),
            # so restored shards are byte-identical to the originals and
            # safe to mix with encoder-sent shards in later gossip rounds
            import jax.numpy as jnp

            parity = (
                np.asarray(self.code.compute_parity(
                    jnp.asarray(rows)[None]
                )[0])
                if self.code.p else rows[:0]
            )
            cw = np.concatenate([rows, parity], axis=0)
            with self._lock:
                self._shards[group].setdefault(vid, {}).update(
                    {i: cw[i] for i in range(self.T)}
                )
        return pickle.loads(buf)

    # ----------------------------------------------------------------- gc
    def gc_below(self, group: int, vid_floor: int) -> int:
        with self._lock:
            drop = [v for v in self._shards[group] if v < vid_floor]
            for v in drop:
                self._shards[group].pop(v, None)
                self._dlen[group].pop(v, None)
                self._spr[group].pop(v, None)
                self._asg[group].pop(v, None)
        return len(drop)

    def size(self, group: int) -> int:
        with self._lock:
            return len(self._shards[group])
