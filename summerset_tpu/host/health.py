"""Gray-failure detection: per-replica health scoring by quorum-median
outlier, and the shared indictment machinery behind leader demotion.

The dominant real-world failure mode for long-lived clusters is
fail-slow, not fail-stop: a limping disk, a rate-limited NIC, a
CPU-starved host — alive enough to answer heartbeats and hold
leadership/leases while tanking the whole group.  The nemesis classes
``slow_disk`` / ``slow_peer`` / ``mem_pressure`` inject exactly that;
this module is the detection half, ``host/server.py``'s demotion path
the mitigation half.

Signals — all things the hubs already emit, no new measurement plane:

- ``fsync_us``    — EWMA of WAL durability-point latency (StorageHub's
                    ``wal_fsync_us`` samples; the slow_disk tell);
- ``wal_tick_us`` — EWMA of TOTAL durability time per tick (mem_pressure
                    forces many cheap fsyncs per tick, so the per-sync
                    latency stays innocent while the per-tick cost
                    explodes);
- ``qd``          — EWMA of the api ingress queue depth (the PR 7
                    ``api_queue_depth`` gauge: a starved replica that
                    cannot drain its intake);
- ``delay_ms``    — per-peer frame delivery delay from the transport's
                    send stamps (the slow_peer tell: the egress token
                    bucket / CPU-starve stall lands AFTER the stamp, so
                    peers see the victim's limp directly).

Each replica piggybacks a compact beacon of its own signals (plus its
*observations* of every peer's frame delay) on the tick frames it
already sends; every replica therefore assembles the same R-row signal
table and computes the same verdict — the indicted LEADER discovers its
own indictment locally and steps down voluntarily.

The verdict is a **robust outlier test relative to the quorum median**,
explicitly NOT an absolute threshold: a replica is outlier on a signal
only when its value exceeds BOTH a per-signal noise floor AND
``ratio x`` the cross-replica median.  Uniform slowness (a loaded box:
every median moves together) and ``clock_skew`` (the victim's clock
runs slow, but its per-op latencies — fsync duration, frame
stamp-to-delivery — stay healthy; only its RATE drops, which no signal
here measures) cannot trip it.  Indictment requires ``hysteresis``
consecutive outlier evaluations and at least a quorum of fresh beacons
(so a partition minority, or the churn window of a legitimate election,
can never indict anyone), and clears after ``clear_after`` consecutive
healthy evaluations — oscillating slowness flaps the streak, not the
leadership.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List, Optional, Tuple

#: the signal table: name -> (beacon key, noise floor).  Floors are the
#: "could this be measurement noise" guard of the outlier test — small
#: enough that every injected fail-slow class clears them by an order of
#: magnitude, large enough that a quiet same-box cluster never does.
SIGNALS: Tuple[Tuple[str, str, float], ...] = (
    ("fsync_us", "f", 5000.0),     # 5ms: healthy same-box fsync ~0.1-1ms
    ("wal_tick_us", "w", 8000.0),  # 8ms of durability per tick
    ("qd", "q", 64.0),             # api queue depth
    ("delay_ms", "d", 25.0),       # frame stamp->delivery, same-host
)


@dataclasses.dataclass
class HealthVerdict:
    """One evaluation round's outcome."""

    evaluated: bool                  # quorum of fresh beacons present?
    indicted: List[int]              # replicas past the hysteresis bar
    outliers: Dict[int, List[str]]   # this round's raw outlier signals
    scores: Dict[int, float]         # 1.0 healthy .. 0.0 indicted
    table: Dict[str, Dict[int, float]]  # signal -> {replica: value}


class HealthScorer:
    """Per-replica gray-failure scorer (one per server process).

    Hub seams call ``note_fsync`` / ``note_peer_delay`` from their own
    threads; the replica loop calls ``end_tick`` once per tick,
    ``beacon``/``ingest`` around the frame exchange, and ``evaluate``
    every ``eval_interval`` ticks (the server owns the cadence).
    """

    def __init__(
        self,
        me: int,
        population: int,
        ratio: float = 4.0,
        hysteresis: int = 3,
        clear_after: int = 2,
        stale_s: float = 2.0,
        alpha: float = 0.25,
        floors: Optional[Dict[str, float]] = None,
    ):
        self.me = me
        self.population = population
        self.quorum = population // 2 + 1
        self.ratio = float(ratio)
        self.hysteresis = max(1, int(hysteresis))
        self.clear_after = max(1, int(clear_after))
        self.stale_s = float(stale_s)
        self.alpha = float(alpha)
        self.floors = {
            name: (floors or {}).get(name, floor)
            for name, _k, floor in SIGNALS
        }
        self._lock = threading.Lock()
        # own-signal EWMAs (written under the lock: storage's logger
        # thread and transport's messenger threads feed them)
        self._fsync_us = 0.0
        self._wal_tick_us = 0.0
        self._qd = 0.0
        self._tick_sync_us = 0.0   # this tick's durability accumulator
        self._have_own = False
        # my observations of each peer's frame delay (EWMA, ms)
        self._peer_delay_ms: Dict[int, float] = {}
        # freshest beacon per peer: (monotonic stamp, beacon dict)
        self._beacons: Dict[int, Tuple[float, Dict[str, Any]]] = {}
        # hysteresis state
        self._bad_streak: Dict[int, int] = {}
        self._good_streak: Dict[int, int] = {}
        self._indicted: set = set()

    # -- hub write side ------------------------------------------------------
    def _ewma(self, old: float, x: float) -> float:
        return x if old <= 0.0 else (1 - self.alpha) * old + self.alpha * x

    def note_fsync(self, dur_s: float) -> None:
        """One durability point completed (StorageHub logger thread)."""
        us = dur_s * 1e6
        with self._lock:
            self._fsync_us = self._ewma(self._fsync_us, us)
            self._tick_sync_us += us

    def note_peer_delay(self, peer: int, delay_s: float) -> None:
        """One send-stamped frame delivered from ``peer`` (transport
        messenger thread; same-host stamps only, like the adaptive
        plane's samples)."""
        ms = max(0.0, delay_s * 1e3)
        with self._lock:
            self._peer_delay_ms[peer] = self._ewma(
                self._peer_delay_ms.get(peer, 0.0), ms
            )

    # -- replica-loop side ---------------------------------------------------
    def end_tick(self, queue_depth: float) -> None:
        """Fold this tick's accumulators into the per-tick EWMAs."""
        with self._lock:
            self._wal_tick_us = self._ewma(
                self._wal_tick_us, self._tick_sync_us
            )
            self._tick_sync_us = 0.0
            self._qd = self._ewma(self._qd, float(queue_depth))
            self._have_own = True

    def beacon(self) -> Dict[str, Any]:
        """The compact health blob piggybacked on every tick frame."""
        with self._lock:
            return {
                "f": round(self._fsync_us, 1),
                "w": round(self._wal_tick_us, 1),
                "q": round(self._qd, 2),
                "o": {
                    p: round(v, 2)
                    for p, v in self._peer_delay_ms.items()
                },
            }

    def ingest(self, src: int, beacon: Dict[str, Any], now: float) -> None:
        """A peer's beacon arrived on a tick frame."""
        if not isinstance(beacon, dict):
            return
        with self._lock:
            self._beacons[int(src)] = (float(now), dict(beacon))

    # -- verdict -------------------------------------------------------------
    def _signal_table(self, now: float) -> Dict[str, Dict[int, float]]:
        """signal -> {replica: value} over fresh reporters (self always
        fresh).  ``delay_ms[r]`` is the median over OBSERVERS of r —
        self-reports can't hide a limping egress."""
        with self._lock:
            fresh: Dict[int, Dict[str, Any]] = {
                self.me: {
                    "f": self._fsync_us, "w": self._wal_tick_us,
                    "q": self._qd, "o": dict(self._peer_delay_ms),
                }
            }
            if not self._have_own:
                del fresh[self.me]
            for p, (t, b) in self._beacons.items():
                if now - t <= self.stale_s:
                    fresh[p] = b
        table: Dict[str, Dict[int, float]] = {}
        for name, key, _fl in SIGNALS:
            if name == "delay_ms":
                continue
            table[name] = {
                r: float(b.get(key, 0.0) or 0.0) for r, b in fresh.items()
            }
        obs: Dict[int, List[float]] = {}
        for o, b in fresh.items():
            for subj, v in (b.get("o") or {}).items():
                obs.setdefault(int(subj), []).append(float(v))
        table["delay_ms"] = {
            subj: _median(vals)
            for subj, vals in obs.items() if int(subj) in fresh
        }
        return table

    def evaluate(self, now: float) -> HealthVerdict:
        """One outlier round.  Quorum-gated: with fewer than a quorum of
        fresh reporters (partition minority, election churn taking peers'
        frames away) nothing is evaluated and every streak resets toward
        healthy — absence of evidence never indicts."""
        table = self._signal_table(now)
        reporters = set(table["fsync_us"])
        if len(reporters) < self.quorum:
            for r in list(self._bad_streak):
                self._bad_streak[r] = 0
            return HealthVerdict(
                False, sorted(self._indicted), {}, self._scores(), table
            )
        outliers: Dict[int, List[str]] = {}
        for name, _key, _fl in SIGNALS:
            vals = table.get(name) or {}
            if len(vals) < self.quorum:
                continue
            med = _median(list(vals.values()))
            floor = self.floors[name]
            for r, x in vals.items():
                if x > floor and x > self.ratio * max(med, 1e-9):
                    outliers.setdefault(r, []).append(name)
        for r in reporters:
            if r in outliers:
                self._bad_streak[r] = self._bad_streak.get(r, 0) + 1
                self._good_streak[r] = 0
                if self._bad_streak[r] >= self.hysteresis:
                    self._indicted.add(r)
            else:
                self._good_streak[r] = self._good_streak.get(r, 0) + 1
                self._bad_streak[r] = 0
                if self._good_streak[r] >= self.clear_after:
                    self._indicted.discard(r)
        return HealthVerdict(
            True, sorted(self._indicted), outliers, self._scores(), table
        )

    def _scores(self) -> Dict[int, float]:
        """1.0 healthy .. 0.0 indicted (the ``health_score`` gauge)."""
        out = {}
        for r in range(self.population):
            if r in self._indicted:
                out[r] = 0.0
            else:
                out[r] = round(
                    max(0.0, 1.0 - self._bad_streak.get(r, 0)
                        / self.hysteresis), 3
                )
        return out

    @property
    def self_indicted(self) -> bool:
        return self.me in self._indicted


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])
