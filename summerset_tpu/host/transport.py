"""TransportHub: the server-to-server TCP mesh for real deployments.

Parity: reference ``src/server/transport.rs`` — an acceptor task plus one
messenger per peer, full mesh built by proactively connecting to lower-id
peers and accepting from higher ids (transport.rs:388-849).

Lockstep adaptation: a server process owns replica index ``me`` of every
group.  Each tick it sends one frame per peer carrying (tick number, its
outbox slices for that destination, an optional payload piggyback) and
assembles its inbox from the freshest frame available from each peer.

Delivery semantics are deliberately NOT tick-aligned: replica tick
counters skew freely (jit compile pauses, GIL scheduling, restarts), so
matching frames by tick number would wedge the mesh the moment counters
diverge.  Instead ``recv_tick`` waits until the deadline for at least one
frame per peer and returns every frame that arrived, oldest to newest.
Consumers take the *kernel* lanes from the newest frame only (they carry
cumulative state — go-back-N ranges, frontier bars, ballot maxima — so a
newer frame supersedes an older one exactly like the netmodel delivering
only the latest broadcast) and union the *payload* piggybacks from all
frames (payload delivery is request/serve and self-heals via the ``need``
lists).  A peer with no frame by the deadline is a drop — the kernels'
loss machinery recovers, matching the netmodel's loss semantics rather
than TCP's infinite retry.
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
from typing import Any, Dict, Optional, Tuple

from ..utils import safetcp, wirecodec
from ..utils.errors import SummersetError
from ..utils.logging import pf_info, pf_logger, pf_warn

logger = pf_logger("transport")

#: one in N codec frames is also pickled to sample wire_bytes_saved
_SAVE_EVERY = 64


_TCP_STATES = {
    1: "ESTABLISHED", 4: "FIN_WAIT1", 5: "FIN_WAIT2", 6: "TIME_WAIT",
    7: "CLOSE", 8: "CLOSE_WAIT", 9: "LAST_ACK", 10: "LISTEN", 11: "CLOSING",
}


def _port_holders(port: int) -> list:
    """Diagnostic: enumerate /proc/net/tcp entries touching ``port``."""
    out = []
    try:
        for line in open("/proc/net/tcp").readlines()[1:]:
            f = line.split()
            lport = int(f[1].split(":")[1], 16)
            rport = int(f[2].split(":")[1], 16)
            if port in (lport, rport):
                st = int(f[3], 16)
                out.append((lport, rport, _TCP_STATES.get(st, st)))
    except OSError:
        pass
    return out


def hard_close(sock: socket.socket) -> None:
    """Abortive close (SO_LINGER 0 -> RST): releases the local port
    immediately instead of parking in FIN_WAIT/TIME_WAIT.  Correct for
    the tick mesh — frames are idempotent cumulative snapshots with drop
    semantics, so losing in-flight bytes at teardown is indistinguishable
    from a drop — and required for crash-restart rebinds: a graceful
    close would hold the p2p/api port until the far end also closes."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        # wake any thread blocked in recv() on this socket — close() alone
        # defers the real close (and the RST/port release) until that
        # thread's in-flight syscall returns
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class TransportHub:
    def __init__(self, me: int, population: int, p2p_addr: Tuple[str, int],
                 registry=None, flight=None, codec: Optional[bool] = None):
        self.me = me
        self.population = population
        self.p2p_addr = p2p_addr
        # wire codec (utils/wirecodec.py): when on, hot tick frames leave
        # as compact binary segments through one vectored sendmsg — lane
        # arrays ride zero-copy from the kernel outbox to the socket.
        # None follows the process default (SMR_WIRE_CODEC); the decode
        # side ALWAYS dispatches per frame, so a mixed mesh (codec peers
        # talking to pickle peers) interoperates with no negotiation.
        self.codec = wirecodec.default_on() if codec is None else bool(codec)
        self._enc = wirecodec.FrameEncoder()
        # sampled codec-savings accounting: every _SAVE_EVERY'th encoded
        # frame is also pickled to measure the byte delta (pickling every
        # frame would give back the codec's own win); pre-registered so
        # "codec off / never sampled" reads as a zero series
        self._save_probe = 0
        # telemetry seam (host/telemetry.MetricsRegistry): per-peer frame
        # and byte counters both directions, plus connect events — a
        # reconnect storm shows up as transport_connects outrunning the
        # population
        self.registry = registry
        if registry is not None:
            registry.counter_add("wire_bytes_saved", 0)
            registry.gauge_set("wire_codec_on", 1 if self.codec else 0)
        # graftscope seam (host/tracing.FlightRecorder): frame_tx /
        # frame_rx events with (peer, seq) where seq is the SENDER's tick
        # number — it already rides the wire in every frame, so tx and rx
        # pair across two servers' dumps with no wire-format change
        self.flight = flight
        # gray-failure seam (host/health.py HealthScorer): per-peer
        # delivery-delay observations feed the scorer's slow_peer signal
        # (attached by the server after construction; None = off)
        self.health = None
        self._conns: Dict[int, socket.socket] = {}
        self._wlocks: Dict[int, threading.Lock] = {}
        # live-cluster fault injection (host/nemesis.py): a FrameFaults
        # verdict engine consulted on every egress frame (send_tick) and
        # ingress frame (messenger threads).  None = zero-cost fast path.
        self._faults: Optional[safetcp.FrameFaults] = None
        # per-peer cumulative frame egress (bytes on the wire, framing
        # included) — the coarse half of the payload-economy accounting;
        # the server keeps the payload-plane-only counter (pp_bytes)
        self.bytes_sent: Dict[int, int] = {}
        # (peer, frame bytes, delay ms) delivery samples; deque appends
        # are thread-safe, the replica loop drains them opportunistically
        from collections import deque

        self.samples: Any = deque(maxlen=4096)
        # per-peer "clocks comparable" flag: send-stamped delay samples
        # subtract the sender's time.monotonic() from ours, and monotonic
        # bases are unrelated across machines — only loopback/same-host
        # peers produce meaningful deltas, so sampling is gated on it
        # (bogus cross-host positives would silently steer the adaptive
        # Crossword spr choice)
        self._same_host: Dict[int, bool] = {}
        # per-peer receive queues of (tick, payload)
        self._rq: Dict[int, queue.Queue] = {
            p: queue.Queue() for p in range(population) if p != me
        }
        self._listener = None
        deadline = None
        while True:
            try:
                self._listener = socket.create_server(
                    p2p_addr, reuse_port=False, backlog=population
                )
                break
            except OSError:
                # transient rebind race after a crash-restart: a peer may
                # not yet have reaped its half of an old accepted conn
                import time

                if deadline is None:
                    deadline = time.monotonic() + 10.0
                elif time.monotonic() > deadline:
                    pf_warn(
                        logger,
                        f"bind {p2p_addr} failed; holders: "
                        f"{_port_holders(p2p_addr[1])}",
                    )
                    raise
                time.sleep(0.1)
        self._accept_thread = threading.Thread(
            target=self._acceptor, daemon=True
        )
        self._accept_thread.start()

    # ------------------------------------------------------ fault injection
    def set_faults(self, spec: Optional[dict], seed: int = 0) -> None:
        """Install (or clear, with ``spec=None``) a message-fault spec.

        Crash/pause faults stay on the manager control plane; this hook
        covers the message plane only — partitions (mute/deaf), iid drop,
        duplication, and added delay — with verdicts drawn from a seeded
        RNG (see ``safetcp.FrameFaults``).  Swapped atomically; the tick
        loop and messenger threads read the reference once per frame."""
        self._faults = (
            safetcp.FrameFaults(spec, seed) if spec else None
        )

    # ---------------------------------------------------------- mesh setup
    def peers(self) -> list:
        """Currently connected peer ids (hub API surface; callers must
        not reach into the connection map)."""
        return sorted(self._conns)

    def connected(self, peer: int) -> bool:
        return peer in self._conns

    def connect_to_peer(self, peer: int, addr: Tuple[str, int]) -> None:
        """Proactively connect to a lower-id peer (transport.rs:162)."""
        sock = None
        for _ in range(50):
            try:
                sock = socket.create_connection(tuple(addr), timeout=5.0)
                break
            except OSError:
                import time

                time.sleep(0.2)
        if sock is None:
            raise SummersetError(f"cannot connect to peer {peer} @ {addr}")
        sock.settimeout(None)
        safetcp.send_msg_sync(sock, self.me)  # identify ourselves
        self._register(peer, sock)

    def wait_for_group(self, timeout: float = 30.0) -> None:
        """Block until the full mesh is connected (transport.rs:181)."""
        import time

        deadline = time.monotonic() + timeout
        while len(self._conns) < self.population - 1:
            if time.monotonic() > deadline:
                raise SummersetError(
                    f"mesh incomplete: {sorted(self._conns)} of "
                    f"{self.population - 1} peers"
                )
            time.sleep(0.05)
        pf_info(logger, f"p2p mesh complete ({self.population} replicas)")

    def _register(self, peer: int, sock: socket.socket) -> None:
        # close a replaced connection: an accepted socket shares the
        # listener's local port, so leaking it would hold the port past
        # shutdown and wedge an in-process crash-restart on rebind
        old = self._conns.get(peer)
        if old is not None and old is not sock:
            hard_close(old)
        try:
            rip = sock.getpeername()[0]
            lip = sock.getsockname()[0]
        except OSError:
            rip, lip = "", "-"

        def _norm(ip: str) -> str:
            # dual-stack listeners hand back IPv4-mapped IPv6 addresses
            return ip[7:] if ip.startswith("::ffff:") else ip

        rip, lip = _norm(rip), _norm(lip)
        # same host <=> loopback, or the peer's source address equals our
        # own address on this very connection (same machine via its real
        # IP; works for bind-all listeners where p2p_addr is 0.0.0.0)
        self._same_host[peer] = (
            rip.startswith("127.") or rip == "::1"
            or (rip != "" and rip == lip)
        )
        self._conns[peer] = sock
        self._wlocks[peer] = threading.Lock()
        if self.registry is not None:
            self.registry.counter_add("transport_connects", peer=peer)
        t = threading.Thread(
            target=self._messenger_recv, args=(peer, sock), daemon=True
        )
        t.start()

    def _acceptor(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            try:
                peer = int(safetcp.recv_msg_sync(sock))
            except Exception as e:
                # a dialer that never completes the id handshake (port
                # scanner, crashed peer) is survivable — but record it:
                # a systematic handshake failure (codec skew after a
                # partial upgrade) would otherwise look like a mesh
                # that silently never forms
                if self.flight is not None:
                    self.flight.record(
                        "transport_handshake_fail",
                        error=f"{type(e).__name__}: {e}",
                    )
                sock.close()
                continue
            self._register(peer, sock)

    def _messenger_recv(self, peer: int, sock: socket.socket) -> None:
        import time

        rx = safetcp.FrameReceiver()
        reg = self.registry
        try:
            while True:
                body = rx.recv_raw(sock)
                nbytes = len(body)
                # decode timed on its own — the blocking recv above
                # waits out the peer's whole tick interval and would
                # swamp the histogram by ~1000x
                t_dec = time.monotonic()
                tick, payload = wirecodec.decode_body(body)
                if reg is not None:
                    reg.observe_s(
                        "wire_decode_us", time.monotonic() - t_dec,
                        plane="p2p",
                    )
                faults = self._faults
                if faults is not None and faults.ingress_drop(peer):
                    # count AFTER the drop decision: a frame the fault
                    # plane discards was never "received", exactly as
                    # real packet loss would look in these counters
                    continue  # deaf to this peer (one partition half)
                if self.registry is not None:
                    self.registry.counter_add(
                        "transport_frames_recv", peer=peer
                    )
                    self.registry.counter_add(
                        "transport_bytes_recv", nbytes, peer=peer
                    )
                if faults is not None:
                    d = faults.ingress_delay(peer)
                    if d > 0:
                        # sleeping in the per-peer messenger delays every
                        # later frame too — a slow link, never reordering
                        time.sleep(d)
                if self.flight is not None:
                    # post-drop AND post-delay, like the counters: the
                    # event marks DELIVERY to the replica, so a delayed
                    # link shows its injected latency in the exported
                    # tx→rx arrows instead of a fictitious instant hop
                    self.flight.record(
                        "frame_rx", peer=peer, seq=int(tick),
                        nbytes=nbytes,
                    )
                self._rq[peer].put((tick, payload))
                # per-peer delivery sample for the adaptive perf model
                # (send-stamped frames; monotonic is machine-wide, so the
                # delta is a real one-way delay ONLY for same-host peers —
                # cross-host samples are dropped, see _same_host above)
                ts = payload.get("ts") if isinstance(payload, dict) else None
                if ts is not None and self._same_host.get(peer, False):
                    delay_s = time.monotonic() - ts
                    self.samples.append((peer, nbytes, delay_s * 1e3))
                    # per-peer ack/heartbeat latency: the frame delay IS
                    # the heartbeat-delivery latency on the tick mesh —
                    # the health scorer's slow_peer signal, and a
                    # DECLARED histogram so a limping peer is visible in
                    # every metrics_dump scrape
                    if self.registry is not None:
                        self.registry.observe_s(
                            "peer_ack_delay_us", delay_s, peer=peer
                        )
                    if self.health is not None:
                        self.health.note_peer_delay(peer, delay_s)
        except Exception:
            pf_warn(logger, f"peer {peer} connection lost")
            if self._conns.get(peer) is sock:
                del self._conns[peer]
            hard_close(sock)

    # ------------------------------------------------------------ tick I/O
    def send_tick(self, tick: int, per_peer: Dict[int, Any],
                  fence=None) -> None:
        """Send this tick's outbox slice to each connected peer.

        Egress is vectored and coalesced per peer: the frame's length
        prefix, codec chunks, and zero-copy lane-array views — times
        the dup count, when the fault plane duplicates — leave in ONE
        ``sendmsg`` syscall, with no join copy of the body (the old
        path concatenated header + pickle body per peer per tick).

        ``fence`` is the pipelined loop's durability gate: a callable
        (``ServerReplica._fence_wait``) invoked BEFORE the first byte of
        any frame leaves — the frames carry votes/acks computed by the
        step whose WAL records the fence covers, and a failed fence
        raises here, before anything escapes the process."""
        import time

        if fence is not None:
            fence()
        faults = self._faults
        enc = self._enc
        reg = self.registry
        for peer, payload in per_peer.items():
            sock = self._conns.get(peer)
            if sock is None:
                continue
            copies = 1
            if faults is not None:
                verdict = faults.egress(peer)
                if verdict == "drop":
                    continue  # frame lost: kernels' loss machinery heals
                if verdict == "dup":
                    copies = 2
            t_enc = time.monotonic()
            segs, nbytes = safetcp.encode_frame_into(
                (tick, payload), enc, codec=self.codec
            )
            if reg is not None:
                reg.observe_s(
                    "wire_encode_us", time.monotonic() - t_enc,
                    plane="p2p",
                )
                if self.codec:
                    self._save_probe += 1
                    if self._save_probe >= _SAVE_EVERY:
                        self._save_probe = 0
                        base = len(safetcp.encode_frame(
                            (tick, payload), codec=False
                        ))
                        reg.counter_add(
                            "wire_bytes_saved", max(0, base - nbytes)
                        )
            if copies > 1:
                segs = segs * copies
            if faults is not None:
                # fail-slow slow_peer: the egress token bucket / CPU
                # starve duty stalls the SENDER's tick loop — the host is
                # limping, unlike `delay` which only slows the link in
                # the receiver's messenger thread.  Stalled strictly
                # AFTER the frame was stamped (payload "ts"), so peers'
                # delivery-delay samples see the injected limp.
                stall = faults.host_stall(
                    copies * nbytes, time.monotonic()
                )
                if stall > 0:
                    time.sleep(stall)
            try:
                # graftlint: disable=H101 -- the per-peer write lock exists to serialize frame writers on one socket; it guards nothing else, so blocking inside it cannot deadlock other state
                with self._wlocks[peer]:
                    safetcp.sendmsg_all(sock, segs, copies * nbytes)
                # bytes_sent (debug_state + adaptive consumers) and the
                # registry counter must account identically — update both
                # here or neither
                self.bytes_sent[peer] = (
                    self.bytes_sent.get(peer, 0) + copies * nbytes
                )
                if reg is not None:
                    reg.counter_add(
                        "transport_frames_sent", copies, peer=peer
                    )
                    reg.counter_add(
                        "transport_bytes_sent", copies * nbytes,
                        peer=peer,
                    )
                if self.flight is not None:
                    # recorded after the send (outside the write lock):
                    # an egress-dropped or failed frame was never on the
                    # wire, so it must not mint a tx event
                    self.flight.record(
                        "frame_tx", peer=peer, seq=int(tick),
                        nbytes=copies * nbytes,
                    )
            except OSError:
                if self._conns.get(peer) is sock:
                    self._conns.pop(peer, None)
                hard_close(sock)
            finally:
                enc.release()

    def recv_tick(
        self, tick: int, deadline: float
    ) -> Dict[int, Optional[list]]:
        """Collect peers' queued frames, waiting until ``deadline``
        (monotonic seconds) for at least one frame from each connected
        peer.  Returns ``{peer: [frame, ...] oldest-to-newest}`` with
        ``None`` for peers that produced nothing (drop semantics).  Frame
        tick tags are ignored — counters skew across processes (see module
        docstring)."""
        import time

        out: Dict[int, Optional[list]] = {p: None for p in self._rq}

        def drain() -> None:
            for p, q in self._rq.items():
                while True:
                    try:
                        _t, payload = q.get_nowait()
                    except queue.Empty:
                        break
                    if out[p] is None:
                        out[p] = []
                    out[p].append(payload)

        while True:
            drain()
            waiting = [
                p for p in self._rq
                if out[p] is None and p in self._conns
            ]
            budget = deadline - time.monotonic()
            if not waiting or budget <= 0:
                return out
            # block on one lagging peer's queue, then re-drain all
            try:
                _t, payload = self._rq[waiting[0]].get(timeout=budget)
                if out[waiting[0]] is None:
                    out[waiting[0]] = []
                out[waiting[0]].append(payload)
            except queue.Empty:
                pass

    def close(self) -> None:
        # shutdown() first: close() alone does not free the kernel socket
        # while the acceptor thread sits in accept() (the in-flight syscall
        # pins it in LISTEN, blocking a crash-restart rebind); shutdown
        # forces the blocked accept() to return
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._listener.close()
        for sock in list(self._conns.values()):
            hard_close(sock)
