"""TransportHub: the server-to-server TCP mesh for real deployments.

Parity: reference ``src/server/transport.rs`` — an acceptor task plus one
messenger per peer, full mesh built by proactively connecting to lower-id
peers and accepting from higher ids (transport.rs:388-849).

Lockstep adaptation: a server process owns replica index ``me`` of every
group.  Each tick it sends one frame per peer carrying (tick number, its
outbox slices for that destination, an optional payload piggyback) and
assembles the inbox for tick ``t`` from peers' frames.  A peer frame that
misses the per-tick deadline is treated as dropped — the kernels' loss
machinery (go-back-N streams, re-campaigns) recovers, matching the
netmodel's loss semantics rather than TCP's infinite retry.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Any, Dict, Optional, Tuple

from ..utils import safetcp
from ..utils.errors import SummersetError
from ..utils.logging import pf_info, pf_logger, pf_warn

logger = pf_logger("transport")


class TransportHub:
    def __init__(self, me: int, population: int, p2p_addr: Tuple[str, int]):
        self.me = me
        self.population = population
        self.p2p_addr = p2p_addr
        self._conns: Dict[int, socket.socket] = {}
        self._wlocks: Dict[int, threading.Lock] = {}
        # per-peer receive queues of (tick, payload)
        self._rq: Dict[int, queue.Queue] = {
            p: queue.Queue() for p in range(population) if p != me
        }
        self._stash: Dict[int, Dict[int, Any]] = {
            p: {} for p in range(population) if p != me
        }
        self._listener = socket.create_server(
            p2p_addr, reuse_port=False, backlog=population
        )
        self._accept_thread = threading.Thread(
            target=self._acceptor, daemon=True
        )
        self._accept_thread.start()

    # ---------------------------------------------------------- mesh setup
    def connect_to_peer(self, peer: int, addr: Tuple[str, int]) -> None:
        """Proactively connect to a lower-id peer (transport.rs:162)."""
        sock = None
        for _ in range(50):
            try:
                sock = socket.create_connection(tuple(addr), timeout=5.0)
                break
            except OSError:
                import time

                time.sleep(0.2)
        if sock is None:
            raise SummersetError(f"cannot connect to peer {peer} @ {addr}")
        sock.settimeout(None)
        safetcp.send_msg_sync(sock, self.me)  # identify ourselves
        self._register(peer, sock)

    def wait_for_group(self, timeout: float = 30.0) -> None:
        """Block until the full mesh is connected (transport.rs:181)."""
        import time

        deadline = time.monotonic() + timeout
        while len(self._conns) < self.population - 1:
            if time.monotonic() > deadline:
                raise SummersetError(
                    f"mesh incomplete: {sorted(self._conns)} of "
                    f"{self.population - 1} peers"
                )
            time.sleep(0.05)
        pf_info(logger, f"p2p mesh complete ({self.population} replicas)")

    def _register(self, peer: int, sock: socket.socket) -> None:
        self._conns[peer] = sock
        self._wlocks[peer] = threading.Lock()
        t = threading.Thread(
            target=self._messenger_recv, args=(peer, sock), daemon=True
        )
        t.start()

    def _acceptor(self) -> None:
        while True:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            try:
                peer = int(safetcp.recv_msg_sync(sock))
            except Exception:
                sock.close()
                continue
            self._register(peer, sock)

    def _messenger_recv(self, peer: int, sock: socket.socket) -> None:
        try:
            while True:
                tick, payload = safetcp.recv_msg_sync(sock)
                self._rq[peer].put((tick, payload))
        except Exception:
            pf_warn(logger, f"peer {peer} connection lost")
            if self._conns.get(peer) is sock:
                del self._conns[peer]

    # ------------------------------------------------------------ tick I/O
    def send_tick(self, tick: int, per_peer: Dict[int, Any]) -> None:
        """Send this tick's outbox slice to each connected peer."""
        for peer, payload in per_peer.items():
            sock = self._conns.get(peer)
            if sock is None:
                continue
            try:
                with self._wlocks[peer]:
                    safetcp.send_msg_sync(sock, (tick, payload))
            except OSError:
                self._conns.pop(peer, None)

    def recv_tick(
        self, tick: int, deadline: float
    ) -> Dict[int, Optional[Any]]:
        """Collect peers' frames for `tick`, waiting until `deadline`
        (monotonic seconds).  Missing frames return None (dropped); frames
        for future ticks are stashed, stale ones discarded."""
        import time

        out: Dict[int, Optional[Any]] = {}
        for peer, q in self._rq.items():
            stash = self._stash[peer]
            if tick in stash:
                out[peer] = stash.pop(tick)
                continue
            got = None
            while True:
                budget = deadline - time.monotonic()
                if budget <= 0:
                    break
                try:
                    t, payload = q.get(timeout=budget)
                except queue.Empty:
                    break
                if t == tick:
                    got = payload
                    break
                if t > tick:
                    stash[t] = payload
                    break
                # t < tick: stale, drop
            out[peer] = got
        return out

    def close(self) -> None:
        self._listener.close()
        for sock in list(self._conns.values()):
            try:
                sock.close()
            except OSError:
                pass
