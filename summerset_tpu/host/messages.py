"""Wire message types for the three TCP planes.

Parity: reference ``ApiRequest``/``ApiReply`` (``src/server/external.rs:
33-183``), ``CtrlMsg`` (``src/manager/reigner.rs:30-83``), ``CtrlRequest``/
``CtrlReply`` (``src/manager/reactor.rs:29-105``).  Dataclasses are pickled
through the safetcp frames; field names track the reference closely so the
tester/bench clients port one-to-one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from .statemach import Command, CommandResult


# --------------------------------------------------------------- data plane
@dataclasses.dataclass(frozen=True)
class ApiRequest:
    """Client -> server (parity: ``ApiRequest::{Req, Conf, Leave}``).

    The compartmentalized serving plane (``host/ingress.py``) adds three
    tier-to-tier kinds that ride the same wire:

    - ``"batch"`` — an ingress proxy's aggregated forward: ``batch`` is
      a list of ``(proxy req id, Command)`` pairs that the shard unpacks
      into individual ops.  A batch occupies ONE slot in the shard's
      bounded ingress queue (the fan-in amortization that moves the shed
      point off the shard and onto the proxy tier), and a shed refusal
      covers the whole batch with one negative ack.
    - ``"sub"``   — a learner/read-tier subscription: the replica
      replies with a full KV snapshot + its commit-feed sequence number,
      then streams ``"note"`` replies for every applied put.
    - ``"probe"`` — a read-tier freshness probe for ``cmd``'s key: the
      replica answers (on its own tick thread, exactly where the fused
      lease-read decision is made) whether a lease-local read is
      currently allowed for that key's group, plus its commit-feed seq —
      the learner serves locally iff its learned seq covers the probe's.

    ``"stats"`` is answered by INGRESS PROXIES only (per-tier metrics
    scrape over the data plane; a fused server answers error).
    """

    kind: str                      # "req" | "conf" | "leave"
    #                              # | "batch" | "sub" | "probe" | "stats"
    req_id: int = 0
    cmd: Optional[Command] = None  # kind == "req" | "probe"
    conf_delta: Optional[dict] = None  # kind == "conf" (protocol-specific)
    batch: Optional[list] = None   # kind == "batch": [(prid, Command)]


@dataclasses.dataclass(frozen=True)
class ApiReply:
    """Server -> client (parity: ``ApiReply``, external.rs:155-183).

    ``kind == "shed"`` is the ingress-backpressure negative ack: the
    bounded api queue was full, the request was REFUSED BEFORE entering
    the queue (so it can never have been proposed, let alone executed —
    ``utils/linearize`` soundly excludes shed puts on that guarantee),
    and ``retry_after_ms`` hints when the client should retry (drivers
    honor it with seeded jittered backoff instead of hot-retrying into
    the same full queue)."""

    kind: str   # "reply" | "conf" | "redirect" | "error" | "shed"
    #             | "leave" | "sub" | "note" | "probe" | "stats"
    req_id: int = 0
    result: Optional[CommandResult] = None
    redirect: Optional[int] = None  # hinted leader id
    success: bool = True
    rq_retry: bool = False          # read-query retry hint
    local: bool = False             # served as a leased local read
    retry_after_ms: int = 0         # shed: suggested client backoff
    # commit-feed plane (read tier, host/ingress.py): "sub" carries the
    # snapshot KV dict in `notes` with `seq` = the feed position it
    # covers; "note" streams [(seq, key, value), ...] applied puts in
    # apply order; "probe" answers success=lease_ok + the current seq
    seq: int = 0
    notes: Optional[Any] = None


# -------------------------------------------------------------- p2p plane
# Server-to-server tick frames are plain dicts (host/server.py builds
# them, host/transport.py ships them): ``msg`` carries the kernel outbox
# slices; payload-plane keys ride alongside:
#   pp: {(group, vid): ReqBatch}            full-copy piggybacks
#   ps: {(group, vid): ShardPayload}        proposer -> peer assigned shards
#   cw: {(group, vid): ShardPayload}        gossip replies (held shards)
#   cw_need: [(group, vid, have_mask, urgent)]   shard-gossip requests
#   need / kv_need / kv / rq / rqr: full-payload + snapshot + quorum-read
#                                   planes (pre-codeword machinery)
@dataclasses.dataclass(frozen=True)
class ShardPayload:
    """A subset of one value's RS codeword on the wire (parity role:
    the shard-subset ``RSCodeword`` carried by Accept / Reconstruct
    messages, ``rspaxos/mod.rs:597-608``, ``messages.rs:468-560``)."""

    data_len: int        # original serialized ReqBatch byte length
    shards: Dict[int, Any] = dataclasses.field(default_factory=dict)
    # shard id -> [L] int32 lane array (4 packed bytes per lane)


# ------------------------------------------------------------ control plane
@dataclasses.dataclass(frozen=True)
class CtrlMsg:
    """Server <-> manager (parity: ``CtrlMsg``, reigner.rs:30-83)."""

    kind: str
    # kind-specific payload:
    #   new_server_join: protocol, api_addr, p2p_addr
    #   connect_to_peers: population, to_peers {id: p2p_addr}
    #   leader_status: step_up (bool)
    #   responders_conf: conf_num, new_conf
    #   reset_state / pause / resume / take_snapshot (+ _reply forms)
    #   snapshot_up_to: new_start
    #   metrics_dump -> metrics_reply: snapshot (telemetry scrape;
    #     server.metrics_snapshot() — device lanes + host registry)
    #   flight_dump -> flight_reply: flight (graftscope scrape;
    #     server.flight_snapshot() — the typed-event ring + drop
    #     accounting; request payload may carry {"last_n": n})
    #   range_change -> range_reply: change (host/resharding.RangeChange
    #     as_dict) — every replica seals the range and acks; the
    #     destination leader later proposes the adopt through its log
    #   range_installed: entry — proposer -> manager adoption notice
    #   install_ranges: seq, installed, pending, expired — manager ->
    #     servers re-announce (newest seq wins; the ConfChange
    #     install_conf pattern) so late joiners learn installed ranges,
    #     re-seal pending ones, and un-seal expired ones
    #   adopt_intent -> adopt_decision: rc_id (+ ok on the decision) —
    #     the adopting leader asks the manager to pin the cutover
    #     before proposing; a grant makes the change non-expirable, a
    #     refusal (already expired) rolls the seal back
    #   range_expire: rc_id — a source server reports a sealed range
    #     whose destination stayed leaderless past seal_ttl_ticks; the
    #     manager expires the pending change iff no adopt grant exists
    #   autopilot_ctl -> autopilot_reply: act ("demote" | "retune" |
    #     "announce") + actuator fields (reason / api_max_batch /
    #     pipeline / mode / cooldowns) — the autopilot driver's
    #     actuation fan-out (host/autopilot.py)
    #   watch_frame: one graftwatch delta frame (host/graftwatch.py
    #     WatchEmitter.frame — sid/tier/group/widx + counter deltas,
    #     gauge values, histogram window snapshots), server -> manager
    #     one-way on the watch cadence; clusman ingests it into the
    #     FleetSeries ring, no reply
    #   leave / leave_reply
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class CtrlRequest:
    """Client -> manager (parity: ``CtrlRequest``, reactor.rs:29-64)."""

    kind: str  # query_info | query_conf | reset_servers | pause_servers
    #            | resume_servers | take_snapshot | inject_faults
    #            | metrics_dump | flight_dump | proxy_join | leave
    #            | range_change (payload: op/start/end/dst_group —
    #              validated into a host/resharding.RangeChange, fanned
    #              to every server, replied with conf={"rc_id": n})
    #            | autopilot_ctl (payload: act + actuator fields,
    #              relayed verbatim to target servers; the autopilot
    #              driver's actuation plane — host/autopilot.py)
    #            | watch_series (graftwatch: the manager's FleetSeries
    #              export — answered locally from the ring, no server
    #              fan-out; reply carries payloads={"fleet": export})
    servers: Optional[List[int]] = None  # None = all
    durable: bool = True                 # reset: keep durable files?
    payload: Optional[Dict[str, Any]] = None  # inject_faults: fault spec
    #   {"net": FrameFaults spec | None, "wal": wal spec | None, "seed": n}
    #   relayed verbatim to each target server as a ``fault_ctl`` CtrlMsg;
    #   flight_dump: {"last_n": n} trims each replica's dump to its n
    #   newest events


@dataclasses.dataclass(frozen=True)
class CtrlReply:
    """Manager -> client (parity: ``CtrlReply``, reactor.rs:66-105)."""

    kind: str
    population: int = 0
    servers: Dict[int, Tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )  # id -> (api_addr, p2p_addr)
    leader: Optional[int] = None
    conf: Optional[dict] = None
    done: Optional[List[int]] = None
    # gather fan-outs with a per-server deadline mark the servers that
    # did NOT answer in time here (slow-but-alive under fail-slow): the
    # caller gets partial results immediately instead of waiting the
    # full fan-out window on one limping replica, and the slow server
    # is visible instead of silently absent
    missing: Optional[List[int]] = None
    # per-server reply payloads gathered by the fan-out (metrics_dump:
    # sid -> telemetry snapshot); None for ack-only orchestration kinds
    payloads: Optional[Dict[int, Any]] = None
    # registered ingress proxies (host/ingress.py): pid -> api_addr,
    # returned by query_info so clients discover the proxy tier through
    # the same manager round they already make (a proxy deregisters when
    # its ctrl connection drops, so rediscovery after a proxy crash is
    # one fresh query_info away)
    proxies: Optional[Dict[int, Any]] = None
    # installed range overrides (host/resharding.py), in adoption order:
    # query_info returns them so proxies learn live splits/merges through
    # their existing refresh round (the same late-joiner re-announce
    # contract as `conf`)
    ranges: Optional[list] = None
