"""ServerReplica: a real networked replica process around the device kernel.

Parity: reference ``GenericReplica`` + ``summerset_server`` (SURVEY.md
§2.2/§2.6) — ``new_and_setup`` composes ControlHub -> StateMachine ->
StorageHub -> TransportHub -> ExternalApi, joins via the manager, then
``run()`` drives the event loop; returning True means crash-restart
(``summerset_server/src/main.rs:127-160``).

TPU-native split: this process owns replica index ``me`` of every group.
Each tick it (1) drains the client batch, (2) steps the vectorized kernel
with the inbox assembled from peers' TCP frames, (3) sends its outbox
slice + payload piggybacks, (4) WAL-logs newly committed slots, applies
them to the KV store, and replies to clients it originated.  Consensus
messages ride the device outbox; request payloads ride host frames keyed
by value id (the device log stores int32 references only — SURVEY.md §7
hard part (b)).

Leadership, failover, leases, and commit tallies all happen inside the
kernel; this loop only reflects ``is_leader`` edges to the manager and
redirects clients when not serving.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from ..protocols import make_protocol
from ..utils.logging import pf_info, pf_logger, pf_warn
from .control import ControlHub
from .external import ExternalApi
from .messages import ApiReply, ApiRequest, CtrlMsg
from .payload import PayloadStore
from .statemach import StateMachine, apply_command
from .storage import LogAction, StorageHub
from .transport import TransportHub

logger = pf_logger("server")


class ServerReplica:
    def __init__(
        self,
        protocol: str,
        api_addr: Tuple[str, int],
        p2p_addr: Tuple[str, int],
        manager_addr: Tuple[str, int],
        config: Optional[dict] = None,
        num_groups: int = 1,
        window: int = 64,
        tick_interval: float = 0.002,
        backer_dir: str = "/tmp/summerset_tpu",
    ):
        cfg = dict(config or {})
        self.protocol = protocol
        self.api_addr = api_addr
        self.p2p_addr = p2p_addr
        self.tick_interval = tick_interval
        self.G = num_groups
        self.window = window

        # control plane first: the manager assigns our id (control.rs:43)
        self.ctrl = ControlHub(manager_addr)
        self.me = self.ctrl.me
        self.population = self.ctrl.population

        # protocol kernel over [G, R]; host applier drives the exec bar.
        # Supported here: the MultiPaxos-family kernels sharing the
        # (n_proposals, value_base, exec_floor) input contract.
        kercfg_cls = type(
            make_protocol(protocol, 1, self.population, 64).config
        )
        known = {f.name for f in dataclasses.fields(kercfg_cls)}
        kcfg = kercfg_cls(**{k: v for k, v in cfg.items() if k in known})
        if hasattr(kcfg, "exec_follows_commit"):
            kcfg.exec_follows_commit = False
        if hasattr(kcfg, "max_proposals_per_tick"):
            kcfg.max_proposals_per_tick = 1  # one ReqBatch per tick
        self.kernel = make_protocol(
            protocol, self.G, self.population, window, kcfg
        )
        self.state = self.kernel.init_state(seed=0)
        self._step = jax.jit(self.kernel.step)

        os.makedirs(backer_dir, exist_ok=True)
        self.wal_path = os.path.join(backer_dir, f"r{self.me}.wal")
        self.wal = StorageHub(self.wal_path)
        self.snapdir = os.path.join(backer_dir, f"r{self.me}.snap")
        self.statemach = StateMachine()
        self.payloads = PayloadStore(self.G)
        self.applied = [0] * self.G        # exec floor per group (own row)
        self._voted_logged: Dict[int, tuple] = {}   # g -> last logged vote
        self._logged_vids: Dict[int, set] = {
            g: set() for g in range(self.G)
        }
        self.origin: set = set()           # vids proposed by this server
        self.missing: set = set()           # committed vids lacking payloads
        self.kv_need = False
        self.paused = False
        self.stopping = False  # cooperative stop for embedded harnesses
        self.was_leader = False
        self.tick = 0
        self._pending_serve: Dict[int, Any] = {}  # peers' payload requests
        self._pending_kv_serve = False

        self._recover_from_wal()

        # p2p mesh join (multipaxos/mod.rs:717-737): proactively connect to
        # lower-id peers, accept from higher ids.  The join is re-sent until
        # the mesh completes — concurrent bring-up means a lower-id peer may
        # join after us, so one connect_to_peers snapshot is not enough.
        self.transport = TransportHub(self.me, self.population, p2p_addr)
        join = CtrlMsg("new_server_join", {
            "protocol": protocol,
            "api_addr": api_addr,
            "p2p_addr": p2p_addr,
        })
        connected: set = set()
        deadline = time.monotonic() + 60
        while True:
            self.ctrl.send_ctrl(join)
            try:
                msg = self.ctrl.recv_ctrl(timeout=3)
            except Exception:
                msg = None
            if msg is not None and msg.kind == "connect_to_peers":
                for peer, addr in msg.payload["to_peers"].items():
                    p = int(peer)
                    if p not in connected and p not in self.transport._conns:
                        self.transport.connect_to_peer(p, addr)
                        connected.add(p)
            try:
                self.transport.wait_for_group(timeout=2)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise

        self.external = ExternalApi(api_addr)
        pf_info(logger, f"replica {self.me} ready")

    # -------------------------------------------------------- WAL recovery
    def _recover_from_wal(self) -> None:
        """Replay the WAL: apply records rebuild payloads + KV + exec
        floors; the last vote record per group rebuilds the kernel row's
        acceptor state (parity: recovery.rs replay loop SURVEY.md §3.4 +
        raft durable curr_term/voted_for, raft/mod.rs:144-176)."""
        off = 0
        n = 0
        votes: Dict[int, dict] = {}
        while True:
            res = self.wal.do_sync_action(LogAction("read", offset=off))
            if not res.offset_ok or res.entry is None:
                break
            rec = res.entry
            if isinstance(rec, tuple) and rec and rec[0] == "vote":
                g, v = rec[1], rec[2]
                votes[g] = v
                for vid, batch in v.get("pp", {}).items():
                    self.payloads._data[g].setdefault(vid, batch)
                    self.payloads._next[g] = max(
                        self.payloads._next[g], vid + 1
                    )
                    self._logged_vids[g].add(vid)
            else:
                g, slot, vid, batch = rec
                self.payloads._data[g][vid] = batch
                self.payloads._next[g] = max(self.payloads._next[g], vid + 1)
                if batch is not None:
                    for client, req in batch:
                        if req.cmd is not None:
                            apply_command(self.statemach._kv, req.cmd)
                self.applied[g] = max(self.applied[g], slot + 1)
            off = res.end_offset
            n += 1
        for g, v in votes.items():
            self._restore_vote_row(g, v)
        if n:
            pf_info(
                logger,
                f"recovered {n} WAL records ({len(votes)} vote rows)",
            )

    def _restore_vote_row(self, g: int, v: dict) -> None:
        """Reinstate our acceptor row in the kernel state from a logged
        vote record — a crash-restarted replica must not forget its
        promises/votes (double-vote) nor its voted window content."""
        st = self.state
        if "vote_bal" not in st:
            return  # kernel family without the vote-run contract
        me = self.me
        i32 = jnp.int32
        floor = i32(self.applied[g])
        st["bal_max"] = st["bal_max"].at[g, me].max(i32(v["bal_max"]))
        st["vote_bal"] = st["vote_bal"].at[g, me].set(i32(v["vote_bal"]))
        st["vote_from"] = st["vote_from"].at[g, me].set(i32(v["vote_from"]))
        st["vote_bar"] = st["vote_bar"].at[g, me].max(floor)
        st["vote_bar"] = st["vote_bar"].at[g, me].max(i32(v["vote_bar"]))
        st["dur_bar"] = st["dur_bar"].at[g, me].set(
            jnp.maximum(i32(v["vote_bar"]), floor)
        )
        st["commit_bar"] = st["commit_bar"].at[g, me].max(floor)
        st["exec_bar"] = st["exec_bar"].at[g, me].max(floor)
        st["win_abs"] = st["win_abs"].at[g, me].set(
            jnp.asarray(v["win_abs"], i32)
        )
        st["win_bal"] = st["win_bal"].at[g, me].set(
            jnp.asarray(v["win_bal"], i32)
        )
        st["win_val"] = st["win_val"].at[g, me].set(
            jnp.asarray(v["win_val"], i32)
        )

    def _log_votes(self) -> None:
        """Durably log acceptor-state changes BEFORE the outbox carrying
        the corresponding acks is released (next tick's send).

        Parity: the reference appends PrepareBal/AcceptData and fsyncs
        before a follower sends AcceptReply (durability.rs:85-216) and
        Raft persists curr_term/voted_for (raft/mod.rs:144-176).  Payload
        batches for newly voted value ids ride the same record so a
        crashed-and-recovered quorum can re-serve committed values even if
        every replica restarts."""
        st = self.state
        if "vote_bal" not in st:
            return
        me = self.me
        bal_max = np.asarray(st["bal_max"])[:, me]
        vote_bal = np.asarray(st["vote_bal"])[:, me]
        vote_from = np.asarray(st["vote_from"])[:, me]
        vote_bar = np.asarray(st["vote_bar"])[:, me]
        win_abs = np.asarray(st["win_abs"])[:, me]
        win_bal = np.asarray(st["win_bal"])[:, me]
        win_val = np.asarray(st["win_val"])[:, me]
        for g in range(self.G):
            key = (
                int(bal_max[g]), int(vote_bal[g]), int(vote_from[g]),
                int(vote_bar[g]), win_abs[g].tobytes(),
                win_bal[g].tobytes(), win_val[g].tobytes(),
            )
            if self._voted_logged.get(g) == key:
                continue
            self._voted_logged[g] = key
            new_pp = {}
            for vid in set(int(x) for x in win_val[g]):
                if vid and vid not in self._logged_vids[g]:
                    b = self.payloads.get(g, vid)
                    if b is not None:
                        new_pp[vid] = b
                        self._logged_vids[g].add(vid)
            rec = ("vote", g, {
                "bal_max": int(bal_max[g]),
                "vote_bal": int(vote_bal[g]),
                "vote_from": int(vote_from[g]),
                "vote_bar": int(vote_bar[g]),
                "win_abs": win_abs[g].tolist(),
                "win_bal": win_bal[g].tolist(),
                "win_val": win_val[g].tolist(),
                "pp": new_pp,
            })
            self.wal.do_sync_action(
                LogAction("append", entry=rec, sync=True)
            )

    # ----------------------------------------------------------- tick I/O
    def _slice_outbox(self, out) -> Dict[int, Dict[str, Any]]:
        """Per-peer frame: per-pair fields sliced [G] at (me, dst),
        broadcast lanes sent whole."""
        lanes = self.kernel.broadcast_lanes
        frames: Dict[int, Dict[str, Any]] = {}
        np_out = {k: np.asarray(v) for k, v in out.items()}
        for dst in range(self.population):
            if dst == self.me:
                continue
            f = {}
            for k, v in np_out.items():
                f[k] = v[:, self.me] if k in lanes else v[:, self.me, dst]
            frames[dst] = f
        return frames

    def _assemble_inbox(self, own_out, peer_frames) -> Dict[str, Any]:
        """Receiver-oriented inbox: row `me` filled from peers + self.

        ``peer_frames`` maps src -> list of frames (oldest..newest) or
        None; kernel lanes come from the newest frame only — they carry
        cumulative state, so the latest supersedes (transport docstring).
        """
        lanes = self.kernel.broadcast_lanes
        zero = self.kernel.zero_outbox()
        inbox = {}
        for k, z in zero.items():
            arr = np.zeros_like(np.asarray(z))
            if k in lanes:
                arr[:, self.me] = np.asarray(own_out[k])[:, self.me]
                for src, fl in peer_frames.items():
                    if fl:
                        arr[:, src] = fl[-1]["msg"][k]
            else:
                # transposed orientation: [G, dst(me), src]
                arr[:, self.me, self.me] = np.asarray(own_out[k])[
                    :, self.me, self.me
                ]
                for src, fl in peer_frames.items():
                    if fl:
                        arr[:, self.me, src] = fl[-1]["msg"][k]
            inbox[k] = jnp.asarray(arr)
        return inbox

    # --------------------------------------------------------- main loop
    def run(self) -> bool:
        """Event loop; returns True to request a crash-restart."""
        last_out = {
            k: jnp.asarray(v) for k, v in self.kernel.zero_outbox().items()
        }
        while True:
            if self.stopping:
                return False
            t0 = time.monotonic()
            restart = self._handle_ctrl()
            if restart is not None:
                return restart
            if self.paused:
                time.sleep(self.tick_interval)
                continue

            # 1. client intake -> payload ids (one ReqBatch per group/tick);
            # non-leaders redirect with the hinted leader id
            # (request.rs:128-154)
            batch = self.external.get_req_batch(timeout=0)
            n_prop = np.zeros((self.G,), np.int32)
            vbase = np.zeros((self.G,), np.int32)
            piggy: Dict[int, Any] = {}
            if batch:
                reqs = [(c, r) for c, r in batch if r.kind == "req"]
                if reqs and not self.was_leader:
                    hint = int(np.asarray(self.state["leader"])[0, self.me]
                               ) if "leader" in self.state else -1
                    for c, r in reqs:
                        self.external.send_reply(
                            ApiReply("redirect", req_id=r.req_id,
                                     redirect=hint, success=False),
                            c,
                        )
                    reqs = []
                if reqs:
                    g = 0  # client plane addresses group 0
                    vid = self.payloads.put(g, reqs)
                    self.origin.add(vid)
                    n_prop[g] = 1
                    vbase[g] = vid
                    piggy[vid] = reqs

            # 2. exchange tick frames and step the kernel
            frames = self._slice_outbox(last_out)
            deadline = t0 + self.tick_interval
            piggy.update(self._pending_serve)
            self._pending_serve = {}
            payload_msg: Dict[str, Any] = {
                "pp": piggy,
                "need": sorted(self.missing)[:64],
                "kv_need": self.kv_need,
            }
            if self._pending_kv_serve:
                payload_msg["kv"] = self.statemach.snapshot_items()
                payload_msg["kv_floor"] = self.applied[0]
                self._pending_kv_serve = False
            self.transport.send_tick(
                self.tick,
                {dst: {"msg": frames[dst], **payload_msg}
                 for dst in frames},
            )
            got = self.transport.recv_tick(self.tick, deadline)
            self._ingest_payloads(got)
            inbox = self._assemble_inbox(last_out, got)
            inputs = {
                "n_proposals": jnp.asarray(n_prop),
                "value_base": jnp.asarray(vbase),
                "exec_floor": jnp.asarray(
                    np.broadcast_to(
                        np.asarray(self.applied, np.int32)[:, None],
                        (self.G, self.population),
                    )
                ),
            }
            self.state, last_out, fx = self._step(
                self.state, inbox, inputs
            )

            # 3. durability before the acks in last_out leave (top of next
            # iteration); then apply newly committed slots + leadership
            self._log_votes()
            self._apply_committed(fx)
            self._leader_edges(fx)
            self.tick += 1

            rem = deadline - time.monotonic()
            if rem > 0:
                time.sleep(rem)

    # -------------------------------------------------- payload exchange
    def _ingest_payloads(self, got) -> None:
        # payload piggybacks are unioned across ALL frames a peer sent
        # since our last tick (unlike kernel lanes, they are not
        # cumulative — skipping one could drop a served payload)
        for src, fl in got.items():
            for f in fl or ():
                for vid, batch in f.get("pp", {}).items():
                    if self.payloads.get(0, vid) is None:
                        self.payloads._data[0][vid] = batch
                    self.missing.discard(vid)
                # serve peers' missing payloads / kv requests next tick by
                # folding them into our own piggyback
                for vid in f.get("need", []):
                    b = self.payloads.get(0, vid)
                    if b is not None:
                        self._pending_serve[vid] = b
                if f.get("kv_need") and not self.kv_need:
                    self._pending_kv_serve = True
                if "kv" in f and self.kv_need:
                    self.statemach._kv.update(f["kv"])
                    self.applied[0] = max(self.applied[0], f["kv_floor"])
                    self.kv_need = False

    # ------------------------------------------------------- application
    def _apply_committed(self, fx) -> None:
        cb = int(np.asarray(fx.commit_bar)[0, self.me])
        g = 0
        if cb <= self.applied[g]:
            return
        win_abs = np.asarray(self.state["win_abs"])[g, self.me]
        win_val = np.asarray(self.state["win_val"])[g, self.me]
        W = self.kernel.W
        while self.applied[g] < cb:
            slot = self.applied[g]
            pos = np.where(win_abs == slot)[0]
            if len(pos) == 0:
                # below the window: an install-snapshot jumped us forward;
                # fetch the KV state from peers host-side
                self.kv_need = True
                self.applied[g] = cb
                return
            vid = int(win_val[pos[0]])
            batch = self.payloads.get(g, vid)
            if vid != 0 and batch is None:
                self.missing.add(vid)
                return  # stall the exec floor until the payload arrives
            # durability before client-visible effects (storage.rs intent):
            # the apply record is fsynced before the reply below, so an
            # acked write survives machine crash, not just process restart
            self.wal.do_sync_action(LogAction(
                "append", entry=(g, slot, vid, batch), sync=True
            ))
            if batch is not None:
                mine = vid in self.origin
                for client, req in batch:
                    res = apply_command(self.statemach._kv, req.cmd)
                    if mine:
                        self.external.send_reply(
                            ApiReply("reply", req_id=req.req_id,
                                     result=res),
                            client,
                        )
            self.applied[g] = slot + 1

    def _leader_edges(self, fx) -> None:
        is_l = bool(np.asarray(
            fx.extra.get("is_leader", np.zeros((self.G, self.population)))
        )[0, self.me])
        if is_l != self.was_leader:
            self.ctrl.send_ctrl(
                CtrlMsg("leader_status", {"step_up": is_l})
            )
            self.was_leader = is_l

    # ----------------------------------------------------------- control
    def _handle_ctrl(self) -> Optional[bool]:
        msg = self.ctrl.try_recv_ctrl()
        if msg is None:
            return None
        if msg.kind == "pause":
            self.paused = True
            self.ctrl.send_ctrl(CtrlMsg("pause_reply"))
        elif msg.kind == "resume":
            self.paused = False
            self.ctrl.send_ctrl(CtrlMsg("resume_reply"))
        elif msg.kind == "reset_state":
            if not msg.payload.get("durable", True):
                self.wal.stop()
                try:
                    os.remove(self.wal_path)
                except OSError:
                    pass
            self.ctrl.send_ctrl(CtrlMsg("reset_reply"))
            return True
        elif msg.kind == "take_snapshot":
            kv = self.statemach.snapshot_items()
            snap = StorageHub(self.snapdir)
            snap.do_sync_action(LogAction(
                "append", entry=("kv", kv, self.applied[0]), sync=True
            ))
            snap.stop()
            self.ctrl.send_ctrl(CtrlMsg("snapshot_reply"))
            self.ctrl.send_ctrl(CtrlMsg(
                "snapshot_up_to", {"new_start": self.applied[0]}
            ))
        elif msg.kind == "leave":
            return False
        return None

    def debug_state(self) -> dict:
        """One-line snapshot for wedge diagnosis (VERDICT r2 #1)."""
        st = self.state
        me = self.me
        out = {
            "me": me,
            "tick": self.tick,
            "applied": list(self.applied),
            "kv_need": self.kv_need,
            "missing": sorted(self.missing),
            "paused": self.paused,
            "peers": sorted(self.transport._conns),
            "was_leader": self.was_leader,
        }
        for k in ("leader", "commit_bar", "exec_bar", "vote_bar", "bal_max"):
            if k in st:
                out[k] = np.asarray(st[k])[:, me].tolist()
        return out

    def shutdown(self) -> None:
        self.external.stop()
        self.transport.close()
        self.statemach.stop()
        self.wal.stop()
        self.ctrl.close()
