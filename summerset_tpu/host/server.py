"""ServerReplica: a real networked replica process around the device kernel.

Parity: reference ``GenericReplica`` + ``summerset_server`` (SURVEY.md
§2.2/§2.6) — ``new_and_setup`` composes ControlHub -> StateMachine ->
StorageHub -> TransportHub -> ExternalApi, joins via the manager, then
``run()`` drives the event loop; returning True means crash-restart
(``summerset_server/src/main.rs:127-160``).

TPU-native split: this process owns replica index ``me`` of every group.
Each tick it (1) drains the client batch — requests are routed to groups
by key hash (the multi-group axis is the design's headline: thousands of
consensus groups step in one kernel launch), (2) steps the vectorized
kernel with the inbox assembled from peers' TCP frames, (3) sends its
outbox slice + payload piggybacks, (4) WAL-logs dirty acceptor rows
*before* the acks referencing them leave, applies newly committed slots,
and replies to clients it originated.  Consensus messages ride the device
outbox; request payloads ride host frames keyed by (group, value id) —
the device log stores int32 references only (SURVEY.md §7 hard part (b)).

Leadership, failover, leases, and commit tallies all happen inside the
kernel; this loop reflects ``is_leader`` edges to the manager, redirects
clients when not serving, serves **leased local reads** when the kernel
says the replica may (quorum_leases/quorumlease.rs:10-17 is_local_reader,
bodega/localread.rs:8-26), and drives client ``ConfChange`` requests
through the kernel's conf plane (external.rs:106-121 -> quorumconf.rs).

Durability contract: each kernel declares ``DURABLE_SCALARS`` /
``DURABLE_WINDOWS`` (core/protocol.py); kernels without a declared
contract are refused loudly — never served without durability.
Snapshots: ``take_snapshot`` writes the full KV + applied floors and
compacts the WAL down to one acceptor record per group (parity:
multipaxos/snapshot.rs:121-303 take_new_snapshot + snapshot_discard_log);
startup loads the snapshot before WAL replay (snapshot.rs:189).
"""

from __future__ import annotations

import dataclasses
import functools
import os
import pickle
import queue
import time
import zlib
from typing import Any, Dict, List, Optional, Set, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from ..core import telemetry as dev_telemetry
from ..protocols import make_protocol
from ..utils import wirecodec
from ..utils.errors import SummersetError
from ..utils.logging import pf_info, pf_logger, pf_warn
from .codeword import assigned_sids
from .control import ControlHub
from .external import ExternalApi
from .graftwatch import WatchEmitter
from .health import HealthScorer
from .messages import ApiReply, ApiRequest, CtrlMsg, ShardPayload
from .payload import PayloadStore
from .resharding import RangeHeat, RangeTable
from .statemach import Command, CommandResult, StateMachine, apply_command
from .storage import LogAction, StorageHub
from .telemetry import MetricsRegistry, SlotTraces
from .tracing import FlightRecorder
from .transport import TransportHub

logger = pf_logger("server")

# run-loop stage names for the loop_stage_us histograms (one timing
# system: the old record_breakdown stopwatch dict folded into the
# metrics registry; the reference leader's bd print, mod.rs:932-943,
# now reads the same histograms every server exposes via metrics_dump).
# The serial loop emits the first five; the pipelined loop replaces
# "step" with "inbox" (host-side inbox/input assembly) + "dispatch"
# (the async launch + prefetch kickoff) + "device_wait" (host time
# blocked on the in-flight step) and adds "overlap" (host-stage time
# coincident with the device step — the pipelining win the A/B gates).
_STAGES = ("intake", "exchange", "step", "log", "apply",
           "inbox", "dispatch", "device_wait", "overlap")

# process-wide pipelined-loop default (mirrors wirecodec.default_on):
# per-replica `pipeline` config wins; SMR_PIPELINE flips every tier of
# a bench/soak subprocess tree at once — how the A/B drivers run the
# same workload serial vs pipelined without touching configs
_pipeline_default = os.environ.get(
    "SMR_PIPELINE", "1"
).lower() not in ("0", "false", "no", "off")


def pipeline_default() -> bool:
    """Process-wide pipelined-loop default (env ``SMR_PIPELINE``, on
    unless set to 0/false/no/off)."""
    return _pipeline_default


def set_pipeline_default(on: bool) -> bool:
    """Flip the process-wide default; returns the previous value (the
    in-process A/B harnesses save/restore around each leg)."""
    global _pipeline_default
    prev = _pipeline_default
    _pipeline_default = bool(on)
    return prev


_VID_BITS = 40  # vids fit far below 2**40; keys combine (g << 40) | vid


def _unique_window_keys(val_win: np.ndarray, groups: np.ndarray) -> np.ndarray:
    """Sorted unique combined (group << 40) | vid keys over the selected
    group rows of a (G, ...) value window, computed in one vectorized
    pass.  Replaces per-element Python int loops on the tick path — the
    Python-side cost downstream is proportional to the number of distinct
    (group, vid) pairs (or, with an ``np.isin`` filter, to the NEW pairs
    only), not to G*W."""
    if len(groups) == 0:
        return np.empty(0, np.int64)
    rows = np.asarray(val_win)[groups].reshape(len(groups), -1)
    flat = rows.ravel().astype(np.int64)
    gcol = np.repeat(np.asarray(groups, dtype=np.int64), rows.shape[1])
    m = flat > 0
    if not m.any():
        return np.empty(0, np.int64)
    return np.unique((gcol[m] << _VID_BITS) | flat[m])


def _unique_window_vids(val_win: np.ndarray, groups: np.ndarray) -> dict:
    """{g: [vid, ...]} decode of :func:`_unique_window_keys`."""
    key = _unique_window_keys(val_win, groups)
    if len(key) == 0:
        return {}
    gs = key >> _VID_BITS
    vs = key & ((1 << _VID_BITS) - 1)
    out: dict = {}
    # gs is sorted, so slices per group come from one boundary scan
    bounds = np.nonzero(np.diff(gs))[0] + 1
    for lo, hi in zip(np.concatenate([[0], bounds]),
                      np.concatenate([bounds, [len(gs)]])):
        out[int(gs[lo])] = vs[lo:hi].tolist()
    return out


def _sp_size(sp: ShardPayload) -> int:
    """Approximate pickled wire size of a ShardPayload without
    re-serializing it (the frame encoder pickles the real thing moments
    later; paying a second full pickle just for the egress meter would
    double the payload-plane serialization cost on the hot path)."""
    return 64 + sum(
        np.asarray(a).nbytes + 144 for a in sp.shards.values()
    )


@functools.lru_cache(maxsize=64)
def _shared_step(kernel, mesh_shape=None):
    """One jitted step per (kernel class, geometry, config): kernels are
    hashable by static key, so a crash-restarted replica reuses the
    already-compiled executable instead of re-tracing — restarts come
    back in milliseconds, which the reset/election tests depend on.

    ``mesh_shape=(group_shards, replica_shards)`` compiles the pod-scale
    serving variant (the ``device_mesh`` server knob): the ``[G, R,
    ...]`` state is constrained to the ``(group, replica)`` device mesh
    on entry and exit, so it stays sharded across this host's local
    devices tick to tick while the inbox/outbox/effects seams (host
    TCP + telemetry + flight) are untouched.  No donation here — the
    serving loop feeds the inbox and drains effects every tick, so the
    carry is rebound per call anyway and the host must be free to read
    the previous state between ticks."""
    if mesh_shape is None:
        return jax.jit(kernel.step)
    from ..core import sharding as shardlib

    mesh = shardlib.mesh_for(*mesh_shape)

    def sharded_step(state, inbox, inputs):
        state = shardlib.constrain_state(mesh, state)
        new_state, out, fx = kernel.step(state, inbox, inputs)
        return shardlib.constrain_state(mesh, new_state), out, fx

    return jax.jit(sharded_step)


class ServerReplica:
    def __init__(
        self,
        protocol: str,
        api_addr: Tuple[str, int],
        p2p_addr: Tuple[str, int],
        manager_addr: Tuple[str, int],
        config: Optional[dict] = None,
        num_groups: int = 1,
        window: int = 64,
        tick_interval: float = 0.002,
        backer_dir: str = "/tmp/summerset_tpu",
    ):
        cfg = dict(config or {})
        self.protocol = protocol
        self.api_addr = api_addr
        self.p2p_addr = p2p_addr
        self.tick_interval = tick_interval
        self.G = num_groups
        self.window = window
        # host-side knobs (not kernel config fields)
        self.snapshot_interval = int(cfg.pop("snapshot_interval", 0))
        self.record_breakdown = bool(cfg.pop("record_breakdown", False))
        # ingress backpressure knobs (host/external.py): api_max_batch
        # caps what one tick's intake drains (it DEFINES the ingress
        # capacity api_max_batch / tick_interval that the workload
        # plane's overload soak offers 2x of), api_max_pending bounds
        # the queue — beyond it requests are shed with a retry-after
        # hint instead of buffered without bound
        self.api_max_batch = int(cfg.pop("api_max_batch", 5000))
        self.api_max_pending = int(cfg.pop("api_max_pending", 16384))
        # pod-scale serving: "GxR" shards the [G, R, ...] device state
        # over a (group, replica) mesh of this host's local devices
        # (core/sharding.py); "" = the single-device legacy compile
        self.device_mesh = str(cfg.pop("device_mesh", "") or "")
        # wire-plane codec (utils/wirecodec.py): hot frames on the p2p
        # tick mesh and the api reply path leave in the compact binary
        # form instead of pickle.  None = process default (env
        # SMR_WIRE_CODEC); decode always dispatches per frame, so mixed
        # codec-on/off meshes interoperate (the A/B bench runs exactly
        # that).  Threaded to TransportHub AND ExternalApi below.
        _wc = cfg.pop("wire_codec", None)
        self.wire_codec = (
            wirecodec.default_on() if _wc is None else bool(_wc)
        )
        # pipelined tick loop (the software pipeline over the serving
        # path): the device scan is dispatched asynchronously and
        # drained only at its first consumer (payload ingest +
        # bookkeeping run under it), and the WAL group-commit fsync
        # runs on the logger thread behind a durability fence waited at
        # the next tick's first egress — step N's fsync overlaps the
        # deadline sleep, tick N+1's intake, and its frame build, while
        # client replies and peer frames stay gated on it (see
        # _tick_pipelined).  pipeline=False compiles the exact old
        # serial order — byte-identical digests, the A/B baseline.
        _pl = cfg.pop("pipeline", None)
        self.pipeline = (
            pipeline_default() if _pl is None else bool(_pl)
        )
        # pipeline registers: the in-flight dispatched step (device
        # arrays, unforced), the host-view np cache pinned to the last
        # DRAINED state, and the durability fence token gating frames/
        # replies on the background fsync
        self._pl: Optional[Dict[str, Any]] = None
        self._np_cache: Dict[str, np.ndarray] = {}
        self._fence_token: Optional[int] = None
        self._prefetch_keys: Optional[List[str]] = None
        self._bd_last_print = time.monotonic()
        self.near_quorum_reads = bool(cfg.pop("near_quorum_reads", False))
        # telemetry plane: one registry threaded through every hub seam
        # (host/telemetry.py); loop-stage histograms are always on — the
        # record_breakdown knob now only controls the 5s summary print.
        # trace_sample: every n-th proposed batch gets a slot trace
        # (arrival → proposed → committed → applied → replied); 0 = off.
        self.metrics = MetricsRegistry()
        # graftscope flight recorder (host/tracing.py): a per-server ring
        # of typed monotonic-stamped events threaded through every hub
        # seam; flight_record=0 compiles the recorder-off variant the
        # tier-2f overhead gate compares against
        self.flight = FlightRecorder(
            capacity=int(cfg.pop("flight_capacity", 8192)),
            enabled=bool(cfg.pop("flight_record", True)),
        )
        self.traces = SlotTraces(
            self.metrics, sample_every=int(cfg.pop("trace_sample", 8)),
            flight=self.flight,
        )
        # graftwatch streaming (host/graftwatch.py): every watch_ticks
        # ticks the replica ships one delta frame (counter deltas, gauge
        # values, histogram window snapshots) over the ctrl connection
        # as a one-way watch_frame; graftwatch=0 compiles the emitter
        # out entirely — the streaming-OFF ablation variant
        self.graftwatch = bool(cfg.pop("graftwatch", True))
        self.watch_ticks = max(1, int(cfg.pop("watch_ticks", 50)))
        self.watch: Optional[WatchEmitter] = None
        self._trace_replied: List[Tuple[int, int]] = []
        # gray-failure plane (host/health.py): the quorum-median outlier
        # scorer over signals the hubs already emit.  health_enabled
        # compiles the whole plane out; health_mitigation gates only the
        # ACTIONS (leader demotion, lease-read steering) so the soak can
        # run an observe-only twin of every fail-slow cell.
        self.health_enabled = bool(cfg.pop("health_enabled", True))
        self.health_mitigation = bool(cfg.pop("health_mitigation", True))
        self.health_eval_ticks = max(1, int(cfg.pop("health_eval_ticks", 10)))
        # demotion pacing: the demote kernel input stays armed for
        # health_demote_ticks (long enough for peers to observe the
        # abdication), and a new demotion cannot fire for
        # health_cooldown_ticks (anti-flap, on top of the scorer's own
        # hysteresis)
        self.health_demote_ticks = int(cfg.pop("health_demote_ticks", 40))
        self.health_cooldown_ticks = int(
            cfg.pop("health_cooldown_ticks", 800)
        )
        _health_kw = {
            k: cfg.pop(f"health_{k}")
            for k in ("ratio", "hysteresis", "clear_after", "stale_s")
            if f"health_{k}" in cfg
        }
        # nemesis clock-skew: wall-clock stretch factor on the tick
        # interval (fault_ctl {"skew": f}); 1.0 = healthy
        self._tick_scale = 1.0
        # nemesis snapshot crash point (fault_ctl {"snap_crash": n}): the
        # next n snapshots crash between the snapshot write and the WAL
        # truncate — the half-compacted window recovery must survive
        self._snap_crash = 0
        # set by _recover_from_snapshot when a PRESENT snapshot fails to
        # load; fatal if the WAL turns out to be compacted (the snapshot
        # then held committed state nothing else can replay)
        self._snap_unreadable: Optional[str] = None
        # the applied floors the snapshot ACTUALLY restored (None = no
        # snapshot loaded); a compacted WAL's snap_floor marker demands
        # a snapshot covering its floors — missing or stale is as fatal
        # as unreadable
        self._snap_floors: Optional[List[int]] = None

        # control plane first: the manager assigns our id (control.rs:43)
        self.ctrl = ControlHub(manager_addr)
        self.me = self.ctrl.me
        self.population = self.ctrl.population
        self.flight.me = self.me
        if self.graftwatch:
            self.watch = WatchEmitter(
                self.metrics, self.me, span_ticks=self.watch_ticks,
                tier="shard", group=0,
            )

        # gray-failure scorer (host/health.py): beacons ride the tick
        # frames, every replica assembles the same signal table, and the
        # indicted leader discovers its own indictment locally
        self.health = (
            HealthScorer(self.me, self.population, **_health_kw)
            if self.health_enabled else None
        )
        self._health_self_bad = False
        # demotion state machine: _demote_until arms the kernel demote
        # input; "revoking" means a QL/Bodega lease revoke (an empty-
        # responders ConfChange through the revoke-then-adopt barrier)
        # must complete before the abdication
        self._demote_until = 0
        self._demote_cooldown_until = 0
        self._demote_revoke_deadline: Optional[int] = None
        # pre-revoke responders (bitmask-decoded list), restored if the
        # indictment clears while the revoke ConfChange is in flight —
        # a false alarm must not leave lease-local reads revoked forever
        self._demote_restore_resp: Optional[List[int]] = None
        # autopilot-initiated demotion: when the policy tier (rather
        # than the health plane's own indictment path) started a
        # QL/Bodega revoke-then-demote, the health plane's false-alarm
        # restore must not cancel it — _autopilot_tick owns resolution
        self._ap_demote_pending = False
        self.metrics.counter_add("leader_demotions", 0)
        self.metrics.gauge_set("health_score", 1.0)
        # live resharding (host/resharding.py): counters/gauges declared
        # up front so scrapes see them at zero; the cutover histogram
        # gets one zero sample for the same always-present contract
        self.metrics.counter_add("reshard_splits", 0)
        self.metrics.counter_add("reshard_merges", 0)
        self.metrics.gauge_set("range_heat", 0.0)
        self.metrics.observe("reshard_cutover_us", 0)
        self.metrics.counter_add("reshard_seal_expired", 0)
        # ordered range reads (scan plane): pre-registered at zero so
        # "no scans yet" reads as 0, not a missing series
        self.metrics.counter_add("scan_served", 0)
        self.metrics.counter_add("scan_shed", 0)
        self.metrics.counter_add("scan_keys", 0)
        # autopilot series (host/autopilot.py): zero until a driver in
        # act mode announces / actuates here
        self.metrics.counter_add("autopilot_actions", 0)
        self.metrics.gauge_set("autopilot_mode", 0.0)
        self.metrics.gauge_set("autopilot_cooldown", 0.0)
        # graftscope ring accounting + graftwatch streaming: zero until
        # the ring actually overwrites / the first frame ships
        self.metrics.counter_add("trace_dropped_total", 0)
        self.metrics.counter_add("watch_frames_total", 0)
        self.metrics.observe("watch_emit_us", 0)

        # protocol kernel over [G, R]; host applier drives the exec bar
        kercfg_cls = type(
            make_protocol(protocol, 1, self.population, 32).config
        )
        known = {f.name for f in dataclasses.fields(kercfg_cls)}
        kcfg = kercfg_cls(**{k: v for k, v in cfg.items() if k in known})
        if hasattr(kcfg, "exec_follows_commit"):
            kcfg.exec_follows_commit = False
        if hasattr(kcfg, "max_proposals_per_tick"):
            kcfg.max_proposals_per_tick = 1  # one ReqBatch per group/tick
        if protocol.lower() == "epaxos":
            # leaderless multi-bucket intake: one ReqBatch PER KEY BUCKET
            # per group per tick, vids passed as an explicit list
            kcfg.max_proposals_per_tick = max(
                1, min(kcfg.num_key_buckets, window // 2)
            )
        # EPaxos conflict detection rides vid % num_key_buckets: the host
        # mints vids in residue classes that encode (key bucket, replica)
        # so same-key commands interfere and different-key commands stay
        # concurrent (see _intake's per-bucket proposal path)
        self.kernel = make_protocol(
            protocol, self.G, self.population, window, kcfg
        )
        if self.kernel.DURABLE_SCALARS is None:
            raise SummersetError(
                f"protocol {protocol} declares no durable acceptor "
                "contract; refusing to serve it without durability "
                "(see ProtocolKernel.DURABLE_SCALARS)"
            )
        # leader demotion is kernel-assisted: only families declaring the
        # `demote` input (MultiPaxos + Raft and their variants) get the
        # mitigation path; leaderless/static kernels keep scoring only
        self._demote_supported = (
            "demote" in {n for n, _ in self.kernel.EXTRA_INPUTS}
        )
        self.state = self.kernel.init_state(seed=0)
        # device metric lanes ride the jitted step's state (row `me` of
        # the [G, R, K] block is this server's [G, K] matrix; peers'
        # rows stay zero — each server scrapes only its own)
        dev_telemetry.attach(self.state, self.G, self.population)
        # pod-scale serving mesh: validated here (axis-named errors),
        # state placed onto it AFTER recovery restores acceptor rows
        self._mesh = None
        self._mesh_shape = None
        if self.device_mesh:
            from ..core import sharding as shardlib

            gs, rs = shardlib.parse_mesh(self.device_mesh)
            self._mesh = shardlib.mesh_for(gs, rs)
            shardlib.check_mesh(self._mesh, self.G, self.population)
            self._mesh_shape = (gs, rs)
        self._step = _shared_step(self.kernel, self._mesh_shape)

        os.makedirs(backer_dir, exist_ok=True)
        self.wal_path = os.path.join(backer_dir, f"r{self.me}.wal")
        self.snap_path = os.path.join(backer_dir, f"r{self.me}.snap")
        # checked BEFORE the StorageHub open creates the wal file: this
        # is what distinguishes a crash-restart (durable state found)
        # from a first boot in the flight recorder's restart event
        self._cold_boot = not (
            os.path.exists(self.wal_path) or os.path.exists(self.snap_path)
        )
        self.wal = StorageHub(
            self.wal_path, registry=self.metrics, flight=self.flight
        )
        self.wal.health = self.health
        self.statemach = StateMachine()
        self.payloads = PayloadStore(self.G)
        self.applied = [0] * self.G        # exec floor per group (own row)
        self._sig: Optional[np.ndarray] = None  # durable-row dirty cache
        self._logged_vids: Dict[int, set] = {
            g: set() for g in range(self.G)
        }
        # sorted combined (g << 40)|vid keys mirroring _logged_vids, for
        # the C-speed np.isin new-vid filter on the _log_votes tick path
        self._logged_keys = np.empty(0, np.int64)
        self.origin: Set[Tuple[int, int]] = set()   # (g, vid) we proposed
        self.missing: Set[Tuple[int, int]] = set()  # committed, no payload
        # group commit: appends within a tick are sync=False; one fsync
        # runs before any reply/ack referencing them leaves the process
        self._wal_dirty = False
        self._reply_queue: List[Tuple[int, ApiReply]] = []
        # near-quorum reads (parity: multipaxos/quorumread.rs): per-key
        # last applied write slot + in-flight read-query bookkeeping
        self._wslot: Dict[str, int] = {}
        self._qreads: Dict[int, dict] = {}
        self._qread_next = 0
        self._pending_rq: Dict[int, list] = {}  # dst -> [(rid, key, g)]
        self._pending_rqr: Dict[int, list] = {}
        self.kv_need: Set[int] = set()     # groups that jumped past window
        self.paused = False
        self.stopping = False  # cooperative stop for embedded harnesses
        self.was_leader = False
        self._is_leader = np.zeros(self.G, bool)
        self._leader_hint = np.full(self.G, -1, np.int64)
        self._last_extra: Dict[str, np.ndarray] = {}
        self.tick = 0
        self._snap_last = 0           # sum(applied) at last auto-snapshot
        self._pending_serve: Dict[Tuple[int, int], Any] = {}
        self._pending_kv_serve = False
        # commit feed (serving-plane read tier, host/ingress.py): a
        # learner subscribes with ApiRequest("sub") and receives every
        # applied put as ordered (seq, key, value) notes; "probe"
        # requests answer the lease-local-read verdict for a key's group
        # plus the current feed seq ON THE REPLICA THREAD — the same
        # place the fused local-read decision is made, so the learner's
        # freshness rule (learned seq >= probe seq, notes and probe
        # replies FIFO on one writer) inherits the identical lease
        # safety argument.  Zero cost with no subscribers: the seq only
        # advances (and notes only accumulate) while _subs is non-empty.
        self._subs: Dict[int, bool] = {}
        self._sub_seq = 0
        self._sub_notes: List[Tuple[int, str, Any]] = []
        # ordered range reads (scan plane): commit-bar barrier scans in
        # flight on the fused fallback path — sbid -> {client, req_id,
        # cmd, need (groups whose marker hasn't applied), tick (for GC)}
        self._scan_pend: Dict[int, dict] = {}
        self._scan_next = 1
        # client ConfChange plane (external.rs:106-121): one in flight
        self._conf_kind = (
            "ql" if "ql_out" in self.state
            else "bodega" if "conf_resp" in self.state
            else None
        )
        self._conf_active: Optional[dict] = None
        # entries: (client id, request) from the data plane, or
        # (None, request) for manager-relayed installs
        self._conf_queue: List[Tuple[Optional[int], ApiRequest]] = []
        self._conf_seq_seen = 0
        # live resharding plane (host/resharding.py): installed range
        # overrides (rangetab), ranges sealed awaiting adoption (rc_id ->
        # change dict + sealed_at), adopted rc_ids (idempotency), the
        # newest install_ranges seq seen, adopt re-propose marks (tick of
        # last proposal per rc_id), the adopt proposals awaiting intake,
        # and per-key heat at the api seam.  _range_adopted means the
        # adopt command EXECUTED here (its KV/wslot merge happened);
        # _range_override means only the routing override was learned
        # from a manager re-announce — the replicated adopt at this
        # replica's destination-group slot must still merge, so the two
        # sets are kept strictly apart (conflating them skipped the
        # merge and permanently diverged re-announced replicas)
        self.rangetab = RangeTable()
        self._range_sealed: Dict[int, dict] = {}
        self._range_adopted: Set[int] = set()
        self._range_override: Set[int] = set()
        self._range_seq_seen = 0
        self._range_adopt_mark: Dict[int, int] = {}
        self._range_adopt_ready: List[Tuple[int, ApiRequest]] = []
        self._range_heat = RangeHeat()
        # seal-TTL escape hatch: a sealed range whose destination group
        # never produced a leader (so the manager never granted adopt
        # intent) is un-sealed after seal_ttl_ticks and the source
        # resumes serving it — bounding worst-case unavailability at the
        # cost of rolling back the pending change.  The manager's
        # adopt_intent grant is the linearizability pivot: expiry is
        # only honored pre-grant, and the grant set makes adopt-vs-
        # expire race-free (both resolve on the manager's event loop).
        # seal_ttl_ticks=0 disables expiry.
        self.seal_ttl_ticks = int(cfg.pop("seal_ttl_ticks", 2400))
        self._range_adopt_granted: Set[int] = set()
        self._range_expired: Set[int] = set()
        self._range_intent_sent: Dict[int, int] = {}
        self._range_expire_sent: Dict[int, int] = {}
        # EPaxos: leaderless — every replica proposes into its own row;
        # execution runs through the exact host Tarjan applier.  Every
        # key bucket with pending requests proposes in the SAME tick
        # (vids carried as an explicit list; residue encodes the bucket);
        # _ep_defer only holds overflow beyond max_proposals_per_tick.
        self._epaxos = "st2" in self.state
        self._ep_exec: Dict[int, Any] = {}
        self._ep_defer: Dict[int, list] = {}
        self._ep_prop_vids = (
            np.zeros((self.G, self.kernel.config.max_proposals_per_tick),
                     np.int32)
            if self._epaxos else None
        )
        if self._epaxos:
            from .epaxos_exec import EPaxosExecutor

            for g in range(self.G):
                self._ep_exec[g] = EPaxosExecutor(
                    self.population, window, self._make_ep_apply(g)
                )
                self._ep_defer[g] = []
        # Crossword: host predictive shard-assignment (linreg + qdisc);
        # assignment_adaptive=False pins both the kernel's reactive policy
        # AND this host override to init_spr (deterministic slicing)
        self._adaptive = None
        if "cur_spr" in self.state and getattr(
            self.kernel.config, "assignment_adaptive", True
        ):
            from .adaptive import CrosswordAdaptive

            self._adaptive = CrosswordAdaptive(
                self.population, self.kernel.data_shards, self.me,
            )
            self._batch_bytes = 0.0  # EWMA of proposed batch sizes
            self._spr_tick = [self.kernel.data_shards] * self.G

        # codeword payload plane (RS erasure-coded family): the kernel
        # runs the coded control plane; this store ships/holds the actual
        # shard bytes so peer payload frames shrink to ~1/d of the batch
        # (rspaxos/mod.rs:597-608, crossword/gossiping.rs:14-193)
        self.codewords = None
        self._cw_dj = 1
        self._cw_spr0 = 1
        if hasattr(self.kernel, "num_data") and "full_bar" in self.state:
            from ..ops.rscoding import RSCode
            from .codeword import CodewordStore

            if "cur_spr" in self.state:  # Crossword: T shards, dj-wide base
                cw_T = self.kernel.total_shards
                cw_d = self.kernel.data_shards
                self._cw_dj = self.kernel.dj
                self._cw_spr0 = self.kernel.init_spr
            else:  # RSPaxos / CRaft: shard r -> replica r
                cw_T = self.population
                cw_d = self.kernel.num_data
                self._cw_spr0 = 1
            self.codewords = CodewordStore(
                self.G, RSCode(cw_d, cw_T - cw_d), cw_T, self._cw_dj
            )
        self._cw_first_missing: Dict[Tuple[int, int], int] = {}
        self._pending_shards: Dict[int, dict] = {}  # dst -> {(g,vid): sp}
        self._pending_cw: Dict[int, dict] = {}      # dst -> {(g,vid): sp}
        # per-peer payload-plane egress accounting (bytes + payload count
        # of the pp/ps frame parts) — the measurable shard-economy hook
        # the cluster tests and PERF.md read; bytes/count is the per-
        # payload frame size (~batch for full copies, ~batch/d + parity
        # overhead for shard sends)
        self.pp_bytes = [0] * self.population
        self.pp_items = [0] * self.population
        self.cw_bytes = [0] * self.population  # gossip-reply egress
        # CRaft full-copy fallback mirror (host view of _append_mode; may
        # trail the kernel's stamp by one tick — the same documented
        # weakening window as the reference's global latch,
        # craft/mod.rs:280-283)
        self._craft_mode = "win_full" in self.state

        # near-quorum reads need the MultiPaxos-family vote-run contract
        # and a single-writer-per-slot log (not the EPaxos 2-D space)
        self._nqr_ok = (
            self.near_quorum_reads
            and "vote_bar" in self.state
            and not self._epaxos
        )

        self._recover_from_snapshot()
        self._recover_from_wal()
        if self._mesh is not None:
            # place the recovered state onto the serving mesh; every
            # subsequent tick's output is constrained back to it, so the
            # [G, R, ...] plane never migrates off its shards
            from ..core.sharding import shard_pytree

            self.state = shard_pytree(self._mesh, self.state)
        # flight event: bring-up recovery done.  cold=False (durable
        # state predated this boot) is the restarted-replica marker the
        # crash reports / repro bundles look for; cold=True is a first
        # boot on an empty backer.
        self.flight.record(
            "restart", cold=self._cold_boot, wal_size=self.wal.size,
            applied=int(sum(self.applied)),
        )

        # p2p mesh join (multipaxos/mod.rs:717-737): proactively connect to
        # lower-id peers, accept from higher ids.  The join is re-sent until
        # the mesh completes — concurrent bring-up means a lower-id peer may
        # join after us, so one connect_to_peers snapshot is not enough.
        try:
            self.transport = TransportHub(
                self.me, self.population, p2p_addr,
                registry=self.metrics, flight=self.flight,
                codec=self.wire_codec,
            )
            self.transport.health = self.health
            join = CtrlMsg("new_server_join", {
                "protocol": protocol,
                "api_addr": api_addr,
                "p2p_addr": p2p_addr,
            })
            connected: set = set()
            deadline = time.monotonic() + 60
            while True:
                self.ctrl.send_ctrl(join)
                try:
                    msg = self.ctrl.recv_ctrl(timeout=3)
                except (queue.Empty, SummersetError):
                    # the only two recv_ctrl outcomes besides a frame:
                    # poll timeout and manager-gone — both mean "re-send
                    # the join and keep waiting".  Anything else (a
                    # decode bug, a poisoned frame) must surface, not
                    # dissolve into an infinite join loop.
                    msg = None
                if msg is not None and msg.kind == "connect_to_peers":
                    for peer, addr in msg.payload["to_peers"].items():
                        p = int(peer)
                        if (
                            p in connected
                            or self.transport.connected(p)
                        ):
                            continue
                        try:
                            self.transport.connect_to_peer(p, addr)
                        except (SummersetError, OSError):
                            # the peer may itself be mid-crash-restart
                            # (nemesis finding: a WAL-fault self-crash
                            # racing a manager reset): retry next round,
                            # or it rejoins later and dials us — either
                            # way killing OUR bring-up over it would
                            # cascade one crash into two
                            continue
                        connected.add(p)
                try:
                    self.transport.wait_for_group(timeout=2)
                    break
                except Exception:
                    if time.monotonic() > deadline:
                        raise

            self.external = ExternalApi(
                api_addr, max_batch_size=self.api_max_batch,
                max_pending=self.api_max_pending,
                registry=self.metrics, flight=self.flight,
                codec=self.wire_codec,
            )
            # recovery/attach mutated the state dict above: start the
            # host-view cache fresh, and seed the outbox register the
            # first tick's frames slice from (both loop modes)
            self._np_cache = {}
            self._last_out = {
                k: jnp.asarray(v)
                for k, v in self.kernel.zero_outbox().items()
            }
        except BaseException:
            # failed bring-up must release every port/handle it grabbed:
            # the supervisor retries the constructor, and a leaked p2p
            # listener or WAL handle would wedge every retry on rebind
            tr = getattr(self, "transport", None)
            if tr is not None:
                try:
                    tr.close()
                # graftlint: disable=H106 -- best-effort unwind: the original bring-up exception is re-raised below, and a close() failure on a half-built hub must not mask it
                except Exception:
                    pass
            for closer in (
                self.wal.stop, self.statemach.stop, self.ctrl.close,
            ):
                try:
                    closer()
                # graftlint: disable=H106 -- best-effort unwind: the original bring-up exception is re-raised below, and a stop() failure on a half-built hub must not mask it
                except Exception:
                    pass
            raise
        pf_info(logger, f"replica {self.me} ready")

    # ------------------------------------------------------------- routing
    def group_of(self, key: str) -> int:
        """Key -> consensus group (the multi-group serving axis; parity
        role: the reference runs one cluster per keyspace, SURVEY §2.8
        'group batching')."""
        if self.G == 1:
            return 0
        return zlib.crc32(key.encode()) % self.G

    def route_group(self, key: str) -> int:
        """Live placement: installed range overrides first (adopted
        splits/merges, host/resharding.py), hash placement otherwise."""
        if len(self.rangetab):
            e = self.rangetab.lookup(key)
            if e is not None:
                return int(e["group"]) % self.G
        return self.group_of(key)

    def _range_sealed_for(self, key: str) -> Optional[dict]:
        """The sealed-range change covering ``key``, if any (the set is
        tiny — at most the in-flight cutovers — so a scan is fine)."""
        for ch in self._range_sealed.values():
            end = ch.get("end")
            if key >= ch["start"] and (end is None or key < end):
                return ch
        return None

    def _range_seal_overlaps(self, start: str, end: Optional[str]) -> bool:
        """Does any sealed (mid-cutover) range intersect the half-open
        span ``[start, end)``?  A scan touching a sealed range cannot be
        proven consistent against the adopting group, so it is shed
        BEFORE any proposal — the same never-acked-then-shed guarantee
        point gets have."""
        for ch in self._range_sealed.values():
            ce = ch.get("end")
            if (end is None or ch["start"] < end) and (
                    ce is None or start < ce):
                return True
        return False

    # ----------------------------------------------------- host state views
    def _np_state(self, k: str) -> np.ndarray:
        """Host view of one state leaf, pinned to the last DRAINED step.

        The pipelined loop keeps ``self.state`` at the newest drained
        results while a later step is in flight on the device; every
        host read goes through this one seam so nothing on the host path
        accidentally forces the in-flight computation (the ``np.asarray``
        right after ``_step`` that the serial loop paid).  Views are
        cached per leaf until the next drain — the serial loop reuses
        the same cache, which only deduplicates conversions it already
        made every tick."""
        # setdefault on __dict__: harness-built bare instances
        # (Server.__new__ in unit tests) get a cache on first read
        cache = self.__dict__.setdefault("_np_cache", {})
        v = cache.get(k)
        if v is None:
            v = np.asarray(self.state[k])
            cache[k] = v
        return v

    def _set_state(self, new_state) -> None:
        """Swap in a new device state and invalidate the host views."""
        self.state = new_state
        self._np_cache = {}

    # ------------------------------------------------------------ recovery
    def _recover_from_snapshot(self) -> None:
        """Load the snapshot (full KV + applied floors) before WAL replay
        (parity: snapshot.rs:189 recover_from_snapshot)."""
        if not os.path.exists(self.snap_path):
            return
        try:
            with open(self.snap_path, "rb") as f:
                kind, kv, meta = pickle.load(f)
            if not isinstance(meta, dict):  # pre-r4 bare floors list
                meta = {"applied": list(meta)}
            assert kind == "kv"
        except Exception as e:
            # defer the verdict to _recover_from_wal: with a FULL
            # (never-compacted) WAL the replay alone rebuilds everything
            # and the bad snapshot is truly ignorable; if the WAL was
            # compacted to the snapshot floor, proceeding would silently
            # lose committed state — that case is fatal there
            self._snap_unreadable = repr(e)
            pf_warn(
                logger,
                f"snapshot unreadable: {e} — fatal unless the WAL still "
                "holds full history",
            )
            return
        self.statemach._kv.update(kv)
        floors = meta["applied"]
        self._snap_floors = [int(fl) for fl in floors[: self.G]]
        for g, fl in enumerate(floors[: self.G]):
            self.applied[g] = max(self.applied[g], int(fl))
        for k, s in meta.get("wslots", {}).items():
            self._wslot[k] = max(self._wslot.get(k, -1), int(s))
        # pre-fix snapshots carried no radopted list: every install was a
        # local adoption then, so default to treating all of them as such
        radopted = meta.get("radopted")
        radopted = None if radopted is None else {int(r) for r in radopted}
        for entry in meta.get("ranges", []):
            # range installs are snapshot state like the KV they moved:
            # restore the override table, and restore each rc_id into
            # the SAME idempotency set it lived in — an override-only
            # install must leave the adopt replay free to merge
            rc_id = int(entry["rc_id"])
            if radopted is None or rc_id in radopted:
                self._range_adopted.add(rc_id)
            else:
                self._range_override.add(rc_id)
            self.rangetab.install(entry)
        for ch in meta.get("rseals", []):
            # sealed-but-unadopted at snapshot time: re-seal (fresh
            # sealed_at — the cutover clock restarts with the process;
            # seal-complete is re-learned from the manager re-announce)
            if int(ch["rc_id"]) not in self._range_adopted \
                    and int(ch["rc_id"]) not in self._range_override:
                ch = dict(ch)
                ch["sealed_at"] = time.monotonic()
                self._range_sealed[int(ch["rc_id"])] = ch
        for g, rows in enumerate(meta.get("ep_rows", [])[: self.G]):
            ex = self._ep_exec.get(g)
            if ex is not None:
                ex.floor = [
                    max(a, int(b)) for a, b in zip(ex.floor, rows)
                ]
        pf_info(
            logger,
            f"recovered snapshot: {len(kv)} keys, floors {floors[:4]}...",
        )

    def _recover_from_wal(self) -> None:
        """Replay the WAL: apply records rebuild payloads + KV + exec
        floors; the last durable record per group rebuilds the kernel
        row's acceptor state (parity: recovery.rs replay loop SURVEY.md
        §3.4 + raft durable curr_term/voted_for, raft/mod.rs:144-176)."""
        off = 0
        n = 0
        votes: Dict[int, dict] = {}
        while True:
            res = self.wal.do_sync_action(LogAction("read", offset=off))
            if not res.offset_ok or res.entry is None:
                break
            rec = res.entry
            if isinstance(rec, tuple) and rec and rec[0] == "snap_floor":
                # compaction marker: _take_snapshot writes this as the
                # compacted WAL's first record.  Apply records below
                # these floors exist ONLY in the snapshot now — so a
                # snapshot that is unreadable, MISSING (lost file, or a
                # crash where the WAL rename was durable but the
                # snapshot rename was not), or STALE (floors below the
                # marker's) means committed, acked state is gone, and
                # serving anyway would un-commit it.  Crash instead so
                # the supervisor surfaces the corruption.
                marker = [int(fl) for fl in rec[1][: self.G]]
                if self._snap_unreadable is not None:
                    why = f"unreadable ({self._snap_unreadable})"
                elif self._snap_floors is None:
                    why = "missing"
                elif any(sf < mf for sf, mf in
                         zip(self._snap_floors, marker)):
                    why = (f"stale (snapshot floors {self._snap_floors} "
                           f"below the marker's)")
                else:
                    why = None
                if why is not None:
                    raise SummersetError(
                        f"snapshot {why} but the WAL was compacted to "
                        f"floors {marker} — committed state below the "
                        "snapshot floor is unrecoverable; refusing to "
                        "serve"
                    )
                for g, fl in enumerate(marker):
                    self.applied[g] = max(self.applied[g], int(fl))
            elif isinstance(rec, tuple) and rec and rec[0] == "vote":
                g, v = rec[1], rec[2]
                votes[g] = v
                for vid, batch in v.get("pp", {}).items():
                    self.payloads.install(g, vid, batch, overwrite=False)
                    self._logged_vids[g].add(vid)
                for vid, (dlen, sh) in v.get("cw", {}).items():
                    # shard-only durable record: a recovered quorum's
                    # shards re-serve committed values through the gossip
                    # plane (reference Reconstruct reads)
                    if self.codewords is not None:
                        self.codewords.add_shards(
                            g, vid, dlen, sh, assigned=True
                        )
                    self.payloads.note_seen(g, vid)
                    self._logged_vids[g].add(vid)
            elif isinstance(rec, tuple) and rec and rec[0] == "rseal":
                # a range sealed before the crash and (as far as this WAL
                # knows) never adopted: re-seal it so recovery cannot
                # admit ops the pre-crash replica was already refusing.
                # A later adopt record (ours or a manager re-announce)
                # clears it exactly as it would have live.
                ch = dict(rec[1])
                if int(ch["rc_id"]) not in self._range_adopted \
                        and int(ch["rc_id"]) not in self._range_override:
                    ch["sealed_at"] = time.monotonic()
                    self._range_sealed[int(ch["rc_id"])] = ch
            elif isinstance(rec, tuple) and rec and rec[0] == "eapply":
                # EPaxos exec record: replay in logged (= execution)
                # order; per-row floors advance contiguously
                _, g, row, col, vid, batch = rec
                if batch is not None:
                    self.payloads.install(g, vid, batch)
                    for client, req in batch:
                        if req.cmd is not None:
                            apply_command(self.statemach._kv, req.cmd)
                ex = self._ep_exec.get(g)
                if ex is not None and col >= ex.floor[row]:
                    ex.floor[row] = col + 1
                self.applied[g] = sum(
                    self._ep_exec[g].floor
                ) if g in self._ep_exec else self.applied[g]
            else:
                g, slot, vid, batch = rec
                self.payloads.install(g, vid, batch)
                if batch is not None and slot >= self.applied[g]:
                    for client, req in batch:
                        if req.cmd is None:
                            continue
                        if req.cmd.kind == "adopt":
                            # replicated range adoption replays exactly
                            # like it applied live (idempotent per rc_id)
                            self._apply_adopt(
                                req.cmd.value, announce=False,
                                recovery=True,
                            )
                            continue
                        if req.cmd.kind == "put":
                            ent = self.rangetab.lookup(req.cmd.key)
                            if ent is not None and \
                                    int(ent["group"]) % self.G != g:
                                floors = ent.get("floors") or []
                                fg = int(floors[g]) if g < len(floors) \
                                    else 0
                                if slot < fg:
                                    # straggler below the handoff floor:
                                    # its value already rode the adopt
                                    # snapshot — re-applying would
                                    # regress the moved key
                                    continue
                        apply_command(self.statemach._kv, req.cmd)
                        if req.cmd.kind == "put":
                            k = req.cmd.key
                            self._wslot[k] = max(
                                self._wslot.get(k, -1), slot
                            )
                self.applied[g] = max(self.applied[g], slot + 1)
            off = res.end_offset
            n += 1
        if off < self.wal.size:
            # torn tail: a crash mid-group-commit left a partial record
            # (nothing beyond it was ever acked — acks wait for the
            # fsync).  Truncate it away, or post-restart appends would
            # land past garbage that a LATER recovery cannot read through
            # — silently losing fsynced, acked writes.
            pf_warn(
                logger,
                f"truncating torn WAL tail at {off} (size {self.wal.size})",
            )
            self.wal.do_sync_action(
                LogAction("truncate", offset=off, sync=True)
            )
        for g, v in votes.items():
            self.kernel.restore_durable(
                self.state, g, self.me, v, self.applied[g]
            )
        self._rebuild_logged_keys()
        if n:
            pf_info(
                logger,
                f"recovered {n} WAL records ({len(votes)} acceptor rows)",
            )

    # ----------------------------------------------------------- durability
    def _wal_append(self, entry: Any) -> None:
        """One unsynced WAL append on the tick path, routed per loop
        mode: the serial loop submits-and-waits (the exact old order —
        byte-identical digests with ``pipeline=False``), the pipelined
        loop fires-and-forgets onto the logger thread and settles at the
        durability fence — a failed append surfaces at ``_fence_wait``,
        before any frame or reply gated on it leaves."""
        if self.pipeline:
            self.wal.append_nowait(entry)
        else:
            self.wal.do_sync_action(
                LogAction("append", entry=entry, sync=False)
            )
        self._wal_dirty = True

    def _fence_begin(self) -> None:
        """Open the durability fence over every record appended since
        the last one: a background group-commit sync point whose token
        ``_fence_wait`` blocks on.  No-op on a clean tick."""
        if self._wal_dirty:
            self._fence_token = self.wal.flush_token()
            self._wal_dirty = False

    def _fence_wait(self) -> None:
        """THE durability fence: block until the open token's fsync
        completed.  Nothing a step computed — votes/acks in frames,
        client replies, commit-feed notes — may leave the process
        before this returns; a background append or fsync failure
        raises here (``SummersetError``) and crashes the replica with
        everything gated on the token still unsent.  Idempotent: the
        first wait consumes the token."""
        token = self._fence_token
        if token is None:
            return
        self._fence_token = None
        self.wal.wait_flush(token)

    def _rebuild_logged_keys(self) -> None:
        ks = [
            (g << _VID_BITS) | v
            for g, s in self._logged_vids.items() for v in s
        ]
        self._logged_keys = (
            np.asarray(sorted(ks), np.int64) if ks
            else np.empty(0, np.int64)
        )

    def _log_votes(self) -> None:
        """Durably log dirty acceptor rows BEFORE the outbox carrying the
        corresponding acks is released (next tick's send).

        Parity: the reference appends PrepareBal/AcceptData and fsyncs
        before a follower sends AcceptReply (durability.rs:85-216) and
        Raft persists curr_term/voted_for (raft/mod.rs:144-176).  Payload
        batches for newly voted value ids ride the same record so a
        crashed-and-recovered quorum can re-serve committed values even if
        every replica restarts.  Dirty-group detection is one vectorized
        signature compare — O(G) python work only for groups that changed.
        """
        ker = self.kernel
        me = self.me
        scal = {
            k: self._np_state(k)[:, me] for k in ker.DURABLE_SCALARS
        }
        wins = {
            k: self._np_state(k)[:, me] for k in ker.DURABLE_WINDOWS
        }
        parts = [
            a.reshape(self.G, -1).astype(np.int64)
            for a in list(scal.values()) + list(wins.values())
        ]
        sig = np.concatenate(parts, axis=1)
        if self._sig is not None and sig.shape == self._sig.shape:
            dirty = np.nonzero((sig != self._sig).any(axis=1))[0]
        else:
            dirty = np.arange(self.G)
        self._sig = sig
        if len(dirty) == 0:
            return
        val_win = wins[ker.VALUE_WINDOW]
        # one vectorized unique over all dirty groups' windows + an isin
        # filter against the already-logged keys, instead of a Python int
        # conversion per window element per group — at the bench shape
        # (G=4096, W=128) the old loop was ~0.5M PyLong boxes per tick;
        # now only NEWLY-voted (group, vid) pairs reach Python at all
        keys = _unique_window_keys(val_win, np.asarray(dirty))
        # membership via searchsorted against the (sorted) logged keys:
        # O(k log N) instead of isin/union1d's full concatenate-and-sort
        # of the whole logged history every dirty tick
        if len(self._logged_keys):
            pos = np.minimum(
                np.searchsorted(self._logged_keys, keys),
                len(self._logged_keys) - 1,
            )
            cand = keys[self._logged_keys[pos] != keys]
        else:
            cand = keys
        new_pp_by_g: Dict[int, dict] = {}
        new_cw_by_g: Dict[int, dict] = {}
        taken = []
        for k in cand.tolist():
            g, vid = k >> _VID_BITS, k & ((1 << _VID_BITS) - 1)
            logged = False
            if self.codewords is not None:
                # codeword plane: a voter durably logs the shard subset
                # its vote stands for (its assigned slice), not the full
                # batch — the recovered quorum's shards rebuild committed
                # values through gossip (reference durability.rs logs
                # accepted shard data)
                got = self.codewords.wal_shards(g, vid, self.me)
                if got is not None:
                    new_cw_by_g.setdefault(g, {})[vid] = got
                    logged = True
            if not logged:
                b = self.payloads.get(g, vid)
                if b is not None:
                    new_pp_by_g.setdefault(g, {})[vid] = b
                    logged = True
            if logged:
                self._logged_vids[g].add(vid)
                taken.append(k)
        if taken:
            # taken is sorted (cand is sorted and scanned in order), so a
            # positional insert keeps _logged_keys sorted without a re-sort
            tk = np.asarray(taken, np.int64)
            self._logged_keys = np.insert(
                self._logged_keys, np.searchsorted(self._logged_keys, tk),
                tk,
            )
        for g in dirty:
            g = int(g)
            new_pp = new_pp_by_g.get(g, {})
            rec: Dict[str, Any] = {k: int(v[g]) for k, v in scal.items()}
            rec.update({k: wins[k][g].tolist() for k in wins})
            rec["pp"] = new_pp
            new_cw = new_cw_by_g.get(g, {})
            if new_cw:
                rec["cw"] = new_cw
            self._wal_append(("vote", g, rec))

    # ------------------------------------------------------------ snapshots
    def _take_snapshot(self) -> int:
        """Write the full KV + applied floors, then compact the WAL down
        to the current acceptor record per group (+ payloads still in the
        window) — apply records below the floors are covered by the
        snapshot.  Parity: snapshot.rs:121-303 (take_new_snapshot +
        snapshot_discard_log); deviation: the flat-file snapshot is
        replaced atomically instead of appended (same recovery semantics,
        'production would use an LSM-tree' note mod.rs:278-280)."""
        kv = self.statemach.snapshot_items()
        meta: Dict[str, Any] = {
            "applied": list(self.applied),
            # near-quorum reads pick the max write slot across a quorum;
            # losing this map to a snapshot would make a recovered
            # replica report wslot -1 for keys it actually holds NEWER
            # values of, letting a lagging peer's older value win
            "wslots": dict(self._wslot),
            # live resharding: adopted range installs travel with the KV
            # they moved; still-sealed changes re-seal on recovery.
            # radopted marks which installs were true local adoptions
            # (merge executed) vs re-announced overrides whose adopt
            # slot is still ahead of the floor — recovery must keep the
            # distinction or the replayed adopt skips its merge
            "ranges": self.rangetab.entries(),
            "radopted": sorted(self._range_adopted),
            "rseals": [
                {k: ch[k] for k in
                 ("rc_id", "op", "start", "end", "dst_group")}
                for ch in self._range_sealed.values()
            ],
        }
        if self._epaxos:
            meta["ep_rows"] = [
                list(self._ep_exec[g].floor) for g in range(self.G)
            ]
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(("kv", kv, meta), f)
            f.flush()
            # graftlint: disable=H104 -- the snapshot tmp file is private to this replica loop and replaced atomically; routing it through StorageHub would serialize bulk snapshot IO behind latency-critical WAL appends
            os.fsync(f.fileno())
        os.replace(tmp, self.snap_path)
        if self._snap_crash > 0:
            # nemesis crash point: the snapshot is durably on disk but
            # the WAL has NOT been compacted yet — recovery must
            # reconcile the new snapshot with the old (longer) WAL
            # without double-applying or losing acked writes
            self._snap_crash -= 1
            raise SummersetError(
                "injected snapshot crash point: snapshot written, WAL "
                "not yet compacted"
            )

        # compact: rewrite the WAL with only the latest durable row per
        # group; window payloads ride along for the unexecuted tail.
        # The first record is the compaction marker: apply history below
        # these floors now lives ONLY in the snapshot, which recovery
        # uses to make an unreadable-snapshot-after-compaction fatal
        # instead of a silent loss of committed state.
        ker = self.kernel
        me = self.me
        scal = {
            k: self._np_state(k)[:, me] for k in ker.DURABLE_SCALARS
        }
        wins = {
            k: self._np_state(k)[:, me] for k in ker.DURABLE_WINDOWS
        }
        val_win = wins[ker.VALUE_WINDOW]
        wtmp = self.wal_path + ".tmp"
        if os.path.exists(wtmp):
            os.remove(wtmp)
        compact = StorageHub(wtmp)
        compact.do_sync_action(LogAction(
            "append", entry=("snap_floor", list(self.applied)), sync=False
        ))
        new_logged: Dict[int, set] = {}
        vids_by_g = _unique_window_vids(val_win, np.arange(self.G))
        for g in range(self.G):
            pp = {}
            cw = {}
            for vid in vids_by_g.get(g, ()):
                got = (
                    self.codewords.wal_shards(g, vid, self.me)
                    if self.codewords is not None else None
                )
                if got is not None:
                    cw[vid] = got
                    continue
                b = self.payloads.get(g, vid)
                if b is not None:
                    pp[vid] = b
            rec: Dict[str, Any] = {k: int(v[g]) for k, v in scal.items()}
            rec.update({k: wins[k][g].tolist() for k in wins})
            rec["pp"] = pp
            if cw:
                rec["cw"] = cw
            compact.do_sync_action(
                LogAction("append", entry=("vote", g, rec), sync=False)
            )
            new_logged[g] = set(pp) | set(cw)
        # the shard store keeps one full codeword per proposed vid at the
        # proposer; the snapshot floor is the natural GC point (vids
        # below every durable-window reference can never be re-served)
        if self.codewords is not None:
            for g in range(self.G):
                vids = vids_by_g.get(g)
                if vids:
                    self.codewords.gc_below(g, min(vids))
        compact.do_sync_action(LogAction("truncate", offset=compact.size,
                                         sync=True))
        compact.stop()
        self.wal.stop()
        os.replace(wtmp, self.wal_path)
        self.wal = StorageHub(
            self.wal_path, registry=self.metrics, flight=self.flight
        )
        self.wal.health = self.health
        self._logged_vids = new_logged
        self._rebuild_logged_keys()
        self._sig = None  # conservative: next tick re-logs any drift
        size = self.wal.size
        pf_info(
            logger,
            f"snapshot taken ({len(kv)} keys); WAL compacted to {size}B",
        )
        return size

    # ----------------------------------------------------------- tick I/O
    def _slice_outbox(self, out) -> Dict[int, Dict[str, Any]]:
        """Per-peer frame: per-pair fields sliced [G] at (me, dst),
        broadcast lanes sent whole."""
        lanes = self.kernel.broadcast_lanes
        frames: Dict[int, Dict[str, Any]] = {}
        np_out = {k: np.asarray(v) for k, v in out.items()}
        for dst in range(self.population):
            if dst == self.me:
                continue
            f = {}
            for k, v in np_out.items():
                f[k] = v[:, self.me] if k in lanes else v[:, self.me, dst]
            frames[dst] = f
        return frames

    def _assemble_inbox(self, own_out, peer_frames) -> Dict[str, Any]:
        """Receiver-oriented inbox: row `me` filled from peers + self.

        ``peer_frames`` maps src -> list of frames (oldest..newest) or
        None; kernel lanes come from the newest frame only — they carry
        cumulative state, so the latest supersedes (transport docstring).
        """
        lanes = self.kernel.broadcast_lanes
        zero = self.kernel.zero_outbox()
        inbox = {}
        for k, z in zero.items():
            arr = np.zeros_like(np.asarray(z))
            if k in lanes:
                arr[:, self.me] = np.asarray(own_out[k])[:, self.me]
                for src, fl in peer_frames.items():
                    if fl:
                        arr[:, src] = fl[-1]["msg"][k]
            else:
                # transposed orientation: [G, dst(me), src]
                arr[:, self.me, self.me] = np.asarray(own_out[k])[
                    :, self.me, self.me
                ]
                for src, fl in peer_frames.items():
                    if fl:
                        arr[:, self.me, src] = fl[-1]["msg"][k]
            inbox[k] = jnp.asarray(arr)
        return inbox

    # -------------------------------------------------------- client intake
    def _reply(self, client: int, reply: ApiReply) -> None:
        """Reply seam, fence-aware: the pipelined loop queues EVERY
        reply — apply acks, local reads, redirects, probe verdicts —
        behind the durability fence (``_drain_replies``), because a
        local read can reveal state whose vote/apply records are still
        in the background group commit; the serial loop's ordering
        already guarantees durability-before-reply, so it sends
        immediately, exactly as before."""
        if self.pipeline:
            self._reply_queue.append((client, reply))
            return
        self.external.send_reply(reply, client)

    def _drain_replies_if_settled(self) -> None:
        """End-of-tick reply release: drain only if the open fence's
        fsync already completed (or the tick was clean), else leave the
        queue for the next tick's exchange-stage drain — never block
        the loop here.  The poll raises a latched background error, so
        a failed group commit still crashes before anything escapes."""
        token = self._fence_token
        if token is not None and not self.wal.poll_flush(token):
            return
        self._drain_replies()

    def _drain_replies(self) -> None:
        """Release everything gated on the durability fence: queued
        client replies and commit-feed notes, in arrival order.  The
        fence is waited FIRST (and re-checked by
        ``ExternalApi.send_replies``), so a failed background fsync
        crashes the replica with every gated reply still unsent."""
        self._fence_wait()
        self._flush_notes()  # queues note replies behind the same gate
        q = self._reply_queue
        if q:
            self._reply_queue = []
            self.external.send_replies(q, fence=self._fence_wait)
        if self._trace_replied:
            now = time.monotonic()
            for g, vid in self._trace_replied:
                self.traces.mark_replied(g, vid, now)
            self._trace_replied.clear()

    def _can_local_read(self, g: int) -> bool:
        """May this replica serve a linearizable read locally right now?
        Conservative host form of the per-key-bucket kernel rule: all
        buckets quiescent + the lease condition holds (quorumlease.rs
        is_local_reader / bodega localread.rs:8-26)."""
        ex = self._last_extra
        if not ex:
            return False
        if self._health_self_bad and self.health_mitigation:
            # responder mitigation: a limping replica stops serving
            # lease-local reads — clients get the leader redirect instead
            # of queueing behind a gray disk/NIC (the lease itself stays
            # valid; this is steering, not revocation)
            return False
        K = getattr(self.kernel.config, "num_key_buckets", 0)
        if "lease_held" in ex:      # QuorumLeases
            return bool(ex["lease_held"][g, self.me]) and int(
                ex["n_local_buckets"][g, self.me]
            ) == K
        if "local_read_buckets" in ex:  # Bodega
            return int(ex["n_local_buckets"][g, self.me]) == K
        return False

    def _leader_read_ok(self, g: int) -> bool:
        """May this LEADER serve reads locally under a stable-leader
        lease (a confirmed quorum of follower vote-refusal promises)?
        Parity: multipaxos/leaderlease.rs:10-21 + quorumread.rs's
        highest-slot freshness check, played by _tail_writes_key."""
        ex = self._last_extra
        return bool(ex) and "leader_read_ok" in ex and bool(
            ex["leader_read_ok"][g, self.me]
        )

    def _scan_read_ok(self, start: str, end: Optional[str]) -> bool:
        """May a linearizable range read over ``[start, end)`` be served
        from applied state RIGHT NOW?  Range keys hash-scatter across
        ALL groups, so the per-group read predicate must hold everywhere
        — leader-lease freshness where this replica leads, lease-local
        rights elsewhere — and no voted-unexecuted write anywhere may
        target the span (the range form of the highest-slot freshness
        check a leased get plays per key)."""
        for g in range(self.G):
            if self._is_leader[g]:
                if not self._leader_read_ok(g):
                    return False
            elif not self._can_local_read(g):
                return False
        return not self._tail_writes_range({"start": start, "end": end})

    def _handle_conf_req(self, client: int, req: ApiRequest) -> None:
        """Queue a client ConfChange (never silently dropped — reply with
        failure if this kernel has no conf plane; parity:
        external.rs:106-121)."""
        if self._conf_kind is None:
            self._reply(client, ApiReply(
                "conf", req_id=req.req_id, success=False,
            ))
            return
        if self._conf_kind == "ql" and not self._is_leader.all():
            # QL conf entries ride the log, so only each group's leader
            # can propose them.  Under split per-group leadership this
            # server cannot install the conf alone: forward the delta
            # through the manager, which re-announces it to EVERY server
            # (each proposes for the groups it leads).  Our own
            # completion check just waits for conf_cur to reach the
            # target in all groups — however the entries got there.
            self.ctrl.send_ctrl(CtrlMsg(
                "conf_forward", {"delta": dict(req.conf_delta or {})}
            ))
        self._conf_queue.append((client, req))

    # ------------------------------------------------------- commit feed
    def _handle_subscribe(self, client: int, req: ApiRequest) -> None:
        """Register a read-tier learner: the reply carries a consistent
        KV snapshot plus the feed seq it covers; every put applied after
        this point streams as a note (parity role: the learner tier of
        compartmentalized SMR — commit notifications without ever
        touching the proposer path)."""
        self._subs[int(client)] = True
        self._reply(client, ApiReply(
            "sub", req_id=req.req_id, success=True, seq=self._sub_seq,
            notes=self.statemach.snapshot_items(),
        ))

    def _handle_probe(self, client: int, req: ApiRequest) -> None:
        """Answer a read-tier freshness probe: may a lease-local read of
        this key be served RIGHT NOW, and what feed seq covers it?  Runs
        on the replica thread between last tick's applies (all flushed as
        notes) and this tick's — so a learner whose stream has reached
        ``seq`` holds every write this replica had applied when the
        verdict was sampled, and the lease condition is read exactly
        where the fused serving path reads it."""
        ok = False
        if req.cmd is not None and req.cmd.kind == "get":
            g = self.route_group(req.cmd.key)
            if self._range_sealed_for(req.cmd.key) is not None:
                # mid-cutover: the range is sealed here — no local read
                # can be proven fresh against the adopting group
                ok = False
            elif self._is_leader[g]:
                ok = self._leader_read_ok(g) and not self._tail_writes_key(
                    g, req.cmd.key
                )
            else:
                ok = self._can_local_read(g)
        elif req.cmd is not None and req.cmd.kind == "scan":
            # range form of the verdict: the span must dodge every
            # sealed cutover AND be read-ready across ALL groups — the
            # learner's scan over its learned state at seq >= this
            # probe's seq then inherits the same lease-safety argument
            # the per-key path has (notes and probe replies FIFO on one
            # writer, verdict sampled where the fused path samples it)
            ok = (
                not self._range_seal_overlaps(req.cmd.key, req.cmd.end)
                and self._scan_read_ok(req.cmd.key, req.cmd.end)
            )
        self._reply(client, ApiReply(
            "probe", req_id=req.req_id, success=bool(ok),
            seq=self._sub_seq,
        ))

    def _note_put(self, key: str, value: Any) -> None:
        """Append one applied put to the commit feed (no-op without
        subscribers — the fused path pays one dict-truthiness check)."""
        if self._subs:
            self._sub_seq += 1
            self._sub_notes.append((self._sub_seq, key, value))

    def _flush_notes(self) -> None:
        """Ship buffered notes to every live subscriber, once per tick,
        strictly AFTER the group-commit fsync (notes reflect applied
        state; like client replies they must never precede the
        durability point covering it).  Dead learners (connection gone)
        are GC'd here instead of accumulating notes forever."""
        if not (self._subs and self._sub_notes):
            return
        notes = self._sub_notes
        self._sub_notes = []
        for c in [c for c in self._subs
                  if not self.external.has_client(c)]:
            del self._subs[c]
        last = notes[-1][0]
        for c in self._subs:
            self._reply(c, ApiReply(
                "note", req_id=0, seq=last, notes=notes,
            ))

    def _intake(self) -> Tuple[np.ndarray, np.ndarray, Dict]:
        """Drain the client plane: route requests to groups, serve leased
        local reads, redirect what we don't lead, answer every request
        kind (request.rs:16-216 treat_read_only_reqs + redirects)."""
        n_prop = np.zeros((self.G,), np.int32)
        vbase = np.zeros((self.G,), np.int32)
        piggy: Dict[Tuple[int, int], Any] = {}
        batch = self.external.get_req_batch(timeout=0)
        if self._scan_pend:
            self._scan_gc()
        if not batch and not self._range_adopt_ready:
            if self._epaxos and any(self._ep_defer.values()):
                # deferred buckets must drain even on idle intake ticks
                return self._intake_epaxos({}, n_prop, vbase, piggy)
            return n_prop, vbase, piggy
        by_group: Dict[int, list] = {}
        if self._range_adopt_ready:
            # barrier-cleared range adoptions enter the DESTINATION
            # group's log like any write (client None = internal; if
            # leadership moved, the non-leader path below drops it and
            # _range_progress re-proposes after its mark expires)
            for g, areq in self._range_adopt_ready:
                by_group.setdefault(g, []).append((None, areq))
            self._range_adopt_ready = []
        for client, req in batch:
            if req.kind == "conf":
                self._handle_conf_req(client, req)
            elif req.kind == "batch":
                # ingress-proxy forward: unpack into individual ops —
                # each (prid, Command) behaves exactly like a direct
                # client "req" from here on (replies route back to the
                # proxy per prid); the batch already paid its ONE
                # bounded-queue slot at the api plane
                for prid, cmd in (req.batch or ()):
                    if cmd is None:
                        continue
                    if cmd.kind == "scan":
                        # proxy-forwarded range read (read-tier probe
                        # refused): same serve/shed/barrier decision as
                        # a direct scan, replied per prid
                        self._intake_scan(client, ApiRequest(
                            "req", req_id=int(prid), cmd=cmd,
                        ), by_group)
                        continue
                    if self._range_sealed_for(cmd.key) is not None:
                        # mid-cutover seal: refuse BEFORE proposal, so a
                        # shed op can never have been acked (the same
                        # guarantee the bounded-queue shed gives, and the
                        # proxy relays it per prid)
                        self._reply(client, ApiReply(
                            "shed", req_id=int(prid), success=False,
                            retry_after_ms=50,
                        ))
                        self.metrics.counter_add("api_shed", 1)
                        continue
                    self._range_heat.note(cmd.key)
                    by_group.setdefault(
                        self.route_group(cmd.key), []
                    ).append((client, ApiRequest(
                        "req", req_id=int(prid), cmd=cmd,
                    )))
            elif req.kind == "sub":
                self._handle_subscribe(client, req)
            elif req.kind == "probe":
                self._handle_probe(client, req)
            elif req.kind == "scan" or (
                    req.kind == "req" and req.cmd is not None
                    and req.cmd.kind == "scan"):
                # "scan" is accepted both as an ApiRequest kind (the
                # documented surface) and as a Command riding "req"
                self._intake_scan(client, req, by_group)
            elif req.kind != "req" or req.cmd is None:
                self._reply(client, ApiReply(
                    "error", req_id=req.req_id, success=False,
                ))
            elif self._range_sealed_for(req.cmd.key) is not None:
                self._reply(client, ApiReply(
                    "shed", req_id=req.req_id, success=False,
                    retry_after_ms=50,
                ))
                self.metrics.counter_add("api_shed", 1)
            else:
                self._range_heat.note(req.cmd.key)
                by_group.setdefault(
                    self.route_group(req.cmd.key), []
                ).append((client, req))
        if self._epaxos:
            return self._intake_epaxos(by_group, n_prop, vbase, piggy)
        cw_fallback = self._craft_fallback_groups() if by_group else None
        for g, reqs in by_group.items():
            if not self._is_leader[g]:
                pending = []
                local_ok = self._can_local_read(g)
                for client, req in reqs:
                    if client is None:
                        # internal adopt proposal and we no longer lead
                        # the destination: drop — re-proposed by
                        # _range_progress once its mark expires
                        continue
                    if local_ok and req.cmd.kind == "get":
                        res = apply_command(self.statemach._kv, req.cmd)
                        self._reply(client, ApiReply(
                            "reply", req_id=req.req_id, result=res,
                            local=True,
                        ))
                    elif self._nqr_ok and req.cmd.kind == "get":
                        self._start_qread(client, req, g)
                    else:
                        pending.append((client, req))
                hint = int(self._leader_hint[g])
                for client, req in pending:
                    self._reply(client, ApiReply(
                        "redirect", req_id=req.req_id, redirect=hint,
                        success=False,
                    ))
                continue
            if self._leader_read_ok(g):
                # stable-leader lease: serve GETs from applied state when
                # no in-flight write to the key sits in the voted tail
                # (every acked write is applied here — acks ride
                # execution — and under a held lease no other proposer
                # can have committed newer state)
                to_log = []
                for client, req in reqs:
                    if (req.cmd.kind == "get"
                            and not self._tail_writes_key(g, req.cmd.key)):
                        res = apply_command(self.statemach._kv, req.cmd)
                        self._reply(client, ApiReply(
                            "reply", req_id=req.req_id, result=res,
                            local=True,
                        ))
                    else:
                        to_log.append((client, req))
                reqs = to_log
                if not reqs:
                    continue
            vid = self.payloads.put(
                g, reqs, stride=self.population, residue=self.me
            )
            self.origin.add((g, vid))
            # slot trace sampling: arrival is intake-stamped (within one
            # batch interval of the socket arrival; the socket-accurate
            # end-to-end latency is ExternalApi's api_request_latency_us).
            # The batch's first (client, req_id) is the representative
            # that joins the request span to the slot span at export.
            self.traces.maybe_start(
                g, vid, self.tick, time.monotonic(),
                client=-1 if reqs[0][0] is None else reqs[0][0],
                req_id=reqs[0][1].req_id,
            )
            n_prop[g] = 1
            vbase[g] = vid
            if self.codewords is not None and not (
                cw_fallback is not None and bool(cw_fallback[g])
            ):
                # codeword plane: peers get only their assigned shard
                # subset; the full batch stays host-local at the proposer
                self._distribute_shards(g, vid, reqs)
            else:
                piggy[(g, vid)] = reqs
            if self._adaptive is not None:
                nb = float(len(pickle.dumps(reqs)))
                self._batch_bytes = 0.9 * self._batch_bytes + 0.1 * nb
        return n_prop, vbase, piggy

    # ------------------------------------------------ ordered range reads
    def _intake_scan(self, client: int, req: ApiRequest,
                     by_group: Dict[int, list]) -> None:
        """Fused-path scan serving (the learner tier's fallback).  In
        order: (1) a span crossing a sealed cutover is shed before any
        proposal (never acked-then-shed); (2) when every group is
        read-ready for the span, serve straight from applied state —
        the replica thread applies serially, so the KV between applies
        IS a consistent cut; (3) otherwise, leading every group, fall
        back to a commit-bar barrier: propose one no-effect scan marker
        into EVERY group's log and read the final cut when the last
        marker applies — any write acked before that instant was acked
        by THIS server (it leads all groups, acks ride execution) and
        so sits ahead of some marker in its group's log, hence applied;
        (4) split leadership redirects, as a get would."""
        cmd = req.cmd
        if cmd is None:
            self._reply(client, ApiReply(
                "error", req_id=req.req_id, success=False,
            ))
            return
        if self._range_seal_overlaps(cmd.key, cmd.end):
            self._reply(client, ApiReply(
                "shed", req_id=req.req_id, success=False,
                retry_after_ms=50,
            ))
            self.metrics.counter_add("api_shed", 1)
            self.metrics.counter_add("scan_shed", 1)
            return
        if self._epaxos:
            # leaderless rows have no single commit bar to barrier on
            # and no lease plane — scans are a lease/learner-tier
            # feature (documented punt; callers get a clean error)
            self._reply(client, ApiReply(
                "error", req_id=req.req_id, success=False,
            ))
            return
        if self._scan_read_ok(cmd.key, cmd.end):
            res = apply_command(self.statemach._kv, cmd)
            self._reply(client, ApiReply(
                "reply", req_id=req.req_id, result=res, local=True,
            ))
            self._scan_served(res)
            return
        if bool(self._is_leader.all()):
            sbid = self._scan_next
            self._scan_next += 1
            self._scan_pend[sbid] = {
                "client": client, "req_id": req.req_id, "cmd": cmd,
                "need": set(range(self.G)), "tick": self.tick,
            }
            marker = ApiRequest("req", req_id=sbid, cmd=cmd)
            for g in range(self.G):
                by_group.setdefault(g, []).append((None, marker))
            return
        hint = int(self._leader_hint[self.route_group(cmd.key)])
        self._reply(client, ApiReply(
            "redirect", req_id=req.req_id, redirect=hint, success=False,
        ))

    def _scan_served(self, res: CommandResult) -> None:
        keys = len(res.items or ())
        self.metrics.counter_add("scan_served", 1)
        self.metrics.counter_add("scan_keys", keys)
        self.flight.record("scan_serve", keys=keys, tick=self.tick)

    def _scan_barrier_hit(self, g: int, sbid: int) -> None:
        """One group's scan barrier marker reached the apply bar on its
        proposer.  When the LAST group lands, the applied KV is a
        linearizable cut for the span (see ``_intake_scan``): read it at
        the bar and release the reply behind the durability fence."""
        p = self._scan_pend.get(sbid)
        if p is None:
            return  # expired (GC replied shed) or a stray duplicate
        p["need"].discard(g)
        if p["need"]:
            return
        del self._scan_pend[sbid]
        res = apply_command(self.statemach._kv, p["cmd"])
        self._reply_queue.append((p["client"], ApiReply(
            "reply", req_id=p["req_id"], result=res,
        )))
        self._scan_served(res)

    def _scan_gc(self, ttl_ticks: int = 500) -> None:
        """Expire barrier scans whose markers never committed (e.g.
        leadership moved mid-barrier and the non-leader path dropped the
        internal proposal): reply shed — a scan is a read, so refusing
        it late is always safe, and a marker that still reaches the bar
        afterwards just misses the pend and no-ops."""
        dead = [sbid for sbid, p in self._scan_pend.items()
                if self.tick - p["tick"] > ttl_ticks]
        for sbid in dead:
            p = self._scan_pend.pop(sbid)
            self._reply(p["client"], ApiReply(
                "shed", req_id=p["req_id"], success=False,
                retry_after_ms=50,
            ))
            self.metrics.counter_add("scan_shed", 1)

    # ---------------------------------------------- codeword payload plane
    def _craft_fallback_groups(self) -> Optional[np.ndarray]:
        """Host mirror of CRaft's per-append full-copy fallback rule
        (``_append_mode``: more than fault_tolerance peers look dead ->
        ship full batches so the majority-threshold commit stays
        recoverable).  Reads the liveness countdowns as of the last tick,
        so it can trail the kernel's stamp by one tick — the same
        documented weakening window as the reference's global latch
        (craft/mod.rs:280-283)."""
        if not (self._craft_mode and self.codewords is not None):
            return None
        ac = self._np_state("alive_cnt")[:, self.me]
        return (ac <= 0).sum(axis=1) > self.kernel.config.fault_tolerance

    def _spr_choice(self, g: int) -> int:
        """Shards-per-replica width for this tick's sends: the SAME
        per-group value the kernel receives as ``spr_override``, clipped
        the way the kernel clips it, so the stamped ``win_spr`` matches
        the bytes actually on the wire.  Static (init_spr / 1) when no
        adaptive policy runs (RSPaxos/CRaft, or assignment_adaptive
        off)."""
        if self._adaptive is None:
            return self._cw_spr0
        d = self.kernel.data_shards
        return int(min(max(int(self._spr_tick[g]), self._cw_dj), d))

    def _distribute_shards(self, g: int, vid: int, batch: Any) -> None:
        """Leader-side send plan: encode once (Pallas on TPU, XLA
        bit-slice on CPU), then queue each peer's assigned row slice of
        the codeword for this tick's frame (rspaxos/mod.rs:597-608;
        Crossword: ``win_spr``-width diagonal slices)."""
        spr = self._spr_choice(g)
        dlen, cw = self.codewords.encode(g, vid, batch, spr)
        T = self.codewords.T
        for dst in range(self.population):
            if dst == self.me:
                continue
            sids = assigned_sids(dst, spr, self._cw_dj, T)
            sp = ShardPayload(dlen, {s: cw[s] for s in sids})
            self._pending_shards.setdefault(dst, {})[(g, vid)] = sp
            self.pp_bytes[dst] += _sp_size(sp)
            self.pp_items[dst] += 1

    def _resolve_payload(self, g: int, vid: int) -> Optional[Any]:
        """Full batch for ``(g, vid)``: the payload store, else a
        codeword reconstruction from >= d held shards (decoded once,
        then installed)."""
        b = self.payloads.get(g, vid)
        if b is None and vid != 0 and self.codewords is not None:
            b = self.codewords.reconstruct_batch(g, vid)
            if b is not None:
                self.payloads.install(g, vid, b, overwrite=False)
                self.missing.discard((g, vid))
                self._cw_first_missing.pop((g, vid), None)
                if bool(self._is_leader[g]):
                    # a leader that had to reconstruct (an adopted slot
                    # from a crashed predecessor) redistributes fresh
                    # slices under its current assignment so followers'
                    # votes are backed by shard bytes again
                    self._distribute_shards(g, vid, b)
        return b

    # ------------------------------------------------- near-quorum reads
    def _tail_writes_key(self, g: int, key: str) -> bool:
        """Does our voted-but-unexecuted window tail possibly contain a
        write to ``key``?  Conservative: an unresolvable payload counts
        as a hit (parity role: quorumread.rs's highest-slot check — a
        voted write the quorum has seen must block the fast read)."""
        win_abs = self._np_state("win_abs")[g, self.me]
        win_bal = self._np_state("win_bal")[g, self.me]
        win_val = self._np_state(self.kernel.VALUE_WINDOW)[g, self.me]
        # Scan EVERY voted-but-unexecuted window slot, with no upper
        # bound: bounding by vote_bar/next_slot is unsound because a
        # higher-ballot accept run-reset rewinds vote_bar without zeroing
        # win_bal above it, and a committed write voted at the old ballot
        # above the rewound bar would be missed — a stale fast read if
        # this replica is the read quorum's only intersection with the
        # write's vote quorum (the reference instead keeps a sticky
        # per-key highest_slot refreshed at every accept,
        # quorumread.rs refresh_highest_slot, which likewise survives
        # ballot resets).  A stale-ballot leftover only costs a
        # conservative leader fallback until the new run overwrites it.
        tail = (win_bal > 0) & (win_abs >= self.applied[g])
        for vid in set(int(v) for v in win_val[tail]):
            if vid == 0:
                continue
            batch = self.payloads.get(g, vid)
            if batch is None:
                return True  # can't inspect: be conservative
            for _c, req in batch:
                if (
                    req.cmd is not None
                    and req.cmd.kind == "put"
                    and req.cmd.key == key
                ):
                    return True
        return False

    def _tail_writes_range(self, ch: dict) -> bool:
        """Does ANY group's voted-but-unexecuted tail possibly hold a
        write inside the sealed range ``ch``?  The adopt barrier: the
        handoff snapshot is only complete once every straggler the seal
        raced has executed (same conservative rules as
        ``_tail_writes_key``, over a key-range predicate and all
        groups — the flat per-process KV means any group's tail could
        still touch the range).  Kernel families mark votes in
        different leaves (ballot families in ``win_bal``, the raft
        family in ``win_term``); a family with neither is
        uninspectable and reads as a permanent conservative hit —
        ``_range_begin`` refuses the seal for those families up front
        (and for epaxos' 2-D instance space, which has no linear
        window at all), so an uninspectable seal never exists to
        wedge."""
        start, end = ch["start"], ch.get("end")
        marker_leaf = next(
            (k for k in ("win_bal", "win_term") if k in self.state), None
        )
        if marker_leaf is None or "win_abs" not in self.state:
            return True
        for g in range(self.G):
            win_abs = self._np_state("win_abs")[g, self.me]
            win_mark = self._np_state(marker_leaf)[g, self.me]
            win_val = self._np_state(self.kernel.VALUE_WINDOW)[g, self.me]
            tail = (win_mark > 0) & (win_abs >= self.applied[g])
            for vid in set(int(v) for v in win_val[tail]):
                if vid == 0:
                    continue
                batch = self.payloads.get(g, vid)
                if batch is None:
                    return True  # can't inspect: be conservative
                for _c, req in batch:
                    if (
                        req.cmd is not None
                        and req.cmd.kind == "put"
                        and req.cmd.key >= start
                        and (end is None or req.cmd.key < end)
                    ):
                        return True
        return False

    def _local_read_sample(self, g: int, key: str) -> Tuple[Any, int, bool]:
        return (
            self.statemach._kv.get(key),
            self._wslot.get(key, -1),
            self._tail_writes_key(g, key),
        )

    def _start_qread(self, client: int, req: ApiRequest, g: int) -> None:
        """Begin a near-quorum read (quorumread.rs ReadQuery fan-out):
        sample ourselves now, ask every peer through the tick frames, and
        serve once a majority answered with no in-flight write in sight.
        Safety: a completed write holds votes at a majority, which
        intersects our read quorum — the intersecting member either
        applied it (its wslot sample reflects it) or still has it in its
        voted tail (tail hit -> fall back to the leader path)."""
        key = req.cmd.key
        need = self.kernel.quorum - 1
        peers = self.transport.peers()
        if len(peers) < need:
            # not enough connected peers for a quorum of samples: redirect
            # to the leader immediately instead of parking the read until
            # the expiry sweep (it could never complete)
            self._reply(client, ApiReply(
                "redirect", req_id=req.req_id,
                redirect=int(self._leader_hint[g]),
                success=False, rq_retry=True,
            ))
            return
        rid = self._qread_next
        self._qread_next += 1
        self._qreads[rid] = {
            "client": client,
            "req": req,
            "g": g,
            "key": key,
            "replies": {self.me: self._local_read_sample(g, key)},
            "deadline": self.tick + 400,
        }
        # fan out to EVERY connected peer and complete on the first quorum
        # of replies (late extras are discarded at _qread_check): querying
        # exactly quorum-1 peers lets one paused-but-connected or slow
        # peer stall every read until the expiry redirect
        for dst in peers:
            self._pending_rq.setdefault(dst, []).append((rid, key, g))
        self._qread_check(rid)

    def _qread_check(self, rid: int) -> None:
        qr = self._qreads.get(rid)
        if qr is None or len(qr["replies"]) < self.kernel.quorum:
            return
        del self._qreads[rid]
        req = qr["req"]
        samples = list(qr["replies"].values())
        if any(hit for _v, _s, hit in samples):
            # an in-flight write touches the key: fall back to the log
            # path at the leader (the reference's rq_retry hint)
            hint = int(self._leader_hint[qr["g"]])
            self._reply(qr["client"], ApiReply(
                "redirect", req_id=req.req_id, redirect=hint,
                success=False, rq_retry=True,
            ))
            return
        value, _slot, _hit = max(samples, key=lambda x: x[1])
        self._reply(qr["client"], ApiReply(
            "reply", req_id=req.req_id,
            result=CommandResult("get", value=value), local=True,
        ))

    def _qread_expire(self) -> None:
        for rid in [
            r for r, q in self._qreads.items()
            if self.tick > q["deadline"]
        ]:
            qr = self._qreads.pop(rid)
            hint = int(self._leader_hint[qr["g"]])
            self._reply(qr["client"], ApiReply(
                "redirect", req_id=qr["req"].req_id, redirect=hint,
                success=False, rq_retry=True,
            ))

    def _key_bucket(self, key: str) -> int:
        """Key -> EPaxos conflict bucket (independent hash from the
        group routing so multi-group deployments don't alias)."""
        K = self.kernel.config.num_key_buckets
        return zlib.crc32(key.encode() + b"#b") % K

    def _intake_epaxos(self, by_group, n_prop, vbase, piggy):
        """EPaxos proposal path: every replica proposes (leaderless).
        ALL key buckets with pending requests propose in the same tick —
        one ReqBatch per bucket, each vid minted in the residue class
        ``bucket + K * me (mod K * R)`` so the kernel's ``vid % K``
        conflict detection sees real key interference while concurrent
        proposers stay collision-free.  The vid list rides the tick's
        ``prop_vids`` input; only overflow beyond max_proposals_per_tick
        buckets waits in ``_ep_defer`` (reference: EPaxos commits
        interfering and non-interfering commands concurrently,
        dependency.rs:180-240)."""
        K = self.kernel.config.num_key_buckets
        R = self.population
        pmax = self.kernel.config.max_proposals_per_tick
        self._ep_prop_vids[:] = 0
        for g, reqs in by_group.items():
            self._ep_defer[g].extend(reqs)
        own_next = self._np_state("own_next")[:, self.me]
        # the kernel's own window guard reads exec_row as of the LAST
        # tick (its _propose runs before _execute applies this tick's
        # exec_floor_rows), so the space computation must use the SAME
        # stale value — the live Tarjan floor runs one tick ahead and
        # would let us mint vids the kernel then silently refuses to
        # propose, orphaning their payload batches
        exec_me = self._np_state("exec_row")[:, self.me, self.me]
        for g in range(self.G):
            pend = self._ep_defer[g]
            if not pend:
                continue
            by_bucket: Dict[int, list] = {}
            for c, r in pend:
                by_bucket.setdefault(
                    self._key_bucket(r.cmd.key), []
                ).append((c, r))
            space = max(0, int(exec_me[g]) + self.window - int(own_next[g]))
            take_buckets = list(by_bucket)[:min(pmax, space)]
            keep = [
                cr for b in by_bucket if b not in take_buckets
                for cr in by_bucket[b]
            ]
            self._ep_defer[g] = keep
            for i, b in enumerate(take_buckets):
                take = by_bucket[b]
                vid = self.payloads.put(
                    g, take, stride=K * R, residue=b + K * self.me
                )
                self.origin.add((g, vid))
                self.traces.maybe_start(
                    g, vid, self.tick, time.monotonic(),
                    client=take[0][0], req_id=take[0][1].req_id,
                )
                self._ep_prop_vids[g, i] = vid
                piggy[(g, vid)] = take
            n_prop[g] = len(take_buckets)
            vbase[g] = int(self._ep_prop_vids[g, 0])
        return n_prop, vbase, piggy

    # ------------------------------------------------------------ conf plane
    def _conf_inputs(self, inputs: Dict[str, Any]) -> None:
        """Feed the active ConfChange into the kernel's conf inputs."""
        i32 = jnp.int32
        if self._conf_kind is None:
            return
        if self._conf_active is None and self._conf_queue:
            client, req = self._conf_queue.pop(0)
            d = dict(req.conf_delta or {})
            resp = 0
            for r in d.get("responders", []):
                resp |= 1 << int(r)
            lead = d.get("leader")
            if lead is None:
                # a responders-only change must NOT move the leader: the
                # target defaults to the current conf leader (Bodega), or
                # stays unused (QL, whose conf plane carries no leader)
                if "conf_leader" in self.state:
                    cur = int(
                        self._np_state("conf_leader")[0, self.me]
                    )
                    lead = cur if cur >= 0 else self.me
                else:
                    lead = -1
            self._conf_active = {
                "client": client,
                "req_id": req.req_id,
                "resp": resp,
                "leader": int(lead),
                "deadline": self.tick + 2000,
            }
        a = self._conf_active
        if self._conf_kind == "ql":
            tgt = a["resp"] if a is not None else -1
            inputs["conf_target"] = jnp.full((self.G,), tgt, i32)
        else:  # bodega
            init = self.me if a is not None else -1
            inputs["conf_init"] = jnp.full((self.G,), init, i32)
            inputs["conf_leader_target"] = jnp.full(
                (self.G,), a["leader"] if a else -1, i32
            )
            inputs["conf_resp_target"] = jnp.full(
                (self.G,), a["resp"] if a else 0, i32
            )
            inputs["conf_bucket"] = jnp.full((self.G,), -1, i32)

    def _conf_progress(self) -> None:
        """Detect conf installation, reply to the requesting client, and
        reflect the new conf to the manager (reigner.rs RespondersConf)."""
        a = self._conf_active
        if a is None:
            return
        me = self.me
        if self._conf_kind == "ql":
            cur = self._np_state("conf_cur")[:, me]
            done = bool((cur == a["resp"]).all())
        else:
            resp = self._np_state("conf_resp")[:, me, :]
            lead = self._np_state("conf_leader")[:, me]
            done = bool(
                (resp == a["resp"]).all() and (lead == a["leader"]).all()
            )
        if done:
            if a["client"] is not None:
                self._reply(a["client"], ApiReply(
                    "conf", req_id=a["req_id"], success=True,
                ))
            new_conf = {
                "responders": [
                    r for r in range(self.population)
                    if a["resp"] >> r & 1
                ],
            }
            if a["leader"] >= 0:  # QL's conf plane carries no leader
                new_conf["leader"] = a["leader"]
            self.ctrl.send_ctrl(CtrlMsg(
                "responders_conf", {"new_conf": new_conf}
            ))
            self._conf_active = None
        elif self.tick > a["deadline"]:
            if a["client"] is not None:
                self._reply(a["client"], ApiReply(
                    "conf", req_id=a["req_id"], success=False,
                ))
            self._conf_active = None

    # ------------------------------------------------- live resharding
    def _range_begin(self, ch: dict, replayed: bool = False) -> None:
        """Seal a range for cutover (the revoke half of revoke-then-
        adopt): from this point no new op on the range is admitted —
        shed at intake, never silently dropped — until the destination
        group's adopt applies.  The seal is WAL-durable so a crashed
        replica cannot resurrect admitting (``replayed`` installs skip
        the append: the manager re-announces pending changes to every
        rejoiner)."""
        rc_id = int(ch.get("rc_id", 0))
        if rc_id in self._range_adopted or rc_id in self._range_sealed \
                or rc_id in self._range_override \
                or rc_id in self._range_expired:
            return
        if self._epaxos:
            # leaderless: no single commit-slot barrier to drain against
            # — refuse the cutover (the ctrl reply still flows, so the
            # manager sees the op answered rather than hung)
            pf_warn(logger, f"range_change {rc_id} refused: leaderless "
                            "protocol has no seal barrier")
            return
        if "win_abs" not in self.state or not any(
            k in self.state for k in ("win_bal", "win_term")
        ):
            # no inspectable vote window (chain_rep / simple_push /
            # rep_nothing mark votes in neither win_bal nor win_term):
            # _tail_writes_range could never prove the tail drained, so
            # the barrier would never clear and a sealed range would
            # shed its ops FOREVER.  Refuse up front, exactly like the
            # leaderless refusal, instead of sealing unadoptably.
            pf_warn(logger, f"range_change {rc_id} refused: kernel "
                            "family has no inspectable vote window")
            return
        ch = dict(ch)
        ch["sealed_at"] = time.monotonic()
        # seal-TTL base: WAL-replayed seals restart their TTL from the
        # recovery tick (the manager's pending re-announce keeps the
        # change alive; the TTL bounds LEADERLESS-destination time, not
        # wall time since the original seal)
        ch["sealed_tick"] = self.tick
        self._range_sealed[rc_id] = ch
        if not replayed:
            self._wal_append(("rseal", {
                k: ch[k]
                for k in ("rc_id", "op", "start", "end", "dst_group")
            }))
        self.flight.record(
            "range_seal", rc_id=rc_id, op=str(ch.get("op")),
            tick=self.tick,
        )

    def _range_progress(self) -> None:
        """Propose adoption for sealed ranges whose barrier cleared: we
        must lead the destination group, the manager must have granted
        seal-complete (EVERY server acked the seal fan-out — the local
        vote window can't see a write a not-yet-sealed peer admitted),
        and no voted-but-unexecuted tail write to the range may remain
        in ANY group (the commit-slot barrier) — then the range-
        filtered KV, write-slot watermarks, and per-group apply floors
        ride ONE ``adopt`` command through the destination group's own
        log, making the cutover itself replicated and recoverable."""
        if not self._range_sealed or self._epaxos:
            return
        for rc_id in sorted(self._range_sealed):
            ch = self._range_sealed[rc_id]
            dst = int(ch["dst_group"]) % self.G
            if (self.seal_ttl_ticks > 0
                    and rc_id not in self._range_adopt_granted
                    and self.tick - int(ch.get("sealed_tick", 0))
                    > self.seal_ttl_ticks):
                # seal-TTL escape hatch: no adopt grant within the TTL
                # (destination leaderless, or the grant round itself is
                # starved) — ask the manager to expire the change.  The
                # manager refuses if a grant raced ahead (its event
                # loop serializes grant-vs-expire), so a stale expire
                # request cannot roll back an adoption in flight.
                last = self._range_expire_sent.get(rc_id)
                if last is None or self.tick - last >= 200:
                    self._range_expire_sent[rc_id] = self.tick
                    self.ctrl.send_ctrl(CtrlMsg(
                        "range_expire", {"rc_id": rc_id},
                    ))
                continue
            if not bool(self._is_leader[dst]):
                continue
            if not ch.get("sealed_ok"):
                # cluster-wide seal unconfirmed: a server the fan-out
                # has not reached yet could still admit (and commit) a
                # write to the range above our barrier — adopting now
                # would let the old group overwrite a newer destination
                # write of a moved key after the cutover.  The manager
                # re-announces the flag (install_ranges pending) once
                # all acks are in; until then the range sheds.
                continue
            mark = self._range_adopt_mark.get(rc_id)
            if mark is not None and self.tick - mark < 400:
                # an adopt is in flight (or recently lost to a leader
                # change); adoption is idempotent, so a re-propose after
                # the mark expires is safe even if both land
                continue
            if self._tail_writes_range(ch):
                continue
            if rc_id not in self._range_adopt_granted:
                # barrier cleared — ask the manager for the adopt grant
                # before proposing.  The grant pins the change against
                # seal-TTL expiry: once granted, only the (idempotent,
                # re-proposable) adopt resolves the cutover, so adopt
                # and expire can never both win.  Rate-limited like the
                # adopt re-propose; a lost decision just re-asks.
                last = self._range_intent_sent.get(rc_id)
                if last is None or self.tick - last >= 200:
                    self._range_intent_sent[rc_id] = self.tick
                    self.ctrl.send_ctrl(CtrlMsg(
                        "adopt_intent", {"rc_id": rc_id},
                    ))
                continue
            start, end = ch["start"], ch.get("end")

            def _inr(k: str) -> bool:
                return k >= start and (end is None or k < end)

            val = {
                "rc_id": rc_id, "op": ch.get("op", "split"),
                "start": start, "end": end, "dst_group": dst,
                "kv": {
                    k: v for k, v in self.statemach._kv.items()
                    if _inr(k)
                },
                "wslots": {
                    k: s for k, s in self._wslot.items() if _inr(k)
                },
                "floors": list(self.applied),
            }
            self._range_adopt_ready.append((dst, ApiRequest(
                "req", req_id=0,
                cmd=Command("adopt", key=f"__adopt__{rc_id}", value=val),
            )))
            self._range_adopt_mark[rc_id] = self.tick

    def _apply_adopt(self, val: Any, announce: bool,
                     recovery: bool = False) -> None:
        """Execute an ``adopt`` command at its destination-group slot:
        install the range override, merge the handed-off KV + write-slot
        watermarks, unseal, and (at the proposer, live only) notify the
        manager so proxies and late joiners learn the install.
        Idempotent per rc_id — a duplicate adopt from a re-propose race
        is a no-op."""
        val = dict(val or {})
        rc_id = int(val.get("rc_id", 0))
        if rc_id in self._range_adopted:
            return
        self._range_adopted.add(rc_id)
        # a manager re-announce may have installed the routing override
        # first; this is the real adoption (the merge below), so the
        # override-only marker retires
        self._range_override.discard(rc_id)
        entry = {
            "rc_id": rc_id, "op": val.get("op", "split"),
            "start": val["start"], "end": val.get("end"),
            "group": int(val.get("dst_group", 0)) % self.G,
            "floors": [int(f) for f in (val.get("floors") or [])],
        }
        self.rangetab.install(entry)
        kv = dict(val.get("kv") or {})
        self.statemach._kv.update(kv)
        for k, v in kv.items():
            # moved keys re-enter the commit feed so read-tier learners
            # converge on the post-cutover placement's values
            self._note_put(k, v)
        for k, s in (val.get("wslots") or {}).items():
            self._wslot[k] = max(self._wslot.get(k, -1), int(s))
        sealed = self._range_sealed.pop(rc_id, None)
        self._range_adopt_mark.pop(rc_id, None)
        self.metrics.counter_add(
            "reshard_splits" if entry["op"] == "split"
            else "reshard_merges", 1,
        )
        if sealed is not None and not recovery \
                and "sealed_at" in sealed:
            self.metrics.observe(
                "reshard_cutover_us",
                int((time.monotonic() - sealed["sealed_at"]) * 1e6),
            )
        self.flight.record(
            "range_adopt", rc_id=rc_id, op=str(entry["op"]),
            dst=entry["group"], keys=len(kv), tick=self.tick,
        )
        if announce and not recovery:
            self.ctrl.send_ctrl(CtrlMsg(
                "range_installed", {"entry": entry}
            ))

    def _range_unseal(self, rc_id: int, why: str) -> None:
        """Roll back a sealed-but-never-adopted range change: drop the
        seal so the source resumes serving the range, and remember the
        rc_id as expired so a straggling re-announce of the same change
        cannot re-seal it.  Only reached via the manager's expired list
        (install_ranges) — the manager already refused expiry for any
        change whose adopt intent was granted, so there is no adoption
        in flight to race."""
        rc_id = int(rc_id)
        if rc_id in self._range_expired or rc_id in self._range_adopted:
            return
        self._range_expired.add(rc_id)
        self._range_adopt_granted.discard(rc_id)
        self._range_intent_sent.pop(rc_id, None)
        self._range_expire_sent.pop(rc_id, None)
        sealed = self._range_sealed.pop(rc_id, None)
        self._range_adopt_mark.pop(rc_id, None)
        # un-propose: an adopt batch still waiting in the intake queue
        # for this rc_id must not reach the log after the rollback
        self._range_adopt_ready = [
            (g, req) for g, req in self._range_adopt_ready
            if int((req.cmd.value or {}).get("rc_id", -1)) != rc_id
        ]
        if sealed is None:
            return
        self.metrics.counter_add("reshard_seal_expired", 1)
        self.flight.record(
            "range_unseal", rc_id=rc_id, why=str(why), tick=self.tick,
        )
        pf_warn(logger, f"range_change {rc_id} un-sealed ({why}): "
                        "source resumes serving the range")

    # --------------------------------------------------------- main loop
    def run(self) -> bool:
        """Event loop; returns True to request a crash-restart.

        Two tick bodies share every helper: ``_tick_serial`` is the
        exact old strictly-ordered loop (``pipeline=False`` —
        byte-identical digests), ``_tick_pipelined`` keeps the same
        dataflow but moves the device scan and the WAL group-commit
        fsync off the critical path behind the explicit durability
        fence (see its docstring)."""
        while True:
            if self.stopping:
                self._pipeline_flush()
                return False
            t0 = time.monotonic()
            restart = self._handle_ctrl()
            if restart is not None:
                if restart is False:
                    # graceful leave: settle the in-flight step so every
                    # already-applied op is acked before teardown
                    self._pipeline_flush()
                return restart
            if self.paused:
                time.sleep(self.tick_interval)
                continue
            if self.pipeline:
                self._tick_pipelined(t0)
            else:
                self._tick_serial(t0)

    def _stage_clock(self, t0: float):
        """Per-tick stage stopwatch: returns ``(stage_us, mark)`` where
        ``mark(name)`` records the segment since the previous mark into
        the ``loop_stage_us`` histogram and the tick's stage dict."""
        stage_us: Dict[str, int] = {}
        box = [t0]

        def mark(name: str) -> None:
            now = time.monotonic()
            d = int((now - box[0]) * 1e6)
            self.metrics.observe("loop_stage_us", d, stage=name)
            stage_us[name] = d
            box[0] = now

        return stage_us, mark

    def _fold_adaptive(self) -> None:
        """Fold delivery samples + pick this tick's assignment width
        BEFORE intake: the same choice slices the shard sends and rides
        the ``spr_override`` kernel input."""
        if self._adaptive is None:
            return
        while self.transport.samples:
            try:
                p, nb, dly = self.transport.samples.popleft()
            except IndexError:
                break
            self._adaptive.observe(p, nb, dly)
        self._spr_tick = self._adaptive.overrides(
            self.G, self._batch_bytes
        )

    def _build_tick_frames(self, frames, piggy) -> Dict[int, dict]:
        """Assemble this tick's per-peer frames: kernel lane slices plus
        the payload piggyback, need/serve planes, codeword gossip,
        health beacon, and near-quorum-read queries — identical content
        in both loop modes."""
        piggy.update(self._pending_serve)
        self._pending_serve = {}
        payload_msg: Dict[str, Any] = {
            "pp": piggy,
            "kv_need": bool(self.kv_need),
            "ts": time.monotonic(),  # adaptive delivery sampling
        }
        if self.health is not None:
            # health beacon: own signal EWMAs + my observations of
            # every peer's frame delay — each replica assembles the
            # same R-row table, so the indicted leader sees its own
            # indictment without any extra protocol
            payload_msg["hb"] = self.health.beacon()
        cw_need_by_dst: Dict[int, list] = {}
        # the full-payload "need" plane stays on in codeword mode:
        # CRaft full-copy-fallback values are never encoded into any
        # shard store, so only a full-batch serve can heal them.
        # Responders skip vids they hold shards for (the gossip
        # plane's job), so coded values never regress to full-copy
        # serving through this path.
        needs = sorted(self.missing)[:64]
        payload_msg["need"] = needs
        if self.codewords is not None:
            # shard-gossip requests, TARGETED: ask the fewest peers
            # whose base diagonal slices cover the deficit, leaders
            # last — steady-state heal traffic flows follower-to-
            # follower and the leader's egress is genuinely shed
            # (Compartmentalization-style), not re-centralized.
            # Entries unserved for ~40 ticks escalate to urgent:
            # broadcast, and peers answer with ANY held shard.
            cw_T, cw_dj = self.codewords.T, self._cw_dj
            for g, vid in needs:
                first = self._cw_first_missing.setdefault(
                    (g, vid), self.tick
                )
                have = self.codewords.have_mask(g, vid)
                if self.tick - first > 40:
                    for dst in range(self.population):
                        if dst != self.me:
                            cw_need_by_dst.setdefault(dst, []).append(
                                (g, vid, have, True)
                            )
                    continue
                lead = int(self._leader_hint[g])
                order = sorted(
                    (d for d in range(self.population)
                     if d != self.me),
                    key=lambda d: (d == lead, d),
                )
                cover = have
                for dst in order:
                    add = [
                        s for s in assigned_sids(
                            dst, cw_dj, cw_dj, cw_T
                        )
                        if not (cover >> s) & 1
                    ]
                    if not add:
                        continue
                    cw_need_by_dst.setdefault(dst, []).append(
                        (g, vid, have, False)
                    )
                    for s in add:
                        cover |= 1 << s
                    if bin(cover).count("1") >= self.codewords.d:
                        break
        if self._pending_kv_serve:
            payload_msg["kv"] = self.statemach.snapshot_items()
            payload_msg["kv_floor"] = list(self.applied)
            payload_msg["kv_wslots"] = dict(self._wslot)
            if self._epaxos:
                payload_msg["kv_ep"] = [
                    list(self._ep_exec[g].floor)
                    for g in range(self.G)
                ]
            self._pending_kv_serve = False
        rq = self._pending_rq
        rqr = self._pending_rqr
        self._pending_rq = {}
        self._pending_rqr = {}
        ps_pend = self._pending_shards
        cw_pend = self._pending_cw
        self._pending_shards = {}
        self._pending_cw = {}

        def _frame(dst):
            f = {"msg": frames[dst], **payload_msg}
            if dst in rq:
                f["rq"] = rq[dst]
            if dst in rqr:
                f["rqr"] = rqr[dst]
            if dst in ps_pend:
                f["ps"] = ps_pend[dst]
            if dst in cw_pend:
                f["cw"] = cw_pend[dst]
            if dst in cw_need_by_dst:
                f["cw_need"] = cw_need_by_dst[dst]
            return f

        tick_frames = {dst: _frame(dst) for dst in frames}
        # payload-plane egress accounting (the shard-economy meter:
        # full-copy piggybacks are identical per peer; shard sends
        # and gossip replies are sized once at enqueue time).  Sized
        # with the wire's own serializer (HIGHEST_PROTOCOL pickle in
        # both frame formats — the codec carries non-lane payload keys
        # in its rest-pickle blob), not a bare default-protocol dumps
        # that drifts from the bytes actually sent.
        if piggy:
            pp_len = wirecodec.payload_nbytes(piggy)
            for dst in tick_frames:
                self.pp_bytes[dst] += pp_len
                self.pp_items[dst] += len(piggy)
        return tick_frames

    def _build_inputs(self, n_prop, vbase) -> Dict[str, Any]:
        """This tick's kernel step inputs.  Both loop modes call it
        strictly after tick N-1's apply, so the common case sees the
        same ``exec_floor``.  One deliberate divergence: the serial
        loop ingests THIS tick's peer payloads before building inputs,
        so a kv install-snapshot merge arriving this tick jumps
        ``self.applied`` pre-step; the pipelined loop ingests during
        the overlap stage (that ingest IS the work hidden behind the
        scan), so a same-tick snapshot jump reaches the kernel one tick
        later.  Floors are monotone lower bounds the kernels tolerate
        at arbitrary lag — the cost is one extra catch-up tick on the
        snapshot path, not a safety difference."""
        inputs: Dict[str, Any] = {
            "n_proposals": jnp.asarray(n_prop),
            "value_base": jnp.asarray(vbase),
            "exec_floor": jnp.asarray(
                np.broadcast_to(
                    np.asarray(self.applied, np.int32)[:, None],
                    (self.G, self.population),
                )
            ),
        }
        self._conf_inputs(inputs)
        if self._demote_supported:
            dem = np.zeros((self.G, self.population), bool)
            if self.tick < self._demote_until:
                dem[:, self.me] = True
            inputs["demote"] = jnp.asarray(dem)
        if self._epaxos:
            floors = np.zeros(
                (self.G, self.population, self.population), np.int32
            )
            for g in range(self.G):
                floors[g, self.me, :] = self._ep_exec[g].floor
            inputs["exec_floor_rows"] = jnp.asarray(floors)
            inputs["prop_replica"] = jnp.full(
                (self.G,), self.me, jnp.int32
            )
            inputs["prop_vids"] = jnp.asarray(self._ep_prop_vids)
        if self._adaptive is not None:
            # the same choice that sliced this tick's shard sends
            # (picked before intake) — kernel win_spr stamps stay in
            # lockstep with the bytes on the wire
            inputs["spr_override"] = jnp.asarray(
                self._spr_tick, jnp.int32
            )
        return inputs

    def _tick_end(self, t0: float, deadline: float) -> None:
        """Shared tick epilogue: breakdown print, tick advance, the
        snapshot schedule, and the deadline sleep (with the nemesis
        clock-skew stretch)."""
        if self.record_breakdown:
            now = time.monotonic()
            if now - self._bd_last_print >= 5.0:
                # stage p50/p99 over the LAST window only (parity:
                # the reference leader prints bd stats every 5s and
                # resets, multipaxos/mod.rs:932-943 — a lifetime
                # quantile would pin to history and hide a fresh
                # stall); the cumulative histograms still ride every
                # metrics_dump scrape untouched
                parts = []
                prev = getattr(self, "_bd_prev", {})
                nxt = {}
                for n in _STAGES:
                    h = self.metrics.hist("loop_stage_us", stage=n)
                    if h is None:
                        continue
                    win = h.since(prev.get(n))
                    nxt[n] = h.copy()
                    parts.append(
                        f"{n}={win.quantile(0.5):.0f}us(p99 "
                        f"{win.quantile(0.99):.0f})"
                    )
                self._bd_prev = nxt
                pf_info(logger, "breakdown " + " ".join(parts))
                self._bd_last_print = now
        self.tick += 1
        if (
            self.snapshot_interval
            and self.tick % self.snapshot_interval == 0
            and sum(self.applied) > self._snap_last
        ):
            self._snap_last = sum(self.applied)
            self._take_snapshot()
            self.ctrl.send_ctrl(CtrlMsg(
                "snapshot_up_to", {"new_start": list(self.applied)}
            ))
        if self.watch is not None and self.tick % self.watch_ticks == 0:
            # graftwatch delta frame on the watch cadence: built from
            # one export_raw diff, shipped one-way over the existing
            # ctrl connection (never blocks the tick on a reply)
            t_emit = time.monotonic()
            frame = self.watch.frame(self.tick)
            self.ctrl.send_ctrl(CtrlMsg("watch_frame", frame))
            self.metrics.counter_add("watch_frames_total", 1)
            self.metrics.observe_s(
                "watch_emit_us", time.monotonic() - t_emit
            )

        now = time.monotonic()
        rem = deadline - now
        if self._tick_scale > 1.0:
            # a compute-bound loop never reaches the deadline sleep,
            # so stretching the deadline alone cannot slow the tick
            # clock; pad by the scaled ACTUAL loop time so the
            # victim's period is ~scale x its natural period either
            # way (verified live: tick-advance ratio tracks the
            # injected factor)
            rem = max(rem, (self._tick_scale - 1.0) * (now - t0))
        if rem > 0:
            time.sleep(rem)

    def _tick_serial(self, t0: float) -> None:
        """One strictly-ordered tick — the exact pre-pipeline loop:
        intake -> send/recv -> step (forced) -> WAL log -> group-commit
        fsync -> apply/reply.  ``pipeline=False`` serves byte-identical
        digests through this body (the A/B control)."""
        stage_us, _stage = self._stage_clock(t0)

        # 1. client intake -> payload ids (one ReqBatch per group/tick)
        self._fold_adaptive()
        n_prop, vbase, piggy = self._intake()
        _stage("intake")

        # 2. exchange tick frames and step the kernel
        frames = self._slice_outbox(self._last_out)
        # _tick_scale > 1 is the nemesis clock-skew fault: this
        # replica's tick clock runs slow relative to its peers
        deadline = t0 + self.tick_interval * self._tick_scale
        tick_frames = self._build_tick_frames(frames, piggy)
        # graftlint: disable=H105 -- serial loop: these frames carry step N-1's outbox, whose WAL records _flush_durability fsynced at the END of tick N-1 — the strict stage order IS the fence
        self.transport.send_tick(self.tick, tick_frames)
        got = self.transport.recv_tick(self.tick, deadline)
        self._ingest_payloads(got)
        inbox = self._assemble_inbox(self._last_out, got)
        inputs = self._build_inputs(n_prop, vbase)
        _stage("exchange")  # frame exchange + inbox assembly
        new_state, new_out, fx = self._step(self.state, inbox, inputs)
        self._set_state(new_state)
        self._last_out = new_out
        _stage("step")  # kernel step (forced by the WAL log's reads)

        # 3. durability before the acks in last_out leave (top of next
        # iteration); then apply newly committed slots + leadership
        self._log_votes()
        _stage("log")  # durable acceptor log
        self._apply_committed(fx)
        self._flush_durability()
        self._qread_expire()
        self._conf_progress()
        self._range_progress()
        self._leader_edges(fx)
        self._health_tick()
        self._autopilot_tick()
        _stage("apply")  # apply + reply
        # per-tick flight event: the loop_stage_us stopwatches become
        # child spans of this tick at export (the `step` stage is the
        # device scan, so device and host tracks share one timeline)
        self.flight.record("tick", tick=self.tick, **stage_us)
        self._tick_end(t0, deadline)

    def _tick_pipelined(self, t0: float) -> None:
        """One software-pipelined tick: same DATAFLOW order as the
        serial loop — intake, send, recv, step, log, apply — but with
        the two wait-shaped stages moved off the critical path:

        - the device step is DISPATCHED asynchronously right after the
          inbox is assembled and drained only at its first consumer
          (``overlap``/``device_wait`` stages): peer-payload ingest and
          the conf/qread/health bookkeeping run while the scan is in
          flight;
        - the WAL group-commit fsync runs on the logger thread
          (``StorageHub.flush_token``), opened right after apply/log
          append this step's records; the loop never blocks on it
          mid-tick — replies release at tick end IF the fsync already
          settled under the deadline sleep (idle), else at the next
          tick's exchange (saturated), and frames gate at the next
          send, so the fsync always overlaps sleep + the next tick's
          head instead of sitting on the critical path.

        Stage order (one iteration)::

            intake   tick N's client batch -> proposals
            exchange frames out (lanes N-1 + tick-N piggyback, gated on
                     the fence over step N-1's records), any replies
                     still deferred from tick N-1 released behind the
                     same fence, then frame recv until the deadline
            inbox    inbox lane assembly (device idle — its device_put
                     calls would serialize against an in-flight scan)
            dispatch inputs built, step N launched (async)
            overlap  peer-payload ingest + bookkeeping, coincident with
                     the in-flight scan
            device_wait  residual block on step N's results
            apply    apply N's commits, queue replies
            log      N's durable acceptor rows -> background appends,
                     fence N opened (the fsync launches here)
            (sleep to the deadline, fsync running under it)
            drain    fence N POLLED: replies/notes released now if the
                     fsync settled, else at N+1's exchange — never a
                     blocking wait here

        Keeping the serial dataflow (step N consumes THIS tick's
        received frames, apply N lands the same tick) means pipelining
        adds no per-hop message latency; the win is the fsync and the
        scan leaving the critical path.  The durability fence: no
        vote/ack computed by step N leaves in a frame or reply before
        step N's WAL records are fsynced — ``_fence_wait``/``poll_
        flush`` gate both egress seams, and a failed fsync crashes the
        replica with everything gated on it still unsent."""
        stage_us, _stage = self._stage_clock(t0)
        deadline = t0 + self.tick_interval * self._tick_scale
        stage_us["device_wait"] = 0

        # 1. client intake -> payload ids (one ReqBatch per group/tick)
        self._fold_adaptive()
        n_prop, vbase, piggy = self._intake()
        _stage("intake")

        # 2. egress behind the fence: tick N-1's outbox lanes + this
        # tick's piggyback.  Tick N-1's own drain consumed its fence,
        # so the gate inside send_tick is normally a no-op — it matters
        # exactly when the previous tick aborted between fence-open and
        # drain (ctrl-plane exit paths), where a failed background
        # fsync must still raise HERE, before anything escapes.  The
        # drain call releases replies a ctrl handler queued between
        # ticks.
        frames = self._slice_outbox(self._last_out)
        tick_frames = self._build_tick_frames(frames, piggy)
        self.transport.send_tick(
            self.tick, tick_frames, fence=self._fence_wait
        )
        self._drain_replies()
        got = self.transport.recv_tick(self.tick, deadline)
        _stage("exchange")

        # 3. inbox assembly while the device is idle, then the async
        # dispatch: the host stops forcing an early sync — nothing
        # below touches step N's results until the drain
        inbox = self._assemble_inbox(self._last_out, got)
        _stage("inbox")
        inputs = self._build_inputs(n_prop, vbase)
        new_state, new_out, nfx = self._step(self.state, inbox, inputs)
        self._pl = {
            "state": new_state, "out": new_out, "fx": nfx,
            "tick": self.tick, "t_dispatch": time.monotonic(),
        }
        self._prefetch_async(new_state, new_out, nfx)
        _stage("dispatch")

        # 4. overlapped host work: everything that does NOT consume
        # step N runs while the scan is in flight — the "overlap"
        # stage is the pipelining win the A/B gates on (host-stage
        # wall time coincident with the dispatched device step)
        self._ingest_payloads(got)
        self._qread_expire()
        self._conf_progress()
        self._range_progress()
        self._health_tick()
        self._autopilot_tick()
        _stage("overlap")

        # 5. drain step N (residual wait only — the scan had stage 4
        # to finish) and retire it: apply commits, queue replies, log
        # the durable rows, open fence N (the fsync launches on the
        # logger thread and runs under the sleep + next tick's head)
        pl = self._pl
        self._pl = None
        jax.block_until_ready(pl["out"])  # one executable: state+fx too
        _stage("device_wait")
        self.flight.record(
            "device_step", tick=pl["tick"],
            dur_us=int((time.monotonic() - pl["t_dispatch"]) * 1e6),
            wait_us=stage_us["device_wait"],
        )
        self._set_state(pl["state"])
        self._last_out = pl["out"]
        self._apply_committed(pl["fx"])
        self._leader_edges(pl["fx"])
        _stage("apply")
        self._log_votes()
        self._fence_begin()
        _stage("log")

        self.flight.record(
            "tick", tick=self.tick, pipelined=1, **stage_us
        )
        self._tick_end(t0, deadline)

        # 6. release this tick's replies/notes if the fence already
        # settled (idle: the deadline sleep absorbed the group-commit
        # fsync, so replies leave the same tick, like serial's); if the
        # fsync is still in flight (saturated: no sleep), DEFER to the
        # next tick's exchange rather than block — blocking here
        # re-serializes the fsync into the critical path and was
        # measured costing 15% saturated throughput, while the one-tick
        # ack deferral costs closed-loop clients nothing at saturation
        # (ticks are short exactly when the loop is busy).  The poll
        # still raises a latched background-fsync error, so a failed
        # group commit crashes the replica with every reply unsent.
        self._drain_replies_if_settled()

    def _prefetch_async(self, new_state, new_out, fx) -> None:
        """Start device->host copies for every leaf the host will read
        next tick — the drain then finds the bytes already on their way
        instead of paying a synchronous copy per ``np.asarray`` (the
        'no np.asarray right after _step' rule).

        Accelerator backends only: on the CPU backend ``np.asarray`` of
        a ready array is already a zero-copy view, so the ~30 per-leaf
        async-copy dispatches per tick are pure overhead (measured ~15%
        of the pipelined tick rate on the bench box) with nothing to
        prefetch across a PCIe/ICI link."""
        if self._prefetch_keys is None:
            if jax.default_backend() == "cpu":
                self._prefetch_keys = []
                return
            ker = self.kernel
            cand = set(ker.DURABLE_SCALARS or ()) | set(
                ker.DURABLE_WINDOWS or ()
            )
            cand.update((
                ker.VALUE_WINDOW, "win_abs", "win_bal", "win_cfg",
                "win_noop", "leader", "alive_cnt", "conf_cur",
                "conf_resp", "conf_leader", "own_next", "exec_row",
                "cmt_row", "abs2", "st2", "seq2", "val2", "noop2",
                "deps2", dev_telemetry.TELEM_KEY,
            ))
            self._prefetch_keys = sorted(
                k for k in cand if k in new_state
            )
        if not self._prefetch_keys:
            return  # CPU backend / no async-copy support: nothing to do
        try:
            for k in self._prefetch_keys:
                new_state[k].copy_to_host_async()
            for v in new_out.values():
                v.copy_to_host_async()
            fx.commit_bar.copy_to_host_async()
            for v in fx.extra.values():
                v.copy_to_host_async()
        except AttributeError:
            # backend arrays without async host copies: the drain's
            # np.asarray still works, just without the head start
            self._prefetch_keys = []

    def _pipeline_flush(self) -> None:
        """Settle the pipeline: drain any in-flight step (defensive —
        the tick body retires it before returning, so this only fires
        if a tick aborted between dispatch and drain), retire its host
        side (log/apply), and release everything gated on the fence.
        Runs on graceful exit (leave/stop) so already-applied ops are
        acked before teardown (tick counters are NOT advanced — this is
        retirement, not a new tick)."""
        pl = self._pl
        if pl is not None:
            self._pl = None
            jax.block_until_ready(pl["out"])
            self._set_state(pl["state"])
            self._last_out = pl["out"]
            self._apply_committed(pl["fx"])
            self._leader_edges(pl["fx"])
            self._log_votes()
        self._fence_begin()
        self._drain_replies()

    # -------------------------------------------------- payload exchange
    def _ingest_payloads(self, got) -> None:
        # payload piggybacks are unioned across ALL frames a peer sent
        # since our last tick (unlike kernel lanes, they are not
        # cumulative — skipping one could drop a served payload)
        for src, fl in got.items():
            for f in fl or ():
                if self.health is not None and "hb" in f:
                    self.health.ingest(src, f["hb"], time.monotonic())
                for (g, vid), batch in f.get("pp", {}).items():
                    self.payloads.install(g, vid, batch, overwrite=False)
                    self.missing.discard((g, vid))
                    self._cw_first_missing.pop((g, vid), None)
                # codeword plane: proposer-assigned shard subsets ("ps")
                # and gossip fills ("cw") land in the shard store; the
                # exec path reconstructs lazily once >= d are held
                if self.codewords is not None:
                    # "ps" rows are this replica's ASSIGNMENT (vote-
                    # loggable); "cw" gossip fills are not (wal_shards)
                    for key in ("ps", "cw"):
                        for (g, vid), sp in (f.get(key) or {}).items():
                            self.codewords.add_shards(
                                g, vid, sp.data_len, sp.shards,
                                assigned=(key == "ps"),
                            )
                            self.payloads.note_seen(g, vid)
                    # serve shard-gossip requests next tick from held
                    # shards: non-urgent rounds answer only with our own
                    # diagonal slice (load stays spread across peers —
                    # the leader is not re-centralized), urgent rounds
                    # with anything held the requester lacks
                    own = assigned_sids(
                        self.me, self._cw_dj, self._cw_dj,
                        self.codewords.T,
                    )
                    for g, vid, have, urgent in f.get("cw_need", ())[:64]:
                        held = self.codewords.shards_for(
                            g, vid, exclude_mask=have,
                            only_sids=None if urgent else own,
                        )
                        if held is not None:
                            sp = ShardPayload(held[0], held[1])
                            self._pending_cw.setdefault(src, {})[
                                (g, vid)
                            ] = sp
                            self.cw_bytes[src] += _sp_size(sp)
                # serve peers' missing payloads / kv requests next tick by
                # folding them into our own piggyback (codeword mode:
                # only values with no shard presence here — full-copy
                # fallback batches — take this full-serve path)
                for g, vid in f.get("need", []):
                    if (
                        self.codewords is not None
                        and self.codewords.have_mask(g, vid)
                    ):
                        continue
                    b = self.payloads.get(g, vid)
                    if b is not None:
                        self._pending_serve[(g, vid)] = b
                if f.get("kv_need") and not self.kv_need:
                    self._pending_kv_serve = True
                if "kv" in f and self.kv_need:
                    self._merge_kv(
                        f["kv"], f["kv_floor"], f.get("kv_ep"),
                        f.get("kv_wslots"),
                    )
                # near-quorum read queries/replies (quorumread.rs planes)
                for rid, key, g in f.get("rq", []):
                    self._pending_rqr.setdefault(src, []).append(
                        (rid,) + self._local_read_sample(g, key)
                    )
                for rid, value, wslot, hit in f.get("rqr", []):
                    qr = self._qreads.get(rid)
                    if qr is not None and src not in qr["replies"]:
                        qr["replies"][src] = (value, wslot, hit)
                        self._qread_check(rid)

    def _merge_kv(self, kv: dict, floors: list,
                  ep_floors: Optional[list] = None,
                  wslots: Optional[dict] = None) -> None:
        """Install-snapshot KV merge, guarded per group: only groups that
        jumped take the provider's state, and only when the provider's
        floor covers our claimed floor — a stale provider must never
        overwrite newer local execution (this was possible before r4).
        For EPaxos the provider's per-row exec floors ride along so the
        executor can jump past rows whose instances slid out of the
        stored-copy window."""
        def dominates(g: int) -> bool:
            if g >= len(floors) or floors[g] <= self.applied[g]:
                return False
            if not self._epaxos:
                return True
            # EPaxos: the provider must be ahead or equal on EVERY row —
            # a sum-ahead provider that lags one row would regress that
            # row's keys and the floor merge would mark them executed
            if ep_floors is None or g >= len(ep_floors):
                return False
            return all(
                int(p) >= l
                for p, l in zip(ep_floors[g], self._ep_exec[g].floor)
            )

        ok_groups = {g for g in self.kv_need if dominates(g)}
        if not ok_groups:
            return
        upd = {
            k: v for k, v in kv.items() if self.route_group(k) in ok_groups
        }
        self.statemach._kv.update(upd)
        # install-snapshot jumps bypass the per-slot apply loop, so the
        # commit feed must carry the transferred values too — a learner
        # of a jumped replica would otherwise hold keys the replica
        # itself serves newer values of
        for k, v in upd.items():
            self._note_put(k, v)
        # the transferred values' write slots must ride along, or a
        # jumped replica would report stale/absent wslots for NEWER
        # values and lose the near-quorum-read max-by-wslot comparison
        # to a lagging peer's older value (linearizability violation)
        for k in upd:
            s = (wslots or {}).get(k)
            if s is not None:
                self._wslot[k] = max(self._wslot.get(k, -1), int(s))
        for g in ok_groups:
            self.applied[g] = max(self.applied[g], int(floors[g]))
            if self._epaxos:
                ex = self._ep_exec[g]
                ex.floor = [
                    max(a, int(b)) for a, b in zip(ex.floor, ep_floors[g])
                ]
                ex.lost_rows = []
                self.applied[g] = max(self.applied[g], sum(ex.floor))
            self.kv_need.discard(g)

    # ------------------------------------------------------- application
    def _make_ep_apply(self, g: int):
        """Build the EPaxos executor's apply callback for group ``g``:
        WAL-log the exec record, apply to the KV, reply to originated
        clients (parity: epaxos/execution.rs commit_execute path)."""
        def apply_fn(row: int, col: int, vid: int, noop: bool) -> None:
            batch = (
                None if (noop or vid == 0) else self.payloads.get(g, vid)
            )
            self._wal_append(("eapply", g, row, col, vid, batch))
            if batch is not None:
                self.traces.mark_committed(g, vid, self.tick)
                self.flight.record(
                    "commit", g=g, vid=vid, row=row, col=col,
                    tick=self.tick,
                )
                mine = (g, vid) in self.origin
                for client, req in batch:
                    res = apply_command(self.statemach._kv, req.cmd)
                    if req.cmd.kind == "put":
                        self._note_put(req.cmd.key, req.cmd.value)
                    if mine:
                        self._reply_queue.append((client, ApiReply(
                            "reply", req_id=req.req_id, result=res,
                        )))
                self.metrics.counter_add(
                    "commits_applied_total", len(batch)
                )
                self.traces.mark_applied(g, vid, self.tick)
                self.flight.record(
                    "apply", g=g, vid=vid, row=row, col=col,
                    tick=self.tick,
                )
                if mine:
                    self._trace_replied.append((g, vid))
        return apply_fn

    def _apply_committed_epaxos(self) -> None:
        me = self.me
        cmt = self._np_state("cmt_row")[:, me]
        arrs = None
        for g in range(self.G):
            ex = self._ep_exec[g]
            if int(cmt[g].sum()) <= sum(ex.floor):
                continue
            if arrs is None:
                arrs = {
                    k: self._np_state(k)[:, me]
                    for k in ("abs2", "st2", "seq2", "val2", "noop2",
                              "deps2")
                }

            def payload_ok(vid: int, noop: bool, g=g) -> bool:
                if noop or vid == 0:
                    return True
                if self.payloads.get(g, vid) is None:
                    self.missing.add((g, vid))
                    return False
                return True

            ex.advance(
                arrs["abs2"][g], arrs["st2"][g], arrs["seq2"][g],
                arrs["val2"][g], arrs["noop2"][g], arrs["deps2"][g],
                cmt[g], payload_ok,
            )
            if ex.lost_rows:
                # committed instances slid out of our stored-copy window
                # (paused/partitioned too long): catch up via the KV
                # install-snapshot plane, same as the frontier kernels
                self.kv_need.add(g)
            self.applied[g] = sum(ex.floor)

    def _apply_committed(self, fx) -> None:
        self._last_extra = {
            k: np.asarray(v) for k, v in fx.extra.items()
        }
        if self._epaxos:
            self._apply_committed_epaxos()
            return
        cbs = np.asarray(fx.commit_bar)[:, self.me]
        applied = np.asarray(self.applied)
        for g in np.nonzero(cbs > applied)[0]:
            self._apply_group(int(g), int(cbs[g]))

    def _apply_group(self, g: int, cb: int) -> None:
        if g in self.kv_need:
            # a window jump is pending its KV transfer: applying further
            # slots against a KV missing the jumped range would serve
            # stale values — hold the exec floor until the merge lands
            return
        win_abs = self._np_state("win_abs")[g, self.me]
        win_val = self._np_state(self.kernel.VALUE_WINDOW)[g, self.me]
        # marker lanes whose slots carry non-payload values: conf entries
        # (win_cfg stores the grantee bitmap in win_val) and no-op fills
        marker = np.zeros_like(win_abs, bool)
        for lane in ("win_cfg", "win_noop"):
            if lane in self.state:
                marker |= self._np_state(lane)[g, self.me] != 0
        while self.applied[g] < cb:
            slot = self.applied[g]
            pos = np.where(win_abs == slot)[0]
            if len(pos) == 0:
                # below the window: an install-snapshot jumped us forward;
                # fetch the KV state from peers host-side.  applied[g] is
                # NOT advanced — the provider's floor covers the jump, so
                # the merge both fills the KV and moves the floor (moving
                # it here would let later slots execute over a hole)
                self.kv_need.add(g)
                return
            is_marker = bool(marker[pos[0]])
            vid = 0 if is_marker else int(win_val[pos[0]])
            if vid != 0:
                # host-side commit observation: the slot passed under the
                # commit bar this tick (ticks_to_commit distribution +
                # the flight recorder's commit event — on EVERY replica,
                # so follower timelines carry the bar too)
                self.traces.mark_committed(g, vid, self.tick)
                self.flight.record(
                    "commit", g=g, vid=vid, slot=slot, tick=self.tick
                )
            batch = self._resolve_payload(g, vid)
            if vid != 0 and batch is None:
                self.missing.add((g, vid))
                return  # stall the exec floor until the payload arrives
            # durability before client-visible effects (storage.rs intent):
            # the apply record lands now, the group-commit fsync runs
            # before the queued reply leaves — an acked write survives
            # machine crash, not just process restart
            self._wal_append((g, slot, vid, batch))
            if batch is not None:
                mine = (g, vid) in self.origin
                for client, req in batch:
                    if req.cmd is not None and req.cmd.kind == "adopt":
                        # replicated range adoption: executes at its
                        # destination-group slot on every replica; only
                        # the proposer announces to the manager
                        self._apply_adopt(req.cmd.value, announce=mine)
                        continue
                    if req.cmd.kind == "scan" and client is None:
                        # commit-bar scan barrier marker: no KV effect
                        # anywhere; the proposer reads the final cut
                        # when its LAST group's marker lands
                        if mine:
                            self._scan_barrier_hit(g, req.req_id)
                        continue
                    if req.cmd.kind == "put":
                        ent = self.rangetab.lookup(req.cmd.key)
                        if ent is not None \
                                and int(ent["group"]) % self.G != g:
                            # a write to a moved-away range surfacing in
                            # its OLD group's log: below the handoff
                            # floor its value already rode the adopt
                            # snapshot — ack without applying (applying
                            # would regress the moved key); above the
                            # floor is unreachable given the cluster-
                            # wide seal confirmation + tail barrier,
                            # but if it ever fires, never lose the ack
                            floors = ent.get("floors") or []
                            fg = int(floors[g]) if g < len(floors) else 0
                            if slot < fg:
                                if mine:
                                    self._reply_queue.append((
                                        client, ApiReply(
                                            "reply", req_id=req.req_id,
                                            result=CommandResult("put"),
                                        ),
                                    ))
                                continue
                            pf_warn(
                                logger,
                                f"post-floor write to moved range at "
                                f"g{g} slot {slot} key "
                                f"{req.cmd.key!r}: applying",
                            )
                    res = apply_command(self.statemach._kv, req.cmd)
                    if req.cmd.kind == "put":
                        k = req.cmd.key
                        # monotone across group moves: the handed-off
                        # watermark may exceed this group's slot numbers
                        self._wslot[k] = max(
                            slot, self._wslot.get(k, -1) + 1
                        )
                        self._note_put(k, req.cmd.value)
                    if mine:
                        self._reply_queue.append((client, ApiReply(
                            "reply", req_id=req.req_id, result=res,
                        )))
                self.metrics.counter_add(
                    "commits_applied_total", len(batch)
                )
                self.traces.mark_applied(g, vid, self.tick)
                self.flight.record(
                    "apply", g=g, vid=vid, slot=slot, tick=self.tick
                )
                if mine:
                    self._trace_replied.append((g, vid))
            self.applied[g] = slot + 1

    def _flush_durability(self) -> None:
        """Group commit: one fsync covers every record appended this
        tick, then the replies gated on them go out.  The kernel acks in
        the outbox leave at the top of the NEXT tick, strictly after
        this point — the durability-before-ack invariant holds with one
        fsync per tick instead of one per record."""
        if self._wal_dirty:
            res = self.wal.do_sync_action(LogAction("sync"))
            if not res.offset_ok:
                # a failed fsync (EIO/ENOSPC) must NEVER release the
                # replies gated on it — crash instead; the restart loop
                # recovers from whatever actually reached the disk
                raise SummersetError(
                    f"WAL group-commit fsync failed: {res.entry}"
                )
            self._wal_dirty = False
        for client, reply in self._reply_queue:
            self._reply(client, reply)
        self._reply_queue.clear()
        self._flush_notes()
        if self._trace_replied:
            now = time.monotonic()
            for g, vid in self._trace_replied:
                self.traces.mark_replied(g, vid, now)
            self._trace_replied.clear()

    def _leader_edges(self, fx) -> None:
        ex = self._last_extra
        is_l = ex.get("is_leader")
        if is_l is None:
            return
        self._is_leader = is_l[:, self.me].astype(bool)
        if "leader" in self.state:
            lead = self._np_state("leader")[:, self.me]
            self._leader_hint = np.where(
                (lead == self.me) & ~self._is_leader, -1, lead
            )
        # manager tracking follows group 0 (the reference has one group).
        # Level-based with periodic re-announce, not edge-only: an edge
        # can be lost when leadership bounces through a third replica
        # while our own flag never flips (verified wedge: kernel-healthy
        # leader + manager stuck at leader=None, steering clients wrong)
        g0 = bool(self._is_leader[0])
        if g0 != self.was_leader:
            self.ctrl.send_ctrl(
                CtrlMsg("leader_status", {"step_up": g0})
            )
            self.was_leader = g0
            self._lead_announced = self.tick
        elif g0 and self.tick - getattr(self, "_lead_announced", 0) >= 200:
            self.ctrl.send_ctrl(CtrlMsg("leader_status", {"step_up": True}))
            self._lead_announced = self.tick

    # ------------------------------------------------------ gray failure
    def _health_tick(self) -> None:
        """Feed the scorer, and every ``health_eval_ticks`` run the
        quorum-median outlier round.  When the verdict indicts THIS
        replica: as a leader, step down voluntarily through the kernel's
        own election machinery (QuorumLeases/Bodega first revoke their
        lease responders through the conf plane's revoke-then-adopt
        barrier); as a lease responder, ``_can_local_read`` starts
        steering reads back to the leader.  Mitigation-disabled servers
        (the soak's observe-only twins) still score and export
        ``health_score`` — they just never act."""
        h = self.health
        if h is None:
            return
        h.end_tick(self.metrics.gauge_value("api_queue_depth", 0.0))
        if self.tick % self.health_eval_ticks:
            return
        verdict = h.evaluate(time.monotonic())
        self.metrics.gauge_set(
            "health_score", verdict.scores.get(self.me, 1.0)
        )
        self._health_self_bad = self.me in verdict.indicted
        if not (self.health_mitigation and self._demote_supported):
            return
        if self._ap_demote_pending:
            # an autopilot-initiated revoke-then-demote is in flight;
            # _autopilot_tick owns its resolution — the health plane's
            # false-alarm restore must not cancel a deliberate,
            # policy-driven re-placement
            return
        if self._demote_revoke_deadline is not None:
            # an in-flight lease-revoke must RESOLVE either way — a
            # frozen deadline would both strand the revoked responders
            # and let a much-later indictment skip the barrier entirely
            conf_idle = self._conf_active is None and not self._conf_queue
            if not conf_idle and self.tick <= self._demote_revoke_deadline:
                return  # still installing
            self._demote_revoke_deadline = None
            restore = self._demote_restore_resp
            self._demote_restore_resp = None
            if verdict.evaluated and not self._health_self_bad:
                # false alarm: the indictment cleared while revoking —
                # cancel the demotion and restore the pre-revoke
                # responders so lease-local reads come back
                if restore:
                    self._handle_conf_req(None, ApiRequest(
                        "conf", conf_delta={"responders": restore},
                    ))
                return
            # still indicted (or beacons starved — the limp itself can
            # do that): abdicate; lease TTLs make a straggling revoke
            # safe, same as a leader crash
            self._arm_demotion(verdict)
            return
        if not (verdict.evaluated and self._health_self_bad):
            return
        if self.tick < max(self._demote_cooldown_until, self._demote_until):
            return
        if not self._is_leader.any():
            return  # responder indictment: steering only, no demotion
        if self._conf_kind is not None:
            # QuorumLeases/Bodega: revoke the read-lease responders
            # FIRST (an empty-responders ConfChange through the existing
            # revoke-then-adopt barrier) so lease-local reads drain
            # cleanly instead of riding TTL expiry under a gone leader
            self._demote_restore_resp = self._current_responders()
            self._handle_conf_req(None, ApiRequest(
                "conf", conf_delta={"responders": []},
            ))
            self._demote_revoke_deadline = self.tick + 600
            pf_warn(
                logger,
                f"health: replica {self.me} (leader) indicted "
                f"{verdict.outliers.get(self.me)} — revoking leases "
                "before demotion",
            )
            return
        self._arm_demotion(verdict)

    def _current_responders(self) -> List[int]:
        """The currently installed lease responders (group 0's conf —
        the manager-tracking convention), for restore-on-false-alarm."""
        if self._conf_kind == "ql":
            bits = int(self._np_state("conf_cur")[0, self.me])
        elif self._conf_kind == "bodega":
            bits = int(self._np_state("conf_resp")[0, self.me, 0])
        else:
            return []
        if bits <= 0:
            return []
        return [r for r in range(self.population) if bits >> r & 1]

    def _arm_demotion(self, verdict) -> None:
        """Arm the kernel ``demote`` input for this replica's rows and
        stamp the demotion everywhere it must be attributable."""
        self._demote_until = self.tick + self.health_demote_ticks
        self._demote_cooldown_until = (
            self._demote_until + self.health_cooldown_ticks
        )
        self.metrics.counter_add("leader_demotions")
        self.flight.record(
            "demote", tick=self.tick,
            signals=",".join(verdict.outliers.get(self.me, ())),
            score=verdict.scores.get(self.me, 0.0),
        )
        pf_warn(
            logger,
            f"health: replica {self.me} stepping down "
            f"(outlier on {verdict.outliers.get(self.me)})",
        )

    # ---------------------------------------------------------- autopilot
    def _autopilot_demote(self, reason: str) -> bool:
        """Targeted voluntary demotion on behalf of the autopilot's
        lead_move actuator.  Reuses the health plane's machinery — the
        same kernel ``demote`` input, the same QL/Bodega revoke-first
        barrier, the same cooldown stamps — but is driven by policy
        (leader re-placement near traffic) rather than an indictment.
        Returns False when the demotion cannot apply here (family
        without the demote input, cooldown, not a leader, or a revoke
        already in flight)."""
        if not self._demote_supported:
            return False
        if self._ap_demote_pending \
                or self._demote_revoke_deadline is not None:
            return False
        if self.tick < max(self._demote_cooldown_until,
                           self._demote_until):
            return False
        if not self._is_leader.any():
            return False
        if self._conf_kind is not None:
            # lease protocols: revoke responders first, exactly like
            # the health path; _autopilot_tick resolves the barrier
            self._demote_restore_resp = self._current_responders()
            self._handle_conf_req(None, ApiRequest(
                "conf", conf_delta={"responders": []},
            ))
            self._demote_revoke_deadline = self.tick + 600
            self._ap_demote_pending = True
            pf_warn(logger, f"autopilot: replica {self.me} revoking "
                            f"leases before demotion ({reason})")
            return True
        self._ap_arm_demotion(reason)
        return True

    def _ap_arm_demotion(self, reason: str) -> None:
        """The autopilot twin of ``_arm_demotion``: same kernel input
        and pacing stamps, attributed to the policy tier."""
        self._demote_until = self.tick + self.health_demote_ticks
        self._demote_cooldown_until = (
            self._demote_until + self.health_cooldown_ticks
        )
        self.metrics.counter_add("leader_demotions")
        self.metrics.counter_add(
            "autopilot_actions", 1, actuator="lead_move",
        )
        self.flight.record(
            "autopilot_act", act="demote", reason=str(reason),
            tick=self.tick,
        )
        pf_warn(logger, f"autopilot: replica {self.me} stepping down "
                        f"({reason})")

    def _autopilot_tick(self) -> None:
        """Resolve an autopilot-initiated lease revoke (the barrier the
        health plane's ``_health_tick`` deliberately skips while
        ``_ap_demote_pending`` is set): once the empty-responders
        ConfChange installs — or its deadline passes with the conf
        plane wedged — arm the demotion.  Unlike the health path there
        is no false-alarm restore: the policy decided to move the
        leader, so the demotion always completes."""
        if not self._ap_demote_pending:
            return
        if self._demote_revoke_deadline is None:
            self._ap_demote_pending = False
            return
        conf_idle = self._conf_active is None and not self._conf_queue
        if not conf_idle and self.tick <= self._demote_revoke_deadline:
            return  # still installing
        self._demote_revoke_deadline = None
        self._demote_restore_resp = None
        self._ap_demote_pending = False
        self._ap_arm_demotion("lease-revoke-complete")

    # ----------------------------------------------------------- control
    def _handle_ctrl(self) -> Optional[bool]:
        msg = self.ctrl.try_recv_ctrl()
        if msg is None:
            return None
        if msg.kind == "pause":
            self.paused = True
            self.ctrl.send_ctrl(CtrlMsg("pause_reply"))
        elif msg.kind == "resume":
            self.paused = False
            self.ctrl.send_ctrl(CtrlMsg("resume_reply"))
        elif msg.kind == "reset_state":
            if not msg.payload.get("durable", True):
                self.wal.stop()
                for path in (self.wal_path, self.snap_path):
                    try:
                        os.remove(path)
                    except OSError:
                        pass
            self.ctrl.send_ctrl(CtrlMsg("reset_reply"))
            return True
        elif msg.kind == "install_conf":
            # manager-relayed ConfChange (split per-group leadership),
            # newest-seq-wins: a stale relay must neither re-queue
            # behind a newer one (it would revert the conf when it
            # activated) nor occupy the single active slot
            d = msg.payload.get("delta") or {}
            seq = int(msg.payload.get("seq", 0))
            if self._conf_kind is not None and seq > self._conf_seq_seen:
                self._conf_seq_seen = seq
                resp = 0
                for r in d.get("responders", []):
                    resp |= 1 << int(r)
                a = self._conf_active
                # drop superseded manager-relayed entries from the queue
                self._conf_queue = [
                    (c, q) for c, q in self._conf_queue if c is not None
                ]
                if not (a is not None and a.get("resp") == resp):
                    self._conf_queue.append((None, ApiRequest(
                        "conf", conf_delta=d,
                    )))
        elif msg.kind == "range_change":
            # live resharding seal (host/resharding.py): every replica
            # seals immediately; the adopting leader proposes the adopt
            # once the barrier clears.  Always ack — a refused change
            # (leaderless protocol) still answers the manager's fan-out.
            self._range_begin(dict(msg.payload.get("change") or {}))
            self.ctrl.send_ctrl(CtrlMsg("range_reply"))
        elif msg.kind == "install_ranges":
            # manager re-announce (late joiners + fan-out stragglers),
            # newest-seq-wins like install_conf.  Installed entries land
            # WITHOUT their KV data — the moved keys reach this replica
            # through its own adopt apply or the install-snapshot plane.
            # Crucially the re-announce installs only the routing
            # OVERRIDE (and unseals): the rc_id is NOT marked adopted,
            # so when the replicated adopt command later executes at
            # this replica's destination-group slot, _apply_adopt still
            # merges the handed-off KV/wslots.  Marking it adopted here
            # made that merge a no-op, and a replica with unexecuted
            # below-floor source slots then had NO path to the moved
            # keys' committed values short of a full install-snapshot.
            seq = int(msg.payload.get("seq", 0))
            if seq > self._range_seq_seen:
                self._range_seq_seen = seq
                for entry in msg.payload.get("installed", []):
                    rc_id = int(entry["rc_id"])
                    if rc_id not in self._range_adopted \
                            and rc_id not in self._range_override:
                        self._range_override.add(rc_id)
                        self.rangetab.install(entry)
                        self._range_sealed.pop(rc_id, None)
                        self._range_adopt_mark.pop(rc_id, None)
                for ch in msg.payload.get("pending", []):
                    rc_id = int(ch.get("rc_id", 0))
                    sealed = self._range_sealed.get(rc_id)
                    if sealed is not None:
                        # already sealed: only the seal-complete flag can
                        # change (the manager grants it once every server
                        # acked the seal fan-out — the adopt barrier's
                        # cluster-wide half)
                        if ch.get("sealed_ok"):
                            sealed["sealed_ok"] = True
                    elif rc_id not in self._range_adopted \
                            and rc_id not in self._range_override:
                        self._range_begin(dict(ch), replayed=True)
                for rc_id in msg.payload.get("expired", []):
                    # seal-TTL rollback: the manager expired a pending
                    # change (destination leaderless past the TTL, no
                    # adopt grant issued) — un-seal and resume serving
                    self._range_unseal(int(rc_id), why="seal-ttl")
        elif msg.kind == "adopt_decision":
            # manager's answer to our adopt_intent: a grant pins the
            # change against seal-TTL expiry (adopt proceeds next
            # _range_progress); a refusal means the change expired
            # under us — roll it back here too
            rc_id = int(msg.payload.get("rc_id", 0))
            if msg.payload.get("ok"):
                if rc_id in self._range_sealed:
                    self._range_adopt_granted.add(rc_id)
            else:
                self._range_unseal(rc_id, why="adopt-refused")
        elif msg.kind == "fault_ctl":
            # nemesis fault injection (host/nemesis.py): swap the message-
            # plane and/or disk-plane fault specs.  A key present with a
            # None value clears that plane; an absent key leaves it alone.
            p = msg.payload
            seed = int(p.get("seed", 0))
            if "net" in p:
                self.transport.set_faults(p.get("net"), seed=seed)
            if "wal" in p:
                self.wal.set_faults(p.get("wal"), seed=seed)
            if "skew" in p:
                # clock-skew: stretch this replica's tick interval by the
                # given factor (None / 1.0 heals).  The device-plane
                # analog is the duty-cycled alive mask compiled by
                # FaultPlan (netmodel.ControlInputs.skew_alive).
                f = p.get("skew")
                self._tick_scale = float(f) if f else 1.0
            if "snap_crash" in p:
                # arm (or clear) the snapshot crash point: the next n
                # take_snapshot calls die between the snapshot write and
                # the WAL truncate (host/nemesis.py take_snapshot events
                # with the crash arg)
                self._snap_crash = int(p.get("snap_crash") or 0)
            def _is_heal(k: str) -> bool:
                v = p.get(k)
                if k == "skew":
                    return v is None or v == 1.0
                # net/wal heal with None, snap_crash with 0/None —
                # NOT `v in (None, 1.0)`: snap_crash=1 would compare
                # equal to the skew-healthy 1.0 and stamp the arming
                # of a crash point as a heal event
                return not v

            self.flight.record(
                "fault_ctl", tick=self.tick,
                planes=",".join(sorted(
                    k for k in ("net", "wal", "skew", "snap_crash")
                    if k in p
                )),
                heal=all(
                    _is_heal(k)
                    for k in ("net", "wal", "skew", "snap_crash")
                    if k in p
                ),
            )
            self.ctrl.send_ctrl(CtrlMsg("fault_reply"))
        elif msg.kind == "autopilot_ctl":
            # autopilot actuation fan-out (host/autopilot.py driver in
            # act mode).  Three acts: "demote" re-places leadership
            # through the health plane's own machinery; "retune" turns
            # the live serving knobs (api_max_batch, pipeline);
            # "announce" exports the policy state through the gauges.
            # Always ack with what actually applied — the driver logs
            # refusals rather than retrying blindly.
            p = msg.payload or {}
            act = str(p.get("act", ""))
            applied: Dict[str, Any] = {"act": act, "ok": True}
            if act == "demote":
                applied["ok"] = self._autopilot_demote(
                    str(p.get("reason", "autopilot"))
                )
            elif act == "retune":
                if "api_max_batch" in p:
                    nb = max(1, int(p["api_max_batch"]))
                    self.api_max_batch = nb
                    self.external.max_batch_size = nb
                    applied["api_max_batch"] = nb
                if "pipeline" in p:
                    want = bool(p["pipeline"])
                    if want != self.pipeline:
                        # settle the in-flight device step before the
                        # loop switches tick bodies (same barrier the
                        # graceful paths use); safe here because
                        # _handle_ctrl runs on the loop thread
                        self._pipeline_flush()
                        self.pipeline = want
                    applied["pipeline"] = want
                self.metrics.counter_add(
                    "autopilot_actions", 1,
                    actuator="pipeline" if "pipeline" in p else "batch",
                )
                self.flight.record(
                    "autopilot_act", act="retune", tick=self.tick,
                    **{k: p[k] for k in ("api_max_batch", "pipeline")
                       if k in p},
                )
            elif act == "announce":
                self.metrics.gauge_set(
                    "autopilot_mode",
                    1.0 if p.get("mode") == "act" else 0.0,
                )
                for a, cd in (p.get("cooldowns") or {}).items():
                    self.metrics.gauge_set(
                        "autopilot_cooldown", float(cd),
                        actuator=str(a),
                    )
            else:
                applied["ok"] = False
            self.ctrl.send_ctrl(CtrlMsg("autopilot_reply", applied))
        elif msg.kind == "metrics_dump":
            # ctrl-plane scrape: one deterministic snapshot combining the
            # device metric lanes, the host registry, and sampled traces
            self.ctrl.send_ctrl(CtrlMsg(
                "metrics_reply", {"snapshot": self.metrics_snapshot()}
            ))
        elif msg.kind == "flight_dump":
            # graftscope scrape: this replica's flight-recorder ring
            # (modeled on metrics_dump; trace_export merges the fan-out)
            self.ctrl.send_ctrl(CtrlMsg("flight_reply", {
                "flight": self.flight_snapshot(
                    last_n=(msg.payload or {}).get("last_n")
                ),
            }))
        elif msg.kind == "take_snapshot":
            self._take_snapshot()
            self.ctrl.send_ctrl(CtrlMsg("snapshot_reply"))
            self.ctrl.send_ctrl(CtrlMsg(
                "snapshot_up_to", {"new_start": list(self.applied)}
            ))
        elif msg.kind == "leave":
            return False
        return None

    def metrics_snapshot(self) -> dict:
        """The ``metrics_dump`` scrape payload: device metric lanes (this
        replica's [G, K] block decoded per lane), the host registry
        (counters/gauges/histograms incl. fsync latency, request latency,
        loop stages, ticks-to-commit), and the last sampled slot traces.
        Everything is plain ints/lists — JSON-able, so bench/soak scripts
        attach it verbatim to their committed artifacts."""
        # payload-plane egress gauges are maintained as plain lists on
        # the hot path; fold them in at scrape time
        for dst in range(self.population):
            if dst == self.me:
                continue
            self.metrics.gauge_set("pp_bytes", self.pp_bytes[dst], peer=dst)
            self.metrics.gauge_set("pp_items", self.pp_items[dst], peer=dst)
            self.metrics.gauge_set("cw_bytes", self.cw_bytes[dst], peer=dst)
        # per-key-range heat at the api seam: top-K as labeled gauges
        # (the ResharderPolicy's input when driving a fused cluster)
        # plus the bare total
        self.metrics.gauge_set(
            "range_heat", float(self._range_heat.total())
        )
        for k, n in self._range_heat.top(8):
            self.metrics.gauge_set("range_heat", float(n), key=k)
        # graftscope ring accounting: mirror per-type drop counts into
        # trace_dropped_total{type=...} (scrape-time, never the record
        # hot path)
        self.flight.publish_drops(self.metrics)
        return {
            "me": self.me,
            "protocol": self.protocol,
            "tick": self.tick,
            "wire_codec": self.wire_codec,
            "pipeline": self.pipeline,
            "api_max_batch": self.api_max_batch,
            "applied": list(self.applied),
            "device": dev_telemetry.snapshot_row(
                self._np_state(dev_telemetry.TELEM_KEY), self.me
            ),
            "host": self.metrics.snapshot(),
            "traces": self.traces.sampled(),
        }

    def flight_snapshot(self, last_n: Optional[int] = None) -> dict:
        """The ``flight_dump`` scrape payload: the recorder ring (typed
        events, drop accounting) plus the identity/progress header and
        the device metric-lane totals — the anchor that lets the
        exporter line the device track up against the host tracks on
        one timeline."""
        out = self.flight.dump(last_n=last_n)
        out.update({
            "protocol": self.protocol,
            "tick": self.tick,
            "applied": list(self.applied),
            "device_lanes": dev_telemetry.snapshot_row(
                self._np_state(dev_telemetry.TELEM_KEY), self.me
            )["lanes"],
        })
        return out

    def debug_state(self) -> dict:
        """One-line snapshot for wedge diagnosis (VERDICT r2 #1)."""
        st = self.state
        me = self.me  # reads go through the drained-state host views
        out = {
            "me": me,
            "tick": self.tick,
            "applied": list(self.applied),
            "kv_need": sorted(self.kv_need),
            "missing": sorted(self.missing),
            "paused": self.paused,
            "peers": self.transport.peers(),
            "was_leader": self.was_leader,
            "wal_size": self.wal.size,
            "pp_bytes": list(self.pp_bytes),
            "pp_items": list(self.pp_items),
            "cw_bytes": list(self.cw_bytes),
            "net_bytes": dict(self.transport.bytes_sent),
        }
        if self.codewords is not None:
            out["cw_vids"] = [
                self.codewords.size(g) for g in range(self.G)
            ]
        for k in (
            "leader", "commit_bar", "exec_bar", "vote_bar", "bal_max",
            "bal_prepared", "next_slot", "dur_bar",
            "term", "voted_for", "conf_cur",
        ):
            if k in st:
                out[k] = self._np_state(k)[:, me].tolist()
        return out

    def shutdown(self) -> None:
        # idempotent: reachable from both the crash-restart loop and an
        # external harness stop (StorageHub.stop guards the native WAL
        # double-close; the rest tolerate repeats)
        if getattr(self, "_shutdown_done", False):
            return
        self._shutdown_done = True
        self.external.stop()
        self.transport.close()
        self.statemach.stop()
        self.wal.stop()
        self.ctrl.close()
