"""ServerReplica: a real networked replica process around the device kernel.

Parity: reference ``GenericReplica`` + ``summerset_server`` (SURVEY.md
§2.2/§2.6) — ``new_and_setup`` composes ControlHub -> StateMachine ->
StorageHub -> TransportHub -> ExternalApi, joins via the manager, then
``run()`` drives the event loop; returning True means crash-restart
(``summerset_server/src/main.rs:127-160``).

TPU-native split: this process owns replica index ``me`` of every group.
Each tick it (1) drains the client batch, (2) steps the vectorized kernel
with the inbox assembled from peers' TCP frames, (3) sends its outbox
slice + payload piggybacks, (4) WAL-logs newly committed slots, applies
them to the KV store, and replies to clients it originated.  Consensus
messages ride the device outbox; request payloads ride host frames keyed
by value id (the device log stores int32 references only — SURVEY.md §7
hard part (b)).

Leadership, failover, leases, and commit tallies all happen inside the
kernel; this loop only reflects ``is_leader`` edges to the manager and
redirects clients when not serving.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from ..protocols import make_protocol
from ..utils.logging import pf_info, pf_logger, pf_warn
from .control import ControlHub
from .external import ExternalApi
from .messages import ApiReply, ApiRequest, CtrlMsg
from .payload import PayloadStore
from .statemach import StateMachine, apply_command
from .storage import LogAction, StorageHub
from .transport import TransportHub

logger = pf_logger("server")


class ServerReplica:
    def __init__(
        self,
        protocol: str,
        api_addr: Tuple[str, int],
        p2p_addr: Tuple[str, int],
        manager_addr: Tuple[str, int],
        config: Optional[dict] = None,
        num_groups: int = 1,
        window: int = 64,
        tick_interval: float = 0.002,
        backer_dir: str = "/tmp/summerset_tpu",
    ):
        cfg = dict(config or {})
        self.protocol = protocol
        self.api_addr = api_addr
        self.p2p_addr = p2p_addr
        self.tick_interval = tick_interval
        self.G = num_groups
        self.window = window

        # control plane first: the manager assigns our id (control.rs:43)
        self.ctrl = ControlHub(manager_addr)
        self.me = self.ctrl.me
        self.population = self.ctrl.population

        # protocol kernel over [G, R]; host applier drives the exec bar.
        # Supported here: the MultiPaxos-family kernels sharing the
        # (n_proposals, value_base, exec_floor) input contract.
        kercfg_cls = type(
            make_protocol(protocol, 1, self.population, 64).config
        )
        known = {f.name for f in dataclasses.fields(kercfg_cls)}
        kcfg = kercfg_cls(**{k: v for k, v in cfg.items() if k in known})
        if hasattr(kcfg, "exec_follows_commit"):
            kcfg.exec_follows_commit = False
        if hasattr(kcfg, "max_proposals_per_tick"):
            kcfg.max_proposals_per_tick = 1  # one ReqBatch per tick
        self.kernel = make_protocol(
            protocol, self.G, self.population, window, kcfg
        )
        self.state = self.kernel.init_state(seed=0)
        self._step = jax.jit(self.kernel.step)

        os.makedirs(backer_dir, exist_ok=True)
        self.wal_path = os.path.join(backer_dir, f"r{self.me}.wal")
        self.wal = StorageHub(self.wal_path)
        self.snapdir = os.path.join(backer_dir, f"r{self.me}.snap")
        self.statemach = StateMachine()
        self.payloads = PayloadStore(self.G)
        self.applied = [0] * self.G        # exec floor per group (own row)
        self.origin: set = set()           # vids proposed by this server
        self.missing: set = set()           # committed vids lacking payloads
        self.kv_need = False
        self.paused = False
        self.stopping = False  # cooperative stop for embedded harnesses
        self.was_leader = False
        self.tick = 0
        self._pending_serve: Dict[int, Any] = {}  # peers' payload requests
        self._pending_kv_serve = False

        self._recover_from_wal()

        # p2p mesh join (multipaxos/mod.rs:717-737): proactively connect to
        # lower-id peers, accept from higher ids.  The join is re-sent until
        # the mesh completes — concurrent bring-up means a lower-id peer may
        # join after us, so one connect_to_peers snapshot is not enough.
        self.transport = TransportHub(self.me, self.population, p2p_addr)
        join = CtrlMsg("new_server_join", {
            "protocol": protocol,
            "api_addr": api_addr,
            "p2p_addr": p2p_addr,
        })
        connected: set = set()
        deadline = time.monotonic() + 60
        while True:
            self.ctrl.send_ctrl(join)
            try:
                msg = self.ctrl.recv_ctrl(timeout=3)
            except Exception:
                msg = None
            if msg is not None and msg.kind == "connect_to_peers":
                for peer, addr in msg.payload["to_peers"].items():
                    if int(peer) not in connected:
                        self.transport.connect_to_peer(int(peer), addr)
                        connected.add(int(peer))
            try:
                self.transport.wait_for_group(timeout=2)
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise

        self.external = ExternalApi(api_addr)
        pf_info(logger, f"replica {self.me} ready")

    # -------------------------------------------------------- WAL recovery
    def _recover_from_wal(self) -> None:
        """Replay committed records: payloads + KV + exec floors
        (parity: recovery.rs replay loop, SURVEY.md §3.4)."""
        off = 0
        n = 0
        while True:
            res = self.wal.do_sync_action(LogAction("read", offset=off))
            if not res.offset_ok or res.entry is None:
                break
            g, slot, vid, batch = res.entry
            self.payloads._data[g][vid] = batch
            self.payloads._next[g] = max(self.payloads._next[g], vid + 1)
            if batch is not None:
                for client, req in batch:
                    if req.cmd is not None:
                        apply_command(self.statemach._kv, req.cmd)
            self.applied[g] = max(self.applied[g], slot + 1)
            off = res.end_offset
            n += 1
        if n:
            pf_info(logger, f"recovered {n} WAL records")

    # ----------------------------------------------------------- tick I/O
    def _slice_outbox(self, out) -> Dict[int, Dict[str, Any]]:
        """Per-peer frame: per-pair fields sliced [G] at (me, dst),
        broadcast lanes sent whole."""
        lanes = self.kernel.broadcast_lanes
        frames: Dict[int, Dict[str, Any]] = {}
        np_out = {k: np.asarray(v) for k, v in out.items()}
        for dst in range(self.population):
            if dst == self.me:
                continue
            f = {}
            for k, v in np_out.items():
                f[k] = v[:, self.me] if k in lanes else v[:, self.me, dst]
            frames[dst] = f
        return frames

    def _assemble_inbox(self, own_out, peer_frames) -> Dict[str, Any]:
        """Receiver-oriented inbox: row `me` filled from peers + self."""
        lanes = self.kernel.broadcast_lanes
        zero = self.kernel.zero_outbox()
        inbox = {}
        for k, z in zero.items():
            arr = np.zeros_like(np.asarray(z))
            if k in lanes:
                arr[:, self.me] = np.asarray(own_out[k])[:, self.me]
                for src, f in peer_frames.items():
                    if f is not None:
                        arr[:, src] = f["msg"][k]
            else:
                # transposed orientation: [G, dst(me), src]
                arr[:, self.me, self.me] = np.asarray(own_out[k])[
                    :, self.me, self.me
                ]
                for src, f in peer_frames.items():
                    if f is not None:
                        arr[:, self.me, src] = f["msg"][k]
            inbox[k] = jnp.asarray(arr)
        return inbox

    # --------------------------------------------------------- main loop
    def run(self) -> bool:
        """Event loop; returns True to request a crash-restart."""
        last_out = {
            k: jnp.asarray(v) for k, v in self.kernel.zero_outbox().items()
        }
        while True:
            if self.stopping:
                return False
            t0 = time.monotonic()
            restart = self._handle_ctrl()
            if restart is not None:
                return restart
            if self.paused:
                time.sleep(self.tick_interval)
                continue

            # 1. client intake -> payload ids (one ReqBatch per group/tick);
            # non-leaders redirect with the hinted leader id
            # (request.rs:128-154)
            batch = self.external.get_req_batch(timeout=0)
            n_prop = np.zeros((self.G,), np.int32)
            vbase = np.zeros((self.G,), np.int32)
            piggy: Dict[int, Any] = {}
            if batch:
                reqs = [(c, r) for c, r in batch if r.kind == "req"]
                if reqs and not self.was_leader:
                    hint = int(np.asarray(self.state["leader"])[0, self.me]
                               ) if "leader" in self.state else -1
                    for c, r in reqs:
                        self.external.send_reply(
                            ApiReply("redirect", req_id=r.req_id,
                                     redirect=hint, success=False),
                            c,
                        )
                    reqs = []
                if reqs:
                    g = 0  # client plane addresses group 0
                    vid = self.payloads.put(g, reqs)
                    self.origin.add(vid)
                    n_prop[g] = 1
                    vbase[g] = vid
                    piggy[vid] = reqs

            # 2. exchange tick frames and step the kernel
            frames = self._slice_outbox(last_out)
            deadline = t0 + self.tick_interval
            piggy.update(self._pending_serve)
            self._pending_serve = {}
            payload_msg: Dict[str, Any] = {
                "pp": piggy,
                "need": sorted(self.missing)[:64],
                "kv_need": self.kv_need,
            }
            if self._pending_kv_serve:
                payload_msg["kv"] = self.statemach.snapshot_items()
                payload_msg["kv_floor"] = self.applied[0]
                self._pending_kv_serve = False
            self.transport.send_tick(
                self.tick,
                {dst: {"msg": frames[dst], **payload_msg}
                 for dst in frames},
            )
            got = self.transport.recv_tick(self.tick, deadline)
            self._ingest_payloads(got)
            inbox = self._assemble_inbox(last_out, got)
            inputs = {
                "n_proposals": jnp.asarray(n_prop),
                "value_base": jnp.asarray(vbase),
                "exec_floor": jnp.asarray(
                    np.broadcast_to(
                        np.asarray(self.applied, np.int32)[:, None],
                        (self.G, self.population),
                    )
                ),
            }
            self.state, last_out, fx = self._step(
                self.state, inbox, inputs
            )

            # 3. apply newly committed slots; reflect leadership
            self._apply_committed(fx)
            self._leader_edges(fx)
            self.tick += 1

            rem = deadline - time.monotonic()
            if rem > 0:
                time.sleep(rem)

    # -------------------------------------------------- payload exchange
    def _ingest_payloads(self, got) -> None:
        for src, f in got.items():
            if f is None:
                continue
            for vid, batch in f.get("pp", {}).items():
                if self.payloads.get(0, vid) is None:
                    self.payloads._data[0][vid] = batch
                self.missing.discard(vid)
            # serve peers' missing payloads / kv requests next tick by
            # folding them into our own piggyback
            for vid in f.get("need", []):
                b = self.payloads.get(0, vid)
                if b is not None:
                    self._pending_serve[vid] = b
            if f.get("kv_need") and not self.kv_need:
                self._pending_kv_serve = True
            if "kv" in f and self.kv_need:
                self.statemach._kv.update(f["kv"])
                self.applied[0] = max(self.applied[0], f["kv_floor"])
                self.kv_need = False

    # ------------------------------------------------------- application
    def _apply_committed(self, fx) -> None:
        cb = int(np.asarray(fx.commit_bar)[0, self.me])
        g = 0
        if cb <= self.applied[g]:
            return
        win_abs = np.asarray(self.state["win_abs"])[g, self.me]
        win_val = np.asarray(self.state["win_val"])[g, self.me]
        W = self.kernel.W
        while self.applied[g] < cb:
            slot = self.applied[g]
            pos = np.where(win_abs == slot)[0]
            if len(pos) == 0:
                # below the window: an install-snapshot jumped us forward;
                # fetch the KV state from peers host-side
                self.kv_need = True
                self.applied[g] = cb
                return
            vid = int(win_val[pos[0]])
            batch = self.payloads.get(g, vid)
            if vid != 0 and batch is None:
                self.missing.add(vid)
                return  # stall the exec floor until the payload arrives
            # durability before client-visible effects (storage.rs intent)
            self.wal.do_sync_action(LogAction(
                "append", entry=(g, slot, vid, batch), sync=False
            ))
            if batch is not None:
                mine = vid in self.origin
                for client, req in batch:
                    res = apply_command(self.statemach._kv, req.cmd)
                    if mine:
                        self.external.send_reply(
                            ApiReply("reply", req_id=req.req_id,
                                     result=res),
                            client,
                        )
            self.applied[g] = slot + 1

    def _leader_edges(self, fx) -> None:
        is_l = bool(np.asarray(
            fx.extra.get("is_leader", np.zeros((self.G, self.population)))
        )[0, self.me])
        if is_l != self.was_leader:
            self.ctrl.send_ctrl(
                CtrlMsg("leader_status", {"step_up": is_l})
            )
            self.was_leader = is_l

    # ----------------------------------------------------------- control
    def _handle_ctrl(self) -> Optional[bool]:
        msg = self.ctrl.try_recv_ctrl()
        if msg is None:
            return None
        if msg.kind == "pause":
            self.paused = True
            self.ctrl.send_ctrl(CtrlMsg("pause_reply"))
        elif msg.kind == "resume":
            self.paused = False
            self.ctrl.send_ctrl(CtrlMsg("resume_reply"))
        elif msg.kind == "reset_state":
            if not msg.payload.get("durable", True):
                self.wal.stop()
                try:
                    os.remove(self.wal_path)
                except OSError:
                    pass
            self.ctrl.send_ctrl(CtrlMsg("reset_reply"))
            return True
        elif msg.kind == "take_snapshot":
            kv = self.statemach.snapshot_items()
            snap = StorageHub(self.snapdir)
            snap.do_sync_action(LogAction(
                "append", entry=("kv", kv, self.applied[0]), sync=True
            ))
            snap.stop()
            self.ctrl.send_ctrl(CtrlMsg("snapshot_reply"))
            self.ctrl.send_ctrl(CtrlMsg(
                "snapshot_up_to", {"new_start": self.applied[0]}
            ))
        elif msg.kind == "leave":
            return False
        return None

    def shutdown(self) -> None:
        self.external.stop()
        self.transport.close()
        self.statemach.stop()
        self.wal.stop()
        self.ctrl.close()
