"""Compartmentalized serving plane: stateless ingress proxies + a
learner read tier in front of the replica shards.

Motivation (PAPERS.md "Scaling Replicated State Machines with
Compartmentalization", "HT-Paxos"): the fused ``ServerReplica`` process
pins the whole group's ingress at one ``ExternalApi``'s ``api_max_batch``
drain rate — one process owns accept, dedupe, batching, shedding, AND
consensus.  This module decouples those roles into independently
scalable stateless tiers, the frontend/router-vs-model-shard split of an
inference serving stack:

- :class:`IngressProxy` — N stateless processes, each owning its OWN
  ``ExternalApi`` instance (same listener/servant/bounded-queue/shed
  machinery, under the ``proxy_*`` metric namespace).  A proxy accepts
  client connections, **dedupes** by ``(client, req_id)`` (a bounded
  replay cache answers retried-already-replied requests locally),
  **batches** accepted ops, and **routes** them to per-group owner
  shards through a :class:`RoutingTable` built on
  ``utils/keyrange.KeyRangeMap``.  The forwarded unit is ONE
  ``ApiRequest("batch")`` per owner per cycle — one slot in the shard's
  bounded ingress queue regardless of how many client ops it aggregates,
  which is exactly the fan-in amortization that moves the shed point off
  the shard and onto the proxy tier (visible as ``api_shed`` staying
  flat while ``proxy_shed`` absorbs the overload).

- the **learner read tier** (:class:`LearnerReadTier`, one per proxy) —
  subscribes to a non-proposer replica's commit feed
  (``ApiRequest("sub")`` -> snapshot + ordered ``"note"`` streams of
  applied puts) and serves gets from its learned state, gated by a
  per-read freshness **probe**: the upstream replica answers — on its
  own tick thread, exactly where the fused lease-read decision is made —
  whether a lease-local read of that key's group is allowed right now,
  plus the feed seq its applied state corresponds to.  Because probe
  replies and notes ride ONE writer FIFO, a probe reply's arrival
  implies every note up to its seq has been learned, so "serve iff
  ``lease_ok`` and ``learned_seq >= probe_seq``" inherits the identical
  lease-safety argument as the replica's own ``_can_local_read`` path —
  and the value bytes never touch the proposer.

- :class:`ServingPlane` — the assembly: brings up N proxies (plus read
  tiers) in front of a live cluster and exposes per-tier scrape /
  flight / crash-restart handles for benches, soaks, and the
  ``proxy_crash`` nemesis class.  **Fused single-process mode remains
  the default everywhere**: with zero proxies constructed, no wire
  message changes shape, no client behavior changes, and every existing
  test/bench/soak digest is untouched (clients only enter proxy mode
  when the manager actually lists registered proxies).

Failure semantics: a proxy registers with the manager over its ctrl
connection (``CtrlRequest("proxy_join")``) and is deregistered the
moment that connection drops — client rediscovery after a proxy crash is
one ``query_info`` away (the ``rotate``/backoff machinery clients
already have).  A proxy NEVER retries an op after it was sent upstream
unless the shard explicitly refused it without proposing (redirect /
shed): re-sending a possibly-proposed put would double-execute it, which
the workload soak's linearizability checker would correctly flag.  Ops
stranded by an upstream or proxy death surface as client timeouts and
are recorded unacked — the same contract as a fused-server crash.
"""

from __future__ import annotations

import bisect
import collections
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ..utils import safetcp, wirecodec
from ..utils.errors import SummersetError
from ..utils.keyrange import KeyRangeMap
from ..utils.logging import pf_info, pf_logger, pf_warn
from .external import ExternalApi
from .messages import ApiReply, ApiRequest, CtrlRequest
from .resharding import RangeHeat
from .statemach import CommandResult
from .telemetry import MetricsRegistry, PROXY_DECLARED
from .tracing import FlightRecorder

logger = pf_logger("ingress")

#: learner connections offset their wire identity by this so a proxy's
#: forward and learner connections to the SAME shard never collide in
#: the shard ExternalApi's per-client writer table (manager-assigned
#: cids start at 1000 and increment; collision would need 500k clients)
LEARNER_ID_OFFSET = 500_000


def scrape_proxy(addr: Tuple[str, int], timeout: float = 5.0
                 ) -> Optional[dict]:
    """One-shot per-tier scrape of a live ingress proxy over its data
    plane (``ApiRequest("stats")``): returns the proxy's
    ``metrics_snapshot()`` dict, or None when unreachable — best-effort
    like ``scrape_metrics``, so bench artifact writers never die on
    their own diagnostics."""
    try:
        sock = socket.create_connection(tuple(addr), timeout=timeout)
        try:
            sock.settimeout(timeout)
            safetcp.send_msg_sync(sock, SCRAPE_CLIENT_ID)
            safetcp.send_msg_sync(sock, ApiRequest("stats", req_id=1))
            while True:
                rep = safetcp.recv_msg_sync(sock)
                if getattr(rep, "kind", None) == "stats":
                    return rep.notes
        finally:
            sock.close()
    # graftlint: disable=H106 -- best-effort diagnostics by contract: the
    # None return IS the signal (docstring above), and bench artifact
    # writers must never die on their own scrape
    except Exception:
        return None


#: wire identity scrape connections present (outside the manager cid,
#: learner, and fleet id bands)
SCRAPE_CLIENT_ID = 900_000


class RoutingTable:
    """Proxy-side routing state: a ``KeyRangeMap`` from key ranges to
    owner shard ids, the server address book, and the lease-responder
    conf (for read-tier upstream selection).

    Updates swap immutable maps (build-then-assign), so readers on the
    forward/pump threads never see a half-built table and no lock is
    held on the routing hot path.  The default map is one full range
    owned by the cluster leader — deployments with per-range ownership
    (the manager's ``RespondersConf`` generalizes to key ranges) install
    finer ranges through :meth:`set_owner` and the lookup cost stays one
    bisect either way.
    """

    def __init__(self) -> None:
        self.version = 0
        self.servers: Dict[int, Tuple[str, int]] = {}
        self.leader: Optional[int] = None
        self.responders: List[int] = []
        self._owners: KeyRangeMap = KeyRangeMap()
        self._overrides: List[Tuple[str, Optional[str], int]] = []
        # manager-announced installed ranges (live resharding): replaced
        # wholesale on refresh, applied below manual overrides
        self._ranges: List[Tuple[str, Optional[str], int]] = []
        self._hint_fresh_until = 0.0

    # -- update side (refresher thread + redirect hints) ------------------
    def update(self, servers: Dict[int, Tuple[str, int]],
               leader: Optional[int],
               responders: Optional[List[int]] = None) -> None:
        self.servers = dict(servers)
        # a data-plane redirect hint is FRESHER than the manager's view
        # (which can lag a whole election): a recent note_leader wins
        # over a conflicting manager refresh for a short grace window,
        # or post-crash forwards would flap back to the dead leader
        # every refresh until the manager catches up
        hint_fresh = (
            time.monotonic() < self._hint_fresh_until
            and self.leader in self.servers
        )
        if (leader is not None or self.leader not in self.servers) \
                and not (hint_fresh and leader != self.leader):
            self.leader = leader
        if responders is not None:
            self.responders = [int(r) for r in responders]
        self._rebuild()

    def note_leader(self, sid: Optional[int]) -> None:
        """Fold a data-plane redirect hint into the owner map (the
        freshest leadership signal available — the manager's view can
        lag a whole election)."""
        if sid is not None and sid >= 0:
            self._hint_fresh_until = time.monotonic() + 2.0
            if sid != self.leader:
                self.leader = int(sid)
                self._rebuild()

    def set_owner(self, start: str, end: Optional[str], sid: int) -> None:
        """Install a per-key-range owner override (kept across leader
        updates; later inserts overwrite overlapped spans — rangemap
        semantics).  Re-setting the same span replaces its entry instead
        of growing the override list without bound."""
        self._overrides = [
            o for o in self._overrides if (o[0], o[1]) != (start, end)
        ] + [(start, end, int(sid))]
        self._rebuild()

    def set_ranges(
        self, triples: List[Tuple[str, Optional[str], int]],
    ) -> None:
        """Replace the manager-announced installed-range set (live
        resharding, host/resharding.py) wholesale.  No-op when unchanged
        so the 0.5s refresh loop doesn't churn the routing version."""
        triples = [(s, e, int(sid)) for s, e, sid in triples]
        if triples == self._ranges:
            return
        self._ranges = triples
        self._rebuild()

    def _rebuild(self) -> None:
        m: KeyRangeMap = KeyRangeMap()
        default = self.leader
        if default is None or default not in self.servers:
            default = min(self.servers) if self.servers else None
        if default is not None:
            m.full_range(default)
        # overrides whose owner is gone from the address book fall back
        # to the default instead of wedging their range: _flush can
        # never resolve an upstream for a dead sid, and the leftover
        # would park every op in the range until the backlog shed
        for start, end, sid in self._ranges:
            if sid in self.servers:
                m.insert(start, end, sid)
        for start, end, sid in self._overrides:  # manual overrides win
            if sid in self.servers:
                m.insert(start, end, sid)
        self._owners = m  # atomic ref swap
        self.version += 1

    # -- lookup side (forward loop / pump threads) ------------------------
    def owner_for(self, key: str) -> Optional[int]:
        return self._owners.get(key)

    def write_target(self) -> Optional[int]:
        """Conf/default destination: the leader, else any known shard."""
        if self.leader is not None and self.leader in self.servers:
            return self.leader
        return min(self.servers) if self.servers else None

    def reader_sid(self) -> Optional[int]:
        """The read tier's upstream: a lease responder off the proposer
        path, else any non-leader replica (probes on a non-responder
        simply refuse, steering the read back to the owner) — never the
        leader, whose load is exactly what the tier exists to shed."""
        for r in self.responders:
            if r in self.servers and r != self.leader:
                return r
        rest = sorted(s for s in self.servers if s != self.leader)
        return rest[0] if rest else None


class _Upstream:
    """One forward connection proxy -> shard: a raw safetcp socket plus
    its reply pump thread.  All SENDS happen on the proxy's forward
    loop (single-writer — no per-socket lock needed, by construction);
    the pump only receives."""

    __slots__ = ("sid", "sock", "alive", "inflight", "pump")

    def __init__(self, sid: int, sock: socket.socket):
        self.sid = sid
        self.sock = sock
        self.alive = True
        self.inflight: set = set()  # outstanding batch ids (proxy lock)
        self.pump: Optional[threading.Thread] = None


class LearnerReadTier:
    """The learner half of the read tier, embedded one-per-proxy: a
    commit-feed subscription to a non-proposer replica plus the learned
    KV it maintains, serving probe-gated lease-local gets.

    Thread shape: this class's own thread owns (re)connecting, the
    subscription handshake, and the receive loop (notes + probe
    replies); probe SENDS come from the proxy's forward loop after the
    socket is published — the two never send concurrently because the
    socket is only published after the handshake writes finish, and is
    retired (``ready = False``) before any reconnect."""

    def __init__(self, proxy: "IngressProxy"):
        self.proxy = proxy
        self.kv: Dict[str, Any] = {}
        # ordered index over the learned keys (bisect-maintained,
        # learner thread only): the sorted view scans slice — one
        # insort per NEW key amortizes far below re-sorting per scan
        self._keys: List[str] = []
        self.seq = 0
        self.ready = False
        self.upstream: Optional[int] = None
        self._sock: Optional[socket.socket] = None
        self._probes: Dict[int, float] = {}  # prid -> deadline (proxy lock)
        self._live_sock: Optional[socket.socket] = None
        # probe refusal backoff: a protocol without held leases (or a
        # revoked responder) refuses EVERY probe — without this gate the
        # read tier would burn one shard queue slot per get just to be
        # told no, stealing ingress capacity from the write path under
        # exactly the overload the tier exists to absorb
        self._refuse_until = 0.0
        self.refusal_backoff_s = 0.5
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ingress-learner"
        )
        self._thread.start()

    # -- forward-loop side ---------------------------------------------------
    def try_probe(self, prid: int, cmd) -> bool:
        """Route a get through the read tier: send a freshness probe on
        the learner connection.  Returns False (caller falls back to
        owner forwarding) when the tier is not ready."""
        if not self.ready:
            return False
        if time.monotonic() < self._refuse_until:
            return False  # recently refused: owner path serves reads
        sock = self._sock
        if sock is None:
            return False
        # book-keep BEFORE the send (same discipline as _send_batch): a
        # reply racing a post-send registration would find no probe
        # entry and silently drop the get
        with self.proxy._lock:
            self._probes[prid] = time.monotonic() + 2.0
            depth = len(self._probes)
        try:
            safetcp.send_msg_sync(
                sock, ApiRequest("probe", req_id=prid, cmd=cmd),
                codec=self.proxy.codec,
            )
        # graftlint: disable=H106 -- the False return IS the recorded
        # signal: ready drops, the probe entry is unwound, and the caller
        # routes the get through the owner path instead
        except Exception:
            self.ready = False
            with self.proxy._lock:
                self._probes.pop(prid, None)
            return False
        self.proxy.metrics.gauge_set("read_tier_backlog", depth)
        return True

    def _gauge_backlog(self) -> None:
        """Refresh the backlog gauge after probes SHRINK too — a gauge
        only written on insertion would stick at the burst high-water
        mark forever in the committed scrapes."""
        self.proxy.metrics.gauge_set(
            "read_tier_backlog", len(self._probes)
        )

    def expire_probes(self, now: float) -> None:
        """Drop probes that never answered (upstream wedged): the pend
        is dropped too — the client's own timeout/retry machinery owns
        recovery, and a late probe reply finds nothing to serve."""
        with self.proxy._lock:
            dead = [p for p, dl in self._probes.items() if now > dl]
            for p in dead:
                del self._probes[p]
        for p in dead:
            self.proxy._drop_pend(p)
        if dead:
            self._gauge_backlog()

    # -- learner-thread side -------------------------------------------------
    def _fail_outstanding(self) -> None:
        """Subscription died: fall every in-flight probe back to the
        owner-forward path (a probe is read-only — re-routing it can
        never double-execute anything)."""
        with self.proxy._lock:
            pend = list(self._probes)
            self._probes.clear()
        for prid in pend:
            self.proxy._requeue.append(prid)
        self._gauge_backlog()

    def _on_probe_reply(self, rep: ApiReply) -> None:
        with self.proxy._lock:
            dl = self._probes.pop(rep.req_id, None)
        self._gauge_backlog()
        if dl is None:
            return  # expired / failed over already
        if rep.kind == "probe" and rep.success and self.seq >= rep.seq:
            pend = self.proxy._pop_pend(rep.req_id)
            if pend is None:
                return
            cmd = pend["cmd"]
            if cmd.kind == "scan":
                # ordered range read off the learned state: the probe
                # verdict covered the WHOLE span (sealed-cutover overlap
                # + all-groups lease freshness), so the sorted-index
                # slice at learned_seq >= probe_seq is a linearizable
                # cut that never touched the proposer
                res = CommandResult(
                    "scan", items=self.scan_learned(
                        cmd.key, cmd.end, cmd.limit,
                    ),
                )
                self.proxy.metrics.counter_add("read_tier_scans")
                self.proxy.flight.record(
                    "scan_serve", client=pend["client"],
                    req_id=pend["req_id"], seq=self.seq,
                )
            else:
                res = CommandResult("get", value=self.kv.get(cmd.key))
                self.proxy.flight.record(
                    "read_serve", client=pend["client"],
                    req_id=pend["req_id"], seq=self.seq,
                )
            self.proxy.metrics.counter_add("read_tier_served")
            self.proxy._reply_client(pend, ApiReply(
                "reply", req_id=pend["req_id"],
                result=res, local=True,
            ))
        else:
            # no lease / not quiescent / shed: the owner-forward path
            # serves it (the same fallback the fused server takes), and
            # probing pauses briefly so a lease-less upstream is not
            # re-asked once per get
            self._refuse_until = (
                time.monotonic() + self.refusal_backoff_s
            )
            self.proxy._requeue.append(rep.req_id)

    def scan_learned(self, start: str, end: Optional[str],
                     limit: int) -> tuple:
        """Slice the ordered learned index over ``[start, end)`` —
        learner thread only (the index and kv mutate on this thread
        between receives, never under a scan)."""
        lo = bisect.bisect_left(self._keys, start)
        hi = (len(self._keys) if end is None
              else bisect.bisect_left(self._keys, end))
        keys = self._keys[lo:hi]
        if limit and limit > 0:
            keys = keys[:limit]
        return tuple((k, self.kv[k]) for k in keys)

    def _run(self) -> None:
        stop = self.proxy._stop
        while not stop.is_set():
            sid = self.proxy.routing.reader_sid()
            addr = self.proxy.routing.servers.get(sid) if sid is not None \
                else None
            if addr is None:
                stop.wait(0.3)
                continue
            sock = None
            try:
                sock = socket.create_connection(tuple(addr), timeout=2.0)
                self._live_sock = sock
                sock.settimeout(None)
                safetcp.send_msg_sync(
                    sock, self.proxy.cid + LEARNER_ID_OFFSET
                )
                safetcp.send_msg_sync(sock, ApiRequest("sub", req_id=0))
            # graftlint: disable=H106 -- connect/subscribe retry loop:
            # failure closes the half-open socket and retries after a
            # backoff; the tier simply stays not-ready until it lands
            except Exception:
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                stop.wait(0.5)
                continue
            self.upstream = sid
            try:
                while not stop.is_set():
                    rep = safetcp.recv_msg_sync(sock)
                    if not isinstance(rep, ApiReply):
                        continue
                    if rep.kind == "sub":
                        # snapshot installs BEFORE the socket is
                        # published for probes: a probe can never race a
                        # half-installed learner state
                        self.kv = dict(rep.notes or {})
                        self._keys = sorted(self.kv)
                        self.seq = int(rep.seq)
                        self._sock = sock
                        self.ready = True
                        pf_info(
                            logger,
                            f"read tier subscribed to replica {sid} "
                            f"(seq {self.seq}, {len(self.kv)} keys)",
                        )
                    elif rep.kind == "note":
                        for s, k, v in rep.notes or ():
                            if k not in self.kv:
                                bisect.insort(self._keys, k)
                            self.kv[k] = v
                        self.seq = max(self.seq, int(rep.seq))
                    else:  # probe verdicts (incl. shed/error fallbacks)
                        self._on_probe_reply(rep)
                    # bring-up can pick an upstream before the manager
                    # knows the leader; once routing learns this IS the
                    # proposer, resubscribe off it (the tier's whole
                    # point is reads that never touch the proposer)
                    better = self.proxy.routing.reader_sid()
                    if (
                        sid == self.proxy.routing.leader
                        and better is not None and better != sid
                    ):
                        break
            # graftlint: disable=H106 -- any recv/apply failure falls
            # through to the full teardown right below: ready drops, the
            # socket is unpublished, and _fail_outstanding() records the
            # failure to every waiting probe before the resubscribe
            except Exception:
                pass
            self.ready = False
            self._sock = None
            self._live_sock = None
            self.upstream = None
            self._fail_outstanding()
            try:
                sock.close()
            except OSError:
                pass
            stop.wait(0.5)

    def close(self) -> None:
        """Tear the subscription down NOW (proxy stop/crash): shutdown
        wakes the thread out of its blocked recv — a closed fd alone
        would not — so the upstream replica sees the connection drop and
        GCs this subscriber instead of buffering notes for a ghost."""
        sock = self._live_sock
        self.ready = False
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._thread.join(timeout=2)


class IngressProxy:
    """One stateless ingress proxy: accept + dedupe + batch + route.

    Bounded at every layer (the overload contract): the embedded
    ``ExternalApi``'s ``max_pending`` sheds at the front door under the
    ``proxy_*`` metric namespace; the internal forward backlog is capped
    at ``backlog_limit`` (when full, the front queue stops draining and
    fills, which is what arms the front-door shed); each upstream
    carries at most ``upstream_window`` un-acked batches of at most
    ``forward_batch`` commands — so a saturated shard backpressures the
    proxy instead of growing an unbounded queue anywhere.
    """

    def __init__(
        self,
        manager_addr: Tuple[str, int],
        api_addr: Tuple[str, int],
        *,
        max_batch: int = 4096,
        max_pending: int = 1024,
        forward_batch: int = 64,
        upstream_window: int = 4,
        backlog_limit: Optional[int] = None,
        tick_interval: float = 0.001,
        read_tier: bool = True,
        refresh_s: float = 0.5,
        dedupe_cap: int = 4096,
        retry_redirects: int = 3,
        pend_timeout: float = 15.0,
        flight_capacity: int = 4096,
        codec: Optional[bool] = None,
    ):
        from ..client.endpoint import ClientCtrlStub

        self.manager_addr = tuple(manager_addr)
        self.api_addr = (str(api_addr[0]), int(api_addr[1]))
        # wire codec for the tier's hot hops: client-facing replies (the
        # embedded ExternalApi below) AND the upstream forward batches /
        # read-tier probes.  None = process default; ingress of either
        # format dispatches per frame (utils/wirecodec.py)
        self.codec = (
            wirecodec.default_on() if codec is None else bool(codec)
        )
        self.forward_batch = max(1, int(forward_batch))
        self.upstream_window = max(1, int(upstream_window))
        self.backlog_limit = int(
            backlog_limit if backlog_limit is not None
            else 4 * self.forward_batch
        )
        self.tick_interval = float(tick_interval)
        self.refresh_s = float(refresh_s)
        self.dedupe_cap = max(16, int(dedupe_cap))
        self.retry_redirects = int(retry_redirects)
        self.pend_timeout = float(pend_timeout)

        self.metrics = MetricsRegistry()
        self.flight = FlightRecorder(capacity=flight_capacity)
        # pre-register the proxy-tier series (PROXY_DECLARED): zero must
        # read as "never happened", not "not measured" — the external
        # api contributes its namespace family below
        for name in ("proxy_requests_total", "proxy_replies_total",
                     "proxy_routed", "proxy_dedupe_hits",
                     "proxy_upstream_shed", "read_tier_served",
                     "read_tier_scans"):
            self.metrics.counter_add(name, 0)
        for name in ("proxy_backlog", "read_tier_backlog"):
            self.metrics.gauge_set(name, 0)
        # per-key-range heat lane (live resharding, host/resharding.py)
        self.metrics.gauge_set("range_heat", 0.0)
        self._range_heat = RangeHeat()

        # control plane: register with the manager; identity = ctrl cid
        # (liveness and registration share one socket — deregistration
        # IS the connection drop)
        self.ctrl = ClientCtrlStub(self.manager_addr)
        self.cid = self.ctrl.id
        self.flight.me = self.cid
        rep = self.ctrl.request(CtrlRequest(
            "proxy_join", payload={"api_addr": list(self.api_addr)},
        ))
        if not rep.done:
            raise SummersetError("manager refused proxy_join")
        self.routing = RoutingTable()
        self._stop = threading.Event()
        self._refresh_routing(timeout=10.0)

        # forward state (one proxy-wide lock; no blocking I/O inside it)
        self._lock = threading.Lock()
        self._pends: Dict[int, dict] = {}
        self._inflight: Dict[Tuple[int, int], int] = {}
        self._replied: "collections.OrderedDict" = collections.OrderedDict()
        self._batches: Dict[int, set] = {}
        self._bid_sid: Dict[int, int] = {}
        self._backlog: collections.deque = collections.deque()
        self._requeue: collections.deque = collections.deque()
        self._next_rid = 1
        self._next_gc = 0.0
        self._ups: Dict[int, _Upstream] = {}
        self._up_fail: Dict[int, float] = {}

        # the front door: the SAME ExternalApi class the fused server
        # runs, under the proxy metric namespace — accept, bounded
        # queue, shed hints, reply routing all inherited
        self.external = ExternalApi(
            self.api_addr, batch_interval=self.tick_interval,
            max_batch_size=max_batch, max_pending=max_pending,
            registry=self.metrics, flight=self.flight,
            metric_ns="proxy", codec=self.codec,
        )

        self.read_tier: Optional[LearnerReadTier] = (
            LearnerReadTier(self) if read_tier else None
        )
        self._fwd_thread = threading.Thread(
            target=self._forward_loop, daemon=True, name="ingress-fwd"
        )
        self._fwd_thread.start()
        self._refresh_thread = threading.Thread(
            target=self._refresh_loop, daemon=True, name="ingress-refresh"
        )
        self._refresh_thread.start()
        pf_info(
            logger,
            f"ingress proxy {self.cid} serving @ {self.api_addr}",
        )

    # ----------------------------------------------------------- control
    def _refresh_routing(self, timeout: float = 5.0) -> None:
        info = self.ctrl.request(CtrlRequest("query_info"),
                                 timeout=timeout)
        responders = None
        try:
            conf = self.ctrl.request(CtrlRequest("query_conf"),
                                     timeout=timeout)
            if conf.conf:
                responders = list(conf.conf.get("responders") or [])
        # graftlint: disable=H106 -- the responder conf is an optional
        # refinement: on failure responders stays None and the routing
        # update below still lands with the fresh server/leader info
        except Exception:
            pass
        self.routing.update(
            servers={
                int(sid): tuple(addrs[0])
                for sid, addrs in (info.servers or {}).items()
            },
            leader=info.leader,
            responders=responders,
        )
        # live resharding: installed ranges arrive on the SAME refresh
        # round (manager re-announce path).  Each installed range routes
        # to its per-group OWNER sid — the destination-group leader that
        # announced the install (the manager stamps it) — so steering
        # tracks where the range actually adopted instead of pinning
        # every range to the cluster-wide announced leader; entries
        # without an owner stamp (pre-stamp manager state) fall back to
        # the leader sid as before.
        triples = []
        for e in (getattr(info, "ranges", None) or ()):
            own = e.get("owner")
            sid = int(own) if own is not None else info.leader
            if sid is None:
                continue
            triples.append((e["start"], e.get("end"), int(sid)))
        if triples or info.leader is not None:
            self.routing.set_ranges(triples)

    def _refresh_loop(self) -> None:
        while not self._stop.wait(self.refresh_s):
            try:
                self._refresh_routing()
            # graftlint: disable=H106 -- manager mid-fault is the
            # expected cause: the proxy keeps serving off the cached
            # routing table and the next refresh tick retries
            except Exception:
                pass

    # ------------------------------------------------------ forward loop
    def _forward_loop(self) -> None:
        while not self._stop.is_set():
            try:
                drained = self._cycle()
            except Exception as e:  # never let the loop die silently
                pf_warn(logger, f"proxy forward cycle error: {e!r}")
                drained = False
            if not drained:
                # backlog full (or error): front queue keeps filling —
                # that is the designed backpressure — but this thread
                # must not spin while upstream windows stay closed
                time.sleep(self.tick_interval)

    def _cycle(self) -> bool:
        now = time.monotonic()
        if now >= self._next_gc:
            # coarse cadence: deadline GC walks every pend under the
            # lock — at a 1ms forward tick that sweep must not ride the
            # hot path whose drain rate the shed point measures
            self._next_gc = now + 0.25
            self._gc(now)
            if self.read_tier is not None:
                self.read_tier.expire_probes(now)
        while True:
            try:
                self._backlog.append(self._requeue.popleft())
            except IndexError:
                break
        drained = False
        if len(self._backlog) < self.backlog_limit:
            batch = self.external.get_req_batch(
                timeout=self.tick_interval
            )
            drained = True
            for client, req in batch:
                self._classify(int(client), req)
        self._flush(now)
        self.metrics.gauge_set("proxy_backlog", len(self._backlog))
        return drained

    def _mint(self, client: int, req: ApiRequest, kind: str) -> int:
        with self._lock:
            prid = self._next_rid
            self._next_rid += 1
            self._pends[prid] = {
                "client": client, "req_id": req.req_id, "kind": kind,
                "cmd": req.cmd, "conf_delta": req.conf_delta,
                "attempts": 0, "force": None,
                "deadline": time.monotonic() + self.pend_timeout,
                "bid": None, "sid": None,
            }
            self._inflight[(client, req.req_id)] = prid
        return prid

    def _classify(self, client: int, req: ApiRequest) -> None:
        key = (client, req.req_id)
        with self._lock:
            cached = self._replied.get(key)
            dup_inflight = cached is None and key in self._inflight
        if cached is not None:
            # dedupe: a retransmitted already-replied request replays
            # the cached reply without touching any shard
            self.metrics.counter_add("proxy_dedupe_hits")
            self.external.send_reply(cached, client)
            return
        if dup_inflight:
            # duplicate of an op still in flight: exactly one reply is
            # already on its way — forwarding again could double-propose
            self.metrics.counter_add("proxy_dedupe_hits")
            return
        if req.kind == "stats":
            # per-tier scrape over the data plane: works identically
            # for thread- and process-mode proxies (no manager change),
            # bypasses the bound like any control-plane op
            self.external.send_reply(ApiReply(
                "stats", req_id=req.req_id, success=True,
                notes=self.metrics_snapshot(),
            ), client)
            return
        if req.kind == "conf":
            self._backlog.append(self._mint(client, req, "conf"))
            return
        if req.kind == "scan" and req.cmd is not None:
            # "scan" as an ApiRequest kind normalizes to a Command
            # riding "req" — one pend shape for the whole forward path
            req = ApiRequest("req", req_id=req.req_id, cmd=req.cmd)
        if req.kind != "req" or req.cmd is None:
            self.external.send_reply(ApiReply(
                "error", req_id=req.req_id, success=False,
            ), client)
            return
        # per-key-range heat at the proxy seam (live resharding input)
        self._range_heat.note(req.cmd.key)
        prid = self._mint(client, req, "req")
        if (
            req.cmd.kind in ("get", "scan")
            and self.read_tier is not None
            and self.read_tier.try_probe(prid, req.cmd)
        ):
            return  # the learner serves it or falls it back to us
        self._backlog.append(prid)

    def _flush(self, now: float) -> None:
        if not self._backlog:
            return
        groups: Dict[int, List[int]] = {}
        confs: List[Tuple[int, int]] = []
        leftover: collections.deque = collections.deque()
        while self._backlog:
            prid = self._backlog.popleft()
            pend = self._pends.get(prid)
            if pend is None:
                continue
            if pend["kind"] == "conf":
                sid = self.routing.write_target()
                if sid is None:
                    leftover.append(prid)
                else:
                    confs.append((sid, prid))
                continue
            sid = pend["force"]
            if sid is None:
                sid = self.routing.owner_for(pend["cmd"].key)
            if sid is None:
                leftover.append(prid)
                continue
            groups.setdefault(sid, []).append(prid)
        for sid, prids in groups.items():
            up = self._upstream(sid, now)
            if up is None:
                # unreachable target: clear any redirect-derived force
                # so the NEXT cycle re-routes via the (refreshed) owner
                # map instead of pinning the op to a dead replica until
                # the pend GC
                for prid in prids:
                    pend = self._pends.get(prid)
                    if pend is not None:
                        pend["force"] = None
                leftover.extend(prids)
                continue
            i = 0
            while i < len(prids):
                if up is None or len(up.inflight) >= self.upstream_window:
                    leftover.extend(prids[i:])
                    break
                chunk = prids[i:i + self.forward_batch]
                i += len(chunk)
                if not self._send_batch(up, chunk):
                    up = None
                    leftover.extend(prids[i:])
                    break
        for sid, prid in confs:
            up = self._upstream(sid, now)
            if up is None or not self._send_conf(up, prid):
                leftover.append(prid)
        self._backlog = leftover

    def _send_batch(self, up: _Upstream, prids: List[int]) -> bool:
        with self._lock:
            bid = self._next_rid
            self._next_rid += 1
            entries = []
            for prid in prids:
                pend = self._pends.get(prid)
                if pend is None:
                    continue
                pend["sid"] = up.sid
                pend["bid"] = bid
                entries.append((prid, pend["cmd"]))
            if not entries:
                return True
            self._batches[bid] = {p for p, _ in entries}
            self._bid_sid[bid] = up.sid
            up.inflight.add(bid)
        try:
            safetcp.send_msg_sync(up.sock, ApiRequest(
                "batch", req_id=bid, batch=entries,
            ), codec=self.codec)
        # graftlint: disable=H106 -- send failure means the upstream is
        # gone: _kill_upstream records it (connect cooldown + stranding
        # its in-flight batches) and the False return re-queues nothing,
        # matching the fused-server-crash contract
        except Exception:
            self._kill_upstream(up)
            return False
        self.metrics.counter_add("proxy_routed", len(entries))
        # one hop event per forwarded batch: pairs with the shard's
        # api_ingress at (client == fwd_id, req_id == prid)
        self.flight.record(
            "proxy_fwd", sid=up.sid, prid=bid, n=len(entries),
            fwd_id=self.cid,
        )
        return True

    def _send_conf(self, up: _Upstream, prid: int) -> bool:
        pend = self._pends.get(prid)
        if pend is None:
            return True
        with self._lock:
            pend["sid"] = up.sid
        try:
            safetcp.send_msg_sync(up.sock, ApiRequest(
                "conf", req_id=prid, conf_delta=pend["conf_delta"],
            ))
        # graftlint: disable=H106 -- same contract as _send_batch: the
        # dead upstream is recorded by _kill_upstream and the caller
        # sees False
        except Exception:
            self._kill_upstream(up)
            return False
        self.flight.record(
            "proxy_fwd", sid=up.sid, prid=prid, n=1, fwd_id=self.cid,
        )
        return True

    # -------------------------------------------------- upstream plumbing
    def _upstream(self, sid: int, now: float) -> Optional[_Upstream]:
        up = self._ups.get(sid)
        if up is not None and up.alive:
            return up
        if now - self._up_fail.get(sid, 0.0) < 0.5:
            return None  # connect cooldown: no reconnect storm
        addr = self.routing.servers.get(sid)
        if addr is None:
            return None
        try:
            sock = socket.create_connection(tuple(addr), timeout=2.0)
            sock.settimeout(None)
            safetcp.send_msg_sync(sock, self.cid)
        # graftlint: disable=H106 -- connect failure is recorded in the
        # per-sid cooldown stamp (no reconnect storm) and the None
        # return routes the batch elsewhere or sheds it
        except Exception:
            self._up_fail[sid] = now
            return None
        up = _Upstream(sid, sock)
        up.pump = threading.Thread(
            target=self._pump, args=(up,), daemon=True,
            name=f"ingress-pump-{sid}",
        )
        self._ups[sid] = up
        up.pump.start()
        return up

    def _kill_upstream(self, up: _Upstream) -> None:
        up.alive = False
        try:
            up.sock.close()
        except OSError:
            pass
        with self._lock:
            if self._ups.get(up.sid) is up:
                del self._ups[up.sid]
            self._up_fail[up.sid] = time.monotonic()
            # strand this upstream's in-flight ops: a sent op may have
            # been proposed by the (probably dead) shard, so re-sending
            # could double-execute — clients time out and record unacked,
            # the same contract as a fused-server crash
            doomed: List[int] = []
            for bid in list(up.inflight):
                doomed.extend(self._batches.pop(bid, ()))
                self._bid_sid.pop(bid, None)
            up.inflight.clear()
            for prid in doomed:
                pend = self._pends.pop(prid, None)
                if pend is not None:
                    self._inflight.pop(
                        (pend["client"], pend["req_id"]), None
                    )

    def _pump(self, up: _Upstream) -> None:
        while up.alive and not self._stop.is_set():
            try:
                rep = safetcp.recv_msg_sync(up.sock)
            # graftlint: disable=H106 -- recv failure breaks to the
            # _kill_upstream below the loop, which records the death and
            # strands this upstream's in-flight ops
            except Exception:
                break
            if isinstance(rep, ApiReply):
                try:
                    self._on_reply(up, rep)
                except Exception as e:
                    pf_warn(logger, f"proxy reply handling error: {e!r}")
        self._kill_upstream(up)

    # --------------------------------------------------- reply handling
    def _detach(self, prid: int, pend: dict) -> None:
        """(lock held) Remove prid from its batch bookkeeping."""
        bid = pend.get("bid")
        if bid is None:
            return
        prids = self._batches.get(bid)
        if prids is not None:
            prids.discard(prid)
            if not prids:
                del self._batches[bid]
                sid = self._bid_sid.pop(bid, None)
                up = self._ups.get(sid)
                if up is not None:
                    up.inflight.discard(bid)
        pend["bid"] = None

    def _pop_pend(self, prid: int) -> Optional[dict]:
        with self._lock:
            pend = self._pends.pop(prid, None)
            if pend is None:
                return None
            self._inflight.pop((pend["client"], pend["req_id"]), None)
            self._detach(prid, pend)
        return pend

    def _drop_pend(self, prid: int) -> None:
        self._pop_pend(prid)

    def _reply_client(self, pend: dict, reply: ApiReply,
                      cache: bool = True) -> None:
        if cache:
            key = (pend["client"], pend["req_id"])
            with self._lock:
                self._replied[key] = reply
                while len(self._replied) > self.dedupe_cap:
                    self._replied.popitem(last=False)
        self.external.send_reply(reply, pend["client"])

    def _on_reply(self, up: _Upstream, rep: ApiReply) -> None:
        self.flight.record(
            "proxy_rcv", sid=up.sid, prid=rep.req_id, kind=rep.kind,
        )
        if rep.kind == "shed":
            # the shard refused (batch: the WHOLE batch; conf: one op)
            # before proposing — relay the negative ack + hint to every
            # affected client (shard-tier shed, attributable as such)
            with self._lock:
                prids = self._batches.pop(rep.req_id, None)
                self._bid_sid.pop(rep.req_id, None)
                up.inflight.discard(rep.req_id)
            targets = list(prids) if prids is not None else [rep.req_id]
            pends = [self._pop_pend(p) for p in targets]
            pends = [p for p in pends if p is not None]
            if pends:
                self.metrics.counter_add(
                    "proxy_upstream_shed", len(pends)
                )
            for pend in pends:
                self._reply_client(pend, ApiReply(
                    "shed", req_id=pend["req_id"], success=False,
                    retry_after_ms=rep.retry_after_ms,
                ), cache=False)
            return
        if rep.kind == "redirect":
            self.routing.note_leader(rep.redirect)
            give_up = False
            with self._lock:
                pend = self._pends.get(rep.req_id)
                if pend is None:
                    return
                pend["attempts"] += 1
                give_up = pend["attempts"] > self.retry_redirects
                if not give_up:
                    # refused WITHOUT proposing: re-forwarding is safe
                    self._detach(rep.req_id, pend)
                    pend["force"] = (
                        rep.redirect
                        if rep.redirect is not None and rep.redirect >= 0
                        else None
                    )
            if give_up:
                pend = self._pop_pend(rep.req_id)
                if pend is not None:
                    # hand the client a proxy-space rotate (no server id
                    # leaks through the tier boundary)
                    self._reply_client(pend, ApiReply(
                        "redirect", req_id=pend["req_id"],
                        redirect=None, success=False,
                    ), cache=False)
            else:
                self._requeue.append(rep.req_id)
            return
        if rep.kind in ("reply", "conf", "error"):
            pend = self._pop_pend(rep.req_id)
            if pend is None:
                return
            out = ApiReply(
                rep.kind if rep.kind != "error" else "error",
                req_id=pend["req_id"], result=rep.result,
                success=rep.success, local=rep.local,
            )
            self._reply_client(
                pend, out, cache=rep.kind in ("reply", "conf"),
            )

    # ------------------------------------------------------------- misc
    def _gc(self, now: float) -> None:
        with self._lock:
            dead = [
                p for p, pend in self._pends.items()
                if now > pend["deadline"]
            ]
        for prid in dead:
            self._drop_pend(prid)

    def metrics_snapshot(self) -> dict:
        self.metrics.gauge_set(
            "range_heat", float(self._range_heat.total())
        )
        for k, n in self._range_heat.top(8):
            self.metrics.gauge_set("range_heat", float(n), key=k)
        return {
            "cid": self.cid,
            "tier": "proxy",
            "api_addr": list(self.api_addr),
            "routing_version": self.routing.version,
            "read_tier_upstream": (
                self.read_tier.upstream
                if self.read_tier is not None else None
            ),
            "host": self.metrics.snapshot(),
        }

    def flight_snapshot(self, last_n: Optional[int] = None) -> dict:
        out = self.flight.dump(last_n=last_n)
        out["tier"] = "proxy"
        return out

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self.external.stop()
        if self.read_tier is not None:
            self.read_tier.close()
        for up in list(self._ups.values()):
            self._kill_upstream(up)
        try:
            self.ctrl.close()  # the manager deregisters on this close
        # graftlint: disable=H106 -- best-effort shutdown: a manager that
        # is already gone must not keep stop() from joining the forward
        # thread and releasing the port
        except Exception:
            pass
        self._fwd_thread.join(timeout=3)


class ServingPlane:
    """Assembly of the compartmentalized serving plane: N ingress
    proxies (each optionally carrying a learner read tier) in front of a
    live cluster, with per-tier scrape / flight / crash handles.

    ``proxies == 0`` IS fused mode: nothing is constructed, clients see
    no registered proxies, and every code path is byte-identical to the
    pre-split serving plane — which is why fused stays the default for
    all existing tests, benches, and soaks.
    """

    def __init__(
        self,
        manager_addr: Tuple[str, int],
        proxies: int = 2,
        *,
        host: str = "127.0.0.1",
        ports: Optional[List[int]] = None,
        read_tier: bool = True,
        proxy_config: Optional[dict] = None,
        mode: str = "thread",
        cpus: Optional[set] = None,
    ):
        self.manager_addr = tuple(manager_addr)
        self.n = int(proxies)
        self.host = host
        self.read_tier = bool(read_tier)
        self.cfg = dict(proxy_config or {})
        # "thread": proxies live in this process (soaks/tests — cheap
        # crash/restart/scrape handles); "process": each proxy is its
        # own OS process via cli/proxy.py — the deployment shape, and
        # what the >= 10k-client bench uses so the serving process's
        # GIL never pays for proxy-side pickling
        self.mode = str(mode)
        # optional CPU set for process-mode proxies: when the bench
        # co-locates every tier on one box, pinning the frontend off
        # the serving cores keeps the device scan's thread pool
        # uncontended (deployment puts proxies on separate hosts)
        self.cpus = set(cpus) if cpus else None
        if ports is None:
            ports = []
            socks = []
            for _ in range(self.n):
                s = socket.socket()
                s.bind((host, 0))
                socks.append(s)
                ports.append(s.getsockname()[1])
            for s in socks:
                s.close()
        self.ports = list(ports)
        self.proxies: List[Optional[IngressProxy]] = [None] * self.n
        self.procs: List[Optional[Any]] = [None] * self.n

    # ------------------------------------------------------ process mode
    _CFG_FLAGS = {
        "max_batch": "--max-batch",
        "max_pending": "--max-pending",
        "forward_batch": "--forward-batch",
        "upstream_window": "--upstream-window",
        "backlog_limit": "--backlog-limit",
        "tick_interval": "--tick-interval",
    }

    def _spawn(self, i: int):
        import subprocess
        import sys

        argv = [
            sys.executable, "-m", "summerset_tpu.cli.proxy",
            "-m", f"{self.manager_addr[0]}:{self.manager_addr[1]}",
            "--bind-ip", self.host, "-a", str(self.ports[i]),
        ]
        for k, flag in self._CFG_FLAGS.items():
            if k in self.cfg and self.cfg[k] is not None:
                argv += [flag, str(self.cfg[k])]
        if not self.read_tier:
            argv.append("--no-read-tier")
        env = None
        if self.cfg.get("codec") is not None:
            # wire-codec pin rides the env into the child (the same
            # SMR_WIRE_CODEC default the A/B bench flips process-wide)
            env = dict(os.environ)
            env["SMR_WIRE_CODEC"] = "1" if self.cfg["codec"] else "0"
        cpus = self.cpus

        def _deprioritize() -> None:
            # the stateless tier yields CPU to the device plane when
            # co-located on one box (deployment runs it on frontend
            # hosts; the bench must not let it slow the scan it meters)
            try:
                os.nice(5)
                if cpus and hasattr(os, "sched_setaffinity"):
                    os.sched_setaffinity(0, cpus)
            except OSError:
                pass

        return subprocess.Popen(
            argv, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            preexec_fn=_deprioritize, env=env,
        )

    def _wait_registered(self, want: int, timeout: float = 20.0) -> None:
        from ..client.endpoint import ClientCtrlStub

        stub = ClientCtrlStub(self.manager_addr)
        try:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                rep = stub.request(CtrlRequest("query_info"), timeout=5)
                if len(rep.proxies or {}) >= want:
                    return
                time.sleep(0.2)
            raise SummersetError(
                f"proxy tier never registered {want} proxies"
            )
        finally:
            stub.close()

    def start(self) -> "ServingPlane":
        if self.mode == "process":
            for i in range(self.n):
                if self.procs[i] is None:
                    self.procs[i] = self._spawn(i)
            self._wait_registered(self.n)
            return self
        for i in range(self.n):
            if self.proxies[i] is None:
                self.proxies[i] = IngressProxy(
                    self.manager_addr, (self.host, self.ports[i]),
                    read_tier=self.read_tier, **self.cfg,
                )
        return self

    @property
    def addrs(self) -> List[Tuple[str, int]]:
        return [(self.host, p) for p in self.ports]

    def crash_proxy(self, i: int) -> None:
        """Kill proxy ``i`` abruptly: its ctrl connection drops, the
        manager deregisters it, clients rediscover on their next
        rotate — the proxy_crash nemesis action."""
        if self.mode == "process":
            p = self.procs[i]
            self.procs[i] = None
            if p is not None:
                p.kill()
                p.wait(timeout=10)
            return
        p = self.proxies[i]
        self.proxies[i] = None
        if p is not None:
            p.stop()

    def restart_proxy(self, i: int) -> None:
        """Bring proxy ``i`` back on its original port (a fresh
        incarnation: empty dedupe cache, fresh routing — exactly what a
        process supervisor restart would produce)."""
        if self.mode == "process":
            if self.procs[i] is None:
                self.procs[i] = self._spawn(i)
            return
        if self.proxies[i] is None:
            self.proxies[i] = IngressProxy(
                self.manager_addr, (self.host, self.ports[i]),
                read_tier=self.read_tier, **self.cfg,
            )

    def scrape(self) -> Dict[str, dict]:
        if self.mode == "process":
            out = {}
            for i, proc in enumerate(self.procs):
                if proc is None:
                    continue
                snap = scrape_proxy((self.host, self.ports[i]))
                if snap is not None:
                    out[f"p{i}"] = snap
            return out
        return {
            f"p{i}": p.metrics_snapshot()
            for i, p in enumerate(self.proxies) if p is not None
        }

    def flight_dumps(self, last_n: Optional[int] = None
                     ) -> Dict[str, dict]:
        """Per-proxy flight-recorder dumps for trace_export (the
        client→proxy→shard chain).  THREAD MODE ONLY: process-mode
        proxies keep their rings in their own address space and no
        remote dump channel exists yet — the empty result is flagged so
        a debugging session never mistakes it for an idle tier."""
        if self.mode == "process" and any(
            p is not None for p in self.procs
        ):
            pf_warn(
                logger,
                "flight_dumps: process-mode proxies have no remote "
                "flight channel; returning no events (use thread mode "
                "for proxy-hop traces)",
            )
        return {
            f"p{i}": p.flight_snapshot(last_n=last_n)
            for i, p in enumerate(self.proxies) if p is not None
        }

    def shed_counts(self) -> Dict[str, int]:
        """Per-proxy front-door shed counters (the proxy-tier half of
        shed attribution; the shard half is the api_shed scrape)."""
        if self.mode == "process":
            return {
                pid: snap.get("host", {}).get("counters", {})
                         .get("proxy_shed", 0)
                for pid, snap in self.scrape().items()
            }
        return {
            f"p{i}": p.metrics.counter_value("proxy_shed")
            for i, p in enumerate(self.proxies) if p is not None
        }

    def stop(self) -> None:
        for i, p in enumerate(self.proxies):
            self.proxies[i] = None
            if p is not None:
                p.stop()
        for i, proc in enumerate(self.procs):
            self.procs[i] = None
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                # graftlint: disable=H106 -- escalation IS the handling:
                # a child that ignores terminate for 10s gets kill()ed so
                # plane teardown always completes
                except Exception:
                    proc.kill()


# keep the declared proxy series and this module in lockstep (import
# side effect free; referenced here so a rename breaks loudly at import)
_ = PROXY_DECLARED
