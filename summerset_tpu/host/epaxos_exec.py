"""EPaxos host-side execution: exact Tarjan SCC ordering over the
committed dependency graph, producing the ``exec_floor_rows`` kernel
input (the authoritative execution path; the in-kernel row-frontier
heuristic is the device-only approximation — epaxos.py module docstring).

Parity: reference ``src/protocols/epaxos/execution.rs:11-87`` — build the
dependency graph over committed-but-unexecuted instances, Tarjan SCCs
(petgraph ``tarjan_scc``), execute SCCs in reverse topological order,
ordering within an SCC by sequence number.

Adaptation to the kernel's frontier dependency encoding: an instance's
``deps`` vector stores, per row, the highest interfering column — the
dependency set is the whole prefix of each row up to that column
(transitively closed by construction, mod.rs:110-124).  Because the
kernel consumes execution progress as a per-row contiguous *frontier*
(``exec_row``), instances additionally chain on their own-row
predecessor, which linearizes execution within a row without changing
the cross-row SCC order.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

COMMITTED = 3  # epaxos.py status code


class EPaxosExecutor:
    """Per-group incremental Tarjan applier.

    ``advance(...)`` consumes the replica's own view of the 2-D window
    arrays and returns the new per-row exec floors after applying every
    instance whose full dependency closure is committed.  ``apply_fn``
    receives ``(row, col, vid, is_noop)`` in the exact execution order.
    """

    def __init__(self, num_rows: int, window: int,
                 apply_fn: Callable[[int, int, int, bool], None]):
        self.R = num_rows
        self.W = window
        self.apply_fn = apply_fn
        self.floor = [0] * num_rows  # contiguous executed frontier
        self.lost_rows: List[int] = []  # rows needing install-snapshot

    # ------------------------------------------------------------ advance
    def advance(
        self,
        abs2: np.ndarray,    # [R, W] absolute column at window pos (-1 =
        st2: np.ndarray,     # [R, W] status                      empty)
        seq2: np.ndarray,    # [R, W]
        val2: np.ndarray,    # [R, W]
        noop2: np.ndarray,   # [R, W]
        deps2: np.ndarray,   # [R, W, R] per-row interference frontier
        cmt_row: np.ndarray,  # [R] per-row contiguous committed frontier
        payload_ok: Optional[Callable[[int, bool], bool]] = None,
    ) -> List[int]:
        R, W = self.R, self.W

        def lookup(r: int, c: int) -> Optional[int]:
            p = c % W
            return p if abs2[r, p] == c else None

        # candidate nodes: committed, unexecuted, inside the window.  A
        # committed column that our stored copy no longer holds (the
        # window slid past it while we were paused/partitioned) is a LOST
        # instance: the row stalls here and the caller must install-
        # snapshot past it (self.lost_rows signals that need).
        self.lost_rows: List[int] = []
        nodes: Dict[Tuple[int, int], Tuple[int, int, bool, np.ndarray]] = {}
        for r in range(R):
            for c in range(self.floor[r], int(cmt_row[r])):
                p = lookup(r, c)
                if p is None:
                    self.lost_rows.append(r)
                    break
                if st2[r, p] != COMMITTED:
                    break  # gap: not yet committed contiguously
                nodes[(r, c)] = (
                    int(seq2[r, p]), int(val2[r, p]),
                    bool(noop2[r, p]), deps2[r, p],
                )

        if not nodes:
            return list(self.floor)

        # edges: own-row predecessor + the per-row dependency frontiers.
        # ``dep[r2]`` is an EXCLUSIVE bar (the kernel's interference
        # tables carry "highest same-bucket column bar": columns < bar
        # are dependencies).  A bar below the floor is already executed;
        # a bar past the row's committed frontier blocks the node.
        blocked: set = set()
        edges: Dict[Tuple[int, int], List[Tuple[int, int]]] = {}
        for (r, c), (_seq, _vid, _noop, dep) in nodes.items():
            if payload_ok is not None and not payload_ok(_vid, _noop):
                blocked.add((r, c))  # committed but payload not yet here
            out = []
            if c - 1 >= self.floor[r]:
                out.append((r, c - 1))
            for r2 in range(R):
                if r2 == r:
                    continue
                d = int(dep[r2])
                if d <= 0:
                    continue  # no dependency on this row
                if d > int(cmt_row[r2]):
                    blocked.add((r, c))  # depends on uncommitted tail
                # prefix semantics: an edge to the last dependency column
                # suffices — that node chains to the rest of the prefix
                hi = min(d, int(cmt_row[r2])) - 1
                if hi >= self.floor[r2]:
                    out.append((r2, hi))
            kept = []
            for e in out:
                if e in nodes:
                    kept.append(e)
                elif e[1] >= self.floor[e[0]]:
                    # the dependency is unexecuted but absent from the
                    # candidate set (lost to a window slide or an
                    # uncommitted gap): the dependent must WAIT, never
                    # execute ahead of it
                    blocked.add((r, c))
            edges[(r, c)] = kept

        # transitively block nodes that reach a blocked node
        changed = True
        while changed:
            changed = False
            for n, outs in edges.items():
                if n not in blocked and any(e in blocked for e in outs):
                    blocked.add(n)
                    changed = True
        runnable = {n for n in nodes if n not in blocked}
        if not runnable:
            return list(self.floor)

        # iterative Tarjan over the runnable subgraph
        index: Dict[Tuple[int, int], int] = {}
        low: Dict[Tuple[int, int], int] = {}
        on_stack: set = set()
        stack: List[Tuple[int, int]] = []
        sccs: List[List[Tuple[int, int]]] = []
        counter = [0]

        def strongconnect(root):
            work = [(root, iter(
                [e for e in edges[root] if e in runnable]
            ))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                v, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(
                            [e for e in edges[w] if e in runnable]
                        )))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[v] = min(low[v], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
                if low[v] == index[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == v:
                            break
                    sccs.append(comp)

        for n in sorted(runnable):
            if n not in index:
                strongconnect(n)

        # Tarjan emits SCCs in reverse topological order of the
        # condensation — i.e. dependencies first, which IS execution
        # order (execution.rs processes tarjan_scc output in order).
        # Within an SCC: sequence number, row id as tie-break.
        executed: set = set()
        for comp in sccs:
            comp.sort(key=lambda n: (nodes[n][0], n[0], n[1]))
            for (r, c) in comp:
                seq, vid, noop, _dep = nodes[(r, c)]
                self.apply_fn(r, c, vid, noop)
                executed.add((r, c))

        # advance contiguous per-row floors over executed prefixes
        for r in range(R):
            c = self.floor[r]
            while (r, c) in executed:
                c += 1
            self.floor[r] = c
        return list(self.floor)
