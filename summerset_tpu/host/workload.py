"""Deterministic workload plane: seeded adversarial traffic schedules.

The workload twin of ``host/nemesis.py``: where a ``FaultPlan`` decides
*what breaks and when*, a ``WorkloadPlan`` decides *what traffic arrives
and when* — and both obey the same determinism contract, enforced by the
same lint (graftlint H103 covers this module's plan/stream classes):
``WorkloadPlan.generate(seed, wl_class, ...)`` draws only from
``random.Random`` seeded off its arguments, so the same seed always
yields a byte-identical ``timeline()`` and the same per-client op
sequence.  Every overload bug found under a workload schedule is a
one-line repro (``--wl-class C --seed N``), and the joint
workload × nemesis soak (``scripts/workload_soak.py``) replays BOTH
schedules from their seeds.

Classes are YCSB-style (PAPERS.md: compartmentalized SMR and HT-Paxos
both assume an ingress tier that batches and absorbs client load; these
classes are the traffic that tier must absorb):

- ``uniform``      — uniform keys, balanced mix (the legacy bench class);
- ``read_mostly``  — zipfian hot keys, ~5-10% puts (YCSB-B territory);
- ``write_heavy``  — zipfian hot keys, ~85-95% puts (ingest pressure on
                     the log + WAL planes);
- ``value_mix``    — log-uniform value sizes over a wide range (frame
                     encoder / payload-plane stress);
- ``multi_tenant`` — per-client private key ranges plus a small shared
                     hot range (the KeyRangeMap routing scenario);
- ``hot_burst``    — strong zipfian skew plus an open-loop arrival
                     schedule whose burst phase offers ~2x the ingress
                     capacity: the overload-survival scenario (bounded
                     queues must shed visibly, not buffer unboundedly).

Split of responsibilities: everything *logical* (op kinds, keys, value
sizes, phase structure, rate multipliers) lives here and is a pure
function of the seed; everything *temporal* (mapping phase ticks to wall
seconds, expovariate arrival pacing against the monotonic clock) lives
in the drivers (``client/drivers.DriverOpenLoopPaced`` and the soak
runner), exactly as ``NemesisRunner`` owns wall pacing for fault plans.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import zlib
from typing import List, Tuple

#: every workload class the plane knows how to generate
WORKLOAD_CLASSES = (
    "uniform",
    "read_mostly",
    "write_heavy",
    "value_mix",
    "multi_tenant",
    "hot_burst",
)


@dataclasses.dataclass(frozen=True)
class WorkloadPhase:
    """One open-loop arrival phase.  ``tick``/``ticks`` are workload
    schedule ticks (the runner maps them to wall seconds with its
    ``tick_len``, sharing the logical clock with the FaultPlan playing
    alongside); ``rate_x`` is the offered-arrival multiplier relative to
    the serving path's ingress capacity (``api_max_batch / tick``) — a
    phase with ``rate_x >= 1`` offers more than the ingress tier can
    drain and MUST surface as visible shedding, not unbounded queues."""

    tick: int
    ticks: int
    rate_x: float

    def render(self) -> str:
        return (
            f"@{self.tick:05d} phase rate_x={self.rate_x:g}"
            f" ticks={self.ticks}"
        )


@dataclasses.dataclass(frozen=True)
class WorkloadPlan:
    seed: int
    wl_class: str
    clients: int
    num_keys: int
    put_ratio: float
    zipf_s: float           # 0 = uniform key popularity
    value_lo: int
    value_hi: int
    log_values: bool        # log-uniform (vs uniform) value sizes
    tenant_span: int        # >0: per-client private key range width
    shared_keys: int        # multi-tenant: size of the shared hot range
    shared_frac: float      # fraction of multi-tenant ops on shared keys
    phases: Tuple[WorkloadPhase, ...]

    # ------------------------------------------------------------ build
    @staticmethod
    def generate(
        seed: int,
        wl_class: str,
        clients: int = 3,
        num_keys: int = 24,
        horizon: int = 120,
    ) -> "WorkloadPlan":
        """Draw a plan from the seed.  Class parameters are jittered
        per-seed inside each class's envelope, so different seeds of the
        same class are genuinely different workloads while the class's
        character (skew, mix, burst shape) is preserved."""
        import random

        if wl_class not in WORKLOAD_CLASSES:
            raise ValueError(f"unknown workload class {wl_class!r}")
        # class-salted seed: seed 1 of read_mostly and seed 1 of
        # write_heavy must not share a random stream
        rng = random.Random(
            (seed << 16) ^ zlib.crc32(wl_class.encode())
        )
        put_ratio, zipf_s = 0.5, 0.0
        value_lo, value_hi, log_values = 48, 64, False
        tenant_span, shared_keys, shared_frac = 0, 0, 0.0
        steady = round(0.25 + rng.uniform(0.0, 0.15), 3)
        phases: List[WorkloadPhase] = [
            WorkloadPhase(0, horizon, steady)
        ]
        if wl_class == "read_mostly":
            put_ratio = round(rng.uniform(0.04, 0.10), 3)
            zipf_s = round(rng.uniform(0.9, 1.2), 3)
            value_lo, value_hi = 32, 128
        elif wl_class == "write_heavy":
            put_ratio = round(rng.uniform(0.85, 0.95), 3)
            zipf_s = round(rng.uniform(0.8, 1.1), 3)
            value_lo, value_hi = 64, 192
        elif wl_class == "value_mix":
            value_lo, value_hi, log_values = 16, 2048, True
        elif wl_class == "multi_tenant":
            put_ratio = round(rng.uniform(0.3, 0.5), 3)
            tenant_span = rng.randint(6, 10)
            shared_keys = rng.randint(3, 5)
            shared_frac = round(rng.uniform(0.2, 0.4), 3)
            num_keys = clients * tenant_span + shared_keys
        elif wl_class == "hot_burst":
            zipf_s = round(rng.uniform(1.1, 1.3), 3)
            # steady → burst (~2x ingress capacity) → recover; the
            # recover tail is where the soak measures throughput
            # returning to the pre-burst steady state
            t1 = int(horizon * rng.uniform(0.28, 0.34))
            blen = int(horizon * rng.uniform(0.22, 0.28))
            burst_x = round(rng.uniform(1.9, 2.2), 3)
            phases = [
                WorkloadPhase(0, t1, steady),
                WorkloadPhase(t1, blen, burst_x),
                WorkloadPhase(t1 + blen, horizon - t1 - blen, steady),
            ]
        return WorkloadPlan(
            seed, wl_class, clients, num_keys, put_ratio, zipf_s,
            value_lo, value_hi, log_values, tenant_span, shared_keys,
            shared_frac, tuple(phases),
        )

    # ------------------------------------------------------- determinism
    def timeline(self) -> str:
        """Canonical rendering; byte-identical for identical plans (the
        repro contract — soak failures print this plus the seed)."""
        head = (
            f"# WorkloadPlan v1 seed={self.seed} class={self.wl_class}"
            f" clients={self.clients}\n"
            f"keys={self.num_keys} put={self.put_ratio:g}"
            f" zipf={self.zipf_s:g}"
            f" value=[{self.value_lo},{self.value_hi}"
            f"{',log' if self.log_values else ''}]"
            f" tenant_span={self.tenant_span}"
            f" shared={self.shared_keys}@{self.shared_frac:g}\n"
        )
        return head + "".join(p.render() + "\n" for p in self.phases)

    def digest(self) -> str:
        return hashlib.sha256(self.timeline().encode()).hexdigest()[:16]

    # ---------------------------------------------------------- streams
    def rate_x_at(self, tick: float) -> float:
        """Offered-rate multiplier at a workload tick (0 past the
        horizon — issuing stops, inflight ops drain)."""
        for p in self.phases:
            if p.tick <= tick < p.tick + p.ticks:
                return p.rate_x
        return 0.0

    def horizon(self) -> int:
        return max(p.tick + p.ticks for p in self.phases)

    def opstream(self, ci: int) -> "OpStream":
        """The per-client op stream: a pure function of (plan, ci)."""
        return OpStream(self, ci)


class OpStream:
    """Seeded per-client op generator: ``next()`` yields
    ``(kind, key, value_size)`` tuples drawn from this client's own
    ``random.Random`` — replaying a client from the same (plan, ci)
    yields the identical op sequence.

    Key popularity: zipfian over a per-plan shuffled key order (the hot
    key identity varies per seed but is SHARED across clients, so skew
    creates real cross-client contention).  Multi-tenant plans route
    ``shared_frac`` of ops to the shared hot range and the rest to this
    client's private range (disjoint from every other client's)."""

    def __init__(self, plan: WorkloadPlan, ci: int):
        import random

        self.plan = plan
        self.ci = int(ci)
        self._rng = random.Random(
            plan.seed * 7919 + self.ci * 104729 + 13
        )
        if plan.tenant_span > 0:
            self._shared = [
                f"t_shared{i}" for i in range(plan.shared_keys)
            ]
            self._private = [
                f"t{self.ci}_k{j}" for j in range(plan.tenant_span)
            ]
            self.keys = self._shared + self._private
            self._cdf: List[float] = []
        else:
            # per-plan (client-shared) hot-key identity: one shuffle
            # seeded off the plan alone
            order = list(range(plan.num_keys))
            random.Random((plan.seed << 8) | 0xA5).shuffle(order)
            self.keys = [f"w{i}" for i in order]
            self._shared, self._private = [], []
            s = plan.zipf_s
            if s > 0:
                w = [1.0 / ((i + 1) ** s) for i in range(plan.num_keys)]
                tot = sum(w)
                acc, cdf = 0.0, []
                for x in w:
                    acc += x / tot
                    cdf.append(acc)
                self._cdf = cdf
            else:
                self._cdf = []

    def _pick_key(self) -> str:
        p = self.plan
        if p.tenant_span > 0:
            if self._shared and self._rng.random() < p.shared_frac:
                return self._rng.choice(self._shared)
            return self._rng.choice(self._private)
        if self._cdf:
            i = bisect.bisect_left(self._cdf, self._rng.random())
            return self.keys[min(i, len(self.keys) - 1)]
        return self._rng.choice(self.keys)

    def _pick_size(self) -> int:
        p = self.plan
        if p.value_hi <= p.value_lo:
            return p.value_lo
        if p.log_values:
            # log-uniform: small values dominate, the tail reaches
            # value_hi (frame-encoder stress without every op paying it)
            import math

            lo, hi = math.log(p.value_lo), math.log(p.value_hi)
            return int(round(math.exp(self._rng.uniform(lo, hi))))
        return self._rng.randint(p.value_lo, p.value_hi)

    def next(self) -> Tuple[str, str, int]:
        """One op: ``("put"|"get", key, value_size)`` (size is 0 for
        gets)."""
        key = self._pick_key()
        if self._rng.random() < self.plan.put_ratio:
            return "put", key, self._pick_size()
        return "get", key, 0
